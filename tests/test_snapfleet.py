"""snapfleet: the consistent-hashed snapserve fleet — ring routing,
membership generations, supervision, chunk pushdown, multi-tenant
admission, client failover, and the fleet fault matrix (ISSUE 17).

Invariants pinned here:

- Ring spread: with >= 128 vnodes per member no member owns more than
  2x its ideal key share, and losing a member remaps ONLY that
  member's keys (survivors keep their owners — and their warm caches).
- Generations: a stale re-register raises; the supervisor refuses a
  stale probe answer, counts it, and re-registers a respawn one
  generation up. Hung != dead: a probe timeout is a strike, only
  consecutive strikes mark a member down; a refused connection is
  death now.
- Chunk pushdown: the ``plan`` RPC answer equals the client's local
  pushdown cut AND a brute-force ground truth; malformed plan docs are
  server errors, not hangs.
- Tenant admission: an over-quota tenant's requests DEFER (never
  error) behind its own quota while another tenant's requests grant
  immediately; oversize responses are admitted alone when the tenant
  is idle.
- Failover ladder: owner death mid-fan-out surfaces as ring-replica
  failover (counted, bit-exact, zero client-visible errors, zero
  direct fallbacks while replicas live); exhausting the whole fleet
  degrades to direct reads with reason ``fleet-exhausted`` and fires
  the ``fleet-degraded`` doctor rule.
- Conn pools: entries keyed to a CLOSED event loop are swept (the
  id-recycle liveness bug), and pooled conns whose writer is closing
  are never handed out.
"""

import asyncio
import threading
import uuid

import numpy as np
import pytest

from torchsnapshot_tpu import RemoteSnapshot, Snapshot, StateDict, snapserve
from torchsnapshot_tpu import faultline as fl
from torchsnapshot_tpu.io_types import IOReq
from torchsnapshot_tpu.snapserve import client as sv_client
from torchsnapshot_tpu.snapserve import fleet as sv_fleet
from torchsnapshot_tpu.snapserve import pushdown
from torchsnapshot_tpu.snapserve.server import TenantAdmission
from torchsnapshot_tpu.storage_plugin import url_to_storage_plugin
from torchsnapshot_tpu.telemetry import report as flight
from torchsnapshot_tpu.telemetry.doctor import diagnose_report
from torchsnapshot_tpu.wire import RemoteServerError


@pytest.fixture(autouse=True)
def _fleet_hygiene(monkeypatch):
    """Short down-cooldowns (one test's latch must not slow the next)
    and no leaked in-process servers or member registrations."""
    monkeypatch.setenv("TPUSNAPSHOT_SNAPSERVE_DOWN_COOLDOWN_S", "0.2")
    yield
    for name in sv_fleet.local_member_names():
        sv_fleet.unregister_local_member(name)
    snapserve.kill_local_servers()


def _mem_root(tag):
    return f"memory://snapfleet-{tag}-{uuid.uuid4().hex[:10]}/run"


def _state(n_params=4, n=2048, seed=11):
    rng = np.random.default_rng(seed)
    return {
        "m": StateDict(
            **{
                f"p{i}": rng.standard_normal(n).astype(np.float32)
                for i in range(n_params)
            }
        )
    }


def _zero_like(state):
    return {
        "m": StateDict(
            **{k: np.zeros_like(v) for k, v in state["m"].items()}
        )
    }


def _assert_exact(target, state):
    for k, v in state["m"].items():
        assert np.array_equal(target["m"][k], v), k


def _restore_report(root):
    storage = url_to_storage_plugin(root)
    try:
        return asyncio.run(
            flight.aread_json(storage, flight.RESTORE_REPORT_FNAME)
        )
    finally:
        storage.close()


# ------------------------------------------------------------------- ring


def test_ring_spread_within_2x_ideal_at_128_vnodes():
    members = [f"10.0.0.{i}:7000" for i in range(5)]
    ring = sv_fleet.HashRing(members, vnodes=128)
    counts = {m: 0 for m in members}
    n_keys = 10_000
    for i in range(n_keys):
        counts[ring.owner(f"key-{i}")] += 1
    ideal = n_keys / len(members)
    assert sum(counts.values()) == n_keys
    for m, c in counts.items():
        assert c <= 2 * ideal, f"{m} owns {c} keys (ideal {ideal})"
        assert c > 0, f"{m} owns nothing"


def test_ring_member_loss_remaps_only_lost_members_keys():
    members = [f"h{i}:1" for i in range(4)]
    ring = sv_fleet.HashRing(members, vnodes=128)
    smaller = sv_fleet.HashRing(members[:-1], vnodes=128)
    keys = [f"obj-{i}" for i in range(4000)]
    moved = 0
    for k in keys:
        before = ring.owner(k)
        after = smaller.owner(k)
        if before == members[-1]:
            assert after != members[-1]
        else:
            # A surviving member's keys NEVER move: its cache stays
            # warm through someone else's death.
            assert after == before
            continue
        moved += 1
    # The lost member owned ~1/4 of the keyspace; only that share moved.
    assert moved <= 2 * len(keys) / len(members)


def test_ring_preference_is_distinct_and_starts_at_owner():
    members = [f"h{i}:1" for i in range(4)]
    ring = sv_fleet.HashRing(members, vnodes=64)
    for i in range(64):
        pref = ring.preference(f"k{i}")
        assert len(pref) == len(members)
        assert len(set(pref)) == len(members)
        assert pref[0] == ring.owner(f"k{i}")


def test_routing_key_content_addressed_vs_location():
    from torchsnapshot_tpu.chunkstore import chunk_object_path

    key = "xs128:" + "ab" * 16 + "-4096-raw"
    path = chunk_object_path(key)
    # Chunk objects route by content key: the same chunk referenced
    # from two different backends keeps ONE ring owner.
    assert sv_fleet.routing_key("memory://a/x", path) == sv_fleet.routing_key(
        "memory://b/y", path
    )
    # Ordinary objects route by backend-qualified location.
    assert sv_fleet.routing_key(
        "memory://a/x", "0/m.0"
    ) != sv_fleet.routing_key("memory://b/y", "0/m.0")


# ------------------------------------------------- membership + supervision


def test_membership_doc_round_trip_and_stale_register_refused():
    ms = sv_fleet.FleetMembership()
    ms.register("m0", "127.0.0.1:7001", generation=3)
    ms.register("m1", "127.0.0.1:7002", generation=1)
    # Same generation re-register is a no-op refresh; higher wins.
    ms.register("m0", "127.0.0.1:7001", generation=4)
    with pytest.raises(sv_fleet.StaleGenerationError):
        ms.register("m0", "127.0.0.1:7001", generation=2)
    doc = ms.to_doc()
    back = sv_fleet.FleetMembership.from_doc(doc)
    assert back.get("m0").generation == 4
    assert back.get("m1").addr == "127.0.0.1:7002"


def test_supervisor_hung_is_strikes_dead_is_now_stale_is_refused():
    ms = sv_fleet.FleetMembership()
    ms.register("m0", "a0", generation=2)
    ms.register("m1", "a1", generation=2)
    verdicts = {"a0": "ok", "a1": "ok"}

    def probe(addr, timeout_s):
        v = verdicts[addr]
        if v == "hang":
            raise asyncio.TimeoutError("probe deadline")
        if v == "dead":
            raise ConnectionRefusedError("refused")
        if v == "stale":
            return {"member": "m0", "generation": 1}
        if v == "respawn":
            return {"member": "m0", "generation": 3}
        return {"member": addr, "generation": 2}

    sup = sv_fleet.FleetSupervisor(ms, probe=probe, hung_strikes=2)
    # Hung != dead: one missed deadline is a strike, not a death.
    verdicts["a0"] = "hang"
    sup.tick()
    assert ms.get("m0").status == "up"
    assert ms.get("m0").strikes == 1
    sup.tick()
    assert ms.get("m0").status == "down"
    # Recovery is observed by the background re-probe of down members.
    verdicts["a0"] = "ok"
    sup.tick()
    assert ms.get("m0").status == "up"
    assert ms.get("m0").strikes == 0
    # A refused connection is death NOW, no strikes.
    verdicts["a1"] = "dead"
    sup.tick()
    assert ms.get("m1").status == "down"
    verdicts["a1"] = "ok"
    # A stale zombie answering is refused and counted; state unchanged.
    verdicts["a0"] = "stale"
    before = sup.refused_generations
    sup.tick()
    assert sup.refused_generations == before + 1
    assert ms.get("m0").generation == 2
    # A respawn answers one generation UP and re-registers.
    verdicts["a0"] = "respawn"
    sup.tick()
    assert ms.get("m0").generation == 3
    assert ms.get("m0").status == "up"


def test_supervisor_probes_real_fleet_and_respawn_reregisters():
    lf = sv_fleet.start_local_fleet(n=2)
    try:
        sup = sv_fleet.FleetSupervisor(lf.membership, probe_timeout_s=5.0)
        sup.tick()
        assert len(lf.membership.up_members()) == 2
        # Kill m0; the next tick sees a refused connection = down.
        dead_addr = lf.membership.get("m0").addr
        sv_fleet.kill_local_member("m0")
        sup.tick()
        assert lf.membership.get("m0").status == "down"
        assert lf.membership.get("m1").status == "up"
        # Respawn m0 one generation up on the SAME logical name (fresh
        # port — the doc's addr follows the re-register).
        server = snapserve.start_local_server(
            member_name="m0", generation=2
        )
        sv_fleet.register_local_member("m0", server)
        lf.membership.register("m0", server.addr, generation=2)
        sup.tick()
        assert lf.membership.get("m0").status == "up"
        assert lf.membership.get("m0").generation == 2
        assert lf.membership.get("m0").addr != dead_addr
    finally:
        lf.stop()


# --------------------------------------------------------- chunk pushdown


def test_pushdown_hull_and_select_against_brute_force():
    shape = (16, 24)
    itemsize = 4
    rng = np.random.default_rng(3)
    flat = np.arange(shape[0] * shape[1] * itemsize, dtype=np.uint8)
    for _ in range(50):
        r0 = int(rng.integers(0, shape[0]))
        r1 = int(rng.integers(r0 + 1, shape[0] + 1))
        c0 = int(rng.integers(0, shape[1]))
        c1 = int(rng.integers(c0 + 1, shape[1] + 1))
        box = ((r0, r1), (c0, c1))
        hull = pushdown.slice_byte_hull(shape, box, itemsize)
        # Brute force: the strided element footprint of the box.
        footprint = set()
        for r in range(r0, r1):
            for c in range(c0, c1):
                base = (r * shape[1] + c) * itemsize
                footprint.update(range(base, base + itemsize))
        lo, hi = hull
        # The hull is a conservative SUPERSET of the footprint and
        # tight at both ends (first and last footprint byte).
        assert lo == min(footprint) and hi == max(footprint) + 1
        # Record selection covers every footprint byte.
        sizes = [96, 160, 64] * ((len(flat) // 320) + 1)
        total, record_sizes = 0, []
        for n in sizes:
            if total >= len(flat):
                break
            record_sizes.append(min(n, len(flat) - total))
            total += record_sizes[-1]
        plan = pushdown.select_records(
            record_sizes,
            pushdown.needed_intervals(shape, [box], itemsize),
        )
        offsets = np.cumsum([0] + record_sizes)
        covered = set()
        for i in plan.indices:
            covered.update(range(offsets[i], offsets[i + 1]))
        assert footprint <= covered


def test_pushdown_plan_rpc_equals_local_cut():
    server = snapserve.start_local_server()
    try:
        doc = {
            "shape": [64, 64],
            "itemsize": 4,
            "record_sizes": [4096] * 4,
            "boxes": [[[0, 16], [0, 64]], [[48, 64], [0, 64]]],
        }
        remote = snapserve.plan_remote(server.addr, doc)
        local = pushdown.plan_from_doc(doc)
        assert remote == local
        assert remote["indices"] == [0, 3]
        assert remote["selected_bytes"] == 8192
        # Malformed docs are server errors, never hangs.
        with pytest.raises((RemoteServerError, ValueError)):
            snapserve.plan_remote(server.addr, {"shape": "nope"})
    finally:
        server.stop()


def test_content_chunks_read_state_selected_cut():
    from torchsnapshot_tpu.chunkstore import chunk_object_path
    from torchsnapshot_tpu.io_preparer import _ContentChunksReadState

    records = [
        {"k": f"xs128:{format(i, '032x')}-100-raw", "n": 100}
        for i in range(5)
    ]
    state = _ContentChunksReadState(
        inner=None,
        records=records,
        dtype_name="float32",
        store_base=None,
        selected=[1, 3],
    )
    reqs = state.build_reads()
    # Only the selected records are fetched, at their ORIGINAL
    # cumulative offsets; the full-size assembly buffer is retained.
    assert state.nbytes == 500
    assert [r.path for r in reqs] == [
        chunk_object_path(records[1]["k"]),
        chunk_object_path(records[3]["k"]),
    ]
    assert [c.buffer_consumer._offset for c in reqs] == [100, 300]
    assert [c.buffer_consumer._first for c in reqs] == [True, False]
    assert state._remaining == 2


def test_differently_meshed_chunked_restore_through_fleet_bit_exact(
    monkeypatch,
):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    # 64 KiB chunks over a 1 MiB array → 16 content chunks whose ring
    # owners spread over the fleet.
    monkeypatch.setenv("TPUSNAPSHOT_CHUNK_BYTES", str(64 << 10))
    root = _mem_root("mesh")
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("x",))
    arr = jax.device_put(
        jnp.arange(512 * 512, dtype=jnp.float32).reshape(512, 512),
        NamedSharding(mesh, P("x")),
    )
    Snapshot.take(f"{root}/step-1", {"m": StateDict(w=arr)}, chunks=True)
    lf = sv_fleet.start_local_fleet(n=3)
    try:
        before = snapserve.stats_snapshot()
        mesh2 = Mesh(np.array(jax.devices()[:8]).reshape(8), ("x",))
        t = {
            "m": StateDict(
                w=jax.device_put(
                    jnp.zeros((512, 512), jnp.float32),
                    NamedSharding(mesh2, P(None, "x")),
                )
            )
        }
        RemoteSnapshot(f"{root}/step-1", addr=lf.addr_spec).restore(t)
        assert np.array_equal(np.asarray(t["m"]["w"]), np.asarray(arr))
        after = snapserve.stats_snapshot()
        assert after["remote_objects"] > before["remote_objects"]
        assert after["fallback_objects"] == before["fallback_objects"]
        # Fleet routing spread the reads over more than one member.
        assert len(after["servers"]) > 1
    finally:
        lf.stop()


# ------------------------------------------------------- tenant admission


def test_tenant_quota_defers_over_quota_and_isolates_small_tenant():
    async def _run():
        adm = TenantAdmission(100)
        order = []

        async def big(tag, n):
            await adm.acquire("big", n)
            order.append(f"grant:{tag}")

        # 60 + 60 > 100: the second BIG request parks.
        await big("b1", 60)
        task_b2 = asyncio.ensure_future(big("b2", 60))
        await asyncio.sleep(0.01)
        assert not task_b2.done()
        st = adm.stats()
        assert st["big"]["deferrals"] == 1
        assert st["big"]["inflight_bytes"] == 60
        # The SMALL tenant is untouched by big's backlog.
        await asyncio.wait_for(adm.acquire("small", 50), timeout=1)
        assert adm.stats()["small"]["deferrals"] == 0
        # Oversize admitted alone when its tenant is idle.
        await asyncio.wait_for(adm.acquire("huge", 10_000), timeout=1)
        adm.release("huge", 10_000)
        # Releasing big's first grant pumps the parked request.
        adm.release("big", 60)
        await asyncio.wait_for(task_b2, timeout=1)
        assert order == ["grant:b1", "grant:b2"]
        adm.release("big", 60)
        adm.release("small", 50)
        st = adm.stats()
        assert st["big"]["inflight_bytes"] == 0
        assert st["big"]["grant_wait_p95_s"] >= 0.0

    asyncio.run(_run())


def test_tenant_quota_isolation_under_concurrency():
    """Against a REAL quota-limited server: a saturating tenant's
    oversize responses serialize behind their own quota (deferrals
    counted) while a small tenant's sequential reads all grant with
    zero wait — and every byte stays correct for both."""
    root = _mem_root("tenants")
    # The saturating responses must exceed BOTH the quota (so they are
    # oversize-admitted alone and concurrent peers defer) and the
    # loopback socket buffers (so the send genuinely yields while the
    # quota is held): 4 MiB responses against a 1 MiB quota.
    payload = (np.arange(4 << 20, dtype=np.uint8) % 251).tobytes()
    backend = url_to_storage_plugin(root)
    try:
        asyncio.run(backend.write(IOReq(path="blob", data=payload)))
        asyncio.run(
            backend.write(IOReq(path="tiny", data=payload[:4096]))
        )
    finally:
        backend.close()
    server = snapserve.start_local_server(tenant_quota_bytes=1 << 20)
    try:
        errors = []

        def _reads(tenant, path, want, n):
            plugin = snapserve.SnapServePlugin(f"{server.addr}/{root}")
            plugin.tenant_override = tenant
            try:

                async def _go():
                    for _ in range(n):
                        req = IOReq(path=path)
                        await plugin.read(req)
                        assert bytes(req.data) == want

                asyncio.run(_go())
            except Exception as e:
                errors.append(repr(e))
            finally:
                plugin.close()

        sat = [
            threading.Thread(
                target=_reads,
                args=("sat", "blob", payload, 3),
                daemon=True,
            )
            for _ in range(4)
        ]
        small = threading.Thread(
            target=_reads,
            args=("small", "tiny", payload[:4096], 8),
            daemon=True,
        )
        for t in sat:
            t.start()
        small.start()
        for t in sat + [small]:
            t.join(timeout=120)
        assert not errors, errors
        tenants = snapserve.fetch_server_stats(server.addr)["tenants"]
        # 4 MiB responses against a 1 MiB quota: every concurrent
        # saturating request beyond the first defers (admitted alone
        # when the tenant drains) — never errors.
        assert tenants["sat"]["deferrals"] > 0
        assert tenants["small"]["deferrals"] == 0
        assert tenants["small"]["grant_wait_p95_s"] == 0.0
        assert tenants["sat"]["requests"] == 12
        assert tenants["small"]["requests"] == 8
    finally:
        server.stop()


# -------------------------------------------------------- failover ladder


def test_owner_death_fails_over_to_replica_never_direct():
    root = _mem_root("ladder")
    # Enough distinct object paths that the lone survivor cannot own
    # ALL of them (two of three members dead → some owner is dead).
    state = _state(n_params=12, n=1024)
    Snapshot.take(root, state)
    lf = sv_fleet.start_local_fleet(n=3)
    try:
        before = snapserve.stats_snapshot()
        sv_fleet.kill_local_member("m0")
        sv_fleet.kill_local_member("m1")
        target = _zero_like(state)
        RemoteSnapshot(root, addr=lf.addr_spec).restore(target)
        _assert_exact(target, state)
        after = snapserve.stats_snapshot()
        # The survivor absorbed the dead members' shares: zero direct
        # fallbacks, and every object owned by a dead member counted as
        # failover (first touch) or owner_miss (after the down latch).
        assert after["fallback_objects"] == before["fallback_objects"]
        assert (
            after["failover_objects"] + after["owner_misses"]
            > before["failover_objects"] + before["owner_misses"]
        )
        # Degraded-but-absorbed routing is doctor-visible as a WARN
        # (critical is reserved for fleet-exhausted direct fallbacks).
        findings = diagnose_report(_restore_report(root))
        fleet_findings = [
            f for f in findings if f.rule == "fleet-degraded"
        ]
        assert fleet_findings and fleet_findings[0].severity == "warn"
    finally:
        lf.stop()


def test_fleet_exhausted_degrades_direct_and_fires_fleet_degraded():
    root = _mem_root("exhaust")
    state = _state(n_params=2, n=512)
    Snapshot.take(root, state)
    lf = sv_fleet.start_local_fleet(n=3)
    try:
        for name in list(sv_fleet.local_member_names()):
            sv_fleet.kill_local_member(name)
        before = snapserve.stats_snapshot()
        target = _zero_like(state)
        RemoteSnapshot(root, addr=lf.addr_spec).restore(target)
        _assert_exact(target, state)
        after = snapserve.stats_snapshot()
        assert after["fallback_objects"] > before["fallback_objects"]
        assert (
            after["reasons"].get("fleet-exhausted", 0)
            > before["reasons"].get("fleet-exhausted", 0)
        )
        report = _restore_report(root)
        findings = diagnose_report(report)
        fleet_findings = [
            f for f in findings if f.rule == "fleet-degraded"
        ]
        assert fleet_findings and fleet_findings[0].severity == "critical"
        assert any(
            f.rule == "read-plane-degraded" for f in findings
        )
    finally:
        lf.stop()


@pytest.mark.faultline
@pytest.mark.parametrize("victim", ["m0", "m1", "m2"])
def test_fleet_crash_matrix_member_killed_mid_fanout(victim):
    """Each of the 3 members killed deterministically mid-32-client
    fan-out: every client stays bit-exact with ZERO visible errors,
    the failover counter moves, and NO client leaves the fleet for the
    direct backend (replicas were alive the whole time)."""
    root = _mem_root(f"matrix-{victim}")
    # 24 params + manifest = 25 distinct ring keys: the probability the
    # victim owns NONE of them (which would leave the failover counter
    # still) is (2/3)^25 — negligible.
    state = _state(n_params=24, n=1024)
    Snapshot.take(root, state)
    lf = sv_fleet.start_local_fleet(n=3)
    try:
        before = snapserve.stats_snapshot()
        sched = fl.FaultSchedule().kill_fleet_member(victim, nth=20)
        errors = []
        barrier = threading.Barrier(32)

        def _one():
            try:
                barrier.wait(timeout=60)
                target = _zero_like(state)
                RemoteSnapshot(root, addr=lf.addr_spec).restore(target)
                _assert_exact(target, state)
            except Exception as e:
                errors.append(repr(e))

        with fl.inject(sched) as ctl:
            threads = [
                threading.Thread(target=_one, daemon=True)
                for _ in range(32)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
        assert not errors, errors[:5]
        assert ctl.fault_counts().get("killmember") == 1
        assert victim not in sv_fleet.local_member_names()
        after = snapserve.stats_snapshot()
        assert (
            after["failover_objects"] + after["owner_misses"]
            > before["failover_objects"] + before["owner_misses"]
        )
        assert after["fallback_objects"] == before["fallback_objects"]
    finally:
        lf.stop()


@pytest.mark.faultline
def test_slow_fleet_member_keeps_serving_correct_bytes():
    root = _mem_root("slowmember")
    state = _state(n_params=2, n=512)
    Snapshot.take(root, state)
    lf = sv_fleet.start_local_fleet(n=2)
    try:
        sched = fl.FaultSchedule().slow_fleet_member(
            "m0", seconds=0.02, nth=1
        )
        with fl.inject(sched) as ctl:
            target = _zero_like(state)
            RemoteSnapshot(root, addr=lf.addr_spec).restore(target)
        _assert_exact(target, state)
        assert ctl.fault_counts().get("slowmember") == 1
        # Slow-but-alive: no fallbacks, no member death.
        assert set(sv_fleet.local_member_names()) == {"m0", "m1"}
    finally:
        lf.stop()


# ------------------------------------------------------------- conn pools


class _FakeTransport:
    def __init__(self, log):
        self._log = log

    def abort(self):
        self._log.append("abort")


class _FakeWriter:
    def __init__(self, log, closing=False):
        self.transport = _FakeTransport(log)
        self._closing = closing

    def is_closing(self):
        return self._closing

    def close(self):
        self.transport.abort()


def test_conn_pool_sweeps_closed_loop_entries():
    """The id-recycle liveness bug: an entry whose loop has been
    CLOSED must be swept on the next lookup (its sockets can never be
    awaited again), even when the dict key never collides — and a
    recycled id with a dead loop object must not hand out its conns."""
    server = snapserve.start_local_server()
    try:
        plugin = snapserve.SnapServePlugin(
            f"{server.addr}/memory://pool-test/x"
        )
        try:
            dead_loop = asyncio.new_event_loop()
            dead_loop.close()
            log = []
            stale_conn = (object(), _FakeWriter(log))
            addr = server.addr

            async def _use():
                # Plant two stale entries: one under an arbitrary key
                # (leak scenario), one under THIS loop's id (recycle
                # scenario — same id, different loop object).
                loop = asyncio.get_running_loop()
                plugin._pools[(987654321, addr)] = (
                    dead_loop,
                    [stale_conn],
                )
                plugin._pools[(id(loop), addr)] = (
                    dead_loop,
                    [(object(), _FakeWriter(log))],
                )
                conn = await plugin._checkout(addr)
                # The dial produced a REAL conn, not a planted one.
                assert conn is not stale_conn
                plugin._checkin(addr, conn)
                assert (987654321, addr) not in plugin._pools

            asyncio.run(_use())
            # Both planted conns were aborted, not leaked.
            assert log.count("abort") == 2
        finally:
            plugin.close()
    finally:
        server.stop()


def test_conn_pool_two_sequential_loops_at_same_id_stay_live():
    """Regression for the loop-id-recycle scenario end-to-end: two
    sequential asyncio.run() loops (CPython may allocate the second
    loop at the first's address) must each get live sockets."""
    server = snapserve.start_local_server()
    root = _mem_root("pool2")
    payload = b"y" * 2048
    backend = url_to_storage_plugin(root)
    try:
        asyncio.run(backend.write(IOReq(path="obj", data=payload)))
    finally:
        backend.close()
    try:
        plugin = snapserve.SnapServePlugin(f"{server.addr}/{root}")
        try:
            for _ in range(3):

                async def _read():
                    req = IOReq(path="obj")
                    await plugin.read(req)
                    return bytes(req.data)

                assert asyncio.run(_read()) == payload
            before = snapserve.stats_snapshot()["fallback_objects"]
            assert (
                snapserve.stats_snapshot()["fallback_objects"] == before
            )
        finally:
            plugin.close()
    finally:
        server.stop()


def test_conn_pool_skips_closing_conns_on_checkout():
    server = snapserve.start_local_server()
    try:
        plugin = snapserve.SnapServePlugin(
            f"{server.addr}/memory://pool-test/y"
        )
        try:
            log = []
            closing = (object(), _FakeWriter(log, closing=True))
            addr = server.addr

            async def _use():
                pool = plugin._pool(addr)
                pool.append(closing)
                conn = await plugin._checkout(addr)
                assert conn is not closing
                plugin._checkin(addr, conn)

            asyncio.run(_use())
            assert "abort" in log
        finally:
            plugin.close()
    finally:
        server.stop()


# ----------------------------------------------------------- fleet plugin


def test_env_fleet_addrs_merge_additively(monkeypatch):
    lf = sv_fleet.start_local_fleet(n=2)
    try:
        a0, a1 = lf.addrs
        monkeypatch.setenv(
            sv_fleet.FLEET_ADDRS_ENV_VAR, f"{a1},{a0}"
        )
        plugin = snapserve.SnapServePlugin(f"{a0}/memory://env-merge/x")
        try:
            # URL seed first, env members appended without duplicates.
            assert plugin._addrs == [a0, a1]
            assert plugin._fleet is not None
        finally:
            plugin.close()
    finally:
        lf.stop()


def test_single_server_url_keeps_legacy_single_path():
    server = snapserve.start_local_server()
    try:
        plugin = snapserve.SnapServePlugin(
            f"{server.addr}/memory://legacy/x"
        )
        try:
            assert plugin._fleet is None
        finally:
            plugin.close()
    finally:
        server.stop()
