"""Codec registry (torchsnapshot_tpu/codecs.py): lossless round-trips,
the int8 quantizer's tolerance contract, and codec-plan resolution."""

import numpy as np
import pytest

from torchsnapshot_tpu import codecs


def _payload(n=5000, seed=0, dtype=np.float32):
    return np.random.RandomState(seed).randn(n).astype(dtype)


class TestLossless:
    def test_zlib_round_trip_bit_exact(self):
        raw = _payload().tobytes()
        enc = codecs.encode("zlib", raw)
        assert codecs.decode("zlib", enc) == raw

    def test_identity(self):
        raw = b"abc" * 100
        assert codecs.encode(None, raw) == raw
        assert codecs.decode(None, raw) == raw
        assert codecs.encode("identity", raw) == raw

    @pytest.mark.skipif(
        "zstd" not in codecs.available_codecs(),
        reason="no zstd backend importable in this environment",
    )
    def test_zstd_round_trip_bit_exact(self):
        raw = _payload().tobytes()
        enc = codecs.encode("zstd", raw)
        assert codecs.decode("zstd", enc) == raw

    def test_zstd_unavailable_raises_clearly(self):
        if "zstd" in codecs.available_codecs():
            pytest.skip("zstd available here")
        with pytest.raises(codecs.CodecUnavailable):
            codecs.check_codec("zstd")

    def test_best_lossless_is_usable(self):
        name = codecs.best_lossless()
        raw = _payload().tobytes()
        assert codecs.decode(name, codecs.encode(name, raw)) == raw

    def test_unknown_codec_rejected(self):
        with pytest.raises(ValueError):
            codecs.encode("lzma9000", b"x")
        with pytest.raises(ValueError):
            codecs.check_codec("lzma9000")


class TestInt8:
    @pytest.mark.parametrize("dtype", ["float32", "float16", "bfloat16"])
    def test_within_documented_tolerance(self, dtype):
        import ml_dtypes

        np_dtype = (
            ml_dtypes.bfloat16 if dtype == "bfloat16" else np.dtype(dtype)
        )
        arr = _payload(4096 + 17, seed=3).astype(np_dtype)
        enc = codecs.encode("int8", arr.tobytes(), dtype)
        dec = np.frombuffer(codecs.decode("int8", enc, dtype), np_dtype)
        err = np.abs(
            dec.astype(np.float32) - arr.astype(np.float32)
        ).max()
        bound = codecs.quant_error_bound(
            arr.astype(np.float32), dtype_name=dtype
        )
        assert 0 < err <= bound

    def test_ratio_roughly_4x_for_float32(self):
        raw = _payload(1 << 16).tobytes()
        enc = codecs.encode("int8", raw, "float32")
        assert len(enc) < 0.3 * len(raw)

    def test_constant_block_is_exact(self):
        arr = np.full(2048, 3.25, np.float32)
        enc = codecs.encode("int8", arr.tobytes(), "float32")
        dec = np.frombuffer(codecs.decode("int8", enc, "float32"), np.float32)
        assert np.array_equal(dec, arr)

    def test_nonfinite_payload_unsuitable(self):
        arr = _payload(2048)
        arr[100] = np.inf
        with pytest.raises(codecs.CodecUnsuitable):
            codecs.encode("int8", arr.tobytes(), "float32")

    def test_int_dtype_unsuitable(self):
        arr = np.arange(2048, dtype=np.int32)
        with pytest.raises(codecs.CodecUnsuitable):
            codecs.encode("int8", arr.tobytes(), "int32")

    def test_frame_self_verifies(self):
        raw = _payload(2048).tobytes()
        enc = bytearray(codecs.encode("int8", raw, "float32"))
        enc[-1] ^= 0xFF  # flip a quantized byte
        with pytest.raises(RuntimeError, match="crc"):
            codecs.decode("int8", bytes(enc), "float32")

    def test_non_frame_bytes_rejected(self):
        with pytest.raises(RuntimeError, match="TSQ1"):
            codecs.decode("int8", b"not a frame at all", "float32")


class TestPlans:
    def test_none_spec_is_identity(self, monkeypatch):
        monkeypatch.delenv("TPUSNAPSHOT_CODEC", raising=False)
        plan = codecs.resolve_codec_plan(None)
        assert plan.codec_for("model/w") is None

    def test_bare_name_applies_everywhere(self):
        plan = codecs.resolve_codec_plan("zlib")
        assert plan.codec_for("model/w") == "zlib"
        assert plan.codec_for("opt/mu/w") == "zlib"

    def test_glob_mapping_specific_first(self):
        plan = codecs.resolve_codec_plan({"opt/*": "int8", "*": "zlib"})
        assert plan.codec_for("opt/mu/w", dtype_name="float32") == "int8"
        assert plan.codec_for("model/w", dtype_name="float32") == "zlib"

    def test_env_string_form(self, monkeypatch):
        monkeypatch.setenv("TPUSNAPSHOT_CODEC", "opt/*=int8,*=zlib")
        plan = codecs.resolve_codec_plan(None)
        assert plan.codec_for("opt/nu/w", dtype_name="float32") == "int8"
        assert plan.codec_for("model/w") == "zlib"

    def test_lossy_fallback_rejected(self):
        with pytest.raises(ValueError, match="explicit per-leaf glob"):
            codecs.resolve_codec_plan("int8")
        with pytest.raises(ValueError, match="explicit per-leaf glob"):
            codecs.resolve_codec_plan({"*": "int8"})

    def test_lossy_degrades_on_unquantizable_leaf(self):
        plan = codecs.resolve_codec_plan({"opt/*": "int8"})
        # int dtype and PRNG key data must never quantize.
        assert plan.codec_for("opt/step", dtype_name="int64") is None
        assert (
            plan.codec_for(
                "opt/key", dtype_name="uint32", prng_impl="threefry2x32"
            )
            is None
        )

    def test_lossy_degrade_falls_through_to_fallback_rule(self):
        # An unquantizable leaf under a lossy glob still gets the
        # user's lossless fallback, not raw identity.
        plan = codecs.resolve_codec_plan({"opt/*": "int8", "*": "zlib"})
        assert plan.codec_for("opt/step", dtype_name="int64") == "zlib"
        assert (
            plan.codec_for(
                "opt/key", dtype_name="uint32", prng_impl="threefry2x32"
            )
            == "zlib"
        )
        assert plan.codec_for("opt/mu", dtype_name="float32") == "int8"

    def test_identity_aliases(self):
        plan = codecs.resolve_codec_plan({"*": "none"})
        assert plan.codec_for("model/w") is None
