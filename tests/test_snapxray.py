"""snapxray: cross-process causal tracing + the restore consume
micro-profiler (ISSUE 11).

Pinned here:

- take/restore roots stamp a contextvar trace id; every pipeline span
  under the root carries it.
- snapserve RPCs propagate the context in request frames: the server's
  spans adopt the client's trace id and the client/server emit paired
  Perfetto flow events (``s``/``t``/``f``) under one flow id.
- A mid-restore server kill keeps the degraded direct reads under the
  SAME trace id, with the transition visible as a
  ``snapserve.degraded`` instant (satellite 3).
- hottier replicate/drain/tierdown spans inherit the originating
  take's trace id, however long after the ack the drain runs.
- The restore flight report carries a consume sub-phase breakdown
  whose in-consume sub-steps plus ``other`` sum to the consume wall
  exactly, plus consume GB/s as a fraction of the H2D probe; the
  ledger restore digest folds it; the doctor's
  ``consume-dominated-restore`` rule names the dominant sub-step.
- telemetry.merge accepts multi-PROCESS inputs (ranks + a server),
  aligns a barrier-less server via paired flows, counts cross-process
  flows, and names the gating process in the critical path.
- Trace files are per-process: role/pid env suffixes, and a forked
  child's flush can never clobber the parent's file.
"""

import json
import os
import uuid

import numpy as np
import pytest

import jax.numpy as jnp

from torchsnapshot_tpu import RemoteSnapshot, Snapshot, StateDict
from torchsnapshot_tpu import faultline as fl
from torchsnapshot_tpu import hottier, snapserve, tracing
from torchsnapshot_tpu.telemetry import consume_profile
from torchsnapshot_tpu.telemetry import ledger as runledger
from torchsnapshot_tpu.telemetry import merge, summarize
from torchsnapshot_tpu.telemetry.doctor import diagnose_report


# ----------------------------------------------------------------- helpers


@pytest.fixture(autouse=True)
def _clean_tracing_and_servers(monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_SNAPSERVE_DOWN_COOLDOWN_S", "0.2")
    tracing.disable()
    yield
    tracing.disable()
    snapserve.kill_local_servers()


def _mem_root(tag):
    return f"memory://snapxray-{tag}-{uuid.uuid4().hex[:10]}/run"


def _state(n_params=3, n=2048, seed=3):
    rng = np.random.default_rng(seed)
    return {
        "m": StateDict(
            **{
                f"p{i}": rng.standard_normal(n).astype(np.float32)
                for i in range(n_params)
            }
        )
    }


def _zero_like(state):
    return {
        "m": StateDict(
            **{k: np.zeros_like(v) for k, v in state["m"].items()}
        )
    }


def _assert_exact(target, state):
    for k, v in state["m"].items():
        np.testing.assert_array_equal(target["m"][k], v)


def _flush_events(path):
    tracing.flush()
    with open(path) as f:
        return json.load(f)["traceEvents"]


def _spans(events, name):
    return [
        e for e in events if e.get("name") == name and e.get("ph") == "b"
    ]


def _trace_ids(events, name):
    return {
        (e.get("args") or {}).get("trace")
        for e in _spans(events, name)
    }


def _restore_report(root):
    import asyncio

    from torchsnapshot_tpu.storage_plugin import url_to_storage_plugin
    from torchsnapshot_tpu.telemetry import report as flight

    storage = url_to_storage_plugin(root)
    try:
        return asyncio.run(
            flight.aread_json(storage, flight.RESTORE_REPORT_FNAME)
        )
    finally:
        storage.close()


# ----------------------------------------------------- trace-context roots


def test_take_and_restore_roots_stamp_distinct_trace_ids(tmp_path):
    trace_path = str(tmp_path / "t.json")
    tracing.enable(trace_path)
    root = _mem_root("roots")
    state = _state()
    Snapshot.take(root, state)
    target = _zero_like(state)
    Snapshot(root).restore(target)
    events = _flush_events(trace_path)

    take_traces = _trace_ids(events, "Snapshot.take")
    restore_traces = _trace_ids(events, "Snapshot.restore")
    assert len(take_traces) == 1 and None not in take_traces
    assert len(restore_traces) == 1 and None not in restore_traces
    (take_id,) = take_traces
    (restore_id,) = restore_traces
    assert take_id != restore_id
    assert take_id.startswith("take-")
    assert restore_id.startswith("restore-")
    # Every pipeline span under a root carries that root's id.
    for name in ("stage", "write"):
        assert _trace_ids(events, name) == {take_id}
    for name in ("read", "consume"):
        assert _trace_ids(events, name) == {restore_id}


def test_chunked_take_encode_spans_inherit_take_trace(tmp_path):
    # Regression (SNAP008 true positive): ChunkStager hands _stage_sync
    # to the staging executor; without explicit adoption the encode
    # span ran in a fresh context and attributed to no trace.
    trace_path = str(tmp_path / "enc.json")
    tracing.enable(trace_path)
    root = _mem_root("encode")
    rng = np.random.default_rng(7)
    state = {
        "m": StateDict(
            w=rng.standard_normal(262144).astype(np.float32)
        )
    }
    Snapshot.take(root, state, chunks=True, codec="zlib")
    events = _flush_events(trace_path)
    (take_id,) = _trace_ids(events, "Snapshot.take")
    encode_traces = _trace_ids(events, "encode")
    assert encode_traces, "expected encode spans from the codec stage"
    assert encode_traces == {take_id}, encode_traces


def test_finalize_via_pool_keeps_restore_trace(tmp_path, monkeypatch):
    # Regression (SNAP008 true positive): when finalize hops to the
    # finalize pool (engine done-callback thread), the assemble span
    # ran in the pool thread's fresh context. The plan captures the
    # restore's trace id at plan-build and re-adopts it.
    import torchsnapshot_tpu.io_preparer as iop

    monkeypatch.setattr(iop, "_on_h2d_engine_thread", lambda: True)
    trace_path = str(tmp_path / "fin.json")
    tracing.enable(trace_path)
    root = _mem_root("finalize")
    state = _state()
    Snapshot.take(root, state)
    target = _zero_like(state)
    Snapshot(root).restore(target)
    _assert_exact(target, state)
    events = _flush_events(trace_path)
    (restore_id,) = _trace_ids(events, "Snapshot.restore")
    assemble_traces = _trace_ids(events, "assemble")
    assert assemble_traces, "expected assemble spans from finalize"
    assert assemble_traces == {restore_id}, assemble_traces


def test_trace_context_cheap_and_absent_outside_roots():
    assert tracing.current_trace_id() is None
    with tracing.trace_scope("take") as tid:
        assert tracing.current_trace_id() == tid
        with tracing.adopt_trace("other-1"):
            assert tracing.current_trace_id() == "other-1"
        assert tracing.current_trace_id() == tid
    assert tracing.current_trace_id() is None
    # flow ids without tracing enabled AND without a scope: nothing to
    # bind to, so no id is minted.
    assert tracing.flow_start("x") is None
    with tracing.trace_scope("restore"):
        # Scope active but tracing off: the id still exists for the
        # wire (a tracing-on server can bind to it).
        assert tracing.flow_start("x") is not None


# ----------------------------------------------- snapserve propagation


def test_rpc_flow_events_and_server_spans_join_client_trace(tmp_path):
    trace_path = str(tmp_path / "rpc.json")
    root = _mem_root("rpc")
    state = _state()
    Snapshot.take(root, state)
    server = snapserve.start_local_server()
    try:
        tracing.enable(trace_path)
        target = _zero_like(state)
        RemoteSnapshot(root, addr=server.addr).restore(target)
        events = _flush_events(trace_path)
    finally:
        server.stop()
    _assert_exact(target, state)

    restore_traces = _trace_ids(events, "Snapshot.restore")
    (restore_id,) = restore_traces
    # Server spans adopted the client's trace id (in-process server:
    # same trace file, same causal chain).
    req_traces = _trace_ids(events, "snapserve.request")
    assert req_traces == {restore_id}, req_traces
    fetch_traces = _trace_ids(events, "snapserve.backend_fetch")
    assert restore_id in fetch_traces
    # Paired flow events under shared ids: s (client out) + t (server
    # handling) + f (client response in).
    flows = {}
    for e in events:
        if e.get("ph") in ("s", "t", "f"):
            flows.setdefault(e["id"], set()).add(e["ph"])
    full = [fid for fid, phs in flows.items() if {"s", "t", "f"} <= phs]
    assert full, flows
    assert all(restore_id in fid for fid in full)
    # Cache events are visible server-side.
    assert any(
        e.get("name") in ("snapserve.cache_hit", "snapserve.cache_miss")
        for e in events
    )


@pytest.mark.faultline
def test_kill_server_keeps_trace_id_through_degraded_fallback(tmp_path):
    """Satellite 3: a mid-restore server kill keeps the fallback direct
    reads under the SAME trace id, with the degraded transition visible
    as an instant."""
    trace_path = str(tmp_path / "kill.json")
    root = _mem_root("kill")
    state = _state(n_params=6)
    Snapshot.take(root, state)
    server = snapserve.start_local_server()
    remote = RemoteSnapshot(root, addr=server.addr)
    sched = fl.FaultSchedule().kill_server(nth=3)
    tracing.enable(trace_path)
    with fl.inject(sched) as ctl:
        target = _zero_like(state)
        remote.restore(target)
    events = _flush_events(trace_path)
    _assert_exact(target, state)
    assert ctl.fault_counts().get("killserver") == 1

    (restore_id,) = _trace_ids(events, "Snapshot.restore")
    # The transition instant, under the restore's trace.
    degraded = [
        e for e in events if e.get("name") == "snapserve.degraded"
    ]
    assert degraded, "no snapserve.degraded instant in the trace"
    assert all(
        (e.get("args") or {}).get("trace") == restore_id for e in degraded
    )
    # Every read span — served AND fallback-direct — is under the same
    # trace id: one causal story across the degradation.
    assert _trace_ids(events, "read") == {restore_id}
    report = _restore_report(root)
    planes = [s.get("read_plane") for s in report["ranks"] if s]
    assert planes and planes[0]["fallback_objects"] > 0


# ----------------------------------------------------- hottier inheritance


@pytest.mark.faultline
def test_hottier_drain_spans_inherit_take_trace(tmp_path):
    trace_path = str(tmp_path / "tier.json")
    tracing.enable(trace_path)
    root = _mem_root("tier")
    with hottier.hot_tier(rank=0, world=4, k=2, drain="manual"):
        Snapshot.take(root, {"s": StateDict(w=jnp.ones((1024,)))})
        events = _flush_events(trace_path)
        (take_id,) = _trace_ids(events, "Snapshot.take")
        replicate_traces = _trace_ids(events, "hottier.replicate")
        assert replicate_traces == {take_id}, replicate_traces
        # The drain runs long after the take returned, on the drain
        # executor's own thread — its spans still carry the take's id.
        hottier.drain_now()
        events = _flush_events(trace_path)
        assert _trace_ids(events, "hottier.drain") == {take_id}
        assert _trace_ids(events, "hottier.tierdown") == {take_id}
    hottier.reset_hot_tier()


# ------------------------------------------------- consume micro-profiler


def test_restore_report_carries_reconciling_consume_breakdown(
    tmp_path, monkeypatch
):
    monkeypatch.setenv("TPUSNAPSHOT_H2D_PROBE_MIN_BYTES", "0")
    monkeypatch.setenv("TPUSNAPSHOT_H2D_PROBE_BYTES", str(1 << 20))
    root = _mem_root("prof")
    state = _state(n_params=4)
    Snapshot.take(root, state)
    target = _zero_like(state)
    Snapshot(root).restore(target)
    _assert_exact(target, state)

    report = _restore_report(root)
    profile = next(
        s["consume_profile"]
        for s in report["ranks"]
        if s and s.get("consume_profile")
    )
    substeps = profile["substeps"]
    assert profile["bytes"] > 0
    # Acceptance: the in-consume sub-steps (``other`` included) sum to
    # the consume wall exactly; read_wait and h2d_overlap (the overlap
    # engine's transfer seconds) sit beside them.
    in_consume = sum(
        entry["seconds"]
        for name, entry in substeps.items()
        if name not in ("read_wait", "h2d_overlap", "overlap_other")
    )
    assert in_consume == pytest.approx(profile["consume_s"], abs=1e-3)
    assert "read_wait" in substeps
    # The H2D probe anchors consume GB/s against the hardware bound.
    assert profile["h2d_probe_gbps"] > 0
    assert profile["h2d_fraction"] == pytest.approx(
        profile["consume_gbps"] / profile["h2d_probe_gbps"], rel=1e-3
    )


def test_small_restore_skips_h2d_probe(monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_H2D_PROBE_MIN_BYTES", str(1 << 30))
    root = _mem_root("noprobe")
    state = _state(n_params=1, n=256)
    Snapshot.take(root, state)
    Snapshot(root).restore(_zero_like(state))
    report = _restore_report(root)
    profile = next(
        (
            s["consume_profile"]
            for s in report["ranks"]
            if s and s.get("consume_profile")
        ),
        None,
    )
    assert profile is not None
    assert "h2d_probe_gbps" not in profile


def test_compressed_restore_attributes_decode_seconds():
    root = _mem_root("zlib")
    state = _state(n_params=2, n=1 << 16)
    Snapshot.take(root, state, compression="zlib")
    Snapshot(root).restore(_zero_like(state))
    report = _restore_report(root)
    profile = next(
        s["consume_profile"]
        for s in report["ranks"]
        if s and s.get("consume_profile")
    )
    assert profile["substeps"]["decode"]["seconds"] > 0
    assert profile["substeps"]["decode"]["bytes"] > 0


def test_chunked_restore_attributes_decode_and_verify():
    root = _mem_root("chunks")
    state = _state(n_params=2, n=1 << 16)
    Snapshot.take(root, state, chunks=True, codec="zlib")
    target = _zero_like(state)
    Snapshot(root).restore(target)
    _assert_exact(target, state)
    report = _restore_report(root)
    profile = next(
        s["consume_profile"]
        for s in report["ranks"]
        if s and s.get("consume_profile")
    )
    # Chunk-store restores decode (codec) AND verify (content
    # fingerprint) every chunk inside the consume executor.
    assert profile["substeps"]["decode"]["seconds"] > 0
    assert profile["substeps"]["verify"]["seconds"] > 0
    assert profile["substeps"]["reassemble"]["bytes"] > 0


def test_ledger_restore_digest_folds_consume_block(monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_H2D_PROBE_MIN_BYTES", "0")
    monkeypatch.setenv("TPUSNAPSHOT_H2D_PROBE_BYTES", str(1 << 20))
    root = _mem_root("ledger")
    state = _state()
    Snapshot.take(root, state)
    Snapshot(root).restore(_zero_like(state))
    records, _ = runledger.read_records(root)
    restores = [r for r in records if r["kind"] == "restore"]
    assert restores, records
    consume = restores[-1]["consume"]
    assert consume is not None
    assert consume["consume_s"] >= 0
    assert set(consume["substeps"]) >= {"other"}
    assert consume["h2d_fraction"] > 0


def test_concurrent_restores_do_not_cross_attribute_profiles():
    """Two restores in flight: each report's breakdown reflects only
    its own traffic (contextvar scoping, as for read_plane)."""
    import threading

    roots = [_mem_root("conc-a"), _mem_root("conc-b")]
    states = [_state(seed=1), _state(seed=2)]
    for root, state in zip(roots, states):
        Snapshot.take(root, state)
    errors = []

    def _restore(root, state):
        try:
            target = _zero_like(state)
            Snapshot(root).restore(target)
            _assert_exact(target, state)
        except Exception as e:  # pragma: no cover
            errors.append(repr(e))

    threads = [
        threading.Thread(target=_restore, args=(r, s))
        for r, s in zip(roots, states)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    for root in roots:
        report = _restore_report(root)
        profile = next(
            s["consume_profile"]
            for s in report["ranks"]
            if s and s.get("consume_profile")
        )
        in_consume = sum(
            e["seconds"]
            for n, e in profile["substeps"].items()
            if n not in ("read_wait", "h2d_overlap", "overlap_other")
        )
        # Cross-attribution would break the per-restore reconciliation
        # (one report absorbing the other's sub-step seconds).
        assert in_consume == pytest.approx(
            profile["consume_s"], abs=1e-3
        )


# ------------------------------------------------------------- doctor rule


def _synthetic_consume_report(dominant="decode"):
    substeps = {
        "decode": {"count": 10, "seconds": 2.0, "bytes": 1 << 30},
        "verify": {"count": 10, "seconds": 1.0, "bytes": 1 << 30},
        "device_put": {"count": 10, "seconds": 0.5, "bytes": 1 << 30},
        "other": {"count": 0, "seconds": 0.5, "bytes": 0},
        "read_wait": {"count": 10, "seconds": 99.0, "bytes": 0},
    }
    if dominant == "device_put":
        substeps["device_put"]["seconds"] = 30.0
    return {
        "format_version": 1,
        "kind": "restore",
        "path": "memory://x",
        "world_size": 1,
        "ranks": [
            {
                "rank": 0,
                "wall_s": 5.0,
                "phases": {"read_s": 0.3, "consume_s": 4.0},
                "bytes": 1 << 30,
                "consume_profile": {
                    "substeps": substeps,
                    "consume_s": 4.0,
                    "consume_gbps": 0.25,
                    "h2d_probe_gbps": 2.5,
                    "h2d_fraction": 0.1,
                },
            }
        ],
        "totals": {"bytes": 1 << 30, "wall_s": 5.0},
    }


def test_doctor_names_dominant_substep_with_specific_remediation():
    findings = diagnose_report(_synthetic_consume_report())
    finding = next(
        f for f in findings if f.rule == "consume-dominated-restore"
    )
    assert finding.evidence["dominant_substep"] == "decode"
    assert "decode" in finding.title
    assert "zstd" in finding.remediation  # decode-specific advice
    assert finding.evidence["consume_h2d_fraction"] == pytest.approx(0.1)
    # read_wait is NOT an in-consume sub-step and must never be named
    # dominant even when large.
    assert "read_wait" not in finding.evidence["substeps_s"]

    findings = diagnose_report(
        _synthetic_consume_report(dominant="device_put")
    )
    finding = next(
        f for f in findings if f.rule == "consume-dominated-restore"
    )
    assert finding.evidence["dominant_substep"] == "device_put"
    # Post-fastlane advice: device_put dominating consume means the
    # overlap engine is not engaging — the remediation names the
    # streaming pipeline's tuning envs.
    assert "TPUSNAPSHOT_H2D_DEPTH" in finding.remediation


# ------------------------------------------------------- multi-process merge


def _client_doc(epoch=1_700_000_000.0):
    fid = "restore-abc/100.1"
    return {
        "traceEvents": [
            {"name": "read", "cat": "snapshot", "ph": "b", "id": 1,
             "ts": 0.0, "pid": 100, "tid": 1,
             "args": {"trace": "restore-abc"}},
            {"name": "read", "cat": "snapshot", "ph": "e", "id": 1,
             "ts": 400_000.0, "pid": 100, "tid": 1},
            {"name": "snapserve.rpc", "cat": "flow", "ph": "s",
             "id": fid, "ts": 10_000.0, "pid": 100, "tid": 1},
            {"name": "snapserve.rpc", "cat": "flow", "ph": "f",
             "bp": "e", "id": fid, "ts": 210_000.0, "pid": 100,
             "tid": 1},
            {"name": "consume", "cat": "snapshot", "ph": "b", "id": 2,
             "ts": 400_000.0, "pid": 100, "tid": 1},
            {"name": "consume", "cat": "snapshot", "ph": "e", "id": 2,
             "ts": 900_000.0, "pid": 100, "tid": 1},
        ],
        "metadata": {
            "clock_epoch_s": epoch,
            "rank": 0,
            "host": "client-host",
            "pid": 100,
        },
    }


def _server_doc(epoch=1_700_000_000.0, skew_s=0.0):
    fid = "restore-abc/100.1"
    # True wall times sit inside the client's s/f bracket; the recorded
    # epoch carries the injected skew.
    return {
        "traceEvents": [
            {"name": "snapserve.rpc", "cat": "flow", "ph": "t",
             "id": fid, "ts": 110_000.0, "pid": 999, "tid": 1},
            {"name": "snapserve.request", "cat": "snapshot", "ph": "b",
             "id": 1, "ts": 105_000.0, "pid": 999, "tid": 1,
             "args": {"trace": "restore-abc"}},
            {"name": "snapserve.request", "cat": "snapshot", "ph": "e",
             "id": 1, "ts": 200_000.0, "pid": 999, "tid": 1},
            {"name": "snapserve.backend_fetch", "cat": "snapshot",
             "ph": "b", "id": 2, "ts": 120_000.0, "pid": 999, "tid": 1},
            {"name": "snapserve.backend_fetch", "cat": "snapshot",
             "ph": "e", "id": 2, "ts": 190_000.0, "pid": 999, "tid": 1},
        ],
        "metadata": {
            "clock_epoch_s": epoch + skew_s,
            "rank": 0,
            "host": "server-host",
            "pid": 999,
            "role": "server",
        },
    }


def test_merge_multiprocess_client_plus_server(tmp_path, capsys):
    a = tmp_path / "client.json"
    b = tmp_path / "server.json"
    a.write_text(json.dumps(_client_doc()))
    b.write_text(json.dumps(_server_doc()))
    merged_path = str(tmp_path / "m.json")
    assert (
        merge.main([str(a), str(b), "-o", merged_path, "--json"]) == 0
    )
    info = json.loads(capsys.readouterr().out)
    # A server doc with the same rank number is NOT a duplicate-rank
    # error: it is a distinct process.
    assert info["cross_process_flows"] >= 1
    labels = {p["label"] for p in info["processes"]}
    assert "rank 0 (client-host)" in labels
    assert "server pid 999 (server-host)" in labels
    # Critical path: the client's consume ends last (0.9s) — the gating
    # process is the client, and the server's serving spans are in the
    # per-process table.
    cp = info["critical_path"]
    assert cp["gating_process"] == "rank 0 (client-host)"
    assert cp["gating_phase"] == "consume"
    processes = {row["process"] for row in cp["per_rank"]}
    assert "server pid 999 (server-host)" in processes

    merged = json.load(open(merged_path))
    # Flow ids survive un-namespaced (they must match across
    # processes); span ids are namespaced per process.
    flow_ids = {
        e["id"]
        for e in merged["traceEvents"]
        if e.get("ph") in ("s", "t", "f")
    }
    assert flow_ids == {"restore-abc/100.1"}
    span_ids = {
        e["id"]
        for e in merged["traceEvents"]
        if e.get("ph") in ("b", "e")
    }
    assert all(":" in str(i) for i in span_ids)


def test_merge_flow_pairs_align_barrierless_server_clock(tmp_path, capsys):
    a = tmp_path / "client.json"
    b = tmp_path / "server.json"
    a.write_text(json.dumps(_client_doc()))
    b.write_text(json.dumps(_server_doc(skew_s=0.5)))
    assert (
        merge.main(
            [str(a), str(b), "-o", str(tmp_path / "m.json"), "--json"]
        )
        == 0
    )
    info = json.loads(capsys.readouterr().out)
    # The server has no barrier anchors; its skew comes from the
    # paired flow midpoint: t_wall(0.11 + 0.5 skew) vs client bracket
    # midpoint (0.01 + 0.21)/2 = 0.11 → skew ≈ +0.5.
    assert info["skew_s"]["server:999"] == pytest.approx(0.5, abs=0.01)
    assert info["skew_s"]["0"] == pytest.approx(0.0, abs=1e-6)


def test_summarize_merged_trace_names_gating_process(tmp_path, capsys):
    a = tmp_path / "client.json"
    b = tmp_path / "server.json"
    a.write_text(json.dumps(_client_doc()))
    b.write_text(json.dumps(_server_doc(skew_s=0.5)))
    merged_path = str(tmp_path / "m.json")
    assert merge.main([str(a), str(b), "-o", merged_path]) == 0
    capsys.readouterr()
    assert summarize.main([merged_path]) == 0
    out = capsys.readouterr().out
    assert "critical path: rank 0 gated the commit" in out
    # The server's row joins the skew table through its skew_key (the
    # table keys by "<role>:<os-pid>", the row by merged pid) — the
    # flow-pair-corrected skew must actually render.
    server_line = next(
        ln for ln in out.splitlines()
        if "server pid 999 (server-host)" in ln
    )
    assert "clock skew +0.5" in server_line, server_line


# --------------------------------------------------- summarize breakdown


def _fixture_trace_with_substeps(tmp_path):
    """A restore trace with consume.* sub-step spans (what the
    micro-profiler emits while tracing is on)."""
    events = []
    sid = [0]

    def span(name, b_us, e_us, **args):
        sid[0] += 1
        events.append(
            {"name": name, "cat": "snapshot", "ph": "b", "id": sid[0],
             "ts": float(b_us), "pid": 1, "tid": 1,
             **({"args": args} if args else {})}
        )
        events.append(
            {"name": name, "cat": "snapshot", "ph": "e", "id": sid[0],
             "ts": float(e_us), "pid": 1, "tid": 1}
        )

    span("read", 0, 100_000, bytes=1 << 28)
    span("consume", 100_000, 1_100_000, bytes=1 << 28)
    span("consume.decode", 100_000, 700_000, bytes=1 << 28)
    span("consume.verify", 700_000, 900_000, bytes=1 << 28)
    span("consume.device_put", 900_000, 1_050_000, bytes=1 << 28)
    p = tmp_path / "fixture.json"
    p.write_text(
        json.dumps(
            {
                "traceEvents": events,
                "metadata": {"clock_epoch_s": 0.0, "rank": 0,
                             "host": "h", "pid": 1},
            }
        )
    )
    return str(p)


def test_summarize_folds_consume_substeps_golden(tmp_path, capsys):
    """Golden-ish: the summarize output for a fixture trace names the
    dominant sub-step and the per-sub-step shares."""
    path = _fixture_trace_with_substeps(tmp_path)
    assert summarize.main([path, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    breakdown = doc["consume_breakdown"]
    assert breakdown["dominant_substep"] == "decode"
    assert breakdown["substeps"]["decode"]["share"] == pytest.approx(
        0.6, abs=0.01
    )
    assert breakdown["substeps"]["verify"]["share"] == pytest.approx(
        0.2, abs=0.01
    )
    assert summarize.main([path]) == 0
    out = capsys.readouterr().out
    assert "consume breakdown (dominant sub-step: decode):" in out
    assert "consume.decode" in out
    assert "60.0% of consume" in out
    # The plain dominance verdict still fires (consume >= 3x read).
    assert "restore is consume-dominated" in out


# ------------------------------------------------ per-process trace files


def test_env_trace_path_role_and_pid_suffixes():
    pid = os.getpid()
    assert tracing.derive_env_path("/tmp/t.json", None) == (
        f"/tmp/t.pid{pid}.json"
    )
    assert tracing.derive_env_path("/tmp/t.json", "server") == (
        f"/tmp/t.server.pid{pid}.json"
    )
    assert tracing.derive_env_path("/tmp/t-{pid}.json", None) == (
        f"/tmp/t-{pid}.json"
    )
    assert tracing.derive_env_path("/tmp/t-{role}.json", "server") == (
        f"/tmp/t-server.pid{pid}.json"
    )


def test_forked_child_flush_cannot_clobber_parent_trace(tmp_path):
    """A child inheriting an enabled tracer (fork) re-suffixes its
    output with its own pid instead of replacing the parent's file."""
    parent_path = str(tmp_path / "trace.json")
    tracing.enable(parent_path)
    with tracing.span("parent-span"):
        pass
    assert tracing.flush() == parent_path
    parent_doc = json.load(open(parent_path))

    # Simulate the fork: the module state says "enabled at pid X" while
    # os.getpid() returns something else.
    tracing._pid_at_enable = os.getpid() + 1
    try:
        with tracing.span("child-span"):
            pass
        child_path = tracing.flush()
    finally:
        tracing._pid_at_enable = os.getpid()
    assert child_path != parent_path
    assert os.path.exists(child_path)
    # Parent file untouched by the child's flush.
    assert json.load(open(parent_path)) == parent_doc


def test_server_subprocess_writes_distinct_trace_file(tmp_path):
    """A snapserve server subprocess launched with the SAME
    TPUSNAPSHOT_TRACE as its client writes its own role+pid-suffixed
    file (satellite 1)."""
    import subprocess
    import sys

    trace = str(tmp_path / "shared.json")
    env = dict(
        os.environ,
        TPUSNAPSHOT_TRACE=trace,
        TPUSNAPSHOT_TRACE_ROLE="server",
        JAX_PLATFORMS="cpu",
    )
    code = (
        "from torchsnapshot_tpu import tracing\n"
        "print(tracing.flush())\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr
    written = out.stdout.strip().splitlines()[-1]
    assert written != trace
    assert ".server.pid" in written
    doc = json.load(open(written))
    assert doc["metadata"]["role"] == "server"


# ------------------------------------------------------- overhead guard


def test_profiler_accounting_is_cheap_when_tracing_off():
    """The always-on accounting is a monotonic pair per sub-step; with
    tracing off and no profile scope the substep helper must be a
    plain passthrough (no span machinery)."""
    assert not tracing.enabled()
    import timeit

    def _noop_substep():
        with consume_profile.substep(None, "decode", 0):
            pass

    per_call = timeit.timeit(_noop_substep, number=10000) / 10000
    # Generous bound (contextmanager overhead only): the real guard is
    # bench's <2% restore-wall criterion; this pins the no-op path.
    assert per_call < 50e-6, per_call
