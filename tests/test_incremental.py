"""Incremental (deduplicated) snapshots — beyond reference parity.

``Snapshot.take(..., base=prev)`` skips the device→host transfer and the
storage write for arrays whose device-computed content fingerprint
matches what ``prev`` recorded; the manifest references the base's
objects instead (``@base<N>/…`` via storage_plugin.RefRouterPlugin).
See torchsnapshot_tpu/incremental.py for the safety model under test:
misses degrade to full writes, hits require fingerprint+checksum+
shape/dtype/region equality, chains flatten, back-link markers guard
base deletion.
"""

import json
import os
import shutil
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.coord import DictStore, StoreCoordinator
from torchsnapshot_tpu.manifest import ArrayEntry, ShardedArrayEntry


def _count_payload_files(root: str) -> int:
    """Stored objects under a snapshot dir, excluding metadata/markers."""
    n = 0
    for dirpath, _, files in os.walk(root):
        for f in files:
            rel = os.path.relpath(os.path.join(dirpath, f), root)
            if rel == ".snapshot_metadata" or rel.startswith(
                (".completed", ".report", ".telemetry", "refs")
            ):
                continue
            n += 1
    return n


def _state(seed=0, n=1024):
    rng = np.random.RandomState(seed)
    return StateDict(
        w=jnp.asarray(rng.randn(n).astype(np.float32)),
        b=rng.randn(32).astype(np.float32),  # host numpy leaf
        step=7,
    )


def test_unchanged_take_writes_no_array_objects(tmp_path):
    app = {"model": _state()}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    s2 = Snapshot.take(str(tmp_path / "s2"), app, base=s1)
    assert _count_payload_files(str(tmp_path / "s2")) == 0
    m = s2.get_manifest()
    assert m["0/model/w"].base is not None
    assert m["0/model/b"].base is not None  # host leaf dedups too
    # restore is bit-exact through the reference
    fresh = {"model": StateDict(w=jnp.zeros(1024, jnp.float32),
                                b=np.zeros(32, np.float32), step=0)}
    s2.restore(fresh)
    assert np.array_equal(np.asarray(fresh["model"]["w"]),
                          np.asarray(app["model"]["w"]))
    assert np.array_equal(fresh["model"]["b"], app["model"]["b"])
    assert fresh["model"]["step"] == 7
    assert s2.verify() == {}


def test_changed_subset_writes_only_changed(tmp_path):
    app = {"model": _state()}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    app["model"]["b"] = app["model"]["b"] + 1.0
    s2 = Snapshot.take(str(tmp_path / "s2"), app, base=s1)
    m = s2.get_manifest()
    assert m["0/model/w"].base is not None  # unchanged: ref
    assert m["0/model/b"].base is None  # changed: written
    assert _count_payload_files(str(tmp_path / "s2")) == 1
    fresh = {"model": StateDict(w=jnp.zeros(1024, jnp.float32),
                                b=np.zeros(32, np.float32), step=0)}
    s2.restore(fresh)
    assert np.array_equal(fresh["model"]["b"], app["model"]["b"])
    assert s2.verify() == {}


def test_chain_flattens_to_original_writer(tmp_path):
    app = {"model": _state()}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    s2 = Snapshot.take(str(tmp_path / "s2"), app, base=s1)
    s3 = Snapshot.take(str(tmp_path / "s3"), app, base=s2)
    meta = s3._read_snapshot_metadata(s3._open_storage())
    # w was PHYSICALLY written by s1; s3 must reference s1 directly even
    # though its base argument was s2 (chains never deepen).
    w = meta.manifest["0/model/w"]
    idx = w.base
    assert meta.base_paths[idx] == "rel:s1"
    # s3 restores bit-exact even if the INTERMEDIATE s2 is deleted
    s2_handle = Snapshot(str(tmp_path / "s2"))
    s2_handle.delete()
    fresh = {"model": StateDict(w=jnp.zeros(1024, jnp.float32),
                                b=np.zeros(32, np.float32), step=0)}
    Snapshot(str(tmp_path / "s3")).restore(fresh)
    assert np.array_equal(np.asarray(fresh["model"]["w"]),
                          np.asarray(app["model"]["w"]))


def test_sharded_partial_region_dedup(tmp_path):
    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = jax.sharding.Mesh(np.array(devices[:8]).reshape(8), ("dp",))
    sharding = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("dp")
    )
    x = jax.device_put(
        np.arange(8 * 64, dtype=np.float32).reshape(8, 64), sharding
    )
    app = {"model": StateDict(emb=x)}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    # touch ONE shard's rows
    host = np.asarray(x).copy()
    host[3] += 1.0
    app["model"]["emb"] = jax.device_put(host, sharding)
    s2 = Snapshot.take(str(tmp_path / "s2"), app, base=s1)
    entry = s2.get_manifest()["0/model/emb"]
    assert isinstance(entry, ShardedArrayEntry)
    refs = [s for s in entry.shards if s.array.base is not None]
    writes = [s for s in entry.shards if s.array.base is None]
    assert len(refs) == 7 and len(writes) == 1
    assert writes[0].offsets == [3, 0]
    fresh = {"model": StateDict(emb=jax.device_put(
        np.zeros((8, 64), np.float32), sharding))}
    s2.restore(fresh)
    assert np.array_equal(np.asarray(fresh["model"]["emb"]), host)
    assert s2.verify() == {}


def test_chunked_dense_dedup(tmp_path, monkeypatch):
    import torchsnapshot_tpu.io_preparer as iop

    monkeypatch.setattr(iop, "MAX_CHUNK_SIZE_BYTES", 1 << 12)
    big = np.arange(4096, dtype=np.float32)  # 16 KiB -> 4 chunks
    app = {"model": StateDict(big=jnp.asarray(big))}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    big2 = big.copy()
    big2[0] += 1.0  # dirty only the first chunk
    app["model"]["big"] = jnp.asarray(big2)
    s2 = Snapshot.take(str(tmp_path / "s2"), app, base=s1)
    entry = s2.get_manifest()["0/model/big"]
    refs = [s for s in entry.shards if s.array.base is not None]
    writes = [s for s in entry.shards if s.array.base is None]
    assert len(writes) == 1 and writes[0].offsets == [0]
    assert len(refs) == len(entry.shards) - 1
    fresh = {"model": StateDict(big=jnp.zeros(4096, jnp.float32))}
    s2.restore(fresh)
    assert np.array_equal(np.asarray(fresh["model"]["big"]), big2)
    assert s2.verify() == {}


def _run_world(world, fn):
    store = DictStore()
    errors, results = [], [None] * world

    def worker(rank):
        try:
            coord = StoreCoordinator(store, rank, world, timeout_s=60)
            results[rank] = fn(coord, rank)
        except BaseException as e:  # pragma: no cover
            import traceback

            errors.append((rank, e, traceback.format_exc()))

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    if errors:
        raise AssertionError(f"rank {errors[0][0]} failed:\n{errors[0][2]}")
    return results


def test_replicated_striping_dedup_world2(tmp_path):
    """Replicated leaves: only the stripe owner evaluates the dedup, and
    the merged manifest serves the referencing entry to every rank —
    verify()/copy_to() must treat the snapshot as healthy even though
    non-owner mirrors were never rewritten."""
    base_path = str(tmp_path / "s1")
    inc_path = str(tmp_path / "s2")

    def take_base(coord, rank):
        app = {"model": StateDict(
            foo=jnp.asarray(np.full(256, 1.0, np.float32)),
            bar=jnp.asarray(np.full(128, 2.0, np.float32)),
        )}
        Snapshot.take(base_path, app, coord=coord,
                      replicated=["**"], fingerprint=True)

    def take_inc(coord, rank):
        app = {"model": StateDict(
            foo=jnp.asarray(np.full(256, 1.0, np.float32)),
            bar=jnp.asarray(np.full(128, 3.0, np.float32)),  # changed
        )}
        Snapshot.take(inc_path, app, coord=coord,
                      replicated=["**"], base=base_path)

    _run_world(2, take_base)
    _run_world(2, take_inc)
    s2 = Snapshot(inc_path)
    assert s2.verify() == {}
    # both ranks can restore the referencing entry
    def restore(coord, rank):
        fresh = {"model": StateDict(
            foo=jnp.zeros(256, jnp.float32), bar=jnp.zeros(128, jnp.float32)
        )}
        Snapshot(inc_path).restore(fresh, coord=coord)
        assert np.allclose(np.asarray(fresh["model"]["foo"]), 1.0)
        assert np.allclose(np.asarray(fresh["model"]["bar"]), 3.0)

    _run_world(2, restore)
    # copy_to materializes a self-contained snapshot
    flat = s2.copy_to(str(tmp_path / "flat"))
    assert flat.verify() == {}
    meta = flat._read_snapshot_metadata(flat._open_storage())
    assert meta.base_paths == []
    # only the changed replicated leaf was stored in s2's own root
    assert _count_payload_files(inc_path) == 1


def test_delete_protection_lifecycle(tmp_path):
    app = {"model": _state()}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    s2 = Snapshot.take(str(tmp_path / "s2"), app, base=s1)
    with pytest.raises(RuntimeError, match="referenced by"):
        Snapshot(str(tmp_path / "s1")).delete()
    # the child keeps working, then unblocks the base
    Snapshot(str(tmp_path / "s2")).delete()
    Snapshot(str(tmp_path / "s1")).delete()
    assert _count_payload_files(str(tmp_path / "s1")) == 0
    for root, _, files in os.walk(tmp_path):
        assert not files, (root, files)


def test_delete_force_overrides_protection(tmp_path):
    app = {"model": _state()}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    Snapshot.take(str(tmp_path / "s2"), app, base=s1)
    Snapshot(str(tmp_path / "s1")).delete(force=True)
    # the child is now broken (documented force semantics)
    fresh = {"model": _state(seed=9)}
    with pytest.raises(Exception):
        Snapshot(str(tmp_path / "s2")).restore(fresh)


def test_young_orphan_marker_blocks_delete(tmp_path, monkeypatch):
    """A back-link marker with no committed child metadata is an
    IN-FLIGHT take if young: delete must fail closed (the marker lands
    before the child's payload writes)."""
    app = {"model": _state()}
    Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    refs_dir = tmp_path / "s1" / "refs"
    refs_dir.mkdir()
    (refs_dir / "inc_deadbeef_0").write_text(
        json.dumps({"path": "rel:s_inflight"})
    )
    with pytest.raises(RuntimeError, match="referenced by"):
        Snapshot(str(tmp_path / "s1")).delete()
    # the sweep knob must NOT disable this guard (separate knobs)
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    with pytest.raises(RuntimeError, match="referenced by"):
        Snapshot(str(tmp_path / "s1")).delete()
    # old marker (or the refs escape hatch) sweeps as stale
    monkeypatch.setenv("TPUSNAPSHOT_REFS_MIN_AGE_S", "0")
    Snapshot(str(tmp_path / "s1")).delete()


def test_copy_to_survives_base_deletion(tmp_path):
    app = {"model": _state()}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    s2 = Snapshot.take(str(tmp_path / "s2"), app, base=s1)
    flat = s2.copy_to(str(tmp_path / "flat"))
    Snapshot(str(tmp_path / "s2")).delete()
    Snapshot(str(tmp_path / "s1")).delete()
    fresh = {"model": StateDict(w=jnp.zeros(1024, jnp.float32),
                                b=np.zeros(32, np.float32), step=0)}
    Snapshot(str(tmp_path / "flat")).restore(fresh)
    assert np.array_equal(np.asarray(fresh["model"]["w"]),
                          np.asarray(app["model"]["w"]))
    assert flat.verify() == {}


def test_verify_detects_corrupt_base_object(tmp_path):
    app = {"model": _state()}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    s2 = Snapshot.take(str(tmp_path / "s2"), app, base=s1)
    # flip a byte in the BASE's stored object
    target = tmp_path / "s1" / "0" / "model" / "w"
    raw = bytearray(target.read_bytes())
    raw[10] ^= 0xFF
    target.write_bytes(bytes(raw))
    problems = s2.verify()
    assert any("0/model/w" in loc for loc in problems), problems


def test_base_without_fingerprints_degrades_to_full_write(tmp_path):
    app = {"model": _state()}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=False)
    s2 = Snapshot.take(str(tmp_path / "s2"), app, base=s1)
    m = s2.get_manifest()
    assert m["0/model/w"].base is None  # no base fingerprint -> full write
    assert _count_payload_files(str(tmp_path / "s2")) == 2  # w and b (step inlines)
    assert s2.verify() == {}
    # ...but s2 recorded fingerprints, so s3 CAN dedup against s2
    s3 = Snapshot.take(str(tmp_path / "s3"), app, base=s2)
    assert _count_payload_files(str(tmp_path / "s3")) == 0


def test_async_take_with_base(tmp_path):
    app = {"model": _state()}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    app["model"]["b"] = app["model"]["b"] + 5.0
    pending = Snapshot.async_take(str(tmp_path / "s2"), app, base=s1)
    s2 = pending.wait()
    assert _count_payload_files(str(tmp_path / "s2")) == 1
    fresh = {"model": StateDict(w=jnp.zeros(1024, jnp.float32),
                                b=np.zeros(32, np.float32), step=0)}
    s2.restore(fresh)
    assert np.array_equal(fresh["model"]["b"], app["model"]["b"])
    assert s2.verify() == {}


def test_moved_family_rel_refs(tmp_path):
    src_dir = tmp_path / "ckpts"
    src_dir.mkdir()
    app = {"model": _state()}
    s1 = Snapshot.take(str(src_dir / "s1"), app, fingerprint=True)
    Snapshot.take(str(src_dir / "s2"), app, base=s1)
    moved = tmp_path / "archive"
    shutil.move(str(src_dir), str(moved))
    fresh = {"model": StateDict(w=jnp.zeros(1024, jnp.float32),
                                b=np.zeros(32, np.float32), step=0)}
    Snapshot(str(moved / "s2")).restore(fresh)
    assert np.array_equal(np.asarray(fresh["model"]["w"]),
                          np.asarray(app["model"]["w"]))
    assert Snapshot(str(moved / "s2")).verify() == {}


def test_paths_filter_restore_with_refs(tmp_path):
    app = {"model": _state()}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    s2 = Snapshot.take(str(tmp_path / "s2"), app, base=s1)
    fresh = {"model": StateDict(w=jnp.zeros(1024, jnp.float32),
                                b=np.zeros(32, np.float32), step=0)}
    s2.restore(fresh, paths=["model/w"])
    assert np.array_equal(np.asarray(fresh["model"]["w"]),
                          np.asarray(app["model"]["w"]))
    assert np.array_equal(fresh["model"]["b"], np.zeros(32, np.float32))


def test_read_object_through_refs(tmp_path):
    app = {"model": _state()}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    s2 = Snapshot.take(str(tmp_path / "s2"), app, base=s1)
    w = s2.read_object("model/w")
    assert np.array_equal(np.asarray(w), np.asarray(app["model"]["w"]))


def test_base_unreadable_raises(tmp_path):
    app = {"model": _state()}
    with pytest.raises(ValueError, match="unreadable"):
        Snapshot.take(
            str(tmp_path / "s2"), app, base=str(tmp_path / "nonexistent")
        )


def test_base_equals_path_raises(tmp_path):
    app = {"model": _state()}
    with pytest.raises(ValueError, match="NEW path"):
        Snapshot.take(str(tmp_path / "s1"), app, base=str(tmp_path / "s1"))


def test_fingerprint_env_knob(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_FINGERPRINT", "1")
    app = {"model": _state()}
    s1 = Snapshot.take(str(tmp_path / "s1"), app)
    entry = s1.get_manifest()["0/model/w"]
    assert entry.fingerprint is not None
    assert entry.fingerprint.startswith("xs128:")


def test_fingerprints_off_by_default(tmp_path):
    app = {"model": _state()}
    s1 = Snapshot.take(str(tmp_path / "s1"), app)
    assert s1.get_manifest()["0/model/w"].fingerprint is None


def test_object_entries_not_deduped(tmp_path):
    """Pickled-object leaves are v1 out of scope: written every take."""
    app = {"model": StateDict(w=jnp.arange(16, dtype=jnp.float32),
                              cfg={"tags": {"adam", "fp32"}})}  # set pickles
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    s2 = Snapshot.take(str(tmp_path / "s2"), app, base=s1)
    m = s2.get_manifest()
    assert m["0/model/w"].base is not None
    # objects carry no ref machinery at all: always written
    assert getattr(m["0/model/cfg/tags"], "base", None) is None
    fresh = {"model": StateDict(w=jnp.zeros(16, jnp.float32),
                                cfg={"tags": set()})}
    s2.restore(fresh)
    assert fresh["model"]["cfg"]["tags"] == {"adam", "fp32"}


def test_dtype_or_shape_change_degrades_to_full_write(tmp_path):
    app = {"model": StateDict(w=jnp.arange(64, dtype=jnp.float32))}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    app["model"]["w"] = jnp.arange(64, dtype=jnp.bfloat16)  # dtype change
    s2 = Snapshot.take(str(tmp_path / "s2"), app, base=s1)
    assert s2.get_manifest()["0/model/w"].base is None
    app["model"]["w"] = jnp.arange(128, dtype=jnp.bfloat16)  # shape change
    s3 = Snapshot.take(str(tmp_path / "s3"), app, base=s2)
    assert s3.get_manifest()["0/model/w"].base is None


def test_fingerprint_false_with_base_still_dedups_without_recording(tmp_path):
    app = {"model": _state()}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    s2 = Snapshot.take(str(tmp_path / "s2"), app, base=s1, fingerprint=False)
    m = s2.get_manifest()
    assert m["0/model/w"].base is not None  # dedup still happened...
    assert m["0/model/w"].fingerprint is None  # ...but nothing recorded
    assert _count_payload_files(str(tmp_path / "s2")) == 0


def test_decorated_handle_cache_reused_as_base(tmp_path):
    """Using a handle whose metadata cache was DECORATED (by a prior
    restore) as the next take's base must still produce bare locations
    in the new snapshot's references."""
    app = {"model": _state()}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    s2 = Snapshot.take(str(tmp_path / "s2"), app, base=s1)
    # force-decorate s2's cache the way a restore would
    fresh = {"model": StateDict(w=jnp.zeros(1024, jnp.float32),
                                b=np.zeros(32, np.float32), step=0)}
    s2.restore(fresh)
    assert s2._metadata_cache is not None
    s3 = Snapshot.take(str(tmp_path / "s3"), app, base=s2)
    meta3 = s3._read_snapshot_metadata(s3._open_storage())
    for key in ("0/model/w", "0/model/b"):
        e = meta3.manifest[key]
        assert e.base is not None
        # decorated exactly once (single @base prefix), resolving to s1
        assert e.location.count("@base") == 1
        assert meta3.base_paths[e.base] == "rel:s1"
    fresh2 = {"model": StateDict(w=jnp.zeros(1024, jnp.float32),
                                 b=np.zeros(32, np.float32), step=0)}
    s3.restore(fresh2)
    assert np.array_equal(np.asarray(fresh2["model"]["w"]),
                          np.asarray(app["model"]["w"]))
    assert s3.verify() == {}


def test_backlink_markers_idempotent_across_takes(tmp_path):
    app = {"model": _state()}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    Snapshot.take(str(tmp_path / "s2"), app, base=s1)
    Snapshot.take(str(tmp_path / "s3"), app, base=s1)
    markers = sorted(os.listdir(tmp_path / "s1" / "refs"))
    # one marker per referencing snapshot, not per take attempt/rank
    assert len(markers) == 2, markers


def _mgr_state(head_val: float):
    return {"model": StateDict(
        backbone=jnp.asarray(np.full(4096, 7.0, np.float32)),  # frozen
        head=jnp.asarray(np.full(64, head_val, np.float32)),   # trains
    )}


def test_manager_incremental_end_to_end(tmp_path):
    from torchsnapshot_tpu.manager import CheckpointManager

    mgr = CheckpointManager(str(tmp_path), max_to_keep=2, incremental=True)
    for step in range(1, 5):
        mgr.save(step, _mgr_state(float(step)))
    # steps 3,4 retained by the window; step 1 (the frozen backbone's
    # original writer) is DEFERRED, not silently dropped; step 2 holds
    # nothing anyone references and is pruned.
    assert mgr.all_steps() == [1, 3, 4]
    # the incremental steps actually deduplicated: only the changed head
    # was stored
    assert _count_payload_files(str(tmp_path / "step-4")) == 1
    fresh = _mgr_state(0.0)
    assert mgr.restore(fresh) == 4
    assert np.allclose(np.asarray(fresh["model"]["head"]), 4.0)
    assert np.allclose(np.asarray(fresh["model"]["backbone"]), 7.0)


def test_manager_full_period_unpins_bases(tmp_path):
    from torchsnapshot_tpu.manager import CheckpointManager

    mgr = CheckpointManager(
        str(tmp_path), max_to_keep=2, incremental=True, full_period=2
    )
    for step in range(1, 6):
        mgr.save(step, _mgr_state(float(step)))
    # step 4 was a FULL take; step 5 bases on it. Nothing references
    # steps 1-3 anymore, so the window holds exactly [4, 5].
    assert mgr.all_steps() == [4, 5]
    m5 = Snapshot(str(tmp_path / "step-5")).get_manifest()
    assert m5["0/model/backbone"].base is not None  # still deduped vs 4
    fresh = _mgr_state(0.0)
    assert mgr.restore(fresh) == 5
    assert np.allclose(np.asarray(fresh["model"]["head"]), 5.0)


def test_manager_incremental_world2(tmp_path, caplog):
    """Multi-rank managed incremental saves: non-zero ranks defer base
    resolution to rank 0 via the sentinel — no divergence warnings, and
    the dedup still lands."""
    import logging

    from torchsnapshot_tpu.manager import CheckpointManager

    root = str(tmp_path)

    def run(coord, rank):
        mgr = CheckpointManager(root, incremental=True, coord=coord)
        for step in (1, 2):
            mgr.save(step, {"model": StateDict(
                w=jnp.asarray(np.full(256, float(step), np.float32)),
                frozen=jnp.asarray(np.full(512, 3.0, np.float32)),
            )}, replicated=["**"])

    with caplog.at_level(logging.WARNING):
        _run_world(2, run)
    assert not [r for r in caplog.records if "but rank 0" in r.message]
    m = Snapshot(f"{root}/step-2").get_manifest()
    assert m["0/model/frozen"].base is not None
    assert m["0/model/w"].base is None
    assert Snapshot(f"{root}/step-2").verify() == {}


def test_manager_async_incremental(tmp_path):
    from torchsnapshot_tpu.manager import CheckpointManager

    mgr = CheckpointManager(str(tmp_path), incremental=True)
    mgr.save(1, _mgr_state(1.0))
    handle = mgr.async_save(2, _mgr_state(2.0))
    assert handle.wait() is not None
    assert mgr.all_steps() == [1, 2]
    assert _count_payload_files(str(tmp_path / "step-2")) == 1
    fresh = _mgr_state(0.0)
    assert mgr.restore(fresh) == 2
    assert np.allclose(np.asarray(fresh["model"]["head"]), 2.0)


def test_diff_reports_changed_and_unchanged(tmp_path):
    app = {"model": StateDict(
        w=jnp.arange(128, dtype=jnp.float32),
        b=jnp.ones(16, jnp.float32),
        lr=0.1,
    )}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    app["model"]["b"] = app["model"]["b"] + 1.0
    app["model"]["lr"] = 0.01
    del app["model"]["w"]
    app["model"]["new"] = jnp.zeros(4, jnp.float32)
    s2 = Snapshot.take(str(tmp_path / "s2"), app, fingerprint=True)
    d = s2.diff(s1)
    assert d["added"] == ["model/new"]
    assert d["removed"] == ["model/w"]
    assert sorted(d["changed"]) == ["model/b", "model/lr"]
    assert d["unchanged"] == []
    # identical snapshots diff clean
    s3 = Snapshot.take(str(tmp_path / "s3"), app, base=s2)
    d2 = s3.diff(s2)
    assert not d2["added"] and not d2["removed"] and not d2["changed"]
    assert sorted(d2["unchanged"]) == ["model/b", "model/lr", "model/new"]


def test_diff_without_fingerprints_uses_checksums(tmp_path):
    app = {"model": StateDict(w=jnp.arange(128, dtype=jnp.float32))}
    s1 = Snapshot.take(str(tmp_path / "s1"), app)
    s2 = Snapshot.take(str(tmp_path / "s2"), app)
    d = s2.diff(s1)
    assert d["unchanged"] == ["model/w"]  # equal crc32 of logical bytes
    app["model"]["w"] = app["model"]["w"] + 1
    s3 = Snapshot.take(str(tmp_path / "s3"), app)
    assert s3.diff(s1)["changed"] == ["model/w"]


def test_diff_sharded_region_granular(tmp_path):
    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = jax.sharding.Mesh(np.array(devices[:8]).reshape(8), ("dp",))
    sharding = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("dp")
    )
    x = jax.device_put(np.ones((8, 16), np.float32), sharding)
    app = {"model": StateDict(emb=x)}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    host = np.asarray(x).copy()
    host[2] = 5.0
    app["model"]["emb"] = jax.device_put(host, sharding)
    s2 = Snapshot.take(str(tmp_path / "s2"), app, fingerprint=True)
    assert s2.diff(s1)["changed"] == ["model/emb"]
    s3 = Snapshot.take(str(tmp_path / "s3"), app, fingerprint=True)
    assert s3.diff(s2)["unchanged"] == ["model/emb"]


def test_inspect_diff_cli(tmp_path, capsys):
    from torchsnapshot_tpu.inspect import main

    app = {"model": StateDict(w=jnp.arange(32, dtype=jnp.float32))}
    Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    app["model"]["w"] = app["model"]["w"] * 2
    Snapshot.take(str(tmp_path / "s2"), app, fingerprint=True)
    rc = main([str(tmp_path / "s2"), "--diff", str(tmp_path / "s1")])
    out = capsys.readouterr().out
    assert rc == 1 and "changed" in out and "model/w" in out
    rc = main([str(tmp_path / "s1"), "--diff", str(tmp_path / "s1")])
    assert rc == 0
    # inconclusive is exit 3 — distinct from both "identical" (0) and
    # argparse's usage-error 2: differing compression settings make the
    # stored checksums incomparable without fingerprints
    app2 = {"model": StateDict(w=jnp.ones(64, jnp.float32))}
    Snapshot.take(str(tmp_path / "o1"), app2)
    Snapshot.take(str(tmp_path / "o2"), app2, compression="zlib")
    rc = main([str(tmp_path / "o2"), "--diff", str(tmp_path / "o1")])
    assert rc == 3


def test_restore_verify_device_passes_and_catches_corruption(tmp_path):
    app = {"model": _state()}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    fresh = {"model": StateDict(w=jnp.zeros(1024, jnp.float32),
                                b=np.zeros(32, np.float32), step=0)}
    s1.restore(fresh, verify_device=True)  # clean path
    assert np.array_equal(np.asarray(fresh["model"]["w"]),
                          np.asarray(app["model"]["w"]))
    # Corrupt the manifest's recorded fingerprint to simulate restored
    # bytes not matching what the snapshot recorded.
    meta = s1._read_snapshot_metadata(s1._open_storage())
    meta.manifest["0/model/w"].fingerprint = "xs128:" + "f" * 32
    with pytest.raises(RuntimeError, match="model/w"):
        s1.restore(fresh, verify_device=True)


def test_restore_verify_device_sharded(tmp_path):
    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = jax.sharding.Mesh(np.array(devices[:8]).reshape(8), ("dp",))
    sharding = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec("dp")
    )
    x = jax.device_put(
        np.arange(8 * 32, dtype=np.float32).reshape(8, 32), sharding
    )
    app = {"model": StateDict(emb=x)}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    fresh = {"model": StateDict(emb=jax.device_put(
        np.zeros((8, 32), np.float32), sharding))}
    s1.restore(fresh, verify_device=True)
    assert np.array_equal(np.asarray(fresh["model"]["emb"]), np.asarray(x))


def test_restore_verify_device_skips_unfingerprinted(tmp_path, caplog):
    import logging

    app = {"model": _state()}
    s1 = Snapshot.take(str(tmp_path / "s1"), app)  # no fingerprints
    fresh = {"model": StateDict(w=jnp.zeros(1024, jnp.float32),
                                b=np.zeros(32, np.float32), step=0)}
    with caplog.at_level(logging.INFO):
        s1.restore(fresh, verify_device=True)
    assert np.array_equal(np.asarray(fresh["model"]["w"]),
                          np.asarray(app["model"]["w"]))


def test_incremental_across_compression_change(tmp_path):
    """A compressed base dedups into an uncompressed take (and back):
    fingerprints cover the UNCOMPRESSED logical payload, and the ref
    entry copies the base's compression tag so restore decodes right."""
    app = {"model": StateDict(w=jnp.arange(4096, dtype=jnp.float32))}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, compression="zlib",
                       fingerprint=True)
    s2 = Snapshot.take(str(tmp_path / "s2"), app, base=s1)  # no compression
    m2 = s2.get_manifest()
    assert m2["0/model/w"].base is not None
    assert m2["0/model/w"].compression == "zlib"  # describes base's object
    fresh = {"model": StateDict(w=jnp.zeros(4096, jnp.float32))}
    s2.restore(fresh, verify_device=True)
    assert np.array_equal(np.asarray(fresh["model"]["w"]),
                          np.arange(4096, dtype=np.float32))
    assert s2.verify() == {}
    # and a compressed take over an uncompressed-referencing base
    s3 = Snapshot.take(str(tmp_path / "s3"), app, base=s2,
                       compression="zlib")
    assert s3.get_manifest()["0/model/w"].base is not None
    assert s3.verify() == {}


def test_incremental_bfloat16_leaves(tmp_path):
    app = {"model": StateDict(w=jnp.ones((64, 64), jnp.bfloat16))}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    s2 = Snapshot.take(str(tmp_path / "s2"), app, base=s1)
    assert s2.get_manifest()["0/model/w"].base is not None
    fresh = {"model": StateDict(w=jnp.zeros((64, 64), jnp.bfloat16))}
    s2.restore(fresh, verify_device=True)
    assert np.array_equal(
        np.asarray(fresh["model"]["w"]).view(np.uint16),
        np.asarray(app["model"]["w"]).view(np.uint16),
    )


def test_rng_state_flows_through_incremental(tmp_path):
    from torchsnapshot_tpu import RNGState

    np.random.seed(3)
    app = {"rng": RNGState(), "model": _state()}
    s1 = Snapshot.take(str(tmp_path / "s1"), app, fingerprint=True)
    s2 = Snapshot.take(str(tmp_path / "s2"), app, base=s1)
    expected = np.random.rand()
    np.random.seed(99)
    fresh = {"rng": RNGState(), "model": _state(seed=5)}
    s2.restore(fresh)
    # np RNG stream restored: the next draw matches the original stream
    assert np.random.rand() == expected
    assert s2.verify() == {}
