"""Scheduler pipeline tests (reference analog: scheduler.py behavior)."""

import asyncio
import time

import pytest

from torchsnapshot_tpu.coord import NoOpCoordinator
from torchsnapshot_tpu.io_types import (
    BufferConsumer,
    BufferStager,
    IOReq,
    ReadReq,
    WriteReq,
)
from torchsnapshot_tpu.scheduler import (
    execute_read_reqs,
    execute_write_reqs,
    get_local_world_size,
    get_process_memory_budget_bytes,
)
from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin


class _Stager(BufferStager):
    def __init__(self, payload: bytes, tracker=None):
        self.payload = payload
        self.tracker = tracker

    async def stage_buffer(self, executor=None):
        if self.tracker is not None:
            self.tracker["staging"] += 1
            self.tracker["max_staging"] = max(
                self.tracker["max_staging"], self.tracker["staging"]
            )
            await asyncio.sleep(0.005)
            self.tracker["staging"] -= 1
        return self.payload

    def get_staging_cost_bytes(self) -> int:
        return len(self.payload)


class _Consumer(BufferConsumer):
    def __init__(self, sink, key):
        self.sink = sink
        self.key = key

    async def consume_buffer(self, buf, executor=None):
        self.sink[self.key] = bytes(buf)

    def get_consuming_cost_bytes(self) -> int:
        return 64


def test_write_read_round_trip():
    storage = MemoryStoragePlugin()
    payloads = {f"p{i}": bytes([i]) * (i + 1) for i in range(50)}
    write_reqs = [
        WriteReq(path=k, buffer_stager=_Stager(v)) for k, v in payloads.items()
    ]
    written = asyncio.run(
        execute_write_reqs(write_reqs, storage, memory_budget_bytes=1 << 20, rank=0)
    )
    assert written == sum(len(v) for v in payloads.values())
    assert storage.store == payloads

    sink = {}
    read_reqs = [
        ReadReq(path=k, buffer_consumer=_Consumer(sink, k)) for k in payloads
    ]
    read = asyncio.run(
        execute_read_reqs(read_reqs, storage, memory_budget_bytes=1 << 20, rank=0)
    )
    assert read == written
    assert sink == payloads


def test_budget_limits_concurrent_staging():
    storage = MemoryStoragePlugin()
    tracker = {"staging": 0, "max_staging": 0}
    # 100-byte buffers with a 250-byte budget: at most 2 staged at once.
    write_reqs = [
        WriteReq(path=f"p{i}", buffer_stager=_Stager(b"x" * 100, tracker))
        for i in range(10)
    ]
    asyncio.run(
        execute_write_reqs(write_reqs, storage, memory_budget_bytes=250, rank=0)
    )
    assert tracker["max_staging"] <= 2
    assert len(storage.store) == 10


def test_over_budget_buffer_still_progresses():
    storage = MemoryStoragePlugin()
    write_reqs = [WriteReq(path="big", buffer_stager=_Stager(b"x" * 1000))]
    written = asyncio.run(
        execute_write_reqs(write_reqs, storage, memory_budget_bytes=10, rank=0)
    )
    assert written == 1000


def test_write_error_propagates():
    class _FailingStorage(MemoryStoragePlugin):
        async def write(self, io_req: IOReq) -> None:
            raise IOError("disk on fire")

    with pytest.raises(IOError, match="disk on fire"):
        asyncio.run(
            execute_write_reqs(
                [WriteReq(path="p", buffer_stager=_Stager(b"x"))],
                _FailingStorage(),
                memory_budget_bytes=1 << 20,
                rank=0,
            )
        )


def test_memory_budget_env_override(monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_PER_RANK_MEMORY_BUDGET_BYTES", "12345")
    assert get_process_memory_budget_bytes(NoOpCoordinator()) == 12345


def test_memory_budget_default():
    budget = get_process_memory_budget_bytes(NoOpCoordinator())
    assert 0 < budget <= 32 * 1024 * 1024 * 1024


def test_local_world_size():
    assert get_local_world_size(NoOpCoordinator()) == 1


class _DeferredConsumer(BufferConsumer):
    """Consumes instantly but holds a deferred reservation (the split-read
    assembly-buffer shape) released only when the test fires it."""

    def __init__(self, events, release_gate):
        self.events = events
        self.release_gate = release_gate
        self._release = None

    async def consume_buffer(self, buf, executor=None):
        self.events.append("A consumed")

        async def _later():
            await self.release_gate.wait()
            self.events.append("released")
            self._release(150)

        asyncio.ensure_future(_later())

    def get_consuming_cost_bytes(self) -> int:
        return 150

    def get_deferred_cost_bytes(self) -> int:
        return 150

    def set_cost_releaser(self, release):
        self._release = release


def test_deferred_cost_held_until_release():
    """A consumer's deferred reservation must stay charged after its
    consume task completes: a same-cost read behind it is only admitted
    once the consumer's releaser fires (ADVICE r4 medium — without this,
    concurrent split reads overrun the budget by the sum of their
    assembly buffers). All three requests share one cost so the
    largest-first dispatch sort keeps their list order (stable tie)."""
    events = []

    class _GatedConsumer(BufferConsumer):
        # Holds a never-refunded deferred reservation and keeps the
        # pipeline non-empty while it unblocks A's release — so the ONLY
        # budget that can admit B is A's released reservation.
        def __init__(self, release_gate):
            self.release_gate = release_gate

        async def consume_buffer(self, buf, executor=None):
            self.release_gate.set()
            await asyncio.sleep(0.02)
            events.append("C consumed")

        def get_consuming_cost_bytes(self) -> int:
            return 150

        def get_deferred_cost_bytes(self) -> int:
            return 150

        def set_cost_releaser(self, release):
            pass  # never released within this pipeline run

    class _RecordingConsumer(BufferConsumer):
        async def consume_buffer(self, buf, executor=None):
            events.append("B consumed")

        def get_consuming_cost_bytes(self) -> int:
            return 150

    async def _run():
        storage = MemoryStoragePlugin()
        for p in ("a", "b", "c"):
            await storage.write(IOReq(path=p, data=b"x"))
        gate = asyncio.Event()
        reqs = [
            ReadReq(path="a", buffer_consumer=_DeferredConsumer(events, gate)),
            ReadReq(path="c", buffer_consumer=_GatedConsumer(gate)),
            ReadReq(path="b", buffer_consumer=_RecordingConsumer()),
        ]
        # Budget admits A+C (300) but not B (needs 150 more); A's
        # consume refunds nothing (fully deferred), C's never refunds —
        # only A's explicit release can admit B.
        await execute_read_reqs(reqs, storage, memory_budget_bytes=350, rank=0)

    asyncio.run(_run())
    assert "released" in events and "B consumed" in events
    assert events.index("released") < events.index("B consumed")


def test_split_read_state_releases_assembly_cost_once():
    from torchsnapshot_tpu.io_preparer import _SplitObjectReadState

    sink = {}
    state = _SplitObjectReadState(10, _Consumer(sink, "k"))
    reqs = state.add_sub_reads("p", 4)
    assert len(reqs) == 3
    consumers = [r.buffer_consumer for r in reqs]
    assert consumers[0].get_deferred_cost_bytes() == 10
    assert consumers[1].get_deferred_cost_bytes() == 0
    calls = []
    consumers[0].set_cost_releaser(calls.append)

    async def _run():
        await consumers[0].consume_buffer(b"aaaa")
        await consumers[1].consume_buffer(b"bbbb")
        assert calls == []  # buffer still allocated: reservation held
        await consumers[2].consume_buffer(b"cc")

    asyncio.run(_run())
    assert calls == [10]  # released exactly once, on the last sub-read
    assert sink["k"] == b"aaaabbbbcc"


def test_streaming_split_defers_per_part_and_releases_on_drain():
    """The streaming split has NO host assembly buffer: it must not
    charge the whole object on the first sub-read (that serializes
    concurrent large restores), only defer each part's payload while it
    may sit in the out-of-order crc stash — or, post-fastlane, while
    the H2D overlap engine still holds it; the re-credit then arrives
    asynchronously once the transfer lands."""
    import zlib

    import jax
    import numpy as np

    from torchsnapshot_tpu.io_preparer import (
        _StreamingSplitState,
        _TargetRegion,
    )

    data = np.arange(4, dtype=np.float32).tobytes()  # 16 bytes
    crc = f"crc32:{zlib.crc32(data) & 0xFFFFFFFF:08x}"
    region = _TargetRegion([0], [4], np.dtype(np.float32))
    region.devices.append(jax.devices("cpu")[0])
    done = []
    state = _StreamingSplitState(
        16,
        region=region,
        dtype=np.dtype(np.float32),
        checksum=crc,
        on_done=lambda: done.append(1),
    )
    reqs = state.add_sub_reads("p", 8)
    c0, c1 = (r.buffer_consumer for r in reqs)
    assert c0.get_consuming_cost_bytes() == 8  # payload only, no nbytes
    assert c0.get_deferred_cost_bytes() == 8
    assert c1.get_deferred_cost_bytes() == 8
    released = []
    c0.set_cost_releaser(released.append)

    async def _run():
        # Out of order: the second part stashes (nothing drained yet —
        # its crc hold can only drop once the prefix lands).
        await c1.consume_buffer(data[8:16])
        await c0.consume_buffer(data[0:8])

    asyncio.run(_run())
    # Completion (and the budget re-credit) is asynchronous: the
    # overlap engine's done-callback fires it once both parts' H2D
    # transfers land.
    deadline = time.monotonic() + 30
    while not done and time.monotonic() < deadline:
        time.sleep(0.005)
    assert done == [1]
    assert sum(released) == 16  # both parts re-credited exactly once
    assert region.device_chunks is not None
    assert len(region.device_chunks) == 2
