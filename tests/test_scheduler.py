"""Scheduler pipeline tests (reference analog: scheduler.py behavior)."""

import asyncio

import pytest

from torchsnapshot_tpu.coord import NoOpCoordinator
from torchsnapshot_tpu.io_types import (
    BufferConsumer,
    BufferStager,
    IOReq,
    ReadReq,
    WriteReq,
)
from torchsnapshot_tpu.scheduler import (
    execute_read_reqs,
    execute_write_reqs,
    get_local_world_size,
    get_process_memory_budget_bytes,
)
from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin


class _Stager(BufferStager):
    def __init__(self, payload: bytes, tracker=None):
        self.payload = payload
        self.tracker = tracker

    async def stage_buffer(self, executor=None):
        if self.tracker is not None:
            self.tracker["staging"] += 1
            self.tracker["max_staging"] = max(
                self.tracker["max_staging"], self.tracker["staging"]
            )
            await asyncio.sleep(0.005)
            self.tracker["staging"] -= 1
        return self.payload

    def get_staging_cost_bytes(self) -> int:
        return len(self.payload)


class _Consumer(BufferConsumer):
    def __init__(self, sink, key):
        self.sink = sink
        self.key = key

    async def consume_buffer(self, buf, executor=None):
        self.sink[self.key] = bytes(buf)

    def get_consuming_cost_bytes(self) -> int:
        return 64


def test_write_read_round_trip():
    storage = MemoryStoragePlugin()
    payloads = {f"p{i}": bytes([i]) * (i + 1) for i in range(50)}
    write_reqs = [
        WriteReq(path=k, buffer_stager=_Stager(v)) for k, v in payloads.items()
    ]
    written = asyncio.run(
        execute_write_reqs(write_reqs, storage, memory_budget_bytes=1 << 20, rank=0)
    )
    assert written == sum(len(v) for v in payloads.values())
    assert storage.store == payloads

    sink = {}
    read_reqs = [
        ReadReq(path=k, buffer_consumer=_Consumer(sink, k)) for k in payloads
    ]
    read = asyncio.run(
        execute_read_reqs(read_reqs, storage, memory_budget_bytes=1 << 20, rank=0)
    )
    assert read == written
    assert sink == payloads


def test_budget_limits_concurrent_staging():
    storage = MemoryStoragePlugin()
    tracker = {"staging": 0, "max_staging": 0}
    # 100-byte buffers with a 250-byte budget: at most 2 staged at once.
    write_reqs = [
        WriteReq(path=f"p{i}", buffer_stager=_Stager(b"x" * 100, tracker))
        for i in range(10)
    ]
    asyncio.run(
        execute_write_reqs(write_reqs, storage, memory_budget_bytes=250, rank=0)
    )
    assert tracker["max_staging"] <= 2
    assert len(storage.store) == 10


def test_over_budget_buffer_still_progresses():
    storage = MemoryStoragePlugin()
    write_reqs = [WriteReq(path="big", buffer_stager=_Stager(b"x" * 1000))]
    written = asyncio.run(
        execute_write_reqs(write_reqs, storage, memory_budget_bytes=10, rank=0)
    )
    assert written == 1000


def test_write_error_propagates():
    class _FailingStorage(MemoryStoragePlugin):
        async def write(self, io_req: IOReq) -> None:
            raise IOError("disk on fire")

    with pytest.raises(IOError, match="disk on fire"):
        asyncio.run(
            execute_write_reqs(
                [WriteReq(path="p", buffer_stager=_Stager(b"x"))],
                _FailingStorage(),
                memory_budget_bytes=1 << 20,
                rank=0,
            )
        )


def test_memory_budget_env_override(monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_PER_RANK_MEMORY_BUDGET_BYTES", "12345")
    assert get_process_memory_budget_bytes(NoOpCoordinator()) == 12345


def test_memory_budget_default():
    budget = get_process_memory_budget_bytes(NoOpCoordinator())
    assert 0 < budget <= 32 * 1024 * 1024 * 1024


def test_local_world_size():
    assert get_local_world_size(NoOpCoordinator()) == 1
