"""Format-level chunking of large dense arrays (VERDICT r4 #3).

A dense array larger than ``MAX_CHUNK_SIZE_BYTES`` persists as a chunked
``ShardedArrayEntry`` — multiple one-region storage objects — instead of
one monolithic object, so bounded staging, write fan-out, and
split/streaming restores stop depending on per-backend tricks. The
reference subdivides only ShardedTensor shards
(torchsnapshot/io_preparer.py:38,40-72); the dense path here gets the
same treatment while preserving the dense entry's elasticity category
(replicated / per-rank).

Tests shrink the threshold via monkeypatch so the chunk machinery runs
at MiB scale hermetically.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import torchsnapshot_tpu.io_preparer as iop
from torchsnapshot_tpu import Snapshot
from torchsnapshot_tpu.coord import DictStore, StoreCoordinator
from torchsnapshot_tpu.manifest import ShardedArrayEntry


class _Holder:
    def __init__(self, sd):
        self.sd = sd

    def state_dict(self):
        return self.sd

    def load_state_dict(self, sd):
        self.sd = sd


@pytest.fixture
def small_chunks(monkeypatch):
    """1 MiB chunk ceiling: a few-MiB array exercises the same chunking
    a 1.5 GiB param hits at the default 512 MiB."""
    monkeypatch.setattr(iop, "MAX_CHUNK_SIZE_BYTES", 1 << 20)


def _big_array(nbytes=3 * (1 << 20) + 512 * 1024, seed=0):
    rng = np.random.default_rng(seed)
    n = nbytes // 4
    return jnp.asarray(rng.standard_normal(n), dtype=jnp.float32)


def test_large_dense_writes_multiple_objects(tmp_path, small_chunks):
    arr = _big_array()  # 3.5 MiB -> 4 chunks at a 1 MiB ceiling
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": _Holder({"w": arr})})

    manifest = Snapshot(path).get_manifest()
    entry = manifest["0/m/w"]
    assert isinstance(entry, ShardedArrayEntry)
    assert entry.per_rank and not entry.replicated
    assert len(entry.shards) >= 3
    for shard in entry.shards:
        # One-region chunks in the owner's slice of the dedicated
        # chunk namespace (disjoint from dense leaf locations, so a
        # sibling leaf literally named "w__chunk_0" can never collide).
        assert shard.array.location.startswith("chunked/0/m/w__chunk_")
        assert (tmp_path / "snap" / shard.array.location).exists()
        assert shard.array.checksum is not None
    # Chunks tile the array exactly.
    covered = sum(s.sizes[0] for s in entry.shards)
    assert covered == arr.shape[0]

    target = {"m": _Holder({"w": jnp.zeros_like(arr)})}
    Snapshot(path).restore(target)
    np.testing.assert_array_equal(
        np.asarray(target["m"].sd["w"]), np.asarray(arr)
    )


def test_chunked_dense_restores_to_numpy_and_resharded(tmp_path, small_chunks):
    arr = _big_array(seed=1)
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": _Holder({"w": arr})})

    # Host template.
    target = {"m": _Holder({"w": np.zeros(arr.shape, np.float32)})}
    Snapshot(path).restore(target)
    np.testing.assert_array_equal(target["m"].sd["w"], np.asarray(arr))

    # Mesh-sharded template: chunk boundaries do not align with the
    # 8-way partition, exercising the overlap math chunk x shard.
    mesh = Mesh(np.array(jax.devices()), ("x",))
    sharded_zero = jax.device_put(
        jnp.zeros_like(arr), NamedSharding(mesh, P("x"))
    )
    target2 = {"m": _Holder({"w": sharded_zero})}
    Snapshot(path).restore(target2)
    out = target2["m"].sd["w"]
    assert out.sharding.is_equivalent_to(
        NamedSharding(mesh, P("x")), arr.ndim
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(arr))


def test_chunked_dense_verify_delete_copy_account_every_object(
    tmp_path, small_chunks
):
    arr = _big_array(seed=2)
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": _Holder({"w": arr})})
    snap = Snapshot(path)
    entry = snap.get_manifest()["0/m/w"]
    locations = [s.array.location for s in entry.shards]
    assert len(locations) >= 3

    assert snap.verify() == {}
    # Corrupt ONE chunk: verify must name exactly that object.
    victim = tmp_path / "snap" / locations[1]
    raw = bytearray(victim.read_bytes())
    raw[10] ^= 0xFF
    victim.write_bytes(bytes(raw))
    problems = snap.verify()
    assert set(problems) == {locations[1]}

    # copy_to moves every chunk (and refuses the corrupt one by default).
    with pytest.raises(RuntimeError):
        snap.copy_to(str(tmp_path / "copy-fail"))
    raw[10] ^= 0xFF  # heal
    victim.write_bytes(bytes(raw))
    copied = snap.copy_to(str(tmp_path / "copy"))
    for loc in locations:
        assert (tmp_path / "copy" / loc).exists()
    target = {"m": _Holder({"w": jnp.zeros_like(arr)})}
    copied.restore(target)
    np.testing.assert_array_equal(
        np.asarray(target["m"].sd["w"]), np.asarray(arr)
    )

    # delete removes every chunk object.
    snap.delete()
    for loc in locations:
        assert not (tmp_path / "snap" / loc).exists()


def test_chunked_dense_async_take_round_trip(tmp_path, small_chunks):
    arr = _big_array(seed=3)
    path = str(tmp_path / "snap")
    pending = Snapshot.async_take(path, {"m": _Holder({"w": arr})})
    pending.wait()
    target = {"m": _Holder({"w": jnp.zeros_like(arr)})}
    Snapshot(path).restore(target)
    np.testing.assert_array_equal(
        np.asarray(target["m"].sd["w"]), np.asarray(arr)
    )


def _run_world(world, fn):
    store = DictStore()
    errors = []
    results = [None] * world

    def worker(rank):
        try:
            coord = StoreCoordinator(store, rank, world, timeout_s=60)
            results[rank] = fn(coord, rank)
        except BaseException as e:  # pragma: no cover
            import traceback

            errors.append((rank, e, traceback.format_exc()))

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    if errors:
        raise AssertionError(f"rank {errors[0][0]} failed:\n{errors[0][2]}")
    return results


def test_chunked_dense_replicated_stripe_owner_writes_once(
    tmp_path, small_chunks
):
    """A replicated large dense param chunks AND stripes: the negotiated
    owner writes every chunk exactly once into replicated/, checksums
    come from the owner, and every rank can restore."""
    path = str(tmp_path / "snap")
    arr = _big_array(seed=4)

    def worker(coord, rank):
        app = {"m": _Holder({"w": arr})}
        Snapshot.take(path, app, coord=coord, replicated=["**"])
        return None

    _run_world(2, worker)

    snap = Snapshot(path)
    manifest = snap.get_manifest()
    for r in range(2):
        entry = manifest[f"{r}/m/w"]
        assert isinstance(entry, ShardedArrayEntry)
        assert entry.replicated and not entry.per_rank
    # One set of chunk objects, under the replicated chunk namespace.
    chunk_files = sorted(
        p.name
        for p in (tmp_path / "snap" / "chunked" / "replicated" / "m").iterdir()
    )
    assert len(chunk_files) >= 3
    assert all(name.startswith("w__chunk_") for name in chunk_files)
    # The merged view carries the owner's checksums.
    assert snap.verify() == {}

    # Every rank restores bit-exactly (including a world-size change).
    def restore_worker(coord, rank):
        target = {"m": _Holder({"w": jnp.zeros_like(arr)})}
        Snapshot(path).restore(target, coord=coord)
        np.testing.assert_array_equal(
            np.asarray(target["m"].sd["w"]), np.asarray(arr)
        )

    _run_world(3, restore_worker)


def test_chunked_dense_per_rank_values_stay_per_rank(tmp_path, small_chunks):
    """Two ranks' same-named large per-rank values must NOT merge: each
    rank restores its own bytes, and storage paths never collide."""
    path = str(tmp_path / "snap")

    def worker(coord, rank):
        arr = _big_array(seed=10 + rank)
        Snapshot.take(path, {"m": _Holder({"w": arr})}, coord=coord)
        return None

    _run_world(2, worker)

    manifest = Snapshot(path).get_manifest()
    locs0 = {s.array.location for s in manifest["0/m/w"].shards}
    locs1 = {s.array.location for s in manifest["1/m/w"].shards}
    assert not (locs0 & locs1)

    def restore_worker(coord, rank):
        expected = _big_array(seed=10 + rank)
        target = {"m": _Holder({"w": jnp.zeros_like(expected)})}
        Snapshot(path).restore(target, coord=coord)
        np.testing.assert_array_equal(
            np.asarray(target["m"].sd["w"]), np.asarray(expected)
        )

    _run_world(2, restore_worker)


class _StubCoordinator:
    """Single-threaded stand-in reporting an arbitrary rank/world (the
    test_elastic.py pattern for probing one rank's view)."""

    def __init__(self, rank, world):
        self._rank, self._world = rank, world

    def get_rank(self):
        return self._rank

    def get_world_size(self):
        return self._world

    def barrier(self, timeout_s=None):
        pass

    def all_gather_object(self, obj):
        return [obj] * self._world

    def broadcast_object(self, obj, src=0):
        return obj


def test_chunked_dense_per_rank_elasticity_error(tmp_path, small_chunks):
    """Restoring a per-rank chunked value with a grown world produces
    the actionable elasticity error, exactly like a dense per-rank
    entry (reference snapshot.py:388-406)."""
    path = str(tmp_path / "snap")

    def worker(coord, rank):
        arr = _big_array(seed=20 + rank)
        Snapshot.take(path, {"m": _Holder({"w": arr})}, coord=coord)

    _run_world(2, worker)

    # Rank 2 of a hypothetical world=3 has no per-rank entry.
    target = {"m": _Holder({"w": jnp.zeros(896 * 1024, jnp.float32)})}
    with pytest.raises(RuntimeError, match="only elastic"):
        Snapshot(path).restore(target, coord=_StubCoordinator(rank=2, world=3))


def test_chunked_dense_2d_and_compression(tmp_path, small_chunks):
    """2-D arrays chunk along the largest dim; compressed chunks
    round-trip (each chunk compresses independently)."""
    rng = np.random.default_rng(7)
    arr = jnp.asarray(rng.standard_normal((1536, 512)), jnp.float32)  # 3 MiB
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": _Holder({"w": arr})}, compression="zlib")
    entry = Snapshot(path).get_manifest()["0/m/w"]
    assert isinstance(entry, ShardedArrayEntry)
    assert len(entry.shards) >= 3
    assert all(s.array.compression == "zlib" for s in entry.shards)
    target = {"m": _Holder({"w": jnp.zeros_like(arr)})}
    Snapshot(path).restore(target)
    np.testing.assert_array_equal(
        np.asarray(target["m"].sd["w"]), np.asarray(arr)
    )
