"""Snapshot.copy_to: backend→backend migration with in-transit
verification and metadata-last commit (beyond reference parity — the
reference leaves snapshot migration to external tooling like gsutil,
which verifies nothing and has no commit point)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torchsnapshot_tpu import Snapshot, StateDict


class _Holder:
    def __init__(self, sd):
        self.sd = sd

    def state_dict(self):
        return self.sd

    def load_state_dict(self, sd):
        self.sd = sd


def _app(arr):
    return {"m": _Holder({"w": arr, "meta": {"step": 7, "name": "run"}})}


def test_copy_to_fs_round_trip(tmp_path):
    rng = np.random.default_rng(0)
    arr = jnp.asarray(rng.standard_normal((128, 32)), dtype=jnp.float32)
    src = str(tmp_path / "src")
    Snapshot.take(src, _app(arr))
    dst = str(tmp_path / "dst")
    copied = Snapshot(src).copy_to(dst)
    target = _app(jnp.zeros_like(arr))
    copied.restore(target)
    np.testing.assert_array_equal(np.asarray(target["m"].sd["w"]), arr)
    assert target["m"].sd["meta"]["step"] == 7
    # The copy stands alone: deleting the source must not affect it.
    Snapshot(src).delete()
    target2 = _app(jnp.zeros_like(arr))
    Snapshot(dst).restore(target2)
    np.testing.assert_array_equal(np.asarray(target2["m"].sd["w"]), arr)


def test_copy_to_verifies_in_transit(tmp_path):
    arr = jnp.arange(4096, dtype=jnp.float32)
    src = str(tmp_path / "src")
    Snapshot.take(src, _app(arr))
    # Corrupt a payload on the SOURCE; the copy must refuse to
    # propagate it and must not commit the destination.
    obj = tmp_path / "src" / "0" / "m" / "w"
    raw = bytearray(obj.read_bytes())
    raw[100:104] = b"\xde\xad\xbe\xef"
    obj.write_bytes(bytes(raw))
    dst = str(tmp_path / "dst")
    with pytest.raises(RuntimeError, match="[Cc]hecksum"):
        Snapshot(src).copy_to(dst)
    assert not (tmp_path / "dst" / ".snapshot_metadata").exists()


def test_copy_to_interrupted_leaves_no_commit(tmp_path, monkeypatch):
    """A copy that dies mid-payload leaves the destination invisible
    (metadata-last), so a reader can never observe a half-copied
    snapshot."""
    import torchsnapshot_tpu.snapshot as snap_mod
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    arr = jnp.arange(8192, dtype=jnp.float32)
    src = str(tmp_path / "src")
    app = _app(arr)
    # A second array guarantees the copy has >= 2 payload writes, so
    # the failure below lands mid-payload, before any metadata write.
    app["m"].sd["w2"] = jnp.arange(64, dtype=jnp.float32)
    Snapshot.take(src, app)

    calls = {"n": 0}

    class _DyingFS(FSStoragePlugin):
        async def write(self, io_req):
            calls["n"] += 1
            if calls["n"] >= 2:
                raise IOError("disk on fire")
            await super().write(io_req)

    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRIES", "0")
    orig = snap_mod.url_to_storage_plugin

    def router(path):
        if path.endswith("dst"):
            return _DyingFS(path)
        return orig(path)

    monkeypatch.setattr(snap_mod, "url_to_storage_plugin", router)
    with pytest.raises(IOError, match="disk on fire"):
        Snapshot(src).copy_to(str(tmp_path / "dst"))
    assert not (tmp_path / "dst" / ".snapshot_metadata").exists()


def test_copy_to_sharded_and_compressed(tmp_path):
    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip("needs >= 2 devices")
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(devices[:2]), ("x",))
    arr = jnp.asarray(
        np.random.default_rng(1).standard_normal((64, 16)), jnp.float32
    )
    sharded = jax.device_put(arr, NamedSharding(mesh, P("x", None)))
    src = str(tmp_path / "src")
    Snapshot.take(src, _app(sharded), compression="zlib")
    dst = str(tmp_path / "dst")
    Snapshot(src).copy_to(dst)
    target = _app(jnp.zeros_like(arr))
    Snapshot(dst).restore(target)
    np.testing.assert_array_equal(np.asarray(target["m"].sd["w"]), arr)


def test_copy_to_fake_gcs(monkeypatch, tmp_path):
    """fs → gs:// migration through the fake GCS client — the headline
    use case (local checkpoint promoted to the cloud bucket)."""
    import sys

    sys.path.insert(0, "tests")
    from test_cloud_plugins import _FakeGCSClient

    from torchsnapshot_tpu.storage_plugins.gcs import GCSStoragePlugin
    from torchsnapshot_tpu.io_types import RetryingStoragePlugin
    import torchsnapshot_tpu.snapshot as snap_mod

    client = _FakeGCSClient()
    orig = snap_mod.url_to_storage_plugin

    def router(url):
        if url.startswith("gs://"):
            return RetryingStoragePlugin(
                GCSStoragePlugin(root=url[len("gs://"):], client=client)
            )
        return orig(url)

    monkeypatch.setattr(snap_mod, "url_to_storage_plugin", router)
    arr = jnp.arange(2048, dtype=jnp.float32)
    src = str(tmp_path / "src")
    Snapshot.take(src, _app(arr))
    Snapshot(src).copy_to("gs://bucket/promoted")
    target = _app(jnp.zeros_like(arr))
    Snapshot("gs://bucket/promoted").restore(target)
    np.testing.assert_array_equal(
        np.asarray(target["m"].sd["w"]), np.arange(2048, dtype=np.float32)
    )


def test_copy_to_malformed_budget_env_falls_back(tmp_path, monkeypatch):
    """A malformed TPUSNAPSHOT_COPY_BUDGET_BYTES must log-and-default
    like the sibling env knobs, not abort the copy (ADVICE r4)."""
    monkeypatch.setenv("TPUSNAPSHOT_COPY_BUDGET_BYTES", "not-a-number")
    arr = jnp.arange(256, dtype=jnp.float32)
    src = str(tmp_path / "src")
    Snapshot.take(src, _app(arr))
    dst = str(tmp_path / "dst")
    Snapshot(src).copy_to(dst)
    target = _app(jnp.zeros_like(arr))
    Snapshot(dst).restore(target)
    np.testing.assert_array_equal(
        np.asarray(target["m"].sd["w"]), np.arange(256, dtype=np.float32)
    )


def test_copy_to_sizes_object_entries_from_backend(tmp_path, monkeypatch):
    """Object entries carry no size in the manifest; copy_to must admit
    them against the byte budget at their STORED size (backend stat),
    not a token flat estimate (ADVICE r4)."""
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    sized_paths = []
    orig = FSStoragePlugin.object_size_bytes

    async def _spy(self, path):
        size = await orig(self, path)
        sized_paths.append((path, size))
        return size

    monkeypatch.setattr(FSStoragePlugin, "object_size_bytes", _spy)
    src = str(tmp_path / "src")
    # A set is not a flattenable container/primitive: it persists as a
    # pickled object entry with no size in the manifest.
    app = {"m": _Holder({"w": jnp.arange(8, dtype=jnp.float32),
                         "tags": {"a", "b", "c"}})}
    Snapshot.take(src, app)
    dst = str(tmp_path / "dst")
    Snapshot(src).copy_to(dst)
    assert sized_paths, "object entries should be stat-sized"
    for path, size in sized_paths:
        assert "tags" in path
        real = (tmp_path / "src" / path).stat().st_size
        assert size == real > 0
    target = {"m": _Holder({"w": jnp.zeros(8, jnp.float32), "tags": set()})}
    Snapshot(dst).restore(target)
    assert target["m"].sd["tags"] == {"a", "b", "c"}


def test_inspect_cli_copy_to(tmp_path, capsys):
    arr = jnp.arange(16, dtype=jnp.float32)
    src = str(tmp_path / "src")
    Snapshot.take(src, _app(arr))
    from torchsnapshot_tpu.inspect import main as inspect_main

    dst = str(tmp_path / "dst")
    assert inspect_main([src, "--copy-to", dst]) == 0
    assert "copied" in capsys.readouterr().out
    target = _app(jnp.zeros_like(arr))
    Snapshot(dst).restore(target)
    np.testing.assert_array_equal(
        np.asarray(target["m"].sd["w"]), np.arange(16, dtype=np.float32)
    )
