"""Partial restore (``Snapshot.restore(paths=...)``) and container reads
via ``Snapshot.read_object`` (beyond-parity random-access features)."""

import numpy as np
import pytest

import jax.numpy as jnp

from torchsnapshot_tpu import Snapshot, StateDict


class _Holder:
    def __init__(self, sd):
        self.sd = sd

    def state_dict(self):
        return self.sd

    def load_state_dict(self, sd):
        self.sd = sd


def _app():
    return {
        "model": _Holder(
            {
                "layers": {
                    "w0": jnp.arange(8.0),
                    "w1": jnp.arange(8.0) * 2,
                },
                "head": jnp.arange(4.0),
            }
        ),
        "optim": _Holder({"mu": jnp.ones(8), "step": 7}),
    }


@pytest.fixture
def snap_path(tmp_path):
    path = str(tmp_path / "snap")
    Snapshot.take(path, _app())
    return path


def test_partial_restore_glob(snap_path):
    target = {
        "model": _Holder(
            {
                "layers": {"w0": jnp.zeros(8), "w1": jnp.zeros(8)},
                "head": jnp.zeros(4),
            }
        ),
        "optim": _Holder({"mu": jnp.zeros(8), "step": -1}),
    }
    Snapshot(snap_path).restore(target, paths=["model/layers/**"])
    sd = target["model"].sd
    np.testing.assert_array_equal(np.asarray(sd["layers"]["w0"]), np.arange(8.0))
    np.testing.assert_array_equal(
        np.asarray(sd["layers"]["w1"]), np.arange(8.0) * 2
    )
    # Outside the filter: untouched.
    np.testing.assert_array_equal(np.asarray(sd["head"]), np.zeros(4))
    assert target["optim"].sd["step"] == -1
    np.testing.assert_array_equal(np.asarray(target["optim"].sd["mu"]), np.zeros(8))


def test_partial_restore_whole_stateful(snap_path):
    target = {
        "model": _Holder(
            {
                "layers": {"w0": jnp.zeros(8), "w1": jnp.zeros(8)},
                "head": jnp.zeros(4),
            }
        ),
        "optim": _Holder({"mu": jnp.zeros(8), "step": -1}),
    }
    Snapshot(snap_path).restore(target, paths=["optim/**"])
    assert target["optim"].sd["step"] == 7
    np.testing.assert_array_equal(np.asarray(target["optim"].sd["mu"]), np.ones(8))
    np.testing.assert_array_equal(
        np.asarray(target["model"].sd["layers"]["w0"]), np.zeros(8)
    )


def test_partial_restore_missing_selected_path_still_errors(snap_path):
    target = {"model": _Holder({"layers": {"nonexistent": jnp.zeros(3)}})}
    with pytest.raises(RuntimeError, match="Unable to find an entry"):
        Snapshot(snap_path).restore(target, paths=["model/**"])


def test_partial_restore_filter_excludes_missing_path(snap_path):
    # The same missing path filtered OUT does not error.
    target = {
        "model": _Holder(
            {
                "layers": {
                    "w0": jnp.zeros(8),
                    "w1": jnp.zeros(8),
                    "nonexistent": jnp.zeros(3),
                },
                "head": jnp.zeros(4),
            }
        )
    }
    Snapshot(snap_path).restore(target, paths=["model/head"])
    np.testing.assert_array_equal(np.asarray(target["model"].sd["head"]), np.arange(4.0))


def test_read_object_container(snap_path):
    layers = Snapshot(snap_path).read_object("model/layers")
    assert set(layers.keys()) == {"w0", "w1"}
    np.testing.assert_array_equal(np.asarray(layers["w0"]), np.arange(8.0))
    np.testing.assert_array_equal(np.asarray(layers["w1"]), np.arange(8.0) * 2)


def test_read_object_container_with_primitives_and_objects(tmp_path):
    path = str(tmp_path / "snap")
    Snapshot.take(
        path,
        {
            "st": StateDict(
                epoch=3,
                name="run-a",
                nested={"xs": [1, 2, 3], "arr": np.arange(5.0)},
            )
        },
    )
    nested = Snapshot(path).read_object("st/nested")
    assert nested["xs"] == [1, 2, 3]
    np.testing.assert_array_equal(nested["arr"], np.arange(5.0))
    whole = Snapshot(path).read_object("st")
    assert whole["epoch"] == 3
    assert whole["name"] == "run-a"


def test_read_object_container_rejects_template(snap_path):
    with pytest.raises(ValueError, match="container"):
        Snapshot(snap_path).read_object("model/layers", template=jnp.zeros(8))


def test_partial_restore_no_match_raises(snap_path):
    target = {
        "model": _Holder({"layers": {"w0": jnp.zeros(8), "w1": jnp.zeros(8)},
                          "head": jnp.zeros(4)}),
    }
    with pytest.raises(RuntimeError, match="matched no leaf"):
        Snapshot(snap_path).restore(target, paths=["Model/**"])  # typo'd case
