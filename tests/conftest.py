"""Force an 8-device virtual CPU platform for all tests.

Runs before any test module imports jax. The axon sitecustomize may have
already registered the TPU plugin and set JAX_PLATFORMS=axon, so we both
scrub the env and override the jax config in-process (backends initialize
lazily — on first jax.devices() — which happens after this).

Accelerator-tier escape hatch (the reference's tests/gpu_tests pattern):
``TPUSNAPSHOT_TPU_TESTS=1 pytest tests/tpu_tests`` keeps the ambient
platform (the real TPU) instead. The hatch requires BOTH the env var
``== "1"`` and an invocation that names tpu_tests: the hermetic suite
depends on the forced 8-device CPU mesh, so
``TPUSNAPSHOT_TPU_TESTS=1 pytest tests/`` must not un-force it (the
tpu tier then simply self-skips on the cpu platform).
"""

import os
import sys

_tpu_tier_run = os.environ.get("TPUSNAPSHOT_TPU_TESTS") == "1" and any(
    "tpu_tests" in arg for arg in sys.argv[1:]
)

if not _tpu_tier_run:
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
