"""Force an 8-device virtual CPU platform for all tests.

Runs before any test module imports jax. The axon sitecustomize may have
already registered the TPU plugin and set JAX_PLATFORMS=axon, so we both
scrub the env and override the jax config in-process (backends initialize
lazily — on first jax.devices() — which happens after this).
"""

import os

os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
