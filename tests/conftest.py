"""Force an 8-device virtual CPU platform for all tests.

Runs before any test module imports jax. The axon sitecustomize may have
already registered the TPU plugin and set JAX_PLATFORMS=axon, so we both
scrub the env and override the jax config in-process (backends initialize
lazily — on first jax.devices() — which happens after this).

Accelerator-tier escape hatch (the reference's tests/gpu_tests pattern):
``TPUSNAPSHOT_TPU_TESTS=1 pytest tests/tpu_tests`` keeps the ambient
platform (the real TPU) instead. The hatch requires BOTH the env var
``== "1"`` and an invocation whose test paths all lie inside tpu_tests:
the hermetic suite depends on the forced 8-device CPU mesh, so a mixed
or broad invocation (``TPUSNAPSHOT_TPU_TESTS=1 pytest tests/``) keeps
the forcing and the tpu tier simply self-skips on cpu.
"""

import os
import sys


_TIER_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tpu_tests")


def _inside_tier(path: str) -> bool:
    """Whether ``path`` is the tier directory or inside it (anchored to
    the resolved dir — a checkout path merely *containing* "tpu_tests"
    must not satisfy the gate)."""
    p = os.path.abspath(path)
    return p == _TIER_DIR or p.startswith(_TIER_DIR + os.sep)


def _tpu_tier_invocation() -> bool:
    if os.environ.get("TPUSNAPSHOT_TPU_TESTS") != "1":
        return False
    # Positional args that resolve to existing paths (strip ::nodeid).
    paths = [
        a.split("::")[0]
        for a in sys.argv[1:]
        if not a.startswith("-") and os.path.exists(a.split("::")[0])
    ]
    if paths:
        return all(_inside_tier(p) for p in paths)
    # Bare `pytest` run: honor the env var only from inside the tier dir.
    return _inside_tier(os.getcwd())


if not _tpu_tier_invocation():
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
