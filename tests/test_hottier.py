"""snaptier: preemption-tolerant hot tier — replication, tier-down,
degraded restore, and the host-loss x crash-point fault matrix.

Fast tier (``-m faultline``, runs in tier-1): ack-before-drain
semantics, the k-1 host-loss bit-exact e2e acceptance, per-object
durable fallback (dead / corrupt replicas) with the
``hot-tier-degraded`` doctor rule and the ledger ``tier`` field,
capacity/eviction invariants, reconcile's keep-committed-undrained
proof, and a stride-sampled crash matrix over the tiered
save→commit→tier-down pipeline. The full per-op crash enumeration and
the host-loss x crash-point product are also marked ``slow``.
"""

import json
import uuid

import numpy as np
import pytest

import jax.numpy as jnp

from torchsnapshot_tpu import CheckpointManager, Snapshot, StateDict, hottier
from torchsnapshot_tpu import faultline as fl
from torchsnapshot_tpu.hottier import tier as ht_tier
from torchsnapshot_tpu.io_types import IOReq
from torchsnapshot_tpu.manager import _step_dir
from torchsnapshot_tpu.storage_plugin import url_to_storage_plugin
from torchsnapshot_tpu.telemetry import ledger as runledger
from torchsnapshot_tpu.telemetry.doctor import diagnose_report

pytestmark = pytest.mark.faultline


# ----------------------------------------------------------------- helpers


@pytest.fixture(autouse=True)
def _fresh_tier():
    """Every test starts and ends with an empty hot tier and no runtime
    (a leaked enable would silently re-route every other test's IO)."""
    hottier.disable_hot_tier(flush=False)
    hottier.reset_hot_tier()
    yield
    hottier.disable_hot_tier(flush=False)
    hottier.reset_hot_tier()


def _state(v, n=1024):
    return {"s": StateDict(w=jnp.full((n,), float(v)))}


def _target(n=1024):
    return {"s": StateDict(w=jnp.zeros((n,)))}


def _assert_restored(target, v):
    np.testing.assert_array_equal(np.asarray(target["s"]["w"]), float(v))


def _mem_base(tag):
    return f"memory://hottier-{tag}-{uuid.uuid4().hex[:10]}/run"


def _durable_objects(url):
    storage = url_to_storage_plugin(url)
    try:
        import asyncio

        return sorted(asyncio.run(storage.list_prefix("")) or [])
    finally:
        storage.close()


def _payload_objects(url):
    return [o for o in _durable_objects(url) if hottier.is_payload_path(o)]


def _read_json(url, path):
    import asyncio

    from torchsnapshot_tpu.io_types import io_payload

    storage = url_to_storage_plugin(url)
    try:
        io_req = IOReq(path=path)
        asyncio.run(storage.read(io_req))
        return json.loads(bytes(io_payload(io_req)).decode("utf-8"))
    finally:
        storage.close()


# ------------------------------------------------- ack / drain / watermark


def test_ack_before_drain_and_tierdown_watermark(tmp_path):
    """The take commits with payloads k-replicated in peer RAM only;
    tier-down persists them in the background and records the
    ``.tierdown`` watermark; after a full drain the snapshot restores
    from the durable tier alone."""
    root = str(tmp_path / "step-0")
    with hottier.hot_tier(rank=0, world=4, k=2, drain="manual"):
        snap = Snapshot.take(root, _state(7))
        # Committed (metadata durable) but payloads are hot-tier-only.
        objs = _durable_objects(root)
        assert ".snapshot_metadata" in objs
        assert not _payload_objects(root)
        assert ".tierdown" not in objs
        # Restorable RIGHT NOW, from peer RAM.
        target = _target()
        snap.restore({"s": target["s"]})
        _assert_restored(target, 7)
        # Tier-down: payloads land durable, watermark follows.
        hottier.drain_now()
        assert _payload_objects(root)
        watermark = _read_json(root, ".tierdown")
        assert watermark["format_version"] == 1
        assert watermark["drained_objects"] >= 1
    # Tier disabled (RAM gone): the durable tier alone must suffice.
    hottier.reset_hot_tier()
    target = _target()
    Snapshot(root).restore({"s": target["s"]})
    _assert_restored(target, 7)


def test_verify_clean_while_hot_only(tmp_path):
    """Snapshot.verify() sees through the tier: a committed-but-undrained
    snapshot scrubs clean (bytes exist in >= 1 tier, which is the tiered
    integrity contract)."""
    root = str(tmp_path / "step-0")
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        snap = Snapshot.take(root, _state(3))
        assert not _payload_objects(root)
        assert snap.verify() == {}


# ------------------------------------------------------ host-loss restores


@pytest.mark.parametrize("lost_host", [0, 1])
def test_k1_host_loss_restores_bit_exact(lost_host):
    """E2E acceptance: with k=2 and payloads living ONLY in peer RAM
    (nothing drained), losing any k-1=1 host still restores bit-exact
    from the surviving replicas."""
    base = _mem_base("k1loss")
    root = f"{base}/step-0"
    rng = np.random.default_rng(42)
    payload = rng.standard_normal(4096).astype(np.float32)
    with hottier.hot_tier(rank=0, world=4, k=2, drain="manual"):
        snap = Snapshot.take(root, {"s": StateDict(w=jnp.asarray(payload))})
        assert not _payload_objects(root)  # hot-tier-only on purpose
        hottier.kill_host(lost_host)
        target = {"s": StateDict(w=jnp.zeros((4096,), jnp.float32))}
        snap.restore(target)
        np.testing.assert_array_equal(
            np.asarray(target["s"]["w"]), payload
        )
        stats = hottier.runtime().stats_snapshot()
        assert stats["fallback_objects"] == 0  # never touched durable


def test_all_replicas_lost_falls_back_and_fires_doctor():
    """Losing ALL replica hosts after tier-down degrades to per-object
    durable reads; the restore stays bit-exact, the flight report's
    ``tier`` block names the dead peers, the ``hot-tier-degraded``
    doctor rule fires critical (100% of bytes fell back), and the
    ledger record carries the ``tier`` field."""
    base = _mem_base("alllost")
    root = f"{base}/step-0"
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        snap = Snapshot.take(root, _state(9))
        hottier.drain_now()  # durable copy exists; replicas evictable
        hottier.kill_host(0)
        hottier.kill_host(1)
        target = _target()
        snap.restore({"s": target["s"]})
        _assert_restored(target, 9)
        report = _read_json(root, ".report.restore.json")
        tier_blocks = [
            s.get("tier") for s in report["ranks"] if s and s.get("tier")
        ]
        assert tier_blocks, report["ranks"]
        assert tier_blocks[0]["fallback_objects"] >= 1
        assert tier_blocks[0]["hot_objects"] == 0
        assert sorted(tier_blocks[0]["degraded_peers"]) == [0, 1]
        findings = {f.rule: f for f in diagnose_report(report)}
        assert "hot-tier-degraded" in findings
        finding = findings["hot-tier-degraded"]
        assert finding.severity == "critical"
        assert finding.evidence["degraded_peers"] == "peer hosts 0-1"
        assert finding.evidence["reasons"].get("dead", 0) >= 1
        # Ledger: the restore record carries the tier attribution.
        records, _ = runledger.read_records(root)
        restores = [r for r in records if r["kind"] == "restore"]
        assert restores and restores[-1]["tier"]["fallback_objects"] >= 1
        assert restores[-1]["tier"]["degraded_peers"] == [0, 1]


def test_corrupt_replica_falls_back_per_object():
    """A replica that fails its fingerprint check is dropped and the
    read falls over — to the durable tier here (k=1), bit-exact."""
    base = _mem_base("corrupt")
    root = f"{base}/step-0"
    with hottier.hot_tier(rank=0, world=1, k=1, drain="manual"):
        snap = Snapshot.take(root, _state(5))
        hottier.drain_now()
        # Flip one byte of the single replica in host 0's RAM.
        with ht_tier._TIER_LOCK:
            store = ht_tier._HOSTS[0]
            key = next(iter(store.objects))
            obj = store.objects[key]
            obj.data = obj.data[:-1] + bytes([obj.data[-1] ^ 0xFF])
        target = _target()
        snap.restore({"s": target["s"]})
        _assert_restored(target, 5)
        stats = hottier.runtime().stats_snapshot()
        assert stats["reasons"].get("corrupt", 0) >= 1
        assert stats["fallback_objects"] >= 1
        # The corrupt replica was dropped — nothing can read it again.
        assert ht_tier.total_buffered_bytes() < 4096


def test_lose_host_schedule_is_deterministic():
    """faultline's host-loss schedule kills a peer at a fixed op
    boundary: the take completes, the host is dead afterwards, and the
    injection log records the hostloss."""
    base = _mem_base("sched")
    root = f"{base}/step-0"
    sched = fl.FaultSchedule().lose_host(
        1, op="write", path=".snapshot_metadata"
    )
    with fl.inject(sched) as ctl:
        with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
            snap = Snapshot.take(root, _state(4))
            assert 1 not in hottier.live_hosts()
            assert ctl.fault_counts().get("hostloss") == 1
            # Host 0's replica still serves the restore.
            target = _target()
            snap.restore({"s": target["s"]})
            _assert_restored(target, 4)


# --------------------------------------------------- capacity and eviction


def test_undrained_never_evicted_capacity_degrades_to_write_through():
    """An undrained object is the only copy outside its replica set:
    capacity pressure must refuse the put (degrading the write to a
    synchronous durable write-through), never evict undrained bytes."""
    base = _mem_base("cap")
    root = f"{base}/step-0"
    # Room for roughly one 4 KiB payload per host.
    with hottier.hot_tier(
        rank=0, world=1, k=1, capacity_bytes=6000, drain="manual"
    ):
        snap = Snapshot.take(
            root,
            {
                "a": StateDict(w=jnp.full((1024,), 1.0)),
                "b": StateDict(w=jnp.full((1024,), 2.0)),
            },
        )
        stats = hottier.runtime().stats_snapshot()
        # One payload went hot, the other was refused and wrote through.
        assert stats["write_through"] >= 1
        assert ht_tier.total_buffered_bytes() <= 6000
        # Everything still restores (mixed hot + durable).
        target = {
            "a": StateDict(w=jnp.zeros((1024,))),
            "b": StateDict(w=jnp.zeros((1024,))),
        }
        snap.restore(target)
        got = {
            float(np.asarray(target["a"]["w"])[0]),
            float(np.asarray(target["b"]["w"])[0]),
        }
        assert got == {1.0, 2.0}
        # After tier-down the buffered object is drained and EVICTABLE:
        # the next put may displace it.
        hottier.drain_now()
        rt = hottier.runtime()
        assert rt.hot_put(root, "0/extra/blob", b"x" * 4096) == 1
        assert ht_tier.total_buffered_bytes() <= 6000


def test_k_env_knob(monkeypatch):
    monkeypatch.setenv(hottier.K_ENV_VAR, "3")
    with hottier.hot_tier(rank=0, world=8, drain="manual") as rt:
        assert rt.k == 3
        assert rt.replica_hosts() == [0, 1, 2]
    monkeypatch.setenv(hottier.K_ENV_VAR, "99")
    with hottier.hot_tier(rank=5, world=4, drain="manual") as rt:
        assert rt.k == 4  # clamped to world
        assert rt.replica_hosts() == [5 % 4, 2, 3, 0]


# ------------------------------------------------- delete / reconcile GC


def test_delete_cancels_pending_drain_and_drops_buffers(tmp_path):
    """Deleting a committed-but-undrained snapshot cancels its pending
    tier-down (a background drain must not resurrect deleted objects)
    and drops its replicas; the ``.tierdown`` watermark goes with a
    drained snapshot."""
    root_a = str(tmp_path / "step-0")
    root_b = str(tmp_path / "step-1")
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        snap_a = Snapshot.take(root_a, _state(1))
        assert hottier.buffered_roots()
        snap_a.delete()
        assert not hottier.buffered_roots()
        hottier.drain_now()  # nothing to resurrect
        assert not _payload_objects(root_a)
        # Drained snapshot: delete removes payloads AND the watermark.
        snap_b = Snapshot.take(root_b, _state(2))
        hottier.drain_now()
        assert ".tierdown" in _durable_objects(root_b)
        snap_b.delete()
        assert ".tierdown" not in _durable_objects(root_b)
        assert not hottier.buffered_roots()


def test_reconcile_keeps_committed_undrained_drops_aged_orphans(
    monkeypatch,
):
    """The reconcile sweep must never reclaim replicas a committed-but-
    not-yet-drained take still needs (they are the only copy of its
    payload bytes), while an uncommitted crashed take's buffers — which
    nothing can ever resolve — are reclaimed once aged."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    base = _mem_base("reconcile")
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        mgr = CheckpointManager(base)
        mgr.save(0, _state(0))  # committed, NOT drained
        committed_root = _step_dir(base, 0)
        # Fake an uncommitted crashed take: hot buffers, no metadata.
        rt = hottier.runtime()
        orphan_root = _step_dir(base, 99)
        rt.hot_put(orphan_root, "0/s/w", b"y" * 512)
        rt.enqueue_drain(orphan_root, "0/s/w")
        assert set(hottier.buffered_roots()) == {
            committed_root,
            orphan_root,
        }
        mgr.reconcile(adopt=True)
        # Orphan reclaimed (age guard disabled), committed kept.
        assert set(hottier.buffered_roots()) == {committed_root}
        # ... and the committed step still restores from the hot tier.
        target = _target()
        assert mgr.restore({"s": target["s"]}, step=0) == 0
        _assert_restored(target, 0)
        # With the age guard ON, even an uncommitted orphan is spared
        # (it may be an in-flight take).
        rt.hot_put(orphan_root, "0/s/w", b"y" * 512)
        monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "3600")
        mgr.reconcile(adopt=True)
        assert orphan_root in hottier.buffered_roots()


def test_drain_exhaustion_strands_then_redrives(monkeypatch):
    """A durable outage outlasting the drain attempts leaves the object
    STRANDED: wait_drained() must report the flush dirty (the hot copy
    is the only copy — claiming success would let a caller tear the
    tier down over it), and the next drain_now() re-drives it to a
    clean tier-down."""
    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRIES", "0")
    base = _mem_base("strand")
    root = f"{base}/step-0"
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        # nth=2: the 1st match is the take's logical write (which the
        # tier absorbs into RAM); every durable drain write after it
        # fails permanently.
        sched = fl.FaultSchedule().permanent(op="write", path="0/s/w", nth=2)
        with fl.inject(sched):
            snap = Snapshot.take(root, _state(8))
            hottier.drain_now()  # attempts exhaust; object stranded
            assert not hottier.wait_drained(timeout_s=1.0)
            assert not _payload_objects(root)
            # The snapshot is still fully restorable from the hot tier.
            target = _target()
            snap.restore({"s": target["s"]})
            _assert_restored(target, 8)
        # Outage over (faults uninstalled): re-drive to a clean flush.
        hottier.drain_now()
        assert hottier.wait_drained(timeout_s=5.0)
        assert _payload_objects(root)
        assert ".tierdown" in _durable_objects(root)


def test_tierdown_write_failure_is_redriven(monkeypatch):
    """A failed ``.tierdown`` watermark write must leave a re-drive
    trigger even though the root is fully drained (no object item will
    ever call back into the watermark path)."""
    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRIES", "0")
    base = _mem_base("tdfail")
    root = f"{base}/step-0"
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        sched = fl.FaultSchedule().permanent(op="write", path=".tierdown")
        with fl.inject(sched):
            Snapshot.take(root, _state(2))
            hottier.drain_now()
            assert _payload_objects(root)  # objects drained fine
            assert ".tierdown" not in _durable_objects(root)
            assert not hottier.wait_drained(timeout_s=1.0)
        hottier.drain_now()
        assert hottier.wait_drained(timeout_s=5.0)
        assert ".tierdown" in _durable_objects(root)


# --------------------------------------------------- crash/fault matrices


def _prepare_matrix(monkeypatch, drained_history=True):
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    hottier.reset_hot_tier()
    hottier.reset_pending()
    base = _mem_base("crashmx")
    mgr = CheckpointManager(base, max_to_keep=1)
    mgr.save(0, _state(0))
    mgr.save(1, _state(1))
    if drained_history:
        hottier.drain_now()
    return base


def _faulted_matrix(base):
    # One full tiered lifecycle: take step 2 (replicate + ack + commit +
    # marker + prune), then tier-down (drain + watermark).
    CheckpointManager(base, max_to_keep=1).save(2, _state(2))
    hottier.drain_now()


def _probe(base):
    def probe(step):
        target = _target()
        got = CheckpointManager(base).restore(target, step=step)
        assert got == step
        _assert_restored(target, step)

    return probe


def _check_matrix(base, outcome):
    # (a)/(b): every marker-visible step restores clean (hot tier or
    # durable); reconcile adopts committed-unmarked work and reclaims
    # crashed debris — including hot-tier buffers.
    res = fl.check_recovery_invariant(base, _probe(base))
    outcome.marked_steps = res.marked_steps
    outcome.adopted_steps = res.adopted_steps
    # Recovery re-drive: a fresh save→drain cycle succeeds, re-drives
    # any interrupted tier-down, and leaves no leaked objects in EITHER
    # tier.
    mgr = CheckpointManager(base, max_to_keep=1, reconcile_on_init="adopt")
    mgr.save(3, _state(3))
    hottier.drain_now()
    mgr.reconcile(adopt=True)
    assert mgr.latest_step() == 3
    _probe(base)(3)
    fl.assert_reclaimed(base, [3])
    # Zero leaked hot-tier buffers: only the live step may stay hot.
    live_root = _step_dir(base, 3)
    assert set(hottier.buffered_roots()) <= {live_root}
    # The live step finished its tier-down: watermark present.
    assert ".tierdown" in _durable_objects(live_root)


def test_tiered_crash_matrix_fast_subset(monkeypatch):
    """Stride-sampled crash points across take→ack→commit→tier-down
    with the hot tier on (tier-1). Proves restore-or-detect plus
    leak-free reconcile at every sampled boundary — including the
    hottier.replicate / hottier.drain / hottier.tierdown boundaries the
    tier adds to the op stream."""
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        base = _prepare_matrix(monkeypatch)
        total = fl.count_storage_ops(lambda: _faulted_matrix(base))
        assert total > 0
        stride = max(1, total // 6)
        points = sorted(set(range(1, total + 1, stride)) | {1, total})
        report = fl.enumerate_crash_points(
            prepare=lambda: _prepare_matrix(monkeypatch),
            faulted=_faulted_matrix,
            check=_check_matrix,
            crash_points=points,
            total_ops=total,
        )
        assert set(report.outcomes) == set(points)
        assert any(o.crashed for o in report.outcomes.values())


@pytest.mark.slow
def test_tiered_crash_matrix_full(monkeypatch):
    """Full per-op crash enumeration over the tiered pipeline."""
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        report = fl.enumerate_crash_points(
            prepare=lambda: _prepare_matrix(monkeypatch),
            faulted=_faulted_matrix,
            check=_check_matrix,
        )
        assert report.total_ops > 0
        assert any(o.crashed for o in report.outcomes.values())


@pytest.mark.slow
@pytest.mark.parametrize("lost_host", [0, 1])
def test_host_loss_x_crash_point_enumeration(monkeypatch, lost_host):
    """The product matrix: at every sampled crash point, ALSO lose one
    peer host before recovery runs — any k-1 loss composed with any
    crash must still satisfy restore-or-detect with zero leaks."""

    def check(base, outcome):
        hottier.kill_host(lost_host)
        try:
            _check_matrix(base, outcome)
        finally:
            hottier.revive_host(lost_host)

    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        base = _prepare_matrix(monkeypatch)
        total = fl.count_storage_ops(lambda: _faulted_matrix(base))
        points = sorted(
            set(range(1, total + 1, max(1, total // 12))) | {1, total}
        )
        report = fl.enumerate_crash_points(
            prepare=lambda: _prepare_matrix(monkeypatch),
            faulted=_faulted_matrix,
            check=check,
            crash_points=points,
            total_ops=total,
        )
        assert any(o.crashed for o in report.outcomes.values())


def test_mid_replication_host_loss_during_take(monkeypatch):
    """Partial-tier-down schedule: a peer dies WHILE the take is
    replicating (lose_host bound to a payload write boundary). The take
    must still commit (surviving replicas + write-through degradation)
    and restore bit-exact."""
    base = _mem_base("midloss")
    root = f"{base}/step-0"
    sched = fl.FaultSchedule().lose_host(1, op="hottier.replicate", nth=2)
    with fl.inject(sched):
        with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
            snap = Snapshot.take(root, _state(6))
            target = _target()
            snap.restore({"s": target["s"]})
            _assert_restored(target, 6)
            hottier.drain_now()  # tier-down proceeds from survivors
            assert _payload_objects(root)
            assert ".tierdown" in _durable_objects(root)
