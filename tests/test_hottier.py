"""snaptier: preemption-tolerant hot tier — replication, tier-down,
degraded restore, and the host-loss x crash-point fault matrix.

Fast tier (``-m faultline``, runs in tier-1): ack-before-drain
semantics, the k-1 host-loss bit-exact e2e acceptance, per-object
durable fallback (dead / corrupt replicas) with the
``hot-tier-degraded`` doctor rule and the ledger ``tier`` field,
capacity/eviction invariants, reconcile's keep-committed-undrained
proof, and a stride-sampled crash matrix over the tiered
save→commit→tier-down pipeline. The full per-op crash enumeration and
the host-loss x crash-point product are also marked ``slow``.
"""

import json
import threading
import time
import uuid

import numpy as np
import pytest

import jax.numpy as jnp

from torchsnapshot_tpu import CheckpointManager, Snapshot, StateDict, hottier
from torchsnapshot_tpu import faultline as fl
from torchsnapshot_tpu.hottier import tier as ht_tier
from torchsnapshot_tpu.io_types import IOReq
from torchsnapshot_tpu.manager import _step_dir
from torchsnapshot_tpu.storage_plugin import url_to_storage_plugin
from torchsnapshot_tpu.telemetry import ledger as runledger
from torchsnapshot_tpu.telemetry.doctor import diagnose_report

pytestmark = pytest.mark.faultline


# ----------------------------------------------------------------- helpers


@pytest.fixture(autouse=True)
def _fresh_tier():
    """Every test starts and ends with an empty hot tier and no runtime
    (a leaked enable would silently re-route every other test's IO)."""
    hottier.disable_hot_tier(flush=False)
    hottier.reset_hot_tier()
    yield
    hottier.disable_hot_tier(flush=False)
    hottier.reset_hot_tier()


def _state(v, n=1024):
    return {"s": StateDict(w=jnp.full((n,), float(v)))}


def _target(n=1024):
    return {"s": StateDict(w=jnp.zeros((n,)))}


def _assert_restored(target, v):
    np.testing.assert_array_equal(np.asarray(target["s"]["w"]), float(v))


def _mem_base(tag):
    return f"memory://hottier-{tag}-{uuid.uuid4().hex[:10]}/run"


def _durable_objects(url):
    storage = url_to_storage_plugin(url)
    try:
        import asyncio

        return sorted(asyncio.run(storage.list_prefix("")) or [])
    finally:
        storage.close()


def _payload_objects(url):
    return [o for o in _durable_objects(url) if hottier.is_payload_path(o)]


def _read_bytes(url, path):
    import asyncio

    from torchsnapshot_tpu.io_types import io_payload

    storage = url_to_storage_plugin(url)
    try:
        io_req = IOReq(path=path)
        asyncio.run(storage.read(io_req))
        return bytes(io_payload(io_req))
    finally:
        storage.close()


def _read_json(url, path):
    return json.loads(_read_bytes(url, path).decode("utf-8"))


# ------------------------------------------------- ack / drain / watermark


def test_ack_before_drain_and_tierdown_watermark(tmp_path):
    """The take commits with payloads k-replicated in peer RAM only;
    tier-down persists them in the background and records the
    ``.tierdown`` watermark; after a full drain the snapshot restores
    from the durable tier alone."""
    root = str(tmp_path / "step-0")
    with hottier.hot_tier(rank=0, world=4, k=2, drain="manual"):
        snap = Snapshot.take(root, _state(7))
        # Committed (metadata durable) but payloads are hot-tier-only.
        objs = _durable_objects(root)
        assert ".snapshot_metadata" in objs
        assert not _payload_objects(root)
        assert ".tierdown" not in objs
        # Restorable RIGHT NOW, from peer RAM.
        target = _target()
        snap.restore({"s": target["s"]})
        _assert_restored(target, 7)
        # Tier-down: payloads land durable, watermark follows.
        hottier.drain_now()
        assert _payload_objects(root)
        watermark = _read_json(root, ".tierdown")
        assert watermark["format_version"] == 1
        assert watermark["drained_objects"] >= 1
    # Tier disabled (RAM gone): the durable tier alone must suffice.
    hottier.reset_hot_tier()
    target = _target()
    Snapshot(root).restore({"s": target["s"]})
    _assert_restored(target, 7)


def test_verify_clean_while_hot_only(tmp_path):
    """Snapshot.verify() sees through the tier: a committed-but-undrained
    snapshot scrubs clean (bytes exist in >= 1 tier, which is the tiered
    integrity contract)."""
    root = str(tmp_path / "step-0")
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        snap = Snapshot.take(root, _state(3))
        assert not _payload_objects(root)
        assert snap.verify() == {}


# ------------------------------------------------------ host-loss restores


@pytest.mark.parametrize("lost_host", [0, 1])
def test_k1_host_loss_restores_bit_exact(lost_host):
    """E2E acceptance: with k=2 and payloads living ONLY in peer RAM
    (nothing drained), losing any k-1=1 host still restores bit-exact
    from the surviving replicas."""
    base = _mem_base("k1loss")
    root = f"{base}/step-0"
    rng = np.random.default_rng(42)
    payload = rng.standard_normal(4096).astype(np.float32)
    with hottier.hot_tier(rank=0, world=4, k=2, drain="manual"):
        snap = Snapshot.take(root, {"s": StateDict(w=jnp.asarray(payload))})
        assert not _payload_objects(root)  # hot-tier-only on purpose
        hottier.kill_host(lost_host)
        target = {"s": StateDict(w=jnp.zeros((4096,), jnp.float32))}
        snap.restore(target)
        np.testing.assert_array_equal(
            np.asarray(target["s"]["w"]), payload
        )
        stats = hottier.runtime().stats_snapshot()
        assert stats["fallback_objects"] == 0  # never touched durable


def test_all_replicas_lost_falls_back_and_fires_doctor():
    """Losing ALL replica hosts after tier-down degrades to per-object
    durable reads; the restore stays bit-exact, the flight report's
    ``tier`` block names the dead peers, the ``hot-tier-degraded``
    doctor rule fires critical (100% of bytes fell back), and the
    ledger record carries the ``tier`` field."""
    base = _mem_base("alllost")
    root = f"{base}/step-0"
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        snap = Snapshot.take(root, _state(9))
        hottier.drain_now()  # durable copy exists; replicas evictable
        hottier.kill_host(0)
        hottier.kill_host(1)
        target = _target()
        snap.restore({"s": target["s"]})
        _assert_restored(target, 9)
        report = _read_json(root, ".report.restore.json")
        tier_blocks = [
            s.get("tier") for s in report["ranks"] if s and s.get("tier")
        ]
        assert tier_blocks, report["ranks"]
        assert tier_blocks[0]["fallback_objects"] >= 1
        assert tier_blocks[0]["hot_objects"] == 0
        assert sorted(tier_blocks[0]["degraded_peers"]) == [0, 1]
        findings = {f.rule: f for f in diagnose_report(report)}
        assert "hot-tier-degraded" in findings
        finding = findings["hot-tier-degraded"]
        assert finding.severity == "critical"
        assert finding.evidence["degraded_peers"] == "peer hosts 0-1"
        assert finding.evidence["reasons"].get("dead", 0) >= 1
        # Ledger: the restore record carries the tier attribution.
        records, _ = runledger.read_records(root)
        restores = [r for r in records if r["kind"] == "restore"]
        assert restores and restores[-1]["tier"]["fallback_objects"] >= 1
        assert restores[-1]["tier"]["degraded_peers"] == [0, 1]


def test_corrupt_replica_falls_back_per_object():
    """A replica that fails its fingerprint check is dropped and the
    read falls over — to the durable tier here (k=1), bit-exact."""
    base = _mem_base("corrupt")
    root = f"{base}/step-0"
    with hottier.hot_tier(rank=0, world=1, k=1, drain="manual"):
        snap = Snapshot.take(root, _state(5))
        hottier.drain_now()
        # Flip one byte of the single replica in host 0's RAM.
        with ht_tier._TIER_LOCK:
            store = ht_tier._HOSTS[0]
            key = next(iter(store.objects))
            obj = store.objects[key]
            obj.data = obj.data[:-1] + bytes([obj.data[-1] ^ 0xFF])
        target = _target()
        snap.restore({"s": target["s"]})
        _assert_restored(target, 5)
        stats = hottier.runtime().stats_snapshot()
        assert stats["reasons"].get("corrupt", 0) >= 1
        assert stats["fallback_objects"] >= 1
        # The corrupt replica was dropped — nothing can read it again.
        assert ht_tier.total_buffered_bytes() < 4096


def test_lose_host_schedule_is_deterministic():
    """faultline's host-loss schedule kills a peer at a fixed op
    boundary: the take completes, the host is dead afterwards, and the
    injection log records the hostloss."""
    base = _mem_base("sched")
    root = f"{base}/step-0"
    sched = fl.FaultSchedule().lose_host(
        1, op="write", path=".snapshot_metadata"
    )
    with fl.inject(sched) as ctl:
        with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
            snap = Snapshot.take(root, _state(4))
            assert 1 not in hottier.live_hosts()
            assert ctl.fault_counts().get("hostloss") == 1
            # Host 0's replica still serves the restore.
            target = _target()
            snap.restore({"s": target["s"]})
            _assert_restored(target, 4)


# --------------------------------------------------- capacity and eviction


def test_undrained_never_evicted_capacity_degrades_to_write_through():
    """An undrained object is the only copy outside its replica set:
    capacity pressure must refuse the put (degrading the write to a
    synchronous durable write-through), never evict undrained bytes."""
    base = _mem_base("cap")
    root = f"{base}/step-0"
    # Room for roughly one 4 KiB payload per host.
    with hottier.hot_tier(
        rank=0, world=1, k=1, capacity_bytes=6000, drain="manual"
    ):
        snap = Snapshot.take(
            root,
            {
                "a": StateDict(w=jnp.full((1024,), 1.0)),
                "b": StateDict(w=jnp.full((1024,), 2.0)),
            },
        )
        stats = hottier.runtime().stats_snapshot()
        # One payload went hot, the other was refused and wrote through.
        assert stats["write_through"] >= 1
        assert ht_tier.total_buffered_bytes() <= 6000
        # Everything still restores (mixed hot + durable).
        target = {
            "a": StateDict(w=jnp.zeros((1024,))),
            "b": StateDict(w=jnp.zeros((1024,))),
        }
        snap.restore(target)
        got = {
            float(np.asarray(target["a"]["w"])[0]),
            float(np.asarray(target["b"]["w"])[0]),
        }
        assert got == {1.0, 2.0}
        # After tier-down the buffered object is drained and EVICTABLE:
        # the next put may displace it.
        hottier.drain_now()
        rt = hottier.runtime()
        placed, _tag = rt.hot_put(root, "0/extra/blob", b"x" * 4096)
        assert placed == 1
        assert ht_tier.total_buffered_bytes() <= 6000


def test_k_env_knob(monkeypatch):
    monkeypatch.setenv(hottier.K_ENV_VAR, "3")
    with hottier.hot_tier(rank=0, world=8, drain="manual") as rt:
        assert rt.k == 3
        assert rt.replica_hosts() == [0, 1, 2]
    monkeypatch.setenv(hottier.K_ENV_VAR, "99")
    with hottier.hot_tier(rank=5, world=4, drain="manual") as rt:
        assert rt.k == 4  # clamped to world
        assert rt.replica_hosts() == [5 % 4, 2, 3, 0]


# ------------------------------------------------- delete / reconcile GC


def test_delete_cancels_pending_drain_and_drops_buffers(tmp_path):
    """Deleting a committed-but-undrained snapshot cancels its pending
    tier-down (a background drain must not resurrect deleted objects)
    and drops its replicas; the ``.tierdown`` watermark goes with a
    drained snapshot."""
    root_a = str(tmp_path / "step-0")
    root_b = str(tmp_path / "step-1")
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        snap_a = Snapshot.take(root_a, _state(1))
        assert hottier.buffered_roots()
        snap_a.delete()
        assert not hottier.buffered_roots()
        hottier.drain_now()  # nothing to resurrect
        assert not _payload_objects(root_a)
        # Drained snapshot: delete removes payloads AND the watermark.
        snap_b = Snapshot.take(root_b, _state(2))
        hottier.drain_now()
        assert ".tierdown" in _durable_objects(root_b)
        snap_b.delete()
        assert ".tierdown" not in _durable_objects(root_b)
        assert not hottier.buffered_roots()


def test_reconcile_keeps_committed_undrained_drops_aged_orphans(
    monkeypatch,
):
    """The reconcile sweep must never reclaim replicas a committed-but-
    not-yet-drained take still needs (they are the only copy of its
    payload bytes), while an uncommitted crashed take's buffers — which
    nothing can ever resolve — are reclaimed once aged."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    base = _mem_base("reconcile")
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        mgr = CheckpointManager(base)
        mgr.save(0, _state(0))  # committed, NOT drained
        committed_root = _step_dir(base, 0)
        # Fake an uncommitted crashed take: hot buffers, no metadata.
        rt = hottier.runtime()
        orphan_root = _step_dir(base, 99)
        rt.hot_put(orphan_root, "0/s/w", b"y" * 512)
        rt.enqueue_drain(orphan_root, "0/s/w")
        assert set(hottier.buffered_roots()) == {
            committed_root,
            orphan_root,
        }
        mgr.reconcile(adopt=True)
        # Orphan reclaimed (age guard disabled), committed kept.
        assert set(hottier.buffered_roots()) == {committed_root}
        # ... and the committed step still restores from the hot tier.
        target = _target()
        assert mgr.restore({"s": target["s"]}, step=0) == 0
        _assert_restored(target, 0)
        # With the age guard ON, even an uncommitted orphan is spared
        # (it may be an in-flight take).
        rt.hot_put(orphan_root, "0/s/w", b"y" * 512)
        monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "3600")
        mgr.reconcile(adopt=True)
        assert orphan_root in hottier.buffered_roots()


def test_drain_exhaustion_strands_then_redrives(monkeypatch):
    """A durable outage outlasting the drain attempts leaves the object
    STRANDED: wait_drained() must report the flush dirty (the hot copy
    is the only copy — claiming success would let a caller tear the
    tier down over it), and the next drain_now() re-drives it to a
    clean tier-down."""
    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRIES", "0")
    base = _mem_base("strand")
    root = f"{base}/step-0"
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        # nth=2: the 1st match is the take's logical write (which the
        # tier absorbs into RAM); every durable drain write after it
        # fails permanently.
        sched = fl.FaultSchedule().permanent(op="write", path="0/s/w", nth=2)
        with fl.inject(sched):
            snap = Snapshot.take(root, _state(8))
            hottier.drain_now()  # attempts exhaust; object stranded
            assert not hottier.wait_drained(timeout_s=1.0)
            assert not _payload_objects(root)
            # The snapshot is still fully restorable from the hot tier.
            target = _target()
            snap.restore({"s": target["s"]})
            _assert_restored(target, 8)
        # Outage over (faults uninstalled): re-drive to a clean flush.
        hottier.drain_now()
        assert hottier.wait_drained(timeout_s=5.0)
        assert _payload_objects(root)
        assert ".tierdown" in _durable_objects(root)


def test_tierdown_write_failure_is_redriven(monkeypatch):
    """A failed ``.tierdown`` watermark write must leave a re-drive
    trigger even though the root is fully drained (no object item will
    ever call back into the watermark path)."""
    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRIES", "0")
    base = _mem_base("tdfail")
    root = f"{base}/step-0"
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        sched = fl.FaultSchedule().permanent(op="write", path=".tierdown")
        with fl.inject(sched):
            Snapshot.take(root, _state(2))
            hottier.drain_now()
            assert _payload_objects(root)  # objects drained fine
            assert ".tierdown" not in _durable_objects(root)
            assert not hottier.wait_drained(timeout_s=1.0)
        hottier.drain_now()
        assert hottier.wait_drained(timeout_s=5.0)
        assert ".tierdown" in _durable_objects(root)


# --------------------------------------------------- crash/fault matrices


def _prepare_matrix(monkeypatch, drained_history=True):
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    hottier.reset_hot_tier()
    hottier.reset_pending()
    base = _mem_base("crashmx")
    mgr = CheckpointManager(base, max_to_keep=1)
    mgr.save(0, _state(0))
    mgr.save(1, _state(1))
    if drained_history:
        hottier.drain_now()
    return base


def _faulted_matrix(base):
    # One full tiered lifecycle: take step 2 (replicate + ack + commit +
    # marker + prune), then tier-down (drain + watermark).
    CheckpointManager(base, max_to_keep=1).save(2, _state(2))
    hottier.drain_now()


def _probe(base):
    def probe(step):
        target = _target()
        got = CheckpointManager(base).restore(target, step=step)
        assert got == step
        _assert_restored(target, step)

    return probe


def _check_matrix(base, outcome):
    # (a)/(b): every marker-visible step restores clean (hot tier or
    # durable); reconcile adopts committed-unmarked work and reclaims
    # crashed debris — including hot-tier buffers.
    res = fl.check_recovery_invariant(base, _probe(base))
    outcome.marked_steps = res.marked_steps
    outcome.adopted_steps = res.adopted_steps
    # Recovery re-drive: a fresh save→drain cycle succeeds, re-drives
    # any interrupted tier-down, and leaves no leaked objects in EITHER
    # tier.
    mgr = CheckpointManager(base, max_to_keep=1, reconcile_on_init="adopt")
    mgr.save(3, _state(3))
    hottier.drain_now()
    mgr.reconcile(adopt=True)
    assert mgr.latest_step() == 3
    _probe(base)(3)
    fl.assert_reclaimed(base, [3])
    # Zero leaked hot-tier buffers: only the live step may stay hot.
    live_root = _step_dir(base, 3)
    assert set(hottier.buffered_roots()) <= {live_root}
    # The live step finished its tier-down: watermark present.
    assert ".tierdown" in _durable_objects(live_root)


def test_tiered_crash_matrix_fast_subset(monkeypatch):
    """Stride-sampled crash points across take→ack→commit→tier-down
    with the hot tier on (tier-1). Proves restore-or-detect plus
    leak-free reconcile at every sampled boundary — including the
    hottier.replicate / hottier.drain / hottier.tierdown boundaries the
    tier adds to the op stream."""
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        base = _prepare_matrix(monkeypatch)
        total = fl.count_storage_ops(lambda: _faulted_matrix(base))
        assert total > 0
        stride = max(1, total // 6)
        points = sorted(set(range(1, total + 1, stride)) | {1, total})
        report = fl.enumerate_crash_points(
            prepare=lambda: _prepare_matrix(monkeypatch),
            faulted=_faulted_matrix,
            check=_check_matrix,
            crash_points=points,
            total_ops=total,
        )
        assert set(report.outcomes) == set(points)
        assert any(o.crashed for o in report.outcomes.values())


@pytest.mark.slow
def test_tiered_crash_matrix_full(monkeypatch):
    """Full per-op crash enumeration over the tiered pipeline."""
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        report = fl.enumerate_crash_points(
            prepare=lambda: _prepare_matrix(monkeypatch),
            faulted=_faulted_matrix,
            check=_check_matrix,
        )
        assert report.total_ops > 0
        assert any(o.crashed for o in report.outcomes.values())


@pytest.mark.slow
@pytest.mark.parametrize("lost_host", [0, 1])
def test_host_loss_x_crash_point_enumeration(monkeypatch, lost_host):
    """The product matrix: at every sampled crash point, ALSO lose one
    peer host before recovery runs — any k-1 loss composed with any
    crash must still satisfy restore-or-detect with zero leaks."""

    def check(base, outcome):
        hottier.kill_host(lost_host)
        try:
            _check_matrix(base, outcome)
        finally:
            hottier.revive_host(lost_host)

    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        base = _prepare_matrix(monkeypatch)
        total = fl.count_storage_ops(lambda: _faulted_matrix(base))
        points = sorted(
            set(range(1, total + 1, max(1, total // 12))) | {1, total}
        )
        report = fl.enumerate_crash_points(
            prepare=lambda: _prepare_matrix(monkeypatch),
            faulted=_faulted_matrix,
            check=check,
            crash_points=points,
            total_ops=total,
        )
        assert any(o.crashed for o in report.outcomes.values())


def test_mid_replication_host_loss_during_take(monkeypatch):
    """Partial-tier-down schedule: a peer dies WHILE the take is
    replicating (lose_host bound to a payload write boundary). The take
    must still commit (surviving replicas + write-through degradation)
    and restore bit-exact."""
    base = _mem_base("midloss")
    root = f"{base}/step-0"
    sched = fl.FaultSchedule().lose_host(1, op="hottier.replicate", nth=2)
    with fl.inject(sched):
        with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
            snap = Snapshot.take(root, _state(6))
            target = _target()
            snap.restore({"s": target["s"]})
            _assert_restored(target, 6)
            hottier.drain_now()  # tier-down proceeds from survivors
            assert _payload_objects(root)
            assert ".tierdown" in _durable_objects(root)


# ----------------------------- degraded ack / delete-drain / stale-drain


def test_underreplicated_put_uses_spare_host():
    """A dead ring host must not silently halve the replication factor:
    placement continues to spare hosts outside the ring, so the take
    still acks at k RAM replicas without touching the durable tier."""
    base = _mem_base("spare")
    root = f"{base}/step-0"
    with hottier.hot_tier(rank=0, world=4, k=2, drain="manual"):
        hottier.kill_host(1)  # rank 0's ring is hosts {0, 1}
        snap = Snapshot.take(root, _state(11))
        assert not _payload_objects(root)  # ack'd from RAM alone
        stats = hottier.runtime().stats_snapshot()
        assert stats["write_through"] == 0
        assert stats["degraded_puts"] == 0
        # The k-1-loss invariant holds over the SUBSTITUTED replica
        # set: losing host 0 leaves the spare (host 2) serving reads.
        hottier.kill_host(0)
        target = _target()
        snap.restore({"s": target["s"]})
        _assert_restored(target, 11)
        assert hottier.runtime().stats_snapshot()["fallback_objects"] == 0


def test_underreplicated_put_writes_through_before_ack():
    """When k replicas cannot be placed anywhere (world=2, k=2, one
    host dead: no spares exist), the write must degrade to a
    synchronous durable write-through BEFORE the ack — an acked object
    never rests on a lone RAM copy, so losing the one surviving host
    afterwards cannot lose committed bytes."""
    base = _mem_base("degack")
    root = f"{base}/step-0"
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        hottier.kill_host(1)
        snap = Snapshot.take(root, _state(13))
        # Durable BEFORE any drain ran: the ack did not rely on RAM.
        assert _payload_objects(root)
        stats = hottier.runtime().stats_snapshot()
        assert stats["degraded_puts"] >= 1
        assert stats["write_through"] >= 1
        # Now lose the single surviving replica host too.
        hottier.kill_host(0)
        target = _target()
        snap.restore({"s": target["s"]})
        _assert_restored(target, 13)


def test_inflight_drain_cannot_resurrect_deleted_snapshot(tmp_path):
    """The delete/drain race, in-flight edition: an item already popped
    off the drain queue (the drainer holding the object bytes) when
    ``delete`` runs must not complete its durable write after the
    sweep — the drain re-checks the forgotten root around the write and
    skips (or undoes) it."""
    root = str(tmp_path / "step-0")
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        snap = Snapshot.take(root, _state(4))
        rt = hottier.runtime()
        with rt._cond:  # pop as the background drainer would
            item = rt._queue.popleft()
        snap.delete()  # cancels drains FIRST, then durable deletes
        rt._drain_item(*item)  # the "in-flight" drain now completes
        assert not _payload_objects(root)
        assert ".tierdown" not in _durable_objects(root)
        assert rt.stats_snapshot()["drain_lost"] == 0
        assert not hottier.buffered_roots()


def test_delete_waits_for_inflight_drain(tmp_path):
    """delete must not overtake a drain whose durable write is already
    in flight: forget_root condition-waits on the in-flight item, so
    the durable deletes run strictly after the write lands — and sweep
    it — leaving nothing resurrected."""
    root = str(tmp_path / "step-0")
    # nth=2: the take's logical write is match 1 (absorbed into RAM);
    # the drain's durable write is match 2 and gets the latency.
    sched = fl.FaultSchedule().latency(
        op="write", path="0/s/w", seconds=0.6, nth=2
    )
    with fl.inject(sched):
        with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
            snap = Snapshot.take(root, _state(6))
            rt = hottier.runtime()
            drainer = threading.Thread(target=rt.drain_now)
            drainer.start()
            deadline = time.monotonic() + 5.0
            while not rt._inflight_items and time.monotonic() < deadline:
                time.sleep(0.01)
            assert rt._inflight_items  # the slowed write is in flight
            snap.delete()  # must wait the write out, then remove it
            drainer.join(timeout=10.0)
            assert not drainer.is_alive()
            assert not _payload_objects(root)
            assert not hottier.buffered_roots()


def test_rewrite_while_drain_queued_drains_latest_bytes():
    """Re-writing an object whose drain is still QUEUED replaces the
    queued item (same path, new tag): the drain persists the newest
    bytes, and the durable tier never holds stale data after flush."""
    base = _mem_base("requeue")
    root = f"{base}/step-0"
    old, new = b"A" * 256, b"B" * 256
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        rt = hottier.runtime()
        rt.hot_put(root, "0/s/w", old)
        rt.enqueue_drain(root, "0/s/w")
        rt.hot_put(root, "0/s/w", new)
        rt.enqueue_drain(root, "0/s/w")
        with rt._cond:
            items = [i for i in rt._queue if i[1] == "0/s/w"]
        assert len(items) == 1  # superseded item replaced, not doubled
        hottier.drain_now()
        assert hottier.wait_drained(timeout_s=5.0)
    hottier.reset_hot_tier()
    assert _read_bytes(root, "0/s/w") == new


def test_rewrite_while_drain_inflight_is_not_resurrected_stale():
    """An IN-FLIGHT drain of superseded bytes must neither clear the
    newer write's pending entry nor mark the newer replicas evictable
    (they are the only copy of the newest bytes); the newer item then
    drains the bytes the durable tier must end up with."""
    base = _mem_base("staledrain")
    root = f"{base}/step-0"
    old, new = b"A" * 256, b"B" * 256
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        rt = hottier.runtime()
        rt.hot_put(root, "0/s/w", old)
        rt.enqueue_drain(root, "0/s/w")
        with rt._cond:  # pop as the background drainer would
            item = rt._queue.popleft()
        rt.hot_put(root, "0/s/w", new)
        rt.enqueue_drain(root, "0/s/w")
        rt._drain_item(*item)  # the stale in-flight drain completes
        state = rt.root_state(root)
        assert state.pending == {"0/s/w"}
        key = f"{root}/0/s/w"
        for host in ht_tier.replica_hosts_for(key):
            assert not ht_tier.get_replica(key, host).drained
        hottier.drain_now()
        assert not rt.root_state(root).pending
    hottier.reset_hot_tier()
    assert _read_bytes(root, "0/s/w") == new


def test_degraded_rewrite_cancels_stale_drain_and_keeps_latest():
    """A degraded re-write (write-through) of a path whose drain is
    still queued quiesces the drain pipeline FIRST: the stale item is
    removed before the durable write, so it can never overwrite the
    write-through's bytes, and the flush converges on the latest."""
    import asyncio

    base = _mem_base("degrewrite")
    root = f"{base}/step-0"
    old, new = b"A" * 128, b"B" * 128
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        storage = url_to_storage_plugin(root)
        try:
            asyncio.run(storage.write(IOReq(path="0/s/w", data=old)))
            hottier.kill_host(1)  # the re-write cannot reach k replicas
            asyncio.run(storage.write(IOReq(path="0/s/w", data=new)))
        finally:
            storage.close()
        rt = hottier.runtime()
        with rt._cond:
            assert not [i for i in rt._queue if i[1] == "0/s/w"]
        assert rt.stats_snapshot()["degraded_puts"] == 1
        # The surviving replica holds the new bytes and is evictable.
        key = f"{root}/0/s/w"
        obj = ht_tier.get_replica(key, 0)
        assert obj.data == new and obj.drained
        hottier.drain_now()
    hottier.reset_hot_tier()
    assert _read_bytes(root, "0/s/w") == new


def test_rewrite_drops_stale_replicas_outside_new_placement():
    """When the replica set changes between writes (spare substitution,
    then the ring peer comes back), replicas of the superseded bytes on
    hosts the new placement did not revisit are dropped — they would
    otherwise serve stale reads and pin RAM undrained forever."""
    base = _mem_base("stalepin")
    root = f"{base}/step-0"
    with hottier.hot_tier(rank=0, world=3, k=2, drain="manual"):
        rt = hottier.runtime()
        hottier.kill_host(1)
        placed, _tag = rt.hot_put(root, "0/s/w", b"A" * 64)
        key = f"{root}/0/s/w"
        assert placed == 2
        assert sorted(ht_tier.replica_hosts_for(key)) == [0, 2]
        hottier.revive_host(1)
        placed, _tag = rt.hot_put(root, "0/s/w", b"B" * 64)
        assert placed == 2  # back on the ring: hosts 0 and 1
        hosts = sorted(ht_tier.replica_hosts_for(key))
        assert hosts == [0, 1]  # host 2's stale replica dropped
        for host in hosts:
            assert ht_tier.get_replica(key, host).data == b"B" * 64


def test_write_through_after_commit_still_records_watermark():
    """A write-through that retires the root's LAST pending object
    after commit must still get the ``.tierdown`` watermark recorded —
    no drain item will ever visit the watermark path otherwise."""
    import asyncio

    base = _mem_base("wtwm")
    root = f"{base}/step-0"
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        storage = url_to_storage_plugin(root)
        try:
            asyncio.run(storage.write(IOReq(path="0/s/w", data=b"A" * 64)))
            # Commit with the object still pending: no watermark item.
            asyncio.run(
                storage.write(IOReq(path=".snapshot_metadata", data=b"{}"))
            )
            hottier.kill_host(1)
            asyncio.run(storage.write(IOReq(path="0/s/w", data=b"B" * 64)))
        finally:
            storage.close()
        hottier.drain_now()
        assert ".tierdown" in _durable_objects(root)
        assert hottier.wait_drained(timeout_s=5.0)
    hottier.reset_hot_tier()
    assert _read_bytes(root, "0/s/w") == b"B" * 64


def test_failed_write_through_rearms_drain(monkeypatch):
    """A degraded write-through whose durable write FAILS must not
    silently retire the durability obligation: the drain is re-armed
    for the placed replicas (which stay unevictable — the only copy)
    and the next drain_now lands the bytes durably."""
    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRIES", "0")
    base = _mem_base("wtfail")
    root = f"{base}/step-0"
    # The first matched write is the take's degraded write-through (it
    # fails → the take fails); the drain's first durable re-drive write
    # fails too, the next succeeds.
    sched = fl.FaultSchedule().transient(op="write", path="0/s/w", times=2)
    with fl.inject(sched):
        with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
            hottier.kill_host(1)  # every payload put degrades
            with pytest.raises(Exception):
                Snapshot.take(root, _state(3))
            rt = hottier.runtime()
            # Failed write-through: the obligation survives — newest
            # bytes still pending, the sole replica unevictable.
            assert rt.root_state(root).pending == {"0/s/w"}
            key = f"{root}/0/s/w"
            assert not ht_tier.get_replica(key, 0).drained
            # The re-armed drain re-drives the bytes to durable.
            hottier.drain_now()
            assert hottier.wait_drained(timeout_s=5.0)
            assert _payload_objects(root)
            assert ht_tier.get_replica(key, 0).drained


def test_recreated_root_after_delete_gets_watermark():
    """Deleting a snapshot must not latch its root 'forgotten' forever:
    a snapshot later re-created at the same root — even one whose
    payload writes all degrade to write-through (so enqueue_drain never
    runs) — still gets its ``.tierdown`` watermark and keeps it."""
    base = _mem_base("recreate")
    root = f"{base}/step-0"
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        snap = Snapshot.take(root, _state(1))
        snap.delete()
        hottier.kill_host(1)  # the re-take degrades to write-through
        Snapshot.take(root, _state(2))
        hottier.drain_now()
        assert ".tierdown" in _durable_objects(root)
        target = _target()
        Snapshot(root).restore({"s": target["s"]})
        _assert_restored(target, 2)


def test_drain_executors_serialize_per_path():
    """Two drain executors (background loop + a drain_now re-drive)
    must never drain the same path concurrently: a queued item whose
    path has an in-flight drain is deferred until it finishes — the
    tag ordering between their durable writes would otherwise be lost,
    leaving superseded bytes durable."""
    base = _mem_base("serial")
    root = f"{base}/step-0"
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        rt = hottier.runtime()
        rt.hot_put(root, "0/s/w", b"A" * 64)
        rt.enqueue_drain(root, "0/s/w")
        with rt._cond:  # executor 1 takes the item mid-write
            item = rt._pop_runnable_locked()
            assert item is not None
            rt._inflight_begin_locked(item[0], item[1])
        rt.hot_put(root, "0/s/w", b"B" * 64)
        rt.enqueue_drain(root, "0/s/w")
        with rt._cond:
            # Executor 2 must NOT get the newer item for the same path.
            assert rt._pop_runnable_locked() is None
            rt._inflight_end_locked(item[0], item[1])
            assert rt._pop_runnable_locked() is not None


def test_replica_replacement_mid_drain_is_not_counted_lost():
    """hot_put replacing a path's replicas between a drain item's pop
    and its probe (the foreground re-write window, before enqueue_drain
    updates the bookkeeping) must not be misread as 'every replica
    lost': the item is re-driven instead, and the root converges to a
    clean ``.tierdown`` once the re-write's bookkeeping lands."""
    base = _mem_base("midswap")
    root = f"{base}/step-0"
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        rt = hottier.runtime()
        rt.hot_put(root, "0/s/w", b"A" * 64)
        rt.enqueue_drain(root, "0/s/w")
        with rt._cond:  # the background drainer holds the A item...
            item = rt._pop_runnable_locked()
        rt.hot_put(root, "0/s/w", b"B" * 64)  # ...as the re-write lands
        rt._drain_item(*item)  # probe finds no tag-A replica
        assert rt.stats_snapshot()["drain_lost"] == 0
        rt.enqueue_drain(root, "0/s/w")  # re-write's bookkeeping lands
        rt.on_commit(root)
        hottier.drain_now()
        assert hottier.wait_drained(timeout_s=5.0)
        assert ".tierdown" in _durable_objects(root)
    hottier.reset_hot_tier()
    assert _read_bytes(root, "0/s/w") == b"B" * 64


def test_genuine_replica_loss_still_detected():
    """All replicas actually dying pre-drain is still detected once the
    re-drive budget is spent: the loss is counted and the root can
    never tier down clean (truthful accounting)."""
    base = _mem_base("loss")
    root = f"{base}/step-0"
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        rt = hottier.runtime()
        rt.hot_put(root, "0/s/w", b"A" * 64)
        rt.enqueue_drain(root, "0/s/w")
        hottier.kill_host(0)
        hottier.kill_host(1)
        hottier.drain_now()
        assert rt.stats_snapshot()["drain_lost"] == 1
        rt.on_commit(root)
        hottier.drain_now()
        assert ".tierdown" not in _durable_objects(root)


def test_zero_capacity_forces_pure_write_through():
    """``capacity_bytes=0`` (TPUSNAPSHOT_HOT_TIER_BYTES=0) must refuse
    EVERY put — including the first per host — so nothing is ever
    buffered in RAM the operator sized to zero."""
    base = _mem_base("cap0")
    root = f"{base}/step-0"
    with hottier.hot_tier(
        rank=0, world=2, k=2, capacity_bytes=0, drain="manual"
    ):
        Snapshot.take(root, _state(5))
        assert ht_tier.total_buffered_bytes() == 0
        assert _payload_objects(root)  # everything wrote through
        assert hottier.runtime().stats_snapshot()["write_through"] >= 1


def test_disable_hot_tier_uninstalls_even_if_flush_crashes():
    """A SimulatedCrash striking the flush inside disable_hot_tier must
    not leak the wrap hook / runtime global: the tier must come down
    (and be re-enableable) regardless."""
    base = _mem_base("disablecrash")
    root = f"{base}/step-0"
    sched = fl.FaultSchedule().crash_on(op="hottier.drain")
    with fl.inject(sched):  # inject OUTER: enable/disable stay LIFO
        hottier.enable_hot_tier(rank=0, world=2, k=2, drain="manual")
        Snapshot.take(root, _state(1))
        with pytest.raises(fl.SimulatedCrash):
            hottier.disable_hot_tier(flush=True)
        assert hottier.runtime() is None  # uninstalled despite the crash
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        pass  # re-enable works


def test_same_tag_degraded_rewrite_requeues_drain():
    """Re-writing the SAME bytes while degraded must not let the
    enqueue dedupe drop the drain obligation after begin_write_through
    canceled the queued item: an obligation with no queued/in-flight
    owner would never tier down while wait_drained reports clean."""
    base = _mem_base("sametag")
    root = f"{base}/step-0"
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        rt = hottier.runtime()
        placed, tag = rt.hot_put(root, "0/s/w", b"A" * 64)
        rt.enqueue_drain(root, "0/s/w", tag)
        # Degraded re-write of identical bytes: the quiesce cancels the
        # queued item...
        rt.begin_write_through(root, "0/s/w")
        with rt._cond:
            assert not rt._queue
        # ...the durable write fails, and abort must RE-ARM the drain
        # (the same-tag dedupe must not swallow it).
        rt.abort_write_through(root, "0/s/w", tag, placed)
        with rt._cond:
            assert [i for i in rt._queue if i[1] == "0/s/w"]
        hottier.drain_now()
        assert hottier.wait_drained(timeout_s=5.0)
        assert _payload_objects(root)


def test_drain_now_waits_for_other_executors_inflight():
    """drain_now (the force-flush) must not return while another
    executor still holds the last item in flight — the caller would
    tear the tier down believing the bytes are durable."""
    base = _mem_base("flushwait")
    root = f"{base}/step-0"
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        rt = hottier.runtime()
        rt.hot_put(root, "0/s/w", b"A" * 64)
        rt.enqueue_drain(root, "0/s/w")
        with rt._cond:  # another executor holds the only item
            item = rt._pop_runnable_locked()
            rt._inflight_begin_locked(item[0], item[1])
        done = []
        flusher = threading.Thread(
            target=lambda: (rt.drain_now(), done.append(True))
        )
        flusher.start()
        time.sleep(0.3)
        assert not done  # still waiting on the in-flight item
        rt._drain_item(*item)
        with rt._cond:
            rt._inflight_end_locked(item[0], item[1])
        flusher.join(timeout=5.0)
        assert done


def test_wait_drained_sees_inflight_write_through():
    """wait_drained must not report a clean flush while a degraded
    write-through is mid-flight on the foreground: it owns no queue
    item (begin_write_through canceled it), but the pending entry it
    deliberately leaves alive keeps the flush dirty until
    note/abort_write_through resolves it."""
    base = _mem_base("wtwait")
    root = f"{base}/step-0"
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        rt = hottier.runtime()
        placed, tag = rt.hot_put(root, "0/s/w", b"A" * 64)
        rt.enqueue_drain(root, "0/s/w", tag)
        rt.begin_write_through(root, "0/s/w")  # write-through "in flight"
        assert not hottier.wait_drained(timeout_s=0.3)
        rt.note_write_through(root, "0/s/w", tag, placed)
        assert hottier.wait_drained(timeout_s=5.0)


def test_tierdown_watermark_counts_are_per_root(tmp_path):
    """Each root's ``.tierdown`` records THAT root's drained-object
    count (and its process scope), not the process-cumulative stats
    counter."""
    root_a = str(tmp_path / "step-0")
    root_b = str(tmp_path / "step-1")
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        Snapshot.take(root_a, _state(1))
        Snapshot.take(root_b, _state(2))
        hottier.drain_now()
        for root in (root_a, root_b):
            watermark = _read_json(root, ".tierdown")
            assert watermark["drained_objects"] == 1
            assert watermark["scope"] == "process"
