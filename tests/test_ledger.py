"""snapledger: durable cross-take telemetry ledger (ISSUE 5).

Covers the line codec + torn-tail parser, ledger-root resolution, the
take/restore append wiring on both commit routes, delete/reconcile
durability (records outlive snapshots; sweeps never reclaim them), the
faultline crash/torn-append matrix, and the end-to-end acceptance
criterion: >=5 real takes + 1 restore reproduce per-step trends from
the ledger alone, and an injected slowdown on the last take trips the
regression sentinel naming the metric and step.
"""

import json
import os
import time
import uuid

import numpy as np
import pytest

from torchsnapshot_tpu import CheckpointManager, Snapshot, telemetry
from torchsnapshot_tpu import faultline as fl
from torchsnapshot_tpu.storage_plugin import _MEMORY_STORES
from torchsnapshot_tpu.telemetry import goodput, ledger, timeline
from torchsnapshot_tpu.utils.test_utils import run_thread_ranks

pytestmark = []


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    goodput.reset()
    yield
    telemetry.reset()
    goodput.reset()


class _Model:
    def __init__(self, params):
        self.params = params

    def state_dict(self):
        return self.params

    def load_state_dict(self, sd):
        self.params = sd


def _state(step: int, n: int = 4096):
    return {"m": _Model({"w": np.full(n, float(step), np.float32)})}


# ----------------------------------------------------------- line codec


def test_line_codec_roundtrip():
    record = {"format_version": 1, "kind": "take", "step": 3, "bytes": 17}
    line = ledger.encode_line(record)
    assert ledger.decode_line(line) == record


def test_decode_rejects_corruption():
    record = {"kind": "take", "step": 1}
    line = ledger.encode_line(record)
    assert ledger.decode_line(line.replace('"step":1', '"step":2')) is None
    assert ledger.decode_line("not json") is None
    assert ledger.decode_line('{"no": "crc"}') is None


def test_parser_skips_torn_tail():
    good = [
        ledger.encode_line({"kind": "take", "step": i}) + "\n"
        for i in range(3)
    ]
    intact = "".join(good).encode()
    # Tear mid-way through the last line (a torn append).
    torn = intact[: len(intact) - 10]
    records, valid_len, skipped = ledger.parse_ledger_bytes(torn)
    assert [r["step"] for r in records] == [0, 1]
    assert skipped == 1
    assert valid_len == len((good[0] + good[1]).encode())
    # An intact file parses fully with its whole length valid.
    records, valid_len, skipped = ledger.parse_ledger_bytes(intact)
    assert len(records) == 3 and skipped == 0
    assert valid_len == len(intact)


def test_parser_skips_checksum_mismatch_line():
    lines = [
        ledger.encode_line({"kind": "take", "step": 0}),
        ledger.encode_line({"kind": "take", "step": 1}).replace(
            '"step":1', '"step":9'
        ),
        ledger.encode_line({"kind": "take", "step": 2}),
    ]
    raw = ("\n".join(lines) + "\n").encode()
    records, valid_len, skipped = ledger.parse_ledger_bytes(raw)
    # The corrupt middle line is skipped; the later record is still
    # READ (visible to timeline) but the rewrite prefix stops before
    # the corruption.
    assert [r["step"] for r in records] == [0, 2]
    assert skipped == 1
    assert valid_len == len((lines[0] + "\n").encode())


def test_ledger_root_for():
    assert ledger.ledger_root_for("/a/b/run/step-12") == ("/a/b/run", 12)
    assert ledger.ledger_root_for("/a/b/snap") == ("/a/b/snap", None)
    assert ledger.ledger_root_for("memory://bkt/run/step-3") == (
        "memory://bkt/run",
        3,
    )
    assert ledger.ledger_root_for("memory://bkt/snap") == (
        "memory://bkt/snap",
        None,
    )
    # step-like leaf with no parent directory stays its own root
    assert ledger.ledger_root_for("/step-5")[1] is None or True


# ----------------------------------------------------- take/restore wiring


def test_bare_take_and_restore_append_records(tmp_path):
    path = str(tmp_path / "snap")
    snap = Snapshot.take(path, _state(1))
    snap.restore(_state(0))
    records, skipped = ledger.read_records(path)
    assert skipped == 0
    assert [r["kind"] for r in records] == ["take", "restore"]
    take = records[0]
    assert take["format_version"] == ledger.LEDGER_FORMAT_VERSION
    assert take["step"] is None
    assert take["take_id"]
    assert take["world_size"] == 1
    assert take["bytes"] == 4096 * 4
    assert take["wall_s"] > 0 and take["gbps"] > 0
    assert take["churn"]["basis"] == "full"
    assert take["churn"]["added_bytes"] == take["bytes"]
    assert "capture_s" in take["phases"]
    restore = records[1]
    assert restore["bytes"] == 4096 * 4
    assert "consume_s" in restore["phases"]


def test_manager_steps_share_one_ledger(tmp_path):
    base = str(tmp_path / "run")
    mgr = CheckpointManager(base, incremental=True)
    params = {
        "w": np.arange(2048, dtype=np.float32),
        "frozen": np.ones(2048, np.float32),
    }
    for step in range(3):
        params = dict(params, w=params["w"] + 1)
        mgr.save(step, {"m": _Model(params)})
    records, _ = ledger.read_records(base)
    assert [r["step"] for r in records] == [0, 1, 2]
    assert os.path.exists(os.path.join(base, ledger.LEDGER_OBJECT))
    # Incremental churn: the frozen param dedups from step 1 on.
    assert records[0]["churn"]["basis"] == "full"
    for r in records[1:]:
        assert r["churn"]["basis"] == "incremental"
        assert r["churn"]["unchanged_bytes"] == 2048 * 4
        assert r["churn"]["efficiency"] == pytest.approx(0.5)


def test_storage_commit_route_appends(monkeypatch):
    """The large-manifest marker route (also the async drain's route)
    appends the digest from rank 0's event loop."""
    monkeypatch.setenv("TPUSNAPSHOT_COMMIT_VIA_STORAGE_BYTES", "1")
    bucket = f"ledgerrt-{uuid.uuid4().hex[:8]}"
    _MEMORY_STORES.pop(bucket, None)
    url = f"memory://{bucket}/snap"

    def fn(coord, rank):
        model = _Model({"w": np.full(1024, float(rank), np.float32)})
        return Snapshot.take(url, {"model": model}, coord=coord)

    run_thread_ranks(2, fn)
    records, skipped = ledger.read_records(url)
    assert skipped == 0
    (record,) = records
    assert record["kind"] == "take"
    assert record["world_size"] == 2
    assert record["bytes"] == 2 * 1024 * 4


def test_async_take_appends(tmp_path):
    path = str(tmp_path / "snap")
    pending = Snapshot.async_take(path, _state(1))
    pending.wait()
    records, _ = ledger.read_records(path)
    assert [r["kind"] for r in records] == ["async_take"]
    assert "prestage_s" in records[0]["phases"]


def test_goodput_lands_in_ledger_and_report(tmp_path):
    path = str(tmp_path / "snap")
    goodput.step()
    time.sleep(0.05)
    goodput.step()
    Snapshot.take(path, _state(1))
    with open(tmp_path / "snap" / ".report.json") as f:
        report = json.load(f)
    gp = report["ranks"][0]["goodput"]
    assert gp["train_s"] > 0
    assert gp["by_mode"].get("sync_take", 0) > 0
    assert 0 < gp["goodput_fraction"] < 1
    records, _ = ledger.read_records(path)
    assert records[0]["goodput"]["goodput_fraction"] == pytest.approx(
        gp["goodput_fraction"], abs=0.2
    )


def test_rotation_bounds_active_object_and_keeps_history(
    tmp_path, monkeypatch
):
    """Past the rotate cap the active object archives into an immutable
    segment (per-append IO stays bounded); read_records folds archives
    + active back into the full history."""
    monkeypatch.setenv("TPUSNAPSHOT_LEDGER_ROTATE_BYTES", "600")
    root = str(tmp_path / "run")
    os.makedirs(root)
    for i in range(12):
        ledger.append_for_snapshot(root, {"kind": "take", "seq": i})
    active = os.path.getsize(os.path.join(root, ledger.LEDGER_OBJECT))
    assert active < 600 + 200  # bounded: at most cap + one record
    archives = [
        f
        for f in os.listdir(os.path.join(root, ledger.LEDGER_DIR))
        if f.startswith("ledger-archive-")
    ]
    assert archives
    records, skipped = ledger.read_records(root)
    assert skipped == 0
    assert [r["seq"] for r in records] == list(range(12))


def test_goodput_window_resensitizes_late_run_creep(tmp_path):
    """The ledger stamps the goodput delta since the previous record:
    a cumulative fraction flattens over a long run, but the windowed
    one exposes overhead jumping late (and the sentinel sees it)."""
    root = str(tmp_path / "run")
    os.makedirs(root)
    train, ckpt = 0.0, 0.0
    for i in range(12):
        # 2% overhead for 10 windows, then 40%: cumulative moves only
        # ~0.98 -> ~0.93, the window drops to 0.6.
        d_ckpt = 0.2 if i < 10 else 4.0
        train, ckpt = train + 10.0, ckpt + d_ckpt
        total = train + ckpt
        ledger.append_for_snapshot(
            root,
            {
                "kind": "take",
                "step": i,
                "wall_s": 0.1,
                "gbps": 1.0,
                "goodput": {
                    "train_s": round(train, 3),
                    "checkpoint_s": round(ckpt, 3),
                    "goodput_fraction": round(train / total, 6),
                    "checkpoint_overhead_pct": round(
                        100 * ckpt / total, 3
                    ),
                },
            },
        )
    records, _ = ledger.read_records(root)
    assert records[5]["goodput"]["window_fraction"] == pytest.approx(
        10.0 / 10.2, abs=1e-4
    )
    assert records[11]["goodput"]["window_fraction"] == pytest.approx(
        10.0 / 14.0, abs=1e-4
    )
    # Cumulative stays above 0.9 — it would never trip the sentinel.
    assert records[11]["goodput"]["goodput_fraction"] > 0.9
    findings = timeline.analyze_ledger(records)["regressions"]
    assert any(f["field"] == "goodput.window_fraction" for f in findings)
    assert any(f["label"] == "step 10" for f in findings)


def test_full_take_efficiency_is_missing_data_not_regression(tmp_path):
    """A deliberate periodic full take (full_period) records churn
    basis=full with efficiency 0 — the sentinel must treat it as
    missing data, not a dedup regression."""
    records = [
        {
            "kind": "take",
            "step": i,
            "wall_s": 0.1,
            "gbps": 1.0,
            "churn": {"efficiency": 0.9, "basis": "incremental"},
        }
        for i in range(8)
    ]
    records.append(
        {
            "kind": "take",
            "step": 8,
            "wall_s": 0.1,
            "gbps": 1.0,
            "churn": {"efficiency": 0.0, "basis": "full"},
        }
    )
    findings = timeline.analyze_ledger(records)["regressions"]
    assert not [f for f in findings if f["field"] == "churn.efficiency"]


def test_concurrent_appends_lose_nothing(tmp_path):
    """The drain thread and the foreground race the same ledger object;
    the append lock makes read-modify-write atomic per record."""
    import threading

    root = str(tmp_path / "run")
    os.makedirs(root)
    n = 8

    def appender(i):
        ledger.append_for_snapshot(root, {"kind": "take", "seq": i})

    threads = [
        threading.Thread(target=appender, args=(i,)) for i in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    records, skipped = ledger.read_records(root)
    assert skipped == 0
    assert sorted(r["seq"] for r in records) == list(range(n))


def test_removed_replicated_leaf_counts_once(tmp_path):
    """A replicated leaf is mirrored under every rank's prefix in the
    base manifest; dropping it between takes must count its bytes ONCE
    in the ledger's churn, not world_size times."""
    url1 = str(tmp_path / "s1")
    url2 = str(tmp_path / "s2")
    shared = np.arange(2048, dtype=np.float32)

    def fn(coord, rank):
        own = {"w": np.full(1024, float(rank), np.float32), "r": shared}
        s1 = Snapshot.take(
            url1, {"m": _Model(own)}, coord=coord, replicated=["m/r"],
            fingerprint=True,
        )
        # Next take drops the replicated leaf entirely.
        Snapshot.take(
            url2,
            {"m": _Model({"w": own["w"] + 1})},
            coord=coord,
            base=s1,
        )

    run_thread_ranks(2, fn)
    records, _ = ledger.read_records(url2)
    (record,) = records
    assert record["churn"]["removed_bytes"] == shared.nbytes


# ------------------------------------------------- durability / lifecycle


def test_delete_removes_bare_snapshot_ledger(tmp_path, monkeypatch):
    """A bare snapshot's ledger is its own: delete leaves no orphaned
    .telemetry/ stub. (The manager-base ledger is outside every step
    prefix, so step deletes can never reach it — covered below.)"""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    path = str(tmp_path / "snap")
    snap = Snapshot.take(path, _state(1))
    ledger_file = os.path.join(path, ledger.LEDGER_OBJECT)
    assert os.path.exists(ledger_file)
    snap.delete(sweep=True)
    leftovers = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(path)
        for f in fs
    ]
    assert leftovers == []


def test_step_delete_cannot_touch_manager_ledger(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    base = str(tmp_path / "run")
    mgr = CheckpointManager(base, max_to_keep=5)
    for step in range(2):
        mgr.save(step, _state(step))
    Snapshot(os.path.join(base, "step-0")).delete(sweep=True, force=True)
    records, skipped = ledger.read_records(base)
    assert skipped == 0
    assert [r["step"] for r in records] == [0, 1]


def test_reconcile_never_reclaims_ledger_records(tmp_path, monkeypatch):
    """Acceptance (satellite): reconcile treats the ledger as durable
    metadata — committed takes' records survive both adopt and sweep
    reconciles, while torn .tmp debris under .telemetry/ is cleaned."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    base = str(tmp_path / "run")
    mgr = CheckpointManager(base, max_to_keep=5)
    for step in range(3):
        mgr.save(step, _state(step))
    before, _ = ledger.read_records(base)
    assert len(before) == 3
    # Torn append debris a crashed writer could leave behind.
    debris = os.path.join(base, ledger.LEDGER_DIR, "ledger.jsonl.tmp999")
    with open(debris, "w") as f:
        f.write("torn")
    mgr.reconcile(adopt=True)
    mgr.reconcile(adopt=False)
    after, skipped = ledger.read_records(base)
    assert [r["step"] for r in after] == [r["step"] for r in before]
    assert skipped == 0
    assert not os.path.exists(debris)


def test_prune_keeps_pruned_steps_records(tmp_path, monkeypatch):
    """Retention reclaims a step's payloads; its ledger record is the
    surviving history of that take."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    base = str(tmp_path / "run")
    mgr = CheckpointManager(base, max_to_keep=1)
    for step in range(3):
        mgr.save(step, _state(step))
    assert mgr.all_steps() == [2]
    records, _ = ledger.read_records(base)
    assert [r["step"] for r in records] == [0, 1, 2]


# ------------------------------------------------------ faultline matrix


def test_crash_mid_append_never_corrupts_prior_records(
    tmp_path, monkeypatch
):
    """A crash during the ledger append loses at most the new record;
    prior records stay readable and the manager recovers (the take
    itself committed — reconcile adopts it)."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    base = str(tmp_path / "run")
    mgr = CheckpointManager(base, max_to_keep=5)
    mgr.save(0, _state(0))
    sched = fl.FaultSchedule().crash_on(
        op="write", path=f"{ledger.LEDGER_DIR}/*"
    )
    with fl.inject(sched):
        with pytest.raises(fl.SimulatedCrash):
            CheckpointManager(base, max_to_keep=5).save(1, _state(1))
    records, skipped = ledger.read_records(base)
    assert [r["step"] for r in records] == [0]
    assert skipped == 0
    # The take committed before the append crashed: recovery adopts it.
    mgr2 = CheckpointManager(base, max_to_keep=5)
    assert mgr2.reconcile(adopt=True) == [1]
    target = _state(0)
    assert mgr2.restore(target, step=1) == 1
    np.testing.assert_array_equal(
        np.asarray(target["m"].params["w"]), 1.0
    )
    # The next commit appends cleanly after the crash. (The restore
    # above appended its own step-1 record — the take's record for
    # step 1 stays lost, which is the documented lose-at-most-one.)
    mgr2.save(2, _state(2))
    records, skipped = ledger.read_records(base)
    takes = [r for r in records if r["kind"] == "take"]
    assert [r["step"] for r in takes] == [0, 2]
    assert skipped == 0


def test_torn_append_skipped_and_repaired_on_next_commit(
    tmp_path, monkeypatch
):
    """A torn ledger write (truncated object + crash) leaves prior
    records intact; the parser skips the torn tail and the next commit
    re-appends over it, repairing the file."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    base = str(tmp_path / "run")
    mgr = CheckpointManager(base, max_to_keep=5)
    mgr.save(0, _state(0))
    raw_before = open(
        os.path.join(base, ledger.LEDGER_OBJECT), "rb"
    ).read()
    # Truncate the NEXT append mid-way through the new line: keep the
    # whole prior content plus 10 bytes of the new record.
    sched = fl.FaultSchedule().torn_write(
        path=f"{ledger.LEDGER_DIR}/*",
        keep_bytes=len(raw_before) + 10,
        then="crash",
    )
    with fl.inject(sched):
        with pytest.raises(fl.SimulatedCrash):
            CheckpointManager(base, max_to_keep=5).save(1, _state(1))
    raw_torn = open(os.path.join(base, ledger.LEDGER_OBJECT), "rb").read()
    assert raw_torn[: len(raw_before)] == raw_before  # prior intact
    assert len(raw_torn) == len(raw_before) + 10  # tail torn
    records, skipped = ledger.read_records(base)
    assert [r["step"] for r in records] == [0]
    assert skipped == 1
    # Next commit: the torn tail is dropped, the new record appended.
    CheckpointManager(base, max_to_keep=5).save(2, _state(2))
    records, skipped = ledger.read_records(base)
    assert [r["step"] for r in records] == [0, 2]
    assert skipped == 0
    raw_repaired = open(
        os.path.join(base, ledger.LEDGER_OBJECT), "rb"
    ).read()
    assert raw_repaired[: len(raw_before)] == raw_before


@pytest.mark.faultline
def test_ledger_append_failure_never_fails_the_commit(tmp_path):
    """A permanently failing ledger backend is observability-only: the
    take still commits and restores."""
    base = str(tmp_path / "run")
    sched = fl.FaultSchedule().permanent(
        op="write", path=f"{ledger.LEDGER_DIR}/*"
    )
    mgr = CheckpointManager(base, max_to_keep=5)
    with fl.inject(sched):
        mgr.save(0, _state(0))
    assert mgr.all_steps() == [0]
    target = _state(1)
    failures = telemetry.snapshot().get(
        "tpusnapshot_ledger_append_failures_total", 0
    )
    assert failures >= 1
    records, _ = ledger.read_records(base)
    assert [r for r in records if r["kind"] == "take"] == []
    # The snapshot itself is intact (faults were ledger-only).
    assert mgr.restore(target) == 0
    np.testing.assert_array_equal(np.asarray(target["m"].params["w"]), 0.0)


# --------------------------------------------------- end-to-end acceptance


def test_e2e_timeline_reproduces_trends_and_flags_slow_take(
    tmp_path, capsys
):
    """ISSUE 5 acceptance: >=5 takes + 1 restore through the real
    Snapshot path; timeline reproduces per-step throughput/goodput/churn
    from the ledger ALONE, and an injected slowdown on the last take
    trips the regression sentinel (exit 1) naming the metric + step."""
    base = str(tmp_path / "run")
    mgr = CheckpointManager(base, max_to_keep=10, incremental=True)
    params = {
        "w": np.arange(8192, dtype=np.float32),
        "frozen": np.ones(8192, np.float32),
    }
    n_steps = 6
    for step in range(n_steps):
        goodput.step()
        time.sleep(0.01)  # the "training" between checkpoints
        params = dict(params, w=params["w"] + 1)
        if step == n_steps - 1:
            # The regression under test: every storage write on the
            # last take eats injected latency.
            sched = fl.FaultSchedule().latency(
                op="write", seconds=0.12, times=None
            )
            with fl.inject(sched):
                mgr.save(step, {"m": _Model(params)})
        else:
            mgr.save(step, {"m": _Model(params)})
    mgr.restore({"m": _Model(dict(params))})

    # The ledger alone reproduces the run's trends.
    records, skipped = ledger.read_records(base)
    assert skipped == 0
    takes = [r for r in records if r["kind"] == "take"]
    restores = [r for r in records if r["kind"] == "restore"]
    assert [r["step"] for r in takes] == list(range(n_steps))
    assert len(restores) == 1
    for r in takes:
        assert r["gbps"] > 0
        assert r["churn"] is not None
    for r in takes[1:]:
        assert r["churn"]["efficiency"] == pytest.approx(0.5)
        assert r["goodput"]["goodput_fraction"] is not None
    # The slow take is visibly slower in the ledger. Median, not max:
    # a single ambient fs stall (0.5s+ under full-suite writeback
    # pressure) on ONE healthy mid take must not mask the injected
    # slowdown — the sentinel below is the robust detector anyway.
    import statistics

    mid_walls = [r["wall_s"] for r in takes[1:-1]]
    assert takes[-1]["wall_s"] > 3 * statistics.median(mid_walls)

    # The sentinel names the drifting metric and the first bad step.
    rc = timeline.main([base])
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "REGRESSION take seconds" in out
    assert f"step {n_steps - 1}" in out
    # Trend table reproduces throughput/goodput/churn columns.
    assert "GB/s" in out and "goodput" in out and "churn" in out

    # Healthy prefix: without the slow take, nothing points at its
    # step. (Asserted on the analysis, not the exit code: the toy
    # loop's ambient timings can wiggle under full-suite load, and the
    # property under test is that the INJECTED regression is what the
    # sentinel saw.)
    healthy = [r for r in records if r.get("step") != n_steps - 1]
    result = timeline.analyze_ledger(healthy)
    assert not [
        f
        for f in result["regressions"]
        if f["label"] == f"step {n_steps - 1}"
    ]
