"""CheckpointManager tests: step markers, retention, latest-resolution,
async finalization, multi-rank agreement (beyond reference parity)."""

import os
import time

import numpy as np
import pytest

import jax.numpy as jnp

from torchsnapshot_tpu import CheckpointManager, Snapshot, StateDict
from torchsnapshot_tpu.utils.test_utils import run_thread_ranks


def _state(v):
    return {"s": StateDict(w=jnp.full((8,), float(v)))}


def _target():
    return {"s": StateDict(w=jnp.zeros((8,)))}


def test_save_restore_latest_and_explicit(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    mgr = CheckpointManager(str(tmp_path / "run"))
    for step in (0, 100, 200):
        mgr.save(step, _state(step))
    assert mgr.all_steps() == [0, 100, 200]
    assert mgr.latest_step() == 200

    target = _target()
    assert mgr.restore(target) == 200
    np.testing.assert_array_equal(np.asarray(target["s"]["w"]), 200.0)

    target = _target()
    assert mgr.restore(target, step=100) == 100
    np.testing.assert_array_equal(np.asarray(target["s"]["w"]), 100.0)


def test_retention_prunes_old_steps(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    base = tmp_path / "run"
    mgr = CheckpointManager(str(base), max_to_keep=2)
    for step in range(5):
        mgr.save(step, _state(step))
    assert mgr.all_steps() == [3, 4]
    # Pruned step dirs hold no files (markers AND payloads gone).
    for step in range(3):
        leftovers = [
            os.path.join(dp, f)
            for dp, _, fs in os.walk(base / f"step-{step}")
            for f in fs
        ]
        assert leftovers == [], f"step {step} not pruned: {leftovers}"
    # Retained steps still restore.
    target = _target()
    assert mgr.restore(target) == 4


def test_latest_ignores_uncommitted_dirs(tmp_path):
    """A step directory without a marker (crashed mid-take) is invisible
    to latest_step/restore — the marker is the manager-level commit."""
    base = tmp_path / "run"
    mgr = CheckpointManager(str(base))
    mgr.save(10, _state(10))
    # A crashed later take: payload dir exists, no marker.
    Snapshot.take(str(base / "step-20"), _state(20))
    os.remove(base / "step-20" / ".snapshot_metadata")
    (base / "step-20" / "junk").write_bytes(b"x")
    assert mgr.latest_step() == 10
    target = _target()
    assert mgr.restore(target) == 10


def test_restore_empty_raises(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "none"))
    with pytest.raises(FileNotFoundError, match="No committed checkpoints"):
        mgr.restore(_target())


def test_async_save_finalizes_on_wait(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "run"), max_to_keep=1)
    pending = mgr.async_save(7, _state(7))
    handle_snapshot = pending.wait()
    assert mgr.all_steps() == [7]
    target = _target()
    assert mgr.restore(target) == 7
    np.testing.assert_array_equal(np.asarray(target["s"]["w"]), 7.0)
    assert handle_snapshot.verify() == {}
    # A second async save prunes the first after wait().
    mgr.async_save(8, _state(8)).wait()
    assert mgr.all_steps() == [8]


def test_multi_rank_save_restore(tmp_path, monkeypatch):
    """Every rank calls save/restore; markers and pruning are rank-0
    duties; restore(step=None) agrees across ranks via broadcast."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    base = str(tmp_path / "run")

    def worker(coord, rank):
        mgr = CheckpointManager(base, max_to_keep=2, coord=coord)
        for step in range(3):
            mgr.save(
                step,
                {"s": StateDict(mine=np.full((4,), rank + step * 10.0))},
            )
        target = {"s": StateDict(mine=np.zeros((4,)))}
        restored_step = mgr.restore(target)
        assert restored_step == 2
        np.testing.assert_array_equal(
            np.asarray(target["s"]["mine"]), rank + 20.0
        )
        return restored_step

    assert run_thread_ranks(2, worker) == [2, 2]
    assert CheckpointManager(base).all_steps() == [1, 2]


def test_max_to_keep_validation(tmp_path):
    with pytest.raises(ValueError, match="max_to_keep"):
        CheckpointManager(str(tmp_path), max_to_keep=0)


def test_interrupted_prune_retried_by_next_prune(tmp_path, monkeypatch):
    """A prune killed between marker delete and payload delete must not
    leak the step's payloads forever: the tombstone re-drives it on the
    next prune (code-review r3)."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    base = tmp_path / "run"
    mgr = CheckpointManager(str(base), max_to_keep=2)
    mgr.save(0, _state(0))
    mgr.save(1, _state(1))

    # Simulate the interrupted prune of step 0: marker gone, tombstone
    # present, payloads still on disk.
    os.remove(base / ".steps" / "0")
    (base / ".pruning").mkdir(exist_ok=True)
    (base / ".pruning" / "0").write_bytes(b"1")
    assert (base / "step-0" / ".snapshot_metadata").exists()
    assert mgr.all_steps() == [1]  # step 0 already unresolvable

    # The next retention-triggering save retries the interrupted prune.
    mgr.save(2, _state(2))
    leftovers = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(base / "step-0")
        for f in fs
    ]
    assert leftovers == []
    assert not (base / ".pruning" / "0").exists()
    assert mgr.all_steps() == [1, 2]


def test_tombstone_survives_age_guarded_sweep(tmp_path):
    """Under the DEFAULT sweep age guard (1h), a tombstone retry whose
    payloads are young gets spared — the tombstone must survive so a
    later prune retries, instead of leaking the step forever
    (code-review r3 follow-up)."""
    base = tmp_path / "run"
    mgr = CheckpointManager(str(base), max_to_keep=2)
    mgr.save(0, _state(0))
    mgr.save(1, _state(1))

    # Interrupted prune of step 0: marker AND metadata gone (the
    # interrupted Snapshot.delete removed metadata first), payloads
    # remain and are minutes old.
    os.remove(base / ".steps" / "0")
    os.remove(base / "step-0" / ".snapshot_metadata")
    (base / ".pruning").mkdir(exist_ok=True)
    (base / ".pruning" / "0").write_bytes(b"1")

    # Default age guard active: retry spares the young payloads but the
    # tombstone must survive.
    mgr.save(2, _state(2))
    assert (base / ".pruning" / "0").exists()
    payloads = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(base / "step-0")
        for f in fs
    ]
    assert payloads  # spared, not leaked-and-forgotten

    # Once the payloads age out, the next prune clears them + tombstone.
    old = time.time() - 7200
    for p in payloads:
        os.utime(p, (old, old))
    mgr.save(3, _state(3))
    assert not (base / ".pruning" / "0").exists()
    assert [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(base / "step-0")
        for f in fs
    ] == []


def test_keep_period_archives_periodic_steps(tmp_path, monkeypatch):
    """keep_period steps are archived: never counted against max_to_keep,
    never pruned — a rolling recent window plus periodic keepers."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    mgr = CheckpointManager(
        str(tmp_path / "run"), max_to_keep=2, keep_period=100
    )
    for step in (0, 50, 100, 150, 175, 200, 225, 250):
        mgr.save(step, _state(step))
    # Archived: 0, 100, 200 (multiples of 100). Rolling window: the two
    # newest non-archived steps (225, 250).
    assert mgr.all_steps() == [0, 100, 200, 225, 250]
    target = _target()
    assert mgr.restore(target, step=100) == 100
    np.testing.assert_array_equal(np.asarray(target["s"]["w"]), 100.0)


def test_manager_on_fake_gcs(monkeypatch):
    """The lifecycle layer over the north-star gs:// backend (fake
    client): markers, retention pruning (incl. composite .part orphans),
    and latest-resolution all ride the same StoragePlugin surface."""
    import sys

    sys.path.insert(0, "tests")
    from test_cloud_plugins import _FakeGCSClient

    from torchsnapshot_tpu.storage_plugins.gcs import GCSStoragePlugin
    import torchsnapshot_tpu.storage_plugin as sp

    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    client = _FakeGCSClient()

    def to_plugin(url):
        from torchsnapshot_tpu.io_types import RetryingStoragePlugin

        root = url[len("gs://"):]
        return RetryingStoragePlugin(
            GCSStoragePlugin(root=root, client=client)
        )

    monkeypatch.setattr(sp, "url_to_storage_plugin", to_plugin)
    monkeypatch.setattr(
        "torchsnapshot_tpu.snapshot.url_to_storage_plugin", to_plugin
    )
    monkeypatch.setattr(
        "torchsnapshot_tpu.manager.url_to_storage_plugin", to_plugin
    )

    mgr = CheckpointManager("gs://bucket/run", max_to_keep=1)
    for step in (1, 2):
        mgr.save(step, _state(step))
    assert mgr.all_steps() == [2]
    # Step 1's objects are gone from the bucket; step 2's remain.
    assert not [k for k in client.store if k.startswith("run/step-1/")]
    assert [k for k in client.store if k.startswith("run/step-2/")]

    target = _target()
    assert mgr.restore(target) == 2
    np.testing.assert_array_equal(np.asarray(target["s"]["w"]), 2.0)


def test_lifecycle_stress_with_random_interruptions(tmp_path, monkeypatch):
    """Seeded chaos over the manager's invariants: random saves with
    randomly injected crash artifacts (uncommitted dirs, orphaned
    tombstones, stray markers deleted). Invariants after every event:
    all_steps() only lists steps whose snapshots actually restore, and
    restore(step=None) always succeeds when any step is committed."""
    import random

    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    rng = random.Random(7)
    base = tmp_path / "run"
    mgr = CheckpointManager(str(base), max_to_keep=3)
    committed = set()

    for step in range(30):
        event = rng.random()
        if event < 0.6:
            mgr.save(step, _state(step))
            committed.add(step)
            committed = set(sorted(committed)[-3:])
        elif event < 0.75:
            # Crashed take: payload dir without marker.
            Snapshot.take(str(base / f"step-{step}"), _state(step))
            os.remove(base / f"step-{step}" / ".snapshot_metadata")
        elif event < 0.9 and committed:
            # Interrupted prune of the oldest committed step.
            victim = min(committed)
            os.remove(base / ".steps" / str(victim))
            (base / ".pruning").mkdir(exist_ok=True)
            (base / ".pruning" / str(victim)).write_bytes(b"1")
            committed.discard(victim)
        # else: plain training step, no checkpoint event.

        steps = mgr.all_steps()
        assert steps == sorted(committed), (step, steps, committed)
        for s in steps:
            # Every listed step must be a restorable snapshot.
            target = _target()
            assert mgr.restore(target, step=s) == s
            np.testing.assert_array_equal(
                np.asarray(target["s"]["w"]), float(s)
            )
        if steps:
            assert mgr.restore(_target()) == max(steps)

    # Final cleanliness: one more save drives any leftover tombstones.
    mgr.save(99, _state(99))
    if (base / ".pruning").exists():
        assert list((base / ".pruning").glob("*")) == []


def test_inspect_cli_steps(tmp_path, capsys):
    from torchsnapshot_tpu.inspect import main

    base = str(tmp_path / "run")
    mgr = CheckpointManager(base)
    mgr.save(3, _state(3))
    mgr.save(7, _state(7))
    assert main([base, "--steps"]) == 0
    assert capsys.readouterr().out.split() == ["3", "7"]
    assert main([str(tmp_path / "empty"), "--steps"]) == 1


def test_inspect_cli_steps_mutually_exclusive(tmp_path):
    from torchsnapshot_tpu.inspect import main

    with pytest.raises(SystemExit):
        main([str(tmp_path), "--steps", "--delete"])


def test_finalize_marker_before_barrier_prune_after(tmp_path, monkeypatch):
    """_finalize ordering (ADVICE r3): the step marker must be committed
    before the barrier releases non-zero ranks, and retention pruning —
    whose cloud-backend latency can approach the barrier timeout — must
    run after the barrier so it can never stall the other ranks."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    base = tmp_path / "run"
    events = []

    from torchsnapshot_tpu.coord import NoOpCoordinator

    class RecordingCoord(NoOpCoordinator):
        def barrier(self, timeout_s=None):
            marker_dir = base / ".steps"
            markers = (
                sorted(p.name for p in marker_dir.iterdir())
                if marker_dir.exists()
                else []
            )
            events.append(("barrier", markers))

    orig_prune = CheckpointManager._prune

    def recording_prune(self, storage):
        events.append(("prune", None))
        return orig_prune(self, storage)

    monkeypatch.setattr(CheckpointManager, "_prune", recording_prune)

    mgr = CheckpointManager(str(base), max_to_keep=1, coord=RecordingCoord())
    for step in range(2):
        mgr.save(step, {"s": StateDict(x=np.ones((2,)))})

    barriers = [e for e in events if e[0] == "barrier"]
    prunes = [e for e in events if e[0] == "prune"]
    assert len(prunes) == 2
    # Finalize barriers must observe the just-written marker (take()'s
    # own commit barriers run before any marker exists).
    assert any(e[1] == ["0"] for e in barriers)
    assert any("1" in e[1] for e in barriers)
    # Ordering within the last finalize: marker-bearing barrier precedes
    # the prune.
    last_prune_idx = max(i for i, e in enumerate(events) if e[0] == "prune")
    prior_barriers = [
        e for e in events[:last_prune_idx] if e[0] == "barrier"
    ]
    assert prior_barriers and "1" in prior_barriers[-1][1]


def _orphan_step(base, step, value):
    """Commit step's snapshot but 'crash' before finalize: the inner
    PendingSnapshot commits metadata; the managed handle (which would
    write the step marker) is dropped without wait()."""
    mgr = CheckpointManager(base, max_to_keep=5)
    pending = mgr.async_save(step, _state(value))
    pending._pending.wait()  # drain + metadata commit only
    return mgr


def test_reconcile_adopts_orphaned_async_save(tmp_path, monkeypatch):
    """Crash between the background commit and wait()'s finalize leaves
    a committed-but-invisible step; reconcile() must adopt it so the
    pre-crash work becomes restorable (VERDICT r3 weak #5)."""
    base = str(tmp_path / "run")
    mgr = CheckpointManager(base, max_to_keep=5)
    mgr.save(1, _state(1.0))
    _orphan_step(base, 2, 2.0)

    fresh = CheckpointManager(base, max_to_keep=5)
    assert fresh.latest_step() == 1  # orphan invisible
    assert fresh.reconcile() == [2]
    assert fresh.latest_step() == 2
    target = _target()
    assert fresh.restore(target) == 2
    np.testing.assert_array_equal(np.asarray(target["s"]["w"]), 2.0)
    # Idempotent: nothing left to adopt.
    assert fresh.reconcile() == []


def test_reconcile_adoption_reruns_retention(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    base = str(tmp_path / "run")
    mgr = CheckpointManager(base, max_to_keep=2)
    for step in (1, 2):
        mgr.save(step, _state(step))
    _orphan_step(base, 3, 3.0)
    fresh = CheckpointManager(base, max_to_keep=2)
    assert fresh.reconcile() == [3]
    # Adoption overfilled the window; retention re-ran.
    assert fresh.all_steps() == [2, 3]


def test_reconcile_sweeps_orphan_when_asked(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    base = str(tmp_path / "run")
    mgr = CheckpointManager(base, max_to_keep=5)
    mgr.save(1, _state(1.0))
    _orphan_step(base, 2, 2.0)
    fresh = CheckpointManager(base, max_to_keep=5)
    assert fresh.reconcile(adopt=False) == [2]
    assert fresh.all_steps() == [1]
    assert not (tmp_path / "run" / "step-2" / ".snapshot_metadata").exists()
    # The committed step is untouched and still restorable.
    target = _target()
    assert fresh.restore(target) == 1


def test_reconcile_sweep_spares_young_orphans(tmp_path, monkeypatch):
    """A just-committed orphan may be an in-flight async save whose
    wait() hasn't run yet — the age guard must spare it."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "3600")
    base = str(tmp_path / "run")
    _orphan_step(base, 7, 7.0)
    fresh = CheckpointManager(base)
    assert fresh.reconcile(adopt=False) == []
    assert (tmp_path / "run" / "step-7" / ".snapshot_metadata").exists()


def test_reconcile_sweep_spares_unknown_age_orphans(tmp_path, monkeypatch):
    """A backend that cannot report an object's age (GCS blob with no
    ``updated`` field, soft-None paths) must fail CLOSED: the orphan was
    just listed so its commit object exists, and sweeping it could
    destroy a just-committed async save (ADVICE r4). Setting
    TPUSNAPSHOT_SWEEP_MIN_AGE_S=0 remains the explicit escape hatch
    (guard disabled, sweep regardless of age)."""
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "3600")

    async def _no_age(self, path):
        return None

    monkeypatch.setattr(FSStoragePlugin, "object_age_s", _no_age)
    base = str(tmp_path / "run")
    _orphan_step(base, 7, 7.0)
    fresh = CheckpointManager(base)
    assert fresh.reconcile(adopt=False) == []
    assert (tmp_path / "run" / "step-7" / ".snapshot_metadata").exists()


def test_reconcile_skips_tombstoned_steps(tmp_path, monkeypatch):
    """A step mid-prune (marker deleted, payloads pending, tombstone
    present) is NOT an orphan: adopting it would resurrect a checkpoint
    retention already condemned."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    base = str(tmp_path / "run")
    mgr = CheckpointManager(base, max_to_keep=5)
    mgr.save(1, _state(1.0))
    mgr.save(2, _state(2.0))
    # Simulate an interrupted prune of step 1: tombstone written, marker
    # removed, payloads still on disk.
    (tmp_path / "run" / ".pruning").mkdir()
    (tmp_path / "run" / ".pruning" / "1").write_bytes(b"1")
    os.unlink(tmp_path / "run" / ".steps" / "1")
    fresh = CheckpointManager(base, max_to_keep=5)
    assert fresh.reconcile() == []
    assert fresh.all_steps() == [2]


def test_inspect_cli_reconcile(tmp_path, capsys):
    base = str(tmp_path / "run")
    CheckpointManager(base).save(1, _state(1.0))
    _orphan_step(base, 2, 2.0)

    from torchsnapshot_tpu.inspect import main as inspect_main

    assert inspect_main([base, "--reconcile", "adopt"]) == 0
    out = capsys.readouterr()
    assert out.out.strip() == "2"
    assert "adopted 1 orphaned step(s)" in out.err
    assert CheckpointManager(base).latest_step() == 2
    # Nothing left: exit 0 with a notice.
    assert inspect_main([base, "--reconcile", "adopt"]) == 0
    assert "no orphaned steps" in capsys.readouterr().err


def test_reconcile_on_init(tmp_path):
    """The job-startup hook: a fresh manager constructed with
    reconcile_on_init='adopt' resumes from a step orphaned by a crash
    between the background commit and finalize."""
    base = str(tmp_path / "run")
    CheckpointManager(base).save(1, _state(1.0))
    _orphan_step(base, 2, 2.0)
    fresh = CheckpointManager(base, reconcile_on_init="adopt")
    assert fresh.latest_step() == 2
    with pytest.raises(ValueError, match="reconcile_on_init"):
        CheckpointManager(base, reconcile_on_init="bogus")
