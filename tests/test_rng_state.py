"""RNG invariant tests (reference analog: tests/test_rng_state.py)."""

import random

import numpy as np

from torchsnapshot_tpu import RNGState, Snapshot, StateDict


class StatefulWithRNGSideEffect:
    """state_dict() perturbs host RNG (reference test_rng_state.py:16-23)."""

    def state_dict(self):
        np.random.rand(10)
        random.random()
        return {"noop": 0}

    def load_state_dict(self, state_dict):
        np.random.rand(10)
        random.random()


def test_rng_state_take_restore_identical(tmp_path):
    """The RNG stream observed after take() must equal the stream observed
    after restore() — even when other statefuls perturb RNG inside their
    state_dict() (reference snapshot.py:174-191, 216-221)."""
    app_state = {"rng": RNGState(), "evil": StatefulWithRNGSideEffect()}
    np.random.seed(42)
    random.seed(42)
    snap = Snapshot.take(str(tmp_path / "snap"), app_state)
    after_take_np = np.random.rand(5)
    after_take_py = [random.random() for _ in range(5)]

    # Scramble RNG, then restore: draws must match the post-take draws.
    np.random.seed(777)
    random.seed(777)
    snap.restore({"rng": RNGState(), "evil": StatefulWithRNGSideEffect()})
    np.testing.assert_array_equal(np.random.rand(5), after_take_np)
    assert [random.random() for _ in range(5)] == after_take_py


def test_rng_round_trip_plain(tmp_path):
    np.random.seed(1)
    random.seed(1)
    snap = Snapshot.take(str(tmp_path / "snap"), {"rng": RNGState()})
    expected = np.random.rand(3)
    np.random.seed(2)
    snap.restore({"rng": RNGState()})
    np.testing.assert_array_equal(np.random.rand(3), expected)


def test_two_rng_states_rejected(tmp_path):
    import pytest

    with pytest.raises(RuntimeError, match="at most one RNGState"):
        Snapshot.take(
            str(tmp_path / "snap"), {"a": RNGState(), "b": RNGState()}
        )
