"""Async snapshot tests (BASELINE.json north star: async take with
bounded step stall; SURVEY §7 step 8)."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchsnapshot_tpu import PendingSnapshot, Snapshot, StateDict
from torchsnapshot_tpu.coord import DictStore, StoreCoordinator
from torchsnapshot_tpu.utils.test_utils import assert_state_dict_eq


class _Holder:
    def __init__(self, sd):
        self.sd = sd

    def state_dict(self):
        return self.sd

    def load_state_dict(self, sd):
        self.sd = sd


def test_async_take_round_trip(tmp_path):
    params = {"w": jnp.arange(1024, dtype=jnp.float32).reshape(32, 32)}
    pending = Snapshot.async_take(str(tmp_path / "snap"), {"m": _Holder(params)})
    assert isinstance(pending, PendingSnapshot)
    snap = pending.wait()
    assert pending.done()
    target = _Holder({"w": jnp.zeros((32, 32), dtype=jnp.float32)})
    snap.restore({"m": target})
    np.testing.assert_array_equal(np.asarray(target.sd["w"]), np.asarray(params["w"]))


def test_async_take_consistent_cut(tmp_path):
    """Mutating state after async_take returns must not affect the
    snapshot (staging = consistent cut)."""
    state = {"w": np.arange(100, dtype=np.float32)}
    holder = _Holder(state)
    pending = Snapshot.async_take(str(tmp_path / "snap"), {"m": holder})
    # Mutate immediately — before writes necessarily finished.
    state["w"][:] = -1.0
    snap = pending.wait()
    target = _Holder({"w": np.zeros(100, dtype=np.float32)})
    snap.restore({"m": target})
    np.testing.assert_array_equal(target.sd["w"], np.arange(100, dtype=np.float32))


def test_async_take_donation_safe(tmp_path):
    """Buffers may be donated (deleted) by the next jit step immediately
    after async_take returns; staging must already have happened."""
    import jax

    arr = jnp.arange(4096.0)
    pending = Snapshot.async_take(str(tmp_path / "snap"), {"m": _Holder({"w": arr})})
    arr.delete()  # simulate jit buffer donation
    snap = pending.wait()
    target = _Holder({"w": jnp.zeros(4096)})
    snap.restore({"m": target})
    assert float(np.asarray(target.sd["w"])[123]) == 123.0


def test_async_take_error_surfaces():
    class _Unpicklable:
        def __reduce__(self):
            raise RuntimeError("cannot pickle me")

    with pytest.raises(RuntimeError, match="cannot pickle me"):
        # Pickling happens at prepare time (synchronously).
        Snapshot.async_take("memory://async-err", {"m": _Holder({"o": _Unpicklable()})})


def test_async_take_multirank(tmp_path):
    path = str(tmp_path / "snap")

    def worker_take(coord, rank):
        pending = Snapshot.async_take(
            path, {"st": StateDict(v=rank)}, coord=coord
        )
        pending.wait()

    store = DictStore()
    errors = []

    def worker(rank):
        try:
            coord = StoreCoordinator(store, rank, 2, timeout_s=60)
            worker_take(coord, rank)
        except BaseException:  # pragma: no cover
            import traceback

            errors.append(traceback.format_exc())

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors[0]

    def worker_restore(coord, rank):
        app = {"st": StateDict(v=-1)}
        Snapshot(path).restore(app, coord=coord)
        assert app["st"]["v"] == rank

    store2 = DictStore()
    threads = [
        threading.Thread(
            target=lambda r=r: worker_restore(StoreCoordinator(store2, r, 2, 60), r)
        )
        for r in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)


@pytest.mark.parametrize("stage", ["device", "host", "auto"])
def test_async_take_stage_modes(tmp_path, stage):
    """All staging modes produce identical, donation-safe snapshots."""
    arr = jnp.arange(2048, dtype=jnp.float32) * 3.0
    sharded = {"w": arr, "b": np.full(16, 7.0, dtype=np.float32)}
    pending = Snapshot.async_take(
        str(tmp_path / "snap"), {"m": _Holder(dict(sharded))}, stage=stage
    )
    arr.delete()  # simulate jit buffer donation
    sharded["b"][:] = -1.0  # mutate host memory after the cut
    snap = pending.wait()
    target = _Holder(
        {"w": jnp.zeros(2048), "b": np.zeros(16, dtype=np.float32)}
    )
    snap.restore({"m": target})
    np.testing.assert_array_equal(
        np.asarray(target.sd["w"]), np.arange(2048, dtype=np.float32) * 3.0
    )
    np.testing.assert_array_equal(target.sd["b"], np.full(16, 7.0))


def test_async_take_invalid_stage(tmp_path):
    with pytest.raises(ValueError, match="stage"):
        Snapshot.async_take(
            str(tmp_path / "snap"), {"m": _Holder({})}, stage="bogus"
        )


@pytest.mark.parametrize("stage", ["device", "host"])
def test_async_take_sharded_array(tmp_path, stage):
    """Device-staged async take of a partitioned array: clones preserve
    sharding; the snapshot survives deletion of the source (donation)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("x",))
    arr = jax.device_put(
        jnp.arange(64.0).reshape(8, 8), NamedSharding(mesh, P("x", None))
    )
    pending = Snapshot.async_take(
        str(tmp_path / "snap"), {"m": _Holder({"w": arr})}, stage=stage
    )
    arr.delete()
    snap = pending.wait()

    # Elastic restore onto a smaller mesh.
    mesh2 = Mesh(np.array(jax.devices()[:2]), ("x",))
    template = jax.device_put(
        jnp.zeros((8, 8)), NamedSharding(mesh2, P(None, "x"))
    )
    target = _Holder({"w": template})
    snap.restore({"m": target})
    np.testing.assert_array_equal(
        np.asarray(target.sd["w"]), np.arange(64.0).reshape(8, 8)
    )


def test_async_take_background_write_failure_surfaces(tmp_path, monkeypatch):
    """A storage failure in the background drain must surface on wait(),
    and no metadata commit may appear (the snapshot stays invisible)."""
    import os
    import torchsnapshot_tpu.snapshot as snap_mod
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    class _FailingFS(FSStoragePlugin):
        async def write(self, io_req):
            if not io_req.path.startswith(".completed"):
                raise IOError("disk on fire")
            await super().write(io_req)

    monkeypatch.setattr(
        snap_mod, "url_to_storage_plugin", lambda path: _FailingFS(path)
    )
    pending = Snapshot.async_take(
        str(tmp_path / "snap"), {"m": _Holder({"w": jnp.arange(16.0)})}
    )
    with pytest.raises(IOError, match="disk on fire"):
        pending.wait()
    assert not os.path.exists(tmp_path / "snap" / ".snapshot_metadata")


def test_concurrent_async_takes_to_distinct_paths(tmp_path):
    """Two in-flight async snapshots (e.g. overlapping checkpoint
    cadences) must drain independently and both commit correctly."""
    a = {"w": jnp.arange(4096, dtype=jnp.float32).reshape(64, 64)}
    b = {"w": jnp.arange(4096, dtype=jnp.float32).reshape(64, 64) * 2}
    pa = Snapshot.async_take(str(tmp_path / "a"), {"m": _Holder(a)})
    pb = Snapshot.async_take(str(tmp_path / "b"), {"m": _Holder(b)})
    sa, sb = pa.wait(), pb.wait()

    ta = {"m": _Holder({"w": jnp.zeros((64, 64), jnp.float32)})}
    tb = {"m": _Holder({"w": jnp.zeros((64, 64), jnp.float32)})}
    sa.restore(ta)
    sb.restore(tb)
    np.testing.assert_array_equal(np.asarray(ta["m"].sd["w"]), np.asarray(a["w"]))
    np.testing.assert_array_equal(np.asarray(tb["m"].sd["w"]), np.asarray(b["w"]))


def test_many_small_leaves_round_trip(tmp_path):
    """2000-leaf state: manifest, scheduler, and storage must stay
    linear-ish (regression guard for per-leaf overhead blowups)."""
    leaves = {f"k{i:04d}": jnp.full((4, 4), i, jnp.float32) for i in range(2000)}
    state = StateDict(**leaves)
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"s": state})
    target = StateDict(**{k: jnp.zeros((4, 4), jnp.float32) for k in leaves})
    Snapshot(path).restore({"s": target})
    assert float(target["k1999"][0, 0]) == 1999.0
    assert float(target["k0000"][0, 0]) == 0.0
    assert len(Snapshot(path).get_manifest()) >= 2000


def test_failed_take_leaves_no_commit_and_sweep_recovers(tmp_path):
    """Crash-recovery story: a take that dies mid-write must leave the
    path UNCOMMITTED (no metadata document -> restore raises not-found)
    with its partial writes stranded, a subsequent take to the same path
    must succeed, and delete(sweep=True) then leaves nothing behind
    (orphan-specific collection is covered by the delete-sweep tests)."""
    import os
    import threading

    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    path = str(tmp_path / "snap")
    state = StateDict(
        a=jnp.arange(64, dtype=jnp.float32),
        b=jnp.ones((32,), dtype=jnp.float32),
    )

    real_write = FSStoragePlugin._write_sync
    writes = []
    write_lock = threading.Lock()

    def dying_write(self, io_req):
        # Decide under a lock BEFORE writing: with 2-way write
        # concurrency both writers could otherwise observe len==2 and
        # raise, leaving zero partial writes to recover from. This way
        # write #1 always lands (asyncio.run joins the default executor
        # on teardown) and write #2 always dies.
        with write_lock:
            writes.append(io_req.path)
            n = len(writes)
        if n == 2:
            raise OSError("disk gone")
        real_write(self, io_req)

    FSStoragePlugin._write_sync = dying_write
    try:
        # Storage retries would mask the injected failure; disable.
        os.environ["TPUSNAPSHOT_STORAGE_RETRIES"] = "0"
        with pytest.raises(OSError, match="disk gone"):
            Snapshot.take(path, {"s": state})
    finally:
        FSStoragePlugin._write_sync = real_write
        os.environ.pop("TPUSNAPSHOT_STORAGE_RETRIES", None)

    # The crash stranded at least write #1's object, uncommitted:
    # metadata absent, restore refuses.
    stranded = [
        os.path.join(dp, f) for dp, _, fs in os.walk(path) for f in fs
    ]
    assert stranded, "the failed take should have landed a partial write"
    with pytest.raises(FileNotFoundError):
        Snapshot(path).restore({"s": StateDict(a=jnp.zeros(64), b=jnp.zeros(32))})

    # The same path takes cleanly afterwards (fresh take overwrites), and
    # the snapshot round-trips.
    Snapshot.take(path, {"s": state})
    target = StateDict(
        a=jnp.zeros(64, dtype=jnp.float32), b=jnp.zeros(32, dtype=jnp.float32)
    )
    Snapshot(path).restore({"s": target})
    np.testing.assert_array_equal(np.asarray(target["a"]), np.asarray(state["a"]))

    # Sweep-delete collects everything, including any orphan of the
    # failed attempt.
    Snapshot(path).delete(sweep=True)
    leftovers = [
        os.path.join(dp, f) for dp, _, fs in os.walk(path) for f in fs
    ]
    assert leftovers == []


def test_stale_async_commit_cannot_satisfy_new_take(tmp_path):
    """take_id nonces: a pending wait() for take B must not accept take
    A's already-committed metadata at the same path (the marker/metadata
    poll matches on the nonce, not mere existence). Take B's metadata
    commit is artificially delayed, so an existence-based poll WOULD
    return early — while only A's document exists — and the
    nonce-at-wait-return assertion below would catch it."""
    import os
    import threading
    import time as _time

    from torchsnapshot_tpu.manifest import SnapshotMetadata
    from torchsnapshot_tpu.snapshot import SNAPSHOT_METADATA_FNAME
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    path = str(tmp_path / "snap")
    a = StateDict(x=jnp.zeros(8))
    b = StateDict(x=jnp.ones(8))

    def read_meta():
        with open(os.path.join(path, SNAPSHOT_METADATA_FNAME)) as f:
            return SnapshotMetadata.from_yaml(f.read())

    Snapshot.async_take(path, {"s": a}).wait()
    meta_a = read_meta()

    real_write = FSStoragePlugin._write_sync
    delay_metadata = threading.Event()
    delay_metadata.set()

    def slow_metadata_write(self, io_req):
        if delay_metadata.is_set() and io_req.path == SNAPSHOT_METADATA_FNAME:
            _time.sleep(0.5)
        real_write(self, io_req)

    FSStoragePlugin._write_sync = slow_metadata_write
    try:
        pending_b = Snapshot.async_take(path, {"s": b})
        nonce_b = pending_b._background.take_id
        assert nonce_b and nonce_b != meta_a.take_id
        pending_b.wait()
        # At the instant wait() returns, the visible metadata must
        # already be B's — an existence-based poll would have returned
        # ~0.5 s earlier with A's document still in place.
        meta_at_return = read_meta()
        assert meta_at_return.take_id == nonce_b
    finally:
        FSStoragePlugin._write_sync = real_write

    target = StateDict(x=jnp.full((8,), 7.0))
    Snapshot(path).restore({"s": target})
    np.testing.assert_array_equal(np.asarray(target["x"]), np.ones(8))


def test_wait_timeout_bounds_hung_drain(tmp_path, monkeypatch):
    """wait(timeout_s) must bound the background-drain join (VERDICT r3
    weak #4): a hung storage backend surfaces as a prompt TimeoutError
    naming the stuck phase, and a later wait() can still succeed once
    the backend unblocks."""
    import torchsnapshot_tpu.snapshot as snap_mod
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    release = threading.Event()

    class _HangingFS(FSStoragePlugin):
        async def write(self, io_req):
            if not io_req.path.startswith((".completed", ".snapshot")):
                # Block the drain until the test releases it (simulated
                # wedged backend); poll so the event works from asyncio.
                import asyncio as _a

                while not release.is_set():
                    await _a.sleep(0.01)
            await super().write(io_req)

    monkeypatch.setattr(
        snap_mod, "url_to_storage_plugin", lambda path: _HangingFS(path)
    )
    pending = Snapshot.async_take(
        str(tmp_path / "snap"), {"m": _Holder(StateDict(w=jnp.arange(8.0)))}
    )
    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="storage writes"):
        pending.wait(timeout_s=0.5)
    assert time.monotonic() - t0 < 10
    release.set()
    snap = pending.wait(timeout_s=60)
    target = {"m": _Holder(StateDict(w=jnp.zeros(8)))}
    snap.restore(target)
    np.testing.assert_array_equal(
        np.asarray(target["m"].sd["w"]), np.arange(8.0)
    )


def test_wait_timeout_on_metadata_poll_is_retryable(tmp_path, monkeypatch):
    """A wait() that times out in the METADATA poll (drain finished,
    commit not yet observable — e.g. rank 0 still consolidating) must
    leave the storage plugin open so a later wait() can resume polling
    and succeed."""
    import torchsnapshot_tpu.snapshot as snap_mod
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    release = threading.Event()
    closes = []

    class _HidingFS(FSStoragePlugin):
        async def read(self, io_req):
            if (
                io_req.path == ".snapshot_metadata"
                and not release.is_set()
            ):
                raise FileNotFoundError(io_req.path)
            await super().read(io_req)

        def close(self):
            closes.append(True)
            super().close()

    monkeypatch.setattr(
        snap_mod, "url_to_storage_plugin", lambda path: _HidingFS(path)
    )
    pending = Snapshot.async_take(
        str(tmp_path / "snap"), {"m": _Holder(StateDict(w=jnp.arange(4.0)))}
    )
    with pytest.raises(TimeoutError, match="metadata"):
        pending.wait(timeout_s=2)
    assert not closes  # storage stayed open for the retry
    release.set()
    snap = pending.wait(timeout_s=60)
    assert closes  # closed on success
    target = {"m": _Holder(StateDict(w=jnp.zeros(4)))}
    snap.restore(target)
    np.testing.assert_array_equal(
        np.asarray(target["m"].sd["w"]), np.arange(4.0)
    )


def test_clone_oom_check_knob(tmp_path, monkeypatch):
    """TPUSNAPSHOT_CLONE_OOM_CHECK=0 removes the synchronous
    block_until_ready from the consistent-cut clone (the dominant part
    of the async-take stall on a tunneled device); the round trip stays
    bit-exact either way."""
    import torchsnapshot_tpu.ops.transfer as transfer_mod

    calls = []
    orig = jax.block_until_ready

    def counting(x):
        calls.append(1)
        return orig(x)

    monkeypatch.setattr(jax, "block_until_ready", counting)
    arrs = [jnp.arange(64.0), jnp.ones((8, 8))]

    clones = transfer_mod.device_clone(arrs)
    assert len(calls) == 1  # default: one batched OOM-check wait
    np.testing.assert_array_equal(np.asarray(clones[0]), np.arange(64.0))

    calls.clear()
    monkeypatch.setenv("TPUSNAPSHOT_CLONE_OOM_CHECK", "0")
    clones = transfer_mod.device_clone(arrs)
    assert calls == []  # no blocking wait on the stall path
    np.testing.assert_array_equal(np.asarray(clones[1]), np.ones((8, 8)))

    # Whole async take under the knob: still bit-exact.
    pending = Snapshot.async_take(
        str(tmp_path / "snap"),
        {"m": _Holder(StateDict(w=jnp.arange(32.0)))},
    )
    snap = pending.wait()
    target = {"m": _Holder(StateDict(w=jnp.zeros(32)))}
    snap.restore(target)
    np.testing.assert_array_equal(
        np.asarray(target["m"].sd["w"]), np.arange(32.0)
    )
