"""Real multi-process distributed tests.

TPU-native analog of the reference's torchelastic/gloo pattern
(torchsnapshot/test_utils.py:87-106, tests/test_ddp.py): fork N python
processes that coordinate through a FileStore and — for the sharded test —
form a real multi-process jax.distributed world on CPU, where each process
addresses only its own shard of global arrays.
"""

import os
import sys

import numpy as np
import pytest

from torchsnapshot_tpu.utils.test_utils import run_multiprocess

pytestmark = pytest.mark.slow


def _worker_per_rank_and_replicated(rank, nprocs, store_path, snap_path):
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["JAX_PLATFORMS"] = "cpu"
    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.coord import FileStore, StoreCoordinator

    coord = StoreCoordinator(FileStore(store_path), rank, nprocs, timeout_s=120)
    app = {
        "private": StateDict(rank_id=rank),
        "shared": StateDict(value=12345),
    }
    Snapshot.take(snap_path, app, coord=coord, replicated=["shared/**"])

    target = {"private": StateDict(rank_id=-1), "shared": StateDict(value=-1)}
    coord2 = StoreCoordinator(
        FileStore(store_path + "-restore"), rank, nprocs, timeout_s=120
    )
    Snapshot(snap_path).restore(target, coord=coord2)
    assert target["private"]["rank_id"] == rank, target
    assert target["shared"]["value"] == 12345, target


def test_multiprocess_per_rank_and_replicated(tmp_path):
    run_multiprocess(
        _worker_per_rank_and_replicated,
        nprocs=2,
        store_path=str(tmp_path / "store"),
        args=(str(tmp_path / "snap"),),
    )


def _worker_sharded(rank, nprocs, store_path, snap_path, port):
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax

    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=nprocs,
        process_id=rank,
    )
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu import Snapshot
    from torchsnapshot_tpu.coord import FileStore, StoreCoordinator

    assert len(jax.devices()) == 2 * nprocs

    # Build a global array sharded across all processes' devices.
    mesh = Mesh(np.array(jax.devices()), ("x",))
    global_shape = (16, 4)
    sharding = NamedSharding(mesh, P("x", None))
    data = np.arange(64, dtype=np.float32).reshape(global_shape)
    local_arrays = [
        jax.device_put(data[idx], d)
        for d, idx in sharding.addressable_devices_indices_map(global_shape).items()
    ]
    arr = jax.make_array_from_single_device_arrays(
        global_shape, sharding, local_arrays
    )

    class _Holder:
        def __init__(self, sd):
            self.sd = sd

        def state_dict(self):
            return self.sd

        def load_state_dict(self, sd):
            self.sd = sd

    coord = StoreCoordinator(FileStore(store_path), rank, nprocs, timeout_s=120)
    Snapshot.take(snap_path, {"m": _Holder({"w": arr})}, coord=coord)

    # Restore into a differently-sharded template (still multi-process).
    template = jax.make_array_from_single_device_arrays(
        global_shape,
        sharding,
        [
            jax.device_put(np.zeros_like(data[idx]), d)
            for d, idx in sharding.addressable_devices_indices_map(
                global_shape
            ).items()
        ],
    )
    target = _Holder({"w": template})
    coord2 = StoreCoordinator(
        FileStore(store_path + "-restore"), rank, nprocs, timeout_s=120
    )
    Snapshot(snap_path).restore({"m": target}, coord=coord2)
    restored = target.sd["w"]
    for shard in restored.addressable_shards:
        np.testing.assert_array_equal(np.asarray(shard.data), data[shard.index])


def test_multiprocess_sharded_array(tmp_path):
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    run_multiprocess(
        _worker_sharded,
        nprocs=2,
        store_path=str(tmp_path / "store"),
        args=(str(tmp_path / "snap"), port),
    )


def _worker_sharded_save_then_single_restore(rank, nprocs, store_path, snap_path, port):
    _worker_sharded(rank, nprocs, store_path, snap_path, port)


def test_multiprocess_save_single_process_elastic_restore(tmp_path):
    """Save sharded from 2 processes, restore everything in this (parent)
    process — the pod-shrink elastic scenario, across process boundaries."""
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    run_multiprocess(
        _worker_sharded,
        nprocs=2,
        store_path=str(tmp_path / "store"),
        args=(str(tmp_path / "snap"), port),
    )
    # Parent process: 8 local CPU devices, none shared with the workers.
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu import Snapshot

    data = np.arange(64, dtype=np.float32).reshape(16, 4)
    mesh = Mesh(np.array(jax.devices()[:4]), ("x",))
    template = jax.device_put(
        jnp.zeros((16, 4), dtype=jnp.float32), NamedSharding(mesh, P(None, "x"))
    )

    class _Holder:
        def __init__(self, sd):
            self.sd = sd

        def state_dict(self):
            return self.sd

        def load_state_dict(self, sd):
            self.sd = sd

    target = _Holder({"w": template})
    Snapshot(str(tmp_path / "snap")).restore({"m": target})
    np.testing.assert_array_equal(np.asarray(target.sd["w"]), data)


def _worker_jaxstore(rank, nprocs, store_path, snap_path, port):
    """Exercise the production JaxStore coordinator (jax.distributed KV
    store) end-to-end: collectives + a snapshot round trip ride the
    coordination service instead of a FileStore."""
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=nprocs,
        process_id=rank,
    )
    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.coord import get_coordinator

    coord = get_coordinator()  # auto-resolves to StoreCoordinator(JaxStore)
    assert coord.get_rank() == rank
    assert coord.get_world_size() == nprocs

    # Raw collectives, including a payload above the chunking threshold.
    big = "x" * (700 * 1024)
    gathered = coord.all_gather_object({"rank": rank, "big": big})
    assert [g["rank"] for g in gathered] == list(range(nprocs))
    assert all(g["big"] == big for g in gathered)
    assert coord.broadcast_object(rank, src=0) == 0
    coord.barrier()

    # Snapshot round trip with the auto-resolved coordinator.
    app = {"st": StateDict(v=rank), "shared": StateDict(k=42)}
    Snapshot.take(snap_path, app, replicated=["shared/**"])
    target = {"st": StateDict(v=-1), "shared": StateDict(k=-1)}
    Snapshot(snap_path).restore(target)
    assert target["st"]["v"] == rank
    assert target["shared"]["k"] == 42


def test_multiprocess_jaxstore_coordinator(tmp_path):
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    run_multiprocess(
        _worker_jaxstore,
        nprocs=2,
        store_path=str(tmp_path / "store"),
        args=(str(tmp_path / "snap"), port),
    )


def _worker_pod_topology(rank, nprocs, store_path, snap_path, port):
    """2 processes x 4 virtual devices: a 2-D mesh whose REPLICA axis
    spans the process boundary — the exact case the replica_id==0
    writer dedup (io_preparer._prepare_sharded_array_write) exists for
    (VERDICT r3 missing #3; reference analog: 4-GPU NCCL pod tests,
    reference tests/gpu_tests/test_torchrec.py:139-170)."""
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=nprocs,
        process_id=rank,
    )
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu import Snapshot
    from torchsnapshot_tpu.coord import FileStore, StoreCoordinator

    assert len(jax.devices()) == 4 * nprocs
    assert len(jax.local_devices()) == 4

    # devices.reshape(2, 4).T -> a (shard=4, replica=2) mesh where every
    # replica group pairs one process-0 device with one process-1 device.
    dev_grid = np.array(jax.devices()).reshape(nprocs, 4).T
    mesh = Mesh(dev_grid, ("shard", "replica"))
    global_shape = (16, 8)
    data = np.arange(128, dtype=np.float32).reshape(global_shape)
    sharding = NamedSharding(mesh, P("shard", None))  # replicated on axis 2
    local_arrays = [
        jax.device_put(data[idx], d)
        for d, idx in sharding.addressable_devices_indices_map(
            global_shape
        ).items()
    ]
    arr = jax.make_array_from_single_device_arrays(
        global_shape, sharding, local_arrays
    )

    # Cross-process writer dedup precondition: every region has one
    # replica on EACH process, so without dedup both processes would
    # write every region (or with broken dedup, some region would get
    # zero writers and restore below would fail).
    n_replica0_here = sum(
        1 for s in arr.addressable_shards if s.replica_id == 0
    )
    gathered = StoreCoordinator(
        FileStore(store_path + "-precheck"), rank, nprocs, timeout_s=120
    ).all_gather_object(n_replica0_here)
    assert sum(gathered) == 4, gathered  # exactly one writer per region

    class _Holder:
        def __init__(self, sd):
            self.sd = sd

        def state_dict(self):
            return self.sd

        def load_state_dict(self, sd):
            self.sd = sd

    coord = StoreCoordinator(FileStore(store_path), rank, nprocs, timeout_s=120)
    Snapshot.take(snap_path, {"m": _Holder({"w": arr})}, coord=coord)

    # In-world elastic restore: transpose the mesh so the replica axis
    # is now the sharded one (8-way split never seen at save time).
    flat_mesh = Mesh(np.array(jax.devices()), ("x",))
    template = jax.device_put(
        jnp.zeros(global_shape, dtype=jnp.float32),
        NamedSharding(flat_mesh, P("x", None)),
    )
    target = _Holder({"w": template})
    coord2 = StoreCoordinator(
        FileStore(store_path + "-restore"), rank, nprocs, timeout_s=120
    )
    Snapshot(snap_path).restore({"m": target}, coord=coord2)
    for shard in target.sd["w"].addressable_shards:
        np.testing.assert_array_equal(np.asarray(shard.data), data[shard.index])


def test_pod_topology_replica_group_spans_processes(tmp_path):
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    snap_path = str(tmp_path / "snap")
    run_multiprocess(
        _worker_pod_topology,
        nprocs=2,
        store_path=str(tmp_path / "store"),
        args=(snap_path, port),
    )

    # Storage-level dedup evidence: exactly one object per region (4
    # regions of (4, 6)), not one per replica.
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu import Snapshot
    from torchsnapshot_tpu.manifest import ShardedArrayEntry

    snap = Snapshot(snap_path)
    entry = snap.get_manifest()["0/m/w"]
    assert isinstance(entry, ShardedArrayEntry)
    offsets = sorted(tuple(s.offsets) for s in entry.shards)
    assert offsets == [(0, 0), (4, 0), (8, 0), (12, 0)]
    locations = [s.array.location for s in entry.shards]
    assert len(set(locations)) == 4

    # Elastic restore in the parent onto 8x1 and 1x8 factorizations of
    # a mesh the save never saw.
    data = np.arange(128, dtype=np.float32).reshape(16, 8)
    devices = np.array(jax.devices()[:8])
    for axes_spec in [P("x", None), P(None, "x")]:
        mesh = Mesh(devices, ("x",))
        template = jax.device_put(
            jnp.zeros((16, 8), dtype=jnp.float32),
            NamedSharding(mesh, axes_spec),
        )

        class _Holder:
            def __init__(self, sd):
                self.sd = sd

            def state_dict(self):
                return self.sd

            def load_state_dict(self, sd):
                self.sd = sd

        target = _Holder({"w": template})
        snap.restore({"m": target})
        for shard in target.sd["w"].addressable_shards:
            np.testing.assert_array_equal(
                np.asarray(shard.data), data[shard.index]
            )
