"""Sync-take commit transport at 7B/pod scale (VERDICT r2 weak #2).

The KV all-gather moves every rank's manifest to every rank — O(world^2)
fetch volume through one coordination service. Above a size threshold the
sync path now commits through storage completion markers (the async
path's machinery): each manifest moves once, only rank 0 reads them back.
These tests cover (a) the routing decision, (b) end-to-end correctness
through the storage route, and (c) measured commit time at the
7B-FSDP/world-64 shape the north star names (BASELINE.json).
"""

import asyncio
import time

import numpy as np

import torchsnapshot_tpu.snapshot as snapmod
from torchsnapshot_tpu import Snapshot
from torchsnapshot_tpu.utils.test_utils import run_thread_ranks
from torchsnapshot_tpu.manifest import (
    ArrayEntry,
    Shard,
    ShardedArrayEntry,
    SnapshotMetadata,
)
from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin


class _Holder:
    def __init__(self, sd):
        self.sd = sd

    def state_dict(self):
        return self.sd

    def load_state_dict(self, sd):
        self.sd = sd


def _run_world(world, fn, timeout=300):
    return run_thread_ranks(world, fn, timeout_s=timeout)


def test_sync_take_routes_large_manifests_through_storage(
    tmp_path, monkeypatch
):
    """With the threshold forced to 0, a multi-rank sync take commits via
    storage markers and still round-trips correctly; markers are cleaned
    up and the committed metadata carries every rank's entries."""
    monkeypatch.setenv("TPUSNAPSHOT_COMMIT_VIA_STORAGE_BYTES", "0")
    calls = []
    real = snapmod._acommit_via_storage

    async def spy(*args, **kwargs):
        calls.append(args[1])  # rank
        return await real(*args, **kwargs)

    monkeypatch.setattr(snapmod, "_acommit_via_storage", spy)

    path = str(tmp_path / "snap")
    world = 4

    def worker(coord, rank):
        Snapshot.take(
            path,
            {"m": _Holder({"w": np.full((8,), rank, dtype=np.float32)})},
            coord=coord,
        )

    _run_world(world, worker)
    assert sorted(calls) == list(range(world))  # storage route used

    # No completion markers remain; metadata has all ranks' entries.
    snap_dir = tmp_path / "snap"
    leftover = (
        [p for p in (snap_dir / ".completed").rglob("*") if p.is_file()]
        if (snap_dir / ".completed").exists()
        else []
    )
    assert leftover == []
    meta = SnapshotMetadata.from_yaml(
        snapmod._decode_metadata_doc(
            (snap_dir / ".snapshot_metadata").read_bytes()
        )
    )
    assert {f"{r}/m/w" for r in range(world)} <= set(meta.manifest)

    # Per-rank restore sees per-rank values.
    def restore_worker(coord, rank):
        target = _Holder({"w": np.zeros((8,), dtype=np.float32)})
        Snapshot(path).restore({"m": target}, coord=coord)
        np.testing.assert_array_equal(
            np.asarray(target.sd["w"]), np.full((8,), rank, dtype=np.float32)
        )

    _run_world(world, restore_worker)


def test_sync_take_small_manifests_stay_on_kv_route(tmp_path, monkeypatch):
    """Below the threshold the KV all-gather (one storage write total,
    by rank 0) is still the commit path — storage markers are overhead
    for kilobyte manifests."""
    calls = []
    real = snapmod._acommit_via_storage

    async def spy(*args, **kwargs):  # pragma: no cover - must not run
        calls.append(args[1])
        return await real(*args, **kwargs)

    monkeypatch.setattr(snapmod, "_acommit_via_storage", spy)
    path = str(tmp_path / "snap")

    def worker(coord, rank):
        Snapshot.take(
            path,
            {"m": _Holder({"w": np.arange(4, dtype=np.float32)})},
            coord=coord,
        )

    _run_world(2, worker)
    assert calls == []


def _rank_manifest_7b(rank, world, n_arrays=800):
    """Per-rank slice of the 7B-FSDP shape from
    test_manifest_scales_to_7b_fsdp_shape: 800 arrays, world shards each
    -> 51,200 shard entries globally at world 64."""
    m = {}
    rows = 4096
    per = rows // world
    for i in range(n_arrays):
        m[f"model/layer{i // 16}/param_{i}"] = ShardedArrayEntry(
            dtype="float32",
            shape=[rows, 2048],
            shards=[
                Shard(
                    offsets=[rank * per, 0],
                    sizes=[per, 2048],
                    array=ArrayEntry(
                        location=(
                            f"sharded/model/layer{i // 16}/"
                            f"param_{i}_{rank * per}_0"
                        ),
                        serializer="raw",
                        dtype="float32",
                        shape=[per, 2048],
                        replicated=False,
                        checksum="crc32:deadbeef",
                    ),
                )
            ],
        )
    return m


def _measure_storage_commit(world):
    """Wall-clock of the storage-marker commit segment alone (writes are
    already done at this point in a real take)."""
    shared = {}
    manifests = [_rank_manifest_7b(r, world) for r in range(world)]

    def worker(coord, rank):
        storage = MemoryStoragePlugin(shared)
        take_id = coord.broadcast_object(
            "nonce-7b" if rank == 0 else None, src=0
        )
        t0 = time.monotonic()
        asyncio.run(
            snapmod._acommit_via_storage(
                storage, rank, world, manifests[rank], take_id
            )
        )
        coord.barrier()
        return time.monotonic() - t0

    times = _run_world(world, worker)
    meta = SnapshotMetadata.from_yaml(
        snapmod._decode_metadata_doc(shared[".snapshot_metadata"])
    )
    assert len(meta.manifest) == world * 800
    assert not [k for k in shared if k.startswith(".completed/")]
    return max(times)


def test_sync_commit_scales_to_7b_world64():
    """VERDICT r2 ask #2: the sync commit must hold 64 ranks x 7B-shaped
    manifests. The storage route is O(world) marker ops; the whole
    commit — 64 markers written, polled, parsed, merged (51,200 shard
    entries), metadata serialized and written — must land in interactive
    time even on a loaded 1-core CI host (bound ~6x the measured median;
    see docs/design.md for the numbers)."""
    elapsed = _measure_storage_commit(world=64)
    assert elapsed < 90.0, f"world-64 7B commit took {elapsed:.1f}s"


def test_sync_commit_storage_route_world8_and_16():
    """Smaller-world commits stay fast, and doubling world must not blow
    the commit up quadratically-or-worse (measured ~0.5s/1.4s; the ratio
    guard is generous because shared CI hosts are noisy)."""
    t8 = _measure_storage_commit(world=8)
    t16 = _measure_storage_commit(world=16)
    assert t8 < 30.0 and t16 < 45.0
    assert t16 < max(8 * t8, 10.0), f"world 8->16 blew up: {t8:.2f}s -> {t16:.2f}s"


def test_commit_marker_collection_names_every_straggler():
    """If some ranks never write their completion marker (crashed
    mid-take), the commit poll times out with an error naming EVERY
    straggler — at pod scale "ranks 2 and 3" localizes the failure,
    "rank 2" alone does not. Exercised for the sync storage-route via
    the shared _acommit_via_storage collection helper."""
    import pytest

    shared = {}
    storage = MemoryStoragePlugin(shared)
    world = 4
    # Ranks 0 and 1 committed (markers written directly — rank 0's
    # _acommit_via_storage would poll for everyone); 2 and 3 crashed.
    for rank in (0, 1):
        marker = snapmod.IOReq(path=f".completed/nonce-x/{rank}")
        marker.buf.write(
            snapmod._encode_metadata_doc(
                SnapshotMetadata(
                    version="v",
                    world_size=world,
                    manifest={},
                    take_id="nonce-x",
                ).to_yaml()
            )
        )
        asyncio.run(storage.write(marker))

    with pytest.raises(TimeoutError) as exc_info:
        asyncio.run(
            snapmod._collect_completion_manifests(
                storage, world, "nonce-x", timeout_s=0.5
            )
        )
    message = str(exc_info.value)
    assert "[2, 3]" in message
    assert "NOT committed" in message


def test_full_take_restore_at_world_64():
    """Whole-protocol integration at pod width: 64 thread-ranks run a
    COMPLETE take (key gather, replicated negotiation + LPT striping,
    barriers, commit) and an elastic restore, against one shared
    memory:// bucket. Guards against O(world^2) surprises anywhere in
    the protocol, not just the manifest transport."""
    from torchsnapshot_tpu.storage_plugin import _MEMORY_STORES

    world = 64
    # memory:// buckets are process-shared by path — every thread-rank's
    # plugin instance resolves to this dict.
    shared = _MEMORY_STORES.setdefault("w64", {})
    shared.clear()
    t0 = time.monotonic()

    def worker(coord, rank):
        state = {
            "shared_w": np.arange(256, dtype=np.float32),  # replicated
            "mine": np.full((16,), rank, dtype=np.float32),  # per-rank
        }
        Snapshot.take(
            "memory://w64",
            {"m": _Holder(state)},
            coord=coord,
            replicated=["m/shared_w"],
        )

    _run_world(world, worker, timeout=240)
    take_s = time.monotonic() - t0

    meta = SnapshotMetadata.from_yaml(
        snapmod._decode_metadata_doc(shared[".snapshot_metadata"])
    )
    assert meta.world_size == world
    # Replicated entry resolvable by every rank; exactly one payload.
    locs = {
        e.location
        for p, e in meta.manifest.items()
        if p.endswith("/m/shared_w")
    }
    assert len(locs) == 1
    # Per-rank payloads all present.
    assert all(f"{r}/m/mine" in shared for r in range(world))

    # Elastic restore at world 4 (shrink 16x): replicated available
    # everywhere, per-rank values resolve for surviving ranks.
    def restore_worker(coord, rank):
        target = _Holder(
            {
                "shared_w": np.zeros((256,), dtype=np.float32),
                "mine": np.zeros((16,), dtype=np.float32),
            }
        )
        Snapshot("memory://w64").restore({"m": target}, coord=coord)
        np.testing.assert_array_equal(
            target.sd["shared_w"], np.arange(256, dtype=np.float32)
        )
        np.testing.assert_array_equal(
            target.sd["mine"], np.full((16,), rank, dtype=np.float32)
        )

    _run_world(4, restore_worker, timeout=240)
    # Generous absolute bound: 64 thread-ranks x full protocol on a
    # loaded 1-core host (measured ~10-20s; the bound catches
    # quadratic blowups, which land in minutes).
    assert take_s < 150.0, f"world-64 take took {take_s:.1f}s"
