"""Codec tests (reference analog: tests/test_flatten.py:47-112)."""

from collections import OrderedDict

import numpy as np
import pytest

from torchsnapshot_tpu.flatten import flatten, inflate
from torchsnapshot_tpu.manifest import (
    DictEntry,
    ListEntry,
    OrderedDictEntry,
    TupleEntry,
)


def test_flatten_basic():
    obj = {"foo": [1, 2, OrderedDict(bar=3, baz=4)]}
    manifest, flattened = flatten(obj, prefix="my/prefix")
    assert isinstance(manifest["my/prefix"], DictEntry)
    assert isinstance(manifest["my/prefix/foo"], ListEntry)
    assert isinstance(manifest["my/prefix/foo/2"], OrderedDictEntry)
    assert manifest["my/prefix/foo/2"].keys == ["bar", "baz"]
    assert flattened == {
        "my/prefix/foo/0": 1,
        "my/prefix/foo/1": 2,
        "my/prefix/foo/2/bar": 3,
        "my/prefix/foo/2/baz": 4,
    }


def test_round_trip():
    obj = {
        "a": {"b": [1, 2.5, "x"], "c": OrderedDict(d=None, e=True)},
        "f": [[1], [2, [3]]],
        "g": (1, (2, 3)),
    }
    manifest, flattened = flatten(obj, prefix="p")
    restored = inflate(manifest, flattened, prefix="p")
    assert restored == obj
    assert type(restored["g"]) is tuple
    assert type(restored["g"][1]) is tuple
    assert type(restored["a"]["c"]) is OrderedDict


def test_round_trip_no_prefix():
    obj = {"x": [10, 20]}
    manifest, flattened = flatten(obj)
    assert inflate(manifest, flattened) == obj


def test_long_list_order():
    # The reference scrambles lists with >= 10 elements (lexicographic sort
    # in inflate, flatten.py:106-116); ours must not.
    obj = {"xs": list(range(25))}
    manifest, flattened = flatten(obj, prefix="t")
    assert inflate(manifest, flattened, prefix="t") == obj


def test_int_keys():
    obj = {0: "a", 1: "b", "k": {7: [1]}}
    manifest, flattened = flatten(obj, prefix="t")
    restored = inflate(manifest, flattened, prefix="t")
    assert restored == obj
    assert set(restored.keys()) == {0, 1, "k"}


def test_colliding_keys_not_flattened():
    obj = {"outer": {1: "a", "1": "b"}}
    manifest, flattened = flatten(obj, prefix="t")
    # Colliding str() representations: the inner dict is kept as a leaf.
    assert flattened["t/outer"] == {1: "a", "1": "b"}


def test_slash_keys_not_flattened():
    obj = {"outer": {"a/b": 1}}
    manifest, flattened = flatten(obj, prefix="t")
    assert flattened["t/outer"] == {"a/b": 1}


def test_non_str_int_keys_not_flattened():
    obj = {"outer": {(1, 2): "x"}}
    _, flattened = flatten(obj, prefix="t")
    assert flattened["t/outer"] == {(1, 2): "x"}


def test_array_leaves_pass_through():
    arr = np.arange(6).reshape(2, 3)
    obj = {"w": arr, "nested": [arr]}
    manifest, flattened = flatten(obj, prefix="t")
    assert flattened["t/w"] is arr
    assert flattened["t/nested/0"] is arr
    restored = inflate(manifest, flattened, prefix="t")
    np.testing.assert_array_equal(restored["w"], arr)


def test_tuple_entry_type():
    manifest, _ = flatten({"t": (1, 2)}, prefix="x")
    assert isinstance(manifest["x/t"], TupleEntry)


def test_empty_containers():
    obj = {"e1": {}, "e2": [], "e3": ()}
    manifest, flattened = flatten(obj, prefix="t")
    restored = inflate(manifest, flattened, prefix="t")
    assert restored == obj
    assert type(restored["e3"]) is tuple


def test_inflate_missing_container_entry():
    with pytest.raises(RuntimeError, match="Container entry is absent"):
        inflate({}, {"t/a/b": 1}, prefix="t")
