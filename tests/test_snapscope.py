"""snapscope: runtime sampler, durability-lag (RPO) accounting, the SLO
burn-rate engine, and the unified ops view.

Covers the live-ops acceptance criteria: ``introspect()`` consistency,
the end-to-end durability-lag chain (per-object histogram → watermark →
flight report → ledger ``tierdown`` event → doctor rule → SLO exit
code), the ``slow_drain`` faultline schedule firing the alerts
deterministically, sampler crash isolation + statusfile/scope-object
lifecycle (never survive delete / detected crash), tier-down progress
records, and the ops CLI exit-code contract (live backlog drains to
zero → 0; stranded drain → nonzero naming the root).
"""

import asyncio
import contextlib
import io as _io
import json
import time
import uuid

import pytest

import jax.numpy as jnp

from torchsnapshot_tpu import CheckpointManager, Snapshot, StateDict, hottier
from torchsnapshot_tpu import faultline as fl
from torchsnapshot_tpu import telemetry
from torchsnapshot_tpu.io_types import IOReq, io_payload
from torchsnapshot_tpu.manager import _step_dir
from torchsnapshot_tpu.storage_plugin import url_to_storage_plugin
from torchsnapshot_tpu.telemetry import metrics as _m
from torchsnapshot_tpu.telemetry import ledger as runledger
from torchsnapshot_tpu.telemetry import ops as scope_ops
from torchsnapshot_tpu.telemetry import sampler as scope_sampler
from torchsnapshot_tpu.telemetry import slo as scope_slo
from torchsnapshot_tpu.telemetry import timeline, watch
from torchsnapshot_tpu.telemetry.doctor import diagnose_report
from torchsnapshot_tpu.telemetry.metrics import REGISTRY

pytestmark = pytest.mark.faultline


@pytest.fixture(autouse=True)
def _fresh_tier():
    hottier.disable_hot_tier(flush=False)
    hottier.reset_hot_tier()
    yield
    hottier.disable_hot_tier(flush=False)
    hottier.reset_hot_tier()


def _state(v, n=512, keys=("w",)):
    return {"s": StateDict(**{k: jnp.full((n,), float(v)) for k in keys})}


def _mem_root(tag):
    return f"memory://scope-{tag}-{uuid.uuid4().hex[:10]}/snap"


def _objects(url):
    storage = url_to_storage_plugin(url)
    try:
        return sorted(asyncio.run(storage.list_prefix("")) or [])
    finally:
        storage.close()


def _read_json(url, path):
    storage = url_to_storage_plugin(url)
    try:
        io_req = IOReq(path=path)
        asyncio.run(storage.read(io_req))
        return json.loads(bytes(io_payload(io_req)).decode("utf-8"))
    finally:
        storage.close()


def _run_cli(main, argv):
    buf = _io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(argv)
    return rc, buf.getvalue()


# ------------------------------------------------- introspect / at-risk


def test_introspect_tracks_backlog_and_at_risk_bytes():
    root = _mem_root("intro")
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        Snapshot.take(root, _state(7))
        intro = hottier.introspect()
        assert intro["queue_depth"] >= 1
        assert intro["pending_objects"] >= 1
        assert intro["at_risk_bytes"] > 0
        assert intro["oldest_pending_age_s"] is not None
        assert root in intro["at_risk_by_root"]
        root_view = intro["roots"][root]
        assert root_view["committed"] and not root_view["tierdown_done"]
        assert root_view["pending_bytes"] == intro["at_risk_bytes"]
        # Per-host occupancy reflects the k replicas.
        assert sum(
            h["used_bytes"] for h in intro["hosts"].values()
        ) == 2 * intro["at_risk_bytes"]
        hottier.drain_now()
        intro = hottier.introspect()
        assert intro["queue_depth"] == 0
        assert intro["at_risk_bytes"] == 0
        assert intro["roots"][root]["tierdown_done"]
        assert intro["roots"][root]["durability_lag_s"] is not None


def test_introspect_at_risk_age_excludes_uncommitted_roots():
    """The RPO-relevant age (oldest_at_risk_age_s) counts COMMITTED
    roots only: an in-flight take's old pending object must not read
    as an acked checkpoint's exposure window (review fix)."""
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual") as rt:
        rt.enqueue_drain("memory://scope-phantom/run", "0/s/w")
        intro = hottier.introspect()
        assert intro["oldest_pending_age_s"] is not None
        assert intro["oldest_at_risk_age_s"] is None  # nothing committed
        assert intro["at_risk_bytes"] == 0
        # The live rule stays silent on it, whatever the budget.
        sample = {"hot_tier": intro}
        assert (
            scope_slo.rule_durability_lag_live([sample], budget_s=1e-9)
            is None
        )
        hottier.reset_pending()


def test_slo_live_rules_evaluated_per_rank():
    """A stranded rank must surface even when a healthier rank's
    samples would otherwise shadow it in a flattened series (review
    fix: evaluate_live_by_rank)."""
    stranded_sample = {
        "hot_tier": {
            "queue_depth": 0,
            "inflight": 0,
            "oldest_pending_age_s": None,
            "oldest_at_risk_age_s": None,
            "at_risk_bytes": 64,
            "at_risk_by_root": {},
            "stranded_objects": 1,
            "stranded_roots": ["/run/step-3"],
        }
    }
    healthy_sample = {
        "hot_tier": {
            "queue_depth": 0,
            "inflight": 0,
            "oldest_pending_age_s": None,
            "oldest_at_risk_age_s": None,
            "at_risk_bytes": 0,
            "at_risk_by_root": {},
            "stranded_objects": 0,
            "stranded_roots": [],
        }
    }
    findings = scope_slo.evaluate_live_by_rank(
        {0: [stranded_sample], 1: [healthy_sample]}
    )
    assert any(
        f.rule == "stranded-drains" and f.evidence.get("rank") == 0
        for f in findings
    ), findings


def test_introspect_none_when_disabled():
    assert hottier.introspect() is None
    assert hottier.durability_lag_s("/nowhere") is None


# -------------------------------------------- durability lag, end to end


def test_durability_lag_watermark_report_metrics_ledger():
    """The acceptance chain: per-object histogram + per-take value in
    the watermark, the flight report, the metrics, and the ledger."""
    telemetry.reset()
    root = _mem_root("lag")
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        Snapshot.take(root, _state(3, keys=("a", "b")))
        hottier.drain_now()
        lag = hottier.durability_lag_s(root)
        assert lag is not None and lag >= 0
    # Watermark carries the per-take window.
    td = _read_json(root, ".tierdown")
    assert td["durability_lag_s"] == pytest.approx(lag)
    assert td["drained_objects"] == 2
    # The committed report was back-filled.
    report = _read_json(root, ".report.json")
    assert report["durability_lag_s"] == pytest.approx(lag)
    # Metrics: one per-object observation per drained object, one
    # per-take observation.
    snap = telemetry.snapshot()
    assert snap[_m.HOT_TIER_OBJECT_LAG]["count"] == 2
    assert snap[_m.HOT_TIER_TAKE_LAG]["count"] == 1
    # Ledger: the take digest holds null (window still open at commit);
    # the drain appended a tierdown event record that closes it.
    records, _ = runledger.read_records(root)
    takes = [r for r in records if r["kind"] == "take"]
    drains = [r for r in records if r["kind"] == "tierdown"]
    assert takes and takes[0]["durability_lag_s"] is None
    assert drains and drains[0]["durability_lag_s"] == pytest.approx(lag)
    assert drains[0]["drained_objects"] == 2


def test_write_through_objects_observe_zero_lag():
    telemetry.reset()
    root = _mem_root("wt")
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        hottier.kill_host(1)  # k unreachable: every put degrades
        Snapshot.take(root, _state(5))
        snap = telemetry.snapshot()
        # Durable at ack: the object-lag histogram records ~0.
        hist = snap[_m.HOT_TIER_OBJECT_LAG]
        assert hist["count"] >= 1
        assert hist["sum"] == pytest.approx(0.0, abs=0.05)


# ------------------------------------------------ slow_drain / doctor / SLO


def test_slow_drain_trips_doctor_rule_and_slo_exit(monkeypatch):
    """Acceptance: an injected ``slow_drain`` schedule deterministically
    fires the ``durability-lag-above-budget`` doctor rule and the SLO
    engine's nonzero exit."""
    monkeypatch.setenv(scope_slo.DURABILITY_LAG_ENV_VAR, "0.05")
    root = _mem_root("slow")
    sched = fl.FaultSchedule().slow_drain(seconds=0.12)
    with fl.inject(sched):
        with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
            Snapshot.take(root, _state(9))
            hottier.drain_now()
    report = _read_json(root, ".report.json")
    assert report["durability_lag_s"] > 0.1
    rules = [f.rule for f in diagnose_report(report)]
    assert "durability-lag-above-budget" in rules
    rc, out = _run_cli(scope_slo.main, [root])
    assert rc == 1
    assert "durability-lag-above-budget" in out
    # Without the schedule the same take stays inside the budget.
    root2 = _mem_root("fast")
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        Snapshot.take(root2, _state(9))
        hottier.drain_now()
    report2 = _read_json(root2, ".report.json")
    assert "durability-lag-above-budget" not in [
        f.rule for f in diagnose_report(report2)
    ]
    rc2, _ = _run_cli(scope_slo.main, [root2])
    assert rc2 == 0


def test_slo_self_test_and_burn_rate_windows():
    assert scope_slo._self_test() == 0
    # Burn-rate shape: one blip in a healthy history never breaches.
    obj = scope_slo.Objective(
        name="durability-lag",
        label="lag",
        kinds=("tierdown",),
        field="durability_lag_s",
        target=1.0,
        direction="max",
    )
    verdict = scope_slo.burn_rates([0.1] * 19 + [9.0], obj)
    assert not verdict["breached"]
    assert verdict["windows"][0]["burn_rate"] == pytest.approx(0.8)


def test_timeline_sentinel_flags_durability_lag_regression(tmp_path):
    def rec(i, lag):
        return {
            "format_version": 1,
            "kind": "tierdown",
            "ts_epoch_s": 1e9 + i,
            "path": f"/r/step-{i}",
            "step": i,
            "take_id": None,
            "durability_lag_s": lag,
            "drained_objects": 4,
            "write_through_objects": 0,
        }

    records = [rec(i, 0.5) for i in range(8)] + [rec(8, 60.0)]
    path = tmp_path / "ledger.jsonl"
    path.write_text(
        "".join(runledger.encode_line(r) + "\n" for r in records)
    )
    rc, out = _run_cli(timeline.main, [str(path)])
    assert rc == 1
    assert "durability lag s" in out and "step 8" in out


# ----------------------------------------------------------- the sampler


def test_sampler_ring_statusfile_and_fields(tmp_path):
    root = _mem_root("sampler")
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        Snapshot.take(root, _state(1))
        s = scope_sampler.RuntimeSampler(
            rank=0, statusfile_dir=str(tmp_path), ring=4
        )
        for _ in range(6):
            assert s.sample_once() is not None
        assert len(s.samples()) == 4  # ring is bounded
        latest = s.latest()
        assert latest["hot_tier"]["queue_depth"] >= 1
        assert latest["hot_tier"]["at_risk_bytes"] > 0
        assert set(latest["scheduler"]) == {"write", "read"}
        hottier.drain_now()
    by_rank = scope_sampler.collect_statusfiles(str(tmp_path))
    assert 0 in by_rank and len(by_rank[0]) == 6
    assert by_rank[0][-1]["seq"] == 6


def test_sampler_thread_crash_isolated_and_take_unaffected(
    tmp_path, monkeypatch
):
    """A sampler-thread exception never fails or blocks a take."""

    def _boom():
        raise RuntimeError("sampler injected failure")

    # The sampler reads the tier through the package-level API.
    monkeypatch.setattr(hottier, "introspect", _boom)
    s = scope_sampler.RuntimeSampler(
        rank=0, interval_s=0.05, statusfile_dir=str(tmp_path)
    )
    s.start()
    try:
        before = s.error_count
        root = str(tmp_path / "snap")
        snap = Snapshot.take(root, _state(2))
        target = _state(0)
        snap.restore(target)
        time.sleep(0.2)
        assert s.error_count > before  # it kept running AND kept failing
        assert REGISTRY.counter(_m.SAMPLER_ERRORS).value > 0
    finally:
        s.stop(final_sample=False)
    # The take committed untouched.
    assert float(target["s"]["w"][0]) == 2.0


def test_sampler_scope_objects_never_survive_delete(tmp_path):
    root = _mem_root("scopegc")
    Snapshot.take(root, _state(4))
    s = scope_sampler.RuntimeSampler(rank=0, storage_url=root)
    assert s.sample_once() is not None
    s.stop(final_sample=False)
    assert ".scope/rank0" in _objects(root)
    Snapshot(root).delete(sweep=True)
    assert _objects(root) == []


def test_reconcile_sweeps_crashed_scope_and_sampler_statusfiles(
    tmp_path, monkeypatch
):
    """A detected crash's scope debris is swept (age-guarded) by
    reconcile's debris pass, exactly like progress records."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    base = str(tmp_path / "run")
    mgr = CheckpointManager(base, max_to_keep=None)
    mgr.save(0, _state(1))
    step_root = _step_dir(base, 0)
    s = scope_sampler.RuntimeSampler(rank=0, storage_url=step_root)
    assert s.sample_once() is not None  # "crashed" publisher's debris
    s.stop(final_sample=False)
    assert ".scope/rank0" in _objects(step_root)
    mgr.reconcile()
    assert ".scope/rank0" not in _objects(step_root)
    # The committed snapshot itself is untouched.
    assert ".snapshot_metadata" in _objects(step_root)


def test_reconcile_age_guard_spares_young_scope_records(
    tmp_path, monkeypatch
):
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "3600")
    base = str(tmp_path / "run")
    mgr = CheckpointManager(base, max_to_keep=None)
    mgr.save(0, _state(1))
    step_root = _step_dir(base, 0)
    s = scope_sampler.RuntimeSampler(rank=0, storage_url=step_root)
    assert s.sample_once() is not None
    s.stop(final_sample=False)
    mgr.reconcile()
    assert ".scope/rank0" in _objects(step_root)  # young: spared


# ----------------------------------------- tier-down progress records


def test_background_drain_publishes_tierdown_progress(monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_PROGRESS_INTERVAL_S", "0")
    root = _mem_root("tdprog")
    sched = fl.FaultSchedule().slow_drain(seconds=0.15)
    with fl.inject(sched):
        with hottier.hot_tier(rank=0, world=2, k=2, drain="background"):
            Snapshot.take(root, _state(1, keys=("a", "b", "c")))
            seen = None
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                if ".progress/tierdown/0" in _objects(root):
                    seen = _read_json(root, ".progress/tierdown/0")
                    break
                time.sleep(0.01)
            assert seen is not None, "no tierdown progress record"
            assert seen["phase"] == "tierdown"
            assert seen["kind"] == "tierdown"
            assert seen["bytes_total"] > 0
            # watch renders the drain as a live in-flight operation.
            rc, out = _run_cli(watch.main, [root, "--stale-after", "60"])
            assert rc == 0
            assert "tierdown" in out
            assert hottier.wait_drained(timeout_s=30)
    # Retired with the watermark; never outlives the drain.
    objs = _objects(root)
    assert ".tierdown" in objs
    assert ".progress/tierdown/0" not in objs


def test_manual_drain_publishes_no_progress_records():
    """Manual mode is the fault harness's deterministic-op-stream mode:
    no time-rate-limited publications may enter the op stream."""
    root = _mem_root("manual")
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        Snapshot.take(root, _state(1))
        hottier.drain_now()
    assert not [o for o in _objects(root) if o.startswith(".progress/")]


# ------------------------------------------------------------ ops view


def test_ops_cli_live_backlog_drains_to_zero_and_exits_zero(monkeypatch):
    """Acceptance: against a live async-acked take with the hot tier
    on, the view shows the drain backlog and exits 0; after the drain
    the backlog reads zero and it still exits 0."""
    root = _mem_root("opslive")
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        Snapshot.take(root, _state(6, keys=("a", "b")))
        rc, out = _run_cli(scope_ops.main, [root])
        assert rc == 0, out
        assert "drain backlog 2" in out
        assert "at-risk" in out
        hottier.drain_now()
        rc, out = _run_cli(scope_ops.main, [root])
        assert rc == 0, out
        assert "drain backlog 0" in out


def test_ops_cli_live_async_take_background_drain():
    """The full acceptance shape: a LIVE async take with the hot tier
    on (background drain slowed by ``slow_drain``) — ops exits 0 while
    the backlog is visible, and again once it drained to zero."""
    root = _mem_root("opsasync")
    sched = fl.FaultSchedule().slow_drain(seconds=0.5)
    with fl.inject(sched):
        with hottier.hot_tier(rank=0, world=2, k=2, drain="background"):
            pending = Snapshot.async_take(
                root, _state(4, keys=("a", "b", "c"))
            )
            pending.wait(timeout_s=60)
            # Committed (acked) — but tier-down is still running: the
            # ops view must show the live backlog and stay healthy.
            rc, out = _run_cli(scope_ops.main, [root])
            assert rc == 0, out
            assert "drain backlog" in out
            intro = hottier.introspect()
            assert intro["at_risk_bytes"] > 0  # exposure window open
            assert hottier.wait_drained(timeout_s=60)
            rc, out = _run_cli(scope_ops.main, [root])
            assert rc == 0, out
            assert "drain backlog 0" in out
            assert hottier.introspect()["at_risk_bytes"] == 0


def test_ops_cli_stranded_drain_exits_nonzero_naming_root():
    root = _mem_root("opsstrand")
    sched = fl.FaultSchedule().permanent(op="write", path="0/s/w")
    with fl.inject(sched):
        with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
            Snapshot.take(root, _state(8))
            hottier.drain_now()  # attempts exhaust; object stranded
            assert hottier.introspect()["stranded_objects"] == 1
            rc, out = _run_cli(scope_ops.main, [root])
            assert rc == 1, out
            assert "stranded-drains" in out
            assert root in out  # names the root
    # JSON mode carries the same verdict for machines.
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        rc, out = _run_cli(scope_ops.main, [root, "--json"])
        doc = json.loads(out)
        assert rc == 0  # fresh runtime: nothing stranded anymore
        assert doc["critical"] == []


def test_ops_cli_dir_mode_reads_statusfiles(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_PROGRESS_DIR", str(tmp_path))
    root = _mem_root("opsdir")
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        Snapshot.take(root, _state(2))
        s = scope_sampler.RuntimeSampler(
            rank=0, statusfile_dir=str(tmp_path)
        )
        assert s.sample_once() is not None
        hottier.drain_now()
    # Tier off: dir mode must read state from the statusfiles alone.
    rc, out = _run_cli(scope_ops.main, [str(tmp_path)])
    assert rc == 0, out
    assert "drain backlog" in out
    # The progress statusfile the take wrote renders too.
    assert "take" in out


def test_ops_cli_bad_path_exits_two(tmp_path):
    rc, _ = _run_cli(
        scope_ops.main, [str(tmp_path / "missing-dir-or-snap")]
    )
    assert rc == 2


def test_slo_live_rules_via_sampler_samples(monkeypatch):
    monkeypatch.setenv(scope_slo.DURABILITY_LAG_ENV_VAR, "30")
    root = _mem_root("live")
    sched = fl.FaultSchedule().permanent(op="write", path="0/s/w")
    with fl.inject(sched):
        with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
            Snapshot.take(root, _state(1))
            hottier.drain_now()
            s = scope_sampler.RuntimeSampler(rank=0)
            sample = s.sample_once()
            findings = scope_slo.evaluate_live([sample])
            assert any(
                f.rule == "stranded-drains" and root in f.title
                for f in findings
            )


# ------------------------------------------------ scheduler budget gauges


def test_scheduler_budget_gauges_reset_after_pipeline(tmp_path):
    telemetry.reset()
    root = str(tmp_path / "snap")
    snap = Snapshot.take(root, _state(5, n=4096))
    snap.restore(_state(0, n=4096))
    metrics = telemetry.snapshot()
    for pipeline in ("write", "read"):
        key = f'{_m.SCHED_BUDGET_IN_USE}{{pipeline="{pipeline}"}}'
        assert metrics[key] == 0.0  # reset on pipeline exit
        stalled = f'{_m.SCHED_BUDGET_STALLED}{{pipeline="{pipeline}"}}'
        assert metrics[stalled] == 0.0


# ------------------------------------------------------- bench plumbing


def test_bench_compare_gates_hot_tier_keys():
    import importlib.util
    import os as _os

    spec = importlib.util.spec_from_file_location(
        "bench_compare",
        _os.path.join(
            _os.path.dirname(__file__), "..", "tools", "bench_compare.py"
        ),
    )
    bc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bc)
    assert bc._self_test() == 0

    base = {
        "value": 1.0,
        "hot_tier": {"hot_vs_durable": 7.5, "durability_lag_s": 0.8},
        "every_step": {"hot": {"overhead_pct": 1.9}},
    }
    _, reg = bc.compare(
        base,
        dict(base, hot_tier={"hot_vs_durable": 7.5, "durability_lag_s": 2.0}),
        0.2,
    )
    assert reg and "durability lag" in reg[0]
