"""Replication glob semantics over threaded multi-rank coordinators
(reference analog: tests/test_replication_glob.py + tests/test_ddp.py)."""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.coord import DictStore, StoreCoordinator
from torchsnapshot_tpu.manifest import get_available_entries, is_replicated
from torchsnapshot_tpu.storage_plugin import _MEMORY_STORES


def _run_world(world, fn):
    store = DictStore()
    errors = []
    results = [None] * world

    def worker(rank):
        try:
            coord = StoreCoordinator(store, rank, world, timeout_s=60)
            results[rank] = fn(coord, rank)
        except BaseException as e:  # pragma: no cover
            import traceback

            errors.append((rank, e, traceback.format_exc()))

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    if errors:
        raise AssertionError(f"rank {errors[0][0]} failed:\n{errors[0][2]}")
    return results


class _TestStateful:
    """Fixed mixed-container state (reference test_replication_glob.py:22-32)."""

    def __init__(self, seed=0):
        rng = np.random.RandomState(seed)
        self.sd = {
            "foo": jnp.asarray(rng.randn(4, 4), dtype=jnp.float32),
            "bar": jnp.asarray(rng.randn(2, 2), dtype=jnp.float32),
            "baz": {"qux": jnp.asarray(rng.randn(3), dtype=jnp.float32)},
        }

    def state_dict(self):
        return self.sd

    def load_state_dict(self, sd):
        self.sd = sd


def test_replicated_glob_all(tmp_path):
    """replicated=["**"]: every leaf replicated, writes striped."""
    path = str(tmp_path / "snap")

    def worker(coord, rank):
        app = {"st": _TestStateful(seed=0)}  # same state on all ranks (DDP)
        Snapshot.take(path, app, coord=coord, replicated=["**"])
        return None

    _run_world(4, worker)

    snap = Snapshot(path)
    manifest = snap.get_manifest()
    leaf_paths = [p for p in manifest if p.endswith(("foo", "bar", "qux"))]
    assert leaf_paths
    for p in manifest:
        entry = manifest[p]
        if hasattr(entry, "location") and p.endswith(("foo", "bar", "qux")):
            assert entry.replicated
            assert entry.location.startswith("replicated/")
    # Striping: each replicated object written exactly once on disk.
    root = tmp_path / "snap"
    assert (root / "replicated" / "st" / "foo").exists()

    # Any single process (different world size!) can restore everything.
    target = _TestStateful(seed=9)
    Snapshot(path).restore({"st": target})
    np.testing.assert_array_equal(
        np.asarray(target.sd["foo"]), np.asarray(_TestStateful(seed=0).sd["foo"])
    )


def test_replicated_glob_subset(tmp_path):
    path = str(tmp_path / "snap")

    def worker(coord, rank):
        app = {"st": _TestStateful(seed=0)}
        Snapshot.take(path, app, coord=coord, replicated=["st/baz/**"])

    _run_world(2, worker)
    manifest = Snapshot(path).get_manifest()
    assert manifest["0/st/baz/qux"].replicated
    assert not manifest["0/st/foo"].replicated
    avail5 = get_available_entries(manifest, 5)
    assert "st/baz/qux" in avail5
    assert "st/foo" not in avail5


def test_rank_divergent_globs_intersect(tmp_path):
    """Ranks passing different globs degrade to the intersection
    (reference test_replication_glob.py:103-112)."""
    path = str(tmp_path / "snap")

    def worker(coord, rank):
        app = {"st": _TestStateful(seed=0)}
        globs = ["st/foo", "st/bar"] if rank == 0 else ["st/foo"]
        Snapshot.take(path, app, coord=coord, replicated=globs)

    _run_world(2, worker)
    manifest = Snapshot(path).get_manifest()
    assert manifest["0/st/foo"].replicated
    assert not manifest["0/st/bar"].replicated


def test_per_rank_state(tmp_path):
    """Without replication, each rank's state is private and restorable
    only at the same world size (reference test_ddp.py semantics)."""
    path = str(tmp_path / "snap")

    def take_worker(coord, rank):
        app = {"st": StateDict(val=rank * 100)}
        Snapshot.take(path, app, coord=coord)

    _run_world(3, take_worker)

    def restore_worker(coord, rank):
        app = {"st": StateDict(val=-1)}
        Snapshot(path).restore(app, coord=coord)
        return app["st"]["val"]

    assert _run_world(3, restore_worker) == [0, 100, 200]


def test_metadata_world_size(tmp_path):
    path = str(tmp_path / "snap")

    def worker(coord, rank):
        Snapshot.take(path, {"st": StateDict(x=1)}, coord=coord)

    _run_world(2, worker)
    from torchsnapshot_tpu.snapshot import SNAPSHOT_METADATA_FNAME

    meta_file = tmp_path / "snap" / SNAPSHOT_METADATA_FNAME
    assert meta_file.exists()
    from torchsnapshot_tpu.manifest import SnapshotMetadata

    md = SnapshotMetadata.from_yaml(meta_file.read_text())
    assert md.world_size == 2
