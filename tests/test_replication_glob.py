"""Replication glob semantics over threaded multi-rank coordinators
(reference analog: tests/test_replication_glob.py + tests/test_ddp.py)."""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.coord import DictStore, StoreCoordinator
from torchsnapshot_tpu.manifest import get_available_entries, is_replicated
from torchsnapshot_tpu.storage_plugin import _MEMORY_STORES


def _run_world(world, fn):
    store = DictStore()
    errors = []
    results = [None] * world

    def worker(rank):
        try:
            coord = StoreCoordinator(store, rank, world, timeout_s=60)
            results[rank] = fn(coord, rank)
        except BaseException as e:  # pragma: no cover
            import traceback

            errors.append((rank, e, traceback.format_exc()))

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    if errors:
        raise AssertionError(f"rank {errors[0][0]} failed:\n{errors[0][2]}")
    return results


class _TestStateful:
    """Fixed mixed-container state (reference test_replication_glob.py:22-32)."""

    def __init__(self, seed=0):
        rng = np.random.RandomState(seed)
        self.sd = {
            "foo": jnp.asarray(rng.randn(4, 4), dtype=jnp.float32),
            "bar": jnp.asarray(rng.randn(2, 2), dtype=jnp.float32),
            "baz": {"qux": jnp.asarray(rng.randn(3), dtype=jnp.float32)},
        }

    def state_dict(self):
        return self.sd

    def load_state_dict(self, sd):
        self.sd = sd


def test_replicated_glob_all(tmp_path):
    """replicated=["**"]: every leaf replicated, writes striped."""
    path = str(tmp_path / "snap")

    def worker(coord, rank):
        app = {"st": _TestStateful(seed=0)}  # same state on all ranks (DDP)
        Snapshot.take(path, app, coord=coord, replicated=["**"])
        return None

    _run_world(4, worker)

    snap = Snapshot(path)
    manifest = snap.get_manifest()
    leaf_paths = [p for p in manifest if p.endswith(("foo", "bar", "qux"))]
    assert leaf_paths
    for p in manifest:
        entry = manifest[p]
        if hasattr(entry, "location") and p.endswith(("foo", "bar", "qux")):
            assert entry.replicated
            assert entry.location.startswith("replicated/")
    # Striping: each replicated object written exactly once on disk.
    root = tmp_path / "snap"
    assert (root / "replicated" / "st" / "foo").exists()

    # Any single process (different world size!) can restore everything.
    target = _TestStateful(seed=9)
    Snapshot(path).restore({"st": target})
    np.testing.assert_array_equal(
        np.asarray(target.sd["foo"]), np.asarray(_TestStateful(seed=0).sd["foo"])
    )


def test_replicated_glob_subset(tmp_path):
    path = str(tmp_path / "snap")

    def worker(coord, rank):
        app = {"st": _TestStateful(seed=0)}
        Snapshot.take(path, app, coord=coord, replicated=["st/baz/**"])

    _run_world(2, worker)
    manifest = Snapshot(path).get_manifest()
    assert manifest["0/st/baz/qux"].replicated
    assert not manifest["0/st/foo"].replicated
    avail5 = get_available_entries(manifest, 5)
    assert "st/baz/qux" in avail5
    assert "st/foo" not in avail5


def test_rank_divergent_globs_intersect(tmp_path):
    """Ranks passing different globs degrade to the intersection
    (reference test_replication_glob.py:103-112)."""
    path = str(tmp_path / "snap")

    def worker(coord, rank):
        app = {"st": _TestStateful(seed=0)}
        globs = ["st/foo", "st/bar"] if rank == 0 else ["st/foo"]
        Snapshot.take(path, app, coord=coord, replicated=globs)

    _run_world(2, worker)
    manifest = Snapshot(path).get_manifest()
    assert manifest["0/st/foo"].replicated
    assert not manifest["0/st/bar"].replicated


def test_per_rank_state(tmp_path):
    """Without replication, each rank's state is private and restorable
    only at the same world size (reference test_ddp.py semantics)."""
    path = str(tmp_path / "snap")

    def take_worker(coord, rank):
        app = {"st": StateDict(val=rank * 100)}
        Snapshot.take(path, app, coord=coord)

    _run_world(3, take_worker)

    def restore_worker(coord, rank):
        app = {"st": StateDict(val=-1)}
        Snapshot(path).restore(app, coord=coord)
        return app["st"]["val"]

    assert _run_world(3, restore_worker) == [0, 100, 200]


def test_metadata_world_size(tmp_path):
    path = str(tmp_path / "snap")

    def worker(coord, rank):
        Snapshot.take(path, {"st": StateDict(x=1)}, coord=coord)

    _run_world(2, worker)
    from torchsnapshot_tpu.snapshot import SNAPSHOT_METADATA_FNAME

    meta_file = tmp_path / "snap" / SNAPSHOT_METADATA_FNAME
    assert meta_file.exists()
    from torchsnapshot_tpu.manifest import SnapshotMetadata

    md = SnapshotMetadata.from_yaml(meta_file.read_text())
    assert md.world_size == 2


def test_size_balanced_striping_assignment():
    """Replicated-write ownership is size-balanced (greedy LPT), not
    count-round-robin: one huge leaf among many small ones must not give
    a single rank ~all the bytes (the reference's count-based striping
    does exactly that — its snapshot.py:353-358)."""
    from torchsnapshot_tpu.snapshot import _assign_replicated_owners

    # 1 GB + 100 x 1 MB over 4 ranks.
    sizes = {"big": 1 << 30}
    sizes.update({f"small{i:03d}": 1 << 20 for i in range(100)})
    owners = _assign_replicated_owners(sizes, 4)
    loads = [0, 0, 0, 0]
    for path, owner in owners.items():
        loads[owner] += sizes[path]
    # The big leaf lands alone on one rank; the other three share the
    # small ones — max load is the big leaf, min is ~33 MB, and crucially
    # no rank holds big + a meaningful share of smalls.
    assert max(loads) == 1 << 30
    assert sum(1 for load in loads if load > 1 << 30) == 0
    small_total = 100 * (1 << 20)
    others = sorted(loads)[:3]
    assert sum(others) == small_total
    assert max(others) - min(others) <= 2 * (1 << 20)  # near-even split

    # Count-round-robin for comparison: rank of "big" also gets ~25 of
    # the smalls — the property LPT removes.

    # Determinism: same inputs -> same map (every rank must agree).
    assert owners == _assign_replicated_owners(sizes, 4)

    # Equal sizes degrade to a balanced count split.
    eq = {f"p{i}": 100 for i in range(8)}
    owners_eq = _assign_replicated_owners(eq, 4)
    counts = [0] * 4
    for owner in owners_eq.values():
        counts[owner] += 1
    assert counts == [2, 2, 2, 2]

    # Zero-estimate paths (objects) spread by COUNT, not byte-load-min:
    # a single big array must not attract every object to the other
    # ranks' detriment.
    mixed = {"big": 10 << 20}
    mixed.update({f"obj{i}": 0 for i in range(10)})
    owners_mixed = _assign_replicated_owners(mixed, 2)
    obj_counts = [0, 0]
    for p, o in owners_mixed.items():
        if p != "big":
            obj_counts[o] += 1
    assert abs(obj_counts[0] - obj_counts[1]) <= 1, owners_mixed


def test_size_balanced_striping_end_to_end(tmp_path):
    """2-rank take with one big and many small replicated leaves: each
    rank's written payload bytes reflect size balancing, and the
    snapshot round-trips."""
    import threading

    import numpy as np

    from torchsnapshot_tpu.coord import DictStore, StoreCoordinator

    def worker(rank, store, errors):
        try:
            coord = StoreCoordinator(store, rank, 2, timeout_s=60)
            sd = {"big": np.zeros(1 << 18, dtype=np.float32)}  # 1 MiB
            for i in range(16):
                sd[f"s{i:02d}"] = np.full(1 << 14, i, dtype=np.float32)  # 64 KiB
            class _Raw:
                def __init__(self, sd):
                    self.sd = sd

                def state_dict(self):
                    return self.sd

                def load_state_dict(self, sd):
                    self.sd = sd

            Snapshot.take(
                f"memory://stripe-{rank}",
                {"st": _Raw(sd)},
                coord=coord,
                replicated=["**"],
            )
        except BaseException:  # pragma: no cover
            import traceback

            errors.append(traceback.format_exc())

    store = DictStore()
    errors = []
    threads = [
        threading.Thread(target=worker, args=(r, store, errors))
        for r in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors[0]

    # Path collation broadcasts rank 0's URL, so both ranks wrote into
    # one bucket; per-rank bytes are attributed via the manifest — only
    # the stripe OWNER's entry carries a checksum. Each rank must own
    # ~half the payload bytes (the big leaf on one side, the 16 smalls
    # on the other), not big+half-the-smalls vs half-the-smalls as
    # count-round-robin would give.
    from torchsnapshot_tpu.serialization import array_nbytes

    manifest = Snapshot("memory://stripe-0").get_manifest()
    per_rank = {0: 0, 1: 0}
    for path, entry in manifest.items():
        owner = int(path.split("/", 1)[0])
        if getattr(entry, "checksum", None) and hasattr(entry, "dtype"):
            per_rank[owner] += array_nbytes(entry.dtype, entry.shape)
    total = (1 << 20) + 16 * (1 << 16)
    for nbytes in per_rank.values():
        assert abs(nbytes - total / 2) <= total * 0.05, per_rank
