"""Fused attention kernel: numerics vs the einsum reference, gradient
flow, and transformer integration. Runs the same Pallas kernel the TPU
executes, in interpreter mode on the hermetic CPU suite.

Marked ``slow``: Pallas interpreter mode multiplies trace time by the
grid size, pushing this file past the fast tier's wall-clock budget on a
single-core host. Run with ``-m slow`` (or no ``-m`` filter)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.slow

from torchsnapshot_tpu.ops.attention import (
    _reference_attention,
    flash_attention,
)


def _qkv(shape, seed=0, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(jax.random.key(seed), 3)
    return (
        jax.random.normal(kq, shape, dtype),
        jax.random.normal(kk, shape, dtype),
        jax.random.normal(kv, shape, dtype),
    )


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize(
    "shape,bq,bk",
    [
        ((2, 4, 128, 64), 128, 128),  # single block
        ((1, 2, 256, 32), 64, 128),  # uneven block_q/block_k
        ((2, 2, 256, 64), 128, 64),
    ],
)
def test_flash_matches_reference(shape, bq, bk, causal):
    q, k, v = _qkv(shape, seed=shape[2] + bq)
    out = flash_attention(q, k, v, causal=causal, block_q=bq, block_k=bk)
    expected = _reference_attention(q, k, v, causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), atol=2e-5, rtol=1e-5
    )


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize(
    "bq,bk",
    [
        (128, 128),  # 1x1 grid
        (32, 64),  # multi-block: accumulation + causal block skipping
        (64, 32),  # swapped: uneven grids both ways
    ],
)
def test_flash_gradients_match_reference(bq, bk, causal):
    """The tiled Pallas backward (p reconstructed from the saved
    log-sum-exp) must match the einsum reference's gradients — including
    across multi-block grids, where the dk/dv accumulators persist over
    query blocks and causal tiles are skipped."""
    q, k, v = _qkv((1, 2, 128, 32), seed=7)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=causal, block_q=bq, block_k=bk)
            ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        # Forward outputs differ at float tolerance, so the (output-
        # dependent) cotangents do too; gradients match to tolerance.
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_indivisible_sequence_rejected():
    q, k, v = _qkv((1, 1, 48, 16))
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k, v, block_q=32, block_k=32)


def test_transformer_flash_forward_and_train_step():
    from torchsnapshot_tpu.models.transformer import (
        TransformerConfig,
        forward,
        init_params,
        sgd_train_step,
    )

    base = TransformerConfig(
        vocab_size=64, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=32,
    )
    flash = TransformerConfig(
        vocab_size=64, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=32, flash_attention=True,
    )
    params = init_params(base, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 32), 0, 64)

    out_base = forward(params, tokens, base)
    out_flash = forward(params, tokens, flash)
    np.testing.assert_allclose(
        np.asarray(out_base), np.asarray(out_flash), atol=2e-4, rtol=1e-4
    )

    # Full train step differentiates through the kernel's custom VJP.
    new_params, loss = jax.jit(
        lambda p, t: sgd_train_step(p, t, config=flash)
    )(params, tokens)
    assert np.isfinite(float(loss))
    jax.block_until_ready(new_params)


def test_transformer_flash_nonpow2_seq_and_mesh_guard():
    from jax.sharding import Mesh
    from torchsnapshot_tpu.models.transformer import (
        TransformerConfig,
        forward,
        init_params,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=64, n_heads=4, n_layers=1, d_ff=128,
        max_seq_len=192, flash_attention=True,
    )
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 192), 0, 64)
    out = forward(params, tokens, cfg)  # block=gcd(192,128)=64; must not crash
    assert out.shape == (2, 192, 64)

    mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
    with pytest.raises(ValueError, match="single-device"):
        forward(params, tokens, cfg, mesh=mesh)


def test_transformer_flash_rejects_sub_mxu_blocks():
    from torchsnapshot_tpu.models.transformer import (
        TransformerConfig,
        forward,
        init_params,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=64, n_heads=4, n_layers=1, d_ff=128,
        max_seq_len=132, flash_attention=True,
    )
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (1, 132), 0, 64)
    with pytest.raises(ValueError, match="power-of-two factor"):
        forward(params, tokens, cfg)  # gcd(132,128)=4 < 8


@pytest.mark.parametrize("causal", [True, False])
def test_flash_gqa_matches_repeated_kv_reference(causal):
    """Grouped-query attention: Hq = 8 query heads share Hkv = 2 kv
    heads, expressed purely through kernel index maps (K/V never
    materialize per q-head). Reference: dense attention with kv heads
    repeated group-fold."""
    from torchsnapshot_tpu.ops.attention import (
        _reference_attention,
        flash_attention,
    )

    b, hq, hkv, s, d = 2, 8, 2, 64, 16
    kq, kk, kv = jax.random.split(jax.random.key(31), 3)
    q = jax.random.normal(kq, (b, hq, s, d), jnp.float32)
    k = jax.random.normal(kk, (b, hkv, s, d), jnp.float32)
    v = jax.random.normal(kv, (b, hkv, s, d), jnp.float32)

    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    expected = _reference_attention(
        q, jnp.repeat(k, hq // hkv, axis=1), jnp.repeat(v, hq // hkv, axis=1),
        causal,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), atol=3e-6, rtol=1e-5
    )


def test_flash_gqa_gradients_match_repeated_kv_reference():
    """GQA backward: dq per q-head; dk/dv group-summed onto the shared
    kv heads — equal to differentiating the repeat-kv dense reference
    (jnp.repeat's VJP is exactly the group sum)."""
    from torchsnapshot_tpu.ops.attention import (
        _reference_attention,
        flash_attention,
    )

    b, hq, hkv, s, d = 1, 4, 2, 32, 8
    kq, kk, kv = jax.random.split(jax.random.key(33), 3)
    q = jax.random.normal(kq, (b, hq, s, d), jnp.float32)
    k = jax.random.normal(kk, (b, hkv, s, d), jnp.float32)
    v = jax.random.normal(kv, (b, hkv, s, d), jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, block_q=8, block_k=8) ** 2
        )

    def loss_ref(q, k, v):
        g = hq // hkv
        return jnp.sum(
            _reference_attention(
                q, jnp.repeat(k, g, axis=1), jnp.repeat(v, g, axis=1), True
            )
            ** 2
        )

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        assert a.shape == b_.shape
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), atol=2e-4, rtol=1e-4
        )


def test_flash_gqa_rejects_indivisible_heads():
    from torchsnapshot_tpu.ops.attention import flash_attention

    q = jnp.zeros((1, 6, 16, 8))
    k = jnp.zeros((1, 4, 16, 8))
    with pytest.raises(ValueError, match="multiple of kv heads"):
        flash_attention(q, k, k, causal=True, block_q=8, block_k=8)
