"""Storage plugin tests (reference analog: tests/test_fs_storage_plugin.py)."""

import asyncio
import io
import os

import pytest

from torchsnapshot_tpu.io_types import IOReq, io_payload
from torchsnapshot_tpu.storage_plugin import url_to_storage_plugin
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin
from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin


def _roundtrip(plugin, path, payload, byte_range=None):
    async def _run():
        await plugin.write(IOReq(path=path, data=payload))
        io_req = IOReq(path=path, byte_range=byte_range)
        await plugin.read(io_req)
        return bytes(io_payload(io_req))

    return asyncio.run(_run())


def test_fs_write_read_delete(tmp_path):
    plugin = FSStoragePlugin(root=str(tmp_path))
    payload = os.urandom(1024)
    assert _roundtrip(plugin, "a/b/c", payload) == payload
    assert (tmp_path / "a" / "b" / "c").exists()
    asyncio.run(plugin.delete("a/b/c"))
    assert not (tmp_path / "a" / "b" / "c").exists()
    plugin.close()


def test_fs_ranged_read(tmp_path):
    plugin = FSStoragePlugin(root=str(tmp_path))
    payload = bytes(range(256))
    assert _roundtrip(plugin, "obj", payload, byte_range=(10, 20)) == payload[10:20]


def test_fs_bytesio_write_path(tmp_path):
    plugin = FSStoragePlugin(root=str(tmp_path))

    async def _run():
        io_req = IOReq(path="x", buf=io.BytesIO(b"hello"))
        await plugin.write(io_req)
        out = IOReq(path="x")
        await plugin.read(out)
        return bytes(io_payload(out))

    assert asyncio.run(_run()) == b"hello"


def test_fs_no_partial_write_visible(tmp_path):
    # Writes go to a temp file then rename: the final name either doesn't
    # exist or holds the full payload.
    plugin = FSStoragePlugin(root=str(tmp_path))
    payload = os.urandom(4096)
    _roundtrip(plugin, "atomic", payload)
    leftovers = [p for p in os.listdir(tmp_path) if p.startswith("atomic.tmp")]
    assert leftovers == []


def test_fs_dir_fsyncs_batch_to_publish_point(tmp_path, monkeypatch):
    # Data-object writes defer their directory fsync; the next publish
    # point (dot-prefixed metadata/marker write) pays one fsync per
    # dirty directory, covering every object renamed into it since.
    from torchsnapshot_tpu.storage_plugins import fs as fs_mod

    synced = []
    monkeypatch.setattr(fs_mod, "_fsync_dir", synced.append)
    plugin = FSStoragePlugin(root=str(tmp_path))
    for i in range(3):
        asyncio.run(plugin.write(IOReq(path=f"shard/obj{i}", data=b"x")))
    # Only the one dir-creation fsync (shard's parent, via _prepare_dir);
    # the three object dirents are deferred — nothing references them yet.
    assert synced == [str(tmp_path)]
    assert plugin._dirty_dirs == {str(tmp_path / "shard")}

    asyncio.run(plugin.write(IOReq(path=".snapshot_metadata", data=b"m")))
    # One batched fsync for the dirty data dir, then one for the dir the
    # metadata itself landed in — in that order.
    assert synced[1:] == [str(tmp_path / "shard"), str(tmp_path)]
    assert plugin._dirty_dirs == set()

    # ensure_durable() — the commit-protocol hook for ranks whose route
    # writes no marker of their own — drains the batch too, including
    # through the retry decorator url_to_storage_plugin wraps with.
    wrapped = url_to_storage_plugin(str(tmp_path))
    wrapped._inner._dirty_dirs.add(str(tmp_path / "shard"))
    wrapped.ensure_durable()
    assert synced[-1] == str(tmp_path / "shard")
    assert wrapped._inner._dirty_dirs == set()

    # close() drains anything a publish never covered.
    asyncio.run(plugin.write(IOReq(path="shard/late", data=b"x")))
    plugin.close()
    assert synced[-1] == str(tmp_path / "shard")


def test_fs_fsyncs_created_root_ancestors(tmp_path, monkeypatch):
    # A root that does not exist yet (step dirs under a fresh job dir):
    # makedirs conjures the whole chain, and every created directory's
    # dirent — including the root's own, above the plugin root — must be
    # fsynced, or a crash can drop the entire snapshot directory.
    from torchsnapshot_tpu.storage_plugins import fs as fs_mod

    synced = []
    monkeypatch.setattr(fs_mod, "_fsync_dir", synced.append)
    root = tmp_path / "job" / "step-1"
    plugin = FSStoragePlugin(root=str(root))
    asyncio.run(plugin.write(IOReq(path="shard/obj", data=b"x")))
    # job, step-1, and shard were created: each one's parent is fsynced,
    # top-downward.
    assert synced == [str(tmp_path), str(tmp_path / "job"), str(root)]


def test_memory_plugin():
    plugin = MemoryStoragePlugin()
    payload = os.urandom(64)
    assert _roundtrip(plugin, "k", payload) == payload
    assert _roundtrip(plugin, "k", payload, byte_range=(8, 16)) == payload[8:16]
    asyncio.run(plugin.delete("k"))
    assert "k" not in plugin.store


def test_memory_shared_store():
    a = url_to_storage_plugin("memory://bucket1")
    b = url_to_storage_plugin("memory://bucket1")
    asyncio.run(a.write(IOReq(path="k", data=b"v")))
    io_req = IOReq(path="k")
    asyncio.run(b.read(io_req))
    assert bytes(io_payload(io_req)) == b"v"


def test_url_dispatch(tmp_path):
    # Every resolved plugin is a StoragePlugin wrapped with the retry
    # decorator; the backend type is visible on ._inner.
    from torchsnapshot_tpu.io_types import StoragePlugin

    for url, backend_cls in (
        (str(tmp_path), FSStoragePlugin),
        (f"fs://{tmp_path}", FSStoragePlugin),
        ("memory://x", MemoryStoragePlugin),
    ):
        plugin = url_to_storage_plugin(url)
        assert isinstance(plugin, StoragePlugin)
        assert isinstance(plugin._inner, backend_cls)
    with pytest.raises(RuntimeError, match="Unsupported protocol"):
        url_to_storage_plugin("bogus://x")


def test_installed_plugin_load_error_propagates(monkeypatch):
    # A matched entry point whose load() raises must surface the real
    # error (e.g. a missing optional dep), not "Unsupported protocol" —
    # the plugin IS installed, and the user should be told what broke.
    from torchsnapshot_tpu import storage_plugin as sp_mod

    class BrokenEP:
        name = "myplug"

        def load(self):
            raise ImportError("myplug needs google-cloud-storage")

    class EPs:
        def select(self, group):
            return [BrokenEP()] if group == "storage_plugins" else []

    monkeypatch.setattr(sp_mod.importlib_metadata, "entry_points", EPs)
    with pytest.raises(ImportError, match="google-cloud-storage"):
        url_to_storage_plugin("myplug://bucket")


def test_memory_object_age_visible_across_instances():
    """mtimes ride the SHARED store, not the plugin instance: sweep
    resolves a fresh plugin for the same bucket and its age guard must
    see the ages of objects other instances wrote (code-review r3)."""
    import asyncio

    from torchsnapshot_tpu.io_types import IOReq
    from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin

    shared = {}
    writer = MemoryStoragePlugin(shared)
    asyncio.run(writer.write(IOReq(path="x", data=b"123")))
    reader = MemoryStoragePlugin(shared)
    age = asyncio.run(reader.object_age_s("x"))
    assert age is not None and age < 60.0
    assert asyncio.run(reader.object_age_s("missing")) is None
