"""Storage plugin tests (reference analog: tests/test_fs_storage_plugin.py)."""

import asyncio
import io
import os

import pytest

from torchsnapshot_tpu.io_types import IOReq, io_payload
from torchsnapshot_tpu.storage_plugin import url_to_storage_plugin
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin
from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin


def _roundtrip(plugin, path, payload, byte_range=None):
    async def _run():
        await plugin.write(IOReq(path=path, data=payload))
        io_req = IOReq(path=path, byte_range=byte_range)
        await plugin.read(io_req)
        return bytes(io_payload(io_req))

    return asyncio.run(_run())


def test_fs_write_read_delete(tmp_path):
    plugin = FSStoragePlugin(root=str(tmp_path))
    payload = os.urandom(1024)
    assert _roundtrip(plugin, "a/b/c", payload) == payload
    assert (tmp_path / "a" / "b" / "c").exists()
    asyncio.run(plugin.delete("a/b/c"))
    assert not (tmp_path / "a" / "b" / "c").exists()
    plugin.close()


def test_fs_ranged_read(tmp_path):
    plugin = FSStoragePlugin(root=str(tmp_path))
    payload = bytes(range(256))
    assert _roundtrip(plugin, "obj", payload, byte_range=(10, 20)) == payload[10:20]


def test_fs_bytesio_write_path(tmp_path):
    plugin = FSStoragePlugin(root=str(tmp_path))

    async def _run():
        io_req = IOReq(path="x", buf=io.BytesIO(b"hello"))
        await plugin.write(io_req)
        out = IOReq(path="x")
        await plugin.read(out)
        return bytes(io_payload(out))

    assert asyncio.run(_run()) == b"hello"


def test_fs_no_partial_write_visible(tmp_path):
    # Writes go to a temp file then rename: the final name either doesn't
    # exist or holds the full payload.
    plugin = FSStoragePlugin(root=str(tmp_path))
    payload = os.urandom(4096)
    _roundtrip(plugin, "atomic", payload)
    leftovers = [p for p in os.listdir(tmp_path) if p.startswith("atomic.tmp")]
    assert leftovers == []


def test_memory_plugin():
    plugin = MemoryStoragePlugin()
    payload = os.urandom(64)
    assert _roundtrip(plugin, "k", payload) == payload
    assert _roundtrip(plugin, "k", payload, byte_range=(8, 16)) == payload[8:16]
    asyncio.run(plugin.delete("k"))
    assert "k" not in plugin.store


def test_memory_shared_store():
    a = url_to_storage_plugin("memory://bucket1")
    b = url_to_storage_plugin("memory://bucket1")
    asyncio.run(a.write(IOReq(path="k", data=b"v")))
    io_req = IOReq(path="k")
    asyncio.run(b.read(io_req))
    assert bytes(io_payload(io_req)) == b"v"


def test_url_dispatch(tmp_path):
    # Every resolved plugin is a StoragePlugin wrapped with the retry
    # decorator; the backend type is visible on ._inner.
    from torchsnapshot_tpu.io_types import StoragePlugin

    for url, backend_cls in (
        (str(tmp_path), FSStoragePlugin),
        (f"fs://{tmp_path}", FSStoragePlugin),
        ("memory://x", MemoryStoragePlugin),
    ):
        plugin = url_to_storage_plugin(url)
        assert isinstance(plugin, StoragePlugin)
        assert isinstance(plugin._inner, backend_cls)
    with pytest.raises(RuntimeError, match="Unsupported protocol"):
        url_to_storage_plugin("bogus://x")


def test_memory_object_age_visible_across_instances():
    """mtimes ride the SHARED store, not the plugin instance: sweep
    resolves a fresh plugin for the same bucket and its age guard must
    see the ages of objects other instances wrote (code-review r3)."""
    import asyncio

    from torchsnapshot_tpu.io_types import IOReq
    from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin

    shared = {}
    writer = MemoryStoragePlugin(shared)
    asyncio.run(writer.write(IOReq(path="x", data=b"123")))
    reader = MemoryStoragePlugin(shared)
    age = asyncio.run(reader.object_age_s("x"))
    assert age is not None and age < 60.0
    assert asyncio.run(reader.object_age_s("missing")) is None
