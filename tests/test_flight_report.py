"""Flight recorder: .report.json byte reconciliation, restore breakdown,
both commit routes, and the trace-summarize analytics (ISSUE 3
acceptance criteria)."""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from torchsnapshot_tpu import Snapshot, StateDict, telemetry, tracing
from torchsnapshot_tpu.storage_plugin import _MEMORY_STORES
from torchsnapshot_tpu.telemetry import report as flight
from torchsnapshot_tpu.telemetry import summarize
from torchsnapshot_tpu.utils.test_utils import run_thread_ranks


@pytest.fixture(autouse=True)
def _fresh_registry():
    telemetry.reset()
    yield
    telemetry.reset()


class _Model:
    def __init__(self, params):
        self.params = params

    def state_dict(self):
        return self.params

    def load_state_dict(self, sd):
        self.params = sd


def _rank_state(rank: int, n: int):
    rng = np.random.RandomState(rank)
    return {
        "w": rng.randn(n).astype(np.float32),
        "b": rng.randn(n // 2 + rank).astype(np.float32),  # uneven ranks
    }


def _manifest_rank_bytes(manifest, store, bucket_prefix):
    """Per-rank stored payload bytes implied by the manifest: each rank's
    entries name locations under '<rank>/…'; the stored object's size is
    the authoritative byte count."""
    per_rank = {}
    for key, entry in manifest.items():
        location = getattr(entry, "location", None)
        if not location:
            continue
        owner = int(location.split("/", 1)[0])
        size = len(store[f"{bucket_prefix}{location}"])
        per_rank[owner] = per_rank.get(owner, 0) + size
    return per_rank


def _take_two_ranks(bucket: str, url: str):
    def fn(coord, rank):
        model = _Model(_rank_state(rank, 4096))
        return Snapshot.take(url, {"model": model}, coord=coord)

    return run_thread_ranks(2, fn)


# --------------------------------------------------------- take .report.json


def test_take_report_reconciles_with_manifest_bytes():
    """Acceptance: a 2-rank memory:// take produces a .report.json whose
    per-rank written-byte totals reconcile EXACTLY with the manifest's
    byte accounting (stored object sizes per owning rank)."""
    bucket = "flightrep1"
    _MEMORY_STORES.pop(bucket, None)
    url = f"memory://{bucket}/snap"
    snaps = _take_two_ranks(bucket, url)
    store = _MEMORY_STORES[bucket]
    report = json.loads(store["snap/.report.json"])
    assert report["format_version"] == flight.REPORT_FORMAT_VERSION
    assert report["kind"] == "take"
    assert report["world_size"] == 2
    assert len(report["ranks"]) == 2

    manifest = snaps[0].get_manifest()
    expected = _manifest_rank_bytes(manifest, store, "snap/")
    for r in (0, 1):
        summary = report["ranks"][r]
        assert summary["rank"] == r
        assert summary["bytes"] == expected[r]
    assert report["totals"]["bytes"] == sum(expected.values())
    # phase timings present on every rank
    for summary in report["ranks"]:
        assert set(summary["phases"]) >= {"capture_s", "write_s", "commit_s"}
        assert summary["scheduler_ops"]["write"]["bytes"] == summary["bytes"]
    # the take_id in the report is the committed snapshot's
    meta = json.loads(json.dumps(report))  # plain-data sanity
    assert meta["take_id"]


def test_take_report_via_storage_commit_route(monkeypatch):
    """Forcing the storage-marker commit route (large-manifest path)
    still yields a merged report: summaries ride .report/<id>/<rank>
    objects, which rank 0 collects and deletes."""
    monkeypatch.setenv("TPUSNAPSHOT_COMMIT_VIA_STORAGE_BYTES", "1")
    bucket = "flightrep2"
    _MEMORY_STORES.pop(bucket, None)
    url = f"memory://{bucket}/snap"
    _take_two_ranks(bucket, url)
    store = _MEMORY_STORES[bucket]
    report = json.loads(store["snap/.report.json"])
    assert report["world_size"] == 2
    assert all(s is not None for s in report["ranks"])
    assert {s["rank"] for s in report["ranks"]} == {0, 1}
    assert report["totals"]["bytes"] > 0
    # per-rank summary objects were cleaned up after the merge
    assert [k for k in store if k.startswith("snap/.report/")] == []


def test_async_take_report(tmp_path):
    model = _Model({"w": jnp.arange(512, dtype=jnp.float32)})
    pending = Snapshot.async_take(str(tmp_path / "snap"), {"model": model})
    pending.wait()
    with open(tmp_path / "snap" / ".report.json") as f:
        report = json.load(f)
    assert report["kind"] == "async_take"
    assert report["ranks"][0]["bytes"] == 512 * 4
    assert "prestage_s" in report["ranks"][0]["phases"]


def test_delete_removes_reports(tmp_path):
    model = _Model({"w": np.arange(64, dtype=np.float32)})
    snap = Snapshot.take(str(tmp_path / "snap"), {"model": model})
    snap.restore({"model": _Model({"w": np.zeros(64, np.float32)})})
    assert (tmp_path / "snap" / ".report.json").exists()
    assert (tmp_path / "snap" / ".report.restore.json").exists()
    snap.delete()
    leftovers = (
        list((tmp_path / "snap").rglob("*"))
        if (tmp_path / "snap").exists()
        else []
    )
    assert [p for p in leftovers if p.is_file()] == []


# ------------------------------------------------------------ restore report


def test_restore_report_breakdown():
    bucket = "flightrep3"
    _MEMORY_STORES.pop(bucket, None)
    url = f"memory://{bucket}/snap"
    _take_two_ranks(bucket, url)
    store = _MEMORY_STORES[bucket]

    def restore_fn(coord, rank):
        fresh = _Model(
            {k: np.zeros_like(v) for k, v in _rank_state(rank, 4096).items()}
        )
        Snapshot(url).restore({"model": fresh}, coord=coord)
        np.testing.assert_array_equal(
            fresh.params["w"], _rank_state(rank, 4096)["w"]
        )

    run_thread_ranks(2, restore_fn)
    # Restore symmetry: ONE merged rank-0 digest with per-rank
    # breakdowns (same gather routes as take reports), not N loose
    # rank-local files.
    doc = json.loads(store["snap/.report.restore.json"])
    assert doc["kind"] == "restore"
    assert doc["world_size"] == 2
    assert len(doc["ranks"]) == 2
    assert not any(
        k.startswith("snap/.report.restore.rank") for k in store
    )
    for rank in (0, 1):
        summary = doc["ranks"][rank]
        assert summary["rank"] == rank
        # the read/consume/assemble breakdown is present and the bytes
        # match what this rank's manifest view implies
        assert set(summary["phases"]) >= {
            "read_s",
            "consume_s",
            "assemble_s",
        }
        assert summary["bytes"] == summary["scheduler_ops"]["read"]["bytes"]
        assert summary["scheduler_ops"]["consume"]["count"] > 0
    assert doc["totals"]["bytes"] == sum(
        s["bytes"] for s in doc["ranks"]
    )


# ------------------------------------------------------------ inspect bridge


def test_report_renders_through_inspect():
    from torchsnapshot_tpu.inspect import main as inspect_main

    bucket = "flightrep4"
    _MEMORY_STORES.pop(bucket, None)
    url = f"memory://{bucket}/snap"
    _take_two_ranks(bucket, url)
    assert inspect_main([url, "--report"]) == 0


# ------------------------------------------------------------ trace analytics


def _span_pair(name, span_id, t0_us, t1_us, **args):
    begin = {
        "name": name,
        "cat": "snapshot",
        "ph": "b",
        "id": span_id,
        "ts": t0_us,
        "pid": 1,
        "tid": 1,
    }
    if args:
        begin["args"] = args
    end = dict(begin, ph="e", ts=t1_us)
    end.pop("args", None)
    return [begin, end]


def test_summarize_names_consume_as_dominant_phase(tmp_path, capsys):
    """Acceptance: telemetry.summarize on a restore trace shaped like the
    bench workload (BENCH_r05: restore_consume_span_s 176.3 vs
    restore_read_span_s 0.76) names consume as the dominant phase."""
    events = []
    sid = iter(range(1, 100))
    # reads: short, early, overlapping
    events += _span_pair("read", next(sid), 0, 400_000, bytes=1 << 20)
    events += _span_pair("read", next(sid), 100_000, 760_000, bytes=1 << 20)
    # consumes: the 176.3s pathology
    events += _span_pair(
        "consume", next(sid), 400_000, 176_300_000 + 400_000, bytes=1 << 20
    )
    events += _span_pair("Snapshot.restore", next(sid), 0, 177_000_000)
    trace = tmp_path / "restore-trace.json"
    trace.write_text(json.dumps({"traceEvents": events}))

    assert summarize.main([str(trace)]) == 0
    out = capsys.readouterr().out
    assert "dominant phase: consume" in out
    assert "restore is consume-dominated" in out
    assert "host->device placement is the bottleneck" in out

    # machine-readable verdict too
    assert summarize.main([str(trace), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["verdict"]["dominant_phase"] == "consume"
    assert doc["verdict"]["dominated"] is True
    assert doc["verdict"]["pipeline"] == "restore"
    assert doc["phases"]["consume"]["busy_s"] == pytest.approx(176.3)
    assert doc["phases"]["read"]["busy_s"] == pytest.approx(0.76)


def test_summarize_on_real_restore_trace(tmp_path, capsys):
    """End-to-end: a traced take+restore produces a trace the summarizer
    folds (read/consume rows present, no crash)."""
    trace_path = str(tmp_path / "trace.json")
    tracing.enable(trace_path)
    try:
        model = _Model({"w": np.arange(4096, dtype=np.float32)})
        snap = Snapshot.take(str(tmp_path / "snap"), {"model": model})
        snap.restore({"model": _Model({"w": np.zeros(4096, np.float32)})})
    finally:
        tracing.disable()
    assert summarize.main([trace_path, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    for op in ("stage", "write", "read", "consume"):
        assert doc["phases"][op]["count"] >= 1
    assert doc["phases"]["read"]["bytes"] == 0 or True  # reads carry no bytes arg


def test_summarize_no_spans(tmp_path, capsys):
    trace = tmp_path / "empty.json"
    trace.write_text(json.dumps({"traceEvents": []}))
    assert summarize.main([str(trace)]) == 1


def test_summarize_usage_error(tmp_path):
    assert summarize.main([str(tmp_path / "missing.json")]) == 2
