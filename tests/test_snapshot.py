"""End-to-end single-process take→restore tests (reference analog:
tests/test_snapshot.py:21-73)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.manifest import PrimitiveEntry
from torchsnapshot_tpu.utils.test_utils import assert_state_dict_eq


class _ModelState:
    """A Stateful wrapping a params pytree (plain containers)."""

    def __init__(self, params):
        self.params = params

    def state_dict(self):
        return self.params

    def load_state_dict(self, state_dict):
        self.params = state_dict


def _make_params(seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    return {
        "dense1": {
            "w": jnp.asarray(rng.randn(8, 16), dtype=dtype),
            "b": jnp.asarray(rng.randn(16), dtype=dtype),
        },
        "dense2": {
            "w": jnp.asarray(rng.randn(16, 4), dtype=dtype),
            "b": jnp.asarray(rng.randn(4), dtype=dtype),
        },
    }


def test_state_dict_round_trip(tmp_path):
    progress = StateDict(epoch=3, step=1000, name="run-1", lr=1e-3, done=False)
    Snapshot.take(str(tmp_path / "snap"), {"progress": progress})
    restored = StateDict(epoch=0, step=0, name="", lr=0.0, done=True)
    Snapshot(str(tmp_path / "snap")).restore({"progress": restored})
    assert dict(restored) == dict(progress)
    assert type(restored["epoch"]) is int
    assert type(restored["done"]) is bool


def test_model_round_trip(tmp_path):
    model = _ModelState(_make_params(seed=0))
    Snapshot.take(str(tmp_path / "snap"), {"model": model})
    target = _ModelState(_make_params(seed=1))
    Snapshot(str(tmp_path / "snap")).restore({"model": target})
    assert_state_dict_eq(target.params, model.params)


def test_optimizer_state_round_trip(tmp_path):
    params = _make_params(seed=0)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    # Serialize optax state (NamedTuple pytree) via plain-container dump.
    from torchsnapshot_tpu.utils.tree import from_state_dict, to_state_dict

    class _OptState:
        def __init__(self, state):
            self.state = state

        def state_dict(self):
            return to_state_dict(self.state)

        def load_state_dict(self, sd):
            self.state = from_state_dict(self.state, sd)

    # Take one real step so moments are nonzero.
    grads = jax.tree.map(jnp.ones_like, params)
    updates, opt_state = opt.update(grads, opt_state)

    holder = _OptState(opt_state)
    Snapshot.take(str(tmp_path / "snap"), {"optim": holder})

    fresh = _OptState(opt.init(params))
    Snapshot(str(tmp_path / "snap")).restore({"optim": fresh})
    for a, b in zip(jax.tree.leaves(fresh.state), jax.tree.leaves(opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bf16_and_mixed_dtypes(tmp_path):
    model = _ModelState(
        {
            "bf16": jnp.asarray([[1.5, -2.25]], dtype=jnp.bfloat16),
            "f32": jnp.asarray([1e-38, 3.4e38], dtype=jnp.float32),
            "i8": jnp.asarray([-128, 127], dtype=jnp.int8),
            "u32": jnp.asarray([0, 2**32 - 1], dtype=jnp.uint32),
        }
    )
    Snapshot.take(str(tmp_path / "snap"), {"m": model})
    target = _ModelState(
        {
            "bf16": jnp.zeros((1, 2), dtype=jnp.bfloat16),
            "f32": jnp.zeros(2, dtype=jnp.float32),
            "i8": jnp.zeros(2, dtype=jnp.int8),
            "u32": jnp.zeros(2, dtype=jnp.uint32),
        }
    )
    Snapshot(str(tmp_path / "snap")).restore({"m": target})
    assert_state_dict_eq(target.params, model.params, exact=True)


def test_sharded_model_round_trip(tmp_path):
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("dp", "tp"))
    params = {
        "w1": jax.device_put(
            jnp.arange(16 * 8, dtype=jnp.float32).reshape(16, 8),
            NamedSharding(mesh, P("dp", "tp")),
        ),
        "w2": jax.device_put(
            jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4),
            NamedSharding(mesh, P("tp", None)),
        ),
    }
    model = _ModelState(params)
    Snapshot.take(str(tmp_path / "snap"), {"model": model})

    target = _ModelState(jax.tree.map(jnp.zeros_like, params))
    # Templates keep their shardings.
    target.params = {
        k: jax.device_put(v, params[k].sharding) for k, v in target.params.items()
    }
    Snapshot(str(tmp_path / "snap")).restore({"model": target})
    for k in params:
        np.testing.assert_array_equal(
            np.asarray(target.params[k]), np.asarray(params[k])
        )
        assert target.params[k].sharding.is_equivalent_to(
            params[k].sharding, params[k].ndim
        )


def test_snapshot_dir_layout(tmp_path):
    model = _ModelState(_make_params())
    progress = StateDict(epoch=1)
    Snapshot.take(str(tmp_path / "snap"), {"model": model, "progress": progress})
    root = tmp_path / "snap"
    assert (root / ".snapshot_metadata").exists()
    assert (root / "0" / "model" / "dense1" / "w").exists()


def test_manifest_inspection(tmp_path):
    model = _ModelState(_make_params())
    snap = Snapshot.take(str(tmp_path / "snap"), {"model": model})
    manifest = snap.get_manifest()
    assert "0/model/dense1/w" in manifest


def test_restore_missing_entry_raises(tmp_path):
    model = _ModelState(_make_params())
    Snapshot.take(str(tmp_path / "snap"), {"model": model})
    other = StateDict(not_there=1)
    with pytest.raises(RuntimeError, match="Unable to find an entry"):
        Snapshot(str(tmp_path / "snap")).restore({"other": other})


def test_take_returns_usable_handle(tmp_path):
    model = _ModelState(_make_params(seed=0))
    snap = Snapshot.take(str(tmp_path / "snap"), {"model": model})
    target = _ModelState(_make_params(seed=9))
    snap.restore({"model": target})
    assert_state_dict_eq(target.params, model.params)


def test_memory_storage_round_trip():
    model = _ModelState(_make_params(seed=0))
    Snapshot.take("memory://snap-rt", {"model": model})
    target = _ModelState(_make_params(seed=3))
    Snapshot("memory://snap-rt").restore({"model": target})
    assert_state_dict_eq(target.params, model.params)


def test_nested_containers_with_arrays(tmp_path):
    model = _ModelState(
        {
            "layers": [
                {"w": jnp.ones((2, 2)), "meta": (1, "a")},
                {"w": jnp.zeros((3, 3)), "meta": (2, "b")},
            ],
            "extra": {"tags": ["x", "y"], "count": 7},
        }
    )
    Snapshot.take(str(tmp_path / "snap"), {"m": model})
    target = _ModelState(
        {
            "layers": [
                {"w": jnp.zeros((2, 2)), "meta": (0, "")},
                {"w": jnp.ones((3, 3)), "meta": (0, "")},
            ],
            "extra": {"tags": ["", ""], "count": 0},
        }
    )
    Snapshot(str(tmp_path / "snap")).restore({"m": target})
    assert target.params["extra"]["count"] == 7
    assert target.params["extra"]["tags"] == ["x", "y"]
    assert target.params["layers"][0]["meta"] == (1, "a")
    np.testing.assert_array_equal(
        np.asarray(target.params["layers"][0]["w"]), np.ones((2, 2))
    )
