"""Cross-object streaming restore + HBM admission control
(VERDICT r4 #2/#4).

Streaming used to engage only when ONE stored object exactly covered one
single-device region; format-chunked dense arrays made the dominant
restore shape "several whole chunks tiling one region", which fell back
to host reassembly and serialized H2D behind storage reads. Streaming is
now decided per REGION: every chunk that is a contiguous byte run of the
region's flat layout deposits its sub-ranges on device as they land,
and finalize concatenates in flat-offset order.

The device-side budget mirrors the host budget: consume dispatch is
gated on in-flight streamed bytes, released when assembly frees the
chunks (SURVEY §7 hard-part 5 — the restore-side HBM story the take
side's clone-OOM fallback never covered).
"""

import asyncio

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import torchsnapshot_tpu.io_preparer as iop
import torchsnapshot_tpu.scheduler as sched
from torchsnapshot_tpu import Snapshot
from torchsnapshot_tpu.io_types import BufferConsumer, IOReq, ReadReq
from torchsnapshot_tpu.scheduler import execute_read_reqs
from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin


class _Holder:
    def __init__(self, sd):
        self.sd = sd

    def state_dict(self):
        return self.sd

    def load_state_dict(self, sd):
        self.sd = sd


@pytest.fixture
def small_scale(monkeypatch):
    """1 MiB format chunks, 256 KiB sub-reads: a few-MiB array walks the
    same chunked-streaming machinery a multi-GiB param hits at the
    512 MiB / 64 MiB defaults."""
    monkeypatch.setattr(iop, "MAX_CHUNK_SIZE_BYTES", 1 << 20)
    monkeypatch.setenv("TPUSNAPSHOT_PARALLEL_READ_THRESHOLD", str(256 << 10))


def _arr(nbytes, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(nbytes // 4), jnp.float32)


def test_chunked_dense_restore_streams_across_objects(
    tmp_path, small_scale, monkeypatch
):
    """Every chunk object of a format-chunked dense array must stream to
    device as its sub-ranges land (no host assembly buffer), and the
    flat-offset concat must be bit-exact."""
    from torchsnapshot_tpu.ops.transfer import H2DPipeline

    puts = []
    orig_submit = H2DPipeline.submit

    def _spy_put(self, host, device, profile=None):
        puts.append(int(getattr(host, "nbytes", 0)))
        return orig_submit(self, host, device, profile=profile)

    monkeypatch.setattr(H2DPipeline, "submit", _spy_put)

    arr = _arr(4 << 20, seed=1)  # 4 chunks x 4 sub-reads
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": _Holder({"w": arr})})
    target = {"m": _Holder({"w": jnp.zeros_like(arr)})}
    Snapshot(path).restore(target)
    np.testing.assert_array_equal(
        np.asarray(target["m"].sd["w"]), np.asarray(arr)
    )
    # All bytes arrived via streamed sub-range puts (16 x 256 KiB), not
    # one whole-region device_put at finalize.
    assert sum(puts) == arr.nbytes
    assert len(puts) >= 8


def test_streaming_restore_respects_device_budget(
    tmp_path, small_scale, monkeypatch
):
    """With a forced device budget smaller than the combined streamed
    chunks, in-flight deposited bytes must never exceed budget by more
    than the single force-admitted consume, and every deposited byte
    must be released back by assembly."""
    cells = []

    class _SpyCell(sched._BudgetCell):
        def __init__(self, value):
            super().__init__(value)
            self.initial = value
            self.min_seen = value
            cells.append(self)

        def charge(self, nbytes):
            super().charge(nbytes)
            self.min_seen = min(self.min_seen, self.value)

    monkeypatch.setattr(sched, "_BudgetCell", _SpyCell)
    # Each 3 MiB region charges 2x its size up front (deposits + concat
    # transient) and keeps the resident half charged after assembly. A
    # 9 MiB budget admits region A (charge 6), blocks B until A's
    # transient release (+3 -> 6 free) — concurrent admission would
    # have driven the cell to 9-12 = -3.
    region = 3 << 20
    budget = 9 << 20
    monkeypatch.setenv("TPUSNAPSHOT_DEVICE_BUDGET_BYTES", str(budget))

    a = _arr(region, seed=2)
    b = _arr(region, seed=3)
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": _Holder({"a": a, "b": b})})
    target = {"m": _Holder({"a": jnp.zeros_like(a), "b": jnp.zeros_like(b)})}
    Snapshot(path).restore(target)
    np.testing.assert_array_equal(np.asarray(target["m"].sd["a"]), np.asarray(a))
    np.testing.assert_array_equal(np.asarray(target["m"].sd["b"]), np.asarray(b))

    device_cells = [c for c in cells if c.initial == budget]
    assert device_cells, "device budget cell was never constructed"
    for cell in device_cells:
        # Up-front charging + serialized admission: the cell never goes
        # negative when each region's 2x charge fits the budget.
        assert cell.min_seen >= 0
        # The budget was actually contended (at least one region held).
        assert cell.min_seen <= budget - 2 * region
        # Only the transient halves returned; the restored arrays'
        # resident bytes stay charged.
        assert cell.value == cell.initial - 2 * region


def test_streaming_restore_force_admit_bounded_by_one_region(
    tmp_path, small_scale, monkeypatch
):
    """A region BIGGER than the whole device budget still restores
    (force-admitted when nothing in flight can release), and the overrun
    is bounded by that single region's size."""
    cells = []

    class _SpyCell(sched._BudgetCell):
        def __init__(self, value):
            super().__init__(value)
            self.initial = value
            self.min_seen = value
            cells.append(self)

        def charge(self, nbytes):
            super().charge(nbytes)
            self.min_seen = min(self.min_seen, self.value)

    monkeypatch.setattr(sched, "_BudgetCell", _SpyCell)
    region = 3 << 20
    budget = 4 << 20  # smaller than one region's 2x charge
    monkeypatch.setenv("TPUSNAPSHOT_DEVICE_BUDGET_BYTES", str(budget))

    a = _arr(region, seed=6)
    b = _arr(region, seed=7)
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": _Holder({"a": a, "b": b})})
    target = {"m": _Holder({"a": jnp.zeros_like(a), "b": jnp.zeros_like(b)})}
    Snapshot(path).restore(target)
    np.testing.assert_array_equal(np.asarray(target["m"].sd["a"]), np.asarray(a))
    np.testing.assert_array_equal(np.asarray(target["m"].sd["b"]), np.asarray(b))

    device_cells = [c for c in cells if c.initial == budget]
    assert device_cells
    for cell in device_cells:
        # Overrun bounded to ONE region's 2x charge at a time (plus the
        # prior region's resident half) — never both transients:
        # worst = budget - 2*region (A forced) - region (A resident).
        assert cell.min_seen >= budget - 3 * region
        assert cell.value == cell.initial - 2 * region


def test_streaming_skipped_for_resharded_templates(tmp_path, small_scale):
    """A chunk overlapping TWO regions (resharded restore) must fall
    back to the host-scatter path for that region — and still be
    bit-exact."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    arr = _arr(4 << 20, seed=4)
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": _Holder({"w": arr})})
    mesh = Mesh(np.array(jax.devices()), ("x",))
    target = {
        "m": _Holder(
            {"w": jax.device_put(jnp.zeros_like(arr), NamedSharding(mesh, P("x")))}
        )
    }
    Snapshot(path).restore(target)
    np.testing.assert_array_equal(
        np.asarray(target["m"].sd["w"]), np.asarray(arr)
    )


def test_streaming_detects_corrupt_chunk(tmp_path, small_scale):
    """Per-chunk incremental crc still gates exposure: corrupting ONE
    chunk object fails the restore."""
    arr = _arr(4 << 20, seed=5)
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": _Holder({"w": arr})})
    entry = Snapshot(path).get_manifest()["0/m/w"]
    victim = tmp_path / "snap" / entry.shards[2].array.location
    raw = bytearray(victim.read_bytes())
    raw[1000] ^= 0x55
    victim.write_bytes(bytes(raw))
    target = {"m": _Holder({"w": jnp.zeros_like(arr)})}
    with pytest.raises(RuntimeError, match="[Cc]hecksum"):
        Snapshot(path).restore(target)


def test_scheduler_device_budget_gates_consume_dispatch():
    """Unit: a consume with device cost is not dispatched while the
    device budget is exhausted and another consume is in flight; the
    releaser re-admits it."""
    events = []

    class _DevConsumer(BufferConsumer):
        def __init__(self, name, dcost, hold_s=0.0, release_after=None):
            self.name = name
            self.dcost = dcost
            self.hold_s = hold_s
            self.release_after = release_after
            self._release = None

        async def consume_buffer(self, buf, executor=None):
            events.append(f"start {self.name}")
            if self.hold_s:
                await asyncio.sleep(self.hold_s)
            if self.release_after is not None:
                self._release(self.release_after)
                events.append(f"release {self.name}")
            events.append(f"end {self.name}")

        def get_consuming_cost_bytes(self):
            return 1

        def get_device_cost_bytes(self):
            return self.dcost

        def set_device_cost_releaser(self, release):
            self._release = release

    class _OrderedStorage(MemoryStoragePlugin):
        # Deterministic read-completion order: a first (so its consume
        # holds the budget), then c, then b.
        _delays = {"a": 0.0, "c": 0.01, "b": 0.02}

        async def read(self, io_req):
            await asyncio.sleep(self._delays.get(io_req.path, 0.0))
            await super().read(io_req)

    async def _run():
        storage = _OrderedStorage()
        for p in ("a", "b", "c"):
            await storage.write(IOReq(path=p, data=b"x"))
        reqs = [
            # A: takes 80 of 100, holds it briefly then releases.
            ReadReq(
                path="a",
                buffer_consumer=_DevConsumer(
                    "A", dcost=80, hold_s=0.05, release_after=80
                ),
            ),
            # B: needs 50 — must wait for A's release.
            ReadReq(path="b", buffer_consumer=_DevConsumer("B", dcost=50)),
            # C: no device cost — dispatches freely.
            ReadReq(path="c", buffer_consumer=_DevConsumer("C", dcost=0)),
        ]
        await execute_read_reqs(
            reqs,
            storage,
            memory_budget_bytes=1 << 20,
            rank=0,
            device_budget_bytes=100,
        )

    asyncio.run(_run())
    # B waited for A's release; C (no device cost) skipped past the
    # blocked B instead of head-of-line blocking behind it.
    assert events.index("release A") < events.index("start B")
    assert events.index("start C") < events.index("start B")
