"""Data-parallel CNN workload: replicated params + batch-norm running
stats snapshot/resume (the "DDP ResNet" BASELINE config; reference
analog tests/test_ddp.py — DDP-replicated state saved with
replicated=["**"] and restored into a differently-initialized peer)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from torchsnapshot_tpu import Snapshot
from torchsnapshot_tpu.models.resnet import (
    ResNetConfig,
    dp_shard_batch,
    init_state,
    replicate_state,
    sgd_train_step,
    synthetic_batch,
)
from torchsnapshot_tpu.utils.test_utils import assert_state_dict_eq
from torchsnapshot_tpu.utils.train_state import PytreeStateful
from torchsnapshot_tpu.utils.tree import to_state_dict

CONFIG = ResNetConfig(widths=(8, 16), blocks_per_stage=2, image_size=8)


def _steps(params, stats, mesh, n, seed=1):
    losses = []
    step = jax.jit(
        lambda p, s, im, lb: sgd_train_step(p, s, im, lb, CONFIG)
    )
    for i in range(n):
        images, labels = synthetic_batch(CONFIG, 16, jax.random.key(seed + i))
        images = dp_shard_batch(images, mesh)
        labels = dp_shard_batch(labels, mesh)
        params, stats, loss = step(params, stats, images, labels)
        losses.append(float(loss))
    return params, stats, losses


def test_resnet_dp_snapshot_resume(tmp_path):
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    params, stats = init_state(CONFIG, jax.random.key(0))
    params, stats = replicate_state((params, stats), mesh)
    params, stats, first = _steps(params, stats, mesh, 2)
    assert all(np.isfinite(first))

    path = str(tmp_path / "snap")
    Snapshot.take(
        path,
        {"params": PytreeStateful(params), "stats": PytreeStateful(stats)},
        replicated=["**"],
    )
    expected = _steps(params, stats, mesh, 2, seed=9)[2]

    # Differently-initialized peer restores and must continue identically —
    # including the batch-norm running stats (a wrong resume here shifts
    # eval metrics, not train loss, so it must be checked stateside).
    params2, stats2 = init_state(CONFIG, jax.random.key(42))
    params2, stats2 = replicate_state((params2, stats2), mesh)
    target = {
        "params": PytreeStateful(params2),
        "stats": PytreeStateful(stats2),
    }
    Snapshot(path).restore(target)
    params2, stats2 = target["params"].tree, target["stats"].tree
    assert_state_dict_eq(to_state_dict(params), to_state_dict(params2))
    assert_state_dict_eq(to_state_dict(stats), to_state_dict(stats2))

    resumed = _steps(params2, stats2, mesh, 2, seed=9)[2]
    assert resumed == expected  # bit-exact resume on the same devices


def test_resnet_bn_stats_actually_update(tmp_path):
    """Guards the test above from vacuity: the running stats must change
    during training, or restoring them proves nothing."""
    params, stats = init_state(CONFIG, jax.random.key(0))
    _, new_stats, _ = _steps(params, stats, None, 1)
    before = np.asarray(stats["stages"][0][0]["bn1"]["mean"])
    after = np.asarray(new_stats["stages"][0][0]["bn1"]["mean"])
    assert not np.array_equal(before, after)
