"""IO preparer tests over an 8-device virtual CPU mesh (reference analog:
tests/test_sharded_tensor_io_preparer.py:28-230)."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import torchsnapshot_tpu.io_preparer as io_preparer_mod
from torchsnapshot_tpu.io_preparer import (
    ArrayRestorePlan,
    prepare_read,
    prepare_write,
)
from torchsnapshot_tpu.manifest import (
    ArrayEntry,
    ObjectEntry,
    PrimitiveEntry,
    ShardedArrayEntry,
)
from torchsnapshot_tpu.scheduler import execute_read_reqs, execute_write_reqs
from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin


def _mesh(shape, axes):
    devices = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devices, axes)


def _save_and_load(value, template, storage=None):
    storage = storage or MemoryStoragePlugin()
    entry, write_reqs = prepare_write(value, "sf/x", rank=0)
    asyncio.run(execute_write_reqs(write_reqs, storage, 1 << 30, rank=0))
    out = {}
    read_reqs, finalizers = prepare_read(entry, template, out.__setitem__
                                         if False else (lambda v: out.update(v=v)))
    asyncio.run(execute_read_reqs(read_reqs, storage, 1 << 30, rank=0))
    for fin in finalizers:
        fin()
    return entry, out.get("v"), storage


def test_numpy_round_trip():
    arr = np.arange(24, dtype=np.float32).reshape(4, 6)
    entry, restored, _ = _save_and_load(arr, np.empty_like(arr))
    assert isinstance(entry, ArrayEntry)
    assert entry.dtype == "float32"
    np.testing.assert_array_equal(restored, arr)


def test_bfloat16_bit_exact():
    arr = jnp.asarray(np.random.RandomState(0).randn(16, 8), dtype=jnp.bfloat16)
    entry, restored, _ = _save_and_load(arr, arr)
    assert entry.dtype == "bfloat16"
    assert restored.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(restored).view(np.uint16), np.asarray(arr).view(np.uint16)
    )


@pytest.mark.parametrize(
    "dtype",
    [
        "float16",
        "float32",
        "float64",
        "bfloat16",
        "float8_e4m3fn",
        "float8_e5m2",
        "int8",
        "int16",
        "int32",
        "int64",
        "uint8",
        "uint32",
        "bool_",
        "complex64",
    ],
)
def test_dtype_matrix_bit_exact(dtype):
    """Raw-payload serialization must round-trip every dtype a training
    program can hold bit-exactly (SURVEY §7 hard part #4) — including the
    ml_dtypes families (bfloat16/float8) that lack the buffer protocol.

    64-bit cases use host numpy arrays: without jax_enable_x64, jnp
    silently truncates them to 32 bits and the test would be vacuous —
    and host-side 64-bit state (numpy RNG, progress counters) is exactly
    what those dtypes hold in practice."""
    rng = np.random.RandomState(0)
    if dtype in ("float64", "int64"):
        arr = np.asarray(
            rng.randn(9, 5) * 100, dtype=getattr(np, dtype)
        )
    elif dtype == "bool_":
        arr = jnp.asarray(rng.rand(9, 5) > 0.5)
    elif dtype == "complex64":
        arr = jnp.asarray(
            (rng.randn(9, 5) + 1j * rng.randn(9, 5)).astype(np.complex64)
        )
    elif dtype.startswith(("int", "uint")):
        arr = jnp.asarray(rng.randint(0, 100, (9, 5)), dtype=getattr(jnp, dtype))
    else:
        arr = jnp.asarray(rng.randn(9, 5), dtype=getattr(jnp, dtype))
    entry, restored, _ = _save_and_load(arr, arr)
    assert restored.dtype == arr.dtype
    a = np.asarray(restored)
    b = np.asarray(arr)
    # Compare raw bytes, not values: NaNs and negative zeros must survive.
    np.testing.assert_array_equal(
        a.view(np.uint8).reshape(-1), b.view(np.uint8).reshape(-1)
    )


def test_scalar_array_round_trip():
    arr = jnp.asarray(3.5)
    entry, restored, _ = _save_and_load(arr, arr)
    assert restored.shape == ()
    assert float(restored) == 3.5


def test_primitive_inline():
    entry, write_reqs = prepare_write(42, "sf/epoch", rank=0)
    assert isinstance(entry, PrimitiveEntry)
    assert write_reqs == []
    out = {}
    reqs, fins = prepare_read(entry, None, lambda v: out.update(v=v))
    assert reqs == [] and fins == []
    assert out["v"] == 42


def test_object_round_trip():
    value = {"nested": [1, 2], "s": "hello"}
    entry, restored, _ = _save_and_load(value, None)
    assert isinstance(entry, ObjectEntry)
    assert restored == value


def test_sharded_write_produces_chunks():
    mesh = _mesh((8,), ("x",))
    arr = jax.device_put(
        jnp.arange(64, dtype=jnp.float32).reshape(16, 4),
        NamedSharding(mesh, P("x", None)),
    )
    entry, write_reqs = prepare_write(arr, "sf/w", rank=0)
    assert isinstance(entry, ShardedArrayEntry)
    assert len(entry.shards) == 8
    assert len(write_reqs) == 8
    offsets = sorted(s.offsets[0] for s in entry.shards)
    assert offsets == [0, 2, 4, 6, 8, 10, 12, 14]


def test_sharded_replica_dedupe():
    # P("x", None) on a (4, 2) mesh: axis "y" replicates -> only 4 chunks.
    mesh = _mesh((4, 2), ("x", "y"))
    arr = jax.device_put(
        jnp.arange(32, dtype=jnp.float32).reshape(8, 4),
        NamedSharding(mesh, P("x", None)),
    )
    entry, write_reqs = prepare_write(arr, "sf/w", rank=0)
    assert isinstance(entry, ShardedArrayEntry)
    assert len(entry.shards) == 4
    assert len(write_reqs) == 4


def test_fully_replicated_is_dense():
    mesh = _mesh((8,), ("x",))
    arr = jax.device_put(
        jnp.arange(8, dtype=jnp.float32), NamedSharding(mesh, P(None))
    )
    entry, write_reqs = prepare_write(arr, "sf/w", rank=0)
    assert isinstance(entry, ArrayEntry)
    assert len(write_reqs) == 1


@pytest.mark.parametrize(
    "src_spec,dst_spec",
    [
        (P("x", None), P("x", None)),  # same sharding
        (P("x", None), P(None, "x")),  # transpose the sharded dim
        (P("x", None), P(None)),  # sharded -> replicated
        (P(None), P("x", None)),  # dense -> sharded
        (P(("x", "y"), None), P("y", "x")),  # 2D resharding
        (P("x", "y"), P("y", None)),  # swap axes
    ],
)
def test_reshard_round_trip(src_spec, dst_spec):
    mesh = _mesh((4, 2), ("x", "y"))
    data = jnp.arange(16 * 8, dtype=jnp.float32).reshape(16, 8)
    src = jax.device_put(data, NamedSharding(mesh, src_spec))
    dst_template = jax.device_put(jnp.zeros_like(data), NamedSharding(mesh, dst_spec))
    entry, restored, _ = _save_and_load(src, dst_template)
    assert restored.sharding.is_equivalent_to(dst_template.sharding, data.ndim)
    np.testing.assert_array_equal(np.asarray(restored), np.asarray(data))


def test_reshard_to_smaller_mesh():
    mesh8 = _mesh((8,), ("x",))
    mesh2 = _mesh((2,), ("x",))
    data = jnp.arange(64, dtype=jnp.float32).reshape(16, 4)
    src = jax.device_put(data, NamedSharding(mesh8, P("x", None)))
    dst_template = jax.device_put(
        jnp.zeros_like(data), NamedSharding(mesh2, P("x", None))
    )
    _, restored, _ = _save_and_load(src, dst_template)
    np.testing.assert_array_equal(np.asarray(restored), np.asarray(data))


def test_uneven_chunk_layout_restore():
    # Restore from a hand-built manifest whose chunks are uneven (3+3+4+6
    # rows — e.g. saved by a world with a different subdivision policy)
    # into an evenly-sharded template. JAX itself only produces divisible
    # shardings, but elastic restore must accept any saved chunk layout
    # (reference edge case: non-divisible max_shard_sz_bytes,
    # tests/gpu_tests/test_torchrec.py:165-169).
    from torchsnapshot_tpu.manifest import Shard
    from torchsnapshot_tpu.serialization import array_to_bytes

    data = np.arange(16 * 4, dtype=np.float32).reshape(16, 4)
    storage = MemoryStoragePlugin()
    shards = []
    row_splits = [(0, 3), (3, 3), (6, 4), (10, 6)]
    for start, n in row_splits:
        loc = f"sharded/sf/w_{start}_0"
        storage.store[loc] = bytes(array_to_bytes(data[start:start + n]))
        shards.append(
            Shard(
                offsets=[start, 0],
                sizes=[n, 4],
                array=ArrayEntry(
                    location=loc,
                    serializer="raw",
                    dtype="float32",
                    shape=[n, 4],
                    replicated=False,
                ),
            )
        )
    entry = ShardedArrayEntry(dtype="float32", shape=[16, 4], shards=shards)
    mesh = _mesh((8,), ("x",))
    template = jax.device_put(
        jnp.zeros((16, 4), dtype=jnp.float32), NamedSharding(mesh, P("x", None))
    )
    out = {}
    reqs, fins = prepare_read(entry, template, lambda v: out.update(v=v))
    asyncio.run(execute_read_reqs(reqs, storage, 1 << 30, rank=0))
    for fin in fins:
        fin()
    np.testing.assert_array_equal(np.asarray(out["v"]), data)


def test_chunk_subdivision(monkeypatch):
    monkeypatch.setattr(io_preparer_mod, "MAX_CHUNK_SIZE_BYTES", 64)
    mesh = _mesh((2,), ("x",))
    data = jnp.arange(16 * 4, dtype=jnp.float32).reshape(16, 4)
    src = jax.device_put(data, NamedSharding(mesh, P("x", None)))
    entry, write_reqs = prepare_write(src, "sf/w", rank=0)
    # Each 8x4 shard = 128 bytes -> 2 chunks each.
    assert len(entry.shards) == 4
    assert len(write_reqs) == 4
    storage = MemoryStoragePlugin()
    asyncio.run(execute_write_reqs(write_reqs, storage, 1 << 30, rank=0))
    out = {}
    reqs, fins = prepare_read(
        entry, jax.device_put(jnp.zeros_like(data), NamedSharding(mesh, P(None, "x"))),
        lambda v: out.update(v=v),
    )
    asyncio.run(execute_read_reqs(reqs, storage, 1 << 30, rank=0))
    for fin in fins:
        fin()
    np.testing.assert_array_equal(np.asarray(out["v"]), np.asarray(data))


def test_ranged_reads_used_for_partial_overlap():
    # Dense saved array restored into a row-sharded template: each shard
    # should issue a ranged read, not read the whole object 8 times.
    mesh = _mesh((8,), ("x",))
    data = jnp.arange(16 * 4, dtype=jnp.float32).reshape(16, 4)
    entry, write_reqs = prepare_write(np.asarray(data), "sf/w", rank=0)
    template = jax.device_put(data, NamedSharding(mesh, P("x", None)))
    out = {}
    reqs, fins = prepare_read(entry, template, lambda v: out.update(v=v))
    assert len(reqs) == 8
    assert all(r.byte_range is not None for r in reqs)
    spans = sorted(r.byte_range for r in reqs)
    assert spans[0][0] == 0 and spans[-1][1] == 16 * 4 * 4
    storage = MemoryStoragePlugin()
    asyncio.run(execute_write_reqs(write_reqs, storage, 1 << 30, rank=0))
    asyncio.run(execute_read_reqs(reqs, storage, 1 << 30, rank=0))
    for fin in fins:
        fin()
    np.testing.assert_array_equal(np.asarray(out["v"]), np.asarray(data))


def test_prng_key_round_trip():
    key = jax.random.key(42)
    entry, restored, _ = _save_and_load(key, key)
    assert jax.dtypes.issubdtype(restored.dtype, jax.dtypes.prng_key)
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(restored)),
        np.asarray(jax.random.key_data(key)),
    )
    # The restored key must produce the identical stream.
    a = jax.random.normal(key, (4,))
    b = jax.random.normal(restored, (4,))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_legacy_prng_key_round_trip():
    key = jax.random.PRNGKey(7)  # uint32 array, not a typed key
    entry, restored, _ = _save_and_load(key, key)
    np.testing.assert_array_equal(np.asarray(restored), np.asarray(key))


def test_shape_mismatch_raises():
    arr = np.zeros((4, 4), dtype=np.float32)
    entry, _ = prepare_write(arr, "sf/w", rank=0)
    with pytest.raises(RuntimeError, match="shape"):
        ArrayRestorePlan(entry, np.zeros((2, 2), dtype=np.float32), lambda v: None)


def test_int_dtypes_round_trip():
    for dtype in [np.int8, np.uint8, np.int32, np.int64, np.uint32, np.float64]:
        arr = np.arange(10).astype(dtype)
        _, restored, _ = _save_and_load(arr, np.empty_like(arr))
        np.testing.assert_array_equal(restored, arr)
        assert restored.dtype == dtype
