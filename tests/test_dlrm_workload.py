"""Embedding-parallel DLRM workload: snapshot/restore row-sharded tables +
sharded momentum across mesh shapes.

The TPU-scale analog of the reference's torchrec DLRM flagship
(tests/gpu_tests/test_torchrec.py:88-170: row-wise sharded
EmbeddingBagCollection + fused optimizer, snapshot, restore into a
differently-initialized peer), on the 8-device virtual CPU mesh —
including restoring onto a different "ep" width (elastic) and a
non-divisible table/mesh boundary.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding

from torchsnapshot_tpu import Snapshot
from torchsnapshot_tpu.models.dlrm import (
    DLRMConfig,
    init_momentum,
    init_params,
    shard_params,
    sgd_momentum_train_step,
    synthetic_batch,
)
from torchsnapshot_tpu.utils.test_utils import assert_state_dict_eq
from torchsnapshot_tpu.utils.train_state import PytreeStateful
from torchsnapshot_tpu.utils.tree import to_state_dict

CONFIG = DLRMConfig(
    table_rows={"user": 1024, "item": 2048, "cat": 512},
    embed_dim=16,
    dense_in=8,
    bag_len=4,
    bottom_mlp=(32, 16),
    top_mlp=(32, 1),
)


def _ep_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("ep",))


def _make_state(mesh):
    params = shard_params(init_params(CONFIG, jax.random.key(0)), mesh)
    momentum = shard_params(init_momentum(params), mesh)
    return params, momentum


def _steps(params, momentum, mesh, n, seed=1):
    losses = []
    for i in range(n):
        dense, sparse, labels = synthetic_batch(
            CONFIG, 16, jax.random.key(seed + i)
        )
        params, momentum, loss = sgd_momentum_train_step(
            params, momentum, dense, sparse, labels, CONFIG, mesh
        )
        losses.append(float(loss))
    return params, momentum, losses


@pytest.mark.parametrize("take_mode", ["sync", "async"])
def test_dlrm_elastic_resume(tmp_path, take_mode):
    mesh = _ep_mesh(8)
    params, momentum = _make_state(mesh)
    params, momentum, _ = _steps(params, momentum, mesh, 2)

    app = {
        "params": PytreeStateful(params),
        "momentum": PytreeStateful(momentum),
    }
    path = str(tmp_path / "snap")
    if take_mode == "sync":
        Snapshot.take(path, app)
    else:
        Snapshot.async_take(path, app, stage="device").wait()

    expected = _steps(params, momentum, mesh, 2, seed=9)[2]

    # Elastic restore onto a narrower ep mesh (8 -> 4 devices).
    mesh2 = _ep_mesh(4)
    params2, momentum2 = _make_state(mesh2)
    # zeros_like preserves each leaf's NamedSharding on the new mesh.
    params2 = jax.tree.map(jnp.zeros_like, params2)
    momentum2 = jax.tree.map(jnp.zeros_like, momentum2)
    target = {
        "params": PytreeStateful(params2),
        "momentum": PytreeStateful(momentum2),
    }
    Snapshot(path).restore(target)
    params2, momentum2 = target["params"].tree, target["momentum"].tree

    assert_state_dict_eq(to_state_dict(params), to_state_dict(params2))
    assert_state_dict_eq(to_state_dict(momentum), to_state_dict(momentum2))

    resumed = _steps(params2, momentum2, mesh2, 2, seed=9)[2]
    np.testing.assert_allclose(resumed, expected, rtol=1e-6)


def test_dlrm_uneven_chunk_subdivision_roundtrip(tmp_path, monkeypatch):
    """Force a max chunk size that does not divide the per-device table
    shards (the reference's non-divisible max_shard_sz_bytes edge case,
    tests/gpu_tests/test_torchrec.py:165-169): every chunk boundary must
    still round-trip exactly."""
    from torchsnapshot_tpu import io_preparer as io_preparer_mod

    mesh = _ep_mesh(8)
    params, momentum = _make_state(mesh)

    # user shard = 128 rows x 16 f32 = 8192 B; 3000 does not divide it.
    monkeypatch.setattr(io_preparer_mod, "MAX_CHUNK_SIZE_BYTES", 3000)
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"params": PytreeStateful(params)})

    fresh = shard_params(
        jax.tree.map(jnp.zeros_like, init_params(CONFIG, jax.random.key(3))),
        mesh,
    )
    target = {"params": PytreeStateful(fresh)}
    Snapshot(path).restore(target)
    got = target["params"].tree
    np.testing.assert_array_equal(
        np.asarray(got["tables"]["cat"]), np.asarray(params["tables"]["cat"])
    )
    assert_state_dict_eq(to_state_dict(params), to_state_dict(got))


def test_dlrm_train_step_jits_sharded():
    """The full train step jits over the ep mesh (collective gather over
    the row-sharded tables compiles and runs)."""
    mesh = _ep_mesh(8)
    params, momentum = _make_state(mesh)
    dense, sparse, labels = synthetic_batch(CONFIG, 16, jax.random.key(5))

    stepped = jax.jit(
        lambda p, m: sgd_momentum_train_step(
            p, m, dense, sparse, labels, CONFIG, mesh
        )
    )(params, momentum)
    new_params, new_momentum, loss = stepped
    assert np.isfinite(float(loss))
    # Momentum keeps the tables' row-sharded layout.
    sh = new_momentum["tables"]["user"].sharding
    assert isinstance(sh, NamedSharding)
    assert tuple(sh.spec) in ((("ep",)), ("ep", None))
