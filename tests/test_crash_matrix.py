"""Crash-consistency matrix: replay the save→commit→prune pipeline
crashing at every storage-op boundary and assert the restore-or-detect
invariant (docs/FAULTS.md) — the dynamic counterpart to snapcheck's
static durability-ordering proof.

Fast tier (``-m faultline``, runs in tier-1): a stride sample of crash
points on both backends plus the targeted prune-phase and finalize
scenarios. Full enumeration of every op boundary is also marked
``slow``.
"""

import os
import uuid

import numpy as np
import pytest

import jax.numpy as jnp

from torchsnapshot_tpu import CheckpointManager, StateDict
from torchsnapshot_tpu import faultline as fl
from torchsnapshot_tpu.manager import _PRUNING_PREFIX, _STEP_PREFIX

pytestmark = pytest.mark.faultline


def _state(v):
    return {"s": StateDict(w=jnp.full((4,), float(v)))}


def _target():
    return {"s": StateDict(w=jnp.zeros((4,)))}


def _probe(base):
    def probe(step):
        target = _target()
        got = CheckpointManager(base).restore(target, step=step)
        assert got == step
        np.testing.assert_array_equal(
            np.asarray(target["s"]["w"]), float(step)
        )

    return probe


def _prepare_fs(tmp_path_factory):
    base = str(tmp_path_factory.mktemp("crash") / "run")
    mgr = CheckpointManager(base, max_to_keep=1)
    mgr.save(0, _state(0))
    mgr.save(1, _state(1))
    return base


def _prepare_memory(_tmp_path_factory):
    base = f"memory://crashmx-{uuid.uuid4().hex[:10]}/run"
    mgr = CheckpointManager(base, max_to_keep=1)
    mgr.save(0, _state(0))
    mgr.save(1, _state(1))
    return base


def _faulted(base):
    # One full lifecycle op: take step 2, commit its marker, prune step 1.
    CheckpointManager(base, max_to_keep=1).save(2, _state(2))


def _check(base, outcome):
    # (a)/(b): every visible marker restores clean; reconcile adopts
    # committed-unmarked work (also verified restorable).
    res = fl.check_recovery_invariant(base, _probe(base))
    outcome.marked_steps = res.marked_steps
    outcome.adopted_steps = res.adopted_steps
    # Recovery re-drive: the next save→prune cycle must succeed and
    # re-drive any interrupted prune; reconcile then reclaims crashed
    # uncommitted takes; nothing may leak.
    mgr = CheckpointManager(base, max_to_keep=1, reconcile_on_init="adopt")
    mgr.save(3, _state(3))
    mgr.reconcile(adopt=True)
    assert mgr.latest_step() == 3
    _probe(base)(3)
    fl.assert_reclaimed(base, [3])


_PREPARES = {"fs": _prepare_fs, "memory": _prepare_memory}


@pytest.mark.parametrize("backend", ["fs", "memory"])
def test_crash_matrix_fast_subset(backend, tmp_path_factory, monkeypatch):
    """Stride-sampled crash points across the whole cycle (tier-1)."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    prepare = _PREPARES[backend]
    base = prepare(tmp_path_factory)
    total = fl.count_storage_ops(lambda: _faulted(base))
    assert total > 0
    # ~6 points spread over the op stream, always including the first
    # and last boundaries (commit edges live there).
    stride = max(1, total // 5)
    points = sorted(set(range(1, total + 1, stride)) | {1, total})
    report = fl.enumerate_crash_points(
        lambda: prepare(tmp_path_factory),
        _faulted,
        _check,
        points,
        total_ops=total,
    )
    assert report.total_ops == total
    assert set(report.outcomes) == set(points)


@pytest.mark.slow
@pytest.mark.parametrize("backend", ["fs", "memory"])
def test_crash_matrix_full_enumeration(backend, tmp_path_factory, monkeypatch):
    """EVERY storage-op boundary of the save→commit→prune cycle,
    including fs.py's write→fsync→rename→dir-fsync sub-steps."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    prepare = _PREPARES[backend]
    report = fl.enumerate_crash_points(
        lambda: prepare(tmp_path_factory), _faulted, _check
    )
    assert len(report.outcomes) == report.total_ops
    assert all(o.crashed for o in report.outcomes.values())
    # The matrix must actually span the lifecycle: some crash points land
    # before the take commits (step 2 invisible or adopted), some after
    # (step 2 marked).
    kinds = {
        (2 in o.marked_steps, 2 in o.adopted_steps)
        for o in report.outcomes.values()
    }
    assert (True, False) in kinds  # crashed after the marker commit
    assert (False, False) in kinds or (False, True) in kinds  # before


# ----------------------------------------------------- interrupted _prune


def _prune_crash_scenario(tmp_path, monkeypatch, crash_rule):
    """Build 2 committed steps, crash mid-prune of step 0 per
    ``crash_rule``, and return the base path. max_to_keep=1 makes
    save(1) prune step 0."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    base = str(tmp_path / "run")
    CheckpointManager(base).save(0, _state(0))
    sched = fl.FaultSchedule()
    crash_rule(sched)
    with fl.inject(sched) as ctl:
        with pytest.raises(fl.SimulatedCrash):
            CheckpointManager(base, max_to_keep=1).save(1, _state(1))
    assert ctl.fault_counts().get("crash", 0) >= 1
    return base


def _assert_prune_redriven(base):
    """The crashed prune's step is fully reclaimed by the NEXT cycle and
    live steps survive with their values."""
    # Live state immediately after the crash: step 1 committed, restorable.
    mgr = CheckpointManager(base)
    assert 1 in mgr.all_steps()
    _probe(base)(1)
    # Re-drive: the next save's prune finishes step 0's deletion (via
    # tombstone or marker), prunes step 1, and leaves no debris.
    mgr2 = CheckpointManager(base, max_to_keep=1, reconcile_on_init="adopt")
    mgr2.save(2, _state(2))
    mgr2.reconcile(adopt=True)
    assert mgr2.all_steps() == [2]
    _probe(base)(2)
    fl.assert_reclaimed(base, [2])


def test_prune_crash_before_tombstone_write(tmp_path, monkeypatch):
    base = _prune_crash_scenario(
        tmp_path,
        monkeypatch,
        lambda s: s.crash_on(op="write", path=f"{_PRUNING_PREFIX}0"),
    )
    # Nothing happened yet: step 0's marker must still resolve it.
    assert CheckpointManager(base).all_steps() == [0, 1]
    _probe(base)(0)
    _assert_prune_redriven(base)


def test_prune_crash_between_tombstone_and_marker_delete(
    tmp_path, monkeypatch
):
    base = _prune_crash_scenario(
        tmp_path,
        monkeypatch,
        lambda s: s.crash_on(op="delete", path=f"{_STEP_PREFIX}0"),
    )
    # Tombstone written, marker still visible: the step must STILL be
    # fully restorable (payload deletion is ordered after marker delete).
    assert CheckpointManager(base).all_steps() == [0, 1]
    _probe(base)(0)
    _assert_prune_redriven(base)


def test_prune_crash_between_marker_delete_and_payload_delete(
    tmp_path, monkeypatch
):
    # First payload-prefix delete is the step's metadata uncommit.
    base = _prune_crash_scenario(
        tmp_path,
        monkeypatch,
        lambda s: s.crash_on(op="delete", path=".snapshot_metadata"),
    )
    # Marker gone: step 0 is invisible (unresolvable) even though its
    # payloads survive — and reconcile must NOT resurrect a condemned
    # (tombstoned) step.
    mgr = CheckpointManager(base)
    assert mgr.all_steps() == [1]
    assert mgr.reconcile(adopt=True) == []
    assert mgr.all_steps() == [1]
    _assert_prune_redriven(base)


def test_prune_crash_mid_payload_deletes(tmp_path, monkeypatch):
    base = _prune_crash_scenario(
        tmp_path,
        monkeypatch,
        # Payload objects live under "<rank>/..." within the step root.
        lambda s: s.crash_on(op="delete", path="0/*"),
    )
    assert CheckpointManager(base).all_steps() == [1]
    _assert_prune_redriven(base)


def test_prune_crash_before_tombstone_delete(tmp_path, monkeypatch):
    base = _prune_crash_scenario(
        tmp_path,
        monkeypatch,
        lambda s: s.crash_on(op="delete", path=f"{_PRUNING_PREFIX}0"),
    )
    # Payloads fully deleted; only the tombstone lingers. The next prune
    # pass clears it.
    assert CheckpointManager(base).all_steps() == [1]
    _assert_prune_redriven(base)


# ----------------------------------------- async finalize retriability


def test_async_wait_retries_transient_marker_failure(tmp_path, monkeypatch):
    """A transient marker-write failure during _finalize must leave the
    step finalizable on the next wait(), not silently skipped."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRIES", "0")
    base = str(tmp_path / "run")
    mgr = CheckpointManager(base, max_to_keep=2)
    sched = fl.FaultSchedule().transient(
        op="write", path=f"{_STEP_PREFIX}7", times=1
    )
    with fl.inject(sched) as ctl:
        handle = mgr.async_save(7, _state(7))
        with pytest.raises(fl.InjectedTransientError):
            handle.wait()
        # The snapshot itself committed; only the marker is missing.
        assert mgr.latest_step() is None
        snap = handle.wait()  # idempotent drain; _finalize retries
    assert ctl.fault_counts() == {"transient": 1}
    assert mgr.latest_step() == 7
    _probe(base)(7)
    assert snap.path.endswith("step-7")


def test_async_wait_crash_orphan_adopted_by_reconcile(tmp_path, monkeypatch):
    """Process death between the background commit and wait(): the step
    is committed-but-unmarked, and reconcile(adopt=True) recovers it."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    base = str(tmp_path / "run")
    mgr = CheckpointManager(base, max_to_keep=2)
    sched = fl.FaultSchedule().crash_on(
        op="write", path=f"{_STEP_PREFIX}3"
    )
    with fl.inject(sched):
        handle = mgr.async_save(3, _state(3))
        with pytest.raises(fl.SimulatedCrash):
            handle.wait()
    mgr2 = CheckpointManager(base)
    assert mgr2.all_steps() == []
    assert mgr2.reconcile(adopt=True) == [3]
    assert mgr2.all_steps() == [3]
    _probe(base)(3)


# ------------------------------------------------- uncommitted-take debris


@pytest.mark.parametrize("backend", ["fs", "memory"])
def test_reconcile_reclaims_crashed_uncommitted_take(
    backend, tmp_path, monkeypatch
):
    """A take that crashes before its commit point leaves payloads no
    marker/metadata/tombstone will ever name; reconcile sweeps them."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    if backend == "fs":
        base = str(tmp_path / "run")
    else:
        base = f"memory://uncmt-{uuid.uuid4().hex[:10]}/run"
    mgr = CheckpointManager(base, max_to_keep=2)
    mgr.save(0, _state(0))
    sched = fl.FaultSchedule().crash_on(op="write", path=".snapshot_metadata")
    with fl.inject(sched):
        with pytest.raises(fl.SimulatedCrash):
            CheckpointManager(base, max_to_keep=2).save(1, _state(1))
    mgr2 = CheckpointManager(base)
    assert mgr2.all_steps() == [0]  # detectably incomplete: unresolvable
    handled = mgr2.reconcile(adopt=True)
    assert 1 in handled  # reclaimed, not adopted (no commit point)
    assert mgr2.all_steps() == [0]
    fl.assert_reclaimed(base, [0])


def test_reconcile_age_guard_spares_young_uncommitted_take(
    tmp_path, monkeypatch
):
    """The sweep age guard must protect an in-flight take: young
    uncommitted objects survive reconcile."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "3600")
    base = str(tmp_path / "run")
    mgr = CheckpointManager(base, max_to_keep=2)
    mgr.save(0, _state(0))
    sched = fl.FaultSchedule().crash_on(op="write", path=".snapshot_metadata")
    with fl.inject(sched):
        with pytest.raises(fl.SimulatedCrash):
            CheckpointManager(base, max_to_keep=2).save(1, _state(1))
    mgr2 = CheckpointManager(base)
    handled = mgr2.reconcile(adopt=True)
    assert 1 not in handled
    leftovers = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(os.path.join(base, "step-1"))
        for f in fs
    ]
    assert leftovers  # spared — it might be someone's in-progress take
