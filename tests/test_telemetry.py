"""snapstats: metrics registry, exporters, tracing crash-safety, and the
faultline→telemetry bridge (ISSUE 3)."""

import asyncio
import json
import os
import threading

import numpy as np
import pytest

import jax.numpy as jnp

from torchsnapshot_tpu import Snapshot, StateDict, telemetry, tracing
from torchsnapshot_tpu.telemetry import export as tele_export
from torchsnapshot_tpu.telemetry import metrics as tm


@pytest.fixture(autouse=True)
def _fresh_registry():
    telemetry.reset()
    yield
    telemetry.reset()


class _Model:
    def __init__(self, params):
        self.params = params

    def state_dict(self):
        return self.params

    def load_state_dict(self, sd):
        self.params = sd


# ------------------------------------------------------------------ registry


def test_counter_gauge_histogram_basics():
    c = telemetry.counter("t_total", op="write")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)

    g = telemetry.gauge("t_gauge")
    g.set(7)
    g.set_max(3)  # lower: no-op
    assert g.value == 7
    g.set_max(11)
    assert g.value == 11

    h = telemetry.histogram("t_hist")
    for v in (0.3, 0.6, 1.0, 100.0):
        h.observe(v)
    data = h.collect()
    assert data["count"] == 4
    assert data["sum"] == pytest.approx(101.9)
    # log2 buckets: 0.3→0.5, 0.6→1, 1.0→1, 100→128
    assert data["buckets"] == {"0.5": 1, "1": 2, "128": 1}


def test_bucket_le_edges():
    assert tm.bucket_le(0) == 0.0
    assert tm.bucket_le(-3) == 0.0
    assert tm.bucket_le(1.0) == 1.0  # exact power stays in its own bucket
    assert tm.bucket_le(2.0) == 2.0
    assert tm.bucket_le(2.1) == 4.0
    assert tm.bucket_le(0.25) == 0.25


def test_same_labels_same_metric_instance():
    assert telemetry.counter("t_c", a="1", b="2") is telemetry.counter(
        "t_c", b="2", a="1"
    )
    assert telemetry.counter("t_c") is not telemetry.counter("t_c", a="1")


def test_name_bound_to_one_kind():
    telemetry.counter("t_kind")
    with pytest.raises(ValueError, match="already registered"):
        telemetry.gauge("t_kind")


def test_snapshot_and_diff():
    telemetry.counter("t_n", op="w").inc(3)
    before = telemetry.snapshot()
    assert before['t_n{op="w"}'] == 3
    telemetry.counter("t_n", op="w").inc(2)
    telemetry.histogram("t_h").observe(1.5)
    delta = telemetry.diff_snapshots(before, telemetry.snapshot())
    assert delta['t_n{op="w"}'] == 2
    assert delta["t_h"]["count"] == 1
    # zero-delta samples are dropped
    telemetry.counter("t_quiet").inc(1)
    before2 = telemetry.snapshot()
    assert "t_quiet" not in telemetry.diff_snapshots(
        before2, telemetry.snapshot()
    )


def test_counter_thread_safety():
    c = telemetry.counter("t_race")

    def worker():
        for _ in range(10_000):
            c.inc()

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 80_000


# ----------------------------------------------------------------- exporters


def test_prometheus_textfile_round_trip(tmp_path):
    telemetry.counter("t_ops_total", op="write").inc(5)
    telemetry.gauge("t_hwm", pipeline="read").set(1024)
    h = telemetry.histogram("t_lat_seconds", op="read")
    for v in (0.001, 0.002, 0.5, 3.0):
        h.observe(v)
    path = str(tmp_path / "metrics.prom")
    tele_export.write_textfile(path)
    with open(path) as f:
        doc = f.read()
    parsed = tele_export.parse_textfile(doc)
    assert parsed["t_ops_total"]["type"] == "counter"
    assert parsed["t_ops_total"]["samples"]['t_ops_total{op="write"}'] == 5
    assert parsed["t_hwm"]["samples"]['t_hwm{pipeline="read"}'] == 1024
    hist = parsed["t_lat_seconds"]["samples"]
    assert hist['t_lat_seconds_count{op="read"}'] == 4
    assert hist['t_lat_seconds_sum{op="read"}'] == pytest.approx(3.503)
    # +Inf bucket present and equal to count (validated by the parser,
    # asserted here too so a parser regression cannot mask it)
    assert hist['t_lat_seconds_bucket{le="+Inf",op="read"}'] == 4
    # no tmp debris from the atomic write
    assert [p for p in os.listdir(tmp_path) if ".tmp" in p] == []


def test_textfile_parser_rejects_garbage():
    with pytest.raises(ValueError, match="malformed sample"):
        tele_export.parse_textfile("this is { not a metric\n")
    with pytest.raises(ValueError, match="malformed labels"):
        tele_export.parse_textfile('m{op=unquoted} 1\n')
    with pytest.raises(ValueError, match=r"\+Inf"):
        tele_export.parse_textfile(
            "# TYPE h histogram\n"
            'h_bucket{le="1"} 2\n'
            "h_count 2\n"
        )


def test_label_value_escaping_round_trips():
    telemetry.counter("t_esc", detail='quote"back\\slash').inc()
    parsed = tele_export.parse_textfile(tele_export.render_textfile())
    (key,) = parsed["t_esc"]["samples"]
    assert 'quote' in key
    assert parsed["t_esc"]["samples"][key] == 1


def test_jsonl_append(tmp_path):
    path = str(tmp_path / "t.jsonl")
    tele_export.append_jsonl(path, {"a": 1})
    tele_export.append_jsonl(path, {"b": 2})
    with open(path) as f:
        lines = [json.loads(line) for line in f]
    assert lines == [{"a": 1}, {"b": 2}]


def test_env_auto_export(tmp_path, monkeypatch):
    """A take with the env knobs set rewrites the textfile and appends a
    flight summary line — the always-on exporter wiring. The textfile
    lands at a per-process (.pid<N>) path so ranks sharing the env var
    cannot clobber each other's exposition."""
    prom = str(tmp_path / "m.prom")
    jsonl = str(tmp_path / "t.jsonl")
    monkeypatch.setenv(tele_export.TEXTFILE_ENV_VAR, prom)
    monkeypatch.setenv(tele_export.JSONL_ENV_VAR, jsonl)
    model = _Model({"w": jnp.arange(64, dtype=jnp.float32)})
    Snapshot.take(str(tmp_path / "snap"), {"model": model})
    prom_actual = str(tmp_path / f"m.pid{os.getpid()}.prom")
    parsed = tele_export.parse_textfile(open(prom_actual).read())
    assert 'tpusnapshot_takes_total{mode="sync"}' in (
        parsed[tm.TAKES_TOTAL]["samples"]
    )
    with open(jsonl) as f:
        (record,) = [json.loads(line) for line in f]
    assert record["kind"] == "take"
    assert record["bytes"] == 64 * 4


# ---------------------------------------------------------- scheduler metrics


def test_take_records_scheduler_and_storage_metrics(tmp_path):
    model = _Model({"w": np.arange(2048, dtype=np.float32)})
    Snapshot.take("memory://telemetry-sched/snap", {"model": model})
    snap = telemetry.snapshot()
    assert snap['tpusnapshot_scheduler_op_seconds{op="stage"}']["count"] == 1
    assert snap['tpusnapshot_scheduler_op_bytes{op="write"}']["sum"] == 8192
    # storage-op histograms observed the payload write AND the metadata
    writes = snap['tpusnapshot_storage_op_seconds{backend="memory",op="write"}']
    assert writes["count"] >= 2
    assert snap['tpusnapshot_takes_total{mode="sync"}'] == 1


# ----------------------------------------------------- tracing crash-safety


def test_flush_is_atomic_and_leaves_no_tmp(tmp_path):
    path = str(tmp_path / "trace.json")
    tracing.enable(path)
    try:
        with tracing.span("x"):
            pass
        out = tracing.flush()
    finally:
        tracing.disable()
    assert out == path
    assert [p for p in os.listdir(tmp_path) if ".tmp" in p] == []
    with open(path) as f:
        events = json.load(f)["traceEvents"]
    assert {e["ph"] for e in events} == {"b", "e"}


def test_disable_flushes_pending_spans(tmp_path):
    """enable → span → disable (no explicit flush) must not drop spans."""
    path = str(tmp_path / "trace.json")
    tracing.enable(path)
    with tracing.span("kept"):
        pass
    tracing.disable()
    with open(path) as f:
        events = json.load(f)["traceEvents"]
    assert [e["name"] for e in events] == ["kept", "kept"]
    assert not tracing.enabled()


def test_flush_overwrites_previous_complete_trace(tmp_path):
    """A reader between flushes always sees a complete document."""
    path = str(tmp_path / "trace.json")
    tracing.enable(path)
    try:
        with tracing.span("a"):
            pass
        tracing.flush()
        first = json.load(open(path))
        with tracing.span("b"):
            pass
        tracing.flush()
        second = json.load(open(path))
    finally:
        tracing.disable()
    assert len(first["traceEvents"]) == 2
    assert len(second["traceEvents"]) == 4


# ------------------------------------------------- faultline/telemetry bridge


def test_fault_and_retry_instants_match_counters(tmp_path, monkeypatch):
    """Every fault_injected / storage_retry trace instant has a matching
    always-on counter increment: instant-count == counter-count under a
    scripted FaultSchedule."""
    from torchsnapshot_tpu.faultline import FaultSchedule, inject

    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRIES", "4")
    monkeypatch.setattr(
        "torchsnapshot_tpu.io_types._RETRY_BACKOFF_INITIAL_S", 0.001
    )
    trace_path = str(tmp_path / "trace.json")
    tracing.enable(trace_path)
    try:
        schedule = (
            FaultSchedule()
            .transient(op="write", path="0/model/*", nth=1, times=2)
            .transient(op="write", path=".snapshot_metadata", times=1)
            .latency(op="read", seconds=0.0, times=1)
        )
        with inject(schedule) as ctl:
            model = _Model({"w": np.arange(256, dtype=np.float32)})
            snap = Snapshot.take(str(tmp_path / "snap"), {"model": model})
            fresh = _Model({"w": np.zeros(256, dtype=np.float32)})
            snap.restore({"model": fresh})
        tracing.flush()
    finally:
        tracing.disable()
    with open(trace_path) as f:
        events = json.load(f)["traceEvents"]
    fault_instants = [
        e for e in events if e["ph"] == "i" and e["name"] == "fault_injected"
    ]
    retry_instants = [
        e for e in events if e["ph"] == "i" and e["name"] == "storage_retry"
    ]
    snap_metrics = telemetry.snapshot()
    fault_count = tm.sum_samples(snap_metrics, tm.FAULTS_INJECTED)
    retry_count = tm.sum_samples(snap_metrics, tm.STORAGE_RETRIES)
    assert len(fault_instants) == fault_count == len(ctl.records)
    assert len(retry_instants) == retry_count
    assert retry_count >= 3  # the three injected transients were retried
    # backoff seconds accumulated alongside
    assert tm.sum_samples(snap_metrics, tm.STORAGE_RETRY_BACKOFF) > 0
    # and the fault-kind breakdown matches the controller's log
    by_kind = tm.samples_by_label(snap_metrics, tm.FAULTS_INJECTED, "kind")
    assert by_kind.get("transient") == 3
    assert by_kind.get("latency") == 1


# ------------------------------------------------------------- coord metrics


def test_coord_collectives_record_wait_histograms():
    from torchsnapshot_tpu.utils.test_utils import run_thread_ranks

    def fn(coord, rank):
        coord.barrier()
        coord.all_gather_object(rank)
        coord.broadcast_object(rank if rank == 0 else None, src=0)

    run_thread_ranks(2, fn)
    snap = telemetry.snapshot()
    assert snap['tpusnapshot_coord_wait_seconds{op="barrier"}']["count"] == 2
    assert (
        snap['tpusnapshot_coord_wait_seconds{op="all_gather"}']["count"] == 2
    )
    # only receivers time the broadcast wait (the source publishes)
    assert (
        snap['tpusnapshot_coord_wait_seconds{op="broadcast"}']["count"] == 1
    )
