"""Payload integrity (checksum) tests — beyond reference parity."""

import jax.numpy as jnp
import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.manifest import SnapshotMetadata


class _Holder:
    def __init__(self, sd):
        self.sd = sd

    def state_dict(self):
        return self.sd

    def load_state_dict(self, sd):
        self.sd = sd


def test_checksums_recorded(tmp_path):
    Snapshot.take(
        str(tmp_path / "snap"),
        {"m": _Holder({"w": jnp.arange(16.0), "o": {1, 2}})},
    )
    meta = SnapshotMetadata.from_yaml(
        (tmp_path / "snap" / ".snapshot_metadata").read_text()
    )
    assert meta.manifest["0/m/w"].checksum.startswith("crc32:")
    assert meta.manifest["0/m/o"].checksum.startswith("crc32:")


def test_corrupt_array_detected(tmp_path):
    Snapshot.take(str(tmp_path / "snap"), {"m": _Holder({"w": jnp.arange(16.0)})})
    obj = tmp_path / "snap" / "0" / "m" / "w"
    payload = bytearray(obj.read_bytes())
    payload[3] ^= 0xFF  # flip a bit
    obj.write_bytes(bytes(payload))
    with pytest.raises(RuntimeError, match="Checksum mismatch"):
        Snapshot(str(tmp_path / "snap")).restore(
            {"m": _Holder({"w": jnp.zeros(16)})}
        )


def test_corrupt_object_detected(tmp_path):
    Snapshot.take(str(tmp_path / "snap"), {"m": _Holder({"o": {1, 2, 3}})})
    obj = tmp_path / "snap" / "0" / "m" / "o"
    payload = bytearray(obj.read_bytes())
    payload[-1] ^= 0xFF
    obj.write_bytes(bytes(payload))
    with pytest.raises(RuntimeError, match="Checksum mismatch"):
        Snapshot(str(tmp_path / "snap")).restore({"m": _Holder({"o": set()})})


def test_missing_checksum_is_accepted(tmp_path):
    """Snapshots from writers without checksums restore fine (forward
    compat: verify only when the manifest carries a checksum)."""
    Snapshot.take(str(tmp_path / "snap"), {"m": _Holder({"w": jnp.arange(4.0)})})
    meta_file = tmp_path / "snap" / ".snapshot_metadata"
    meta = SnapshotMetadata.from_yaml(meta_file.read_text())
    meta.manifest["0/m/w"].checksum = None
    meta_file.write_text(meta.to_yaml())
    target = _Holder({"w": jnp.zeros(4)})
    Snapshot(str(tmp_path / "snap")).restore({"m": target})
    np.testing.assert_array_equal(np.asarray(target.sd["w"]), np.arange(4.0))


def test_replicated_striping_checksums(tmp_path):
    """Only the stripe owner's checksum is recorded; restore verifies the
    stored bytes correctly even when the owner is not rank 0, and detects
    corruption of owner-written replicated payloads."""
    import threading

    from torchsnapshot_tpu.coord import DictStore, StoreCoordinator

    path = str(tmp_path / "snap")

    def worker(rank, store, errors):
        try:
            coord = StoreCoordinator(store, rank, 2, timeout_s=60)
            # Two replicated paths: sorted order stripes one to each rank.
            sd = {
                "aa": np.arange(8, dtype=np.float32),
                "bb": np.arange(8, 16, dtype=np.float32),
                "obj": {1, 2, 3},
            }
            Snapshot.take(path, {"st": _Holder(sd)}, coord=coord, replicated=["**"])
        except BaseException:  # pragma: no cover
            import traceback

            errors.append(traceback.format_exc())

    store = DictStore()
    errors = []
    threads = [
        threading.Thread(target=worker, args=(r, store, errors)) for r in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors[0]

    # Every replicated leaf must resolve to a checksum-bearing entry for
    # any restoring rank.
    from torchsnapshot_tpu.manifest import get_available_entries

    manifest = Snapshot(path).get_manifest()
    for r in (0, 1, 5):
        avail = get_available_entries(manifest, r)
        for leaf in ("st/aa", "st/bb", "st/obj"):
            assert avail[leaf].checksum, f"missing checksum for {leaf} rank {r}"

    # A fresh single process restores cleanly (checksums match the actual
    # stored bytes regardless of which rank wrote each object) ...
    target = _Holder(
        {
            "aa": np.zeros(8, dtype=np.float32),
            "bb": np.zeros(8, dtype=np.float32),
            "obj": set(),
        }
    )
    Snapshot(path).restore({"st": target})
    np.testing.assert_array_equal(target.sd["bb"], np.arange(8, 16, dtype=np.float32))

    # ... and corruption of a replicated payload is detected.
    f = tmp_path / "snap" / "replicated" / "st" / "bb"
    payload = bytearray(f.read_bytes())
    payload[0] ^= 0xFF
    f.write_bytes(bytes(payload))
    with pytest.raises(RuntimeError, match="Checksum mismatch"):
        Snapshot(path).restore(
            {
                "st": _Holder(
                    {
                        "aa": np.zeros(8, dtype=np.float32),
                        "bb": np.zeros(8, dtype=np.float32),
                        "obj": set(),
                    }
                )
            }
        )


def test_checksum_yaml_round_trip(tmp_path):
    snap = Snapshot.take(
        str(tmp_path / "snap"), {"p": StateDict(x=jnp.arange(8.0))}
    )
    manifest = snap.get_manifest()
    e = manifest["0/p/x"]
    restored = SnapshotMetadata.from_yaml(
        SnapshotMetadata(version="v", world_size=1, manifest={"0/p/x": e}).to_yaml()
    )
    assert restored.manifest["0/p/x"].checksum == e.checksum


def test_strict_integrity_detects_corruption_on_reshard(tmp_path, monkeypatch):
    """Ranged partial reads skip checksum verification by design;
    TPUSNAPSHOT_STRICT_INTEGRITY=1 forces whole-chunk verified reads so a
    reshard-restore still detects corruption."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu import Snapshot

    class _Holder:
        def __init__(self, sd):
            self.sd = sd

        def state_dict(self):
            return self.sd

        def load_state_dict(self, sd):
            self.sd = sd

    data = np.arange(64, dtype=np.float32)
    mesh2 = Mesh(np.array(jax.devices()[:2]), ("x",))
    arr = jax.device_put(data, NamedSharding(mesh2, P("x")))
    Snapshot.take(str(tmp_path / "snap"), {"m": _Holder({"w": arr})})

    # Corrupt one stored chunk.
    chunks = sorted((tmp_path / "snap" / "sharded").rglob("*"))
    chunks = [c for c in chunks if c.is_file()]
    payload = bytearray(chunks[0].read_bytes())
    payload[8] ^= 0xFF
    chunks[0].write_bytes(bytes(payload))

    # Restore onto a finer sharding => partial (ranged) reads of each chunk.
    mesh4 = Mesh(np.array(jax.devices()[:4]), ("x",))
    template = jax.device_put(
        jnp.zeros((64,), dtype=jnp.float32), NamedSharding(mesh4, P("x"))
    )

    monkeypatch.setenv("TPUSNAPSHOT_STRICT_INTEGRITY", "1")
    target = _Holder({"w": template})
    with pytest.raises(Exception, match="[Cc]hecksum|corrupt"):
        Snapshot(str(tmp_path / "snap")).restore({"m": target})


def test_object_checksum_set_at_stage_time_only():
    """Non-owner ranks of replicated objects drop their write reqs before
    staging; the checksum/compression must therefore be patched at stage
    time (owners), never in the constructor."""
    import asyncio

    from torchsnapshot_tpu.io_preparer import ObjectBufferStager
    from torchsnapshot_tpu.manifest import ObjectEntry

    entry = ObjectEntry(location="0/x", serializer="pickle", replicated=True)
    stager = ObjectBufferStager({1, 2, 3}, entry=entry, compression="zlib")
    assert entry.checksum is None and entry.compression is None
    buf = asyncio.run(stager.stage_buffer())
    assert entry.checksum is not None and entry.compression == "zlib"
    from torchsnapshot_tpu.serialization import decompress_payload, bytes_to_object

    assert bytes_to_object(decompress_payload(buf, "zlib")) == {1, 2, 3}


def test_snapshot_verify_scrubs_payloads(tmp_path):
    """Snapshot.verify(): clean snapshot -> {}; corrupted payload ->
    checksum problem; truncated payload -> size problem; deleted payload
    -> unreadable. No device involvement."""
    import os

    state = StateDict(
        a=jnp.arange(64, dtype=jnp.float32),
        b=jnp.ones((32,), dtype=jnp.bfloat16),
        note="hello",
    )
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"s": state})
    assert Snapshot(path).verify() == {}

    # Flip one byte of `a` (content corruption: size unchanged).
    a_path = os.path.join(path, "0", "s", "a")
    data = bytearray(open(a_path, "rb").read())
    data[7] ^= 0xFF
    open(a_path, "wb").write(bytes(data))
    problems = Snapshot(path).verify()
    assert list(problems) == ["0/s/a"]
    assert "Checksum mismatch" in problems["0/s/a"]

    # Truncate `b` (size mismatch reported before checksum).
    b_path = os.path.join(path, "0", "s", "b")
    open(b_path, "wb").write(open(b_path, "rb").read()[:10])
    problems = Snapshot(path).verify()
    assert "size mismatch" in problems["0/s/b"]

    # Remove the object entirely.
    os.remove(a_path)
    problems = Snapshot(path).verify()
    assert "unreadable" in problems["0/s/a"]


def test_verify_covers_sharded_and_compressed(tmp_path):
    import numpy as np

    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu.utils.train_state import PytreeStateful

    mesh = Mesh(np.array(jax.devices()), ("x",))
    arr = jax.device_put(
        jax.random.normal(jax.random.key(0), (64, 8)),
        NamedSharding(mesh, P("x", None)),
    )
    path = str(tmp_path / "snap")
    Snapshot.take(
        path, {"m": PytreeStateful({"w": arr})}, compression="zlib"
    )
    assert Snapshot(path).verify() == {}


def test_inspect_cli_verify(tmp_path, capsys):
    import os

    from torchsnapshot_tpu.inspect import main

    path = str(tmp_path / "snap")
    Snapshot.take(path, {"s": StateDict(w=jnp.arange(16, dtype=jnp.float32))})
    assert main([path, "--verify"]) == 0
    assert "OK" in capsys.readouterr().out

    w = os.path.join(path, "0", "s", "w")
    data = bytearray(open(w, "rb").read())
    data[0] ^= 0xFF
    open(w, "wb").write(bytes(data))
    assert main([path, "--verify"]) == 1
    assert "BAD 0/s/w" in capsys.readouterr().out


def test_verify_uses_owner_checksum_for_replicated_stripes(tmp_path):
    """Replicated payloads appear once per rank in the merged manifest
    and only the stripe owner's entry carries a checksum. verify() must
    use the owner's checksum even when a checksum-less copy (another
    rank's view) appears first (code-review r2: first-wins dedup let
    corrupted replicated payloads pass as clean)."""
    import os

    from torchsnapshot_tpu.manifest import ArrayEntry, SnapshotMetadata
    from torchsnapshot_tpu.serialization import compute_checksum
    from torchsnapshot_tpu.snapshot import SNAPSHOT_METADATA_FNAME

    payload = np.arange(16, dtype=np.float32).tobytes()
    path = tmp_path / "snap"
    (path / "replicated" / "s").mkdir(parents=True)
    (path / "replicated" / "s" / "w").write_bytes(payload)

    def entry(checksum):
        return ArrayEntry(
            location="replicated/s/w",
            serializer="raw",
            dtype="float32",
            shape=[16],
            replicated=True,
            checksum=checksum,
        )

    # Rank 0 (non-owner, no checksum) appears BEFORE rank 1 (owner).
    md = SnapshotMetadata(
        version="v",
        world_size=2,
        manifest={
            "0/s/w": entry(None),
            "1/s/w": entry(compute_checksum(payload)),
        },
    )
    (path / SNAPSHOT_METADATA_FNAME).write_text(md.to_yaml())

    assert Snapshot(str(path)).verify() == {}

    corrupted = bytearray(payload)
    corrupted[5] ^= 0xFF
    (path / "replicated" / "s" / "w").write_bytes(bytes(corrupted))
    problems = Snapshot(str(path)).verify()
    assert "Checksum mismatch" in problems.get("replicated/s/w", "")


def test_inspect_verify_delete_mutually_exclusive(tmp_path, capsys):
    import pytest as _pytest

    from torchsnapshot_tpu.inspect import main

    path = str(tmp_path / "snap")
    Snapshot.take(path, {"s": StateDict(w=jnp.arange(4.0))})
    with _pytest.raises(SystemExit):
        main([path, "--verify", "--delete"])


def test_verify_streams_large_objects(tmp_path, monkeypatch):
    """Objects above the scrub chunk verify via sequential ranged reads
    + streaming crc32 (bounded memory). Forced here with a tiny chunk:
    clean passes, mid-stream corruption, truncation, and trailing
    garbage are all caught."""
    import os

    import torchsnapshot_tpu.snapshot as snapmod

    monkeypatch.setattr(snapmod, "_VERIFY_SCRUB_CHUNK_BYTES", 64)

    state = StateDict(a=jnp.arange(256, dtype=jnp.float32))  # 1 KiB
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"s": state})
    assert Snapshot(path).verify() == {}

    a_path = os.path.join(path, "0", "s", "a")
    payload = open(a_path, "rb").read()

    # Corrupt a byte in the third chunk.
    data = bytearray(payload)
    data[200] ^= 0xFF
    open(a_path, "wb").write(bytes(data))
    assert "Checksum mismatch" in Snapshot(path).verify()["0/s/a"]

    # Truncate mid-stream.
    open(a_path, "wb").write(payload[:300])
    assert "size mismatch" in Snapshot(path).verify()["0/s/a"]

    # Trailing garbage past the manifest size.
    open(a_path, "wb").write(payload + b"xx")
    assert "size mismatch" in Snapshot(path).verify()["0/s/a"]

    # StreamingCrc32 produces the same tag as the one-shot helper.
    from torchsnapshot_tpu.serialization import (
        StreamingCrc32,
        compute_checksum,
    )

    crc = StreamingCrc32()
    for i in range(0, len(payload), 100):
        crc.update(payload[i : i + 100])
    assert crc.tag() == compute_checksum(payload)


def test_verify_length_only_probes_for_unchecksummed_large_objects(
    tmp_path, monkeypatch
):
    """Large objects without a verifiable crc32 tag get a two-probe
    length check (last byte + one past the end) instead of a full
    download whose crc nothing would be compared to; unknown future
    checksum algorithms are skipped like verify_checksum does."""
    import os

    import torchsnapshot_tpu.snapshot as snapmod
    from torchsnapshot_tpu.manifest import ArrayEntry, SnapshotMetadata
    from torchsnapshot_tpu.snapshot import SNAPSHOT_METADATA_FNAME

    monkeypatch.setattr(snapmod, "_VERIFY_SCRUB_CHUNK_BYTES", 64)
    payload = np.arange(256, dtype=np.float32).tobytes()  # 1 KiB > chunk
    path = tmp_path / "snap"
    (path / "0" / "s").mkdir(parents=True)
    (path / "0" / "s" / "w").write_bytes(payload)

    def meta(checksum):
        return SnapshotMetadata(
            version="v",
            world_size=1,
            manifest={
                "0/s/w": ArrayEntry(
                    location="0/s/w",
                    serializer="raw",
                    dtype="float32",
                    shape=[256],
                    replicated=False,
                    checksum=checksum,
                )
            },
        ).to_yaml()

    for tag in (None, "xxh3:abcdef"):  # absent + unknown future algo
        (path / SNAPSHOT_METADATA_FNAME).write_text(meta(tag))
        assert Snapshot(str(path)).verify() == {}
        # Truncated and extended objects still fail the length probes.
        (path / "0" / "s" / "w").write_bytes(payload[:-4])
        assert "size mismatch" in Snapshot(str(path)).verify()["0/s/w"]
        (path / "0" / "s" / "w").write_bytes(payload + b"z")
        assert "size mismatch" in Snapshot(str(path)).verify()["0/s/w"]
        (path / "0" / "s" / "w").write_bytes(payload)


class _Range416(Exception):
    """Shaped like google.api_core RequestRangeNotSatisfiable (code=416
    plus ``errors`` — the classifier requires HTTP-library shape, not a
    bare overloaded ``code``)."""

    def __init__(self):
        super().__init__("416 requested range not satisfiable")
        self.code = 416
        self.errors = ()


class _RangeStrict416Storage:
    """Minimal read-only backend with GCS/S3 range semantics: a ranged
    read whose start offset is at or past the object's end raises 416
    instead of returning b'' (local files return empty — exactly the
    divergence verify() must survive)."""

    max_read_concurrency = 4
    max_write_concurrency = 4

    def __init__(self, base):
        self.base = base
        self.read_attempts = {}

    async def read(self, io_req):
        self.read_attempts[io_req.path] = (
            self.read_attempts.get(io_req.path, 0) + 1
        )
        data = (self.base / io_req.path).read_bytes()
        if io_req.byte_range is not None:
            start, end = io_req.byte_range
            if start >= len(data):
                raise _Range416()
            io_req.data = data[start:end]
        else:
            io_req.data = data

    async def write(self, io_req):
        raise NotImplementedError

    async def delete(self, path):
        raise NotImplementedError

    async def list_prefix(self, prefix):
        return None

    def close(self):
        pass


def test_verify_past_eof_probe_on_range_erroring_backend(
    tmp_path, monkeypatch
):
    """On backends that raise for unsatisfiable ranges (GCS 416, S3
    InvalidRange) the past-end probe of a HEALTHY large object raises —
    verify() must classify that as clean EOF, not 'unreadable', and a
    416 on the last-byte probe as 'shorter'. 416s must not churn the
    retry layer (ADVICE r2 medium; VERDICT r2 weak #6)."""
    import torchsnapshot_tpu.snapshot as snapmod
    from torchsnapshot_tpu.io_types import RetryingStoragePlugin
    from torchsnapshot_tpu.manifest import ArrayEntry, SnapshotMetadata
    from torchsnapshot_tpu.snapshot import SNAPSHOT_METADATA_FNAME

    monkeypatch.setattr(snapmod, "_VERIFY_SCRUB_CHUNK_BYTES", 64)
    payload = np.arange(256, dtype=np.float32).tobytes()  # 1 KiB > chunk
    base = tmp_path / "snap"
    (base / "0" / "s").mkdir(parents=True)
    (base / "0" / "s" / "w").write_bytes(payload)
    meta = SnapshotMetadata(
        version="v",
        world_size=1,
        manifest={
            "0/s/w": ArrayEntry(
                location="0/s/w",
                serializer="raw",
                dtype="float32",
                shape=[256],
                replicated=False,
                checksum=None,  # length-only path
            )
        },
    ).to_yaml()
    (base / SNAPSHOT_METADATA_FNAME).write_text(meta)

    backend = _RangeStrict416Storage(base)
    monkeypatch.setattr(
        snapmod,
        "url_to_storage_plugin",
        lambda url: RetryingStoragePlugin(backend),
    )

    # Healthy object of exactly nbytes: past-end probe raises 416 -> clean.
    assert Snapshot(str(base)).verify() == {}
    # Both probes ran but neither retried (416 is deterministic).
    assert backend.read_attempts["0/s/w"] == 2

    # Truncated object: the last-byte probe itself 416s -> "shorter".
    backend.read_attempts.clear()
    (base / "0" / "s" / "w").write_bytes(payload[: len(payload) // 2])
    problems = Snapshot(str(base)).verify()
    assert "shorter" in problems["0/s/w"]
    assert backend.read_attempts["0/s/w"] == 1  # no retry on 416

    # Extended object still caught.
    (base / "0" / "s" / "w").write_bytes(payload + b"zz")
    assert "longer" in Snapshot(str(base)).verify()["0/s/w"]

    # Checksummed streaming scrub: truncation at an exact chunk boundary
    # surfaces as a 416 on the next chunk's ranged read — same "size
    # mismatch" verdict a local backend reaches via an empty read.
    from torchsnapshot_tpu.serialization import compute_checksum

    meta_crc = SnapshotMetadata(
        version="v",
        world_size=1,
        manifest={
            "0/s/w": ArrayEntry(
                location="0/s/w",
                serializer="raw",
                dtype="float32",
                shape=[256],
                replicated=False,
                checksum=compute_checksum(payload),
            )
        },
    ).to_yaml()
    (base / SNAPSHOT_METADATA_FNAME).write_text(meta_crc)
    (base / "0" / "s" / "w").write_bytes(payload[:64])  # one scrub chunk
    assert "size mismatch" in Snapshot(str(base)).verify()["0/s/w"]
    (base / "0" / "s" / "w").write_bytes(payload)
    assert Snapshot(str(base)).verify() == {}


def test_range_not_satisfiable_classifier():
    from torchsnapshot_tpu.io_types import is_range_not_satisfiable_error

    class RequestRangeNotSatisfiable(Exception):  # google.api_core shape
        code = 416

    class BotoClientError(Exception):
        def __init__(self):
            self.response = {
                "Error": {"Code": "InvalidRange"},
                "ResponseMetadata": {"HTTPStatusCode": 416},
            }

    assert is_range_not_satisfiable_error(RequestRangeNotSatisfiable())
    assert is_range_not_satisfiable_error(BotoClientError())
    # Message-substring lookalikes must NOT classify.
    assert not is_range_not_satisfiable_error(
        RuntimeError("proxy error: 416 Range Not Satisfiable")
    )
    assert not is_range_not_satisfiable_error(FileNotFoundError("x"))
    # `code` is an overloaded attribute (grpc status enums, library
    # error codes): code==416 without any HTTP-library shape must not
    # classify (ADVICE r3) — otherwise the retry layer treats a
    # retryable failure as deterministic and gives up.
    class GrpcStatusLookalike(Exception):
        code = 416

    GrpcStatusLookalike.__module__ = "some.rpc.lib"
    assert not is_range_not_satisfiable_error(GrpcStatusLookalike())

    class HttpShapedCode(Exception):  # google.api_core carries .errors
        code = 416
        errors = ()

    HttpShapedCode.__module__ = "some.rpc.lib"
    assert is_range_not_satisfiable_error(HttpShapedCode())
