"""bench.py hard-deadline discipline (VERDICT r4 #1): whatever the
tunnel does, the bench's stdout carries exactly one parsed JSON summary
line and the process exits 0 — the r4 artifact was an rc=124 kill with
no JSON after a collapsed link pushed the phases past the driver window.

Both tests run bench.py as a subprocess on CPU with
TPUSNAPSHOT_BENCH_THROTTLE_GBPS simulating the collapsed link. Marked
``slow``: each burns tens of seconds of real wall-clock by design.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BENCH = os.path.join(_REPO_ROOT, "bench.py")


def _run_bench(tmp_path, budget_s: int, throttle_gbps: float, nbytes: int):
    env = dict(os.environ)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "TPUSNAPSHOT_BENCH_THROTTLE_GBPS": str(throttle_gbps),
            "TPUSNAPSHOT_BENCH_TOTAL_BUDGET_S": str(budget_s),
            "TPUSNAPSHOT_BENCH_BYTES": str(nbytes),
            "TPUSNAPSHOT_BENCH_DIR": str(tmp_path),
        }
    )
    proc = subprocess.run(
        [sys.executable, _BENCH],
        env=env,
        capture_output=True,
        text=True,
        timeout=budget_s + 60,  # the bench must beat this comfortably
        cwd=_REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line: {lines}"
    doc = json.loads(lines[0])
    assert doc["metric"] == "snapshot_take_GBps"
    return doc, proc


def test_bench_supervisor_emits_when_stuck_in_one_call(tmp_path):
    """A link so slow the WARMUP take cannot finish inside the budget:
    the body thread is stuck inside one blocking Snapshot.take, so only
    the supervisor can emit. rc=0 + parsed JSON + abort reason."""
    # 100 MiB warmup at 0.002 GB/s ≈ 50 s > the 40 s budget.
    doc, proc = _run_bench(
        tmp_path, budget_s=40, throttle_gbps=0.002, nbytes=256 << 20
    )
    assert doc["degraded"] is True
    assert doc["abort"] and "stuck" in doc["abort"]
    assert doc["wall_s"] <= 50  # emitted at the deadline, not the kill
    assert "HARD DEADLINE" in proc.stderr


def test_bench_phase_gate_aborts_gracefully_with_partial_results(tmp_path):
    """A link that carries the warmup and one take but not the restore:
    the body's own deadline gate fires between phases, so the summary
    carries the CERTIFIED take numbers plus the abort reason."""
    # Warmup ~10 s, one 512 MiB take ~25 s at 0.02 GB/s, then the
    # restore gate (needs 60 s) fails against the ~90 s budget.
    doc, _ = _run_bench(
        tmp_path, budget_s=90, throttle_gbps=0.02, nbytes=512 << 20
    )
    assert doc["degraded"] is True
    assert doc["abort"] is not None
    # The take DID complete and its numbers are in the artifact.
    assert doc["n_take_runs"] >= 1
    assert doc["value"] is not None and doc["value"] > 0
    assert doc["take_vs_ceiling"] is not None
    assert doc["wall_s"] <= 95
