"""faultline unit tests: the fault-injection plugin, scriptable
schedules, op-granular hooks (fs sub-steps), torn writes, latency,
injected-fault tracing, and rank-fault injection for coordinator
collectives (beyond reference parity — the reference has no fault
model at all; PAPER.md §snapshot commit is the invariant under test)."""

import asyncio
import json
import os
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from torchsnapshot_tpu import CheckpointManager, Snapshot, StateDict, tracing
from torchsnapshot_tpu import faultline as fl
from torchsnapshot_tpu.coord import DictStore, StoreCoordinator
from torchsnapshot_tpu.io_types import (
    IOReq,
    RetryingStoragePlugin,
    add_storage_op_hook,
    remove_storage_op_hook,
)
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin
from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin

pytestmark = pytest.mark.faultline


def _state(v):
    return {"s": StateDict(w=jnp.full((4,), float(v)))}


def _target():
    return {"s": StateDict(w=jnp.zeros((4,)))}


# ------------------------------------------------------------- plugin unit


def test_transient_faults_absorbed_by_retry_layer(monkeypatch):
    """Injected 503s sit UNDER the retry layer: a take under two
    transient write failures succeeds, and the controller logged both."""
    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRIES", "3")
    monkeypatch.setattr(
        "torchsnapshot_tpu.io_types._RETRY_BACKOFF_INITIAL_S", 0.001
    )
    sched = fl.FaultSchedule().transient(op="write", times=2)
    with fl.inject(sched) as ctl:
        store = MemoryStoragePlugin()
        plugin = RetryingStoragePlugin(fl.FaultPlugin(store, ctl))
        asyncio.run(plugin.write(IOReq(path="obj", data=b"payload")))
    assert store.store["obj"] == b"payload"
    assert ctl.fault_counts() == {"transient": 2}


def test_permanent_fault_exhausts_retries(monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRIES", "2")
    monkeypatch.setattr(
        "torchsnapshot_tpu.io_types._RETRY_BACKOFF_INITIAL_S", 0.001
    )
    sched = fl.FaultSchedule().permanent(op="write", path="obj")
    with fl.inject(sched) as ctl:
        plugin = RetryingStoragePlugin(
            fl.FaultPlugin(MemoryStoragePlugin(), ctl)
        )
        with pytest.raises(fl.InjectedPermanentError):
            asyncio.run(plugin.write(IOReq(path="obj", data=b"x")))
    assert ctl.fault_counts()["permanent"] == 3  # initial + 2 retries


def test_torn_write_retry_rewrites_whole_object(monkeypatch):
    """A torn write leaves a truncated object visible; the retry layer's
    rewrite must replace it whole (whole-object puts are idempotent)."""
    monkeypatch.setattr(
        "torchsnapshot_tpu.io_types._RETRY_BACKOFF_INITIAL_S", 0.001
    )
    sched = fl.FaultSchedule().torn_write(path="obj", keep_bytes=3)
    with fl.inject(sched) as ctl:
        store = MemoryStoragePlugin()
        plugin = RetryingStoragePlugin(fl.FaultPlugin(store, ctl))
        asyncio.run(plugin.write(IOReq(path="obj", data=b"0123456789")))
    assert store.store["obj"] == b"0123456789"
    assert ctl.fault_counts()["torn"] == 1


def test_torn_write_permanent_leaves_detectable_truncation(monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRIES", "0")
    sched = fl.FaultSchedule().torn_write(
        path="obj", keep_bytes=3, then="permanent", times=None
    )
    with fl.inject(sched) as ctl:
        store = MemoryStoragePlugin()
        plugin = RetryingStoragePlugin(fl.FaultPlugin(store, ctl))
        with pytest.raises(fl.InjectedPermanentError):
            asyncio.run(plugin.write(IOReq(path="obj", data=b"0123456789")))
    assert store.store["obj"] == b"012"  # torn, and verifiably short


def test_latency_injection_delays_op():
    sched = fl.FaultSchedule().latency(op="write", seconds=0.05, times=1)
    with fl.inject(sched) as ctl:
        plugin = fl.FaultPlugin(MemoryStoragePlugin(), ctl)
        begin = time.monotonic()
        asyncio.run(plugin.write(IOReq(path="obj", data=b"x")))
        assert time.monotonic() - begin >= 0.05
    assert ctl.fault_counts() == {"latency": 1}


def test_crash_is_base_exception_and_latches():
    """SimulatedCrash must not be absorbable by `except Exception`
    recovery paths, and every op after the crash point dies too."""
    assert not issubclass(fl.SimulatedCrash, Exception)
    sched = fl.FaultSchedule().crash_at(2)
    with fl.inject(sched) as ctl:
        plugin = fl.FaultPlugin(MemoryStoragePlugin(), ctl)
        asyncio.run(plugin.write(IOReq(path="a", data=b"1")))  # op 1: fine
        with pytest.raises(fl.SimulatedCrash):
            asyncio.run(plugin.write(IOReq(path="b", data=b"2")))
        with pytest.raises(fl.SimulatedCrash):
            asyncio.run(plugin.read(IOReq(path="a")))
        plugin.close()  # post-crash close is a silent no-op
    assert ctl.crashed


def test_nth_and_path_glob_targeting():
    sched = fl.FaultSchedule().transient(op="delete", path=".steps/*", nth=2)
    with fl.inject(sched) as ctl:
        store = MemoryStoragePlugin()
        plugin = fl.FaultPlugin(store, ctl)
        for p in (".steps/1", "payload/x", ".steps/2", ".steps/3"):
            asyncio.run(plugin.write(IOReq(path=p, data=b"1")))
        asyncio.run(plugin.delete(".steps/1"))  # 1st match: passes
        asyncio.run(plugin.delete("payload/x"))  # not a match
        with pytest.raises(fl.InjectedTransientError):
            asyncio.run(plugin.delete(".steps/2"))  # 2nd match: fires
        asyncio.run(plugin.delete(".steps/3"))  # times=1 spent: passes


def test_injected_transient_error_is_cloud_shaped():
    """The injected 429/503 must classify as retryable, NOT as the
    deterministic not-found/range errors the retry layer propagates."""
    from torchsnapshot_tpu.io_types import (
        is_not_found_error,
        is_range_not_satisfiable_error,
    )

    for status in (429, 503):
        e = fl.InjectedTransientError(status, "write", "x")
        assert not is_not_found_error(e)
        assert not is_range_not_satisfiable_error(e)


# --------------------------------------------------------- op-granular hooks


def test_fs_write_emits_substep_boundaries(tmp_path):
    seen = []

    def hook(op, path):
        if op.startswith("fs."):
            seen.append((op, path))

    add_storage_op_hook(hook)
    try:
        plugin = FSStoragePlugin(str(tmp_path))
        asyncio.run(plugin.write(IOReq(path="dir/obj", data=b"x")))
        plugin.close()
    finally:
        remove_storage_op_hook(hook)
    assert [op for op, _ in seen] == [
        "fs.write.tmp",
        "fs.write.fsync",
        "fs.write.rename",
        "fs.write.dirsync",
    ]
    assert all(p == "dir/obj" for _, p in seen)


def test_crash_between_fsync_and_rename_leaves_uncommitted(tmp_path):
    """Crash after the tmp payload is durable but before the rename: the
    final name never appears — a torn PROTOCOL, not a torn object."""
    path = str(tmp_path / "snap")
    sched = fl.FaultSchedule().crash_on(op="fs.write.rename", path="0/s/w")
    with fl.inject(sched):
        with pytest.raises(fl.SimulatedCrash):
            Snapshot.take(path, {"s": StateDict(w=jnp.arange(4.0))})
    assert not os.path.exists(os.path.join(path, "0", "s", "w"))
    assert not os.path.exists(
        os.path.join(path, ".snapshot_metadata")
    )  # metadata-last held: later ops never ran
    with pytest.raises(FileNotFoundError):
        Snapshot(path).restore({"s": StateDict(w=jnp.zeros(4))})


def test_crash_after_marker_rename_still_restorable(tmp_path, monkeypatch):
    """Crash after the step marker's rename sub-step: the marker is
    visible, so invariant arm (a) applies — the step it names restores."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    base = str(tmp_path / "run")
    sched = fl.FaultSchedule().crash_on(
        op="fs.write.dirsync", path=".steps/0"
    )
    with fl.inject(sched):
        with pytest.raises(fl.SimulatedCrash):
            CheckpointManager(base, max_to_keep=2).save(0, _state(0))
    mgr = CheckpointManager(base)
    assert mgr.all_steps() == [0]
    target = _target()
    assert mgr.restore(target) == 0
    np.testing.assert_array_equal(np.asarray(target["s"]["w"]), 0.0)


# ------------------------------------------------------------ fault tracing


def test_injected_faults_emit_trace_instants(tmp_path, monkeypatch):
    """Every injected fault lands in the trace next to the retry layer's
    storage_retry instants, so traces show recovery behavior."""
    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRIES", "3")
    monkeypatch.setattr(
        "torchsnapshot_tpu.io_types._RETRY_BACKOFF_INITIAL_S", 0.001
    )
    trace_path = str(tmp_path / "trace.json")
    tracing.enable(trace_path)
    try:
        sched = fl.FaultSchedule().transient(op="write", path="obj", times=2)
        with fl.inject(sched) as ctl:
            plugin = RetryingStoragePlugin(
                fl.FaultPlugin(MemoryStoragePlugin(), ctl)
            )
            asyncio.run(plugin.write(IOReq(path="obj", data=b"x")))
    finally:
        tracing.flush()
        tracing.disable()
    with open(trace_path) as f:
        events = json.load(f)["traceEvents"]
    faults = [e for e in events if e["name"] == "fault_injected"]
    retries = [e for e in events if e["name"] == "storage_retry"]
    assert len(faults) == 2
    assert {f["args"]["kind"] for f in faults} == {"transient"}
    assert {f["args"]["op"] for f in faults} == {"write"}
    assert all("op_index" in f["args"] for f in faults)
    # The retry layer retried both failures and recorded each attempt.
    assert len(retries) == 2
    assert all(
        r["args"]["error"] == "InjectedTransientError" for r in retries
    )


# ------------------------------------------------------- rank-fault injection


def test_barrier_names_rank_that_never_published():
    """A rank whose barrier arrival never becomes visible (process death
    after the local call) must be NAMED by every healthy rank's shared-
    deadline TimeoutError — not hang them, not blame a healthy peer."""
    world = 3
    store = fl.MuteRankStore(DictStore(), rank=1)
    messages = [None] * world

    def run(rank):
        coord = StoreCoordinator(store, rank, world, timeout_s=0.5)
        with pytest.raises(TimeoutError) as exc_info:
            coord.barrier()
        messages[rank] = str(exc_info.value)

    threads = [
        threading.Thread(target=run, args=(r,)) for r in range(world)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert not any(t.is_alive() for t in threads)
    for rank, msg in enumerate(messages):
        assert msg is not None, f"rank {rank} did not time out"
        assert "rank 1" in msg and "never arrived" in msg
        assert "rank 0" not in msg.split("observed by")[0]
    assert store.dropped  # the fault actually fired


def test_all_gather_names_all_stalled_ranks():
    """With TWO muted ranks the error must name both — at pod scale
    "ranks 1, 3" localizes a failure that "rank 1" alone does not."""
    world = 4
    store = fl.MuteRankStore(
        DictStore(),
        rank=-1,
        patterns=fl.mute_patterns_for_rank(1)
        + fl.mute_patterns_for_rank(3),
    )
    messages = {}
    lock = threading.Lock()

    def run(rank):
        coord = StoreCoordinator(store, rank, world, timeout_s=0.5)
        with pytest.raises(TimeoutError) as exc_info:
            coord.all_gather_object(rank)
        with lock:
            messages[rank] = str(exc_info.value)

    threads = [
        threading.Thread(target=run, args=(r,)) for r in (0, 2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert not any(t.is_alive() for t in threads)
    for rank in (0, 2):
        msg = messages[rank]
        assert "ranks 1, 3" in msg and "never finished publishing" in msg


def test_partial_chunked_publish_reads_as_never_finished():
    """A rank that dies partway into a chunked publish (head visible,
    parts missing) must read as "never finished publishing", never as
    garbage handed to pickle."""
    world = 2
    big = b"x" * (3 << 20)  # > _CHUNK: forces the chunked path
    store = fl.MuteRankStore(DictStore(), rank=1, mute_after=1)
    messages = {}

    def run0():
        coord = StoreCoordinator(store, 0, world, timeout_s=0.8)
        with pytest.raises(TimeoutError) as exc_info:
            coord.all_gather_object(b"small")
        messages[0] = str(exc_info.value)

    def run1():
        coord = StoreCoordinator(store, 1, world, timeout_s=0.8)
        with pytest.raises(TimeoutError):
            coord.all_gather_object(big)

    threads = [
        threading.Thread(target=run0),
        threading.Thread(target=run1),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert not any(t.is_alive() for t in threads)
    assert "rank 1" in messages[0]
    assert "never finished publishing" in messages[0]


# ------------------------------------------------------------- op counting


def test_count_storage_ops_is_fault_free():
    store_url = f"memory://countbkt-{os.getpid()}"

    def scenario():
        Snapshot.take(store_url, {"s": StateDict(w=jnp.arange(4.0))})

    n = fl.count_storage_ops(scenario)
    assert n > 0
    # The dry run really committed (no faults were injected).
    target = _target()
    Snapshot(store_url).restore({"s": target["s"]})


def test_fmt_ranks_compresses_contiguous_spans():
    """Pod-scale stalls must read as spans ("ranks 17, 40-63"), not a
    thousands-entry comma list."""
    fmt = StoreCoordinator._fmt_ranks
    assert fmt([17]) == "rank 17"
    assert fmt([1, 3]) == "ranks 1, 3"
    assert fmt([1, 2, 3, 7]) == "ranks 1-3, 7"
    assert fmt([17] + list(range(40, 64))) == "ranks 17, 40-63"


def test_stale_tmp_cleanup_spares_live_writer(tmp_path):
    """Publish-point stale-tmp cleanup removes a DEAD writer's torn tmp
    but must never delete a live concurrent writer's in-flight tmp —
    that would turn a safe last-rename-wins race into a non-retryable
    FileNotFoundError on the peer's os.replace."""
    import subprocess

    # A dead pid: a subprocess that already exited (not yet recycled).
    proc = subprocess.Popen(["true"])
    proc.wait()
    dead = proc.pid

    plugin = FSStoragePlugin(str(tmp_path))
    os.makedirs(str(tmp_path / ".steps"), exist_ok=True)
    # A live "writer": pid 1 always exists (answers the liveness probe
    # with EPERM in a container), standing in for a concurrent process
    # mid-write of the same marker.
    live_tmp = str(tmp_path / ".steps" / "5.tmp1")
    dead_tmp = str(tmp_path / ".steps" / f"5.tmp{dead}")
    for p in (live_tmp, dead_tmp):
        with open(p, "wb") as f:
            f.write(b"torn")
    asyncio.run(plugin.write(IOReq(path=".steps/5", data=b"marker")))
    plugin.close()
    assert os.path.exists(live_tmp)  # live writer's tmp survives
    assert not os.path.exists(dead_tmp)  # crashed writer's tmp removed
    with open(str(tmp_path / ".steps" / "5"), "rb") as f:
        assert f.read() == b"marker"


def test_crash_on_close_boundary_skips_deferred_durability():
    """close IS an op boundary: a crash scheduled there dies before the
    inner plugin settles deferred work, and stays dead."""
    sched = fl.FaultSchedule().crash_on(op="close")
    with fl.inject(sched) as ctl:
        plugin = fl.FaultPlugin(MemoryStoragePlugin(), ctl)
        asyncio.run(plugin.write(IOReq(path="a", data=b"1")))
        with pytest.raises(fl.SimulatedCrash):
            plugin.close()
        plugin.close()  # post-crash: silent no-op
    assert ctl.crashed
