"""snapmem: the unified host-memory plane — domain registry/window
mechanics, the leak sentinel's exit contract over a synthetic ledger,
the faultline ``mem_pressure`` rule deterministically tripping
``host-memory-overcommit``, real take/restore flight-report
reconciliation, ``ops --mem`` fleet merging, and the doctor/slo rules
(PR 20 acceptance criteria)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, telemetry
from torchsnapshot_tpu.telemetry import doctor as _doctor
from torchsnapshot_tpu.telemetry import memwatch
from torchsnapshot_tpu.telemetry import ops as scope_ops


@pytest.fixture(autouse=True)
def _fresh_memwatch():
    telemetry.reset()
    memwatch.reset()
    yield
    memwatch.reset()
    telemetry.reset()


class _Model:
    def __init__(self, params):
        self.params = params

    def state_dict(self):
        return self.params

    def load_state_dict(self, sd):
        self.params = sd


# ------------------------------------------------------------- registry


def test_domain_charge_release_and_high_water():
    d = memwatch.register("t.a", cap_bytes=1000)
    d.charge(700)
    d.release(300)
    snap = memwatch.snapshot()
    assert snap["domains"]["t.a"]["used_bytes"] == 400
    assert snap["domains"]["t.a"]["high_water_bytes"] == 700
    assert snap["domains"]["t.a"]["cap_bytes"] == 1000
    assert snap["committed_bytes"] == 400
    d.close()
    assert "t.a" not in memwatch.snapshot()["domains"]


def test_same_name_instances_aggregate():
    a = memwatch.register("t.multi", cap_bytes=100)
    b = memwatch.register("t.multi", cap_bytes=100)
    a.set_used(30)
    b.set_used(50, pinned_bytes=20)
    entry = memwatch.snapshot()["domains"]["t.multi"]
    assert entry["used_bytes"] == 80
    assert entry["pinned_bytes"] == 20
    a.close()
    b.close()


def test_provider_domain_and_external_exclusion():
    memwatch.register_provider("t.poll", lambda: (256, 0, 512))
    memwatch.register_provider(
        "t.remote", lambda: (4096, 4096, None), external=True
    )
    snap = memwatch.snapshot()
    assert snap["domains"]["t.poll"]["used_bytes"] == 256
    assert snap["domains"]["t.remote"]["external"]
    # External bytes are reported but never counted as this process's
    # committed host memory.
    assert snap["committed_bytes"] == 256
    memwatch.unregister_provider("t.poll")
    memwatch.unregister_provider("t.remote")


def test_window_collects_per_domain_high_water_and_counters():
    d = memwatch.register("t.win", cap_bytes=None, watch_residual="used")
    token = memwatch.window_begin()
    d.charge(900)
    d.counter("hits", 2)
    d.release(900)
    block = memwatch.window_collect(token)
    dom = block["domains"]["t.win"]
    assert dom["high_water_bytes"] == 900
    assert dom["end_used_bytes"] == 0
    assert dom["residual_bytes"] == 0
    assert dom["counters"] == {"hits": 2}
    assert memwatch.reconcile(block) == []
    d.close()


def test_window_survives_domain_closed_mid_window():
    token = memwatch.window_begin()
    d = memwatch.register("t.gone", cap_bytes=4096)
    d.charge(2048)
    d.close()
    block = memwatch.window_collect(token)
    assert block["domains"]["t.gone"]["high_water_bytes"] == 2048
    assert block["domains"]["t.gone"]["cap_bytes"] == 4096


def test_host_budget_env_override(monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_HOST_MEM_BUDGET", str(123 << 20))
    budget, source = memwatch.host_budget_bytes()
    assert budget == 123 << 20
    assert source == "env"
    block = memwatch.sample_block()
    assert block["budget_bytes"] == 123 << 20


def test_forecast_overcommit_records_event(monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_HOST_MEM_BUDGET", str(1 << 20))
    token = memwatch.window_begin()
    ev = memwatch.forecast(1 << 30, kind="take")
    assert ev is not None and ev["overcommit"]
    block = memwatch.window_collect(token)
    assert block.get("forecasts")
    finding = _doctor.memory_pressure_finding(block, source="test")
    assert finding is not None
    assert finding.rule == "host-memory-overcommit"
    assert finding.severity == "warn"  # forecast only, nothing landed


def test_reconcile_flags_over_cap_domain():
    bad = {
        "domains": {"x": {"high_water_bytes": 200, "cap_bytes": 100}},
        "high_water_bytes": 200,
    }
    assert any("exceeds cap" in v for v in memwatch.reconcile(bad))


# --------------------------------------------------------- leak sentinel


def _leak_records(n=6):
    """A synthetic ledger series with one injected never-releasing
    domain and one healthy domain that returns to baseline."""
    return [
        {
            "format_version": 1,
            "kind": "take",
            "ts_epoch_s": 1000.0 + i,
            "memory": {
                "domains": {
                    "leaky.retainer": {
                        "residual_bytes": (i + 1) * (2 << 20)
                    },
                    "healthy.pool": {
                        "residual_bytes": 0 if i % 2 else 1024
                    },
                }
            },
        }
        for i in range(n)
    ]


def _write_ledger(path, records):
    from torchsnapshot_tpu.telemetry import ledger as _ledger

    path.write_text(
        "\n".join(_ledger.encode_line(r) for r in records) + "\n"
    )


def test_leak_sentinel_names_injected_domain():
    findings = memwatch.leak_findings(_leak_records())
    assert len(findings) == 1
    assert findings[0].rule == "memory-leak-suspected"
    assert findings[0].evidence["domain"] == "leaky.retainer"


def test_leak_sentinel_cli_exit_contract(tmp_path):
    leaky = tmp_path / "leaky.jsonl"
    _write_ledger(leaky, _leak_records())
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "torchsnapshot_tpu.telemetry.memwatch",
            str(leaky),
            "--json",
        ],
        capture_output=True,
        text=True,
        env=env,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["findings"][0]["rule"] == "memory-leak-suspected"
    assert (
        doc["findings"][0]["evidence"]["domain"] == "leaky.retainer"
    ), doc

    # A flat residual (retention, not growth) exits 0.
    flat = tmp_path / "flat.jsonl"
    _write_ledger(
        flat,
        [
            {
                "format_version": 1,
                "kind": "take",
                "ts_epoch_s": 1000.0 + i,
                "memory": {
                    "domains": {
                        "steady.pool": {"residual_bytes": 4 << 20}
                    }
                },
            }
            for i in range(8)
        ],
    )
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "torchsnapshot_tpu.telemetry.memwatch",
            str(flat),
        ],
        capture_output=True,
        text=True,
        env=env,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr

    # An unreadable path exits 2.
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "torchsnapshot_tpu.telemetry.memwatch",
            str(tmp_path / "nope" / "missing.jsonl"),
        ],
        capture_output=True,
        text=True,
        env=env,
    )
    assert proc.returncode == 2, proc.stdout + proc.stderr


def test_memwatch_self_test():
    assert memwatch._self_test() == 0


# ----------------------------------------------------- faultline fault


def test_mem_pressure_fault_trips_overcommit():
    from torchsnapshot_tpu.faultline.schedule import (
        FaultController,
        FaultSchedule,
    )

    d = memwatch.register("staging_pool", cap_bytes=1 << 20)
    d.set_used(4096, pinned_bytes=4096)
    ctl = FaultController(
        FaultSchedule().mem_pressure("staging_pool", 100)
    )
    # Before the fault fires: healthy.
    assert (
        _doctor.memory_pressure_finding(memwatch.sample_block()) is None
    )
    ctl.on_op("write", "some/object")
    snap = memwatch.snapshot()
    assert snap["domains"]["staging_pool"]["cap_bytes"] == 100
    finding = _doctor.memory_pressure_finding(
        memwatch.sample_block(), source="test"
    )
    assert finding is not None
    assert finding.rule == "host-memory-overcommit"
    assert finding.severity == "critical"
    assert finding.evidence["over_cap_domains"][0]["domain"] == (
        "staging_pool"
    )
    # The injected cap override is a fault, not an accounting bug:
    # reconciliation of a window block stays clean.
    token = memwatch.window_begin()
    assert memwatch.reconcile(memwatch.window_collect(token)) == []
    d.close()


# -------------------------------------------------- real take / restore


def test_take_restore_reports_carry_reconciling_memory(
    tmp_path, monkeypatch
):
    monkeypatch.setenv(
        "TPUSNAPSHOT_RESTORE_STAGING_POOL_BYTES", str(8 << 20)
    )
    from torchsnapshot_tpu import staging_pool as _pool

    _pool.reset_staging_pool()
    snap_path = str(tmp_path / "snap")
    rng = np.random.RandomState(0)
    params = {"w": rng.randn(64 * 1024).astype(np.float32)}
    Snapshot.take(snap_path, {"model": _Model(dict(params))})
    dest = _Model({"w": np.zeros_like(params["w"])})
    Snapshot(snap_path).restore({"model": dest})
    np.testing.assert_array_equal(dest.params["w"], params["w"])

    for fname, expect_domain in (
        (".report.json", "scheduler.write"),
        (".report.restore.json", "staging_pool"),
    ):
        with open(os.path.join(snap_path, fname)) as f:
            report = json.load(f)
        mem = report["ranks"][0].get("memory")
        assert isinstance(mem, dict), f"{fname} missing memory block"
        assert expect_domain in mem["domains"], (
            fname,
            sorted(mem["domains"]),
        )
        assert mem.get("rss_bytes"), f"{fname} must record RSS"
        assert memwatch.reconcile(mem) == []
        # The report rules see the same block the sentinel reads.
        assert _doctor._merged_memory(report), fname

    # The ledger digest rolls the same windows up for trend tooling.
    from torchsnapshot_tpu.telemetry import ledger as _ledger

    records, _ = _ledger.read_records(snap_path)
    by_kind = {r.get("kind"): r for r in records}
    for kind in ("take", "restore"):
        assert (by_kind[kind].get("memory") or {}).get("domains"), (
            by_kind[kind]
        )
    _pool.reset_staging_pool()


# ------------------------------------------------------------ ops --mem


def _scope_line(rank, mem):
    return json.dumps(
        {"format_version": 1, "rank": rank, "ts": 1.0, "memory": mem}
    )


def _mem_block(used, cap, hwm, budget=1 << 30):
    return {
        "domains": {
            "staging_pool": {
                "used_bytes": used,
                "pinned_bytes": used,
                "cap_bytes": cap,
                "high_water_bytes": hwm,
            }
        },
        "committed_bytes": used,
        "high_water_bytes": hwm,
        "budget_bytes": budget,
        "budget_source": "env",
        "rss_bytes": 10 << 20,
        "headroom_bytes": budget - used,
    }


def test_ops_mem_merges_ranks_and_flags_overcommit(tmp_path):
    ops_dir = tmp_path / "liveops"
    ops_dir.mkdir()
    (ops_dir / "rank0.scope.jsonl").write_text(
        _scope_line(0, _mem_block(1024, 4096, 2048)) + "\n"
    )
    (ops_dir / "rank1.scope.jsonl").write_text(
        _scope_line(1, _mem_block(8192, 4096, 8192)) + "\n"
    )
    fleet = scope_ops.collect_fleet_mem(str(ops_dir), [], [])
    assert fleet["reachable"] == 2
    merged = fleet["domains"]["staging_pool"]
    assert merged["members"] == 2
    assert merged["used_bytes"] == 1024 + 8192
    assert merged["high_water_bytes"] == 2048 + 8192
    findings = scope_ops.fleet_mem_findings(fleet)
    assert any(
        f.rule == "host-memory-overcommit" and f.severity == "critical"
        for f in findings
    ), findings
    # CLI exit contract: the over-cap rank makes the view exit 1.
    assert scope_ops.main([str(ops_dir), "--mem"]) == 1


def test_ops_mem_healthy_exits_zero(tmp_path, capsys):
    ops_dir = tmp_path / "liveops"
    ops_dir.mkdir()
    (ops_dir / "rank0.scope.jsonl").write_text(
        _scope_line(0, _mem_block(1024, 4096, 2048)) + "\n"
    )
    assert scope_ops.main([str(ops_dir), "--mem"]) == 0
    out = capsys.readouterr().out
    assert "fleet memory:" in out
    assert "staging_pool" in out


def test_ops_mem_all_unreachable_exits_two(tmp_path):
    # One dead server target, no trainer path: the view is dark.
    rc = scope_ops.main(
        ["--mem", "--wire", "127.0.0.1:1", "--wire-timeout", "2"]
    )
    assert rc == 2


# ----------------------------------------------------------- doctor/slo


def _report_with_memory(mem, kind="restore"):
    return {"kind": kind, "ranks": [{"rank": 0, "memory": mem}]}


def test_doctor_rule_memory_leak_single_report():
    mem = {
        "domains": {
            "staging_pool": {
                "high_water_bytes": 8 << 20,
                "residual_bytes": 4 << 20,
            }
        },
        "high_water_bytes": 8 << 20,
    }
    findings = _doctor.diagnose_report(_report_with_memory(mem))
    leak = [f for f in findings if f.rule == "memory-leak-suspected"]
    assert leak and leak[0].evidence["domain"] == "staging_pool"


def test_doctor_rule_staging_pool_thrash():
    mem = {
        "domains": {
            "staging_pool": {
                "high_water_bytes": 4096,
                "cap_bytes": 4096,
                "residual_bytes": 0,
                "counters": {"hits": 1, "misses": 5, "waits": 3},
            }
        },
        "high_water_bytes": 4096,
    }
    findings = _doctor.diagnose_report(_report_with_memory(mem))
    thrash = [f for f in findings if f.rule == "staging-pool-thrash"]
    assert thrash, findings
    assert thrash[0].evidence["waits"] == 3
    # A pool mostly serving hits is healthy no matter the waits=0.
    mem["domains"]["staging_pool"]["counters"] = {
        "hits": 50,
        "misses": 2,
        "waits": 0,
    }
    findings = _doctor.diagnose_report(_report_with_memory(mem))
    assert not [f for f in findings if f.rule == "staging-pool-thrash"]


def test_doctor_rule_cache_cap_misfit_thrash_and_oversize():
    thrash = _doctor.cache_misfit_finding(
        {
            "hits": 10,
            "misses": 40,
            "evictions": 30,
            "inserts": 40,
            "cap_bytes": 1000,
            "high_water_bytes": 990,
        }
    )
    assert thrash is not None and thrash.rule == "cache-cap-misfit"
    assert "thrashing" in thrash.title
    oversize = _doctor.cache_misfit_finding(
        {
            "hits": 50,
            "misses": 10,
            "evictions": 0,
            "inserts": 10,
            "cap_bytes": 100000,
            "high_water_bytes": 100,
        }
    )
    assert oversize is not None and "oversized" in oversize.title
    healthy = _doctor.cache_misfit_finding(
        {
            "hits": 45,
            "misses": 15,
            "evictions": 2,
            "inserts": 15,
            "cap_bytes": 1000,
            "high_water_bytes": 600,
        }
    )
    assert healthy is None


def test_slo_live_memory_rule_self_test():
    from torchsnapshot_tpu.telemetry import slo as _slo

    assert _slo._self_test() == 0


# -------------------------------------------------------- domain wiring


def test_staging_pool_publishes_domain_and_gauges():
    from torchsnapshot_tpu.staging_pool import StagingPool

    pool = StagingPool(capacity_bytes=1 << 20)
    lease = pool.acquire(4096)
    entry = memwatch.snapshot()["domains"]["staging_pool"]
    assert entry["pinned_bytes"] >= 4096
    assert entry["cap_bytes"] == 1 << 20
    lease.release()
    stats = pool.stats()
    assert stats["high_water_bytes"] >= 4096
    entry = memwatch.snapshot()["domains"]["staging_pool"]
    assert entry["pinned_bytes"] == 0  # leased bytes returned


def test_byte_lru_publishes_domain_and_counters():
    from torchsnapshot_tpu.snapserve.cache import ByteLRU

    cache = ByteLRU(cap_bytes=8192)
    cache.put("k1", b"x" * 4096)
    assert cache.get("k1") is not None
    assert cache.get("absent") is None
    entry = memwatch.snapshot()["domains"]["snapserve.cache"]
    assert entry["used_bytes"] == 4096
    assert entry["cap_bytes"] == 8192
    stats = cache.stats()
    assert stats["high_water_bytes"] >= 4096
    token = memwatch.window_begin()
    cache.put("k2", b"y" * 4096)
    block = memwatch.window_collect(token)
    counters = block["domains"]["snapserve.cache"]["counters"]
    assert counters.get("inserts") == 1


def test_scheduler_registers_transient_write_domain(tmp_path):
    # A plain take registers scheduler.write for the window and closes
    # it after: nothing may linger in the global registry.
    Snapshot.take(
        str(tmp_path / "snap"),
        {"model": _Model({"w": np.zeros(16, dtype=np.float32)})},
    )
    assert "scheduler.write" not in memwatch.snapshot()["domains"]
