"""Manifest + elasticity kernel tests (reference analog:
tests/test_manifest.py:20-189)."""

import pytest

from torchsnapshot_tpu.manifest import (
    ArrayEntry,
    DictEntry,
    ListEntry,
    ObjectEntry,
    OrderedDictEntry,
    PrimitiveEntry,
    Shard,
    ShardedArrayEntry,
    SnapshotMetadata,
    get_available_entries,
    is_replicated,
)


def _array(location, replicated=False):
    return ArrayEntry(
        location=location,
        serializer="raw",
        dtype="float32",
        shape=[4, 4],
        replicated=replicated,
    )


def _sharded(shards):
    return ShardedArrayEntry(
        dtype="float32",
        shape=[8, 4],
        shards=[
            Shard(offsets=o, sizes=s, array=_array(loc)) for o, s, loc in shards
        ],
    )


def _two_rank_manifest():
    """A hand-written 2-rank manifest (reference test_manifest.py:20-85)."""
    return {
        "0/state": DictEntry(keys=["per_rank_x", "repl_y", "shard_w", "obj"]),
        "0/state/per_rank_x": _array("0/state/per_rank_x"),
        "0/state/repl_y": _array("replicated/state/repl_y", replicated=True),
        "0/state/shard_w": _sharded(
            [([0, 0], [4, 4], "sharded/state/shard_w_0_0")]
        ),
        "0/state/obj": ObjectEntry(
            location="0/state/obj", serializer="pickle", replicated=False
        ),
        "0/state/prim": PrimitiveEntry(ptype="int", readable="42", replicated=True),
        "1/state": DictEntry(keys=["per_rank_x", "repl_y", "shard_w", "obj"]),
        "1/state/per_rank_x": _array("1/state/per_rank_x"),
        "1/state/repl_y": _array("replicated/state/repl_y", replicated=True),
        "1/state/shard_w": _sharded(
            [([4, 0], [4, 4], "sharded/state/shard_w_4_0")]
        ),
        "1/state/obj": ObjectEntry(
            location="1/state/obj", serializer="pickle", replicated=False
        ),
        "1/state/prim": PrimitiveEntry(ptype="int", readable="42", replicated=True),
    }


def test_yaml_round_trip():
    metadata = SnapshotMetadata(
        version="0.1.0", world_size=2, manifest=_two_rank_manifest()
    )
    restored = SnapshotMetadata.from_yaml(metadata.to_yaml())
    assert restored.version == "0.1.0"
    assert restored.world_size == 2
    assert set(restored.manifest.keys()) == set(metadata.manifest.keys())
    entry = restored.manifest["0/state/shard_w"]
    assert isinstance(entry, ShardedArrayEntry)
    assert entry.shards[0].offsets == [0, 0]
    assert entry.shards[0].array.location == "sharded/state/shard_w_0_0"
    assert isinstance(restored.manifest["0/state"], DictEntry)
    assert restored.manifest["0/state"].keys == [
        "per_rank_x",
        "repl_y",
        "shard_w",
        "obj",
    ]
    prim = restored.manifest["0/state/prim"]
    assert prim.get_value() == 42


def test_get_available_entries_same_world():
    manifest = _two_rank_manifest()
    avail0 = get_available_entries(manifest, 0)
    # Sharded: union of both ranks' shards.
    assert len(avail0["state/shard_w"].shards) == 2
    # Replicated + primitive: visible.
    assert avail0["state/repl_y"].replicated
    assert avail0["state/prim"].get_value() == 42
    # Per-rank: own only.
    assert avail0["state/per_rank_x"].location == "0/state/per_rank_x"
    assert avail0["state/obj"].location == "0/state/obj"
    avail1 = get_available_entries(manifest, 1)
    assert avail1["state/per_rank_x"].location == "1/state/per_rank_x"


def test_get_available_entries_larger_world():
    """Restoring with world size > snapshot world size: rank 2 sees
    sharded + replicated entries but no per-rank entries (reference
    test_manifest.py:102-189)."""
    manifest = _two_rank_manifest()
    avail2 = get_available_entries(manifest, 2)
    assert len(avail2["state/shard_w"].shards) == 2
    assert "state/repl_y" in avail2
    assert "state/prim" in avail2
    assert "state/per_rank_x" not in avail2
    assert "state/obj" not in avail2
    # Containers are available to any rank.
    assert isinstance(avail2["state"], DictEntry)


def test_get_available_entries_double_digit_ranks():
    """The reference parses only the first character of the rank token and
    breaks at world size >= 10 (manifest.py:181-182); we must not."""
    manifest = {
        "12/state/x": _array("12/state/x"),
    }
    avail = get_available_entries(manifest, 12)
    assert avail["state/x"].location == "12/state/x"
    assert get_available_entries(manifest, 1) == {}


def test_shard_dedupe_across_ranks():
    # Two ranks reporting the same chunk (replicated-within-sharded case)
    # must not duplicate it in the merged view.
    manifest = {
        "0/s/w": _sharded([([0, 0], [4, 4], "sharded/s/w_0_0")]),
        "1/s/w": _sharded([([0, 0], [4, 4], "sharded/s/w_0_0")]),
    }
    avail = get_available_entries(manifest, 0)
    assert len(avail["s/w"].shards) == 1


def test_is_replicated():
    assert is_replicated(_array("replicated/x", replicated=True))
    assert not is_replicated(_array("0/x"))
    assert not is_replicated(ListEntry())


def test_primitive_entry_values():
    for value in [0, -3, 1.5, float("inf"), True, False, None, "héllo\nworld", 1 + 2j]:
        e = PrimitiveEntry.from_value(value)
        restored = PrimitiveEntry(
            ptype=e.ptype, readable=e.readable, replicated=False
        ).get_value()
        assert restored == value or (value != value and restored != restored)
        assert type(restored) is type(value)


def test_primitive_rejects_container():
    with pytest.raises(TypeError):
        PrimitiveEntry.from_value([1, 2])


def test_ordered_dict_entry_roundtrip():
    metadata = SnapshotMetadata(
        version="0.1.0",
        world_size=1,
        manifest={"0/od": OrderedDictEntry(keys=["b", "a"])},
    )
    restored = SnapshotMetadata.from_yaml(metadata.to_yaml())
    entry = restored.manifest["0/od"]
    assert isinstance(entry, OrderedDictEntry)
    assert entry.keys == ["b", "a"]


def test_manifest_scales_to_7b_fsdp_shape():
    """VERDICT r1 #7: manifest-side costs at the 7B/v5e-64 scale. A
    synthetic 800-array FSDP manifest over world 64 (51,200 shard
    entries, ~21 MB serialized) must stay comfortably inside interactive
    budgets for every step EVERY rank runs at restore start. Bounds are
    ~4x the measured medians on a loaded 1-core CI host (measured:
    merge 0.05s, to_yaml ~1.3s, from_yaml ~2.4s, availability ~0.8s);
    the pre-fix libyaml path took 24s/46s to dump/parse — this is the
    regression guard for that.
    """
    import time

    from torchsnapshot_tpu.snapshot import _merge_manifests

    world, n_arrays = 64, 800

    def rank_manifest(rank):
        m = {}
        for i in range(n_arrays):
            rows = 4096
            per = rows // world
            m[f"model/layer{i // 16}/param_{i}"] = ShardedArrayEntry(
                dtype="float32",
                shape=[rows, 2048],
                shards=[
                    Shard(
                        offsets=[rank * per, 0],
                        sizes=[per, 2048],
                        array=ArrayEntry(
                            location=(
                                f"sharded/model/layer{i // 16}/"
                                f"param_{i}_{rank * per}_0"
                            ),
                            serializer="raw",
                            dtype="float32",
                            shape=[per, 2048],
                            replicated=False,
                            checksum="crc32:deadbeef",
                        ),
                    )
                ],
            )
        return m

    manifests = [rank_manifest(r) for r in range(world)]

    t = time.monotonic()
    merged = _merge_manifests(manifests)
    merge_s = time.monotonic() - t
    assert len(merged) == world * n_arrays

    md = SnapshotMetadata(version="t", world_size=world, manifest=merged)
    t = time.monotonic()
    doc = md.to_yaml()
    dump_s = time.monotonic() - t

    t = time.monotonic()
    md2 = SnapshotMetadata.from_yaml(doc)
    parse_s = time.monotonic() - t

    t = time.monotonic()
    avail = get_available_entries(md2.manifest, 3)
    avail_s = time.monotonic() - t
    assert len(avail) == n_arrays

    # Round-trip fidelity at scale (spot-check one entry deeply).
    k = "17/model/layer2/param_44"
    assert md2.manifest[k] == merged[k]

    assert merge_s < 2.0, f"_merge_manifests took {merge_s:.2f}s"
    assert dump_s < 6.0, f"to_yaml took {dump_s:.2f}s"
    assert parse_s < 10.0, f"from_yaml took {parse_s:.2f}s"
    assert avail_s < 4.0, f"get_available_entries took {avail_s:.2f}s"


def test_metadata_doc_compression_round_trip(tmp_path, monkeypatch):
    """Metadata documents above the threshold store zlib-compressed
    (leading byte 0x78 vs '{' — formats cannot collide) and read back
    transparently; small documents stay plain; both restore fine.
    Completion markers share the codec."""
    import numpy as np

    import jax.numpy as jnp

    import torchsnapshot_tpu.snapshot as snapmod
    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.snapshot import (
        SNAPSHOT_METADATA_FNAME,
        _decode_metadata_doc,
        _encode_metadata_doc,
    )

    # Helper-level round trip at both sizes.
    small = '{"version": "x"}'
    assert _encode_metadata_doc(small) == small.encode()
    big = '{"manifest": "' + "y" * (2 << 20) + '"}'
    enc = _encode_metadata_doc(big)
    assert enc[:1] == b"\x78" and len(enc) < len(big)
    assert _decode_metadata_doc(enc) == big
    assert _decode_metadata_doc(small.encode()) == small

    # End-to-end with the threshold forced low: the stored metadata (and
    # async markers) are compressed on disk, everything still works.
    monkeypatch.setenv("TPUSNAPSHOT_METADATA_COMPRESS_THRESHOLD", "64")
    state = StateDict(w=jnp.arange(128, dtype=jnp.float32))
    path = str(tmp_path / "snap")
    Snapshot.async_take(path, {"s": state}).wait()
    raw = (tmp_path / "snap" / SNAPSHOT_METADATA_FNAME).read_bytes()
    assert raw[:1] == b"\x78"  # compressed on disk

    target = StateDict(w=jnp.zeros(128, dtype=jnp.float32))
    Snapshot(path).restore({"s": target})
    np.testing.assert_array_equal(np.asarray(target["w"]), np.arange(128))

    # Uncompressed legacy documents still read (plain take below the
    # restored threshold).
    monkeypatch.setenv(
        "TPUSNAPSHOT_METADATA_COMPRESS_THRESHOLD", str(1 << 20)
    )
    path2 = str(tmp_path / "snap2")
    Snapshot.take(path2, {"s": state})
    raw2 = (tmp_path / "snap2" / SNAPSHOT_METADATA_FNAME).read_bytes()
    assert raw2[:1] == b"{"
    target2 = StateDict(w=jnp.zeros(128, dtype=jnp.float32))
    Snapshot(path2).restore({"s": target2})
    np.testing.assert_array_equal(np.asarray(target2["w"]), np.arange(128))


def test_torn_compressed_metadata_keeps_polling(tmp_path):
    """A partially-visible COMPRESSED metadata document must read as
    'not committed yet' in the polling paths (zlib.error == torn), and
    fail loudly in the strict committed-read path (code-review r2)."""
    import asyncio

    import zlib

    import pytest as _pytest

    from torchsnapshot_tpu.snapshot import (
        _decode_metadata_doc,
        _read_valid_marker,
        _wait_for_metadata,
    )
    from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin
    from torchsnapshot_tpu.io_types import IOReq

    full = zlib.compress(b'{"version": "v", "world_size": 1, "manifest": {}}', 1)
    torn = full[: len(full) // 2]
    assert torn[:1] == b"\x78"

    # Strict decode raises at the corruption.
    with _pytest.raises(zlib.error):
        _decode_metadata_doc(torn)

    storage = MemoryStoragePlugin()
    req = IOReq(path=".snapshot_metadata")
    req.buf.write(torn)
    asyncio.run(storage.write(req))
    req2 = IOReq(path=".completed/n/0")
    req2.buf.write(torn)
    asyncio.run(storage.write(req2))

    # Polling paths treat torn-compressed as "keep waiting" (timeout,
    # not a zlib crash).
    with _pytest.raises(TimeoutError):
        asyncio.run(_wait_for_metadata(storage, take_id="n", timeout_s=0.2))
    assert (
        asyncio.run(
            _read_valid_marker(
                storage, ".completed/n/0", "n", strict_errors=True
            )
        )
        is None
    )
