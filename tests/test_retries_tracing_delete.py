"""Storage retries, span tracing, and snapshot deletion (beyond reference
parity — the reference has no retries, no tracing, and no snapshot GC,
SURVEY §5)."""

import asyncio
import json
import os
import time

import numpy as np
import pytest

import jax.numpy as jnp

from torchsnapshot_tpu import Snapshot, StateDict, tracing
from torchsnapshot_tpu.io_types import (
    IOReq,
    RetryingStoragePlugin,
    retry_storage_op,
)
from torchsnapshot_tpu.snapshot import SNAPSHOT_METADATA_FNAME
from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin


class FlakyStorage(MemoryStoragePlugin):
    """Fails the first ``fail_n`` write and read attempts."""

    def __init__(self, fail_n: int = 2) -> None:
        super().__init__()
        self.write_attempts = 0
        self.read_attempts = 0
        self._fail_n = fail_n

    async def write(self, io_req: IOReq) -> None:
        self.write_attempts += 1
        if self.write_attempts <= self._fail_n:
            raise ConnectionResetError("transient write failure")
        await super().write(io_req)

    async def read(self, io_req: IOReq) -> None:
        self.read_attempts += 1
        if self.read_attempts <= self._fail_n:
            # Simulate a partial read then failure.
            io_req.buf.write(b"garbage")
            raise TimeoutError("transient read failure")
        await super().read(io_req)


def test_retry_recovers_transient_write_and_read(monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRIES", "3")
    monkeypatch.setattr(
        "torchsnapshot_tpu.io_types._RETRY_BACKOFF_INITIAL_S", 0.001
    )
    from torchsnapshot_tpu.scheduler import execute_read_reqs, execute_write_reqs
    from torchsnapshot_tpu.io_preparer import prepare_read, prepare_write

    inner = FlakyStorage(fail_n=2)
    storage = RetryingStoragePlugin(inner)
    data = np.arange(64, dtype=np.float32)
    entry, wrs = prepare_write(data, "s/v", rank=0)
    asyncio.run(execute_write_reqs(wrs, storage, 1 << 30, rank=0))
    assert inner.write_attempts == 3  # 2 failures + 1 success

    out = {}
    rrs, fins = prepare_read(entry, None, lambda v: out.update(v=v))
    asyncio.run(execute_read_reqs(rrs, storage, 1 << 30, rank=0))
    for f in fins:
        f()
    np.testing.assert_array_equal(out["v"], data)
    assert inner.read_attempts == 3


def test_retry_exhaustion_propagates(monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRIES", "1")
    monkeypatch.setattr(
        "torchsnapshot_tpu.io_types._RETRY_BACKOFF_INITIAL_S", 0.001
    )

    async def _always_fail():
        raise ConnectionResetError("down")

    with pytest.raises(ConnectionResetError):
        asyncio.run(retry_storage_op(_always_fail, "write(x)"))


def test_dispatch_wraps_every_backend_with_retry():
    """All storage traffic (payloads, metadata commit, markers, deletes)
    goes through url_to_storage_plugin, so wrapping there covers every op."""
    from torchsnapshot_tpu.storage_plugin import url_to_storage_plugin

    for url in ("memory://retrytest", "/tmp/retrytest-fs"):
        plugin = url_to_storage_plugin(url)
        assert isinstance(plugin, RetryingStoragePlugin)
        plugin.close()


def test_cloud_not_found_not_retried():
    class FakeGcsNotFound(Exception):
        pass

    FakeGcsNotFound.__name__ = "NotFound"
    calls = []

    async def _missing():
        calls.append(1)
        raise FakeGcsNotFound("404 object missing")

    with pytest.raises(FakeGcsNotFound):
        asyncio.run(retry_storage_op(_missing, "read(z)"))
    assert len(calls) == 1


def test_not_found_is_never_retried():
    calls = []

    async def _missing():
        calls.append(1)
        raise FileNotFoundError("no such object")

    with pytest.raises(FileNotFoundError):
        asyncio.run(retry_storage_op(_missing, "read(y)"))
    assert len(calls) == 1


def test_transient_error_with_404_in_message_is_retried():
    """Classification is structural, never by message substring: a proxied
    HTML error body (or request id) containing "404"/"Not Found" is a
    transient failure and MUST be retried — treating it as a missing
    object would abort reads and stall async-commit polling."""
    from torchsnapshot_tpu.io_types import is_not_found_error

    proxy_err = ConnectionError(
        "<html>504 gateway timeout; upstream said: Not Found (404); "
        "request-id: ab404cd</html>"
    )
    assert not is_not_found_error(proxy_err)

    calls = []

    async def _flaky():
        calls.append(1)
        if len(calls) < 2:
            raise ConnectionError("proxy error: 404 Not Found in body")
        return "ok"

    assert asyncio.run(retry_storage_op(_flaky, "read(w)")) == "ok"
    assert len(calls) == 2


def test_structured_not_found_codes_classified():
    """botocore-style response dicts and google-style .code attributes
    classify as not-found without any name/message matching."""
    from torchsnapshot_tpu.io_types import is_not_found_error

    class ClientError(Exception):
        def __init__(self, response):
            super().__init__("An error occurred")
            self.response = response

    assert is_not_found_error(
        ClientError({"Error": {"Code": "NoSuchKey"}})
    )
    assert is_not_found_error(
        ClientError({"ResponseMetadata": {"HTTPStatusCode": 404}})
    )
    assert not is_not_found_error(
        ClientError({"ResponseMetadata": {"HTTPStatusCode": 500}})
    )

    class ApiError(Exception):  # google.api_core shape: code + errors
        code = 404
        errors = ()

    assert is_not_found_error(ApiError("gone"))

    class ApiError500(Exception):
        code = 500
        errors = ()

    assert not is_not_found_error(ApiError500("boom"))

    # `code` is overloaded (grpc status enums, library error codes): a
    # bare code==404 with no HTTP-library shape must NOT classify
    # (ADVICE r3) — else the retry layer gives up on retryable failures.
    class GrpcLookalike(Exception):
        code = 404

    GrpcLookalike.__module__ = "some.rpc.lib"
    assert not is_not_found_error(GrpcLookalike("status 404"))


def test_tracing_records_snapshot_spans(tmp_path):
    trace_path = str(tmp_path / "trace.json")
    state = StateDict(w=jnp.arange(16, dtype=jnp.float32))
    tracing.enable(trace_path)
    try:
        path = str(tmp_path / "snap")
        Snapshot.take(path, {"s": state})
        target = StateDict(w=jnp.zeros(16, dtype=jnp.float32))
        Snapshot(path).restore({"s": target})
    finally:
        tracing.flush()
        tracing.disable()

    with open(trace_path) as f:
        events = json.load(f)["traceEvents"]
    names = {e["name"] for e in events}
    assert {"Snapshot.take", "Snapshot.restore", "stage", "write", "read",
            "consume"} <= names
    # Async begin/end pairs: every span id opens exactly once and closes
    # exactly once, with non-negative duration (overlap-safe rendering).
    begins = {e["id"]: e["ts"] for e in events if e["ph"] == "b"}
    ends = {e["id"]: e["ts"] for e in events if e["ph"] == "e"}
    assert set(begins) == set(ends) and begins
    assert all(ends[i] >= begins[i] for i in begins)


def test_tracing_disabled_is_noop():
    assert not tracing.enabled()
    with tracing.span("nothing"):
        pass  # must not record or raise
    assert tracing.flush() is None


def test_delete_removes_payloads_and_metadata(tmp_path):
    path = str(tmp_path / "snap")
    state = StateDict(a=jnp.arange(8, dtype=jnp.float32), b="hello")
    Snapshot.take(path, {"s": state})
    assert os.path.exists(os.path.join(path, SNAPSHOT_METADATA_FNAME))

    snap = Snapshot(path)
    snap.delete()

    assert not os.path.exists(os.path.join(path, SNAPSHOT_METADATA_FNAME))
    # Every payload object is gone (only empty directories may remain).
    leftovers = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(path)
        for f in fs
    ]
    assert leftovers == []
    with pytest.raises(FileNotFoundError):
        Snapshot(path).restore({"s": StateDict(a=jnp.zeros(8), b="")})


def test_delete_is_idempotent_and_cleans_async_markers(tmp_path):
    path = str(tmp_path / "snap")
    state = StateDict(a=jnp.arange(8, dtype=jnp.float32))
    Snapshot.async_take(path, {"s": state}).wait()
    completed = os.path.join(path, ".completed")
    assert os.path.isdir(completed) and any(os.scandir(completed))

    Snapshot(path).delete()
    leftovers = [
        os.path.join(dp, f) for dp, _, fs in os.walk(path) for f in fs
    ]
    assert leftovers == []
    with pytest.raises(FileNotFoundError):
        Snapshot(path).delete()  # metadata already gone


def test_delete_sweep_removes_orphans(tmp_path, monkeypatch):
    """delete(sweep=True) enumerates the prefix and removes objects the
    manifest does not reference — leftovers of interrupted/superseded
    takes at the same path (ADVICE r1: plain delete leaked them)."""
    # The freshly-created orphans below would be spared by the
    # concurrent-take age guard; this test is about enumeration.
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    path = str(tmp_path / "snap")
    state = StateDict(a=jnp.arange(8, dtype=jnp.float32))
    Snapshot.take(path, {"s": state})
    # Orphans a crashed earlier take could leave: an uncommitted payload
    # chunk and completion markers under a different nonce.
    os.makedirs(os.path.join(path, ".completed", "deadbeef"), exist_ok=True)
    with open(os.path.join(path, ".completed", "deadbeef", "0"), "w") as f:
        f.write("stale")
    os.makedirs(os.path.join(path, "7"), exist_ok=True)
    with open(os.path.join(path, "7", "orphan_chunk"), "wb") as f:
        f.write(b"\x00" * 64)

    # Plain delete leaves the orphans (documented behavior)...
    Snapshot(path).delete()
    leftovers = [
        os.path.join(dp, f) for dp, _, fs in os.walk(path) for f in fs
    ]
    assert len(leftovers) == 2

    # ...sweep removes them, even with the metadata already gone.
    Snapshot(path).delete(sweep=True)
    leftovers = [
        os.path.join(dp, f) for dp, _, fs in os.walk(path) for f in fs
    ]
    assert leftovers == []


def test_delete_sweep_never_escapes_snapshot_root(tmp_path):
    """list_prefix("") must enumerate only the plugin root: sweeping
    snap-1 must not see (or delete) a sibling snap-2 in the same parent
    directory (code-review r2 finding: walking dirname(root) for an
    empty prefix exposed siblings to the sweep)."""
    s1, s2 = str(tmp_path / "snap-1"), str(tmp_path / "snap-2")
    state = StateDict(a=jnp.arange(4, dtype=jnp.float32))
    Snapshot.take(s1, {"s": state})
    Snapshot.take(s2, {"s": state})

    Snapshot(s1).delete(sweep=True)

    # snap-2 untouched and fully restorable.
    target = StateDict(a=jnp.zeros(4, dtype=jnp.float32))
    Snapshot(s2).restore({"s": target})
    assert np.allclose(np.asarray(target["a"]), np.arange(4))
    # snap-1 empty.
    leftovers = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(s1)
        for f in fs
    ]
    assert leftovers == []


def test_delete_sweep_memory_backend():
    from torchsnapshot_tpu.storage_plugin import _MEMORY_STORES

    path = "memory://sweeptest"
    state = StateDict(a=jnp.arange(4, dtype=jnp.float32))
    Snapshot.take(path, {"s": state})
    store = _MEMORY_STORES["sweeptest"]
    store["0/orphan"] = b"x"
    store[".completed/oldnonce/0"] = b"y"
    Snapshot(path).delete(sweep=True)
    assert store == {}


def test_inspect_cli_delete(tmp_path, capsys):
    from torchsnapshot_tpu.inspect import main

    path = str(tmp_path / "snap")
    Snapshot.take(path, {"s": StateDict(w=jnp.arange(8, dtype=jnp.float32))})
    assert main([path, "--delete"]) == 0
    assert not os.path.exists(os.path.join(path, SNAPSHOT_METADATA_FNAME))
    assert "deleted" in capsys.readouterr().out


def test_delete_sweep_spares_fresh_unreferenced_objects(tmp_path, monkeypatch):
    """The concurrent-take guard (ADVICE r2): unreferenced objects
    younger than TPUSNAPSHOT_SWEEP_MIN_AGE_S look like an in-progress
    take's uncommitted writes and are spared; old ones are swept."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "3600")
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"s": StateDict(a=jnp.arange(4, dtype=jnp.float32))})
    fresh = os.path.join(path, "3", "inflight_chunk")
    old = os.path.join(path, "3", "stale_chunk")
    os.makedirs(os.path.dirname(fresh), exist_ok=True)
    for p in (fresh, old):
        with open(p, "wb") as f:
            f.write(b"\x00" * 16)
    two_hours_ago = time.time() - 7200
    os.utime(old, (two_hours_ago, two_hours_ago))

    Snapshot(path).delete(sweep=True)
    leftovers = [
        os.path.join(dp, f) for dp, _, fs in os.walk(path) for f in fs
    ]
    assert leftovers == [fresh]  # in-progress-looking object survives
    # A later sweep (when it has aged out) removes it.
    os.utime(fresh, (two_hours_ago, two_hours_ago))
    Snapshot(path).delete(sweep=True)
    assert [
        os.path.join(dp, f) for dp, _, fs in os.walk(path) for f in fs
    ] == []


def test_delete_sweep_tolerates_corrupt_metadata(tmp_path, monkeypatch):
    """An interrupted/corrupt metadata document must not make the
    snapshot undeletable: sweep proceeds (ADVICE r2 — previously only
    NOT-FOUND metadata was sweepable); plain delete still raises."""
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"s": StateDict(a=jnp.arange(4, dtype=jnp.float32))})
    with open(os.path.join(path, ".snapshot_metadata"), "wb") as f:
        f.write(b"\x78\x01 torn zlib garbage")

    with pytest.raises(Exception):
        Snapshot(path).delete()  # non-sweep: surface the corruption

    Snapshot(path).delete(sweep=True)
    assert [
        os.path.join(dp, f) for dp, _, fs in os.walk(path) for f in fs
    ] == []


# ---------------------------------------------------- backoff jitter/budget


def test_retry_backoff_is_jittered_and_capped(monkeypatch):
    """Delays must be decorrelated (drawn from [initial, prev*3]) and
    capped — all ranks backing off in lockstep re-hammer recovering
    shared storage at exactly the wrong moments."""
    from torchsnapshot_tpu import io_types

    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRIES", "6")
    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRY_CAP_S", "0.004")
    monkeypatch.setattr(io_types, "_RETRY_BACKOFF_INITIAL_S", 0.001)
    delays = []
    real_sleep = asyncio.sleep

    async def capture_sleep(d):
        delays.append(d)
        await real_sleep(0)

    monkeypatch.setattr(io_types.asyncio, "sleep", capture_sleep)

    calls = []

    async def _flaky():
        calls.append(1)
        if len(calls) < 7:
            raise ConnectionResetError("down")
        return "ok"

    assert asyncio.run(retry_storage_op(_flaky, "write(j)")) == "ok"
    assert len(delays) == 6
    cap = 0.004
    initial = 0.001
    prev = initial
    for d in delays:
        assert initial <= d <= cap + 1e-9, delays
        assert d <= max(initial, prev * 3.0) + 1e-9, delays
        prev = d


def test_retry_budget_bounds_total_episode(monkeypatch):
    """With the elapsed budget at 0 the first failure propagates without
    any sleep: retrying past the budget would pin commits for
    attempts x cap seconds."""
    from torchsnapshot_tpu import io_types

    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRIES", "5")
    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRY_BUDGET_S", "0")
    slept = []

    async def capture_sleep(d):
        slept.append(d)

    monkeypatch.setattr(io_types.asyncio, "sleep", capture_sleep)
    calls = []

    async def _always_fail():
        calls.append(1)
        raise ConnectionResetError("down")

    with pytest.raises(ConnectionResetError):
        asyncio.run(retry_storage_op(_always_fail, "write(b)"))
    assert len(calls) == 1
    assert slept == []


def test_retry_attempts_emit_trace_instants(tmp_path, monkeypatch):
    """Every retry attempt lands in the trace (op, attempt, delay,
    error) so traces show recovery behavior, not just the final state."""
    from torchsnapshot_tpu import io_types

    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRIES", "3")
    monkeypatch.setattr(io_types, "_RETRY_BACKOFF_INITIAL_S", 0.001)
    trace_path = str(tmp_path / "trace.json")
    tracing.enable(trace_path)
    try:
        inner = FlakyStorage(fail_n=2)
        storage = RetryingStoragePlugin(inner)
        asyncio.run(storage.write(IOReq(path="obj", data=b"payload")))
    finally:
        tracing.flush()
        tracing.disable()
    with open(trace_path) as f:
        events = json.load(f)["traceEvents"]
    retries = [e for e in events if e["name"] == "storage_retry"]
    assert len(retries) == 2
    assert [r["args"]["attempt"] for r in retries] == [1, 2]
    for r in retries:
        assert r["args"]["op"] == "write(obj)"
        assert r["args"]["delay_s"] > 0
        assert r["args"]["error"] == "ConnectionResetError"


def test_retry_cap_below_initial_backoff_is_honored(monkeypatch):
    """A cap below the initial backoff must still bound every delay —
    the jitter floor drops to the cap, the cap never rises."""
    from torchsnapshot_tpu import io_types

    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRIES", "3")
    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRY_CAP_S", "0.005")
    delays = []

    async def capture_sleep(d):
        delays.append(d)

    monkeypatch.setattr(io_types.asyncio, "sleep", capture_sleep)
    calls = []

    async def _flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionResetError("down")
        return "ok"

    assert asyncio.run(retry_storage_op(_flaky, "write(c)")) == "ok"
    assert len(delays) == 2
    assert all(0 < d <= 0.005 for d in delays), delays
