"""snapserve: the disaggregated read plane — caching server, RemoteSnapshot
client fan-out, degraded-mode fallback, and the server-fault matrix.

Concurrency invariants pinned here (ISSUE 9):

- 32-client single-flight collapse: exactly ONE backend read per object
  no matter the fan-out.
- The LRU byte cap is never exceeded, even under concurrent fill.
- Cache hits are fingerprint-verified: a corrupt entry is dropped,
  counted, and re-fetched — never served.
- Degraded mode: a dead/killed server falls back to direct backend
  reads bit-exactly, with the fallback counted in client stats, the
  flight report's ``read_plane`` block, the ``read-plane-degraded``
  doctor rule, and the ledger.
"""

import asyncio
import threading
import uuid

import numpy as np
import pytest

from torchsnapshot_tpu import RemoteSnapshot, Snapshot, StateDict, snapserve
from torchsnapshot_tpu import faultline as fl
from torchsnapshot_tpu import telemetry
from torchsnapshot_tpu.io_types import IOReq, StoragePlugin, io_payload
from torchsnapshot_tpu.io_types import is_range_not_satisfiable_error
from torchsnapshot_tpu.snapserve.cache import ByteLRU
from torchsnapshot_tpu.snapserve.client import parse_snapserve_url
from torchsnapshot_tpu.storage_plugin import url_to_storage_plugin
import torchsnapshot_tpu.storage_plugin as sp_mod
from torchsnapshot_tpu.telemetry import ledger as runledger
from torchsnapshot_tpu.telemetry import report as flight
from torchsnapshot_tpu.telemetry.doctor import diagnose_report


# ----------------------------------------------------------------- helpers


@pytest.fixture(autouse=True)
def _no_leaked_servers(monkeypatch):
    """Every test ends with no live in-process server, and fallback
    cooldowns short enough that one test's dead-server latch cannot
    slow the next."""
    monkeypatch.setenv("TPUSNAPSHOT_SNAPSERVE_DOWN_COOLDOWN_S", "0.2")
    yield
    snapserve.kill_local_servers()


def _mem_root(tag):
    return f"memory://snapserve-{tag}-{uuid.uuid4().hex[:10]}/run"


def _state(n_params=4, n=2048, seed=7):
    rng = np.random.default_rng(seed)
    return {
        "m": StateDict(
            **{
                f"p{i}": rng.standard_normal(n).astype(np.float32)
                for i in range(n_params)
            }
        )
    }


def _zero_like(state):
    return {
        "m": StateDict(
            **{k: np.zeros_like(v) for k, v in state["m"].items()}
        )
    }


def _assert_exact(target, state):
    for k, v in state["m"].items():
        np.testing.assert_array_equal(target["m"][k], v)


def _restore_report(root):
    storage = url_to_storage_plugin(root)
    try:
        return asyncio.run(
            flight.aread_json(storage, flight.RESTORE_REPORT_FNAME)
        )
    finally:
        storage.close()


class _CountingPlugin(StoragePlugin):
    """Pass-through plugin counting reads per path (the memoization
    proofs) with an optional per-read delay (the single-flight races)."""

    def __init__(self, inner, counts, delay_s=0.0):
        self._inner = inner
        self._counts = counts
        self._delay_s = delay_s
        self.max_write_concurrency = inner.max_write_concurrency
        self.max_read_concurrency = inner.max_read_concurrency

    async def read(self, io_req):
        self._counts[io_req.path] = self._counts.get(io_req.path, 0) + 1
        if self._delay_s:
            await asyncio.sleep(self._delay_s)
        await self._inner.read(io_req)

    async def write(self, io_req):
        await self._inner.write(io_req)

    async def delete(self, path):
        await self._inner.delete(path)

    async def list_prefix(self, prefix):
        return await self._inner.list_prefix(prefix)

    async def object_age_s(self, path):
        return await self._inner.object_age_s(path)

    async def object_size_bytes(self, path):
        return await self._inner.object_size_bytes(path)

    def ensure_durable(self):
        self._inner.ensure_durable()

    def close(self):
        self._inner.close()


# ------------------------------------------------------------- URL parsing


def test_parse_snapserve_url():
    addr, backend = parse_snapserve_url("127.0.0.1:7077/memory://b/run")
    assert addr == "127.0.0.1:7077" and backend == "memory://b/run"
    addr, backend = parse_snapserve_url("host:1//tmp/snap")
    assert addr == "host:1" and backend == "/tmp/snap"
    # A relative fs spelling resolves absolute rather than pointing at
    # a cwd-relative surprise.
    _, backend = parse_snapserve_url("host:1/tmp/snap")
    assert backend == "/tmp/snap"
    with pytest.raises(ValueError):
        parse_snapserve_url("no-port/memory://b/run")
    with pytest.raises(ValueError):
        parse_snapserve_url("host:7077")
    with pytest.raises(ValueError):
        parse_snapserve_url("h:1/snapserve://h:2/memory://b/run")


# ------------------------------------------------------------------- cache


def test_lru_cap_never_exceeded_under_concurrent_fill():
    cap = 64 << 10
    cache = ByteLRU(cap)
    violations = []
    rng = np.random.default_rng(3)
    payloads = [bytes(rng.bytes(int(s))) for s in rng.integers(1, 8 << 10, 64)]

    def _hammer(tid):
        for i in range(200):
            cache.put(f"k-{tid}-{i % 32}", payloads[(tid + i) % len(payloads)])
            used = cache.bytes_used
            if used > cap:
                violations.append(used)
            cache.get(f"k-{(tid + 1) % 16}-{i % 32}")

    threads = [
        threading.Thread(target=_hammer, args=(t,)) for t in range(16)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not violations, f"byte cap exceeded: {violations[:5]}"
    assert cache.bytes_used <= cap
    stats = cache.stats()
    assert stats["evictions"] > 0  # the cap actually bit


def test_lru_oversize_object_never_admitted():
    cache = ByteLRU(1 << 10)
    assert not cache.put("big", b"x" * (2 << 10))
    assert cache.bytes_used == 0
    assert cache.stats()["oversize_skips"] == 1
    assert cache.get("big") is None


def test_lru_corrupt_entry_dropped_counted_and_refetchable():
    cache = ByteLRU(1 << 16)
    cache.put("k", b"payload-bytes")
    assert cache.get("k") == b"payload-bytes"
    assert cache.corrupt_for_test("k")
    assert cache.get("k") is None  # verified-on-hit: never served corrupt
    stats = cache.stats()
    assert stats["corrupt"] == 1 and stats["entries"] == 0
    cache.put("k", b"payload-bytes")  # the re-fetch path re-admits
    assert cache.get("k") == b"payload-bytes"


# --------------------------------------------------------------- end-to-end


def test_remote_restore_read_object_and_manifest_parity():
    root = _mem_root("parity")
    state = _state()
    Snapshot.take(root, state)
    server = snapserve.start_local_server()
    try:
        remote = RemoteSnapshot(root, addr=server.addr)
        direct = Snapshot(root)
        target = _zero_like(state)
        remote.restore(target)
        _assert_exact(target, state)
        np.testing.assert_array_equal(
            remote.read_object("m/p0"), direct.read_object("m/p0")
        )
        assert remote.get_manifest().keys() == direct.get_manifest().keys()
        assert remote.verify() == {}
        assert remote.backend_path == root
        assert remote.direct().path == root
    finally:
        server.stop()


def test_server_manifest_memoized_across_clients():
    root = _mem_root("memo")
    Snapshot.take(root, _state(n_params=2, n=256))
    counts = {}
    service = snapserve.ReadService(
        backend_resolver=lambda url: _CountingPlugin(
            url_to_storage_plugin(url), counts
        )
    )
    server = snapserve.start_local_server(service=service)
    try:
        for _ in range(5):
            # Fresh handle per iteration: the CLIENT-side memo must not
            # be what's absorbing the repeat loads.
            RemoteSnapshot(root, addr=server.addr).get_manifest()
        stats = service.stats()
        assert counts[".snapshot_metadata"] == 1
        assert stats["manifest_loads"] == 1
        assert stats["manifest_hits"] >= 4
    finally:
        server.stop()


def test_single_flight_collapse_32_clients():
    root = _mem_root("flight")
    payload = bytes(np.random.default_rng(5).bytes(64 << 10))
    storage = url_to_storage_plugin(root)
    try:
        asyncio.run(storage.write(IOReq(path="0/obj", data=payload)))
    finally:
        storage.close()
    counts = {}
    # The backend read is slowed so all 32 requests are in flight
    # together — the collapse must make them share ONE backend read.
    service = snapserve.ReadService(
        backend_resolver=lambda url: _CountingPlugin(
            url_to_storage_plugin(url), counts, delay_s=0.05
        )
    )
    server = snapserve.start_local_server(service=service)
    try:
        spec = f"{server.addr}/{root}"

        async def _fan_out():
            plugins = [
                sp_mod.url_to_storage_plugin(f"snapserve://{spec}")
                for _ in range(32)
            ]
            try:
                reqs = [IOReq(path="0/obj") for _ in plugins]
                await asyncio.gather(
                    *(p.read(r) for p, r in zip(plugins, reqs))
                )
                return [bytes(io_payload(r)) for r in reqs]
            finally:
                for p in plugins:
                    p.close()

        results = asyncio.run(_fan_out())
        assert all(r == payload for r in results)
        assert counts["0/obj"] == 1, counts  # exactly one backend read
        stats = service.stats()
        assert stats["singleflight_collapses"] == 31
        # Fallbacks would mean some client dodged the server entirely.
        assert stats["requests"] >= 32
    finally:
        server.stop()


def test_overlapping_range_reads_coalesce_to_one_backend_read():
    root = _mem_root("ranges")
    payload = bytes(range(256)) * 64  # 16 KiB, position-dependent bytes
    storage = url_to_storage_plugin(root)
    try:
        asyncio.run(storage.write(IOReq(path="0/chunk", data=payload)))
    finally:
        storage.close()
    counts = {}
    service = snapserve.ReadService(
        backend_resolver=lambda url: _CountingPlugin(
            url_to_storage_plugin(url), counts, delay_s=0.02
        )
    )
    server = snapserve.start_local_server(service=service)
    try:
        ranges = [(0, 8192), (4096, 12288), (8192, 16384), (1000, 2000)]

        async def _overlap():
            plugin = sp_mod.url_to_storage_plugin(
                f"snapserve://{server.addr}/{root}"
            )
            try:
                reqs = [
                    IOReq(path="0/chunk", byte_range=r) for r in ranges
                ]
                await asyncio.gather(*(plugin.read(r) for r in reqs))
                return [bytes(io_payload(r)) for r in reqs]
            finally:
                plugin.close()

        results = asyncio.run(_overlap())
        for (start, end), got in zip(ranges, results):
            assert got == payload[start:end]
        assert counts["0/chunk"] == 1, counts  # coalesced
        # A past-the-end range speaks the 416 dialect through the hop,
        # so verify()'s probe works identically via the service.
        async def _past_end():
            plugin = sp_mod.url_to_storage_plugin(
                f"snapserve://{server.addr}/{root}"
            )
            try:
                await plugin.read(
                    IOReq(
                        path="0/chunk",
                        byte_range=(len(payload), len(payload) + 1),
                    )
                )
            finally:
                plugin.close()

        with pytest.raises(Exception) as exc_info:
            asyncio.run(_past_end())
        assert is_range_not_satisfiable_error(exc_info.value)
    finally:
        server.stop()


def test_read_amplification_with_8_concurrent_restores():
    root = _mem_root("amp")
    # Payload large enough (1 MiB) that the per-restore control-plane
    # reads (the growing ledger + metadata — mutable, deliberately
    # never cached) stay inside the 1.2x headroom; real payloads are
    # MBs-to-GBs and drown them entirely.
    state = _state(n_params=4, n=65536)
    Snapshot.take(root, state)
    payload_bytes = sum(v.nbytes for v in state["m"].values())
    service = snapserve.ReadService()
    server = snapserve.start_local_server(service=service)
    try:
        errors = []
        barrier = threading.Barrier(8)

        def _one():
            try:
                target = _zero_like(state)
                barrier.wait(timeout=30)
                RemoteSnapshot(root, addr=server.addr).restore(target)
                _assert_exact(target, state)
            except Exception as e:
                errors.append(repr(e))

        threads = [threading.Thread(target=_one) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        stats = service.stats()
        amplification = stats["backend_read_bytes"] / payload_bytes
        assert amplification <= 1.2, stats
        # Dedup happened — as cache hits, single-flight collapses, or
        # both, depending on how tightly the 8 restores overlapped.
        assert (
            stats["cache"]["hits"] + stats["singleflight_collapses"] > 0
        ), stats
    finally:
        server.stop()


def test_cache_corruption_refetches_through_service():
    root = _mem_root("corrupt")
    payload = b"critical-weights" * 1024
    storage = url_to_storage_plugin(root)
    try:
        asyncio.run(storage.write(IOReq(path="0/w", data=payload)))
    finally:
        storage.close()
    counts = {}
    service = snapserve.ReadService(
        backend_resolver=lambda url: _CountingPlugin(
            url_to_storage_plugin(url), counts
        )
    )
    data, meta = asyncio.run(service.handle_read(root, "0/w"))
    assert data == payload and meta["served"] == "backend"
    data, meta = asyncio.run(service.handle_read(root, "0/w"))
    assert data == payload and meta["served"] == "cache"
    (key,) = list(service.cache._entries)
    assert service.cache.corrupt_for_test(key)
    data, meta = asyncio.run(service.handle_read(root, "0/w"))
    assert data == payload  # authoritative bytes, not the corrupt entry
    assert meta["served"] == "backend"  # re-fetched
    assert counts["0/w"] == 2
    assert service.cache.stats()["corrupt"] == 1
    service.close()


def test_manifest_load_single_flighted_across_cold_clients():
    root = _mem_root("meta-flight")
    Snapshot.take(root, _state(n_params=2, n=256))
    counts = {}
    service = snapserve.ReadService(
        backend_resolver=lambda url: _CountingPlugin(
            url_to_storage_plugin(url), counts, delay_s=0.05
        )
    )
    server = snapserve.start_local_server(service=service)
    try:
        async def _cold_herd():
            plugins = [
                sp_mod.url_to_storage_plugin(
                    f"snapserve://{server.addr}/{root}"
                )
                for _ in range(8)
            ]
            try:
                reqs = [IOReq(path=".snapshot_metadata") for _ in plugins]
                await asyncio.gather(
                    *(p.read(r) for p, r in zip(plugins, reqs))
                )
                return [bytes(io_payload(r)) for r in reqs]
            finally:
                for p in plugins:
                    p.close()

        results = asyncio.run(_cold_herd())
        assert len(set(results)) == 1 and results[0]
        # Exactly ONE backend metadata fetch despite 8 concurrent cold
        # clients: the load is single-flighted, not just memoized.
        assert counts[".snapshot_metadata"] == 1, counts
    finally:
        server.stop()


def test_cancelled_singleflight_leader_does_not_poison_waiters():
    root = _mem_root("cancel")
    payload = b"shared-object" * 512
    storage = url_to_storage_plugin(root)
    try:
        asyncio.run(storage.write(IOReq(path="0/obj", data=payload)))
    finally:
        storage.close()
    counts = {}
    service = snapserve.ReadService(
        backend_resolver=lambda url: _CountingPlugin(
            url_to_storage_plugin(url), counts, delay_s=0.1
        )
    )

    async def _leader_dies():
        leader = asyncio.ensure_future(
            service.handle_read(root, "0/obj")
        )
        await asyncio.sleep(0.02)  # leader is mid-backend-fetch
        waiter = asyncio.ensure_future(
            service.handle_read(root, "0/obj")
        )
        await asyncio.sleep(0.02)  # waiter piggybacks on the flight
        leader.cancel()
        try:
            await leader
        except asyncio.CancelledError:
            pass  # the leader dying is the scenario under test
        # The waiter must still be served the real bytes — the fetch
        # belongs to the service, not the (dead) requester.
        data, _meta = await waiter
        return data

    data = asyncio.run(_leader_dies())
    assert data == payload
    assert counts["0/obj"] == 1  # and still only one backend read
    service.close()


def test_oversize_object_ranged_reads_pass_through():
    root = _mem_root("oversize")
    payload = bytes(range(256)) * 256  # 64 KiB
    storage = url_to_storage_plugin(root)
    try:
        asyncio.run(storage.write(IOReq(path="0/huge", data=payload)))
    finally:
        storage.close()
    counts = {}
    # Cache cap far below the object: a ranged read must NOT trigger
    # (repeated) whole-object fetches.
    service = snapserve.ReadService(
        cache_bytes=4 << 10,
        backend_resolver=lambda url: _CountingPlugin(
            url_to_storage_plugin(url), counts
        ),
    )
    before = service.stats()["backend_read_bytes"]

    async def _ranges():
        out = []
        for r in [(0, 1024), (1024, 2048), (0, 1024)]:
            data, meta = await service.handle_read(
                root, "0/huge", byte_range=r
            )
            out.append((data, meta["served"]))
        return out

    results = asyncio.run(_ranges())
    assert results[0][0] == payload[0:1024]
    assert results[1][0] == payload[1024:2048]
    assert all(served == "backend-range" for _d, served in results)
    read_bytes = service.stats()["backend_read_bytes"] - before
    # 3 ranged GETs of 1 KiB each (plus no manifest here), never
    # 3 x 64 KiB whole-object fetches.
    assert read_bytes <= 4 << 10, read_bytes
    assert service.cache.bytes_used == 0  # nothing oversize was cached
    service.close()


def test_retake_rolls_cache_generation_for_unchecksummed_objects():
    root = _mem_root("generation")
    state = _state(n_params=2, n=256, seed=11)
    Snapshot.take(root, state)
    # An out-of-manifest payload object (no checksum to key against).
    storage = url_to_storage_plugin(root)
    try:
        asyncio.run(storage.write(IOReq(path="0/extra", data=b"v1" * 64)))
    finally:
        storage.close()
    service = snapserve.ReadService(meta_ttl_s=0.0)  # refresh every read
    data, _ = asyncio.run(service.handle_read(root, "0/extra"))
    assert data == b"v1" * 64
    # Rewrite the object AND the manifest (a re-take): the manifest
    # generation tag rolls, so the old cache entry is unreachable.
    storage = url_to_storage_plugin(root)
    try:
        asyncio.run(storage.write(IOReq(path="0/extra", data=b"v2" * 64)))
    finally:
        storage.close()
    Snapshot.take(root, _state(n_params=2, n=256, seed=12))
    data, _ = asyncio.run(service.handle_read(root, "0/extra"))
    assert data == b"v2" * 64  # never the stale v1 cache entry
    service.close()


# ----------------------------------------------------------- degraded mode


def test_unreachable_server_falls_back_bit_exact_and_is_counted():
    root = _mem_root("fallback")
    state = _state(n_params=3, n=1024)
    Snapshot.take(root, state)
    before = snapserve.stats_snapshot()
    # Nothing listens on this port: every read must degrade to direct.
    remote = RemoteSnapshot(root, addr="127.0.0.1:1")
    target = _zero_like(state)
    remote.restore(target)
    _assert_exact(target, state)
    delta_fallback = (
        snapserve.stats_snapshot()["fallback_objects"]
        - before["fallback_objects"]
    )
    assert delta_fallback > 0
    # Flight report carries the read_plane block; the doctor names it.
    report = _restore_report(root)
    assert report is not None
    planes = [
        s.get("read_plane") for s in report["ranks"] if s
    ]
    assert planes and planes[0]["fallback_objects"] > 0
    assert planes[0]["remote_objects"] == 0
    findings = diagnose_report(report)
    rule = {f.rule: f for f in findings}["read-plane-degraded"]
    assert rule.severity == "critical"  # 100% of bytes fell back
    # Ledger restore record carries the same attribution.
    records, _ = runledger.read_records(root)
    restores = [r for r in records if r["kind"] == "restore"]
    assert restores and restores[-1]["read_plane"]["fallback_objects"] > 0


def test_healthy_service_restore_fires_no_read_plane_rule():
    root = _mem_root("healthy")
    state = _state(n_params=2, n=512)
    Snapshot.take(root, state)
    server = snapserve.start_local_server()
    try:
        target = _zero_like(state)
        RemoteSnapshot(root, addr=server.addr).restore(target)
        _assert_exact(target, state)
        report = _restore_report(root)
        assert report is not None
        planes = [s.get("read_plane") for s in report["ranks"] if s]
        assert planes and planes[0]["remote_objects"] > 0
        assert planes[0]["fallback_objects"] == 0
        assert not any(
            f.rule == "read-plane-degraded" for f in diagnose_report(report)
        )
    finally:
        server.stop()


def test_concurrent_restores_do_not_cross_attribute_read_plane_stats():
    """Two restores in flight at once — one healthy (served), one
    degraded (dead server) — must each report THEIR OWN read_plane
    block: the healthy restore's flight report shows zero fallbacks
    even though the other thread was falling back the whole time."""
    healthy_root = _mem_root("attr-healthy")
    degraded_root = _mem_root("attr-degraded")
    state = _state(n_params=3, n=2048)
    Snapshot.take(healthy_root, state)
    Snapshot.take(degraded_root, state)
    server = snapserve.start_local_server()
    try:
        barrier = threading.Barrier(2)
        errors = []

        def _healthy():
            try:
                barrier.wait(timeout=30)
                t = _zero_like(state)
                RemoteSnapshot(healthy_root, addr=server.addr).restore(t)
                _assert_exact(t, state)
            except Exception as e:
                errors.append(repr(e))

        def _degraded():
            try:
                barrier.wait(timeout=30)
                t = _zero_like(state)
                RemoteSnapshot(degraded_root, addr="127.0.0.1:1").restore(t)
                _assert_exact(t, state)
            except Exception as e:
                errors.append(repr(e))

        threads = [
            threading.Thread(target=_healthy),
            threading.Thread(target=_degraded),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        healthy_plane = [
            s.get("read_plane")
            for s in _restore_report(healthy_root)["ranks"]
            if s
        ][0]
        degraded_plane = [
            s.get("read_plane")
            for s in _restore_report(degraded_root)["ranks"]
            if s
        ][0]
        assert healthy_plane["fallback_objects"] == 0, healthy_plane
        assert healthy_plane["remote_objects"] > 0
        assert degraded_plane["fallback_objects"] > 0, degraded_plane
        assert degraded_plane["remote_objects"] == 0
    finally:
        server.stop()


@pytest.mark.faultline
def test_kill_server_mid_restore_degrades_bit_exact_and_fires_doctor():
    root = _mem_root("kill")
    state = _state(n_params=6, n=2048)
    Snapshot.take(root, state)
    server = snapserve.start_local_server()
    remote = RemoteSnapshot(root, addr=server.addr)
    # Deterministic mid-restore death: the 3rd RPC attempt finds the
    # server already gone (the boundary fires before the dial).
    sched = fl.FaultSchedule().kill_server(nth=3)
    with fl.inject(sched) as ctl:
        target = _zero_like(state)
        remote.restore(target)
    _assert_exact(target, state)
    assert ctl.fault_counts().get("killserver") == 1
    report = _restore_report(root)
    planes = [s.get("read_plane") for s in report["ranks"] if s]
    assert planes and planes[0]["fallback_objects"] > 0
    findings = diagnose_report(report)
    assert any(f.rule == "read-plane-degraded" for f in findings)
    records, _ = runledger.read_records(root)
    restores = [r for r in records if r["kind"] == "restore"]
    assert restores[-1]["read_plane"]["fallback_objects"] > 0
    assert "read-plane-degraded" in restores[-1]["doctor"]


@pytest.mark.faultline
def test_slow_server_schedule_injects_latency_deterministically():
    root = _mem_root("slow")
    state = _state(n_params=2, n=512)
    Snapshot.take(root, state)
    server = snapserve.start_local_server()
    try:
        remote = RemoteSnapshot(root, addr=server.addr)
        sched = fl.FaultSchedule().slow_server(seconds=0.03, times=3)
        with fl.inject(sched) as ctl:
            target = _zero_like(state)
            remote.restore(target)
        _assert_exact(target, state)
        assert ctl.fault_counts().get("latency") == 3
        # Slow is not dead: everything was still served by the plane.
        report = _restore_report(root)
        planes = [s.get("read_plane") for s in report["ranks"] if s]
        assert planes and planes[0]["fallback_objects"] == 0
    finally:
        server.stop()


# ------------------------------------------------------------ flow control


def test_flow_control_bounds_inflight_bytes_but_always_progresses():
    root = _mem_root("flow")
    payload = bytes(np.random.default_rng(9).bytes(64 << 10))
    storage = url_to_storage_plugin(root)
    try:
        for i in range(4):
            asyncio.run(
                storage.write(IOReq(path=f"0/big{i}", data=payload))
            )
    finally:
        storage.close()
    before = telemetry.snapshot().get(
        "tpusnapshot_snapserve_flow_control_stall_seconds_total", 0.0
    )
    service = snapserve.ReadService(client_inflight_bytes=16 << 10)
    server = snapserve.start_local_server(service=service)
    try:
        async def _concurrent_bigs():
            plugin = sp_mod.url_to_storage_plugin(
                f"snapserve://{server.addr}/{root}"
            )
            try:
                reqs = [IOReq(path=f"0/big{i}") for i in range(4)]
                await asyncio.gather(*(plugin.read(r) for r in reqs))
                return [bytes(io_payload(r)) for r in reqs]
            finally:
                plugin.close()

        results = asyncio.run(_concurrent_bigs())
        assert all(r == payload for r in results)  # oversize still served
        after = telemetry.snapshot().get(
            "tpusnapshot_snapserve_flow_control_stall_seconds_total", 0.0
        )
        assert after >= before  # stall accounting is wired (may be ~0)
    finally:
        server.stop()


# ------------------------------------------------- local manifest memoization


def test_read_object_fetches_and_parses_manifest_once_per_handle():
    root = _mem_root("local-memo")
    state = _state(n_params=3, n=256)
    Snapshot.take(root, state)

    counts = {}
    prev = sp_mod.set_plugin_wrap_hook(
        lambda plugin, url: _CountingPlugin(plugin, counts)
    )
    try:
        import torchsnapshot_tpu.snapshot as snap_mod

        derive_calls = []
        real = snap_mod.get_available_entries

        def _counting(manifest, rank):
            derive_calls.append(rank)
            return real(manifest, rank)

        snap_mod.get_available_entries = _counting
        try:
            snap = Snapshot(root)
            for i in range(5):
                np.testing.assert_array_equal(
                    snap.read_object(f"m/p{i % 3}"), state["m"][f"p{i % 3}"]
                )
        finally:
            snap_mod.get_available_entries = real
        assert counts[".snapshot_metadata"] == 1, counts
        assert len(derive_calls) == 1, derive_calls
    finally:
        sp_mod.set_plugin_wrap_hook(prev)


def test_delete_invalidates_manifest_memo_and_retake_is_visible():
    root = _mem_root("invalidate")
    state = _state(n_params=2, n=256, seed=1)
    Snapshot.take(root, state)
    snap = Snapshot(root)
    np.testing.assert_array_equal(
        snap.read_object("m/p0"), state["m"]["p0"]
    )
    snap.delete()
    # The memo must not keep serving a deleted snapshot.
    with pytest.raises(Exception):
        snap.read_object("m/p0")
    # Re-take at the same path: the SAME handle sees the new content
    # (its cache was invalidated, the next read refetches).
    state2 = _state(n_params=2, n=256, seed=2)
    Snapshot.take(root, state2)
    np.testing.assert_array_equal(
        snap.read_object("m/p0"), state2["m"]["p0"]
    )


# -------------------------------------------------------- real server process


def test_server_subprocess_entrypoint_over_fs(tmp_path):
    """The ``python -m torchsnapshot_tpu.snapserve.server`` entrypoint
    for real: a separate server process fronting an fs snapshot, an
    ephemeral port discovered via --port-file, reads served
    cross-process (memory:// cannot cross processes; fs can)."""
    import os
    import subprocess
    import sys
    import time

    root = tmp_path / "snap"
    state = _state(n_params=2, n=512)
    Snapshot.take(str(root), state)
    port_file = tmp_path / "port"
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "torchsnapshot_tpu.snapserve.server",
            "--addr",
            "127.0.0.1:0",
            "--port-file",
            str(port_file),
        ],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        deadline = time.monotonic() + 90
        while not port_file.exists():
            assert proc.poll() is None, "server process died during startup"
            assert time.monotonic() < deadline, "server never bound"
            time.sleep(0.1)
        addr = port_file.read_text().strip()
        remote = RemoteSnapshot(str(root), addr=addr)
        np.testing.assert_array_equal(
            remote.read_object("m/p1"), state["m"]["p1"]
        )
        stats = snapserve.fetch_server_stats(addr)
        assert stats["requests"] >= 1
        assert stats["manifest_loads"] == 1
        # Nothing fell back: the cross-process hop really served it.
        report_plane = snapserve.stats_snapshot()
        assert report_plane["remote_objects"] > 0
    finally:
        proc.kill()
        proc.wait(timeout=30)


# -------------------------------------------------------------------- knobs


def test_cache_bytes_env_knob(monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_SNAPSERVE_CACHE_BYTES", str(12345))
    service = snapserve.ReadService()
    assert service.cache.cap_bytes == 12345
    service.close()


def test_remote_snapshot_addr_env_knob(monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_SNAPSERVE_ADDR", "10.0.0.9:7171")
    snap = RemoteSnapshot("memory://b/run")
    assert snap.path == "snapserve://10.0.0.9:7171/memory://b/run"
    assert snap.backend_path == "memory://b/run"
    monkeypatch.delenv("TPUSNAPSHOT_SNAPSERVE_ADDR")
    plain = RemoteSnapshot("memory://b/run")
    assert plain.path == "memory://b/run"  # degenerates to direct


def test_writes_and_deletes_go_direct_to_backend():
    root = _mem_root("writes")
    server = snapserve.start_local_server()
    try:
        url = f"snapserve://{server.addr}/{root}"
        state = _state(n_params=2, n=256)
        # take/delete through a snapserve URL: mutations bypass the
        # server entirely (its request count stays at zero).
        before = snapserve.stats_snapshot()
        snap = Snapshot.take(url, state)
        after = snapserve.stats_snapshot()
        stats_after_take = snapserve.fetch_server_stats(server.addr)
        # The take's only service traffic is the ledger append's
        # read-before-append (a not-found, served THROUGH the service
        # — proving remote not-found propagates rather than falling
        # back); every write went straight to the backend (the server
        # has no write op at all) and zero payload left the server.
        assert stats_after_take["requests"] <= 1
        assert stats_after_take["egress_bytes"] == 0
        assert after["fallback_objects"] == before["fallback_objects"]
        direct = Snapshot(root)
        target = _zero_like(state)
        direct.restore(target)
        _assert_exact(target, state)
        snap.delete()
        storage = url_to_storage_plugin(root)
        try:
            leftovers = asyncio.run(storage.list_prefix(""))
        finally:
            storage.close()
        assert leftovers == []
    finally:
        server.stop()
