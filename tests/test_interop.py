"""Interop tests: reading reference-written snapshots + torch adapters.

The strongest parity evidence available: the *actual reference library*
(imported from /root/reference, which is mounted read-only) writes a
snapshot, and this framework reads/restores/converts it. Gated on the
reference (and torch) being importable.
"""

import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")

from torchsnapshot_tpu import Snapshot
from torchsnapshot_tpu.interop import (
    ReferenceSnapshotReader,
    TorchStateful,
    numpy_to_torch_tree,
    torch_to_numpy_tree,
)
from torchsnapshot_tpu.utils.train_state import PytreeStateful


def _import_reference():
    if "/root/reference" not in sys.path:
        sys.path.insert(0, "/root/reference")
    try:
        import torchsnapshot as ref

        return ref
    except Exception:
        return None


@pytest.fixture(scope="module")
def ref():
    ref = _import_reference()
    if ref is None:
        pytest.skip("reference torchsnapshot not importable")
    return ref


@pytest.fixture()
def ref_snapshot(ref, tmp_path):
    """A genuine reference-written snapshot of a model + progress state."""
    torch.manual_seed(7)
    model = torch.nn.Sequential(
        torch.nn.Linear(8, 4), torch.nn.ReLU(), torch.nn.Linear(4, 2)
    )
    progress = ref.StateDict(epoch=3, steps=[1, 2, 3], name="run-a")
    path = str(tmp_path / "ref_snap")
    ref.Snapshot.take(path=path, app_state={"model": model, "progress": progress})
    return path, model, progress


def test_read_leaf_bitwise(ref_snapshot):
    path, model, _ = ref_snapshot
    reader = ReferenceSnapshotReader(path)
    got = reader.read("model/0.weight")
    want = model.state_dict()["0.weight"].numpy()
    assert got.dtype == want.dtype
    np.testing.assert_array_equal(got, want)


def test_load_subtree_and_objects(ref_snapshot):
    path, model, progress = ref_snapshot
    reader = ReferenceSnapshotReader(path)
    tree = reader.load("progress")
    assert tree["epoch"] == 3
    assert tree["steps"] == [1, 2, 3]
    assert tree["name"] == "run-a"
    model_tree = reader.load("model")
    for key, tensor in model.state_dict().items():
        np.testing.assert_array_equal(model_tree[key], tensor.numpy())


def test_restore_into_jax_templates(ref_snapshot):
    path, model, _ = ref_snapshot
    reader = ReferenceSnapshotReader(path)
    template = {
        key: jnp.zeros(tuple(t.shape), dtype=jnp.float32)
        for key, t in model.state_dict().items()
    }
    holder = PytreeStateful(template)
    reader.restore({"model": holder})
    for key, tensor in model.state_dict().items():
        got = np.asarray(holder.tree[key])
        np.testing.assert_array_equal(got, tensor.numpy())
        assert isinstance(holder.tree[key], jax.Array)


def test_restore_dtype_mismatch_raises(ref_snapshot):
    path, model, _ = ref_snapshot
    reader = ReferenceSnapshotReader(path)
    template = {
        key: jnp.zeros(tuple(t.shape), dtype=jnp.bfloat16)
        for key, t in model.state_dict().items()
    }
    with pytest.raises(RuntimeError, match="dtype mismatch"):
        reader.restore({"model": PytreeStateful(template)})


def test_convert_to_native_format(ref_snapshot, tmp_path):
    path, model, _ = ref_snapshot
    reader = ReferenceSnapshotReader(path)
    native = reader.convert(str(tmp_path / "native"))
    # The converted snapshot restores through the native path.
    template = {
        key: np.zeros(tuple(t.shape), dtype=np.float32)
        for key, t in model.state_dict().items()
    }
    holder = PytreeStateful(template)
    native.restore({"model": holder})
    for key, tensor in model.state_dict().items():
        np.testing.assert_array_equal(holder.tree[key], tensor.numpy())
    # Objects survive conversion too.
    progress = Snapshot(str(tmp_path / "native")).read_object("progress/epoch")
    assert progress == 3


def test_bfloat16_reference_roundtrip(ref, tmp_path):
    class Holder:
        def __init__(self):
            self.t = torch.arange(16, dtype=torch.float32).view(4, 4).bfloat16()

        def state_dict(self):
            return {"t": self.t}

        def load_state_dict(self, sd):
            self.t = sd["t"]

    path = str(tmp_path / "bf16")
    ref.Snapshot.take(path=path, app_state={"h": Holder()})
    got = ReferenceSnapshotReader(path).read("h/t")
    import ml_dtypes

    assert got.dtype == np.dtype(ml_dtypes.bfloat16)
    want = Holder().t.view(torch.int16).numpy().view(ml_dtypes.bfloat16)
    np.testing.assert_array_equal(got.view(np.int16), want.view(np.int16))


def test_sharded_tensor_reassembly(tmp_path):
    """Hand-crafted 2-rank reference manifest with a sharded tensor: the
    reader merges shards across ranks and reassembles the dense array.
    (Creating a real ShardedTensor needs torch.distributed init; the
    format is exercised directly instead — schema per reference
    manifest.py:49-63.)"""
    import io as _io

    import yaml

    full = np.arange(32, dtype=np.float32).reshape(8, 4)
    root = tmp_path / "sharded_snap"
    shards = []
    for rank, row0 in enumerate((0, 4)):
        loc = f"sharded/emb/t_{row0}_0"
        (root / "sharded" / "emb").mkdir(parents=True, exist_ok=True)
        buf = _io.BytesIO()
        torch.save(torch.from_numpy(full[row0 : row0 + 4]), buf)
        (root / loc).write_bytes(buf.getvalue())
        shards.append(
            {
                "offsets": [row0, 0],
                "sizes": [4, 4],
                "tensor": {
                    "type": "Tensor",
                    "location": loc,
                    "serializer": "torch_save",
                    "dtype": "torch.float32",
                    "shape": [4, 4],
                    "replicated": False,
                },
            }
        )
    manifest = {
        f"{rank}/emb/t": {"type": "ShardedTensor", "shards": [shard]}
        for rank, shard in enumerate(shards)
    }
    (root / ".snapshot_metadata").write_text(
        yaml.dump({"version": "0.0.3", "world_size": 2, "manifest": manifest})
    )
    reader = ReferenceSnapshotReader(str(root))
    got = reader.read("emb/t", rank=1)  # any rank sees the merged shards
    np.testing.assert_array_equal(got, full)


def test_convert_refuses_foreign_per_rank(tmp_path):
    import io as _io

    import yaml

    root = tmp_path / "two_rank"
    for rank in range(2):
        (root / str(rank) / "s").mkdir(parents=True, exist_ok=True)
        buf = _io.BytesIO()
        torch.save(torch.tensor([rank]), buf)
        (root / str(rank) / "s" / "v").write_bytes(buf.getvalue())
    manifest = {
        f"{rank}/s/v": {
            "type": "Tensor",
            "location": f"{rank}/s/v",
            "serializer": "torch_save",
            "dtype": "torch.int64",
            "shape": [1],
            "replicated": False,
        }
        for rank in range(2)
    }
    (root / ".snapshot_metadata").write_text(
        yaml.dump({"version": "0.0.3", "world_size": 2, "manifest": manifest})
    )
    with pytest.raises(RuntimeError, match="per-rank"):
        ReferenceSnapshotReader(str(root)).convert(str(tmp_path / "out"))


def test_torch_stateful_roundtrip(tmp_path):
    torch.manual_seed(11)
    model = torch.nn.Linear(6, 3)
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    loss = model(torch.randn(2, 6)).sum()
    loss.backward()
    opt.step()

    path = str(tmp_path / "snap")
    Snapshot.take(
        path, {"model": TorchStateful(model), "opt": TorchStateful(opt)}
    )

    model2 = torch.nn.Linear(6, 3)
    opt2 = torch.optim.Adam(model2.parameters(), lr=1e-3)
    # Adam state must exist before load_state_dict can fill it in place.
    model2(torch.randn(2, 6)).sum().backward()
    opt2.step()
    Snapshot(path).restore(
        {"model": TorchStateful(model2), "opt": TorchStateful(opt2)}
    )

    for (k1, t1), (k2, t2) in zip(
        model.state_dict().items(), model2.state_dict().items()
    ):
        assert k1 == k2
        np.testing.assert_array_equal(t1.numpy(), t2.numpy())
    s1, s2 = opt.state_dict()["state"], opt2.state_dict()["state"]
    assert set(s1.keys()) == set(s2.keys())
    for idx in s1:
        for field in s1[idx]:
            v1, v2 = s1[idx][field], s2[idx][field]
            if isinstance(v1, torch.Tensor):
                np.testing.assert_array_equal(v1.numpy(), v2.numpy())
            else:
                assert v1 == v2


def test_torch_stateful_cross_framework(tmp_path):
    """State saved from a torch module restores into a JAX template."""
    torch.manual_seed(3)
    model = torch.nn.Linear(5, 2)
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"model": TorchStateful(model)})

    template = {
        "weight": jnp.zeros((2, 5), jnp.float32),
        "bias": jnp.zeros((2,), jnp.float32),
    }
    holder = PytreeStateful(template)
    Snapshot(path).restore({"model": holder})
    np.testing.assert_array_equal(
        np.asarray(holder.tree["weight"]), model.weight.detach().numpy()
    )
    np.testing.assert_array_equal(
        np.asarray(holder.tree["bias"]), model.bias.detach().numpy()
    )


def test_torch_restore_dtype_mismatch_raises(tmp_path):
    """Tensor.copy_ would silently cast; the adapter must refuse instead."""
    model = torch.nn.Linear(4, 2)
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"model": TorchStateful(model)})
    model_bf16 = torch.nn.Linear(4, 2).bfloat16()
    with pytest.raises(RuntimeError, match="dtype mismatch"):
        Snapshot(path).restore({"model": TorchStateful(model_bf16)})


def test_numpy_never_leaks_through_conversion():
    """Arrays convert to tensors even where the template has no tensor."""
    tree = numpy_to_torch_tree(
        {"a": np.ones((2,), np.float32)}, template={"a": 5}
    )
    assert isinstance(tree["a"], torch.Tensor)


def test_bf16_tree_conversion_bitwise():
    import ml_dtypes

    t = torch.arange(7, dtype=torch.float32).bfloat16()
    tree = torch_to_numpy_tree({"a": t, "b": [t, 5], "c": "x"})
    assert tree["a"].dtype == np.dtype(ml_dtypes.bfloat16)
    back = numpy_to_torch_tree(tree)
    assert back["a"].dtype == torch.bfloat16
    assert torch.equal(back["a"], t)
    assert torch.equal(back["b"][0], t)
    assert back["b"][1] == 5 and back["c"] == "x"


# ------------------------------------------------- write-side (convert_back)


class _NativeHolder:
    def __init__(self, sd):
        self.sd = sd

    def state_dict(self):
        return self.sd

    def load_state_dict(self, sd):
        self.sd = sd


def test_convert_back_restored_by_reference(ref, tmp_path):
    """native -> reference format -> restored by the ACTUAL reference
    library in-process, bitwise (VERDICT r2 ask #8: migration must be
    reversible). Covers dense fp32 + bf16 arrays, a sharded array
    (assembled dense), nested containers, an object, and primitives."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu import Snapshot
    from torchsnapshot_tpu.interop.reference_writer import convert_back

    w = np.arange(64, dtype=np.float32).reshape(8, 8)
    b16 = np.arange(16, dtype=np.float32).astype("bfloat16")
    devices = np.array(jax.devices()[:4])
    mesh = Mesh(devices, ("x",))
    sharded = jax.device_put(
        jnp.arange(32, dtype=jnp.float32).reshape(8, 4),
        NamedSharding(mesh, P("x", None)),
    )
    native_state = {
        "w": jnp.asarray(w),
        "b16": jnp.asarray(b16),
        "sharded": sharded,
        "nested": {"scale": jnp.full((4,), 2.5)},
        "steps": [1, 2, 3],
        "name": "run-b",
        "epoch": 7,
    }
    native = str(tmp_path / "native")
    Snapshot.take(native, {"m": _NativeHolder(native_state)})

    dest = str(tmp_path / "ref_format")
    convert_back(native, dest)

    # The reference library restores it. The target stateful hands back
    # a PLAIN dict: the reference's flatten uses exact type() checks, so
    # a ref.StateDict would itself be treated as one opaque leaf.
    holder = _NativeHolder(
        {
            "w": torch.zeros(8, 8),
            "b16": torch.zeros(16, dtype=torch.bfloat16),
            "sharded": torch.zeros(8, 4),
            "nested": {"scale": torch.zeros(4)},
            "steps": [0, 0, 0],
            "name": "",
            "epoch": 0,
        }
    )
    ref.Snapshot(dest).restore({"m": holder})
    target = holder.sd

    torch.testing.assert_close(
        target["w"], torch.from_numpy(w), rtol=0, atol=0
    )
    assert target["b16"].dtype == torch.bfloat16
    np.testing.assert_array_equal(
        target["b16"].view(torch.uint16).numpy(),
        b16.view(np.uint16),
    )
    torch.testing.assert_close(
        target["sharded"],
        torch.arange(32, dtype=torch.float32).reshape(8, 4),
        rtol=0,
        atol=0,
    )
    torch.testing.assert_close(
        target["nested"]["scale"], torch.full((4,), 2.5), rtol=0, atol=0
    )
    assert target["steps"] == [1, 2, 3]
    assert target["name"] == "run-b"
    assert target["epoch"] == 7


def test_convert_back_random_access_via_reference_reader(ref, tmp_path):
    """The emitted snapshot is also readable by our own reference-format
    reader — i.e. it IS the reference on-disk schema, not merely
    something the reference's restore tolerates."""
    import jax.numpy as jnp

    from torchsnapshot_tpu import Snapshot
    from torchsnapshot_tpu.interop.reference_writer import convert_back

    native = str(tmp_path / "native")
    Snapshot.take(
        native,
        {"m": _NativeHolder({"w": jnp.arange(16.0), "epoch": 3})},
    )
    dest = str(tmp_path / "ref_format")
    convert_back(native, dest)

    reader = ReferenceSnapshotReader(dest)
    np.testing.assert_array_equal(
        reader.read("m/w"), np.arange(16, dtype=np.float32)
    )
    assert reader.read("m/epoch") == 3
    reader.close()


def test_convert_back_handles_prng_key_arrays(ref, tmp_path):
    """PRNG key arrays are routine training state; convert_back exports
    their raw uint32 key data (torch has no key-array notion)."""
    import jax
    import jax.numpy as jnp

    from torchsnapshot_tpu import Snapshot
    from torchsnapshot_tpu.interop.reference_writer import convert_back

    key = jax.random.key(42)
    native = str(tmp_path / "native")
    Snapshot.take(
        native,
        {"m": _NativeHolder({"rngkey": key, "w": jnp.arange(4.0)})},
    )
    dest = str(tmp_path / "ref_format")
    convert_back(native, dest)

    reader = ReferenceSnapshotReader(dest)
    got = reader.read("m/rngkey")
    np.testing.assert_array_equal(
        got, np.asarray(jax.random.key_data(key))
    )
    reader.close()


def test_inspect_cli_convert_back(ref, tmp_path, capsys):
    """Operator surface: python -m torchsnapshot_tpu.inspect <native>
    --convert-back <dest> exports reference format."""
    import jax.numpy as jnp

    from torchsnapshot_tpu import Snapshot
    from torchsnapshot_tpu.inspect import main

    native = str(tmp_path / "native")
    Snapshot.take(native, {"m": _NativeHolder({"w": jnp.arange(8.0)})})
    dest = str(tmp_path / "ref")
    assert main([native, "--convert-back", dest]) == 0
    assert "exported" in capsys.readouterr().out

    np.testing.assert_array_equal(
        ReferenceSnapshotReader(dest).read("m/w"),
        np.arange(8, dtype=np.float32),
    )

    with pytest.raises(SystemExit):
        main([native, "--convert-back", dest, "--verify"])


def test_convert_back_multi_rank(ref, tmp_path):
    """A world-2 native snapshot (per-rank + replicated entries) exports
    with the reference's rank-prefixed namespace intact: per-rank values
    stay per-rank, replicated values resolve for every rank, and the
    actual reference library restores each rank's view."""
    from torchsnapshot_tpu import Snapshot
    from torchsnapshot_tpu.interop.reference_writer import convert_back
    from torchsnapshot_tpu.utils.test_utils import run_thread_ranks

    native = str(tmp_path / "native")

    def worker(coord, rank):
        Snapshot.take(
            native,
            {
                "m": _NativeHolder(
                    {
                        "mine": np.full((4,), rank, dtype=np.float32),
                        "shared": np.arange(8, dtype=np.float32),
                    }
                )
            },
            coord=coord,
            replicated=["m/shared"],
        )

    run_thread_ranks(2, worker)
    dest = str(tmp_path / "ref_format")
    convert_back(native, dest)

    class _TorchHolder:
        def __init__(self):
            # Sentinels, NOT the expected values: a restore that
            # silently skips an entry must fail the assertions below.
            self.sd = {
                "mine": torch.full((4,), -1.0),
                "shared": torch.full((8,), -1.0),
            }

        def state_dict(self):
            return self.sd

        def load_state_dict(self, sd):
            self.sd = sd

    # Reference restore is rank 0 in this process; rank 1's view is
    # checked through the reader (no second process needed).
    holder = _TorchHolder()
    ref.Snapshot(dest).restore({"m": holder})
    torch.testing.assert_close(
        holder.sd["mine"], torch.zeros(4), rtol=0, atol=0
    )
    torch.testing.assert_close(
        holder.sd["shared"],
        torch.arange(8, dtype=torch.float32),
        rtol=0,
        atol=0,
    )

    reader = ReferenceSnapshotReader(dest)
    np.testing.assert_array_equal(
        reader.read("m/mine", rank=1), np.full((4,), 1, dtype=np.float32)
    )
    np.testing.assert_array_equal(
        reader.read("m/shared", rank=1), np.arange(8, dtype=np.float32)
    )
    reader.close()
