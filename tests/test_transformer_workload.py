"""Flagship workload integration: snapshot/restore a sharded transformer
train state (params + optax Adam moments) across mesh shapes.

The TPU-scale analog of BASELINE.json's "FSDP Llama sharded snapshot →
elastic restore onto a different pod shape" config, scaled down to the
8-device virtual CPU mesh: train a few steps, snapshot (sync and
device-staged async), then restore onto a differently-shaped mesh and
continue training — losses must match bit-exactly.

Marked ``slow``: the flagship model's attention runs the Pallas kernel
in interpreter mode on the hermetic CPU suite, so each train step costs
minutes of trace time on a single-core host. The snapshot machinery the
file integrates is covered in the fast tier by test_snapshot /
test_elastic / test_roundtrip_fuzz. Run with ``-m slow`` (or no ``-m``
filter)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

pytestmark = pytest.mark.slow

from torchsnapshot_tpu import Snapshot
from torchsnapshot_tpu.models.transformer import (
    TransformerConfig,
    init_params,
    loss_fn,
    shard_params,
)
from torchsnapshot_tpu.utils.test_utils import assert_state_dict_eq
from torchsnapshot_tpu.utils.train_state import PytreeStateful
from torchsnapshot_tpu.utils.tree import to_state_dict

CONFIG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq_len=16
)


def _make_state(mesh):
    params = init_params(CONFIG, jax.random.key(0))
    params = shard_params(params, mesh)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    return params, opt, opt_state


def _steps(params, opt, opt_state, mesh, n, seed=1):
    losses = []
    for i in range(n):
        tokens = jax.random.randint(
            jax.random.key(seed + i), (4, 16), 0, CONFIG.vocab_size
        )
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, tokens, CONFIG, mesh)
        )(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        losses.append(float(loss))
    return params, opt_state, losses


@pytest.mark.parametrize("take_mode", ["sync", "async"])
def test_transformer_elastic_resume(tmp_path, take_mode):
    devices = np.array(jax.devices()).reshape(4, 2)
    mesh = Mesh(devices, ("dp", "tp"))
    params, opt, opt_state = _make_state(mesh)
    params, opt_state, _ = _steps(params, opt, opt_state, mesh, 2)

    app = {
        "params": PytreeStateful(params),
        "opt": PytreeStateful(opt_state, convert=True),
    }
    path = str(tmp_path / "snap")
    if take_mode == "sync":
        Snapshot.take(path, app)
    else:
        pending = Snapshot.async_take(path, app, stage="device")
        pending.wait()

    # Ground truth: continue on the original mesh.
    _, _, expected_losses = _steps(params, opt, opt_state, mesh, 2, seed=9)

    # Elastic restore: different mesh shape AND fewer devices.
    mesh2 = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
    params2 = jax.tree.map(
        lambda a: jax.device_put(jnp.zeros_like(a), _resharded(a, mesh2)),
        params,
    )
    opt_state2 = jax.tree.map(
        lambda a: (
            jax.device_put(jnp.zeros_like(a), _resharded(a, mesh2))
            if isinstance(a, jax.Array)
            else a
        ),
        opt.init(params2),
    )
    target = {
        "params": PytreeStateful(params2),
        "opt": PytreeStateful(opt_state2, convert=True),
    }
    Snapshot(path).restore(target)
    params2, opt_state2 = target["params"].tree, target["opt"].tree

    # Bit-exact state, structure-checked (params and Adam moments).
    assert_state_dict_eq(to_state_dict(params), to_state_dict(params2))
    assert_state_dict_eq(to_state_dict(opt_state), to_state_dict(opt_state2))

    # Continued training on the new mesh: reduction order differs across
    # mesh shapes, so losses match to tight tolerance rather than bitwise.
    _, _, resumed_losses = _steps(params2, opt, opt_state2, mesh2, 2, seed=9)
    np.testing.assert_allclose(resumed_losses, expected_losses, rtol=1e-6)

    # Bit-exact resume guarantee holds on the *same* mesh: restore onto an
    # identically-sharded template and the continued losses are identical.
    params_same = jax.tree.map(
        lambda a: jax.device_put(jnp.zeros_like(a), a.sharding), params
    )
    opt_state_same = jax.tree.map(
        lambda a: (
            jax.device_put(jnp.zeros_like(a), _resharded(a, mesh))
            if isinstance(a, jax.Array)
            else a
        ),
        opt_state,
    )
    target_same = {
        "params": PytreeStateful(params_same),
        "opt": PytreeStateful(opt_state_same, convert=True),
    }
    Snapshot(path).restore(target_same)
    assert_state_dict_eq(
        to_state_dict(opt_state), to_state_dict(target_same["opt"].tree)
    )
    _, _, same_mesh_losses = _steps(
        target_same["params"].tree, opt, target_same["opt"].tree, mesh, 2, seed=9
    )
    assert same_mesh_losses == expected_losses


def _resharded(arr, new_mesh):
    """Map an array's NamedSharding spec onto a new mesh."""
    sharding = arr.sharding
    if isinstance(sharding, NamedSharding):
        return NamedSharding(new_mesh, sharding.spec)
    return NamedSharding(new_mesh, P())


def test_gqa_transformer_all_attention_paths_agree():
    """n_kv_heads < n_heads: the dense einsum (repeat-kv reference),
    flash kernel (index-map GQA), and zigzag ring (grouped chunk) paths
    produce the same loss, and the GQA train step runs jitted on a
    dp x sp x tp mesh with kv heads sharded over tp."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
        loss_fn,
        sgd_train_step,
        shard_params,
    )

    kw = dict(
        vocab_size=64, d_model=64, n_heads=8, n_kv_heads=2, n_layers=2,
        d_ff=64, max_seq_len=32,
    )
    dense = TransformerConfig(**kw)
    params = init_params(dense, jax.random.key(0))
    # wk/wv are [d_model, n_kv*head_dim] — the GQA shape.
    assert params["layers"][0]["attn"]["wk"].shape == (64, 16)
    tokens = jax.random.randint(jax.random.key(1), (2, 32), 0, 64)

    loss_dense = float(loss_fn(params, tokens, dense))
    flash = TransformerConfig(**kw, flash_attention=True)
    loss_flash = float(loss_fn(params, tokens, flash))
    np.testing.assert_allclose(loss_flash, loss_dense, rtol=1e-5)

    devices = np.array(jax.devices()).reshape(2, 2, 2)
    mesh = Mesh(devices, ("dp", "sp", "tp"))
    zig = TransformerConfig(**kw, ring_attention="zigzag")
    sharded = shard_params(params, mesh)
    tok_sharded = jax.device_put(
        tokens.repeat(2, axis=0), NamedSharding(mesh, P("dp", "sp"))
    )
    loss_zig = float(
        jax.jit(lambda p, t: loss_fn(p, t, zig, mesh))(sharded, tok_sharded)
    )
    loss_dense_sharded = float(
        jax.jit(lambda p, t: loss_fn(p, t, dense, mesh))(sharded, tok_sharded)
    )
    np.testing.assert_allclose(loss_zig, loss_dense_sharded, rtol=1e-5)

    _, loss = jax.jit(
        lambda p, t: sgd_train_step(p, t, config=zig, mesh=mesh)
    )(sharded, tok_sharded)
    assert np.isfinite(float(loss))


def test_gqa_rejects_indivisible_heads():
    from torchsnapshot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    import jax
    import pytest

    cfg = TransformerConfig(n_heads=4, n_kv_heads=3)
    with pytest.raises(ValueError, match="multiple of"):
        init_params(cfg, jax.random.key(0))
