"""snapmend: the hot tier's self-healing repair plane.

Fast tier (``-m faultline``, runs in tier-1): repair back to k after a
real host loss with a bit-exact restore from a *repaired* (not
original) replica and the under-replicated gauge returning to 0;
subprocess auto-restart one membership generation up with the address
book and port-file hot-reloaded; the hung-not-dead peer (SIGSTOP)
classified lost past the repair deadline with its SIGCONT'd stale
generation refused; deterministic ``flap_host`` lose-then-rejoin
churn; the repair × crash-point stride (full enumeration ``-m
slow``) proving no crash point resurrects a deleted root's objects or
repairs superseded tags; deadline-exceeded escalation to durable
write-through firing ``replication-underreplicated`` critical; the
down-cooldown background re-probe; and the repair telemetry surface
(metrics, ledger ``repair`` record, ops CLI membership section, exit
code).

In-process peers (``start_local_peer``) carry real TCP sockets without
subprocess spawn cost; loss/restart/SIGSTOP scenarios use real
``spawn_peer`` subprocesses — the signal IS the fault.
"""

import os
import signal
import tempfile
import time

import numpy as np
import pytest

import jax.numpy as jnp

from torchsnapshot_tpu import Snapshot, StateDict, hottier, telemetry
from torchsnapshot_tpu import faultline as fl
from torchsnapshot_tpu.hottier import repair as ht_repair
from torchsnapshot_tpu.hottier import tier as ht_tier
from torchsnapshot_tpu.hottier import transport
from torchsnapshot_tpu.hottier.peer import spawn_peer, start_local_peer
from torchsnapshot_tpu.telemetry import ledger as runledger
from torchsnapshot_tpu.telemetry import metrics as m
from torchsnapshot_tpu.telemetry import ops as ops_cli
from torchsnapshot_tpu.telemetry import slo as slo_mod

pytestmark = pytest.mark.faultline


# ----------------------------------------------------------------- helpers


@pytest.fixture(autouse=True)
def _fresh_mend(monkeypatch):
    """Every test starts with an empty tier, no peers, no scripted
    faults, fast-failing wire knobs, and a tight repair cadence."""
    monkeypatch.setenv("TPUSNAPSHOT_REPLICATION_DEADLINE_S", "2")
    monkeypatch.setenv("TPUSNAPSHOT_REPLICATION_RETRY_BUDGET_S", "3")
    monkeypatch.setenv("TPUSNAPSHOT_REPLICATION_DOWN_COOLDOWN_S", "0.2")
    monkeypatch.setenv("TPUSNAPSHOT_REPLICATION_CODEC", "none")
    monkeypatch.setenv("TPUSNAPSHOT_REPAIR_INTERVAL_S", "0.2")
    monkeypatch.setenv("TPUSNAPSHOT_REPAIR_DEADLINE_S", "30")
    hottier.disable_hot_tier(flush=False)
    hottier.reset_hot_tier()
    transport.clear_wire_faults()
    servers = []
    yield servers
    hottier.disable_hot_tier(flush=False)
    hottier.reset_hot_tier()  # closes RemotePeers, kills spawned procs
    transport.clear_wire_faults()
    for server in servers:
        server.stop()


def _local_peer(servers, host_id, capacity_bytes=1 << 26):
    server, peer = start_local_peer(host_id, capacity_bytes=capacity_bytes)
    servers.append(server)
    return peer


def _state(v, n=2048):
    return {"s": StateDict(w=jnp.full((n,), float(v), dtype=jnp.float32))}


def _target(n=2048):
    return {"s": StateDict(w=jnp.zeros((n,), dtype=jnp.float32))}


def _assert_restored(target, v):
    np.testing.assert_array_equal(np.asarray(target["s"]["w"]), float(v))


# --------------------------------------------------------- repair back to k


def test_repair_restores_k_and_restore_from_repaired_replica(_fresh_mend):
    """The headline contract: lose one of the replica hosts →
    repair_tick re-replicates every committed undrained object back to
    k from a surviving replica, the under-replicated gauge returns to
    0, and a restore served ONLY by the repaired replica is
    bit-exact."""
    for h in (1, 2, 3):
        _local_peer(_fresh_mend, h)
    path = "memory://mend-k/run/step_0"
    c_obj = telemetry.counter(m.HOT_TIER_REPAIR_OBJECTS).value
    c_bytes = telemetry.counter(m.HOT_TIER_REPAIR_BYTES).value
    with hottier.hot_tier(
        rank=0, world=4, k=3, drain="manual", repair="manual"
    ):
        snap = Snapshot.take(path, _state(7.0))
        key = path + "/0/s/w"
        assert ht_tier.live_replicas(key) == [0, 1, 2]
        ht_tier.kill_host(1)
        assert ht_tier.live_replicas(key) == [0, 2]
        summary = hottier.repair_tick()
        assert summary["hosts_lost"] == [1]
        assert summary["objects_repaired"] == 1
        assert summary["underreplicated_objects"] == 0
        # Repaired onto the spare host 3 — back at k.
        assert ht_tier.live_replicas(key) == [0, 2, 3]
        assert (
            telemetry.gauge(m.HOT_TIER_UNDERREPLICATED_BYTES).value == 0.0
        )
        assert telemetry.counter(m.HOT_TIER_REPAIR_OBJECTS).value == (
            c_obj + 1
        )
        assert telemetry.counter(m.HOT_TIER_REPAIR_BYTES).value == (
            c_bytes + 8192
        )
        # Kill both ORIGINAL surviving replicas: the restore can only
        # be served by the replica repair placed.
        ht_tier.kill_host(0)
        ht_tier.kill_host(2)
        target = _target()
        snap.restore(target)
        _assert_restored(target, 7.0)
        rt = hottier.runtime()
        assert rt.stats_snapshot()["hot_objects"] >= 1  # not a fallback
        hottier.drain_now()
    # The ledger carries the repair event record for this root.
    records, _ = runledger.read_records(path)
    repairs = [r for r in records if r.get("kind") == "repair"]
    assert repairs and repairs[-1]["objects_repaired"] == 1
    assert repairs[-1]["bytes_repaired"] == 8192
    assert repairs[-1]["underreplicated_bytes"] == 0


def test_sigkill_subprocess_auto_restart_gen_up_and_hot_reload(
    _fresh_mend, monkeypatch
):
    """A real SIGKILLed spawned peer: one tick classifies it lost,
    respawns a FRESH subprocess one membership generation up,
    hot-reloads TPUSNAPSHOT_HOT_TIER_ADDRS and the port-file in place,
    and re-replicates the committed object onto the empty newcomer —
    replica count returns to k with no process restart anywhere."""
    port_file = tempfile.mktemp(prefix="mend-peer-", suffix=".addr")
    proc, addr, _peer = spawn_peer(
        host_id=1, capacity_bytes=1 << 26, port_file=port_file
    )
    monkeypatch.setenv("TPUSNAPSHOT_HOT_TIER_ADDRS", f"1={addr}")
    path = "memory://mend-respawn/run/step_0"
    with hottier.hot_tier(
        rank=0, world=2, k=2, drain="manual", repair="manual"
    ):
        Snapshot.take(path, _state(3.0))
        key = path + "/0/s/w"
        assert ht_tier.live_replicas(key) == [0, 1]
        proc.kill()  # raw SIGKILL behind the tier's back
        proc.wait()
        summary = hottier.repair_tick()
        assert summary["hosts_lost"] == [1]
        assert summary["peer_restarts"] == 1
        new_peer = ht_tier.remote_host(1)
        assert new_peer.generation == 1
        assert ht_tier.host_generation(1) == 1
        assert new_peer.probe()
        # Address book + port-file follow the host across generations.
        assert (
            os.environ["TPUSNAPSHOT_HOT_TIER_ADDRS"]
            == f"1={new_peer.addr_str}"
        )
        with open(port_file) as f:
            assert f.read().strip() == new_peer.addr_str
        # The SAME tick already repaired onto the fresh (empty) peer.
        assert ht_tier.live_replicas(key) == [0, 1]
        q = new_peer.query(key)
        assert q is not None and q["nbytes"] == 2048 * 4
        hottier.drain_now()
    try:
        os.unlink(port_file)
    except OSError:
        pass


def test_background_repair_heals_without_manual_ticks(_fresh_mend):
    """repair="background": the daemon loop alone (no manual ticks)
    detects a host loss and restores k within a few intervals."""
    for h in (1, 2, 3):
        _local_peer(_fresh_mend, h)
    path = "memory://mend-bg/run/step_0"
    with hottier.hot_tier(
        rank=0, world=4, k=3, drain="manual", repair="background"
    ):
        Snapshot.take(path, _state(9.0))
        key = path + "/0/s/w"
        ht_tier.kill_host(2)
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            if len(ht_tier.live_replicas(key)) >= 3:
                break
            time.sleep(0.1)
        assert ht_tier.live_replicas(key) == [0, 1, 3]
        hottier.drain_now()


# ------------------------------------------------- hung, not dead (SIGSTOP)


def test_sigstop_peer_lost_after_deadline_stale_gen_refused(
    _fresh_mend, monkeypatch
):
    """The hung-not-dead peer: SIGSTOP'd, its process never exits but
    its probes fail. Past TPUSNAPSHOT_REPAIR_DEADLINE_S it is
    classified LOST (condemned — never signalled), its objects
    re-replicate elsewhere, and when its replacement has taken the id
    one generation up, the SIGCONT'd predecessor is refused: a probe
    stamped with the CURRENT generation rejects the stale server, the
    shadow occupancy counts the host once, and the restore never sees
    stale bytes."""
    monkeypatch.setenv("TPUSNAPSHOT_REPLICATION_DEADLINE_S", "0.5")
    monkeypatch.setenv("TPUSNAPSHOT_REPAIR_DEADLINE_S", "0.6")
    proc, addr, _peer = spawn_peer(host_id=1, capacity_bytes=1 << 26)
    _local_peer(_fresh_mend, 2)
    path = "memory://mend-stop/run/step_0"
    with hottier.hot_tier(
        rank=0, world=3, k=2, drain="manual", repair="manual"
    ):
        snap = Snapshot.take(path, _state(11.0))
        key = path + "/0/s/w"
        assert ht_tier.live_replicas(key) == [0, 1]
        proc.send_signal(signal.SIGSTOP)  # hung: alive but silent
        t0 = time.monotonic()
        first = hottier.repair_tick()  # probe fails; deadline clock arms
        assert first["hosts_lost"] == []  # not lost yet — only failing
        assert proc.poll() is None
        time.sleep(max(0.0, 0.7 - (time.monotonic() - t0)))
        second = hottier.repair_tick()  # past the deadline: LOST
        assert second["hosts_lost"] == [1]
        assert proc.poll() is None  # never signalled — only condemned
        # Same tick: respawned one generation up AND repaired to k.
        assert ht_tier.host_generation(1) == 1
        assert len(ht_tier.live_replicas(key)) >= 2
        # Wake the stale predecessor: its generation-0 server must be
        # refused by a current-generation probe.
        proc.send_signal(signal.SIGCONT)
        time.sleep(0.1)
        stale_probe = transport.RemotePeer(1, addr, generation=1)
        assert stale_probe.probe() is False  # stale gen refused
        accepts_own = transport.RemotePeer(1, addr, generation=0)
        assert accepts_own.probe() is True  # ...and it IS the gen gate
        stale_probe.close()
        accepts_own.close()
        # No double-count: host 1's occupancy reflects only the
        # current generation's shadow (one object).
        occ = ht_tier.host_occupancy()[1]
        assert occ["objects"] == 1 and occ["alive"]
        target = _target()
        snap.restore(target)
        _assert_restored(target, 11.0)
        hottier.drain_now()
    proc.kill()
    proc.wait()


# --------------------------------------------------------- flap_host churn


def test_flap_host_deterministic_churn_then_repair(_fresh_mend):
    """faultline's flap_host: the wire-backed peer is really SIGKILLed
    at the matched replicate boundary and rejoins two boundaries later
    as a FRESH subprocess one generation up; the repair tick then
    restores k and the restore is bit-exact."""
    proc, _addr, _peer = spawn_peer(host_id=1, capacity_bytes=1 << 26)
    sched = fl.FaultSchedule().flap_host(
        1, revive_after_ops=2, op="hottier.replicate"
    )
    path = "memory://mend-flap/run/step_0"
    with hottier.hot_tier(
        rank=0, world=3, k=2, drain="manual", repair="manual"
    ):
        with fl.inject(sched) as ctl:
            snap = Snapshot.take(path, _state(4.0))
        counts = ctl.fault_counts()
        assert counts.get("flap") == 1
        assert counts.get("revive") == 1
        # The revive record carries the boundary the revival took
        # effect at: exactly revive_after_ops past the loss.
        by_kind = {r.kind: r for r in ctl.records}
        assert (
            by_kind["revive"].op_index == by_kind["flap"].op_index + 2
        )
        assert proc.poll() == -9  # the loss was a REAL SIGKILL
        new_peer = ht_tier.remote_host(1)
        assert new_peer.generation == 1 and new_peer.probe()
        summary = hottier.repair_tick()
        assert summary["underreplicated_objects"] == 0
        key = path + "/0/s/w"
        assert len(ht_tier.live_replicas(key)) >= 2
        ht_tier.kill_host(0)  # force the read onto the churned fleet
        target = _target()
        snap.restore(target)
        _assert_restored(target, 4.0)
        hottier.drain_now()


# --------------------------------------------- repair × crash-point matrix


def _repair_matrix_point(servers, nth):
    """One matrix cell: SimulatedCrash at the nth hottier.repair
    placement boundary; afterwards a clean tick converges back to k,
    the restore is bit-exact, and tier-down retires every obligation."""
    for h in (1, 2, 3):
        _local_peer(servers, h)
    path = f"memory://mend-matrix/run/step_{nth}"
    state = {
        "a": StateDict(x=jnp.full((512,), 1.0 + nth, dtype=jnp.float32)),
        "b": StateDict(y=jnp.full((512,), 2.0 + nth, dtype=jnp.float32)),
    }
    with hottier.hot_tier(
        rank=0, world=4, k=3, drain="manual", repair="manual"
    ):
        snap = Snapshot.take(path, state)
        ht_tier.kill_host(1)  # two objects drop to k-1
        sched = fl.FaultSchedule().crash_on(op="hottier.repair", nth=nth)
        with fl.inject(sched) as ctl:
            with pytest.raises(fl.SimulatedCrash):
                hottier.repair_tick()
        assert ctl.fault_counts().get("crash") == 1
        # The next (un-crashed) tick converges from whatever the crash
        # left behind.
        summary = hottier.repair_tick()
        assert summary["underreplicated_objects"] == 0
        for leaf in ("a/x", "b/y"):
            key = f"{path}/0/{leaf}"
            assert len(ht_tier.live_replicas(key)) >= 3, leaf
        target = {
            "a": StateDict(x=jnp.zeros((512,), dtype=jnp.float32)),
            "b": StateDict(y=jnp.zeros((512,), dtype=jnp.float32)),
        }
        snap.restore(target)
        np.testing.assert_array_equal(np.asarray(target["a"]["x"]), 1.0 + nth)
        np.testing.assert_array_equal(np.asarray(target["b"]["y"]), 2.0 + nth)
        hottier.drain_now()
        assert hottier.wait_drained(timeout_s=30.0)


@pytest.mark.parametrize("nth", [1])
def test_repair_crash_matrix_stride(_fresh_mend, nth):
    """Fast stride subset of the repair × crash-point matrix (2
    under-replicated objects × 1 placement each = 2 repair boundaries;
    the full enumeration runs under -m slow)."""
    _repair_matrix_point(_fresh_mend, nth)


@pytest.mark.slow
@pytest.mark.parametrize("nth", [2])
def test_repair_crash_matrix_full(_fresh_mend, nth):
    _repair_matrix_point(_fresh_mend, nth)


def test_no_crash_point_resurrects_forgotten_root(_fresh_mend):
    """forget-root latch across a crashed repair: a root deleted after
    a crash mid-repair is never resurrected — later ticks skip it and
    every replica (including any the crashed tick placed) is gone."""
    for h in (1, 2, 3):
        _local_peer(_fresh_mend, h)
    path = "memory://mend-forget/run/step_0"
    with hottier.hot_tier(
        rank=0, world=4, k=3, drain="manual", repair="manual"
    ):
        Snapshot.take(path, _state(6.0))
        key = path + "/0/s/w"
        ht_tier.kill_host(1)
        sched = fl.FaultSchedule().crash_on(op="hottier.repair", nth=1)
        with fl.inject(sched):
            with pytest.raises(fl.SimulatedCrash):
                hottier.repair_tick()
        hottier.forget_root(path)  # the snapshot is deleted mid-story
        summary = hottier.repair_tick()
        assert summary["objects_repaired"] == 0
        assert summary["underreplicated_objects"] == 0
        assert ht_tier.live_replicas(key) == []
        assert path not in hottier.buffered_roots()


def test_superseded_tag_never_repaired(_fresh_mend):
    """tag-strict: the under-replication count and the repair source
    are judged against the path's CURRENT tag only. A surviving stale
    replica neither counts toward k nor ever propagates; when current
    bytes DO survive, repair replicates those — replacing the stale
    replica, never multiplying it."""
    _local_peer(_fresh_mend, 1)
    _local_peer(_fresh_mend, 2)
    path = "memory://mend-stale-tag/run/step_0"
    with hottier.hot_tier(
        rank=0, world=3, k=2, drain="manual", repair="manual"
    ):
        Snapshot.take(path, _state(5.0))
        key = path + "/0/s/w"
        rt = hottier.runtime()
        stale_tag = ht_tier.key_tag(key)
        # Model the re-write race: the path's current bytes move on
        # (the foreground re-put lands on host 0) while host 1 still
        # holds the superseded replica.
        new = np.full((2048,), 50.0, dtype=np.float32).tobytes()
        new_tag = ht_tier.payload_tag(new)
        assert ht_tier.put_replica(key, 0, new, new_tag, path)
        with rt._cond:
            rt._roots[path.rstrip("/")].tags["0/s/w"] = new_tag
        # Phase 1: current bytes survive on host 0 only. Repair must
        # source from THEM — the stale host-1 replica is replaced by
        # current bytes, not kept, and never chosen as a source.
        summary = hottier.repair_tick()
        assert summary["objects_repaired"] == 1
        assert sorted(ht_tier.live_replicas(key, new_tag))[:1] == [0]
        assert len(ht_tier.live_replicas(key, new_tag)) >= 2
        assert ht_tier.live_replicas(key, stale_tag) == []
        # Phase 2: make a stale replica the ONLY survivor. Repair must
        # skip the object entirely (the drain loop owns the loss
        # verdict) — superseded bytes are never re-replicated.
        stale = np.full((2048,), 5.0, dtype=np.float32).tobytes()
        assert ht_tier.put_replica(key, 2, stale, stale_tag, path)
        ht_tier.kill_host(0)
        ht_tier.kill_host(1)
        summary = hottier.repair_tick()
        assert summary["objects_repaired"] == 0
        assert summary["underreplicated_objects"] == 1
        assert ht_tier.live_replicas(key, stale_tag) == [2]  # not grown
        assert ht_tier.live_replicas(key, new_tag) == []
        hottier.reset_pending()


def test_corrupt_source_replica_never_repaired(_fresh_mend):
    """A bit-rotted survivor is not a repair source: the fingerprint
    gate drops it, the repair is counted failed, and no host receives
    the corrupt bytes."""
    fails = telemetry.counter(m.HOT_TIER_REPAIRS_FAILED).value
    path = "memory://mend-corrupt/run/step_0"
    with hottier.hot_tier(
        rank=0, world=3, k=2, drain="manual", repair="manual"
    ):
        Snapshot.take(path, _state(1.0))  # in-process hosts 0 and 1
        key = path + "/0/s/w"
        obj = ht_tier._HOSTS[1].objects[key]
        obj.data = b"\x00" * len(obj.data)  # rot host 1's bytes
        ht_tier.kill_host(0)  # the corrupt replica is the only claim
        summary = hottier.repair_tick()
        assert summary["objects_repaired"] == 0
        assert summary["repairs_failed"] == 1
        assert telemetry.counter(m.HOT_TIER_REPAIRS_FAILED).value == (
            fails + 1
        )
        assert ht_tier.live_replicas(key) == []  # dropped, not spread
        hottier.reset_pending()


def test_corrupt_source_among_survivors_reaches_k_in_one_tick(_fresh_mend):
    """A host whose replica the source scan disproved (corrupt,
    dropped) must not count toward k: the placement loop refills to k
    in THIS tick instead of stopping one replica short and waiting
    another interval."""
    for h in (1, 2, 3):
        _local_peer(_fresh_mend, h)
    path = "memory://mend-corrupt-among/run/step_0"
    with hottier.hot_tier(
        rank=0, world=4, k=3, drain="manual", repair="manual"
    ):
        snap = Snapshot.take(path, _state(6.0))
        key = path + "/0/s/w"
        assert ht_tier.live_replicas(key) == [0, 1, 2]
        obj = ht_tier._HOSTS[0].objects[key]
        obj.data = b"\x00" * len(obj.data)  # rot the LOCAL replica
        ht_tier.kill_host(1)  # drop to k-1 so the repair pass runs
        summary = hottier.repair_tick()
        assert summary["objects_repaired"] == 1
        assert summary["underreplicated_objects"] == 0
        live = ht_tier.live_replicas(key)
        assert len(live) == 3 and 1 not in live
        target = _target()
        snap.restore(target)
        _assert_restored(target, 6.0)
        hottier.drain_now()


# ----------------------------------------- escalation & the critical rule


def test_total_replica_loss_escalates_to_loss_verdict(
    _fresh_mend, monkeypatch
):
    """An object with ZERO surviving replicas is the worst state —
    unrecoverable committed bytes — and must not be the one state the
    repair pass silently skips: pre-deadline it counts a failed repair
    per tick, past the deadline it escalates (so the critical rule can
    fire), and after the cross-tick phantom-loss debounce the drain's
    loss verdict is made official (pending retired, drain_lost
    counted) instead of pinning an under-replicated object forever."""
    monkeypatch.setenv("TPUSNAPSHOT_REPAIR_DEADLINE_S", "0.05")
    _local_peer(_fresh_mend, 1)
    path = "memory://mend-allgone/run/step_0"
    with hottier.hot_tier(
        rank=0, world=2, k=2, drain="manual", repair="manual"
    ):
        Snapshot.take(path, _state(4.0))
        key = path + "/0/s/w"
        assert ht_tier.live_replicas(key) == [0, 1]
        ht_tier.kill_host(0)
        ht_tier.kill_host(1)
        assert ht_tier.live_replicas(key) == []
        first = hottier.repair_tick()  # arms the clock; no source
        assert first["underreplicated_objects"] == 1
        assert first["repairs_failed"] == 1
        time.sleep(0.25)  # past the interval AND the deadline
        deferred = hottier.repair_tick()
        # A deferral is an escalation ATTEMPT, not a write-through:
        # nothing durable ran, so the executed count must stay 0.
        assert deferred["escalation_attempts"] == 1
        assert deferred["escalated_write_throughs"] == 0
        assert deferred["underreplicated_objects"] == 1
        # While the verdict is pending, the live rule goes critical.
        sev = {
            f.rule: f.severity
            for f in slo_mod.evaluate_live(
                [{"hot_tier": hottier.introspect()}]
            )
            if f.rule == "replication-underreplicated"
        }
        assert sev == {"replication-underreplicated": "critical"}
        hottier.repair_tick()  # second consecutive no-source tick
        lost0 = hottier.runtime().stats_snapshot()["drain_lost"]
        final = hottier.repair_tick()  # third: the verdict is official
        assert final["underreplicated_objects"] == 0
        assert (
            hottier.runtime().stats_snapshot()["drain_lost"] == lost0 + 1
        )
        hottier.reset_pending()


class _StubChurnPeer:
    """A duck-typed 'spawned' wire peer for churn bookkeeping tests."""

    def __init__(self) -> None:
        self.generation = 0
        self.alive = True
        self.process = object()  # non-None: restartable/spawned
        self.killed = False

    def condemn(self) -> None:
        self.alive = False

    def kill(self) -> None:
        self.killed = True
        self.alive = False

    def close(self) -> None:
        pass


def test_condemned_peer_handles_bounded_under_churn(_fresh_mend):
    """Continuous hung-peer churn must not accumulate condemned
    subprocess handles (each a hung process pinning its replica RAM)
    for the life of the run: beyond _MAX_CONDEMNED the oldest are
    reaped eagerly, the newest kept unsignalled for close()."""
    cap = ht_repair._MAX_CONDEMNED
    with hottier.hot_tier(
        rank=0, world=2, k=2, drain="manual", repair="manual"
    ):
        plane = hottier.repair_plane()
        stubs = []
        for i in range(30, 30 + cap + 4):
            stub = _StubChurnPeer()
            ht_tier.register_remote_host(i, stub)
            view = ht_repair._HostView(i, stub)
            plane._declare_lost(i, stub, view, reason="test churn")
            stubs.append(stub)
        with plane._lock:
            assert len(plane._condemned) == cap
        assert [s.killed for s in stubs] == [True] * 4 + [False] * cap


def test_respawn_host_idempotent_returns_live_replacement(_fresh_mend):
    """Two racing respawns of one lost host (faultline flap revival vs
    the background plane's _restart) must produce ONE replacement: the
    second caller gets the first's live peer back instead of spawning
    a second subprocess whose handle would leak untracked."""
    _proc, _addr, _peer = spawn_peer(host_id=1, capacity_bytes=1 << 26)
    ht_tier.kill_host(1)
    first = ht_repair.respawn_host(1)
    assert first is not None and first.generation == 1
    again = ht_repair.respawn_host(1)  # the "racing" second caller
    assert again is first  # no second spawn, no generation bump
    assert ht_tier.host_generation(1) == 1
    first.kill()


def test_condemn_only_if_spares_midtick_replacement(_fresh_mend):
    """A replacement registered over a host id after the supervisor
    judged its predecessor must NOT be condemned on the stale verdict:
    the only_if identity pin makes the condemn a no-op for the fresh
    peer."""
    judged = _StubChurnPeer()
    ht_tier.register_remote_host(7, judged)
    replacement = _StubChurnPeer()
    replacement.generation = 1
    ht_tier.register_remote_host(7, replacement)  # took the id over
    ht_tier.condemn_host(7, only_if=judged)  # stale verdict lands late
    assert replacement.alive  # the fresh peer was spared
    ht_tier.condemn_host(7, only_if=replacement)  # a CURRENT verdict...
    assert not replacement.alive  # ...still condemns


def test_condemn_host_spares_replacement_shadow_entries(_fresh_mend):
    """The narrower race: the only_if identity check passes, and the
    replacement registers (and receives a replica) while the judged
    predecessor is being condemned OUTSIDE the tier lock. The final
    shadow clear must re-check the registered identity — wiping the
    host's shadow then would erase the REPLACEMENT's replica credit
    (live_replicas stops counting a replica that really exists)."""
    root = "memory://mend-shadow-race/run/step_0"
    key = root + "/0/s/w"
    data = b"fresh replica bytes" * 8
    tag = ht_tier.payload_tag(data)
    judged = _StubChurnPeer()
    ht_tier.register_remote_host(7, judged)

    def _condemn_then_get_replaced():
        judged.alive = False
        # A respawn takes the id over and receives a fresh replica
        # before the condemner reacquires the tier lock.
        _local_peer(_fresh_mend, 7)
        assert ht_tier.put_replica(key, 7, data, tag, root)

    judged.condemn = _condemn_then_get_replaced
    ht_tier.condemn_host(7, only_if=judged)
    assert ht_tier.live_replicas(key, tag) == [7]


def test_probe_adopts_newer_server_generation(_fresh_mend):
    """A client rebuilt from the generation-less address book /
    port-file (generation 0) must ADOPT a respawned server's higher
    generation on first contact — the stale side is the client's view,
    not the server — while a LOWER server generation (the SIGCONT'd
    stale predecessor) stays refused."""
    server, _ = start_local_peer(5, register=False, generation=2)
    _fresh_mend.append(server)
    rebuilt = transport.RemotePeer(5, server.addr, generation=0)
    ht_tier.register_remote_host(5, rebuilt)
    assert rebuilt.probe() is True
    assert rebuilt.generation == 2  # adopted, not refused
    assert ht_tier.host_generation(5) == 2  # membership view synced
    # The gate still refuses the other direction: a server BELOW the
    # client's generation is a stale predecessor.
    stale_view = transport.RemotePeer(5, server.addr, generation=3)
    assert stale_view.probe() is False
    stale_view.close()


def test_supervise_prunes_unregistered_host_views(_fresh_mend):
    """A host that was UNREGISTERED (not condemned — condemned hosts
    stay registered by design) must leave the membership view: a stale
    _HostView would report a nonexistent host forever and feed
    _restart an unrespawnable candidate every tick."""
    _local_peer(_fresh_mend, 1)
    _local_peer(_fresh_mend, 2)
    with hottier.hot_tier(
        rank=0, world=3, k=2, drain="manual", repair="manual"
    ):
        hottier.repair_tick()
        member_ids = set(hottier.introspect()["repair"]["membership"])
        assert {"1", "2"} <= member_ids
        ht_tier.unregister_remote_host(2)
        hottier.repair_tick()
        member_ids = set(hottier.introspect()["repair"]["membership"])
        assert "2" not in member_ids
        assert "1" in member_ids


def test_condemned_peer_kill_still_reaps_subprocess(_fresh_mend):
    """condemn() latches the peer dead WITHOUT signalling; a later
    kill() — the condemned-cap reap, RepairPlane.close(), or
    reset_hot_tier — must still SIGKILL the subprocess. An early
    return on the shared latch would leave every condemned hung peer
    alive past every reap, pinning its replica RAM for the run."""
    proc, _addr, peer = spawn_peer(host_id=1, capacity_bytes=1 << 26)
    proc.send_signal(signal.SIGSTOP)  # hung, not dead
    ht_tier.condemn_host(1)
    assert proc.poll() is None  # condemn never signals...
    peer.kill()  # ...but the reap still must
    assert proc.wait(timeout=10) == -9


def test_deadline_escalation_write_through_and_critical_rule(
    _fresh_mend, monkeypatch, tmp_path
):
    """Past TPUSNAPSHOT_REPAIR_DEADLINE_S with no spare host, the
    repair deterministically escalates to the synchronous durable
    write-through. While the escalation is stalled (durable backend
    faulted), replication-underreplicated fires CRITICAL and the ops
    CLI exits 1; once the escalation lands, the object is durable, the
    gauge returns to 0, and the finding clears."""
    monkeypatch.setenv("TPUSNAPSHOT_REPAIR_DEADLINE_S", "0.05")
    _local_peer(_fresh_mend, 1)
    path = "memory://mend-esc/run/step_0"
    esc0 = telemetry.counter(m.HOT_TIER_REPAIR_ESCALATIONS).value
    with hottier.hot_tier(
        rank=0, world=2, k=2, drain="manual", repair="manual"
    ):
        Snapshot.take(path, _state(8.0))
        ht_tier.kill_host(1)  # world=2: no spare — k is unreachable
        first = hottier.repair_tick()  # observes under-k: clock arms
        assert first["underreplicated_objects"] == 1
        assert first["escalated_write_throughs"] == 0
        time.sleep(0.3)  # age past the interval AND the deadline
        # Stall the escalation: the durable write faults permanently.
        sched = fl.FaultSchedule().permanent(op="write", path="0/s/w")
        with fl.inject(sched):
            stalled = hottier.repair_tick()
        assert stalled["escalated_write_throughs"] == 1
        assert stalled["underreplicated_objects"] == 1
        assert telemetry.counter(
            m.HOT_TIER_REPAIR_ESCALATIONS
        ).value == esc0 + 1
        # The live rule sees the stall as critical...
        sample = {"hot_tier": hottier.introspect()}
        findings = slo_mod.evaluate_live([sample])
        crit = {
            f.rule: f.severity
            for f in findings
            if f.rule == "replication-underreplicated"
        }
        assert crit == {"replication-underreplicated": "critical"}
        # ...and drives the ops CLI's exit-code contract. (An empty
        # dir is a valid statusfile root; the live in-process runtime
        # is folded in.)
        live_dir = str(tmp_path / "liveops")
        os.makedirs(live_dir, exist_ok=True)
        state = ops_cli.collect(live_dir)
        ops_findings = ops_cli.findings_of(state)
        assert any(
            f.rule == "replication-underreplicated"
            and f.severity == "critical"
            for f in ops_findings
        )
        rendered = ops_cli.render(state, stale_after_s=60.0)
        assert "repair[manual]:" in rendered
        assert "membership:" in rendered and "(LOST)" in rendered
        assert ops_cli.main([live_dir]) == 1
        # Un-stall: the next escalation retires the obligation.
        healed = hottier.repair_tick()
        assert healed["escalated_write_throughs"] == 1
        assert healed["underreplicated_objects"] == 0
        assert (
            telemetry.gauge(m.HOT_TIER_UNDERREPLICATED_BYTES).value == 0.0
        )
        after = slo_mod.evaluate_live([{"hot_tier": hottier.introspect()}])
        assert not any(
            f.rule == "replication-underreplicated" for f in after
        )
        hottier.drain_now()
    # The escalated object is durable: restorable with the tier off.
    hottier.reset_hot_tier()
    target = _target()
    Snapshot(path).restore(target)
    _assert_restored(target, 8.0)


# ------------------------------------------------- down-cooldown re-probe


def test_repair_tick_reprobes_peer_out_of_down_cooldown(
    _fresh_mend, monkeypatch
):
    """satellite: a peer latched down by the cooldown used to rejoin
    only when a foreground push tripped over it; the repair tick's
    background re-probe clears the latch within one tick."""
    monkeypatch.setenv("TPUSNAPSHOT_REPLICATION_RETRY_BUDGET_S", "0.5")
    monkeypatch.setenv("TPUSNAPSHOT_REPLICATION_DOWN_COOLDOWN_S", "60")
    peer = _local_peer(_fresh_mend, 1)
    root = "memory://mend-cooldown/run/step_0"
    data = b"c" * 4096
    with hottier.hot_tier(
        rank=0, world=2, k=2, drain="manual", repair="manual"
    ):
        # Exhaust one push's retry budget with scripted drops: the
        # peer latches into its 60s down cooldown.
        for _ in range(64):
            transport.script_wire_fault("drop_conn", host=1)
        with pytest.raises(ht_tier.HostLostError):
            ht_tier.put_replica(
                root + "/a", 1, data, ht_tier.payload_tag(data), root
            )
        transport.clear_wire_faults()
        assert peer.in_cooldown  # healthy peer, latched out anyway
        with pytest.raises(ht_tier.HostLostError):
            ht_tier.put_replica(
                root + "/a", 1, data, ht_tier.payload_tag(data), root
            )
        summary = hottier.repair_tick()  # background re-probe
        assert not peer.in_cooldown
        assert summary["hosts_lost"] == []
        assert ht_tier.put_replica(
            root + "/a", 1, data, ht_tier.payload_tag(data), root
        )
        plane = hottier.repair_plane()
        assert plane.introspect()["stats"]["reprobes"] >= 1
        ht_tier.forget_key(root + "/a")


# ------------------------------------------------------------- introspect


def test_introspect_membership_and_degraded_read_nudge(_fresh_mend):
    """The sampler-facing repair block: per-host generation + liveness
    membership rows, under-replication accounting, and the
    degraded-read nudge wiring (request_scan reaches the plane)."""
    _local_peer(_fresh_mend, 1)
    path = "memory://mend-intro/run/step_0"
    with hottier.hot_tier(
        rank=0, world=2, k=2, drain="manual", repair="manual"
    ):
        Snapshot.take(path, _state(2.0))
        hottier.repair_tick()
        doc = hottier.introspect()
        repair = doc["repair"]
        assert repair["mode"] == "manual"
        assert repair["underreplicated_objects"] == 0
        row = repair["membership"]["1"]
        assert row["alive"] and row["generation"] == 0
        assert row["current_generation"] == 0
        assert repair["stats"]["hosts_lost"] == 0
        rt = hottier.runtime()
        rt.request_repair_scan()  # no-op wiring must not throw
        hottier.drain_now()
    # With repair off, the block is absent (None), not fabricated.
    with hottier.hot_tier(rank=0, world=1, k=1, drain="manual"):
        assert hottier.introspect()["repair"] is None
