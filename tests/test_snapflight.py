"""snapflight: unified wire observability across the three transports.

What the suite pins:

1. **wiretap core** — the shared recording layer's aggregation,
   outcome clamping, window deltas, and quantile math (the module
   self-test plus focused cases).
2. **Blackbox flight recorder** — fault/degrade dumps land as
   crc-framed ``*.blackbox.jsonl`` statusfiles; a torn final record
   (the dumping process died mid-write) parses as a skip, never an
   error — the ledger's torn-tail discipline.
3. **Faultline** — a REAL SIGKILLed hot-tier peer and snapserve server
   mid-traffic: the surviving client's blackbox dump parses and holds
   the victim's last RPCs with trace ids and outcomes.
4. **Doctor / SLO / ops** — an injected ``slow_wire`` /
   ``slow_fleet_member`` deterministically trips the
   ``deadline-margin-collapsing`` rule (report-mode and live-mode),
   and the ops CLI's fleet wire mode aggregates member sample blocks
   with the documented exit-code contract.
"""

import asyncio
import glob
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from torchsnapshot_tpu import snapserve, tracing, wiretap
from torchsnapshot_tpu.hottier.peer import spawn_peer
from torchsnapshot_tpu.hottier.transport import (
    RemotePeer,
    clear_wire_faults,
    script_wire_fault,
)
from torchsnapshot_tpu.telemetry.doctor import (
    diagnose_report,
    wire_pressure_finding,
)
from torchsnapshot_tpu.telemetry import ops as scope_ops
from torchsnapshot_tpu.telemetry import slo as scope_slo

pytestmark = pytest.mark.faultline


@pytest.fixture(autouse=True)
def _fresh_wiretap():
    wiretap.reset()
    clear_wire_faults()
    yield
    wiretap.reset()
    clear_wire_faults()


# ------------------------------------------------------------ wiretap core


def test_wiretap_module_self_test():
    wiretap._self_test()  # raises on any failed pin


def test_record_aggregates_outcomes_and_margins():
    wiretap.reset()
    wiretap.record("snapwire", "put", seconds=0.010, deadline_s=1.0,
                   bytes_out=4096)
    wiretap.record("snapwire", "put", seconds=0.020, deadline_s=1.0,
                   bytes_out=4096, attempt=1)
    wiretap.record(
        "snapwire", "put", seconds=1.5, deadline_s=1.0,
        outcome="deadline_miss",
    )
    # Unknown outcomes clamp into the bounded taxonomy.
    wiretap.record("snapwire", "put", seconds=0.01, outcome="weird-kind")
    ops = wiretap.summary()
    put = ops["snapwire/put"]
    assert put["count"] == 4
    assert put["deadline_misses"] == 1
    assert put["retries"] == 1
    assert put["bytes_out"] == 8192
    assert put["outcomes"]["ok"] == 2
    assert put["outcomes"]["deadline_miss"] == 1
    assert put["outcomes"]["error"] == 1
    assert "weird-kind" not in put["outcomes"]
    # A miss consumed >= the whole budget: margin clamps at >= 1.0.
    assert put["margin_max"] >= 1.0


def test_window_collect_is_a_delta_not_a_total():
    wiretap.reset()
    wiretap.record("snapserve", "read", seconds=0.01, deadline_s=10.0)
    token = wiretap.window_begin()
    wiretap.record("snapserve", "read", seconds=0.03, deadline_s=10.0)
    wiretap.record("snapserve", "read", seconds=0.05, deadline_s=10.0)
    window = wiretap.window_collect(token)
    assert window["snapserve/read"]["count"] == 2  # not 3
    assert wiretap.summary()["snapserve/read"]["count"] == 3


# --------------------------------------------------------------- blackbox


def test_blackbox_dump_parses_and_skips_torn_tail(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_WIRETAP_DIR", str(tmp_path))
    wiretap.reset()
    for i in range(5):
        wiretap.record(
            "snapwire", "put", seconds=0.01 * (i + 1), deadline_s=2.0,
            trace_id=f"take-{i:012x}",
        )
    path = wiretap.dump_blackbox("fault")
    assert path and os.path.exists(path)
    records, skipped = wiretap.read_blackbox(path)
    assert skipped == 0
    assert records[0]["kind"] == "blackbox_header"
    assert records[0]["reason"] == "fault"
    events = [r for r in records if "op" in r]
    assert len(events) == 5
    assert events[-1]["trace"] == "take-000000000004"
    # Torn tail: chop into the final record — the crc discipline skips
    # exactly the truncated piece and keeps everything before it.
    raw = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(raw[:-9])
    records2, skipped2 = wiretap.read_blackbox(path)
    assert skipped2 == 1
    assert len(records2) == len(records) - 1


def test_note_degrade_dumps_with_mark(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_WIRETAP_DIR", str(tmp_path))
    wiretap.reset()
    wiretap.record("snapwire", "get", seconds=0.02, deadline_s=2.0)
    wiretap.note_degrade("peer_down", peer="127.0.0.1:9")
    files = glob.glob(str(tmp_path / "*.blackbox.jsonl"))
    assert len(files) == 1
    records, skipped = wiretap.read_blackbox(files[0])
    assert skipped == 0
    marks = [r for r in records if "mark" in r]
    assert marks and marks[0]["mark"] == "peer_down"
    assert marks[0]["peer"] == "127.0.0.1:9"


# ------------------------------------------------- faultline: SIGKILL'd peers


def test_sigkilled_peer_leaves_survivor_blackbox_with_trace_join(
    tmp_path, monkeypatch
):
    """SIGKILL a real hot-tier peer subprocess mid-traffic: the
    SURVIVING client process's degrade hook dumps its flight recorder,
    and the dump holds the victim's last RPCs — ops, outcomes, and the
    take's trace id (snapxray-joinable)."""
    monkeypatch.setenv("TPUSNAPSHOT_WIRETAP_DIR", str(tmp_path))
    monkeypatch.setenv("TPUSNAPSHOT_REPLICATION_RETRY_BUDGET_S", "0.5")
    wiretap.reset()
    proc, addr, _none = spawn_peer(host_id=7, register=False)
    peer = RemotePeer(host_id=7, addr=addr)
    try:
        from torchsnapshot_tpu.fingerprint import fingerprint_host

        payload = b"z" * 2048
        tag = fingerprint_host(payload)
        with tracing.trace_scope("take") as trace_id:
            for i in range(3):
                stored, _ = peer.put(f"k{i}", payload, tag=tag,
                                     root="memory://flight/run")
                assert stored
            proc.kill()
            proc.wait(timeout=10.0)
            assert proc.poll() == -signal.SIGKILL
            from torchsnapshot_tpu.hottier.tier import HostLostError

            with pytest.raises(HostLostError):
                peer.put("k-dead", payload, tag=tag,
                         root="memory://flight/run")
    finally:
        peer.close()
        if proc.poll() is None:
            proc.kill()
    files = glob.glob(str(tmp_path / "*.blackbox.jsonl"))
    assert files, "survivor produced no blackbox dump"
    events = []
    marks = []
    for f in files:
        records, _skipped = wiretap.read_blackbox(f)
        events += [r for r in records if "op" in r]
        marks += [r for r in records if "mark" in r]
    assert any(m["mark"] == "peer_down" for m in marks)
    puts = [e for e in events if e["op"] == "put"]
    assert any(e["outcome"] == "ok" and e["trace"] == trace_id
               for e in puts), puts
    # The victim's death is in the record stream too: the failed RPC
    # attempts against the dead socket, under the same trace id.
    assert any(e["outcome"] in ("transport", "deadline_miss")
               and e["trace"] == trace_id for e in puts), puts


def test_sigkilled_snapserve_server_marks_survivor_blackbox(
    tmp_path, monkeypatch
):
    """Same discipline on the read plane: kill a real snapserve server
    subprocess mid-traffic and the surviving client dumps a blackbox
    whose tail holds the ok RPCs before the kill and the failure
    after it."""
    monkeypatch.setenv("TPUSNAPSHOT_WIRETAP_DIR", str(tmp_path))
    wiretap.reset()
    port_file = str(tmp_path / "server.addr")
    proc = subprocess.Popen(
        [sys.executable, "-m", "torchsnapshot_tpu.snapserve.server",
         "--addr", "127.0.0.1:0", "--port-file", port_file],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        deadline = time.monotonic() + 30.0
        addr = None
        while time.monotonic() < deadline:
            if os.path.exists(port_file):
                addr = open(port_file).read().strip()
                if addr:
                    break
            if proc.poll() is not None:
                pytest.fail("snapserve server subprocess died at startup")
            time.sleep(0.05)
        assert addr, "server never wrote its port file"
        assert snapserve.ping_server(addr, timeout_s=10.0)["ok"] is True
        proc.kill()
        proc.wait(timeout=10.0)
        with pytest.raises(Exception):
            snapserve.ping_server(addr, timeout_s=2.0)
        wiretap.note_degrade("server_down", peer=addr)
    finally:
        if proc.poll() is None:
            proc.kill()
    files = glob.glob(str(tmp_path / "*.blackbox.jsonl"))
    assert files
    events = [
        r
        for f in files
        for r in wiretap.read_blackbox(f)[0]
        if "op" in r and r.get("transport") == "snapserve"
    ]
    assert any(e["op"] == "ping" and e["outcome"] == "ok" for e in events)
    assert any(e["op"] == "ping" and e["outcome"] != "ok" for e in events)


# ----------------------------------- doctor / slo: deadline-margin-collapsing


def test_slow_wire_trips_deadline_margin_collapsing(monkeypatch):
    """Acceptance: an injected ``slow_wire`` fault deterministically
    trips the doctor rule — the scripted sleep blows the (tightened)
    per-RPC deadline, the retry lands, and the wiretap window carries
    the miss into the report's ``wire`` block."""
    monkeypatch.setenv("TPUSNAPSHOT_REPLICATION_DEADLINE_S", "0.2")
    monkeypatch.setenv("TPUSNAPSHOT_REPLICATION_RETRY_BUDGET_S", "10")
    wiretap.reset()
    from torchsnapshot_tpu.hottier.peer import start_local_peer

    server, _ = start_local_peer(host_id=11, register=False)
    peer = RemotePeer(host_id=11, addr=server.addr)
    token = wiretap.window_begin()
    try:
        from torchsnapshot_tpu.fingerprint import fingerprint_host

        payload = b"w" * 512
        tag = fingerprint_host(payload)
        script_wire_fault("slow_wire", host=11, seconds=0.6)
        stored, _ = peer.put("k", payload, tag=tag,
                             root="memory://slowwire/run")
        assert stored  # the retry after the miss succeeded
    finally:
        peer.close()
        server.stop()
    window = wiretap.window_collect(token)
    put = window["snapwire/put"]
    assert put["deadline_misses"] >= 1
    assert put["retries"] >= 1
    report = {"kind": "take", "ranks": [{"rank": 0, "wire": window}]}
    findings = [
        f for f in diagnose_report(report)
        if f.rule == "deadline-margin-collapsing"
    ]
    assert findings and findings[0].severity == "critical"
    assert findings[0].evidence["pressured_ops"][0]["op"] == "snapwire/put"
    # Healthy traffic stays silent.
    assert wire_pressure_finding(
        {"snapwire/put": {"count": 10, "deadline_misses": 0,
                          "margin_p99": 0.1}}
    ) is None


def test_margin_only_pressure_warns_not_criticals(monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_WIRE_MARGIN_WARN", "0.5")
    f = wire_pressure_finding(
        {"snapserve/read": {"count": 50, "deadline_misses": 0,
                            "margin_p99": 0.62, "p99_s": 6.2,
                            "deadline_s": 10.0}}
    )
    assert f is not None and f.severity == "warn"
    assert "62%" in f.title


def test_slo_live_rule_scores_window_delta():
    def sample(count, misses):
        return {
            "wire": {
                "ops": {
                    "snapwire/put": {
                        "count": count,
                        "deadline_misses": misses,
                        "retries": 0,
                        "margin_p99": 0.2,
                        "deadline_s": 2.0,
                    }
                }
            }
        }

    stale = scope_slo.evaluate_live([sample(50, 3), sample(60, 3)])
    assert not any(
        f.rule == "deadline-margin-collapsing" for f in stale
    ), stale
    fresh = [
        f
        for f in scope_slo.evaluate_live([sample(50, 3), sample(60, 5)])
        if f.rule == "deadline-margin-collapsing"
    ]
    assert fresh and fresh[0].severity == "critical"
    assert fresh[0].evidence["deadline_misses"] == 2


# ------------------------------------------------------ ops fleet wire mode


def test_ops_fleet_wire_aggregates_and_exit_contract(capsys):
    server = snapserve.start_local_server()
    try:
        snapserve.ping_server(server.addr, timeout_s=10.0)
        rc = scope_ops.main(["--wire", server.addr])
        out = capsys.readouterr().out
        assert rc == 0
        assert "fleet wire:" in out
        assert "snapserve/ping" in out
        # One member down (but not all): critical finding, exit 1.
        rc = scope_ops.main(
            ["--wire", f"{server.addr},127.0.0.1:1", "--wire-timeout", "2"]
        )
        out = capsys.readouterr().out
        assert rc == 1
        assert "fleet-member-unreachable" in out
    finally:
        server.stop()
    # Every target unreachable: the view itself is unavailable, exit 2.
    rc = scope_ops.main(["--wire", "127.0.0.1:1", "--wire-timeout", "2"])
    capsys.readouterr()
    assert rc == 2


def test_ops_fleet_wire_json_merges_peer_blocks(capsys):
    from torchsnapshot_tpu.hottier.peer import start_local_peer

    server, _ = start_local_peer(host_id=21, register=False)
    peer = RemotePeer(host_id=21, addr=server.addr)
    try:
        assert peer.probe() is True
        rc = scope_ops.main(
            ["--wire-peers", f"21={server.addr}", "--json"]
        )
        out = capsys.readouterr().out
        assert rc == 0
        doc = json.loads(out)
        assert doc["reachable"] == 1
        assert any(k.startswith("snapwire/") for k in doc["ops"])
    finally:
        peer.close()
        server.stop()
