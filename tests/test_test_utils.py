"""Watch the watchmen: the equality helpers are themselves tested
(reference analog: tests/test_test_utils.py:27-108)."""

import jax
import jax.numpy as jnp
import numpy as np

from torchsnapshot_tpu.utils.test_utils import (
    assert_state_dict_eq,
    check_state_dict_eq,
)


def test_equal_dicts():
    a = {"x": np.arange(4), "y": {"z": jnp.ones(3)}, "s": "str", "n": 5}
    b = {"x": np.arange(4), "y": {"z": jnp.ones(3)}, "s": "str", "n": 5}
    assert check_state_dict_eq(a, b)
    assert_state_dict_eq(a, b)


def test_value_mismatch():
    assert not check_state_dict_eq({"x": np.arange(4)}, {"x": np.arange(1, 5)})


def test_shape_mismatch():
    assert not check_state_dict_eq({"x": np.zeros(3)}, {"x": np.zeros(4)})


def test_dtype_mismatch_exact():
    assert not check_state_dict_eq(
        {"x": np.zeros(3, np.float32)}, {"x": np.zeros(3, np.float64)}
    )


def test_key_mismatch():
    assert not check_state_dict_eq({"x": 1}, {"y": 1})
    assert not check_state_dict_eq({"x": 1}, {"x": 1, "y": 2})


def test_list_and_tuple():
    assert check_state_dict_eq([1, (2, np.ones(2))], [1, (2, np.ones(2))])
    assert not check_state_dict_eq([1, 2], [1, 2, 3])


def test_nan_not_equal_exact():
    assert not check_state_dict_eq(
        {"x": np.array([np.nan])}, {"x": np.array([0.0])}
    )


def test_allclose_mode():
    a = {"x": np.array([1.0])}
    b = {"x": np.array([1.0 + 1e-9])}
    assert not check_state_dict_eq(a, b, exact=True)
    assert check_state_dict_eq(a, b, exact=False)


def test_prng_key_equality():
    a = {"k": jax.random.key(1)}
    b = {"k": jax.random.key(1)}
    c = {"k": jax.random.key(2)}
    assert check_state_dict_eq(a, b)
    assert not check_state_dict_eq(a, c)


def test_mixed_array_and_scalar_not_equal():
    assert not check_state_dict_eq({"x": np.array([1])}, {"x": 1})


def test_statefuls():
    from torchsnapshot_tpu import FnStateful, PytreeStateful

    tree = {"a": np.arange(3), "b": [1, 2]}
    ps = PytreeStateful(tree)
    assert ps.state_dict() is tree
    ps.load_state_dict({"a": np.zeros(3), "b": [0]})
    assert ps.tree["b"] == [0]

    import optax

    opt = optax.adam(1e-3)
    state = opt.init({"w": jnp.ones(3)})
    converted = PytreeStateful(state, convert=True)
    sd = converted.state_dict()
    assert isinstance(sd, dict)
    converted.load_state_dict(sd)
    assert isinstance(converted.tree, tuple)  # NamedTuple structure preserved

    box = {"v": 1}
    fs = FnStateful(lambda: {"v": box["v"]}, lambda sd: box.update(v=sd["v"]))
    assert fs.state_dict() == {"v": 1}
    fs.load_state_dict({"v": 42})
    assert box["v"] == 42
