"""Overlap/subdivision math tests (reference analog:
tests/test_sharded_tensor_io_preparer.py subdivision cases)."""

import numpy as np
import pytest

from torchsnapshot_tpu.resharding import (
    compute_overlap,
    contiguous_byte_range,
    index_to_offsets_sizes,
    subdivide,
)


def test_overlap_disjoint():
    assert compute_overlap([0, 0], [4, 4], [4, 0], [4, 4]) is None
    assert compute_overlap([0, 0], [4, 4], [0, 4], [4, 4]) is None


def test_overlap_identity():
    ov = compute_overlap([0, 0], [4, 4], [0, 0], [4, 4])
    assert ov.chunk_slices == (slice(0, 4), slice(0, 4))
    assert ov.target_slices == (slice(0, 4), slice(0, 4))
    assert ov.sizes == (4, 4)


def test_overlap_partial():
    # chunk rows [2, 6), target rows [4, 8): overlap rows [4, 6).
    ov = compute_overlap([2, 0], [4, 4], [4, 0], [4, 4])
    assert ov.chunk_slices == (slice(2, 4), slice(0, 4))
    assert ov.target_slices == (slice(0, 2), slice(0, 4))
    assert ov.offsets == (4, 0)


def test_overlap_0d():
    ov = compute_overlap([], [], [], [])
    assert ov.chunk_slices == ()
    assert ov.target_slices == ()


def test_overlap_semantics_by_simulation():
    # Random boxes: copying chunk[chunk_slices] -> target[target_slices]
    # must reproduce np slicing semantics exactly.
    rng = np.random.RandomState(0)
    global_arr = rng.rand(16, 12)
    for _ in range(100):
        co = [rng.randint(0, 12), rng.randint(0, 8)]
        cs = [rng.randint(1, 16 - co[0] + 1), rng.randint(1, 12 - co[1] + 1)]
        to = [rng.randint(0, 12), rng.randint(0, 8)]
        ts = [rng.randint(1, 16 - to[0] + 1), rng.randint(1, 12 - to[1] + 1)]
        chunk = global_arr[co[0]:co[0] + cs[0], co[1]:co[1] + cs[1]]
        target = np.zeros(ts)
        ov = compute_overlap(co, cs, to, ts)
        if ov is None:
            continue
        target[ov.target_slices] = chunk[ov.chunk_slices]
        expect = global_arr[to[0]:to[0] + ts[0], to[1]:to[1] + ts[1]]
        mask = np.zeros(ts, dtype=bool)
        mask[ov.target_slices] = True
        np.testing.assert_array_equal(target[mask], expect[mask])


def test_index_to_offsets_sizes():
    off, sz = index_to_offsets_sizes((slice(2, 6), slice(None)), [8, 4])
    assert off == [2, 0]
    assert sz == [4, 4]
    off, sz = index_to_offsets_sizes((), [])
    assert off == []
    assert sz == []
    # Trailing dims not covered by the index are full.
    off, sz = index_to_offsets_sizes((slice(0, 2),), [4, 6])
    assert off == [0, 0]
    assert sz == [2, 6]


def test_subdivide_no_split():
    assert subdivide([0], [10], 4, 1000) == [([0], [10])]


def test_subdivide_even():
    chunks = subdivide([0, 0], [8, 4], itemsize=4, max_chunk_bytes=64)
    # 8*4*4 = 128 bytes -> 2 chunks of 4 rows.
    assert chunks == [([0, 0], [4, 4]), ([4, 0], [4, 4])]


def test_subdivide_uneven_boundary():
    # 7 rows, max 2 rows worth of bytes per chunk: 3+2+2 or similar cover.
    chunks = subdivide([3, 0], [7, 4], itemsize=4, max_chunk_bytes=32)
    total = 0
    pos = 3
    for off, sz in chunks:
        assert off[0] == pos
        assert sz[1] == 4
        pos += sz[0]
        total += sz[0]
    assert total == 7


def test_subdivide_covers_and_respects_cap_various():
    rng = np.random.RandomState(1)
    for _ in range(50):
        sizes = [int(rng.randint(1, 20)), int(rng.randint(1, 20))]
        cap = int(rng.randint(8, 256))
        chunks = subdivide([0, 0], sizes, itemsize=4, max_chunk_bytes=cap)
        seen = np.zeros(sizes, dtype=int)
        for off, sz in chunks:
            seen[off[0]:off[0] + sz[0], off[1]:off[1] + sz[1]] += 1
        assert (seen == 1).all()


def test_subdivide_scalar():
    assert subdivide([], [], 8, 4) == [([], [])]


def test_contiguous_byte_range_full():
    assert contiguous_byte_range([4, 4], (slice(0, 4), slice(0, 4)), 4) == (0, 64)


def test_contiguous_byte_range_rows():
    # Rows [1,3) of a (4,4) chunk: bytes [16, 48) with itemsize 4.
    assert contiguous_byte_range([4, 4], (slice(1, 3), slice(0, 4)), 4) == (16, 48)


def test_contiguous_byte_range_column_not_contiguous():
    assert contiguous_byte_range([4, 4], (slice(0, 4), slice(0, 2)), 4) is None


def test_contiguous_byte_range_single_row_cols():
    # One row, partial cols: contiguous.
    assert contiguous_byte_range([4, 4], (slice(2, 3), slice(1, 3)), 4) == (
        (2 * 4 + 1) * 4,
        (2 * 4 + 3) * 4,
    )


def test_contiguous_byte_range_matches_numpy():
    rng = np.random.RandomState(2)
    for _ in range(200):
        shape = [int(rng.randint(1, 6)) for _ in range(rng.randint(1, 4))]
        arr = np.arange(int(np.prod(shape)), dtype=np.int32).reshape(shape)
        slices = tuple(
            slice(a, a + int(rng.randint(1, s - a + 1)))
            for s, a in ((s, int(rng.randint(0, s))) for s in shape)
        )
        rng_bytes = contiguous_byte_range(shape, slices, 4)
        sel = arr[slices]
        if rng_bytes is None:
            continue
        start, end = rng_bytes
        flat = arr.tobytes()[start:end]
        np.testing.assert_array_equal(
            np.frombuffer(flat, dtype=np.int32).reshape(sel.shape), sel
        )
