"""Ring attention (sequence parallelism over the mesh) vs the dense
einsum reference, on the 8-device virtual mesh.

Marked ``slow``: the inner flash kernel runs in Pallas interpreter mode
on the hermetic CPU suite, once per ring step per device. Run with
``-m slow`` (or no ``-m`` filter)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

pytestmark = pytest.mark.slow

from torchsnapshot_tpu.ops.attention import _reference_attention
from torchsnapshot_tpu.parallel.ring_attention import ring_attention, shard_seq


def _qkv(shape, seed=0):
    kq, kk, kv = jax.random.split(jax.random.key(seed), 3)
    return (
        jax.random.normal(kq, shape, jnp.float32),
        jax.random.normal(kk, shape, jnp.float32),
        jax.random.normal(kv, shape, jnp.float32),
    )


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("shape", [(2, 2, 64, 16), (1, 4, 128, 32)])
def test_ring_matches_dense(shape, causal):
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _qkv(shape, seed=shape[2])
    qs, ks, vs = (shard_seq(t, mesh) for t in (q, k, v))
    out = ring_attention(qs, ks, vs, mesh, causal=causal)
    assert out.sharding.spec == P(None, None, "sp", None)
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(_reference_attention(q, k, v, causal)),
        atol=3e-6,
        rtol=1e-5,
    )


def test_ring_on_dp_sp_mesh():
    """Batch AND sequence sharded: the ring rides the sp axis while dp
    partitions the batch — the long-context layout."""
    devices = np.array(jax.devices()).reshape(2, 4)
    mesh = Mesh(devices, ("dp", "sp"))
    q, k, v = _qkv((4, 2, 64, 16), seed=9)
    spec = P("dp", None, "sp", None)
    qs, ks, vs = (
        jax.device_put(t, NamedSharding(mesh, spec)) for t in (q, k, v)
    )
    out = ring_attention(qs, ks, vs, mesh, causal=True)
    # The batch sharding must survive (a hardcoded seq-only spec would
    # silently all-gather dp and return the batch replicated).
    assert out.sharding.spec == spec
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(_reference_attention(q, k, v, True)),
        atol=3e-6,
        rtol=1e-5,
    )


def test_ring_gradients_flow():
    """ppermute/fori_loop/cond all differentiate; ring gradients match
    the dense reference's."""
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _qkv((1, 2, 32, 8), seed=3)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, True) ** 2)

    qs, ks, vs = (shard_seq(t, mesh) for t in (q, k, v))
    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(qs, ks, vs)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_ring_rejects_indivisible_sequence():
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _qkv((1, 1, 60, 8))
    with pytest.raises(ValueError, match="divisible"):
        ring_attention(q, k, v, mesh)


def test_zigzag_ring_matches_dense():
    """Balanced-layout causal ring: permute -> ring -> unpermute equals
    the dense reference; per-device causal work is constant by layout."""
    from torchsnapshot_tpu.parallel.ring_attention import (
        from_zigzag,
        ring_attention_zigzag,
        to_zigzag,
        zigzag_indices,
    )

    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _qkv((2, 2, 64, 16), seed=11)
    qz, kz, vz = (to_zigzag(t, mesh) for t in (q, k, v))
    out = from_zigzag(ring_attention_zigzag(qz, kz, vz, mesh), mesh)
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(_reference_attention(q, k, v, True)),
        atol=3e-6,
        rtol=1e-5,
    )
    # The permutation is an involution-free bijection; round-trips.
    idx = np.asarray(zigzag_indices(64, 8))
    assert sorted(idx.tolist()) == list(range(64))
    x = jax.random.normal(jax.random.key(0), (1, 1, 64, 4))
    np.testing.assert_array_equal(
        np.asarray(from_zigzag(to_zigzag(x, mesh), mesh)), np.asarray(x)
    )


def test_zigzag_rejects_indivisible():
    from torchsnapshot_tpu.parallel.ring_attention import ring_attention_zigzag

    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _qkv((1, 1, 40, 8))
    with pytest.raises(ValueError, match="divisible"):
        ring_attention_zigzag(q, k, v, mesh)


def test_zigzag_gradients_flow():
    """The zigzag path is the causal-training entry point; its grads
    must match the dense reference (double-nested cond per sub-step)."""
    from torchsnapshot_tpu.parallel.ring_attention import (
        from_zigzag,
        ring_attention_zigzag,
        to_zigzag,
        zigzag_indices,
    )

    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _qkv((1, 2, 32, 8), seed=13)
    idx = zigzag_indices(32, 8)
    inv = jnp.argsort(idx)

    def loss_zig(q, k, v):
        # Permute inside the traced function so grads come back in the
        # original token order; spec passed explicitly (traced inputs
        # have no .sharding).
        qz, kz, vz = (jnp.take(t, idx, axis=2) for t in (q, k, v))
        out = ring_attention_zigzag(
            qz, kz, vz, mesh, spec=jax.sharding.PartitionSpec(None, None, "sp", None)
        )
        return jnp.sum(jnp.take(out, inv, axis=2) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, True) ** 2)

    qs, ks, vs = (shard_seq(t, mesh) for t in (q, k, v))
    gz = jax.grad(loss_zig, argnums=(0, 1, 2))(qs, ks, vs)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gz, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_zigzag_preserves_batch_sharding():
    from torchsnapshot_tpu.parallel.ring_attention import (
        ring_attention_zigzag,
        zigzag_indices,
    )

    devices = np.array(jax.devices()).reshape(2, 4)
    mesh = Mesh(devices, ("dp", "sp"))
    q, k, v = _qkv((4, 2, 64, 16), seed=17)
    idx = zigzag_indices(64, 4)
    spec = P("dp", None, "sp", None)
    qz, kz, vz = (
        jax.device_put(jnp.take(t, idx, axis=2), NamedSharding(mesh, spec))
        for t in (q, k, v)
    )
    out = ring_attention_zigzag(qz, kz, vz, mesh)
    assert out.sharding.spec == spec
    inv = np.argsort(np.asarray(idx))
    np.testing.assert_allclose(
        np.asarray(out)[:, :, inv],
        np.asarray(_reference_attention(q, k, v, True)),
        atol=3e-6,
        rtol=1e-5,
    )


def test_transformer_ring_attention_on_dp_sp_mesh():
    """The flagship transformer with ring attention over a dp x sp mesh
    matches the einsum path; the full train step compiles and runs."""
    from torchsnapshot_tpu.models.transformer import (
        TransformerConfig,
        forward,
        init_params,
        sgd_train_step,
        shard_params,
    )

    devices = np.array(jax.devices()).reshape(2, 2, 2)
    mesh = Mesh(devices, ("dp", "sp", "tp"))
    base = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=32,
    )
    ring = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=32, ring_attention=True,
    )
    params = shard_params(init_params(base, jax.random.key(0)), mesh)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (4, 32), 0, 64),
        NamedSharding(mesh, P("dp", "sp")),
    )
    out_base = jax.jit(lambda p, t: forward(p, t, base, mesh))(params, tokens)
    out_ring = jax.jit(lambda p, t: forward(p, t, ring, mesh))(params, tokens)
    np.testing.assert_allclose(
        np.asarray(out_base), np.asarray(out_ring), atol=2e-4, rtol=1e-4
    )

    _, loss = jax.jit(lambda p, t: sgd_train_step(p, t, config=ring, mesh=mesh))(
        params, tokens
    )
    assert np.isfinite(float(loss))


def test_transformer_ring_requires_sp_mesh():
    from torchsnapshot_tpu.models.transformer import (
        TransformerConfig,
        forward,
        init_params,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=1, d_ff=64,
        max_seq_len=16, ring_attention=True,
    )
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, 64)
    with pytest.raises(ValueError, match='"sp" axis'):
        forward(params, tokens, cfg)  # no mesh


def test_to_zigzag_preserves_batch_sharding():
    from torchsnapshot_tpu.parallel.ring_attention import from_zigzag, to_zigzag

    devices = np.array(jax.devices()).reshape(2, 4)
    mesh = Mesh(devices, ("dp", "sp"))
    x = jax.random.normal(jax.random.key(0), (4, 2, 64, 8))
    xs = jax.device_put(x, NamedSharding(mesh, P("dp", None, "sp", None)))
    z = to_zigzag(xs, mesh)
    assert z.sharding.spec == P("dp", None, "sp", None)
    back = from_zigzag(z, mesh)
    assert back.sharding.spec == P("dp", None, "sp", None)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(x))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_with_flash_chunks_matches_dense(causal):
    """chunk_impl="flash": the fused Pallas kernel computes each
    (q-chunk, k-chunk) tile and its (out, lse) merges into the ring's
    online softmax as (out, lse, 1) — cross-device ring memory plus
    on-device flash memory, composed."""
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _qkv((1, 2, 256, 32), seed=21 + int(causal))
    qs, ks, vs = (shard_seq(t, mesh) for t in (q, k, v))
    out = ring_attention(qs, ks, vs, mesh, causal=causal, chunk_impl="flash")
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(_reference_attention(q, k, v, causal)),
        atol=3e-5,
        rtol=1e-5,
    )


def test_ring_flash_chunk_too_small_rejected():
    from torchsnapshot_tpu.parallel.ring_attention import ring_attention as ra

    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _qkv((1, 1, 8, 8))  # chunk = 1 per device
    with pytest.raises(ValueError, match="power-of-two factor"):
        ra(q, k, v, mesh, chunk_impl="flash")


def test_flash_chunk_attention_vjp_matches_einsum():
    """flash_chunk_attention returns (out, lse) and differentiates w.r.t.
    BOTH cotangents: the lse cotangent folds into the tiled backward as
    delta' = delta - dlse. Reference: einsum attention + logsumexp."""
    from torchsnapshot_tpu.ops.attention import flash_chunk_attention

    kq, kk, kv = jax.random.split(jax.random.key(5), 3)
    shape = (2, 2, 64, 16)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)

    def ref_pair(q, k, v):
        d = q.shape[-1]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) / (
            d**0.5
        )
        length = q.shape[2]
        mask = jnp.tril(jnp.ones((length, length), bool))
        s = jnp.where(mask[None, None], s, -1e30)
        lse = jax.nn.logsumexp(s, axis=-1, keepdims=True)
        out = jnp.einsum(
            "bhqk,bhkd->bhqd", jnp.exp(s - lse), v.astype(jnp.float32)
        )
        return out, lse

    # A loss touching both outputs, so both cotangents are nonzero.
    def loss_flash(q, k, v):
        out, lse = flash_chunk_attention(q, k, v, True, 32, 32, True)
        return jnp.sum(out.astype(jnp.float32) ** 2) + jnp.sum(
            jnp.sin(lse)
        )

    def loss_ref(q, k, v):
        out, lse = ref_pair(q, k, v)
        return jnp.sum(out**2) + jnp.sum(jnp.sin(lse))

    np.testing.assert_allclose(
        float(loss_flash(q, k, v)), float(loss_ref(q, k, v)), rtol=1e-5
    )
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-4
        )


def test_ring_flash_gradients_match_einsum_ring():
    """VERDICT #5 done-criterion: gradient parity of
    ring_attention(chunk_impl="flash") vs the einsum ring on a dp x sp
    mesh — long-context training keeps the fused kernel's memory bound."""
    devices = np.array(jax.devices()).reshape(2, 4)
    mesh = Mesh(devices, ("dp", "sp"))
    q, k, v = _qkv((2, 2, 128, 16), seed=29)
    spec = P("dp", None, "sp", None)
    qs, ks, vs = (
        jax.device_put(t, NamedSharding(mesh, spec)) for t in (q, k, v)
    )

    def loss(impl):
        def f(q, k, v):
            out = ring_attention(
                q, k, v, mesh, causal=True, spec=spec, chunk_impl=impl
            )
            return jnp.sum(out.astype(jnp.float32) ** 2)

        return f

    gf = jax.grad(loss("flash"), argnums=(0, 1, 2))(qs, ks, vs)
    ge = jax.grad(loss("einsum"), argnums=(0, 1, 2))(qs, ks, vs)
    for a, b in zip(gf, ge):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-4
        )


def test_zigzag_flash_chunks_match_dense_and_differentiate():
    from torchsnapshot_tpu.parallel.ring_attention import (
        from_zigzag,
        ring_attention_zigzag,
        to_zigzag,
        zigzag_indices,
    )

    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _qkv((1, 2, 256, 16), seed=31)
    qz, kz, vz = (to_zigzag(t, mesh) for t in (q, k, v))
    out = from_zigzag(
        ring_attention_zigzag(qz, kz, vz, mesh, chunk_impl="flash"), mesh
    )
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(_reference_attention(q, k, v, True)),
        atol=3e-5,
        rtol=1e-5,
    )

    idx = zigzag_indices(256, 8)
    spec = P(None, None, "sp", None)

    def loss(impl):
        def f(q, k, v):
            qz, kz, vz = (jnp.take(t, idx, axis=2) for t in (q, k, v))
            out = ring_attention_zigzag(
                qz, kz, vz, mesh, spec=spec, chunk_impl=impl
            )
            return jnp.sum(out.astype(jnp.float32) ** 2)

        return f

    gf = jax.grad(loss("flash"), argnums=(0, 1, 2))(q, k, v)
    ge = jax.grad(loss("einsum"), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, ge):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-4
        )


def test_zigzag_layout_balances_causal_work():
    """The layout property that makes zigzag worth integrating: per-device
    causal sub-chunk attention count is CONSTANT, while the contiguous
    layout's grows linearly with ring position. Counted from the same
    (q_id, k_id) visibility rule the kernels' lax.cond predicates encode."""
    n = 8
    # zigzag: device j owns q sub-chunks {j, 2n-1-j}; over the n ring
    # steps it sees every k sub-chunk pair {src, 2n-1-src}.
    zig_work = []
    for j in range(n):
        q_ids = (j, 2 * n - 1 - j)
        count = sum(
            1
            for src in range(n)
            for k_id in (src, 2 * n - 1 - src)
            for q_id in q_ids
            if k_id <= q_id
        )
        zig_work.append(count)
    assert len(set(zig_work)) == 1, zig_work  # constant across devices

    # contiguous: device j owns q chunk j and attends k chunks 0..j.
    contig_work = [j + 1 for j in range(n)]
    assert max(contig_work) == n * min(contig_work)  # n-fold imbalance


def test_transformer_zigzag_train_step_matches_dense():
    """VERDICT #4 done-criterion: TransformerConfig(ring_attention=
    "zigzag") trains on a dp x sp x tp mesh; loss and one SGD step match
    the dense einsum config to float tolerance (the loss permutes
    tokens/targets to zigzag order; CE is permutation-invariant)."""
    from torchsnapshot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
        loss_fn,
        sgd_train_step,
        shard_params,
    )

    devices = np.array(jax.devices()).reshape(2, 2, 2)
    mesh = Mesh(devices, ("dp", "sp", "tp"))
    kw = dict(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=32,
    )
    base = TransformerConfig(**kw)
    zig = TransformerConfig(**kw, ring_attention="zigzag")
    params = shard_params(init_params(base, jax.random.key(0)), mesh)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (4, 32), 0, 64),
        NamedSharding(mesh, P("dp", "sp")),
    )
    loss_base = jax.jit(lambda p, t: loss_fn(p, t, base, mesh))(params, tokens)
    loss_zig = jax.jit(lambda p, t: loss_fn(p, t, zig, mesh))(params, tokens)
    np.testing.assert_allclose(
        float(loss_base), float(loss_zig), rtol=1e-5, atol=1e-6
    )

    step = jax.jit(lambda p, t: sgd_train_step(p, t, config=zig, mesh=mesh))
    new_params, loss = step(params, tokens)
    assert np.isfinite(float(loss))
    ref_params, _ = jax.jit(
        lambda p, t: sgd_train_step(p, t, config=base, mesh=mesh)
    )(params, tokens)
    for a, b in zip(
        jax.tree.leaves(new_params), jax.tree.leaves(ref_params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-3
        )


def test_transformer_zigzag_with_flash_chunks():
    """zigzag + flash chunks: the long-context TRAINING configuration —
    balanced causal work, fused-kernel memory, full train step jitted."""
    from torchsnapshot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
        loss_fn,
        sgd_train_step,
        shard_params,
    )

    devices = np.array(jax.devices()).reshape(2, 2, 2)
    mesh = Mesh(devices, ("dp", "sp", "tp"))
    kw = dict(
        vocab_size=64, d_model=32, n_heads=4, n_layers=1, d_ff=64,
        max_seq_len=128,
    )
    base = TransformerConfig(**kw)
    zigflash = TransformerConfig(
        **kw, ring_attention="zigzag", ring_chunk_impl="flash"
    )
    params = shard_params(init_params(base, jax.random.key(2)), mesh)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(3), (2, 128), 0, 64),
        NamedSharding(mesh, P("dp", "sp")),
    )
    loss_base = jax.jit(lambda p, t: loss_fn(p, t, base, mesh))(params, tokens)
    loss_zf = jax.jit(lambda p, t: loss_fn(p, t, zigflash, mesh))(
        params, tokens
    )
    np.testing.assert_allclose(
        float(loss_base), float(loss_zf), rtol=1e-4, atol=1e-5
    )
    _, loss = jax.jit(
        lambda p, t: sgd_train_step(p, t, config=zigflash, mesh=mesh)
    )(params, tokens)
    assert np.isfinite(float(loss))


def test_zigzag_helpers_seq_axis():
    """to_zigzag/from_zigzag work for non-attention layouts: [B, S]
    tokens and [B, S, V] logits via seq_axis=1 (inference callers
    un-permute zigzag logits with this)."""
    from torchsnapshot_tpu.parallel.ring_attention import (
        from_zigzag,
        to_zigzag,
    )

    mesh = Mesh(np.array(jax.devices()), ("sp",))
    tokens = jax.random.randint(jax.random.key(0), (2, 64), 0, 100)
    z = to_zigzag(tokens, mesh, seq_axis=1)
    assert z.sharding.spec[1] == "sp"
    back = from_zigzag(z, mesh, seq_axis=1)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(tokens))

    logits = jax.random.normal(jax.random.key(1), (2, 64, 16))
    back2 = from_zigzag(
        to_zigzag(logits, mesh, seq_axis=1), mesh, seq_axis=1
    )
    np.testing.assert_array_equal(np.asarray(back2), np.asarray(logits))


def test_async_timeout_names_all_missing_ranks(tmp_path):
    """_collect_completion_manifests' timeout error enumerates every
    straggler rank, not just the first missing one."""
    import asyncio

    from torchsnapshot_tpu.manifest import SnapshotMetadata
    from torchsnapshot_tpu.io_types import IOReq
    from torchsnapshot_tpu.snapshot import _collect_completion_manifests
    from torchsnapshot_tpu.storage_plugins.memory import MemoryStoragePlugin

    storage = MemoryStoragePlugin()
    nonce = "abc123"
    # Ranks 0 and 2 completed; 1 and 3 never did.
    for r in (0, 2):
        doc = SnapshotMetadata(
            version="v", world_size=4, manifest={}, take_id=nonce
        ).to_yaml()
        req = IOReq(path=f".completed/{nonce}/{r}")
        req.buf.write(doc.encode())
        asyncio.run(storage.write(req))

    with pytest.raises(TimeoutError, match=r"rank\(s\) \[1, 3\]"):
        asyncio.run(
            _collect_completion_manifests(storage, 4, nonce, timeout_s=0.3)
        )


def _gqa_qkv(b, hq, hkv, s, d, seed):
    ks = jax.random.split(jax.random.key(seed), 3)
    return (
        jax.random.normal(ks[0], (b, hq, s, d), jnp.float32),
        jax.random.normal(ks[1], (b, hkv, s, d), jnp.float32),
        jax.random.normal(ks[2], (b, hkv, s, d), jnp.float32),
    )


@pytest.mark.parametrize("chunk_impl", ["einsum", "flash"])
def test_ring_gqa_matches_repeated_kv_dense(chunk_impl):
    """GQA through the ring: K/V rotate with Hkv heads (ICI traffic
    shrinks by the group factor); result equals dense attention with
    kv heads repeated."""
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _gqa_qkv(1, 4, 2, 128, 16, seed=41)
    qs, ks_, vs = (shard_seq(t, mesh) for t in (q, k, v))
    out = ring_attention(
        qs, ks_, vs, mesh, causal=True, chunk_impl=chunk_impl
    )
    expected = _reference_attention(
        q, jnp.repeat(k, 2, axis=1), jnp.repeat(v, 2, axis=1), True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), atol=3e-5, rtol=1e-5
    )


@pytest.mark.parametrize("chunk_impl", ["einsum", "flash"])
def test_zigzag_gqa_gradients(chunk_impl):
    """GQA + zigzag + both chunk impls differentiates; grads match the
    repeat-kv dense reference (dk/dv group-summed onto shared heads)."""
    from torchsnapshot_tpu.parallel.ring_attention import (
        ring_attention_zigzag,
        zigzag_indices,
    )

    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _gqa_qkv(1, 4, 2, 128, 8, seed=43)
    idx = zigzag_indices(128, 8)
    spec = P(None, None, "sp", None)

    def loss_ring(q, k, v):
        qz, kz, vz = (jnp.take(t, idx, axis=2) for t in (q, k, v))
        out = ring_attention_zigzag(
            qz, kz, vz, mesh, spec=spec, chunk_impl=chunk_impl
        )
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(
            _reference_attention(
                q, jnp.repeat(k, 2, axis=1), jnp.repeat(v, 2, axis=1), True
            )
            ** 2
        )

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        assert a.shape == b.shape
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-4
        )
