"""Ring attention (sequence parallelism over the mesh) vs the dense
einsum reference, on the 8-device virtual mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchsnapshot_tpu.ops.attention import _reference_attention
from torchsnapshot_tpu.parallel.ring_attention import ring_attention, shard_seq


def _qkv(shape, seed=0):
    kq, kk, kv = jax.random.split(jax.random.key(seed), 3)
    return (
        jax.random.normal(kq, shape, jnp.float32),
        jax.random.normal(kk, shape, jnp.float32),
        jax.random.normal(kv, shape, jnp.float32),
    )


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("shape", [(2, 2, 64, 16), (1, 4, 128, 32)])
def test_ring_matches_dense(shape, causal):
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _qkv(shape, seed=shape[2])
    qs, ks, vs = (shard_seq(t, mesh) for t in (q, k, v))
    out = ring_attention(qs, ks, vs, mesh, causal=causal)
    assert out.sharding.spec == P(None, None, "sp", None)
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(_reference_attention(q, k, v, causal)),
        atol=3e-6,
        rtol=1e-5,
    )


def test_ring_on_dp_sp_mesh():
    """Batch AND sequence sharded: the ring rides the sp axis while dp
    partitions the batch — the long-context layout."""
    devices = np.array(jax.devices()).reshape(2, 4)
    mesh = Mesh(devices, ("dp", "sp"))
    q, k, v = _qkv((4, 2, 64, 16), seed=9)
    spec = P("dp", None, "sp", None)
    qs, ks, vs = (
        jax.device_put(t, NamedSharding(mesh, spec)) for t in (q, k, v)
    )
    out = ring_attention(qs, ks, vs, mesh, causal=True)
    # The batch sharding must survive (a hardcoded seq-only spec would
    # silently all-gather dp and return the batch replicated).
    assert out.sharding.spec == spec
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(_reference_attention(q, k, v, True)),
        atol=3e-6,
        rtol=1e-5,
    )


def test_ring_gradients_flow():
    """ppermute/fori_loop/cond all differentiate; ring gradients match
    the dense reference's."""
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _qkv((1, 2, 32, 8), seed=3)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, True) ** 2)

    qs, ks, vs = (shard_seq(t, mesh) for t in (q, k, v))
    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(qs, ks, vs)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_ring_rejects_indivisible_sequence():
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _qkv((1, 1, 60, 8))
    with pytest.raises(ValueError, match="divisible"):
        ring_attention(q, k, v, mesh)


def test_zigzag_ring_matches_dense():
    """Balanced-layout causal ring: permute -> ring -> unpermute equals
    the dense reference; per-device causal work is constant by layout."""
    from torchsnapshot_tpu.parallel.ring_attention import (
        from_zigzag,
        ring_attention_zigzag,
        to_zigzag,
        zigzag_indices,
    )

    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _qkv((2, 2, 64, 16), seed=11)
    qz, kz, vz = (to_zigzag(t, mesh) for t in (q, k, v))
    out = from_zigzag(ring_attention_zigzag(qz, kz, vz, mesh), mesh)
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(_reference_attention(q, k, v, True)),
        atol=3e-6,
        rtol=1e-5,
    )
    # The permutation is an involution-free bijection; round-trips.
    idx = np.asarray(zigzag_indices(64, 8))
    assert sorted(idx.tolist()) == list(range(64))
    x = jax.random.normal(jax.random.key(0), (1, 1, 64, 4))
    np.testing.assert_array_equal(
        np.asarray(from_zigzag(to_zigzag(x, mesh), mesh)), np.asarray(x)
    )


def test_zigzag_rejects_indivisible():
    from torchsnapshot_tpu.parallel.ring_attention import ring_attention_zigzag

    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _qkv((1, 1, 40, 8))
    with pytest.raises(ValueError, match="divisible"):
        ring_attention_zigzag(q, k, v, mesh)


def test_zigzag_gradients_flow():
    """The zigzag path is the causal-training entry point; its grads
    must match the dense reference (double-nested cond per sub-step)."""
    from torchsnapshot_tpu.parallel.ring_attention import (
        from_zigzag,
        ring_attention_zigzag,
        to_zigzag,
        zigzag_indices,
    )

    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _qkv((1, 2, 32, 8), seed=13)
    idx = zigzag_indices(32, 8)
    inv = jnp.argsort(idx)

    def loss_zig(q, k, v):
        # Permute inside the traced function so grads come back in the
        # original token order; spec passed explicitly (traced inputs
        # have no .sharding).
        qz, kz, vz = (jnp.take(t, idx, axis=2) for t in (q, k, v))
        out = ring_attention_zigzag(
            qz, kz, vz, mesh, spec=jax.sharding.PartitionSpec(None, None, "sp", None)
        )
        return jnp.sum(jnp.take(out, inv, axis=2) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, True) ** 2)

    qs, ks, vs = (shard_seq(t, mesh) for t in (q, k, v))
    gz = jax.grad(loss_zig, argnums=(0, 1, 2))(qs, ks, vs)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gz, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_zigzag_preserves_batch_sharding():
    from torchsnapshot_tpu.parallel.ring_attention import (
        ring_attention_zigzag,
        zigzag_indices,
    )

    devices = np.array(jax.devices()).reshape(2, 4)
    mesh = Mesh(devices, ("dp", "sp"))
    q, k, v = _qkv((4, 2, 64, 16), seed=17)
    idx = zigzag_indices(64, 4)
    spec = P("dp", None, "sp", None)
    qz, kz, vz = (
        jax.device_put(jnp.take(t, idx, axis=2), NamedSharding(mesh, spec))
        for t in (q, k, v)
    )
    out = ring_attention_zigzag(qz, kz, vz, mesh)
    assert out.sharding.spec == spec
    inv = np.argsort(np.asarray(idx))
    np.testing.assert_allclose(
        np.asarray(out)[:, :, inv],
        np.asarray(_reference_attention(q, k, v, True)),
        atol=3e-6,
        rtol=1e-5,
    )


def test_transformer_ring_attention_on_dp_sp_mesh():
    """The flagship transformer with ring attention over a dp x sp mesh
    matches the einsum path; the full train step compiles and runs."""
    from torchsnapshot_tpu.models.transformer import (
        TransformerConfig,
        forward,
        init_params,
        sgd_train_step,
        shard_params,
    )

    devices = np.array(jax.devices()).reshape(2, 2, 2)
    mesh = Mesh(devices, ("dp", "sp", "tp"))
    base = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=32,
    )
    ring = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=32, ring_attention=True,
    )
    params = shard_params(init_params(base, jax.random.key(0)), mesh)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (4, 32), 0, 64),
        NamedSharding(mesh, P("dp", "sp")),
    )
    out_base = jax.jit(lambda p, t: forward(p, t, base, mesh))(params, tokens)
    out_ring = jax.jit(lambda p, t: forward(p, t, ring, mesh))(params, tokens)
    np.testing.assert_allclose(
        np.asarray(out_base), np.asarray(out_ring), atol=2e-4, rtol=1e-4
    )

    _, loss = jax.jit(lambda p, t: sgd_train_step(p, t, config=ring, mesh=mesh))(
        params, tokens
    )
    assert np.isfinite(float(loss))


def test_transformer_ring_requires_sp_mesh():
    from torchsnapshot_tpu.models.transformer import (
        TransformerConfig,
        forward,
        init_params,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=1, d_ff=64,
        max_seq_len=16, ring_attention=True,
    )
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, 64)
    with pytest.raises(ValueError, match='"sp" axis'):
        forward(params, tokens, cfg)  # no mesh


def test_to_zigzag_preserves_batch_sharding():
    from torchsnapshot_tpu.parallel.ring_attention import from_zigzag, to_zigzag

    devices = np.array(jax.devices()).reshape(2, 4)
    mesh = Mesh(devices, ("dp", "sp"))
    x = jax.random.normal(jax.random.key(0), (4, 2, 64, 8))
    xs = jax.device_put(x, NamedSharding(mesh, P("dp", None, "sp", None)))
    z = to_zigzag(xs, mesh)
    assert z.sharding.spec == P("dp", None, "sp", None)
    back = from_zigzag(z, mesh)
    assert back.sharding.spec == P("dp", None, "sp", None)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(x))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_with_flash_chunks_matches_dense(causal):
    """chunk_impl="flash": the fused Pallas kernel computes each
    (q-chunk, k-chunk) tile and its (out, lse) merges into the ring's
    online softmax as (out, lse, 1) — cross-device ring memory plus
    on-device flash memory, composed."""
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _qkv((1, 2, 256, 32), seed=21 + int(causal))
    qs, ks, vs = (shard_seq(t, mesh) for t in (q, k, v))
    out = ring_attention(qs, ks, vs, mesh, causal=causal, chunk_impl="flash")
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(_reference_attention(q, k, v, causal)),
        atol=3e-5,
        rtol=1e-5,
    )


def test_ring_flash_chunk_too_small_rejected():
    from torchsnapshot_tpu.parallel.ring_attention import ring_attention as ra

    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _qkv((1, 1, 8, 8))  # chunk = 1 per device
    with pytest.raises(ValueError, match="power-of-two factor"):
        ra(q, k, v, mesh, chunk_impl="flash")
