"""xs128 content fingerprints (incremental-snapshot dedup primitive)."""

import numpy as np
import pytest

import jax.numpy as jnp

from torchsnapshot_tpu.fingerprint import (
    FINGERPRINT_ALGO,
    fingerprint_device_async,
    fingerprint_host,
    format_fingerprint,
)


def _device_fp(x, slices=None) -> str:
    return format_fingerprint(np.asarray(fingerprint_device_async(x, slices)))


@pytest.mark.parametrize(
    "dtype,shape",
    [
        ("float32", (17, 33)),
        ("int32", (64,)),
        ("uint8", (123,)),
        ("bool", (37,)),
        ("bfloat16", (9, 11)),
        ("float16", (31,)),
        ("int8", (5, 7, 3)),
    ],
)
def test_host_device_agree(dtype, shape):
    import ml_dtypes

    rng = np.random.default_rng(0)
    np_dtype = ml_dtypes.bfloat16 if dtype == "bfloat16" else np.dtype(dtype)
    if dtype == "bool":
        x = rng.integers(0, 2, shape).astype(bool)
    elif np.issubdtype(np.dtype("int8" if dtype == "bfloat16" else dtype), np.integer):
        x = rng.integers(-100, 100, shape).astype(np_dtype)
    else:
        x = rng.standard_normal(shape).astype(np_dtype)
    h = fingerprint_host(x)
    assert h.startswith(FINGERPRINT_ALGO + ":") and len(h.split(":")[1]) == 32
    assert _device_fp(jnp.asarray(x)) == h


def test_deterministic_across_calls():
    x = jnp.arange(1000, dtype=jnp.float32)
    assert _device_fp(x) == _device_fp(x)
    hx = np.arange(1000, dtype=np.float32)
    assert fingerprint_host(hx) == fingerprint_host(hx)


def test_sensitive_to_single_bit_flip():
    rng = np.random.default_rng(1)
    x = rng.standard_normal(4096).astype(np.float32)
    y = x.copy()
    y.view(np.uint32)[2048] ^= 1  # lowest mantissa bit
    assert fingerprint_host(x) != fingerprint_host(y)


def test_sensitive_to_permutation():
    x = np.arange(256, dtype=np.float32)
    assert fingerprint_host(x) != fingerprint_host(x[::-1].copy())


def test_sensitive_to_trailing_zeros_vs_shape():
    # [1, 0] vs [1] padded: padding is zeros, so length must matter
    # through the position weights (same words, different index range
    # contributes nothing for the zero word — the ENTRY shape/dtype
    # match requirement is what distinguishes these; the fingerprint
    # itself may legitimately collide here). Document: equal content
    # with different shapes never dedups because shape is part of the
    # match key, not the fingerprint.
    a = np.array([1.0, 0.0], dtype=np.float32)
    b = np.array([1.0], dtype=np.float32)
    # No assertion on inequality — this documents the contract.
    fingerprint_host(a), fingerprint_host(b)


def test_bytes_input_matches_array_view():
    x = np.arange(100, dtype=np.int32)
    assert fingerprint_host(x) == fingerprint_host(x.tobytes())


def test_slice_fingerprint_matches_host_subbox():
    rng = np.random.default_rng(2)
    x = rng.standard_normal((8, 12)).astype(np.float32)
    xd = jnp.asarray(x)
    s = (slice(2, 6), slice(3, 9))
    assert _device_fp(xd, s) == fingerprint_host(np.ascontiguousarray(x[2:6, 3:9]))


def test_empty_array():
    z = np.zeros((0,), np.float32)
    assert fingerprint_host(z) == format_fingerprint(np.zeros(4, np.uint32))
    assert _device_fp(jnp.asarray(z)) == fingerprint_host(z)


def test_odd_byte_lengths_pad_consistently():
    for n in (1, 2, 3, 5, 7):
        x = np.arange(n, dtype=np.uint8)
        assert fingerprint_host(x) == _device_fp(jnp.asarray(x)), n


def test_unpadded_prefix_differs_from_padded():
    # 3 bytes [1,2,3] pads to word 0x00030201; the 4-byte [1,2,3,0]
    # produces the same word stream — shapes/dtypes are what
    # disambiguate at the entry level (see match key contract).
    assert fingerprint_host(np.array([1, 2, 3], np.uint8)) == fingerprint_host(
        np.array([1, 2, 3, 0], np.uint8)
    )
