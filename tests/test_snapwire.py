"""snapwire: the hot tier's REAL cross-host transport — ack-at-k over
the wire, delta replication, and the network fault matrix.

Fast tier (``-m faultline``, runs in tier-1): the over-the-wire
ack-at-k contract (replicas fingerprint-verified by the receiving peer
process before the ack), delta pushes costing exactly the changed-chunk
bytes, an unchanged retake's delta_ratio < 10%, torn-frame /
drop_conn / slow_wire determinism, a real-SIGKILL host-loss ×
crash-point stride subset (full enumeration ``-m slow``),
restore-from-peer after a real process kill, the lose_host
blocked-read abort contract, capacity-refusal spare substitution, the
replication telemetry window (report / ledger / doctor), and the
``TPUSNAPSHOT_HOT_TIER_ADDRS`` address book.

In-process peers (``start_local_peer``) carry real TCP sockets without
subprocess spawn cost; the SIGKILL scenarios use real ``spawn_peer``
subprocesses — killing the process IS the host loss.
"""

import asyncio
import json
import signal
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from torchsnapshot_tpu import Snapshot, StateDict, hottier
from torchsnapshot_tpu import faultline as fl
from torchsnapshot_tpu import wire
from torchsnapshot_tpu.hottier import tier as ht_tier
from torchsnapshot_tpu.hottier import transport
from torchsnapshot_tpu.hottier.peer import spawn_peer, start_local_peer
from torchsnapshot_tpu.io_types import IOReq
from torchsnapshot_tpu.snapserve import protocol as snapserve_protocol
from torchsnapshot_tpu.storage_plugin import url_to_storage_plugin
from torchsnapshot_tpu.telemetry.doctor import diagnose_report

pytestmark = pytest.mark.faultline


# ----------------------------------------------------------------- helpers


@pytest.fixture(autouse=True)
def _fresh_wire(monkeypatch):
    """Every test starts with an empty tier, no registered peers, no
    scripted wire faults, and tight (fast-failing) wire knobs."""
    monkeypatch.setenv("TPUSNAPSHOT_REPLICATION_DEADLINE_S", "2")
    monkeypatch.setenv("TPUSNAPSHOT_REPLICATION_RETRY_BUDGET_S", "3")
    monkeypatch.setenv("TPUSNAPSHOT_REPLICATION_DOWN_COOLDOWN_S", "0.2")
    monkeypatch.setenv("TPUSNAPSHOT_REPLICATION_CODEC", "none")
    hottier.disable_hot_tier(flush=False)
    hottier.reset_hot_tier()
    transport.clear_wire_faults()
    servers = []
    yield servers
    hottier.disable_hot_tier(flush=False)
    hottier.reset_hot_tier()  # closes RemotePeers, kills spawned procs
    transport.clear_wire_faults()
    for server in servers:
        server.stop()


def _local_peer(servers, host_id, capacity_bytes=1 << 26):
    server, peer = start_local_peer(host_id, capacity_bytes=capacity_bytes)
    servers.append(server)
    return peer


def _state(v, n=2048):
    return {"s": StateDict(w=jnp.full((n,), float(v), dtype=jnp.float32))}


def _target(n=2048):
    return {"s": StateDict(w=jnp.zeros((n,), dtype=jnp.float32))}


def _assert_restored(target, v):
    np.testing.assert_array_equal(np.asarray(target["s"]["w"]), float(v))


def _read_report(root):
    plugin = url_to_storage_plugin(root)
    try:
        req = IOReq(path=".report.json")
        asyncio.run(plugin.read(req))
        return json.loads(bytes(req.data).decode("utf-8"))
    finally:
        plugin.close()


# -------------------------------------------------------- framing contract


def test_wire_framing_shared_with_snapserve():
    """snapserve/protocol.py is a re-export of the shared wire module:
    same callables, bit-identical frames — the extraction is
    structurally incapable of drift."""
    assert snapserve_protocol.send_frame is wire.send_frame
    assert snapserve_protocol.recv_frame is wire.recv_frame
    assert snapserve_protocol.error_to_wire is wire.error_to_wire
    assert snapserve_protocol.ProtocolError is wire.ProtocolError
    assert snapserve_protocol.InvalidRange is wire.InvalidRange
    frame = wire.encode_frame({"op": "read", "v": 1}, b"payload")
    # !I header-len, !Q payload-len, sorted-keys JSON, raw payload.
    header = json.dumps({"op": "read", "v": 1}, sort_keys=True).encode()
    assert frame == (
        len(header).to_bytes(4, "big")
        + len(b"payload").to_bytes(8, "big")
        + header
        + b"payload"
    )


# --------------------------------------------------------------- ack-at-k


def test_ack_at_k_over_the_wire(_fresh_wire):
    """k=3 across one local + two wire peers: the take acks only after
    every replica crossed a process-visible socket and was fingerprint-
    verified by the receiver; both peers actually hold the bytes."""
    peer1 = _local_peer(_fresh_wire, 1)
    peer2 = _local_peer(_fresh_wire, 2)
    path = "memory://wire-ack/run/step_0"
    before = transport.wire_stats_snapshot()
    with hottier.hot_tier(rank=0, world=3, k=3, drain="manual"):
        snap = Snapshot.take(path, _state(7.0))
        for peer in (peer1, peer2):
            q = peer.query(path + "/0/s/w")
            assert q is not None and q["nbytes"] == 2048 * 4
        after = transport.wire_stats_snapshot()
        assert after["pushes"] - before["pushes"] == 2
        assert (
            after["payload_bytes"] - before["payload_bytes"] == 2 * 8192
        )
        # Kill the local host: the restore is served from a surviving
        # WIRE replica, bit-exact.
        ht_tier.kill_host(0)
        target = _target()
        snap.restore(target)
        _assert_restored(target, 7.0)
        rt = hottier.runtime()
        assert rt.stats_snapshot()["hot_objects"] >= 1
        hottier.drain_now()


def test_corrupt_push_never_acks(_fresh_wire):
    """The receiver's ack gate: a push whose reconstruction does not
    fingerprint back to the pushed tag is NACKed and stores nothing."""
    peer = _local_peer(_fresh_wire, 1)
    data = b"x" * 4096
    resp, _ = peer._call(
        {
            "v": wire.PROTOCOL_VERSION,
            "op": "put",
            "key": "memory://wire-corrupt/run/step_0/0/s/w",
            "root": "memory://wire-corrupt/run/step_0",
            "tag": "bogus-tag",
            "size": len(data),
            "lossy": False,
            "frames": [["raw", 0, len(data), len(data), None]],
        },
        data,
    )
    assert resp["ok"] is False
    assert resp["error"]["kind"] == "corrupt_push"
    assert peer.query("memory://wire-corrupt/run/step_0/0/s/w") is None


def test_capacity_refusal_substitutes_spare_host(_fresh_wire):
    """A wire peer refusing for capacity is not an ack: placement
    continues to the spare host and the object still reaches k replicas
    without a write-through."""
    _local_peer(_fresh_wire, 1, capacity_bytes=64)  # refuses everything
    path = "memory://wire-cap/run/step_0"
    with hottier.hot_tier(rank=0, world=3, k=2, drain="manual"):
        Snapshot.take(path, _state(3.0))
        rt = hottier.runtime()
        stats = rt.stats_snapshot()
        assert stats["write_through"] == 0
        assert stats["replicas"] == 2  # host 0 + spare host 2
        key = path + "/0/s/w"
        assert sorted(ht_tier.replica_hosts_for(key)) == [0, 2]
        hottier.drain_now()


# ----------------------------------------------------------------- deltas


def test_delta_push_costs_changed_chunk_bytes(_fresh_wire, monkeypatch):
    """A partially-dirty retake's push carries exactly the changed
    chunks (chunkstore-style fingerprints are the diff key); unchanged
    chunks travel as ref frames costing zero payload bytes."""
    monkeypatch.setenv("TPUSNAPSHOT_REPLICATION_CHUNK_BYTES", "1024")
    peer = _local_peer(_fresh_wire, 1)
    root0, root1 = (
        "memory://wire-delta/run/step_0",
        "memory://wire-delta/run/step_1",
    )
    base = np.arange(4096, dtype=np.float32)  # 16 KiB = 16 chunks
    data0 = base.tobytes()
    ht_tier.put_replica(
        root0 + "/0/s/w", 1, data0, ht_tier.payload_tag(data0), root0
    )
    dirty = base.copy()
    dirty[:256] += 1.0  # exactly the first 1024-byte chunk
    data1 = dirty.tobytes()
    before = transport.wire_stats_snapshot()
    ht_tier.put_replica(
        root1 + "/0/s/w", 1, data1, ht_tier.payload_tag(data1), root1
    )
    after = transport.wire_stats_snapshot()
    assert after["wire_bytes"] - before["wire_bytes"] == 1024
    assert peer.get(root1 + "/0/s/w").data == data1
    # Fully-unchanged retake: pure-ref push, zero payload bytes.
    root2 = "memory://wire-delta/run/step_2"
    before = transport.wire_stats_snapshot()
    ht_tier.put_replica(
        root2 + "/0/s/w", 1, data1, ht_tier.payload_tag(data1), root2
    )
    after = transport.wire_stats_snapshot()
    assert after["wire_bytes"] - before["wire_bytes"] == 0
    assert peer.get(root2 + "/0/s/w").data == data1


def test_unchanged_retake_delta_ratio_under_10pct(_fresh_wire, monkeypatch):
    """The acceptance number end-to-end: an unchanged retake through
    Snapshot.take replicates < 10% of its payload bytes over the wire,
    and the take report's tier.replication.delta_ratio certifies it."""
    monkeypatch.setenv("TPUSNAPSHOT_REPLICATION_CHUNK_BYTES", "4096")
    _local_peer(_fresh_wire, 1)
    state = _state(11.0, n=8192)
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        Snapshot.take("memory://wire-retake/run/step_0", state)
        root1 = "memory://wire-retake/run/step_1"
        Snapshot.take(root1, state)  # unchanged
        hottier.drain_now()
    report = _read_report(root1)
    rep = report["ranks"][0]["tier"]["replication"]
    assert rep["pushes"] >= 1
    assert rep["payload_bytes"] >= 8192 * 4
    assert rep["delta_ratio"] < 0.10


def test_stale_basis_recovers_with_full_push(_fresh_wire):
    """A peer that lost the delta basis (restart/eviction modeled by
    dropping the base replica) answers stale_basis; the client re-pushes
    full and converges — never a wrong replica, never a hang."""
    peer = _local_peer(_fresh_wire, 1)
    root0, root1 = (
        "memory://wire-stale/run/step_0",
        "memory://wire-stale/run/step_1",
    )
    data = np.arange(4096, dtype=np.float32).tobytes()
    ht_tier.put_replica(
        root0 + "/0/s/w", 1, data, ht_tier.payload_tag(data), root0
    )
    # The peer loses the basis replica behind the client's back.
    resp, _ = peer._call(
        {
            "v": wire.PROTOCOL_VERSION,
            "op": "drop",
            "key": root0 + "/0/s/w",
        }
    )
    assert resp["ok"]
    ht_tier.put_replica(
        root1 + "/0/s/w", 1, data, ht_tier.payload_tag(data), root1
    )
    assert peer.get(root1 + "/0/s/w").data == data


def test_int8_optin_lossy_wire_replica(_fresh_wire, monkeypatch):
    """Opt-in int8 moments replication: the wire carries quantized
    frames, the peer stores the DEQUANTIZED moments under their own
    verified tag (bounded error), and the drain persists the EXACT
    bytes from the local replica — the durable tier never sees lossy
    data."""
    from torchsnapshot_tpu import codecs

    monkeypatch.setenv("TPUSNAPSHOT_REPLICATION_INT8_GLOBS", "*opt*")
    monkeypatch.setenv("TPUSNAPSHOT_REPLICATION_CHUNK_BYTES", "4096")
    peer = _local_peer(_fresh_wire, 1)
    root = "memory://wire-int8/run/step_0"
    key = root + "/0/opt/m"
    rng = np.random.default_rng(7)
    moments = rng.standard_normal(4096).astype(np.float32)
    data = moments.tobytes()
    tag = ht_tier.payload_tag(data)
    before = transport.wire_stats_snapshot()
    assert ht_tier.put_replica(key, 1, data, tag, root)
    after = transport.wire_stats_snapshot()
    # Quantized frames cross the wire at ~1/4 the float32 payload.
    assert after["wire_bytes"] - before["wire_bytes"] < len(data) // 2
    obj = peer.get(key)
    assert obj.tag != tag  # lossy replica carries its OWN verified tag
    approx = np.frombuffer(obj.data, dtype=np.float32)
    bound = codecs.quant_error_bound(moments)
    assert float(np.max(np.abs(approx - moments))) <= bound + 1e-6
    # key_tag answers the LOGICAL tag (the drain item's match key), so
    # the lossy replica can never satisfy a drain probe.
    assert ht_tier.key_tag(key) == tag


# ------------------------------------------------------------- wire faults


def test_torn_frame_is_deterministic_and_never_acks(_fresh_wire):
    """faultline's torn_frame at a replicate boundary: the torn attempt
    never acks (the receiver's readexactly observes the tear), the
    retry converges, and the fault record is deterministic."""
    peer = _local_peer(_fresh_wire, 1)
    sched = fl.FaultSchedule().torn_frame(host=1, path="host1:*")
    path = "memory://wire-torn/run/step_0"
    before = transport.wire_stats_snapshot()
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        with fl.inject(sched) as ctl:
            snap = Snapshot.take(path, _state(5.0))
        assert ctl.fault_counts() == {"torn_frame": 1}
        after = transport.wire_stats_snapshot()
        assert after["retries"] - before["retries"] >= 1
        assert peer.query(path + "/0/s/w") is not None
        target = _target()
        snap.restore(target)
        _assert_restored(target, 5.0)
        hottier.drain_now()


def test_torn_frames_exhaust_budget_then_degrade(_fresh_wire):
    """Every attempt torn: the push exhausts its retry budget, the
    object is written through to the durable tier BEFORE the ack (the
    obligation is never lost), the peer holds nothing, and the restore
    is bit-exact."""
    peer = _local_peer(_fresh_wire, 1)
    for _ in range(64):  # enough for every retry inside the budget
        transport.script_wire_fault("torn_frame", host=1)
    path = "memory://wire-torn-all/run/step_0"
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        t0 = time.monotonic()
        snap = Snapshot.take(path, _state(6.0))
        assert time.monotonic() - t0 < 20.0  # bounded, no hang
        rt = hottier.runtime()
        stats = rt.stats_snapshot()
        assert stats["write_through"] == 1
        assert stats["degraded_puts"] == 1
        transport.clear_wire_faults()
        time.sleep(0.25)  # wait out the down cooldown
        assert peer.probe()  # the peer itself is healthy — only the
        assert peer.query(path + "/0/s/w") is None  # pushes tore; never acked
        target = _target()
        snap.restore(target)
        _assert_restored(target, 6.0)
        hottier.drain_now()
    report = _read_report(path)
    findings = {f.rule: f.severity for f in diagnose_report(report)}
    assert findings.get("replication-degraded") == "critical"


def test_drop_conn_retry_converges(_fresh_wire):
    peer = _local_peer(_fresh_wire, 1)
    sched = fl.FaultSchedule().drop_conn(host=1, path="host1:*")
    path = "memory://wire-drop/run/step_0"
    before = transport.wire_stats_snapshot()
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        with fl.inject(sched) as ctl:
            Snapshot.take(path, _state(9.0))
        assert ctl.fault_counts() == {"drop_conn": 1}
        after = transport.wire_stats_snapshot()
        assert after["retries"] - before["retries"] >= 1
        assert after["pushes"] - before["pushes"] == 1
        assert peer.query(path + "/0/s/w") is not None
        hottier.drain_now()


def test_slow_wire_misses_deadline_deterministically(_fresh_wire):
    """slow_wire above the RPC deadline: exactly one counted deadline
    miss, then the retry (unscripted) lands the push; the take report's
    replication window carries the miss and the doctor warns."""
    _local_peer(_fresh_wire, 1)
    sched = fl.FaultSchedule().slow_wire(
        seconds=3.0, host=1, path="host1:*"
    )
    path = "memory://wire-slow/run/step_0"
    before = transport.wire_stats_snapshot()
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        with fl.inject(sched) as ctl:
            Snapshot.take(path, _state(2.0))
        assert ctl.fault_counts() == {"slow_wire": 1}
        after = transport.wire_stats_snapshot()
        assert after["deadline_misses"] - before["deadline_misses"] == 1
        hottier.drain_now()
    report = _read_report(path)
    rep = report["ranks"][0]["tier"]["replication"]
    assert rep["deadline_misses"] == 1
    findings = {f.rule: f.severity for f in diagnose_report(report)}
    assert findings.get("replication-degraded") == "warn"


# ------------------------------------------------- real process boundaries


def test_spawn_peer_port_file_and_sigkill(_fresh_wire):
    """The subprocess peer binds via --port-file, answers pings and
    queries over the wire, and dies by real SIGKILL through
    tier.kill_host."""
    proc, addr, peer = spawn_peer(host_id=1, capacity_bytes=1 << 26)
    assert ":" in addr
    assert peer.probe()
    root = "memory://wire-spawn/run/step_0"
    data = b"d" * 4096
    assert ht_tier.put_replica(
        root + "/0/s/w", 1, data, ht_tier.payload_tag(data), root
    )
    assert peer.get(root + "/0/s/w").data == data
    ht_tier.kill_host(1)
    assert proc.poll() == -9  # a REAL SIGKILL, not a flag flip
    with pytest.raises(ht_tier.HostLostError):
        ht_tier.get_replica(root + "/0/s/w", 1)


def test_restore_from_peer_after_real_kill(_fresh_wire):
    """k=3 with two real peer subprocesses; k-1 losses (one real
    SIGKILL + the local host) leave the take restorable bit-exact from
    the surviving peer process."""
    proc1, _, _ = spawn_peer(host_id=1, capacity_bytes=1 << 26)
    proc2, _, _ = spawn_peer(host_id=2, capacity_bytes=1 << 26)
    path = "memory://wire-kill/run/step_0"
    with hottier.hot_tier(rank=0, world=3, k=3, drain="manual"):
        snap = Snapshot.take(path, _state(13.0))
        ht_tier.kill_host(1)  # real SIGKILL
        ht_tier.kill_host(0)  # local host flag — k-1 = 2 losses total
        assert proc1.poll() == -9
        target = _target()
        snap.restore(target)
        _assert_restored(target, 13.0)
        rt = hottier.runtime()
        assert rt.stats_snapshot()["hot_objects"] >= 1
        assert proc2.poll() is None  # the survivor served it
        hottier.drain_now()


def test_lose_host_aborts_blocked_socket_read(_fresh_wire, monkeypatch):
    """The lose_host contract: a socket read blocked on a hung peer
    (SIGSTOP — the process is alive, the socket open, nothing answers)
    observes the loss promptly when kill_host aborts the host's
    in-flight connections, instead of hanging out its full deadline."""
    monkeypatch.setenv("TPUSNAPSHOT_REPLICATION_DEADLINE_S", "30")
    monkeypatch.setenv("TPUSNAPSHOT_REPLICATION_RETRY_BUDGET_S", "60")
    proc, _, peer = spawn_peer(host_id=1, capacity_bytes=1 << 26)
    root = "memory://wire-hang/run/step_0"
    data = b"h" * 4096
    assert ht_tier.put_replica(
        root + "/0/s/w", 1, data, ht_tier.payload_tag(data), root
    )
    proc.send_signal(signal.SIGSTOP)  # the peer hangs, socket stays open
    failures = []
    done = threading.Event()

    def _blocked_get():
        t0 = time.monotonic()
        try:
            ht_tier.get_replica(root + "/0/s/w", 1)
        except ht_tier.HostLostError:
            failures.append(time.monotonic() - t0)
        done.set()

    thread = threading.Thread(target=_blocked_get, daemon=True)
    thread.start()
    time.sleep(0.5)  # let the RPC block on the hung peer
    ht_tier.kill_host(1)  # SIGKILL + in-flight connection abort
    assert done.wait(timeout=10.0), "blocked read never observed the loss"
    thread.join(timeout=5.0)
    assert failures and failures[0] < 10.0  # far below the 30s deadline


def _loss_matrix_point(nth):
    """One host-loss × crash-point matrix cell: a REAL peer subprocess
    is SIGKILLed at the nth hottier.replicate boundary; the take must
    either ack honestly (write-through when k cannot be met) and
    restore bit-exact, with every obligation retired by drain_now."""
    spawn_peer(host_id=1, capacity_bytes=1 << 26)
    path = f"memory://wire-matrix/run/step_{nth}"
    sched = fl.FaultSchedule().lose_host(
        1, op="hottier.replicate", nth=nth
    )
    state = {
        "a": StateDict(x=jnp.full((512,), 1.0 + nth, dtype=jnp.float32)),
        "b": StateDict(y=jnp.full((512,), 2.0 + nth, dtype=jnp.float32)),
    }
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        t0 = time.monotonic()
        with fl.inject(sched) as ctl:
            snap = Snapshot.take(path, state)
        elapsed = time.monotonic() - t0
        assert elapsed < 30.0, f"take hung {elapsed:.1f}s at nth={nth}"
        assert ctl.fault_counts().get("hostloss") == 1
        rt = hottier.runtime()
        stats = rt.stats_snapshot()
        # Every object acked: either at k replicas or via write-through.
        assert stats["write_through"] + stats["replicas"] >= 2
        target = {
            "a": StateDict(x=jnp.zeros((512,), dtype=jnp.float32)),
            "b": StateDict(y=jnp.zeros((512,), dtype=jnp.float32)),
        }
        snap.restore(target)
        np.testing.assert_array_equal(
            np.asarray(target["a"]["x"]), 1.0 + nth
        )
        np.testing.assert_array_equal(
            np.asarray(target["b"]["y"]), 2.0 + nth
        )
        hottier.drain_now()
        assert hottier.wait_drained(timeout_s=30.0)
    # The committed take is durable: restorable with the tier OFF too.
    hottier.reset_hot_tier()
    target2 = {
        "a": StateDict(x=jnp.zeros((512,), dtype=jnp.float32)),
        "b": StateDict(y=jnp.zeros((512,), dtype=jnp.float32)),
    }
    Snapshot(path).restore(target2)
    np.testing.assert_array_equal(np.asarray(target2["a"]["x"]), 1.0 + nth)
    np.testing.assert_array_equal(np.asarray(target2["b"]["y"]), 2.0 + nth)


@pytest.mark.parametrize("nth", [1, 2, 3])
def test_real_sigkill_loss_matrix_stride(_fresh_wire, nth):
    """Fast stride subset of the host-loss × crash-point matrix across
    REAL process boundaries (2 payload objects × k=2 = 4 replicate
    boundaries; the full enumeration runs under -m slow)."""
    _loss_matrix_point(nth)


@pytest.mark.slow
@pytest.mark.parametrize("nth", [4])
def test_real_sigkill_loss_matrix_full(_fresh_wire, nth):
    """The remaining matrix cells (every replicate boundary of the
    2-object take)."""
    _loss_matrix_point(nth)


# --------------------------------------------------------------- plumbing


def test_addrs_env_registers_peers(_fresh_wire, monkeypatch):
    """TPUSNAPSHOT_HOT_TIER_ADDRS is the production address book:
    enable_hot_tier registers the named peers and replication crosses
    the wire with no explicit wiring."""
    server, _ = start_local_peer(1, register=False)
    _fresh_wire.append(server)
    monkeypatch.setenv("TPUSNAPSHOT_HOT_TIER_ADDRS", f"1={server.addr}")
    path = "memory://wire-addrs/run/step_0"
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        assert hottier.remote_host(1) is not None
        before = transport.wire_stats_snapshot()
        Snapshot.take(path, _state(4.0))
        after = transport.wire_stats_snapshot()
        assert after["pushes"] - before["pushes"] == 1
        hottier.drain_now()


def test_replication_ledger_field_and_metrics(_fresh_wire):
    """The per-take ledger digest carries tier.replication with
    delta_ratio; the five replication counters move."""
    from torchsnapshot_tpu import telemetry
    from torchsnapshot_tpu.telemetry import ledger as runledger
    from torchsnapshot_tpu.telemetry import metrics as m

    _local_peer(_fresh_wire, 1)
    path = "memory://wire-ledger/run/step_0"
    c0 = telemetry.counter(m.HOT_TIER_REPLICATION_PUSHES).value
    b0 = telemetry.counter(m.HOT_TIER_REPLICATION_BYTES).value
    with hottier.hot_tier(rank=0, world=2, k=2, drain="manual"):
        Snapshot.take(path, _state(8.0))
        hottier.drain_now()
    assert telemetry.counter(m.HOT_TIER_REPLICATION_PUSHES).value == c0 + 1
    assert telemetry.counter(m.HOT_TIER_REPLICATION_BYTES).value >= b0 + 8192
    records, _ = runledger.read_records(path)
    takes = [r for r in records if r.get("kind") == "take"]
    assert takes, "take digest missing from ledger"
    rep = (takes[-1].get("tier") or {}).get("replication")
    assert rep is not None
    assert rep["pushes"] == 1
    assert rep["delta_ratio"] is not None
