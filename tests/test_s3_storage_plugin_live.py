"""Live S3 bucket integration tests (env-gated, skipped in CI).

Parity with the reference's real-bucket suite
(reference tests/test_s3_storage_plugin.py:25): a ~100 MB payload
round-trips through both the raw plugin and the Snapshot API. Gated like
the reference — set

    TPUSNAPSHOT_ENABLE_AWS_TEST=1 TPUSNAPSHOT_AWS_TEST_BUCKET=<bucket>

with ambient AWS credentials. Skips cleanly otherwise.
"""

import asyncio
import os
import uuid

import numpy as np
import pytest

import jax.numpy as jnp

_GATE = os.environ.get("TPUSNAPSHOT_ENABLE_AWS_TEST") == "1"
_BUCKET = os.environ.get("TPUSNAPSHOT_AWS_TEST_BUCKET")

pytestmark = pytest.mark.skipif(
    not (_GATE and _BUCKET),
    reason=(
        "live S3 test gated: set TPUSNAPSHOT_ENABLE_AWS_TEST=1 and "
        "TPUSNAPSHOT_AWS_TEST_BUCKET"
    ),
)

_PAYLOAD_BYTES = 100 * 1024 * 1024


@pytest.fixture
def s3_prefix():
    prefix = f"tpusnapshot-test/{uuid.uuid4().hex}"
    yield f"{_BUCKET}/{prefix}"
    try:
        from torchsnapshot_tpu.storage_plugins.s3 import S3StoragePlugin

        plugin = S3StoragePlugin(f"{_BUCKET}/{prefix}")
        leftovers = asyncio.run(plugin.list_prefix("")) or []
        for path in leftovers:
            asyncio.run(plugin.delete(path))
        plugin.close()
    except Exception:
        pass


def test_raw_plugin_large_object_round_trip(s3_prefix):
    from torchsnapshot_tpu.io_types import IOReq, io_payload
    from torchsnapshot_tpu.storage_plugins.s3 import S3StoragePlugin

    plugin = S3StoragePlugin(s3_prefix)
    payload = np.random.default_rng(0).bytes(_PAYLOAD_BYTES)
    asyncio.run(plugin.write(IOReq(path="blob", data=payload)))

    out = IOReq(path="blob")
    asyncio.run(plugin.read(out))
    assert bytes(io_payload(out)) == payload

    ranged = IOReq(path="blob", byte_range=(12345, 123456))
    asyncio.run(plugin.read(ranged))
    assert bytes(io_payload(ranged)) == payload[12345:123456]

    asyncio.run(plugin.delete("blob"))
    plugin.close()


def test_snapshot_api_round_trip(s3_prefix):
    from torchsnapshot_tpu import Snapshot, StateDict

    w = jnp.arange(_PAYLOAD_BYTES // 4, dtype=jnp.float32)
    url = f"s3://{s3_prefix}/snap"
    Snapshot.take(url, {"s": StateDict(w=w)})

    target = StateDict(w=jnp.zeros_like(w))
    Snapshot(url).restore({"s": target})
    np.testing.assert_array_equal(np.asarray(target["w"]), np.asarray(w))
    Snapshot(url).delete(sweep=True)
