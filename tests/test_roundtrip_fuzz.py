"""Seeded whole-stack round-trip fuzz: random nested app states through
take -> restore must come back bit-exact. Exercises flatten/manifest/
io_preparer/scheduler/storage jointly on shapes no hand-written test
enumerates (the resharding fuzz covers mesh geometry; this covers
container/dtype geometry)."""

import random
from collections import OrderedDict

import numpy as np
import pytest

import jax.numpy as jnp

from torchsnapshot_tpu import Snapshot

_DTYPES = [
    np.float32,
    np.float16,
    np.int32,
    np.int8,
    np.uint16,
    np.bool_,
]


def _rand_leaf(rng: random.Random):
    kind = rng.random()
    if kind < 0.45:
        dtype = rng.choice(_DTYPES)
        ndim = rng.randint(0, 3)
        shape = tuple(rng.randint(1, 5) for _ in range(ndim))
        n = int(np.prod(shape)) if shape else 1
        if dtype == np.bool_:
            arr = (np.arange(n) % 2 == 0).reshape(shape)
        else:
            arr = (np.arange(n) % 120).astype(dtype).reshape(shape)
        return jnp.asarray(arr) if rng.random() < 0.5 else arr
    if kind < 0.55:
        arr = np.arange(8, dtype=np.float32).view(np.uint16)[:4]
        return arr.copy()  # odd strides/dtype views normalized to copy
    if kind < 0.7:
        return rng.randint(-(10**12), 10**12)  # primitive int
    if kind < 0.8:
        return rng.choice([True, False, None, 2.5, -0.0, "häłlo/☃"])
    if kind < 0.9:
        return {"frozen", "set", rng.randint(0, 9)}  # arbitrary object
    return bytes([rng.randint(0, 255) for _ in range(rng.randint(0, 9))])


def _rand_tree(rng: random.Random, depth: int):
    if depth == 0 or rng.random() < 0.2:
        return _rand_leaf(rng)
    kind = rng.random()
    n = rng.randint(1, 3) if rng.random() < 0.8 else 0
    if kind < 0.4:
        return {f"k{i}": _rand_tree(rng, depth - 1) for i in range(n)}
    if kind < 0.6:
        return OrderedDict(
            (f"o{i}", _rand_tree(rng, depth - 1)) for i in range(n)
        )
    if kind < 0.8:
        return [_rand_tree(rng, depth - 1) for _ in range(n)]
    return tuple(_rand_tree(rng, depth - 1) for _ in range(n))


def _assert_tree_equal(a, b, path="root"):
    # Exact type equality (bool-vs-int and friends matter for resume);
    # jax in / numpy out is the one sanctioned divergence — both carry
    # .shape and compare as arrays below.
    assert type(a) is type(b) or (
        hasattr(a, "shape") and hasattr(b, "shape")
    ), f"{path}: {type(a)} vs {type(b)}"
    if isinstance(a, (dict, OrderedDict)):
        assert list(a.keys()) == list(b.keys()), path
        for k in a:
            _assert_tree_equal(a[k], b[k], f"{path}/{k}")
    elif isinstance(a, (list, tuple)):
        assert len(a) == len(b), path
        for i, (x, y) in enumerate(zip(a, b)):
            _assert_tree_equal(x, y, f"{path}/{i}")
    elif hasattr(a, "shape"):
        an, bn = np.asarray(a), np.asarray(b)
        assert an.dtype == bn.dtype, f"{path}: {an.dtype} vs {bn.dtype}"
        assert an.shape == bn.shape, f"{path}: {an.shape} vs {bn.shape}"
        np.testing.assert_array_equal(an, bn, err_msg=path)
    else:
        assert a == b, f"{path}: {a!r} vs {b!r}"
        if isinstance(a, float):  # -0.0 vs 0.0: == cannot tell
            assert np.signbit(a) == np.signbit(b), path


class _Holder:
    def __init__(self, sd):
        self.sd = sd

    def state_dict(self):
        return self.sd

    def load_state_dict(self, sd):
        self.sd = sd


def test_generator_covers_every_leaf_kind():
    """The fuzz is only as good as what the seeds actually generate:
    every _rand_leaf branch must fire at least once across the seed
    set (code-review r3: an earlier parameterization left str/bool/
    float/np.bool_ leaves never generated)."""
    kinds = set()

    def walk(t):
        if isinstance(t, (dict, OrderedDict)):
            for v in t.values():
                walk(v)
        elif isinstance(t, (list, tuple)) and not isinstance(t, bytes):
            for v in t:
                walk(v)
        elif hasattr(t, "shape"):
            kinds.add(f"array:{np.asarray(t).dtype}")
        else:
            kinds.add(f"scalar:{type(t).__name__}")

    for seed in range(_N_SEEDS):
        walk(_rand_tree(random.Random(seed), depth=3))
    for want in (
        "scalar:int",
        "scalar:float",
        "scalar:bool",
        "scalar:str",
        "scalar:bytes",
        "scalar:set",
        "scalar:NoneType",
        "array:float32",
        "array:bool",
    ):
        assert want in kinds, f"seeds never generate {want}: {sorted(kinds)}"


_N_SEEDS = 16


@pytest.mark.parametrize("split_threshold", [None, 64])
@pytest.mark.parametrize("seed", range(_N_SEEDS))
def test_random_tree_roundtrip(seed, split_threshold, tmp_path, monkeypatch):
    # split_threshold=64 forces nearly every array restore through the
    # split-read paths (host reassembly for numpy templates, device
    # streaming for jax templates) across the same geometry.
    if split_threshold is not None:
        monkeypatch.setenv(
            "TPUSNAPSHOT_PARALLEL_READ_THRESHOLD", str(split_threshold)
        )
    rng = random.Random(seed)
    tree = {"root": _rand_tree(rng, depth=3)}
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": _Holder(tree)})

    # The documented restore contract: a holder with the SAME structure
    # but zeroed/SENTINEL leaves — a restore that silently skipped any
    # leaf must fail the comparison, not pass it vacuously.
    def zero_like(x):
        if hasattr(x, "shape"):
            arr = np.asarray(x)
            # Nonzero fill: an all-zero original (0-d/size-1 arange
            # arrays are) must still differ from its sentinel. Jax-ness
            # is preserved: a jax template restores through the device
            # path (incl. streaming under a tiny split threshold), a
            # numpy one through host reassembly.
            filled = np.full(arr.shape, 1, arr.dtype)
            return jnp.asarray(filled) if isinstance(x, jnp.ndarray) else filled
        if isinstance(x, bool):
            return not x
        if isinstance(x, int):
            return x - 12345
        if isinstance(x, float):
            return 123.456
        if isinstance(x, str):
            return "SENTINEL"
        if isinstance(x, bytes):
            return b"SENTINEL"
        if x is None:
            return None  # no distinguishable sentinel
        # A set: an object LEAF (a list sentinel would flatten as a
        # container and diverge the template structure).
        return {"WRONG_OBJECT"}

    def map_tree(t):
        if isinstance(t, (dict, OrderedDict)):
            return type(t)((k, map_tree(v)) for k, v in t.items())
        if isinstance(t, list):
            return [map_tree(v) for v in t]
        if isinstance(t, tuple):
            return tuple(map_tree(v) for v in t)
        return zero_like(t)

    target = _Holder({"root": map_tree(tree["root"])})
    Snapshot(path).restore({"m": target})
    _assert_tree_equal(tree, target.sd, "m")
