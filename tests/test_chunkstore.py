"""Content-addressed chunk store (torchsnapshot_tpu/chunkstore.py):
cross-take dedup, sub-leaf dedup, codec wiring, GC, telemetry, and the
snapserve chunk-hash cache keying."""

import glob
import json
import os
import uuid

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torchsnapshot_tpu import Snapshot, chunkstore, codecs, telemetry
from torchsnapshot_tpu.manager import CheckpointManager
from torchsnapshot_tpu.state_dict import StateDict
from torchsnapshot_tpu.telemetry import ledger as runledger


@pytest.fixture(autouse=True)
def _chunk_env(monkeypatch):
    # Deterministic GC in tests: no age guards; small chunks so tiny
    # payloads still split; no min-leaf floor.
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    monkeypatch.setenv("TPUSNAPSHOT_REFS_MIN_AGE_S", "0")
    monkeypatch.setenv("TPUSNAPSHOT_CHUNK_BYTES", "4096")
    monkeypatch.setenv("TPUSNAPSHOT_CHUNK_MIN_BYTES", "0")


def _state(seed=0, emb_rows=256):
    rng = np.random.RandomState(seed)
    return {
        "m": StateDict(
            w=jnp.asarray(rng.randn(64, 64).astype(np.float32)),
            emb=jnp.asarray(rng.randn(emb_rows, 32).astype(np.float32)),
        )
    }


def _zeros_like(state):
    return {
        "m": StateDict(
            **{
                k: jnp.zeros(v.shape, v.dtype)
                for k, v in state["m"].items()
            }
        )
    }


def _store_objects(root_dir):
    return sorted(glob.glob(f"{root_dir}/.chunkstore/objects/*/*"))


def _assert_restores(snapshot, expected):
    t = _zeros_like(expected)
    snapshot.restore(t)
    for k, v in expected["m"].items():
        assert np.array_equal(np.asarray(t["m"][k]), np.asarray(v)), k


class TestDedup:
    def test_unchanged_retake_stores_nothing_new(self, tmp_path):
        d = str(tmp_path)
        state = _state()
        s1 = Snapshot.take(f"{d}/step-1", state, chunks=True)
        n1 = len(_store_objects(d))
        assert n1 > 0
        s2 = Snapshot.take(f"{d}/step-2", state, chunks=True)
        assert len(_store_objects(d)) == n1
        _assert_restores(s1, state)
        _assert_restores(s2, state)
        assert s1.verify() == {} and s2.verify() == {}

    def test_partially_dirty_leaf_stores_only_touched_chunks(
        self, tmp_path
    ):
        d = str(tmp_path)
        state = _state()
        Snapshot.take(f"{d}/step-1", state, chunks=True)
        n1 = len(_store_objects(d))
        emb = np.asarray(state["m"]["emb"]).copy()
        emb[:32] += 1.0  # 32 rows * 32 cols * 4 B = 4 KiB = 1 chunk
        state["m"]["emb"] = jnp.asarray(emb)
        s2 = Snapshot.take(f"{d}/step-2", state, chunks=True)
        new = len(_store_objects(d)) - n1
        assert 1 <= new <= 2, f"expected ~1 dirty chunk, stored {new}"
        _assert_restores(s2, state)

    def test_identical_leaves_share_chunks_within_one_take(
        self, tmp_path
    ):
        d = str(tmp_path)
        a = jnp.asarray(
            np.random.RandomState(1).randn(64, 64).astype(np.float32)
        )
        state = {"m": StateDict(x=a, y=a)}
        s = Snapshot.take(f"{d}/step-1", state, chunks=True)
        # Both leaves reference one set of chunk objects.
        keys_x = {
            r["k"]
            for e in s.get_manifest().values()
            if getattr(e, "chunks", None)
            for r in e.chunks
        }
        assert len(_store_objects(d)) == len(keys_x)
        t = {"m": StateDict(x=jnp.zeros_like(a), y=jnp.zeros_like(a))}
        s.restore(t)
        assert np.array_equal(np.asarray(t["m"]["x"]), np.asarray(a))
        assert np.array_equal(np.asarray(t["m"]["y"]), np.asarray(a))

    def test_memory_backend_round_trip(self):
        root = f"memory://cstest-{uuid.uuid4().hex[:8]}/run"
        state = _state(3)
        s1 = Snapshot.take(f"{root}/step-1", state, chunks=True)
        s2 = Snapshot.take(f"{root}/step-2", state, chunks=True)
        _assert_restores(s2, state)
        assert s1.verify() == {} and s2.verify() == {}

    def test_sharded_leaves_chunk_and_reshard(self):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        root = f"memory://cstest-{uuid.uuid4().hex[:8]}/run"
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("x",))
        arr = jax.device_put(
            jnp.arange(4096, dtype=jnp.float32).reshape(64, 64),
            NamedSharding(mesh, P("x")),
        )
        state = {"m": StateDict(w=arr)}
        Snapshot.take(f"{root}/step-1", state, chunks=True)
        s2 = Snapshot.take(f"{root}/step-2", state, chunks=True)
        # Restore onto a DIFFERENT mesh: chunk-stored shard objects
        # still reshard through the overlap machinery.
        mesh2 = Mesh(np.array(jax.devices()[:2]).reshape(2), ("x",))
        t = {
            "m": StateDict(
                w=jax.device_put(
                    jnp.zeros((64, 64), jnp.float32),
                    NamedSharding(mesh2, P(None, "x")),
                )
            )
        }
        s2.restore(t)
        assert np.array_equal(np.asarray(t["m"]["w"]), np.asarray(arr))

    def test_async_take_chunks(self, tmp_path):
        d = str(tmp_path)
        state = _state(5)
        p = Snapshot.async_take(f"{d}/step-1", state, chunks=True)
        s1 = p.wait()
        n1 = len(_store_objects(d))
        p2 = Snapshot.async_take(f"{d}/step-2", state, chunks=True)
        s2 = p2.wait()
        assert len(_store_objects(d)) == n1
        _assert_restores(s2, state)
        assert s1.verify() == {}
        # No intents survive the commits.
        assert not glob.glob(f"{d}/.chunkstore/intents/*")

    @pytest.mark.parametrize(
        "dtype",
        ["float32", "bfloat16", "float16", "int32", "uint8", "bool"],
    )
    def test_dtype_matrix_round_trip(self, tmp_path, dtype):
        d = str(tmp_path)
        rng = np.random.RandomState(22)
        if dtype == "bool":
            host = rng.rand(96, 96) > 0.5
            arr = jnp.asarray(host)
        elif dtype in ("int32", "uint8"):
            arr = jnp.asarray(
                rng.randint(0, 100, (96, 96)).astype(dtype)
            )
        else:
            arr = jnp.asarray(rng.randn(96, 96).astype(np.float32)).astype(
                dtype
            )
        state = {"m": StateDict(x=arr)}
        Snapshot.take(f"{d}/step-1", state, chunks=True)
        n1 = len(_store_objects(d))
        s2 = Snapshot.take(f"{d}/step-2", state, chunks=True)
        assert len(_store_objects(d)) == n1, f"{dtype}: retake re-stored"
        t = {"m": StateDict(x=jnp.zeros(arr.shape, arr.dtype))}
        s2.restore(t)
        assert np.array_equal(np.asarray(t["m"]["x"]), np.asarray(arr))
        assert s2.verify() == {}

    def test_prng_key_leaf_round_trip(self, tmp_path):
        d = str(tmp_path)
        keys = jax.random.split(jax.random.key(3), 512)
        state = {"m": StateDict(k=keys)}
        s = Snapshot.take(f"{d}/step-1", state, chunks=True)
        t = {"m": StateDict(k=jax.random.split(jax.random.key(9), 512))}
        s.restore(t)
        assert np.array_equal(
            np.asarray(jax.random.key_data(t["m"]["k"])),
            np.asarray(jax.random.key_data(keys)),
        )

    def test_rootless_path_degrades_to_plain(self):
        root = f"memory://bare-{uuid.uuid4().hex[:8]}"
        state = _state(6)
        s = Snapshot.take(root, state, chunks=True)  # no parent dir
        _assert_restores(s, state)
        assert not chunkstore.manifest_has_chunks(s.get_manifest())

    def test_composes_with_leaf_incremental(self, tmp_path):
        # A PLAIN fingerprinted base + a chunked base= take: unchanged
        # w leaf-dedups (cheaper — one @base ref, no chunk pass), the
        # partially-dirty emb falls through to sub-leaf chunk dedup.
        d = str(tmp_path)
        state = _state(7)
        s1 = Snapshot.take(f"{d}/step-1", state, fingerprint=True)
        emb = np.asarray(state["m"]["emb"]).copy()
        emb[:32] += 1.0
        state["m"]["emb"] = jnp.asarray(emb)
        s2 = Snapshot.take(f"{d}/step-2", state, base=s1, chunks=True)
        manifest = s2.get_manifest()
        w = manifest["0/m/w"]
        assert w.base is not None and not w.chunks
        emb_entry = manifest["0/m/emb"]
        assert emb_entry.chunks
        _assert_restores(s2, state)

    def test_chunked_base_falls_through_to_chunk_dedup(self, tmp_path):
        # A CHUNK-BACKED base entry is never leaf-borrowed (there is no
        # single object to reference); the chunk pass dedups it per
        # chunk against the store instead — same bytes saved.
        d = str(tmp_path)
        state = _state(7)
        s1 = Snapshot.take(f"{d}/step-1", state, chunks=True)
        n1 = len(_store_objects(d))
        s2 = Snapshot.take(f"{d}/step-2", state, base=s1, chunks=True)
        assert len(_store_objects(d)) == n1  # nothing re-stored
        w = s2.get_manifest()["0/m/w"]
        assert w.chunks, "chunk dedup covers the chunked-base leaf"
        _assert_restores(s2, state)


class TestCodecs:
    def test_lossless_codec_round_trip(self, tmp_path):
        d = str(tmp_path)
        state = _state(8)
        s = Snapshot.take(
            f"{d}/step-1", state, chunks=True, codec=codecs.best_lossless()
        )
        _assert_restores(s, state)
        assert s.verify() == {}
        # Codec recorded per chunk in the manifest.
        recs = [
            r
            for e in s.get_manifest().values()
            if getattr(e, "chunks", None)
            for r in e.chunks
        ]
        assert recs and all(r["c"] == codecs.best_lossless() for r in recs)

    def test_int8_opt_in_only(self, tmp_path):
        d = str(tmp_path)
        rng = np.random.RandomState(9)
        state = {
            "m": StateDict(w=jnp.asarray(rng.randn(64, 64).astype(np.float32))),
            "opt": StateDict(mu=jnp.asarray(rng.randn(64, 64).astype(np.float32))),
        }
        s = Snapshot.take(
            f"{d}/step-1",
            state,
            chunks=True,
            codec={"opt/*": "int8", "*": "zlib"},
        )
        t = {
            "m": StateDict(w=jnp.zeros((64, 64), jnp.float32)),
            "opt": StateDict(mu=jnp.zeros((64, 64), jnp.float32)),
        }
        s.restore(t)
        # Non-opted leaf bit-exact; opted leaf within tolerance only.
        assert np.array_equal(
            np.asarray(t["m"]["w"]), np.asarray(state["m"]["w"])
        )
        mu = np.asarray(state["opt"]["mu"])
        err = np.abs(np.asarray(t["opt"]["mu"]) - mu).max()
        assert 0 < err <= codecs.quant_error_bound(mu)
        for path, e in s.get_manifest().items():
            for r in getattr(e, "chunks", None) or []:
                if "/opt/" in f"/{path}":
                    assert r["c"] == "int8", path
                else:
                    assert r["c"] != "int8", path
        assert s.verify() == {}

    def test_int8_never_aliases_lossless_chunks(self, tmp_path):
        # The same bytes stored through different codecs must get
        # DIFFERENT content keys, or a non-opted leaf could silently
        # reference a quantized object.
        d = str(tmp_path)
        a = jnp.asarray(
            np.random.RandomState(10).randn(64, 64).astype(np.float32)
        )
        state = {
            "m": StateDict(w=a),
            "opt": StateDict(mu=a),  # identical bytes, lossy codec
        }
        s = Snapshot.take(
            f"{d}/step-1", state, chunks=True, codec={"opt/*": "int8"}
        )
        t = {
            "m": StateDict(w=jnp.zeros_like(a)),
            "opt": StateDict(mu=jnp.zeros_like(a)),
        }
        s.restore(t)
        assert np.array_equal(np.asarray(t["m"]["w"]), np.asarray(a))
        assert not np.array_equal(np.asarray(t["opt"]["mu"]), np.asarray(a))

    def test_verify_device_skips_lossy_entries(self, tmp_path):
        d = str(tmp_path)
        state = {
            "opt": StateDict(
                mu=jnp.asarray(
                    np.random.RandomState(11)
                    .randn(64, 64)
                    .astype(np.float32)
                )
            )
        }
        s = Snapshot.take(
            f"{d}/step-1", state, chunks=True, codec={"opt/*": "int8"}
        )
        t = {"opt": StateDict(mu=jnp.zeros((64, 64), jnp.float32))}
        # Must not raise: quantized leaves skip fingerprint verification.
        s.restore(t, verify_device=True)


class TestIntegrity:
    def test_verify_detects_corrupt_chunk(self, tmp_path):
        d = str(tmp_path)
        state = _state(12)
        s = Snapshot.take(f"{d}/step-1", state, chunks=True)
        victim = _store_objects(d)[0]
        raw = bytearray(open(victim, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        open(victim, "wb").write(bytes(raw))
        problems = s.verify()
        assert problems, "corrupt chunk object must fail verify()"
        with pytest.raises(Exception):
            _assert_restores(s, state)

    def test_copy_to_materializes_self_contained(self, tmp_path):
        d = str(tmp_path)
        state = _state(13)
        s = Snapshot.take(
            f"{d}/step-1", state, chunks=True, codec="zlib"
        )
        dest = f"{d}/copies/flat"
        c = s.copy_to(dest)
        md = c.get_manifest()
        assert not chunkstore.manifest_has_chunks(md)
        assert c.verify() == {}
        _assert_restores(c, state)
        # Fully independent: dropping the whole source run (store
        # included) leaves the copy restorable.
        import shutil

        shutil.rmtree(f"{d}/.chunkstore")
        shutil.rmtree(f"{d}/step-1")
        _assert_restores(Snapshot(dest), state)

    def test_read_object_on_chunked_entry(self, tmp_path):
        d = str(tmp_path)
        state = _state(14)
        s = Snapshot.take(f"{d}/step-1", state, chunks=True)
        got = s.read_object("m/emb")
        assert np.array_equal(
            np.asarray(got), np.asarray(state["m"]["emb"])
        )


class TestGC:
    def test_delete_keeps_shared_frees_exclusive(self, tmp_path):
        d = str(tmp_path)
        state = _state(15)
        s1 = Snapshot.take(f"{d}/step-1", state, chunks=True)
        emb = np.asarray(state["m"]["emb"]).copy()
        emb[:32] += 1.0
        state["m"]["emb"] = jnp.asarray(emb)
        s2 = Snapshot.take(f"{d}/step-2", state, chunks=True)
        n_all = len(_store_objects(d))
        s1.delete()
        # Exactly step-1's exclusive chunk(s) freed; the shared
        # majority survives for step-2.
        remaining = _store_objects(d)
        assert len(remaining) < n_all
        assert s2.verify() == {}
        _assert_restores(s2, state)
        s2.delete()
        assert not _store_objects(d)
        assert not glob.glob(f"{d}/.chunkstore/refs/*")

    def test_reconcile_reclaims_orphaned_chunks(self, tmp_path):
        d = str(tmp_path)
        base = f"{d}"
        state = _state(16)
        mgr = CheckpointManager(base, chunks=True)
        mgr.save(1, state)
        # Fake a crashed take: an orphan chunk object + a ref doc whose
        # snapshot never committed + a stale intent.
        orphan = f"{d}/.chunkstore/objects/ff/xs128:{'f' * 32}-4096-raw"
        os.makedirs(os.path.dirname(orphan), exist_ok=True)
        open(orphan, "wb").write(b"\0" * 4096)
        stale_ref = f"{d}/.chunkstore/refs/deadbeefdeadbeef"
        open(stale_ref, "w").write(
            json.dumps({"path": "rel:step-99", "chunks": ["xs128:" + "f" * 32 + "-4096-raw"]})
        )
        stale_intent = f"{d}/.chunkstore/intents/feedface-r0"
        os.makedirs(os.path.dirname(stale_intent), exist_ok=True)
        open(stale_intent, "w").write("{}")
        mgr.reconcile()
        assert not os.path.exists(orphan)
        assert not os.path.exists(stale_ref)
        assert not os.path.exists(stale_intent)
        # The committed step's chunks are untouched.
        s1 = Snapshot(f"{base}/step-1")
        assert s1.verify() == {}
        _assert_restores(s1, state)

    def test_young_age_guard_defers_freeing(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "3600")
        d = str(tmp_path)
        state = _state(17)
        mgr = CheckpointManager(d, chunks=True)
        mgr.save(1, state)
        orphan = f"{d}/.chunkstore/objects/ff/xs128:{'f' * 32}-4096-raw"
        os.makedirs(os.path.dirname(orphan), exist_ok=True)
        open(orphan, "wb").write(b"\0" * 4096)
        mgr.reconcile()
        assert os.path.exists(orphan), "young orphan must be spared"

    def test_bad_codec_spec_leaves_no_store_debris(self, tmp_path):
        # Spec validation precedes ANY store side-effect: a failed take
        # must not strand an intent marker that defers the run's chunk
        # GC for an age-guard window.
        d = str(tmp_path)
        state = _state(24)
        with pytest.raises(ValueError):
            Snapshot.take(
                f"{d}/step-1", state, chunks=True, codec="not-a-codec"
            )
        with pytest.raises(ValueError):
            Snapshot.take(
                f"{d}/step-1", state, chunks=True, codec="int8"
            )  # lossy without a glob
        assert not glob.glob(f"{d}/.chunkstore/intents/*")
        assert not glob.glob(f"{d}/.chunkstore/objects/*/*")

    def test_gc_fails_closed_on_transient_metadata_error(
        self, tmp_path, monkeypatch
    ):
        # A ref doc whose snapshot's metadata read fails TRANSIENTLY
        # (not not-found) might be protecting a committed snapshot:
        # delete-GC must free NOTHING that pass.
        d = str(tmp_path)
        state = _state(25)
        s1 = Snapshot.take(f"{d}/step-1", state, chunks=True)
        s2 = Snapshot.take(f"{d}/step-2", state, chunks=True)
        n_before = len(_store_objects(d))

        import torchsnapshot_tpu.snapshot as snap_mod

        async def _boom(url):
            raise RuntimeError("injected transient metadata failure")

        monkeypatch.setattr(snap_mod, "_aread_metadata_at", _boom)
        s1.delete()
        monkeypatch.undo()
        # Shared chunks survived the blinded GC pass; step-2 healthy.
        assert len(_store_objects(d)) == n_before
        assert s2.verify() == {}
        _assert_restores(s2, state)
        # With visibility restored, reconcile converges: exactly
        # step-2's chunks remain.
        chunkstore.reconcile_store(d)
        live = chunkstore.chunk_keys_of(s2.get_manifest())
        assert {
            p.rsplit("/", 1)[-1] for p in _store_objects(d)
        } == live

    def test_retake_ref_overwrite_cannot_unprotect_committed(
        self, tmp_path
    ):
        # A re-take to the SAME path overwrites the ref doc with its
        # new key set before its own metadata commit; if it crashes
        # there, GC must still protect the committed old snapshot's
        # chunks (the committed MANIFEST is the authority, not the ref
        # doc's key list).
        d = str(tmp_path)
        state = _state(26)
        Snapshot.take(f"{d}/step-1", state, chunks=True)
        s_target = Snapshot.take(f"{d}/step-2", state, chunks=True)
        old_keys = chunkstore.chunk_keys_of(s_target.get_manifest())
        # Simulate the crashed re-take: overwrite step-2's ref doc
        # with a DISJOINT key set (its metadata still references
        # old_keys).
        ref = (
            f"{d}/.chunkstore/refs/"
            f"{chunkstore.ref_doc_name(f'{d}/step-2')}"
        )
        open(ref, "w").write(
            json.dumps(
                {
                    "path": "rel:step-2",
                    "chunks": ["xs128:" + "e" * 32 + "-4096-raw"],
                }
            )
        )
        Snapshot(f"{d}/step-1").delete()
        chunkstore.reconcile_store(d)
        assert s_target.verify() == {}, s_target.verify()
        on_disk = {
            p.rsplit("/", 1)[-1] for p in _store_objects(d)
        }
        assert old_keys <= on_disk
        _assert_restores(Snapshot(f"{d}/step-2"), state)

    def test_prune_via_manager_gc(self, tmp_path):
        d = str(tmp_path)
        state = _state(18)
        mgr = CheckpointManager(d, max_to_keep=2, chunks=True)
        for step in range(1, 5):
            emb = np.asarray(state["m"]["emb"]).copy()
            emb[: 32 * step % 224] += 0.5
            state["m"]["emb"] = jnp.asarray(emb)
            mgr.save(step, state)
        assert mgr.all_steps() == [3, 4]
        # Every surviving chunk is referenced by a retained step.
        live = set()
        for step in (3, 4):
            live |= chunkstore.chunk_keys_of(
                Snapshot(f"{d}/step-{step}").get_manifest()
            )
        on_disk = {p.rsplit("/", 1)[-1] for p in _store_objects(d)}
        assert on_disk == live
        _assert_restores(Snapshot(f"{d}/step-4"), state)


class TestTelemetry:
    def test_ledger_physical_and_codec_ratio(self, tmp_path):
        d = str(tmp_path)
        state = _state(19)
        mgr = CheckpointManager(d, chunks=True, codec="zlib")
        mgr.save(1, state)
        mgr.save(2, state)
        records, _ = runledger.read_records(d)
        takes = [r for r in records if r.get("kind") == "take"]
        assert len(takes) == 2
        churn = takes[1]["churn"]
        assert churn["physical_bytes"] == 0  # unchanged retake
        assert churn["unchanged_bytes"] > 0
        assert churn["basis"] == "incremental"
        assert churn["efficiency"] == pytest.approx(1.0)
        c0 = takes[0]["churn"]
        assert 0 < c0["codec_ratio"] <= 1.0
        assert 0 < c0["physical_bytes"] <= c0["added_bytes"]

    def test_flight_report_surfaces_encode_op(self, tmp_path):
        d = str(tmp_path)
        state = _state(23)
        Snapshot.take(f"{d}/step-1", state, chunks=True, codec="zlib")
        report = json.load(open(f"{d}/step-1/.report.json"))
        ops = report["ranks"][0]["scheduler_ops"]
        assert "encode" in ops, sorted(ops)
        assert ops["encode"]["count"] > 0
        assert ops["encode"]["bytes"] > 0

    def test_doctor_dedup_ineffective(self, monkeypatch):
        from torchsnapshot_tpu.telemetry.doctor import diagnose_report

        monkeypatch.setenv("TPUSNAPSHOT_DEDUP_MIN_BYTES", "1024")

        def _report(hit, clean, logical, misses=4):
            return {
                "kind": "take",
                "ranks": [
                    {
                        "rank": 0,
                        "churn": {
                            "chunk_hits": 8,
                            "chunk_misses": misses,
                            "chunk_hit_bytes": hit,
                            "leaf_clean_bytes": clean,
                            "chunk_logical_bytes": logical,
                        },
                    }
                ],
            }

        # All dedup inside clean leaves -> chunking bought nothing.
        rules = [
            f.rule
            for f in diagnose_report(_report(1 << 20, 1 << 20, 4 << 20))
        ]
        assert "dedup-ineffective" in rules
        # Sub-leaf savings beyond clean leaves -> silent.
        rules = [
            f.rule
            for f in diagnose_report(_report(2 << 20, 1 << 20, 4 << 20))
        ]
        assert "dedup-ineffective" not in rules
        # First take (no dedup at all) -> silent.
        rules = [f.rule for f in diagnose_report(_report(0, 0, 4 << 20))]
        assert "dedup-ineffective" not in rules

    def test_chunk_metrics_counters(self, tmp_path):
        from torchsnapshot_tpu.telemetry import metrics as mn

        d = str(tmp_path)
        state = _state(20)
        before = telemetry.snapshot()
        Snapshot.take(f"{d}/step-1", state, chunks=True)
        Snapshot.take(f"{d}/step-2", state, chunks=True)
        after = telemetry.snapshot()
        from torchsnapshot_tpu.telemetry.metrics import diff_snapshots

        delta = diff_snapshots(before, after)
        hits = sum(
            v
            for k, v in delta.items()
            if isinstance(v, (int, float))
            and k.startswith(mn.CHUNKSTORE_CHUNKS)
            and "hit" in k
        )
        stored = sum(
            v
            for k, v in delta.items()
            if isinstance(v, (int, float))
            and k.startswith(mn.CHUNKSTORE_BYTES)
            and "stored" in k
        )
        assert hits > 0 and stored > 0


class TestSnapserveKeying:
    def test_content_address_recognition(self):
        key = chunkstore.chunk_key("xs128:" + "ab" * 16, 4096, "zlib")
        path = chunkstore.chunk_object_path(key)
        assert chunkstore.content_address_of(path) == key
        assert chunkstore.content_address_of(f"@base1/{path}") == key
        assert chunkstore.content_address_of("0/model/w") is None
        assert (
            chunkstore.content_address_of("objects/zz/not-a-key") is None
        )

    def test_retake_keeps_server_cache_warm(self, tmp_path):
        from torchsnapshot_tpu import snapserve

        d = str(tmp_path)
        state = _state(21)
        Snapshot.take(f"{d}/step-1", state, chunks=True)
        service = snapserve.ReadService()
        server = snapserve.start_local_server(service=service)
        try:
            addr = f"snapserve://{server.addr[0]}:{server.addr[1]}"
            s1 = Snapshot(f"{addr}/{d}/step-1")
            _assert_restores(s1, state)
            backend_before = service.stats()["backend_read_bytes"]
            # Re-take to a NEW path with the same content: the chunk
            # objects have content-addressed cache keys, so the second
            # restore is served almost entirely from cache.
            Snapshot.take(f"{d}/step-2", state, chunks=True)
            s2 = Snapshot(f"{addr}/{d}/step-2")
            _assert_restores(s2, state)
            backend_delta = (
                service.stats()["backend_read_bytes"] - backend_before
            )
            logical = sum(
                int(np.asarray(v).nbytes) for v in state["m"].values()
            )
            # Metadata + manifest fetches only — payload chunks hit.
            assert backend_delta < 0.2 * logical, (
                backend_delta,
                logical,
            )
        finally:
            snapserve.kill_local_servers()
