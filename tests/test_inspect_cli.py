"""Golden-output coverage for the inspect CLI (previously untested):
default, --rank, --raw, and --report paths over a small memory://
snapshot (ISSUE 3 satellite)."""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from torchsnapshot_tpu import Snapshot, StateDict, telemetry
from torchsnapshot_tpu.inspect import main as inspect_main
from torchsnapshot_tpu.storage_plugin import _MEMORY_STORES
from torchsnapshot_tpu.utils.test_utils import run_thread_ranks


class _Model:
    def __init__(self, params):
        self.params = params

    def state_dict(self):
        return self.params

    def load_state_dict(self, sd):
        self.params = sd


def _golden_state():
    return _Model(
        {
            "w": jnp.asarray(np.arange(48, dtype=np.float32).reshape(8, 6)),
            "b": jnp.zeros(6, jnp.float32),
            "meta": {"name": "golden"},
        }
    )


@pytest.fixture()
def golden_snapshot():
    bucket = "inspect-golden"
    _MEMORY_STORES.pop(bucket, None)
    url = f"memory://{bucket}/snap"
    Snapshot.take(
        url,
        {"model": _golden_state(), "progress": StateDict(step=7, done=False)},
    )
    return url


GOLDEN_DEFAULT = """\
model                                                        <dict>
model/b                                                      Array float32(6,) 24B @ 0/model/b
model/meta                                                   <dict>
model/meta/name                                              str = 'golden'
model/w                                                      Array float32(8, 6) 192B @ 0/model/w
progress                                                     <dict>
progress/done                                                bool = False
progress/step                                                int = 7

8 entries, 216B of array data
"""

GOLDEN_RAW = """\
0/model                                                      <dict>
0/model/b                                                    Array float32(6,) 24B @ 0/model/b
0/model/meta                                                 <dict>
0/model/meta/name                                            str = 'golden'
0/model/w                                                    Array float32(8, 6) 192B @ 0/model/w
0/progress                                                   <dict>
0/progress/done                                              bool = False
0/progress/step                                              int = 7

8 entries, 216B of array data
"""


def test_default_listing_golden(golden_snapshot, capsys):
    assert inspect_main([golden_snapshot]) == 0
    assert capsys.readouterr().out == GOLDEN_DEFAULT


def test_raw_listing_golden(golden_snapshot, capsys):
    assert inspect_main([golden_snapshot, "--raw"]) == 0
    assert capsys.readouterr().out == GOLDEN_RAW


def test_rank_selects_per_rank_view(capsys):
    """--rank N shows rank N's values; a 2-rank snapshot's ranks differ."""
    bucket = "inspect-ranks"
    _MEMORY_STORES.pop(bucket, None)
    url = f"memory://{bucket}/snap"

    def fn(coord, rank):
        model = _Model(
            {"w": np.full(4 + rank, float(rank), dtype=np.float32)}
        )
        Snapshot.take(url, {"model": model}, coord=coord)

    run_thread_ranks(2, fn)
    assert inspect_main([url, "--rank", "0"]) == 0
    rank0 = capsys.readouterr().out
    assert inspect_main([url, "--rank", "1"]) == 0
    rank1 = capsys.readouterr().out
    assert "float32(4,)" in rank0 and "@ 0/model/w" in rank0
    assert "float32(5,)" in rank1 and "@ 1/model/w" in rank1
    assert rank0 != rank1


def test_report_golden(golden_snapshot, capsys):
    assert inspect_main([golden_snapshot, "--report"]) == 0
    out = capsys.readouterr().out
    lines = out.splitlines()
    assert lines[0].startswith(f"take report for {golden_snapshot}")
    assert "(take_id " in lines[0]
    assert lines[1].startswith("world 1: 216 bytes in ")
    assert "| retries 0 | faults 0 | budget stall" in lines[1]
    assert lines[2].split() == [
        "rank", "bytes", "MB/s", "stall_s", "retries", "phases",
    ]
    assert lines[3].split()[0] == "0"
    assert lines[3].split()[1] == "216"
    assert "capture=" in lines[3] and "write=" in lines[3]
    assert "commit=" in lines[3]
    assert "stage[n=" in lines[4] and "write[n=" in lines[4]


def test_report_includes_restore_records(golden_snapshot, capsys):
    Snapshot(golden_snapshot).restore(
        {
            "model": _Model(
                {
                    "w": jnp.zeros((8, 6), jnp.float32),
                    "b": jnp.ones(6, jnp.float32),
                    "meta": {"name": ""},
                }
            ),
            "progress": StateDict(step=0, done=True),
        }
    )
    assert inspect_main([golden_snapshot, "--report"]) == 0
    out = capsys.readouterr().out
    assert "take report for" in out
    assert "restore report for" in out
    assert "read=" in out and "consume=" in out and "assemble=" in out


def test_report_on_nonexistent_snapshot_says_so(tmp_path, capsys):
    """A typo'd path reads as "no snapshot", never as "no telemetry"."""
    assert inspect_main([str(tmp_path / "nope"), "--report"]) == 1
    err = capsys.readouterr().err
    assert "no snapshot at" in err
    assert "flight record" not in err


def test_report_missing_exits_1(tmp_path, capsys):
    """A snapshot whose report was removed (or predates telemetry)
    exits 1 with a pointer, not a traceback."""
    model = _Model({"w": np.arange(8, dtype=np.float32)})
    snap_dir = tmp_path / "snap"
    Snapshot.take(str(snap_dir), {"model": model})
    (snap_dir / ".report.json").unlink()
    assert inspect_main([str(snap_dir), "--report"]) == 1
    assert "no flight record" in capsys.readouterr().err


def test_report_is_exclusive_with_verify(golden_snapshot, capsys):
    with pytest.raises(SystemExit):
        inspect_main([golden_snapshot, "--report", "--verify"])
