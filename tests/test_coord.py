"""Coordination shim tests (reference analog: pg_wrapper usage)."""

import threading

import pytest

from torchsnapshot_tpu.coord import (
    DictStore,
    FileStore,
    NoOpCoordinator,
    StoreCoordinator,
    get_coordinator,
)


def _run_ranks(world, fn):
    """Run fn(coordinator, rank) on `world` threads over a shared DictStore."""
    store = DictStore()
    results = [None] * world
    errors = []

    def worker(rank):
        try:
            coord = StoreCoordinator(store, rank, world, timeout_s=30)
            results[rank] = fn(coord, rank)
        except BaseException as e:
            errors.append((rank, e))

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    if errors:
        raise errors[0][1]
    return results


def test_noop_coordinator():
    c = NoOpCoordinator()
    assert c.get_rank() == 0
    assert c.get_world_size() == 1
    c.barrier()
    assert c.all_gather_object("x") == ["x"]
    assert c.broadcast_object("y") == "y"


def test_all_gather_object():
    results = _run_ranks(4, lambda c, r: c.all_gather_object({"rank": r}))
    for res in results:
        assert res == [{"rank": i} for i in range(4)]


def test_broadcast_object():
    results = _run_ranks(3, lambda c, r: c.broadcast_object(f"from{r}", src=1))
    assert results == ["from1"] * 3


def test_barrier_then_gather_sequencing():
    def fn(c, r):
        c.barrier()
        a = c.all_gather_object(r)
        c.barrier()
        b = c.all_gather_object(r * 10)
        return (a, b)

    for a, b in _run_ranks(3, fn):
        assert a == [0, 1, 2]
        assert b == [0, 10, 20]


def test_large_object_chunking():
    big = b"x" * (3 * 1024 * 1024)  # crosses the 512 KB chunk limit

    def fn(c, r):
        return c.all_gather_object(big if r == 0 else "small")

    for res in _run_ranks(2, fn):
        assert res[0] == big
        assert res[1] == "small"


def test_file_store(tmp_path):
    store = FileStore(str(tmp_path))
    store.set("k/with/slash", b"v1")
    assert store.get("k/with/slash", timeout_s=5) == b"v1"
    with pytest.raises(TimeoutError):
        store.get("missing", timeout_s=0.2)


def test_get_coordinator_defaults():
    assert isinstance(get_coordinator(), NoOpCoordinator)
    explicit = NoOpCoordinator()
    assert get_coordinator(explicit) is explicit


def _run_ranks_on_store(store, world, fn, timeout_s=120):
    from torchsnapshot_tpu.utils.test_utils import run_thread_ranks

    return run_thread_ranks(world, fn, store=store, timeout_s=timeout_s)


def test_collective_keys_are_garbage_collected_dictstore():
    """1,000 barriers must leave O(world) keys in the store, not
    O(ops x world) — unbounded coordination-service growth for a job
    snapshotting every N steps for weeks (VERDICT r2 weak #3)."""
    world = 4
    store = DictStore()

    def fn(c, r):
        for _ in range(1000):
            c.barrier()
        return store.key_count()

    _run_ranks_on_store(store, world, fn)
    # Each rank retains at most its final-generation barrier key (a
    # straggler may still need to read it). Without GC this run would
    # leave 1,000 generations x 4 ranks = 4,000 keys.
    assert store.key_count() <= 2 * world


def test_collective_keys_gc_mixed_ops_dictstore():
    """all_gather (incl. chunked >512KiB values) and broadcast keys are
    also collected once a later full-participation collective proves
    global progress."""
    world = 3
    store = DictStore()

    def fn(c, r):
        for i in range(50):
            c.all_gather_object({"rank": r, "i": i})
            c.broadcast_object(b"x" * (700 * 1024) if r == 0 else None, src=0)
        c.barrier()
        c.barrier()
        return store.key_count()

    _run_ranks_on_store(store, world, fn)
    # Pending: final barrier keys only (broadcast/gather gens are all
    # proven consumed by the trailing barriers).
    assert store.key_count() <= 2 * world


def test_collective_keys_are_garbage_collected_filestore(tmp_path):
    world = 2
    store = FileStore(str(tmp_path / "store"))

    def fn(c, r):
        for _ in range(200):
            c.barrier()
        return None

    _run_ranks_on_store(store, world, fn)
    assert store.key_count() <= 2 * world


def test_gc_never_deletes_a_key_a_straggler_still_needs():
    """A rank that sprints far ahead in reads must not delete keys the
    slowest rank still needs: interleave uneven progress via gathers
    carrying increasing payloads and verify every rank sees every value."""
    world = 4
    store = DictStore()

    def fn(c, r):
        seen = []
        for i in range(100):
            got = c.all_gather_object((r, i))
            assert got == [(q, i) for q in range(world)]
            seen.append(got)
        return len(seen)

    assert _run_ranks_on_store(store, world, fn) == [100] * world
