"""Coordination shim tests (reference analog: pg_wrapper usage)."""

import threading

import pytest

from torchsnapshot_tpu.coord import (
    DictStore,
    FileStore,
    NoOpCoordinator,
    StoreCoordinator,
    get_coordinator,
)


def _run_ranks(world, fn):
    """Run fn(coordinator, rank) on `world` threads over a shared DictStore."""
    store = DictStore()
    results = [None] * world
    errors = []

    def worker(rank):
        try:
            coord = StoreCoordinator(store, rank, world, timeout_s=30)
            results[rank] = fn(coord, rank)
        except BaseException as e:
            errors.append((rank, e))

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    if errors:
        raise errors[0][1]
    return results


def test_noop_coordinator():
    c = NoOpCoordinator()
    assert c.get_rank() == 0
    assert c.get_world_size() == 1
    c.barrier()
    assert c.all_gather_object("x") == ["x"]
    assert c.broadcast_object("y") == "y"


def test_all_gather_object():
    results = _run_ranks(4, lambda c, r: c.all_gather_object({"rank": r}))
    for res in results:
        assert res == [{"rank": i} for i in range(4)]


def test_broadcast_object():
    results = _run_ranks(3, lambda c, r: c.broadcast_object(f"from{r}", src=1))
    assert results == ["from1"] * 3


def test_barrier_then_gather_sequencing():
    def fn(c, r):
        c.barrier()
        a = c.all_gather_object(r)
        c.barrier()
        b = c.all_gather_object(r * 10)
        return (a, b)

    for a, b in _run_ranks(3, fn):
        assert a == [0, 1, 2]
        assert b == [0, 10, 20]


def test_large_object_chunking():
    big = b"x" * (3 * 1024 * 1024)  # crosses the 512 KB chunk limit

    def fn(c, r):
        return c.all_gather_object(big if r == 0 else "small")

    for res in _run_ranks(2, fn):
        assert res[0] == big
        assert res[1] == "small"


def test_file_store(tmp_path):
    store = FileStore(str(tmp_path))
    store.set("k/with/slash", b"v1")
    assert store.get("k/with/slash", timeout_s=5) == b"v1"
    with pytest.raises(TimeoutError):
        store.get("missing", timeout_s=0.2)


def test_get_coordinator_defaults():
    assert isinstance(get_coordinator(), NoOpCoordinator)
    explicit = NoOpCoordinator()
    assert get_coordinator(explicit) is explicit


def _run_ranks_on_store(store, world, fn, timeout_s=120):
    from torchsnapshot_tpu.utils.test_utils import run_thread_ranks

    return run_thread_ranks(world, fn, store=store, timeout_s=timeout_s)


def test_collective_keys_are_garbage_collected_dictstore():
    """1,000 barriers must leave O(world) keys in the store, not
    O(ops x world) — unbounded coordination-service growth for a job
    snapshotting every N steps for weeks (VERDICT r2 weak #3)."""
    world = 4
    store = DictStore()

    def fn(c, r):
        for _ in range(1000):
            c.barrier()
        return store.key_count()

    _run_ranks_on_store(store, world, fn)
    # Each rank retains at most its final-generation barrier key (a
    # straggler may still need to read it). Without GC this run would
    # leave 1,000 generations x 4 ranks = 4,000 keys.
    assert store.key_count() <= 2 * world


def test_collective_keys_gc_mixed_ops_dictstore():
    """all_gather (incl. chunked >512KiB values) and broadcast keys are
    also collected once a later full-participation collective proves
    global progress."""
    world = 3
    store = DictStore()

    def fn(c, r):
        for i in range(50):
            c.all_gather_object({"rank": r, "i": i})
            c.broadcast_object(b"x" * (700 * 1024) if r == 0 else None, src=0)
        c.barrier()
        c.barrier()
        return store.key_count()

    _run_ranks_on_store(store, world, fn)
    # Pending: final barrier keys only (broadcast/gather gens are all
    # proven consumed by the trailing barriers).
    assert store.key_count() <= 2 * world


def test_collective_keys_are_garbage_collected_filestore(tmp_path):
    world = 2
    store = FileStore(str(tmp_path / "store"))

    def fn(c, r):
        for _ in range(200):
            c.barrier()
        return None

    _run_ranks_on_store(store, world, fn)
    assert store.key_count() <= 2 * world


def test_gc_never_deletes_a_key_a_straggler_still_needs():
    """A rank that sprints far ahead in reads must not delete keys the
    slowest rank still needs: interleave uneven progress via gathers
    carrying increasing payloads and verify every rank sees every value."""
    world = 4
    store = DictStore()

    def fn(c, r):
        seen = []
        for i in range(100):
            got = c.all_gather_object((r, i))
            assert got == [(q, i) for q in range(world)]
            seen.append(got)
        return len(seen)

    assert _run_ranks_on_store(store, world, fn) == [100] * world


def test_broadcast_only_keys_are_garbage_collected():
    """A broadcast-only steady state (e.g. a serving loop resolving
    latest via restore(step=None) broadcasts) must not grow the store:
    receivers ack each broadcast and the source lazily collects acks at
    its next broadcast, deleting payload keys without any barrier or
    gather ever running (VERDICT r3 weak #6)."""
    world = 4
    store = DictStore()

    def fn(c, r):
        out = []
        for i in range(300):
            out.append(c.broadcast_object(("v", i) if r == 0 else None))
        # In-process bookkeeping must stay bounded too: a receiver that
        # never runs a barrier/gather must not accumulate one _own_keys
        # tuple per broadcast (else the next collective floods the store
        # with an O(history) burst of no-op deletes).
        return out, len(c._own_keys)

    results = _run_ranks_on_store(store, world, fn)
    from torchsnapshot_tpu.coord import _BC_WINDOW as _W

    for res, n_own in results:
        assert res == [("v", i) for i in range(300)]
        assert n_own <= 2 * _W
    # Pending at exit: at most _BC_WINDOW generations (payload + acks,
    # <= world keys each) — the source's bounded in-flight window.
    # Without broadcast GC this loop leaves 300 payload + 900 ack keys.
    from torchsnapshot_tpu.coord import _BC_WINDOW

    assert store.key_count() <= _BC_WINDOW * world


def test_broadcast_only_gc_chunked_and_rotating_sources():
    """Broadcast GC must also collect chunked (>512 KiB) payload keys
    and work when different ranks act as source over time."""
    world = 3
    store = DictStore()
    big = b"z" * (700 * 1024)

    def fn(c, r):
        for i in range(45):
            src = i % world
            got = c.broadcast_object(big if r == src else None, src=src)
            assert got == big
        return None

    _run_ranks_on_store(store, world, fn)
    # 45 chunked broadcasts x (head + 2 parts + 2 acks) = 225 keys
    # without GC; with GC each source's outstanding window is bounded.
    from torchsnapshot_tpu.coord import _BC_WINDOW

    assert store.key_count() <= world * _BC_WINDOW * 5


def test_barrier_timeout_override():
    """barrier(timeout_s=...) must bound the wait for stragglers —
    callers that barrier behind a long rank-0 commit pass the commit's
    own timeout (ADVICE r3 medium)."""
    import time

    store = DictStore()
    c0 = StoreCoordinator(store, 0, 2, timeout_s=60)
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        c0.barrier(timeout_s=0.2)
    assert time.monotonic() - t0 < 10


def test_barrier_timeout_names_stalled_rank():
    """The barrier's TimeoutError must say WHICH rank never arrived —
    an opaque store-key timeout sends the operator grepping logs on
    every host instead of straight to the stalled one."""
    store = DictStore()
    c0 = StoreCoordinator(store, 0, 3, timeout_s=60)
    # Rank 2 pre-arrives at generation 1 (the coordinator's first
    # barrier); rank 1 never does — the error must blame 1, not 2.
    store.set("b/1/2", b"1")
    with pytest.raises(TimeoutError, match=r"rank 1 never arrived"):
        c0.barrier(timeout_s=0.2)


def test_barrier_timeout_is_one_shared_deadline():
    """The caller's timeout bounds the whole barrier, not each rank's
    key wait — otherwise the worst-case wait grows to world x timeout."""
    import time

    store = DictStore()
    c0 = StoreCoordinator(store, 0, 8, timeout_s=60)
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        c0.barrier(timeout_s=0.3)
    # 8 absent ranks at a fresh 0.3s each would take ~2.4s.
    assert time.monotonic() - t0 < 1.5


def test_all_gather_timeout_is_one_shared_deadline():
    """timeout_s bounds the whole gather, not each rank's key (nor each
    chunk part of one rank's payload)."""
    import time

    store = DictStore()
    c0 = StoreCoordinator(store, 0, 6, timeout_s=0.3)
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        c0.all_gather_object("mine")
    # 5 absent ranks at a fresh 0.3s each would take ~1.5s.
    assert time.monotonic() - t0 < 1.2


def test_remaining_floors_above_zero_after_deadline():
    """Past the shared deadline, per-key waits floor at a small positive
    budget instead of 0: a backend that checks the deadline before the
    key (JaxStore's blocking get at 0 ms) would otherwise time out even
    on an already-published key, and the caller would blame a healthy
    rank."""
    import time

    c = StoreCoordinator(DictStore(), 0, 1, timeout_s=60)
    assert c._remaining(time.monotonic() - 100) >= 0.05
    assert c._remaining(time.monotonic() + 30) == pytest.approx(30, abs=1)


def test_all_gather_timeout_names_missing_rank():
    store = DictStore()
    c0 = StoreCoordinator(store, 0, 2, timeout_s=0.2)
    with pytest.raises(
        TimeoutError, match=r"rank 1 never finished publishing"
    ):
        c0.all_gather_object("mine")


def test_broadcast_timeout_names_source_rank():
    store = DictStore()
    c1 = StoreCoordinator(store, 1, 2, timeout_s=0.2)
    with pytest.raises(
        TimeoutError, match=r"source rank 0 never finished publishing"
    ):
        c1.broadcast_object("ignored", src=0)


def test_barrier_compat_with_legacy_coordinator():
    """Out-of-tree Coordinator implementations written against the
    pre-r4 ABC (barrier(self), no timeout) must keep working at commit
    barriers instead of raising TypeError after the storage work."""
    from torchsnapshot_tpu.coord import Coordinator, barrier_compat

    calls = []

    class LegacyCoord(Coordinator):
        def get_rank(self):
            return 0

        def get_world_size(self):
            return 1

        def barrier(self):  # old signature
            calls.append("barrier")

        def all_gather_object(self, obj):
            return [obj]

        def broadcast_object(self, obj, src=0):
            return obj

    barrier_compat(LegacyCoord(), 1800.0)
    assert calls == ["barrier"]

    seen = []

    class NewCoord(LegacyCoord):
        def barrier(self, timeout_s=None):
            seen.append(timeout_s)

    barrier_compat(NewCoord(), 1800.0)
    assert seen == [1800.0]
