"""snapproto: the wire-protocol inventory and its runtime contracts.

Three jobs:

1. **Inventory freshness** — ``docs/PROTOCOL.md`` is byte-identical to
   ``render_markdown(build_inventory())``; the protocol map can never
   drift from the code it describes (CI re-runs this as the
   protocol-smoke step).
2. **Inventory completeness** — the model covers all three wire stacks
   and every client-dispatched op resolves to a server handler.
3. **Registry/runtime conformance** — the module-level op registries
   the analyzer reads are the SAME objects the runtime dispatches
   through: every declared handler is a real method, the idempotency
   registries match dispatch, the repair facade maps onto real tier
   entry points, and a live ping round-trips against a real server.
"""

import json
import os
import subprocess
import sys

import pytest

from torchsnapshot_tpu import snapserve
from torchsnapshot_tpu.analysis.protocol import (
    FACADE_METHOD_OPS,
    build_inventory,
    render_markdown,
)
from torchsnapshot_tpu.hottier import tier
from torchsnapshot_tpu.hottier.peer import PeerServer
from torchsnapshot_tpu.hottier.transport import (
    HOT_TIER_OPS,
    RemotePeer,
)
from torchsnapshot_tpu.hottier.transport import (
    IDEMPOTENT_OPS as HOT_TIER_IDEMPOTENT_OPS,
)
from torchsnapshot_tpu.snapserve.protocol import (
    IDEMPOTENT_OPS as READ_PLANE_IDEMPOTENT_OPS,
)
from torchsnapshot_tpu.snapserve.protocol import READ_PLANE_OPS
from torchsnapshot_tpu.snapserve.server import SnapServer

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(TESTS_DIR)
PROTOCOL_MD = os.path.join(REPO_ROOT, "docs", "PROTOCOL.md")


# ------------------------------------------------------- inventory freshness


def test_protocol_md_is_fresh():
    with open(PROTOCOL_MD, encoding="utf-8") as f:
        on_disk = f.read()
    rendered = render_markdown(build_inventory())
    assert on_disk == rendered, (
        "docs/PROTOCOL.md is stale — regenerate it with:\n"
        "  python -m torchsnapshot_tpu.analysis --inventory "
        "> docs/PROTOCOL.md"
    )


def test_render_is_deterministic():
    assert render_markdown(build_inventory()) == render_markdown(
        build_inventory()
    )


# ----------------------------------------------------- inventory completeness


def test_inventory_covers_all_three_transports():
    inv = build_inventory()
    assert [t["name"] for t in inv["transports"]] == [
        "snapserve",
        "snapwire",
        "snapmend",
    ]
    assert inv["wire"]["protocol_version"] == 1


def test_every_dispatched_op_has_a_handler():
    inv = build_inventory()
    for transport in inv["transports"]:
        assert transport["ops_without_handler"] == [], transport["name"]
        for op, meta in transport["ops"].items():
            assert meta["handled"], (transport["name"], op)
            assert meta["handler"], (transport["name"], op)


def test_inventory_ops_match_runtime_registries():
    inv = build_inventory()
    by_name = {t["name"]: t for t in inv["transports"]}
    assert set(by_name["snapserve"]["ops"]) == set(READ_PLANE_OPS)
    assert set(by_name["snapwire"]["ops"]) == set(HOT_TIER_OPS)
    # The repair plane rides the snapwire peer: its op catalog is the
    # facade image, a subset of the hot-tier registry.
    assert set(by_name["snapmend"]["ops"]) <= set(HOT_TIER_OPS)
    assert set(FACADE_METHOD_OPS.values()) == set(
        by_name["snapmend"]["ops"]
    )


# ------------------------------------------- registry/runtime conformance


def test_hot_tier_handlers_are_peer_server_methods():
    for op, meta in HOT_TIER_OPS.items():
        handler = meta["handler"]
        assert callable(getattr(PeerServer, handler, None)), (op, handler)


def test_read_plane_handlers_are_snap_server_methods():
    for op, meta in READ_PLANE_OPS.items():
        handler = meta["handler"]
        assert callable(getattr(SnapServer, handler, None)), (op, handler)


def test_idempotent_registries_cover_dispatch():
    # Both transports retry through a wrapper that consults the
    # registry; every op the dispatch tables know must be declared
    # (SNAP012 enforces the static half of this).
    assert HOT_TIER_IDEMPOTENT_OPS == frozenset(HOT_TIER_OPS)
    assert READ_PLANE_IDEMPOTENT_OPS == frozenset(READ_PLANE_OPS)


def test_facade_methods_map_to_real_entry_points():
    for method, op in FACADE_METHOD_OPS.items():
        assert op in HOT_TIER_OPS, (method, op)
        target = getattr(tier, method, None) or getattr(
            RemotePeer, method, None
        )
        assert callable(target), method


def test_unknown_op_is_a_programming_error_not_a_wire_frame():
    peer = RemotePeer(host_id=0, addr="127.0.0.1:1")
    with pytest.raises(ValueError, match="unknown snapwire op"):
        peer._call_once({"v": 1, "op": "nope"}, b"", 1.0)


def test_ping_round_trips_against_a_live_server():
    server = snapserve.start_local_server()
    try:
        header = snapserve.ping_server(server.addr, timeout_s=10.0)
        assert header.get("ok") is True
        assert header.get("server") == "snapserve"
    finally:
        server.stop()


# ------------------------------------------------ CLI / SARIF contracts


def run_cli(*args):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.run(
        [sys.executable, "-m", "torchsnapshot_tpu.analysis", *args],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
        env=env,
        timeout=120,
    )


def test_cli_protocol_rules_clean_repo_wide():
    # The acceptance gate verbatim: the four protocol rules exit 0 over
    # the package with zero suppressions spent on them.
    proc = run_cli(
        "--rules",
        "SNAP010,SNAP011,SNAP012,SNAP013",
        "torchsnapshot_tpu/",
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 violation(s)" in proc.stdout
    assert "0 suppressed" in proc.stdout


def test_cli_protocol_rules_dirty_on_fixtures_sarif():
    proc = run_cli(
        "--format",
        "sarif",
        "--rules",
        "SNAP010,SNAP011,SNAP012,SNAP013",
        "tests/analysis_fixtures/bad_protocol/",
    )
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["version"] == "2.1.0"
    driver = doc["runs"][0]["tool"]["driver"]
    assert {r["id"] for r in driver["rules"]} == {
        "SNAP010", "SNAP011", "SNAP012", "SNAP013",
    }
    fired = {r["ruleId"] for r in doc["runs"][0]["results"]}
    assert fired == {"SNAP010", "SNAP011", "SNAP012", "SNAP013"}


def test_cli_inventory_json_and_markdown():
    md = run_cli("--inventory")
    assert md.returncode == 0, md.stderr
    assert md.stdout.startswith("# Wire-protocol inventory")
    js = run_cli("--inventory", "--format", "json")
    assert js.returncode == 0, js.stderr
    doc = json.loads(js.stdout)
    assert [t["name"] for t in doc["transports"]] == [
        "snapserve",
        "snapwire",
        "snapmend",
    ]


# --------------------------------------------- wiretap conformance (snapflight)


def test_inventory_stamps_telemetry_keys():
    inv = build_inventory()
    by_name = {t["name"]: t for t in inv["transports"]}
    assert by_name["snapserve"]["telemetry_transport"] == "snapserve"
    assert by_name["snapwire"]["telemetry_transport"] == "snapwire"
    # The repair facade has no frames of its own: its RPCs surface in
    # the wiretap under the snapwire label it rides.
    assert by_name["snapmend"]["telemetry_transport"] == "snapwire"
    for t in inv["transports"]:
        for op, entry in t["ops"].items():
            assert entry["telemetry_key"] == (
                f"{t['telemetry_transport']}/{op}"
            ), (t["name"], op)


def test_every_protocol_op_reports_through_wiretap():
    """The PROTOCOL.md-driven conformance pin: exercising every op of
    every transport produces a wiretap sample under exactly the
    inventory's telemetry keys — no listed op is dark, and no sample
    appears for an op the protocol map does not list (an unlisted key
    would be an instrumented op the inventory lost, or a typo'd
    transport/op label pair)."""
    import asyncio

    from torchsnapshot_tpu import wiretap
    from torchsnapshot_tpu.hottier.peer import start_local_peer
    from torchsnapshot_tpu.io_types import IOReq
    from torchsnapshot_tpu.snapserve.server import fetch_server_stats
    from torchsnapshot_tpu.storage_plugin import url_to_storage_plugin

    inv = build_inventory()
    expected = {
        entry["telemetry_key"]
        for t in inv["transports"]
        for entry in t["ops"].values()
    }

    root = "memory://wiretap-conformance/run"
    storage = url_to_storage_plugin(root)
    try:
        asyncio.run(
            storage.write(IOReq(path="0/obj", data=b"x" * 1024))
        )
    finally:
        storage.close()

    wiretap.reset()
    server = snapserve.start_local_server()
    peer_server, _ = start_local_peer(host_id=93, register=False)
    peer = RemotePeer(host_id=93, addr=peer_server.addr)
    try:
        # snapserve: the one-shot client helpers + a plugin read.
        snapserve.ping_server(server.addr, timeout_s=10.0)
        snapserve.fetch_member_info(server.addr, timeout_s=10.0)
        snapserve.plan_remote(
            server.addr,
            {
                "shape": [8, 8],
                "itemsize": 4,
                "record_sizes": [128, 128],
                "boxes": [[[0, 8], [0, 8]]],
            },
            timeout_s=10.0,
        )
        fetch_server_stats(server.addr, timeout_s=10.0)

        async def _read():
            plugin = url_to_storage_plugin(
                f"snapserve://{server.addr}/{root}"
            )
            try:
                await plugin.read(IOReq(path="0/obj"))
            finally:
                plugin.close()

        asyncio.run(_read())

        # snapwire: one RemotePeer call per registry op (the snapmend
        # facade rides these same frames — no extra keys to mint).
        from torchsnapshot_tpu.fingerprint import fingerprint_host

        payload = b"y" * 512
        tag = fingerprint_host(payload)
        stored, _tag = peer.put("k", payload, tag=tag, root=root)
        assert stored
        assert peer.get("k").data == payload
        assert peer.query("k") is not None
        peer.mark_drained("k", tag)
        peer.drop_stale("k", [tag])
        peer.drop("k")
        assert peer.occupancy() is not None
        assert peer.probe() is True
    finally:
        peer.close()
        peer_server.stop()
        server.stop()

    recorded = set(wiretap.summary())
    assert recorded == expected, (
        f"wiretap coverage drifted from the protocol inventory:\n"
        f"  ops with no samples: {sorted(expected - recorded)}\n"
        f"  samples for unlisted ops: {sorted(recorded - expected)}"
    )
