"""Tests for snapcheck (torchsnapshot_tpu.analysis) — and the repo gate.

Two jobs:

1. **Rule tests** — every rule has at least one positive (bad fixture,
   exact rule code + line numbers asserted) and one negative (good
   fixture, zero findings), plus suppression and baseline behavior.
   Fixtures live in ``tests/analysis_fixtures/``; the ones under
   ``scoped/`` carry the file names (``scheduler.py``, ``fingerprint.py``,
   …) that module-scoped rules key on.

2. **The gate** — ``test_repo_is_clean`` runs every rule over the whole
   ``torchsnapshot_tpu`` package and fails tier-1 on any new violation.
   Deliberate violations must be suppressed in-line with a justification
   (``# snapcheck: disable=<rule> -- why``), not fixed here.
"""

import json
import os
import subprocess
import sys

import pytest

from torchsnapshot_tpu import analysis
from torchsnapshot_tpu.analysis import (
    AckOrderingRule,
    BlockingSyncRule,
    ContextPropagationRule,
    ContractDriftRule,
    DeterminismRule,
    DurabilityOrderRule,
    EventLoopBlockingRule,
    LifecycleRule,
    LocksetRule,
    RetryIdempotencyRule,
    RpcConformanceRule,
    SwallowedExceptionRule,
    UnboundedWireWaitRule,
)

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
FIXTURES = os.path.join(TESTS_DIR, "analysis_fixtures")
REPO_ROOT = os.path.dirname(TESTS_DIR)
PACKAGE = os.path.join(REPO_ROOT, "torchsnapshot_tpu")


def analyze(fixture, rules=None):
    path = os.path.join(FIXTURES, fixture)
    return analysis.run([path], rules or analysis.default_rules())


def findings(result):
    """(code, line) pairs for every violation, sorted."""
    return sorted((d.code, d.line) for d in result.violations)


# ------------------------------------------------------------------- the gate


def test_repo_is_clean():
    result = analysis.run([PACKAGE], analysis.default_rules())
    formatted = "\n".join(d.format() for d in result.violations)
    assert result.ok, (
        f"snapcheck found new violations in torchsnapshot_tpu/ "
        f"(fix them or suppress with a justification — see "
        f"docs/ANALYSIS.md):\n{formatted}"
        + "".join(f"\nunparseable: {p}: {m}" for p, m in result.errors)
    )


def test_fixture_corpus_is_dirty():
    # EVERY registered rule id must have at least one firing fixture; a
    # rule that stops seeing its bad fixture has silently stopped
    # protecting the package too.
    result = analysis.run([FIXTURES], analysis.default_rules())
    codes = {d.code for d in result.violations}
    assert codes == {r.code for r in analysis.default_rules()}
    assert codes == {
        "SNAP001", "SNAP002", "SNAP003", "SNAP004", "SNAP005",
        "SNAP006", "SNAP007", "SNAP008", "SNAP009", "SNAP010",
        "SNAP011", "SNAP012", "SNAP013",
    }


# ------------------------------------------------------- SNAP001 blocking-sync


def test_blocking_sync_positive():
    result = analyze("bad_blocking_sync.py", [BlockingSyncRule()])
    assert findings(result) == [
        ("SNAP001", 8),  # x.block_until_ready()
        ("SNAP001", 9),  # jax.device_get(x)
        ("SNAP001", 10),  # np.asarray(x)
        ("SNAP001", 11),  # time.sleep(0.1)
    ]


def test_blocking_sync_negative():
    # Sync helpers may block (they run in executors); async code that
    # defers through run_in_executor/asyncio.sleep is clean.
    result = analyze("good_blocking_sync.py", [BlockingSyncRule()])
    assert findings(result) == []


# ---------------------------------------------------- SNAP002 durability-order


def test_durability_order_positive():
    result = analyze("bad_durability.py", [DurabilityOrderRule()])
    assert findings(result) == [
        ("SNAP002", 9),  # os.replace, no fsync
        ("SNAP002", 16),  # append-mode write, no fsync (ledger arm)
    ]
    assert "fsync" in result.violations[0].message
    assert "append" in result.violations[1].message


def test_durability_order_negative():
    # Fsynced renames, fsynced appends, and a justified ephemeral-append
    # suppression are all clean.
    result = analyze("good_durability.py", [DurabilityOrderRule()])
    assert findings(result) == []


# ------------------------------------------------- SNAP003 swallowed-exception


def test_swallowed_exception_positive():
    result = analyze("bad_swallowed.py", [SwallowedExceptionRule()])
    assert findings(result) == [
        ("SNAP003", 7),  # except Exception: return None
        ("SNAP003", 15),  # bare except: pass
        ("SNAP003", 22),  # except BaseException: return False
    ]


def test_swallowed_exception_negative():
    # Logging, re-raising, using the bound value, and capturing via
    # traceback all count as handling; narrow catches are out of scope.
    result = analyze("good_swallowed.py", [SwallowedExceptionRule()])
    assert findings(result) == []


# ------------------------------------------------------ SNAP004 nondeterminism


def test_determinism_positive():
    result = analyze(
        os.path.join("scoped", "fingerprint.py"), [DeterminismRule()]
    )
    assert findings(result) == [
        ("SNAP004", 12),  # time.time()
        ("SNAP004", 13),  # random.random()
        ("SNAP004", 14),  # hash(...)
        ("SNAP004", 19),  # json.dumps without sort_keys
        ("SNAP004", 23),  # yaml.dump(..., sort_keys=False)
        ("SNAP004", 28),  # for e in set(entries)
    ]


def test_determinism_negative():
    result = analyze(
        os.path.join("scoped", "manifest.py"), [DeterminismRule()]
    )
    assert findings(result) == []


def test_determinism_is_module_scoped():
    # The identical nondeterministic code outside a serialization module
    # is not this rule's business.
    rule = DeterminismRule()
    assert not rule.applies_to("torchsnapshot_tpu/scheduler.py")
    result = analyze("bad_blocking_sync.py", [rule])
    assert findings(result) == []


# ------------------------------------------------------------ SNAP005 lockset


def test_lockset_positive():
    result = analyze(
        os.path.join("scoped", "scheduler.py"), [LocksetRule()]
    )
    assert findings(result) == [
        ("SNAP005", 18),  # Cell.charge: self.value -= n, no lock
        ("SNAP005", 21),  # Cell.record: self.history.append, no lock
        ("SNAP005", 39),  # executor callback mutates self.count
        ("SNAP005", 48),  # executor callback assigns nonlocal total
        ("SNAP005", 56),  # global _singleton assigned without module lock
        ("SNAP005", 67),  # global _singleton augmented without module lock
    ]


def test_lockset_negative():
    # with-lock mutations pass; a class with no lock attribute is
    # presumed thread-confined and unchecked.
    result = analyze(os.path.join("scoped", "coord.py"), [LocksetRule()])
    assert findings(result) == []


def test_lockset_callback_reported_once():
    # A callback nested under several functions is reachable from every
    # enclosing function's walk; the violation must not be duplicated.
    source = (
        "class C:\n"
        "    def outer(self, executor):\n"
        "        def mid():\n"
        "            def cb():\n"
        "                self.count += 1\n"
        "            executor.submit(cb)\n"
        "        mid()\n"
    )
    result = analysis.analyze_source(
        source, "scheduler.py", [LocksetRule()]
    )
    assert [(d.code, d.line) for d in result.diagnostics] == [("SNAP005", 5)]


def test_lockset_is_module_scoped():
    rule = LocksetRule()
    assert rule.applies_to("torchsnapshot_tpu/coord.py")
    assert not rule.applies_to("torchsnapshot_tpu/snapshot.py")


# ---------------------------------------------- SNAP006 resource-lifecycle


def test_lifecycle_positive():
    result = analyze("bad_lifecycle.py", [LifecycleRule()])
    assert findings(result) == [
        ("SNAP006", 6),   # leaked lease: release skipped on exception edge
        ("SNAP006", 17),  # double release (finally after conditional)
        ("SNAP006", 21),  # acquire result discarded
        ("SNAP006", 25),  # begin_write_through neither noted nor aborted
        ("SNAP006", 31),  # tracing.span called bare, never entered
        ("SNAP006", 35),  # release skipped on early return
    ]
    msgs = {d.line: d.message for d in result.violations}
    assert "exception path" in msgs[6]
    assert "released twice" in msgs[17]
    assert "discarded" in msgs[21]
    assert "hottier-write-through" in msgs[25]
    assert "context manager" in msgs[31]


def test_lifecycle_negative():
    # try/finally releases, ownership transfer (attribute store, call
    # argument, closure handoff, bound-method releaser), context-managed
    # spans, and loop-scoped leases are all clean.
    result = analyze("good_lifecycle.py", [LifecycleRule()])
    assert findings(result) == []


def test_lifecycle_except_exception_cleanup_counts():
    # An `except Exception: release; raise` discharges the exceptional
    # path — what escapes it is tearing down the process.
    source = (
        "def f(pool, n, use):\n"
        "    lease = pool.acquire(n)\n"
        "    try:\n"
        "        use(lease)\n"
        "    except Exception:\n"
        "        lease.release()\n"
        "        raise\n"
        "    lease.release()\n"
    )
    result = analysis.analyze_source(source, "x.py", [LifecycleRule()])
    assert result.diagnostics == []


def test_lifecycle_while_true_has_no_false_exit():
    # `while True:` only exits via break; the path that releases before
    # breaking is the only exit path, so no leak.
    source = (
        "def f(pool, n, step):\n"
        "    lease = pool.acquire(n)\n"
        "    try:\n"
        "        while True:\n"
        "            if step():\n"
        "                break\n"
        "    finally:\n"
        "        lease.release()\n"
    )
    result = analysis.analyze_source(source, "x.py", [LifecycleRule()])
    assert result.diagnostics == []


def test_lifecycle_return_routes_through_finally():
    source = (
        "def f(pool, n, cond):\n"
        "    lease = pool.acquire(n)\n"
        "    try:\n"
        "        if cond:\n"
        "            return 1\n"
        "        return 2\n"
        "    finally:\n"
        "        lease.release()\n"
    )
    result = analysis.analyze_source(source, "x.py", [LifecycleRule()])
    assert result.diagnostics == []


# --------------------------------------------- SNAP007 event-loop-blocking


def test_eventloop_positive():
    result = analyze("bad_eventloop.py", [EventLoopBlockingRule()])
    assert findings(result) == [
        ("SNAP007", 13),  # sync storage helper in async handler
        ("SNAP007", 16),  # untimed lock.acquire in async handler
        ("SNAP007", 23),  # subprocess wait in async handler
        ("SNAP007", 27),  # time.sleep transitively reachable from async
    ]
    transitive = [d for d in result.violations if d.line == 27]
    assert "drain_step" in transitive[0].message  # names the async origin


def test_eventloop_negative():
    # run_in_executor/to_thread routing, awaited asyncio primitives,
    # timeouts, and purely-sync call chains are all clean.
    result = analyze("good_eventloop.py", [EventLoopBlockingRule()])
    assert findings(result) == []


def test_eventloop_does_not_duplicate_snap001_in_async_bodies():
    # time.sleep directly inside an async def is SNAP001's finding;
    # SNAP007 must not double-report it.
    source = (
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)\n"
    )
    r7 = analysis.analyze_source(
        source, "x.py", [EventLoopBlockingRule()]
    )
    assert r7.diagnostics == []
    r1 = analysis.analyze_source(source, "x.py", [BlockingSyncRule()])
    assert [d.code for d in r1.diagnostics] == ["SNAP001"]


# -------------------------------------------- SNAP008 context-propagation


def test_context_positive():
    result = analyze("bad_context.py", [ContextPropagationRule()])
    assert findings(result) == [
        ("SNAP008", 12),  # executor callback reads current_trace_id
        ("SNAP008", 19),  # thread target emits a span unadopted
        ("SNAP008", 27),  # callback reads a registered ContextVar
    ]


def test_context_negative():
    # Capture-outside-closure, adopt_trace wrapping, copy_context().run
    # submission, and explicit value passing are all clean.
    result = analyze("good_context.py", [ContextPropagationRule()])
    assert findings(result) == []


def test_context_skips_defs_nested_in_submitted_callable():
    # A helper defined INSIDE the submitted callable runs only when the
    # (adopted) body invokes it — the read inside it must not fire.
    source = (
        "from torchsnapshot_tpu import tracing\n"
        "def go(executor, tid):\n"
        "    def work():\n"
        "        def helper():\n"
        "            return tracing.current_trace_id()\n"
        "        with tracing.adopt_trace(tid):\n"
        "            return helper()\n"
        "    executor.submit(work)\n"
    )
    result = analysis.analyze_source(
        source, "x.py", [ContextPropagationRule()]
    )
    assert result.diagnostics == []


# ------------------------------------------------ SNAP009 contract-drift


def test_contract_drift_positive_all_arms():
    result = analyze("contract_tree", [ContractDriftRule()])
    by_arm = sorted(
        (d.message.split("'")[1], os.path.basename(d.path))
        for d in result.violations
    )
    assert by_arm == [
        ("TPUSNAPSHOT_FIXTURE_KNOB", "knobs.py"),
        ("fixture-undocumented-rule", "doctor.py"),
        ("fixture_undocumented", "schedule.py"),
        ("fixture_undocumented_field", "ledger.py"),
        ("tpusnapshot_fixture_undocumented_total", "metrics.py"),
    ]
    # The acceptance-criteria arm: a fixture env knob absent from the
    # fixture doc fails the run.
    assert any(
        "TPUSNAPSHOT_FIXTURE_KNOB" in d.message
        and "docs/api.md" in d.message
        for d in result.violations
    )


def test_contract_drift_negative():
    result = analyze("contract_tree_good", [ContractDriftRule()])
    assert findings(result) == []


def test_contract_drift_resolves_repo_docs_for_package_files():
    # Analyzing a real package file must resolve to the repo's docs/
    # tree (walking up from the file), not require a fixture tree.
    target = os.path.join(PACKAGE, "staging_pool.py")
    result = analysis.run([target], [ContractDriftRule()])
    # staging_pool's knobs are documented in docs/api.md.
    assert findings(result) == []


# -------------------------------------------------------------- suppressions


def test_inline_suppressions():
    result = analyze("suppressed.py")
    # Same-line and comment-line-above forms both silence their finding;
    # the unsuppressed sleep still fires.
    assert findings(result) == [("SNAP001", 15)]
    silenced = sorted((d.code, d.line) for d in result.suppressed)
    assert silenced == [
        ("SNAP001", 6),
        ("SNAP001", 11),
        ("SNAP003", 21),
    ]


def test_suppression_by_rule_code():
    # Diagnostics print the SNAPxxx code first, so a developer copying
    # it from a CI failure into a directive must get a working
    # suppression.
    source = (
        "def swallow(op):\n"
        "    try:\n"
        "        return op()\n"
        "    except Exception:  # snapcheck: disable=SNAP003 -- probe\n"
        "        return None\n"
    )
    result = analysis.analyze_source(
        source, "x.py", [SwallowedExceptionRule()]
    )
    assert result.diagnostics == []
    assert [d.code for d in result.suppressed] == ["SNAP003"]


def test_suppression_comma_list_tolerates_spaces():
    # "disable=a, b" — a space after the comma must not silently drop
    # the rules that follow it.
    source = (
        "def swallow(op):\n"
        "    try:\n"
        "        return op()\n"
        "    except Exception:  "
        "# snapcheck: disable=nondeterminism, swallowed-exception -- why\n"
        "        return None\n"
    )
    result = analysis.analyze_source(
        source, "x.py", [SwallowedExceptionRule()]
    )
    assert result.diagnostics == []
    assert [d.code for d in result.suppressed] == ["SNAP003"]


def test_suppression_justification_glued_to_rules():
    # A justification with no space before the "--" must still cut the
    # rule list there, not become part of a (nonexistent) rule name.
    source = (
        "def swallow(op):\n"
        "    try:\n"
        "        return op()\n"
        "    except Exception:  # snapcheck: disable=swallowed-exception--probe\n"
        "        return None\n"
    )
    result = analysis.analyze_source(
        source, "x.py", [SwallowedExceptionRule()]
    )
    assert result.diagnostics == []
    assert [d.code for d in result.suppressed] == ["SNAP003"]


def test_suppression_inside_string_literal_is_ignored():
    # A directive quoted in a docstring (e.g. documentation of the
    # suppression syntax) must not silence anything — only real
    # comments count.
    source = (
        '"""Docs: write # snapcheck: disable-file=swallowed-exception\n'
        'to silence the rule file-wide."""\n'
        "def swallow(op):\n"
        "    try:\n"
        "        return op()\n"
        "    except Exception:\n"
        "        return None\n"
    )
    result = analysis.analyze_source(
        source, "x.py", [SwallowedExceptionRule()]
    )
    assert [(d.code, d.line) for d in result.diagnostics] == [("SNAP003", 6)]
    assert result.suppressed == []


def test_filewide_suppression_is_per_rule():
    result = analyze("suppressed_filewide.py")
    # disable-file silences every swallowed-exception in the file but
    # leaves other rules armed.
    assert findings(result) == [("SNAP001", 21)]
    assert {d.code for d in result.suppressed} == {"SNAP003"}


# ------------------------------------------------------------------- baseline


def test_baseline_masks_preexisting_findings(tmp_path):
    bad = os.path.join(FIXTURES, "bad_swallowed.py")
    rules = [SwallowedExceptionRule()]
    first = analysis.run([bad], rules)
    assert len(first.violations) == 3

    baseline_path = tmp_path / "baseline.json"
    analysis.save_baseline(str(baseline_path), first.fingerprints)
    baseline = analysis.load_baseline(str(baseline_path))

    masked = analysis.run([bad], rules, baseline=baseline)
    assert masked.ok
    assert len(masked.baselined) == 3
    assert masked.violations == []


def test_baseline_does_not_mask_new_findings(tmp_path):
    bad = os.path.join(FIXTURES, "bad_swallowed.py")
    rules = [SwallowedExceptionRule()]
    first = analysis.run([bad], rules)
    baseline_path = tmp_path / "baseline.json"
    # Baseline only the first finding: the other two stay violations.
    analysis.save_baseline(str(baseline_path), first.fingerprints[:1])
    baseline = analysis.load_baseline(str(baseline_path))
    partial = analysis.run([bad], rules, baseline=baseline)
    assert len(partial.baselined) == 1
    assert len(partial.violations) == 2
    assert not partial.ok


def test_baseline_matches_across_path_spellings(tmp_path):
    # A baseline written via `pkg/file.py` must keep matching when the
    # gate is later invoked as `./pkg/file.py` or an absolute path —
    # otherwise every baselined finding reappears on a CI that spells
    # the target differently than the bootstrap did.
    baseline = str(tmp_path / "baseline.json")
    rel = os.path.relpath(
        os.path.join(FIXTURES, "bad_swallowed.py"), REPO_ROOT
    )
    wrote = run_cli("--write-baseline", baseline, rel)
    assert wrote.returncode == 0, wrote.stdout + wrote.stderr
    for spelling in (os.path.join(".", rel), os.path.join(REPO_ROOT, rel)):
        gated = run_cli("--baseline", baseline, spelling)
        assert gated.returncode == 0, (
            f"{spelling}: {gated.stdout}{gated.stderr}"
        )


def test_baseline_fingerprint_survives_line_drift():
    source_v1 = (
        "def f(op):\n"
        "    try:\n"
        "        return op()\n"
        "    except Exception:\n"
        "        return None\n"
    )
    # Same flagged code, shifted down by a new leading comment.
    source_v2 = "# a new header comment\n\n" + source_v1
    rules = [SwallowedExceptionRule()]
    r1 = analysis.analyze_source(source_v1, "x.py", rules)
    r2 = analysis.analyze_source(source_v2, "x.py", rules)
    assert r1.diagnostics[0].line != r2.diagnostics[0].line
    assert r1.fingerprints[0] == r2.fingerprints[0]


# ------------------------------------------------- SNAP010 rpc-conformance


def test_rpc_conformance_positive_client():
    result = analyze(
        "bad_protocol/client.py", [RpcConformanceRule()]
    )
    assert findings(result) == [
        ("SNAP010", 29),  # op 'fetch' has no server handler
        ("SNAP010", 30),  # response field 'blob' never written
    ]


def test_rpc_conformance_positive_server():
    result = analyze(
        "bad_protocol/server.py", [RpcConformanceRule()]
    )
    assert findings(result) == [
        ("SNAP010", 30),  # request field 'nonce' never sent
        ("SNAP010", 36),  # dead handler: op 'stale'
    ]


def test_rpc_conformance_negative():
    for path in ("good_protocol/client.py", "good_protocol/server.py"):
        assert findings(analyze(path, [RpcConformanceRule()])) == []


def test_rpc_conformance_clean_on_real_transports():
    # The three real wire stacks are the rule's whole reason to exist;
    # each client/server pair must be conformant end to end.
    for rel in (
        "snapserve/client.py",
        "snapserve/server.py",
        "hottier/transport.py",
        "hottier/peer.py",
    ):
        result = analysis.run(
            [os.path.join(PACKAGE, rel)], [RpcConformanceRule()]
        )
        assert findings(result) == [], rel


# ---------------------------------------------- SNAP011 unbounded-wire-wait


def test_unbounded_wire_wait_positive():
    result = analyze(
        "bad_protocol/client.py", [UnboundedWireWaitRule()]
    )
    assert findings(result) == [
        ("SNAP011", 17),  # raw open_connection
        ("SNAP011", 18),  # raw send_frame
        ("SNAP011", 19),  # raw recv_frame
    ]


def test_unbounded_wire_wait_negative():
    # Good client wraps every wait in wait_for; the bad SERVER is also
    # clean — a responder legitimately blocks on the next request and
    # replies on a connection the client is actively reading.
    for path in (
        "good_protocol/client.py",
        "good_protocol/server.py",
        "bad_protocol/server.py",
    ):
        assert findings(analyze(path, [UnboundedWireWaitRule()])) == []


# ----------------------------------------------- SNAP012 retry-idempotency


def test_retry_idempotency_positive():
    result = analyze(
        "bad_protocol/client.py", [RetryIdempotencyRule()]
    )
    assert findings(result) == [
        ("SNAP012", 22),  # while True retry with no budget
        ("SNAP012", 26),  # fixed 1s backoff, no jitter
        ("SNAP012", 29),  # op 'fetch' retried but not idempotent
    ]


def test_retry_idempotency_negative():
    # Jittered, budgeted, every retried op declared IDEMPOTENT_OPS.
    assert (
        findings(analyze("good_protocol/client.py", [RetryIdempotencyRule()]))
        == []
    )


# --------------------------------------------------- SNAP013 ack-ordering


def test_ack_ordering_positive():
    result = analyze(
        "bad_protocol/server.py", [AckOrderingRule()]
    )
    assert findings(result) == [
        ("SNAP013", 42),  # store before fingerprint verification
        ("SNAP013", 48),  # ok=true acked before the store
        ("SNAP013", 49),  # stores + acks with no verification at all
    ]


def test_ack_ordering_negative():
    # verify -> store -> ack on every path.
    assert (
        findings(analyze("good_protocol/server.py", [AckOrderingRule()]))
        == []
    )


# --------------------------------------------------------------- rule registry


def test_select_rules():
    assert len(analysis.select_rules(None)) == 13
    by_name = analysis.select_rules(["blocking-sync", "lockset"])
    assert sorted(r.code for r in by_name) == ["SNAP001", "SNAP005"]
    by_code = analysis.select_rules(["SNAP002"])
    assert [r.name for r in by_code] == ["durability-order"]
    flow = analysis.select_rules(
        ["resource-lifecycle", "SNAP007", "context-propagation", "SNAP009"]
    )
    assert sorted(r.code for r in flow) == [
        "SNAP006", "SNAP007", "SNAP008", "SNAP009",
    ]
    proto = analysis.select_rules(
        ["rpc-conformance", "SNAP011", "retry-idempotency", "SNAP013"]
    )
    assert sorted(r.code for r in proto) == [
        "SNAP010", "SNAP011", "SNAP012", "SNAP013",
    ]
    with pytest.raises(ValueError, match="Unknown rule"):
        analysis.select_rules(["no-such-rule"])


def test_rule_codes_are_unique_and_stable():
    rules = analysis.default_rules()
    codes = [r.code for r in rules]
    assert len(set(codes)) == len(codes)
    assert all(c.startswith("SNAP") for c in codes)
    assert all(r.name and r.description for r in rules)


def test_syntax_error_is_reported_not_raised(tmp_path):
    result = analysis.analyze_source("def broken(:\n", "broken.py", [])
    assert result.error is not None and "syntax error" in result.error
    # An unparseable file fails the gate: it cannot be proven clean.
    broken = tmp_path / "broken.py"
    broken.write_text("def broken(:\n")
    run_result = analysis.run([str(broken)], analysis.default_rules())
    assert not run_result.ok
    assert run_result.errors and run_result.errors[0][0] == str(broken)


def test_unreadable_file_is_reported_not_raised(tmp_path):
    # A non-UTF8 file must fail the gate as a reported error, not crash
    # the whole run with a raw UnicodeDecodeError.
    binary = tmp_path / "binary.py"
    binary.write_bytes(b"\xff\xfe\x00junk")
    result = analysis.run([str(binary)], analysis.default_rules())
    assert not result.ok
    assert result.errors and "unreadable" in result.errors[0][1]


# ------------------------------------------------------------------------ CLI


def run_cli(*args):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.run(
        [sys.executable, "-m", "torchsnapshot_tpu.analysis", *args],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
        env=env,
        timeout=120,
    )


def test_cli_clean_on_package():
    proc = run_cli(PACKAGE)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 violation(s)" in proc.stdout


def test_cli_dirty_on_fixture_corpus_json():
    proc = run_cli("--format", "json", FIXTURES)
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["ok"] is False
    codes = {v["code"] for v in doc["violations"]}
    assert codes == {
        "SNAP001", "SNAP002", "SNAP003", "SNAP004", "SNAP005",
        "SNAP006", "SNAP007", "SNAP008", "SNAP009", "SNAP010",
        "SNAP011", "SNAP012", "SNAP013",
    }
    sample = doc["violations"][0]
    # Machine-readable contract: rule id, stable code, location, message.
    assert set(sample) >= {"rule", "code", "path", "line", "col", "message"}


def test_cli_baseline_roundtrip(tmp_path):
    bad = os.path.join(FIXTURES, "bad_durability.py")
    baseline = str(tmp_path / "baseline.json")
    wrote = run_cli("--write-baseline", baseline, bad)
    assert wrote.returncode == 0
    gated = run_cli("--baseline", baseline, bad)
    assert gated.returncode == 0
    assert "2 baselined" in gated.stdout


def test_cli_rule_filter_and_usage_errors():
    only_async = run_cli("--rules", "blocking-sync", FIXTURES)
    assert only_async.returncode == 1
    assert "SNAP001" in only_async.stdout
    assert "SNAP003" not in only_async.stdout
    bad_rule = run_cli("--rules", "no-such-rule", FIXTURES)
    assert bad_rule.returncode == 2
    # A nonexistent directory is a usage error; a nonexistent .py file
    # is reported like any unreadable file and fails the gate.
    missing_dir = run_cli("/no/such/dir")
    assert missing_dir.returncode == 2
    missing_file = run_cli("/no/such/path.py")
    assert missing_file.returncode == 1
    assert "unreadable" in missing_file.stdout


def test_cli_list_rules():
    proc = run_cli("--list-rules")
    assert proc.returncode == 0
    for code in (
        "SNAP001", "SNAP002", "SNAP003", "SNAP004", "SNAP005",
        "SNAP006", "SNAP007", "SNAP008", "SNAP009", "SNAP010",
        "SNAP011", "SNAP012", "SNAP013",
    ):
        assert code in proc.stdout


# ------------------------------------------------------------------ SARIF


def test_cli_sarif_output_shape():
    proc = run_cli(
        "--format", "sarif",
        os.path.join(FIXTURES, "bad_lifecycle.py"),
    )
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["version"] == "2.1.0"
    run0 = doc["runs"][0]
    driver = run0["tool"]["driver"]
    assert driver["name"] == "snapcheck"
    rule_ids = {r["id"] for r in driver["rules"]}
    assert "SNAP006" in rule_ids
    results = run0["results"]
    assert results, "expected findings in SARIF results"
    sample = results[0]
    assert sample["ruleId"].startswith("SNAP")
    assert sample["level"] == "error"
    loc = sample["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("bad_lifecycle.py")
    assert loc["region"]["startLine"] >= 1


def test_cli_sarif_clean_exits_zero():
    proc = run_cli(
        "--format", "sarif",
        os.path.join(FIXTURES, "good_lifecycle.py"),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["runs"][0]["results"] == []


def test_cli_sarif_baselined_findings_marked(tmp_path):
    bad = os.path.join(FIXTURES, "bad_swallowed.py")
    baseline = str(tmp_path / "baseline.json")
    assert run_cli("--write-baseline", baseline, bad).returncode == 0
    proc = run_cli("--format", "sarif", "--baseline", baseline, bad)
    assert proc.returncode == 0
    results = json.loads(proc.stdout)["runs"][0]["results"]
    assert results
    assert all(r["baselineState"] == "unchanged" for r in results)
    assert all(r["level"] == "note" for r in results)


# ----------------------------------------------------------- changed-only


def _git(cwd, *args):
    return subprocess.run(
        ["git", *args],
        cwd=cwd,
        capture_output=True,
        text=True,
        timeout=60,
        env={
            **os.environ,
            "GIT_AUTHOR_NAME": "t",
            "GIT_AUTHOR_EMAIL": "t@t",
            "GIT_COMMITTER_NAME": "t",
            "GIT_COMMITTER_EMAIL": "t@t",
        },
    )


def run_cli_in(cwd, *args):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    # The tmp repo is outside the source tree; keep the package
    # importable without an install.
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "torchsnapshot_tpu.analysis", *args],
        capture_output=True,
        text=True,
        cwd=cwd,
        env=env,
        timeout=120,
    )


def test_cli_changed_only(tmp_path):
    repo = tmp_path / "repo"
    repo.mkdir()
    assert _git(repo, "init", "-q").returncode == 0
    committed = repo / "committed.py"
    committed.write_text(
        "def swallow(op):\n"
        "    try:\n"
        "        return op()\n"
        "    except Exception:\n"
        "        return None\n"
    )
    _git(repo, "add", ".")
    assert _git(repo, "commit", "-q", "-m", "seed").returncode == 0

    # Nothing changed vs HEAD: exit 0 even though committed.py is dirty
    # by SNAP003 — the fast pre-commit path only lints the diff.
    clean = run_cli_in(repo, "--changed-only", "HEAD", ".")
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert "nothing to analyze" in clean.stdout

    # An untracked new file with a finding fails; the committed file's
    # pre-existing finding still does not enter the run.
    newfile = repo / "new.py"
    newfile.write_text(
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)\n"
    )
    dirty = run_cli_in(repo, "--changed-only", "HEAD", ".")
    assert dirty.returncode == 1
    assert "new.py" in dirty.stdout
    assert "committed.py" not in dirty.stdout

    # A bad ref is a usage error.
    bad_ref = run_cli_in(repo, "--changed-only", "no-such-ref", ".")
    assert bad_ref.returncode == 2


def test_cli_changed_only_sees_untracked_files_from_subdir(tmp_path):
    # `git ls-files --others` is cwd-relative; run from a subdirectory
    # the untracked file must still be joined to the repo root
    # correctly, or the pre-commit gate silently passes a violation.
    repo = tmp_path / "repo"
    sub = repo / "sub"
    sub.mkdir(parents=True)
    assert _git(repo, "init", "-q").returncode == 0
    (repo / "seed.py").write_text("X = 1\n")
    _git(repo, "add", ".")
    assert _git(repo, "commit", "-q", "-m", "seed").returncode == 0
    (sub / "new.py").write_text(
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)\n"
    )
    dirty = run_cli_in(sub, "--changed-only", "HEAD", ".")
    assert dirty.returncode == 1, dirty.stdout + dirty.stderr
    assert "new.py" in dirty.stdout


# ----------------------------------------------------- suppression gate


def test_cli_max_suppressions_gate(tmp_path):
    target = tmp_path / "suppressed_only.py"
    target.write_text(
        "def swallow(op):\n"
        "    try:\n"
        "        return op()\n"
        "    except Exception:  # snapcheck: disable=SNAP003 -- probe\n"
        "        return None\n"
    )
    ok = run_cli("--max-suppressions", "1", str(target))
    assert ok.returncode == 0, ok.stdout + ok.stderr
    blown = run_cli("--max-suppressions", "0", str(target))
    assert blown.returncode == 1
    assert "--max-suppressions" in blown.stderr
    # JSON `ok` must agree with the exit status when a gate trips.
    blown_json = run_cli(
        "--format", "json", "--max-suppressions", "0", str(target)
    )
    assert blown_json.returncode == 1
    assert json.loads(blown_json.stdout)["ok"] is False


# ------------------------------------------------------- baseline drift


def test_cli_fail_stale_baseline(tmp_path):
    bad = os.path.join(FIXTURES, "bad_swallowed.py")
    clean = os.path.join(FIXTURES, "good_swallowed.py")
    baseline = str(tmp_path / "baseline.json")
    assert run_cli("--write-baseline", baseline, bad).returncode == 0

    # The baseline's findings no longer match anything when run against
    # the clean file: without the flag that is tolerated...
    tolerated = run_cli("--baseline", baseline, clean)
    assert tolerated.returncode == 0
    # ...with the flag it is baseline rot and fails.
    stale = run_cli("--fail-stale-baseline", "--baseline", baseline, clean)
    assert stale.returncode == 1
    assert "stale baseline" in stale.stderr

    # A fully-consumed baseline passes the drift check.
    fresh = run_cli("--fail-stale-baseline", "--baseline", baseline, bad)
    assert fresh.returncode == 0, fresh.stdout + fresh.stderr


def test_run_result_reports_stale_entries():
    bad = os.path.join(FIXTURES, "bad_swallowed.py")
    rules = [SwallowedExceptionRule()]
    first = analysis.run([bad], rules)
    fake = dict.fromkeys(first.fingerprints, 1)
    fake["swallowed-exception::gone.py::deadbeef0000"] = 2
    result = analysis.run([bad], rules, baseline=fake)
    assert result.ok
    assert result.stale_baseline == {
        "swallowed-exception::gone.py::deadbeef0000": 2
    }
