"""Ulysses all-to-all sequence parallelism vs the dense reference, on
the 8-device virtual mesh — the second long-context strategy next to
ring attention (parallel/ulysses.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchsnapshot_tpu.ops.attention import _reference_attention
from torchsnapshot_tpu.parallel.ring_attention import shard_seq
from torchsnapshot_tpu.parallel.ulysses import ulysses_attention


def _qkv(shape, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(
        jax.random.normal(k, shape, jnp.float32) for k in ks
    )


def _assert_spec(out, spec):
    """Sharding-spec equality modulo trailing-None normalization: newer
    jax trims trailing Nones from a result's PartitionSpec, so compare
    both padded to the array's rank."""
    def padded(s):
        return tuple(s) + (None,) * (out.ndim - len(s))

    assert padded(out.sharding.spec) == padded(spec), (
        out.sharding.spec,
        spec,
    )


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("attn_impl", ["einsum", "flash"])
def test_ulysses_matches_dense(causal, attn_impl):
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _qkv((2, 8, 64, 16), seed=3)
    qs, ks_, vs = (shard_seq(t, mesh) for t in (q, k, v))
    out = ulysses_attention(
        qs, ks_, vs, mesh, causal=causal, attn_impl=attn_impl
    )
    _assert_spec(out, P(None, None, "sp", None))
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(_reference_attention(q, k, v, causal)),
        atol=3e-5,
        rtol=1e-5,
    )


def test_ulysses_preserves_batch_sharding():
    devices = np.array(jax.devices()).reshape(2, 4)
    mesh = Mesh(devices, ("dp", "sp"))
    q, k, v = _qkv((4, 8, 64, 16), seed=5)
    spec = P("dp", None, "sp", None)
    qs, ks_, vs = (
        jax.device_put(t, NamedSharding(mesh, spec)) for t in (q, k, v)
    )
    out = ulysses_attention(qs, ks_, vs, mesh, causal=True)
    _assert_spec(out, spec)
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(_reference_attention(q, k, v, True)),
        atol=3e-5,
        rtol=1e-5,
    )


@pytest.mark.parametrize("attn_impl", ["einsum", "flash"])
def test_ulysses_gradients_match_dense(attn_impl):
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _qkv((1, 8, 64, 8), seed=7)
    spec = P(None, None, "sp", None)

    def loss_u(q, k, v):
        out = ulysses_attention(
            q, k, v, mesh, causal=True, spec=spec, attn_impl=attn_impl
        )
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def loss_d(q, k, v):
        return jnp.sum(_reference_attention(q, k, v, True) ** 2)

    qs, ks_, vs = (shard_seq(t, mesh) for t in (q, k, v))
    gu = jax.grad(loss_u, argnums=(0, 1, 2))(qs, ks_, vs)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-4
        )


def test_ulysses_gqa_matches_repeated_kv():
    """GQA through the all-to-all: kv heads must also divide the axis;
    8 q / 8 kv over sp=8 works, as does 16 q / 8 kv."""
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    ks = jax.random.split(jax.random.key(9), 3)
    q = jax.random.normal(ks[0], (1, 16, 64, 8), jnp.float32)
    k = jax.random.normal(ks[1], (1, 8, 64, 8), jnp.float32)
    v = jax.random.normal(ks[2], (1, 8, 64, 8), jnp.float32)
    qs, ks_, vs = (shard_seq(t, mesh) for t in (q, k, v))
    out = ulysses_attention(qs, ks_, vs, mesh, causal=True, attn_impl="flash")
    expected = _reference_attention(
        q, jnp.repeat(k, 2, axis=1), jnp.repeat(v, 2, axis=1), True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), atol=3e-5, rtol=1e-5
    )


def test_ulysses_rejects_indivisible_heads():
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q, k, v = _qkv((1, 4, 64, 8))  # 4 heads, sp=8
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v, mesh)


def test_transformer_ulysses_mode_matches_dense():
    """TransformerConfig(ring_attention="ulysses"): loss and a train
    step on a dp x sp mesh match the dense einsum config."""
    from torchsnapshot_tpu.models.transformer import (
        TransformerConfig,
        init_params,
        loss_fn,
        sgd_train_step,
    )

    devices = np.array(jax.devices()).reshape(2, 4)
    mesh = Mesh(devices, ("dp", "sp"))
    kw = dict(
        vocab_size=64, d_model=64, n_heads=8, n_layers=2, d_ff=64,
        max_seq_len=32,
    )
    base = TransformerConfig(**kw)
    uly = TransformerConfig(**kw, ring_attention="ulysses")
    params = init_params(base, jax.random.key(0))
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (4, 32), 0, 64),
        NamedSharding(mesh, P("dp", "sp")),
    )
    loss_base = jax.jit(lambda p, t: loss_fn(p, t, base, mesh))(params, tokens)
    loss_uly = jax.jit(lambda p, t: loss_fn(p, t, uly, mesh))(params, tokens)
    np.testing.assert_allclose(
        float(loss_base), float(loss_uly), rtol=1e-5
    )
    _, loss = jax.jit(
        lambda p, t: sgd_train_step(p, t, config=uly, mesh=mesh)
    )(params, tokens)
    assert np.isfinite(float(loss))
