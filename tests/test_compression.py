"""Lossless payload compression (beyond reference parity).

``Snapshot.take(..., compression="zlib")`` compresses every stored payload;
restore is driven by per-entry manifest metadata so it needs no flag and
mixed (compressed + uncompressed) snapshots restore transparently.
Compressed chunks forgo ranged reads (byte offsets into a compressed
stream are meaningless), exercising the whole-chunk scatter path of
ArrayRestorePlan.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.manifest import ArrayEntry, ObjectEntry
from torchsnapshot_tpu.serialization import (
    check_compression,
    compress_payload,
    decompress_payload,
)


class _Holder:
    def __init__(self, sd):
        self.sd = sd

    def state_dict(self):
        return self.sd

    def load_state_dict(self, sd):
        self.sd = sd


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("x",))


def test_compress_roundtrip_unit():
    payload = b"abc" * 1000
    comp = compress_payload(payload, "zlib")
    assert len(comp) < len(payload)
    assert decompress_payload(comp, "zlib") == payload


def test_unknown_algo_rejected():
    with pytest.raises(ValueError, match="Unknown compression"):
        check_compression("lz77")
    with pytest.raises(ValueError, match="Unknown compression"):
        Snapshot.take("/tmp/never-created", {"s": StateDict(x=1)}, compression="bad")


def test_take_restore_compressed(tmp_path):
    # Compressible state: structured arrays, an object, primitives.
    state = {
        "w": jnp.asarray(np.tile(np.arange(64, dtype=np.float32), 512)),
        "b16": jnp.zeros((128, 33), dtype=jnp.bfloat16),
        "obj": set(range(300)),  # non-container leaf -> pickled ObjectEntry
        "step": 7,
    }
    app = {"m": _Holder(state)}
    Snapshot.take(str(tmp_path / "snap"), app, compression="zlib")

    target = _Holder(
        {
            "w": jnp.zeros((64 * 512,), dtype=jnp.float32),
            "b16": jnp.ones((128, 33), dtype=jnp.bfloat16),
            "obj": None,
            "step": 0,
        }
    )
    Snapshot(str(tmp_path / "snap")).restore({"m": target})
    np.testing.assert_array_equal(np.asarray(target.sd["w"]), np.asarray(state["w"]))
    np.testing.assert_array_equal(
        np.asarray(target.sd["b16"]), np.asarray(state["b16"])
    )
    assert target.sd["obj"] == state["obj"]
    assert target.sd["step"] == 7


def test_compressed_files_smaller_and_manifest_tagged(tmp_path):
    w = jnp.zeros((1024, 256), dtype=jnp.float32)  # 1 MiB of zeros
    Snapshot.take(str(tmp_path / "snap"), {"m": _Holder({"w": w})}, compression="zlib")
    stored = tmp_path / "snap" / "0" / "m" / "w"
    assert stored.stat().st_size < w.nbytes // 100

    manifest = Snapshot(str(tmp_path / "snap")).get_manifest()
    entry = manifest["0/m/w"]
    assert isinstance(entry, ArrayEntry)
    assert entry.compression == "zlib"
    assert entry.checksum is not None  # checksum covers stored bytes


def test_sharded_compressed_elastic_restore(tmp_path):
    """Sharded + compressed: whole-chunk reads with scatter on reshard."""
    data = np.tile(np.arange(32, dtype=np.float32), (64, 1))  # (64, 32)
    arr = jax.device_put(data, NamedSharding(_mesh(8), P("x", None)))
    Snapshot.take(str(tmp_path / "snap"), {"m": _Holder({"w": arr})}, compression="zlib")

    # Restore onto a different sharding (4-way on the other axis).
    template = jax.device_put(
        jnp.zeros((64, 32), dtype=jnp.float32),
        NamedSharding(_mesh(4), P(None, "x")),
    )
    target = _Holder({"w": template})
    Snapshot(str(tmp_path / "snap")).restore({"m": target})
    np.testing.assert_array_equal(np.asarray(target.sd["w"]), data)


def test_compressed_corruption_detected(tmp_path):
    w = jnp.asarray(np.arange(4096, dtype=np.float32))
    Snapshot.take(str(tmp_path / "snap"), {"m": _Holder({"w": w})}, compression="zlib")
    stored = tmp_path / "snap" / "0" / "m" / "w"
    payload = bytearray(stored.read_bytes())
    payload[len(payload) // 2] ^= 0xFF
    stored.write_bytes(bytes(payload))

    target = _Holder({"w": jnp.zeros((4096,), dtype=jnp.float32)})
    with pytest.raises(Exception, match="[Cc]hecksum|corrupt|invalid"):
        Snapshot(str(tmp_path / "snap")).restore({"m": target})


def test_read_object_compressed(tmp_path):
    w = np.arange(1000, dtype=np.int64)
    Snapshot.take(
        str(tmp_path / "snap"),
        {"m": _Holder({"w": jnp.asarray(w), "o": {"k", "v"}})},
        compression="zlib",
    )
    snap = Snapshot(str(tmp_path / "snap"))
    np.testing.assert_array_equal(np.asarray(snap.read_object("m/w")), w)
    assert snap.read_object("m/o") == {"k", "v"}
    entry = snap.get_manifest()["0/m/o"]
    assert isinstance(entry, ObjectEntry) and entry.compression == "zlib"


def test_mixed_snapshot_restores_uncompressed_entries(tmp_path):
    """A snapshot written without compression restores identically after the
    flag is introduced (per-entry metadata, no global mode)."""
    w = jnp.asarray(np.arange(256, dtype=np.float32))
    Snapshot.take(str(tmp_path / "snap"), {"m": _Holder({"w": w})})
    target = _Holder({"w": jnp.zeros((256,), dtype=jnp.float32)})
    Snapshot(str(tmp_path / "snap")).restore({"m": target})
    np.testing.assert_array_equal(np.asarray(target.sd["w"]), np.asarray(w))
    entry = Snapshot(str(tmp_path / "snap")).get_manifest()["0/m/w"]
    assert entry.compression is None
