"""Orbax interop tests (beyond reference parity): the JAX ecosystem's
incumbent checkpointer, two-way. Gated on orbax being importable."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

ocp = pytest.importorskip("orbax.checkpoint")

from torchsnapshot_tpu import Snapshot  # noqa: E402
from torchsnapshot_tpu.interop.orbax_format import (  # noqa: E402
    convert_from_orbax,
    convert_to_orbax,
)


class _Holder:
    def __init__(self, sd):
        self.sd = sd

    def state_dict(self):
        return self.sd

    def load_state_dict(self, sd):
        self.sd = sd


def test_orbax_to_native(tmp_path):
    """orbax checkpoint -> native snapshot: leaves readable through the
    native random-access API, full restore bit-exact, verify clean."""
    tree = {
        "params": {
            "w": jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
            "b16": jnp.arange(16, dtype=jnp.bfloat16),
        },
        "step": np.int64(7),
    }
    orbax_dir = str(tmp_path / "orbax_ckpt")
    ocp.PyTreeCheckpointer().save(orbax_dir, tree)

    native = str(tmp_path / "native")
    snap = convert_from_orbax(orbax_dir, native)

    np.testing.assert_array_equal(
        snap.read_object("state/params/w"),
        np.arange(64, dtype=np.float32).reshape(8, 8),
    )
    got_b16 = snap.read_object("state/params/b16")
    assert got_b16.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        got_b16.view(np.uint16),
        np.asarray(tree["params"]["b16"]).view(np.uint16),
    )
    assert snap.read_object("state/step") == 7
    assert snap.verify() == {}

    target = _Holder(
        {
            "params": {
                "w": jnp.zeros((8, 8), dtype=jnp.float32),
                "b16": jnp.zeros((16,), dtype=jnp.bfloat16),
            },
            "step": np.int64(0),
        }
    )
    Snapshot(native).restore({"state": target})
    np.testing.assert_array_equal(
        np.asarray(target.sd["params"]["w"]),
        np.arange(64, dtype=np.float32).reshape(8, 8),
    )


def test_native_to_orbax_roundtrip(tmp_path):
    """native snapshot -> orbax checkpoint -> orbax restore, bit-exact;
    multi-stateful app states export under their own keys."""
    native = str(tmp_path / "native")
    Snapshot.take(
        native,
        {
            "model": _Holder({"w": jnp.arange(32.0), "depth": 4}),
            "opt": _Holder({"m": jnp.ones((4, 4))}),
        },
    )
    orbax_dir = str(tmp_path / "orbax_out")
    convert_to_orbax(native, orbax_dir)

    restored = ocp.PyTreeCheckpointer().restore(orbax_dir)
    np.testing.assert_array_equal(
        np.asarray(restored["model"]["w"]), np.arange(32, dtype=np.float32)
    )
    assert restored["model"]["depth"] == 4
    np.testing.assert_array_equal(
        np.asarray(restored["opt"]["m"]), np.ones((4, 4), dtype=np.float32)
    )


def test_native_to_orbax_single_stateful_and_sharded(tmp_path):
    """stateful_key exports one stateful as the bare tree; sharded
    arrays assemble dense through the availability union."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = np.array(jax.devices()[:4])
    mesh = Mesh(devices, ("x",))
    sharded = jax.device_put(
        jnp.arange(32, dtype=jnp.float32).reshape(8, 4),
        NamedSharding(mesh, P("x", None)),
    )
    native = str(tmp_path / "native")
    Snapshot.take(native, {"train": _Holder({"emb": sharded})})

    orbax_dir = str(tmp_path / "orbax_out")
    convert_to_orbax(native, orbax_dir, stateful_key="train")
    restored = ocp.PyTreeCheckpointer().restore(orbax_dir)
    np.testing.assert_array_equal(
        np.asarray(restored["emb"]),
        np.arange(32, dtype=np.float32).reshape(8, 4),
    )

    with pytest.raises(KeyError, match="not a top-level stateful"):
        convert_to_orbax(native, str(tmp_path / "x"), stateful_key="nope")


def test_orbax_roundtrip_through_native(tmp_path):
    """Full circle: orbax -> native -> orbax preserves the tree."""
    tree = {"a": jnp.arange(8.0), "nested": {"b": jnp.full((3,), 2.0)}}
    src = str(tmp_path / "src")
    ocp.PyTreeCheckpointer().save(src, tree)
    native = str(tmp_path / "native")
    convert_from_orbax(src, native)
    back = str(tmp_path / "back")
    convert_to_orbax(native, back, stateful_key="state")
    restored = ocp.PyTreeCheckpointer().restore(back)
    np.testing.assert_array_equal(np.asarray(restored["a"]), np.arange(8.0))
    np.testing.assert_array_equal(
        np.asarray(restored["nested"]["b"]), np.full((3,), 2.0)
    )


def test_native_to_orbax_refuses_foreign_per_rank(tmp_path):
    """A multi-rank snapshot with per-rank values refuses the flat
    export (an orbax checkpoint has no rank dimension) unless the
    partial view is explicitly requested — mirroring
    ReferenceSnapshotReader.convert's refusal."""
    from torchsnapshot_tpu.utils.test_utils import run_thread_ranks

    native = str(tmp_path / "native")

    def worker(coord, rank):
        Snapshot.take(
            native,
            {
                "m": _Holder(
                    {
                        "mine": np.full((4,), rank, dtype=np.float32),
                        # Per-rank PRIMITIVE (inline, no location):
                        # must also trip the foreign detection.
                        "count": rank,
                        "shared": np.arange(8, dtype=np.float32),
                    }
                )
            },
            coord=coord,
            replicated=["m/shared"],
        )

    run_thread_ranks(2, worker)

    with pytest.raises(RuntimeError, match="per-rank values owned by"):
        convert_to_orbax(native, str(tmp_path / "flat"))

    # Explicit per-rank exports work, each rank's view to its own dir.
    for rank in range(2):
        out = str(tmp_path / f"rank{rank}")
        convert_to_orbax(native, out, rank=rank, allow_partial=True)
        restored = ocp.PyTreeCheckpointer().restore(out)
        np.testing.assert_array_equal(
            np.asarray(restored["m"]["mine"]),
            np.full((4,), rank, dtype=np.float32),
        )
        np.testing.assert_array_equal(
            np.asarray(restored["m"]["shared"]),
            np.arange(8, dtype=np.float32),
        )
        assert restored["m"]["count"] == rank


def test_allow_partial_skips_foreign_stateful(tmp_path):
    """A stateful owned entirely by another rank exports as ABSENT (with
    a warning) under allow_partial, instead of raising mid-export."""
    from torchsnapshot_tpu.utils.test_utils import run_thread_ranks

    native = str(tmp_path / "native")

    def worker(coord, rank):
        state = {"m": _Holder({"w": np.arange(4, dtype=np.float32)})}
        if rank == 1:
            state["sched"] = _Holder({"t": np.float32(0.5)})
        Snapshot.take(native, state, coord=coord, replicated=["m/w"])

    run_thread_ranks(2, worker)
    out = str(tmp_path / "partial")
    convert_to_orbax(native, out, rank=0, allow_partial=True)
    restored = ocp.PyTreeCheckpointer().restore(out)
    assert "sched" not in restored
    np.testing.assert_array_equal(
        np.asarray(restored["m"]["w"]), np.arange(4, dtype=np.float32)
    )
