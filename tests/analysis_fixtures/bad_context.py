"""SNAP008 positive fixtures: contextvar reads across thread hops."""
import contextvars
import threading

from torchsnapshot_tpu import tracing

_ACCUMULATOR = contextvars.ContextVar("fixture_accumulator", default=None)


def submit_callback_reads_trace(executor):
    def on_done():
        return tracing.current_trace_id()

    executor.submit(on_done)


def drain_thread_emits_span(payloads):
    def loop():
        with tracing.span("drain", n=len(payloads)):
            return list(payloads)

    threading.Thread(target=loop).start()


def callback_reads_accumulator(executor):
    def fold(result):
        scope = _ACCUMULATOR.get()
        if scope is not None:
            scope.append(result)

    executor.submit(fold, 1)
