"""Fixture: server half of a wire transport that violates SNAP010/013."""

from torchsnapshot_tpu import wire


def fingerprint(data):
    return len(data)


class Store:
    def __init__(self):
        self.blobs = {}

    def put_replica(self, key, data):
        self.blobs[key] = data


class BadServer:
    def __init__(self):
        self.store = Store()

    async def handle_conn(self, reader, writer):
        while True:
            header, payload = await wire.recv_frame(reader)
            response, blob = await self.handle(header, payload)
            await wire.send_frame(writer, response, blob)

    async def handle(self, header, payload):
        op = header.get("op")
        nonce = header.get("nonce")
        if op == "get":
            data = self.store.blobs.get(header.get("key"), b"")
            return {"v": 1, "ok": True, "data": nonce}, data
        if op == "put":
            return self._do_put(header, payload), b""
        if op == "stale":
            return {"v": 1, "ok": True}, b""
        return {"v": 1, "ok": False, "error": "bad_request"}, b""

    def _do_put(self, header, payload):
        key = header.get("key")
        self.store.put_replica(key, payload)
        if fingerprint(payload) != header.get("tag"):
            return {"v": 1, "ok": False, "error": "corrupt_push"}
        return {"v": 1, "ok": True}

    async def ack_then_store(self, header, payload, writer):
        await wire.send_frame(writer, {"v": 1, "ok": True}, b"")
        self.store.put_replica(header.get("key"), payload)
