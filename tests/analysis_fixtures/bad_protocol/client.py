"""Fixture: client half of a wire transport that violates SNAP010-012."""

import asyncio
import time

from torchsnapshot_tpu import wire

IDEMPOTENT_OPS = frozenset({"get", "put"})


class BadClient:
    def __init__(self, host, port):
        self.host = host
        self.port = port

    async def rpc(self, doc, payload):
        reader, writer = await asyncio.open_connection(self.host, self.port)
        await wire.send_frame(writer, doc, payload)
        return await wire.recv_frame(reader)

    def call(self, header, payload=b""):
        while True:
            try:
                return asyncio.run(self.rpc(header, payload))
            except OSError:
                time.sleep(1.0)

    def fetch(self, key):
        resp, _ = self.call({"v": 1, "op": "fetch", "key": key})
        return resp.get("blob")

    def get(self, key):
        resp, _ = self.call({"v": 1, "op": "get", "key": key})
        return resp.get("data")

    def push(self, key, data, tag):
        resp, _ = self.call({"v": 1, "op": "put", "key": key, "tag": tag}, data)
        return resp.get("ok")
