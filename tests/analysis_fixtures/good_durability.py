"""Fixture: fsync before rename makes the publish crash-safe."""
import os


def write_marker(path, payload):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_marker_bare_fsync(path, payload):
    # The bare-call spelling of the same durable sequence.
    from os import fsync

    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        fsync(f.fileno())
    os.replace(tmp, path)


def append_record_durable(path, line):
    # Append-only log with the append fsync'd before success.
    with open(path, "a") as f:
        f.write(line + "\n")
        f.flush()
        os.fsync(f.fileno())


def append_record_ephemeral(path, line):
    with open(path, "a") as f:
        # snapcheck: disable=durability-order -- ephemeral log fixture
        f.write(line + "\n")
