"""SNAP006 negative fixtures: every obligation discharged on all paths."""
from torchsnapshot_tpu import tracing


def released_in_finally(pool, nbytes, consume):
    lease = pool.acquire(nbytes)
    try:
        consume(lease.buffer)
    finally:
        lease.release()


def conditional_release_joined(pool, nbytes, consume, fast):
    lease = pool.acquire(nbytes)
    try:
        if fast:
            consume(lease.buffer)
    finally:
        lease.release()


def ownership_transferred(pool, nbytes, state):
    lease = pool.acquire(nbytes)
    state.attach(lease)  # the state object releases at teardown


def handle_stored_on_self(self_like, pool, nbytes):
    self_like._lease = pool.acquire(nbytes)


def released_via_closure_handoff(pool, nbytes, executor):
    lease = pool.acquire(nbytes)

    def done():
        lease.release()

    executor.submit(done)


def write_through_paired_on_all_paths(rt, root, path, write_durable):
    rt.begin_write_through(root, path)
    try:
        write_durable(path)
    except Exception:
        rt.abort_write_through(root, path)
        raise
    rt.note_write_through(root, path)


def budget_handed_off(budget, consumer, cost):
    budget.charge(cost)
    consumer.set_cost_releaser(budget.release)


def span_as_context_manager(path):
    with tracing.span("write", path=path):
        return path


def lease_in_loop_released(pool, sizes, consume):
    for nbytes in sizes:
        lease = pool.acquire(nbytes)
        try:
            consume(lease.buffer)
        finally:
            lease.release()
