"""SNAP007 negative fixtures: blocking work routed off the loop."""
import asyncio
import subprocess
import time


class ReadHandler:
    def _read_sync(self, req):
        return open(req).read()

    async def handle_read(self, req):
        loop = asyncio.get_running_loop()
        # Executor-routed: the helper is an argument, not a call.
        return await loop.run_in_executor(None, self._read_sync, req)

    async def handle_lock(self, req):
        # asyncio primitives are awaited, not thread-blocking.
        await self._cache_lock.acquire()
        try:
            return self._cache[req]
        finally:
            self._cache_lock.release()

    async def handle_lock_with_timeout(self, req):
        self._fallback_lock.acquire(timeout=0.1)
        try:
            return req
        finally:
            self._fallback_lock.release()

    def probe(self, cmd):
        # Blocking in a sync function never called from async code is
        # fine — it runs wherever its (sync) caller runs.
        return subprocess.check_output(cmd)


def _backoff_helper(seconds):
    time.sleep(seconds)


def sync_retry_loop(op):
    # sync-to-sync call chain with no async root: not the loop's business.
    _backoff_helper(0.5)
    return op()


async def drain_step(item):
    await asyncio.to_thread(_backoff_helper, 0.5)
    return item
