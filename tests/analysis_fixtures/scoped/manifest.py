"""Fixture: deterministic serialization passes SNAP004.

Named ``manifest.py`` so the rule's default module scoping applies.
"""
import json


def dump_manifest(doc):
    return json.dumps(doc, sort_keys=True)


def iter_entries(entries):
    out = []
    for e in sorted(set(entries)):
        out.append(e)
    return out
