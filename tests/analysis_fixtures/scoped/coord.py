"""Fixture: lock-disciplined mutations pass SNAP005.

Named ``coord.py`` so the rule's default module scoping applies.
"""
import threading


class Store:
    def __init__(self):
        self._data = {}
        self._cond = threading.Condition()

    def set(self, key, value):
        with self._cond:
            self._data[key] = value
            self._cond.notify_all()

    def delete(self, key):
        with self._cond:
            self._data.pop(key, None)

    def get(self, key):
        with self._cond:
            return self._data.get(key)

    def annotate_only(self):
        # Bare annotation: declares, not mutates -- must not be flagged.
        self.hint: int
        return getattr(self, "hint", None)


class Confined:
    """No lock attribute anywhere: the class is not checked."""

    def __init__(self):
        self.items = []

    def push(self, x):
        self.items.append(x)
