"""Fixture: nondeterminism in a serialization-scoped module (SNAP004).

Named ``fingerprint.py`` so the rule's default module scoping applies.
"""
import json
import random
import time
import yaml


def fingerprint(payload):
    salt = time.time()
    jitter = random.random()
    tag = hash(str(payload))
    return salt, jitter, tag


def dump_manifest(doc):
    return json.dumps(doc)


def dump_manifest_yaml(doc):
    return yaml.dump(doc, sort_keys=False)


def iter_entries(entries):
    out = []
    for e in set(entries):
        out.append(e)
    return out
