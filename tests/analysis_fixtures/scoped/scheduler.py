"""Fixture: lockset violations in a concurrency-scoped module (SNAP005).

Named ``scheduler.py`` so the rule's default module scoping applies.
"""
import threading

_MODULE_LOCK = threading.Lock()
_singleton = None


class Cell:
    def __init__(self):
        self.value = 0
        self.history = []
        self._lock = threading.Lock()

    def charge(self, n):
        self.value -= n

    def record(self, n):
        self.history.append(n)

    def release(self, n):
        with self._lock:
            self.value += n


class Tally:
    """No lock attribute: presumed thread-confined, class scope unchecked."""

    def __init__(self):
        self.count = 0

    def bump(self):
        self.count += 1

    def run(self, executor):
        def _cb():
            self.count += 1

        executor.submit(_cb)

    def run_nonlocal(self, loop, executor):
        total = 0

        def _cb2():
            nonlocal total
            total = total + 1

        loop.run_in_executor(executor, _cb2)
        return total


def set_singleton(value):
    global _singleton
    _singleton = value


def set_singleton_locked(value):
    global _singleton
    with _MODULE_LOCK:
        _singleton = value


def bump_singleton():
    global _singleton
    _singleton += 1
