"""SNAP008 negative fixtures: context captured or adopted across hops."""
import contextvars
import threading

from torchsnapshot_tpu import tracing

_ACCUMULATOR = contextvars.ContextVar("fixture_accumulator", default=None)


def value_captured_outside(executor):
    # The safe idiom: read in the submitting thread, close over the value.
    tid = tracing.current_trace_id()

    def on_done():
        with tracing.adopt_trace(tid):
            return tid

    executor.submit(on_done)


def drain_thread_adopts(payloads, trace_id):
    def loop():
        with tracing.adopt_trace(trace_id):
            with tracing.span("drain", n=len(payloads)):
                return list(payloads)

    threading.Thread(target=loop).start()


def whole_context_copied(executor, work):
    ctx = contextvars.copy_context()
    executor.submit(ctx.run, work)


def accumulator_passed_explicitly(executor):
    scope = _ACCUMULATOR.get()

    def fold(result):
        if scope is not None:
            scope.append(result)

    executor.submit(fold, 1)
