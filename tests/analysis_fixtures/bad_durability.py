"""Fixture: rename publishes un-fsynced file data (SNAP002)."""
import os


def write_marker(path, payload):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
    os.replace(tmp, path)
