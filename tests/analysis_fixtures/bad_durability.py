"""Fixture: rename publishes un-fsynced file data (SNAP002)."""
import os


def write_marker(path, payload):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
    os.replace(tmp, path)


def append_record(path, line):
    # Append-only log: the write IS the publish, but nothing fsyncs it
    # before the function signals success.
    with open(path, "a") as f:
        f.write(line + "\n")
