"""Fixture: inline suppressions silence individual findings."""
import time


async def wait_inline():
    time.sleep(0.01)  # snapcheck: disable=blocking-sync -- fixture: same-line form


async def wait_above():
    # snapcheck: disable=blocking-sync -- fixture: comment-line form
    time.sleep(0.01)


async def wait_unsuppressed():
    time.sleep(0.01)


def swallow():
    try:
        return 1
    except Exception:  # snapcheck: disable=swallowed-exception -- fixture
        return None
