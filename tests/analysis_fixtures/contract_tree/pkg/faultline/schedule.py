"""SNAP009 positive: a FaultRule kind missing from docs/FAULTS.md."""


class FaultRule:
    def __init__(self, kind, op):
        self.kind = kind
        self.op = op


def documented_rule(op):
    return FaultRule(kind="fixture_documented", op=op)


def undocumented_rule(op):
    return FaultRule(kind="fixture_undocumented", op=op)
