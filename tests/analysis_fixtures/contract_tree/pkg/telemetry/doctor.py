"""SNAP009 positive: a doctor rule id missing from the doc table."""


class Finding:
    def __init__(self, rule, severity, title):
        self.rule = rule
        self.severity = severity
        self.title = title


def rule_documented(report):
    return Finding("fixture-documented-rule", "warn", "ok")


def rule_undocumented(report):
    return Finding("fixture-undocumented-rule", "warn", "missing")
