"""SNAP009 positive: a ledger digest field missing from the schema doc."""


def digest_from_report(report):
    return {
        "fixture_documented_field": report.get("wall_s"),
        "fixture_undocumented_field": report.get("gbps"),
    }
