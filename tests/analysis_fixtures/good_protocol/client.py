"""Fixture: client half of a wire transport that satisfies SNAP010-013."""

import asyncio
import random
import time

from torchsnapshot_tpu import wire

WIRE_OPS = {
    "get": {"handler": "_do_get", "retry": "budget"},
    "put": {"handler": "_do_put", "retry": "budget"},
    "ping": {"handler": "_do_ping", "retry": "probe"},
}

IDEMPOTENT_OPS = frozenset(WIRE_OPS)

_rng = random.Random(0x5EED)


class GoodClient:
    def __init__(self, host, port):
        self.host = host
        self.port = port

    async def _rpc(self, doc, payload, deadline_s):
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port), deadline_s
        )
        await asyncio.wait_for(
            wire.send_frame(writer, doc, payload), deadline_s
        )
        return await asyncio.wait_for(wire.recv_frame(reader), deadline_s)

    def call(self, header, payload=b"", budget_s=30.0):
        start = time.monotonic()
        delay = 0.05
        while True:
            try:
                return asyncio.run(self._rpc(header, payload, 5.0))
            except OSError:
                delay = _rng.uniform(0.05, max(0.05, delay * 3.0))
                if time.monotonic() - start + delay > budget_s:
                    raise
                time.sleep(delay)

    def get(self, key):
        resp, _ = self.call({"v": 1, "op": "get", "key": key})
        return resp.get("data")

    def put(self, key, data, tag):
        doc = {"v": 1, "op": "put", "key": key, "tag": tag}
        resp, _ = self.call(doc, data)
        return resp.get("stored")

    def ping(self):
        resp, _ = self.call({"v": 1, "op": "ping"})
        return resp.get("ok")
