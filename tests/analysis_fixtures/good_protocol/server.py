"""Fixture: server half of a wire transport that satisfies SNAP010-013."""

from torchsnapshot_tpu import wire

from .client import WIRE_OPS


def _fingerprint(data):
    return len(data)


class GoodServer:
    def __init__(self):
        self.blobs = {}
        self.tags = {}

    async def handle_conn(self, reader, writer):
        while True:
            header, payload = await wire.recv_frame(reader)
            response, blob = self._dispatch(header, payload)
            await wire.send_frame(writer, response, blob)

    def _dispatch(self, header, payload):
        meta = WIRE_OPS.get(header.get("op"))
        if meta is None:
            return {"v": 1, "ok": False, "error": "bad_request"}, b""
        handler = getattr(self, meta["handler"])
        return handler(header, payload)

    def _do_get(self, header, payload):
        data = self.blobs.get(header.get("key"), b"")
        return {"v": 1, "ok": True, "data": len(data)}, data

    def _do_put(self, header, payload):
        stored_tag = _fingerprint(payload)
        if stored_tag != header.get("tag"):
            return {"v": 1, "ok": False, "error": "corrupt_push"}, b""
        self.put_replica(header.get("key"), payload, stored_tag)
        return {"v": 1, "ok": True, "stored": True}, b""

    def _do_ping(self, header, payload):
        return {"v": 1, "ok": True}, b""

    def put_replica(self, key, data, tag):
        self.blobs[key] = data
        self.tags[key] = tag
