"""SNAP009 positive: env knobs the sibling docs/api.md does not list."""
import os

_INTERVAL_ENV_VAR = "TPUSNAPSHOT_FIXTURE_INTERVAL_S"


def documented_knob():
    return os.environ.get("TPUSNAPSHOT_FIXTURE_DOCUMENTED", "1")


def undocumented_knob():
    return os.environ.get("TPUSNAPSHOT_FIXTURE_KNOB", "0")
