"""SNAP009 positive: a metric name missing from docs/OBSERVABILITY.md."""

FIXTURE_DOCUMENTED = "tpusnapshot_fixture_documented_total"  # counter
FIXTURE_UNDOCUMENTED = "tpusnapshot_fixture_undocumented_total"  # counter
