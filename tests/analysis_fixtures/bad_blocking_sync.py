"""Fixture: blocking device syncs inside async functions (SNAP001)."""
import time
import jax
import numpy as np


async def stage(x):
    x.block_until_ready()
    host = jax.device_get(x)
    arr = np.asarray(x)
    time.sleep(0.1)
    return host, arr
