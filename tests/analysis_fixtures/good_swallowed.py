"""Fixture: broad handlers that propagate, log, or use the failure."""
import logging
import traceback

logger = logging.getLogger(__name__)


def retry(op):
    try:
        return op()
    except Exception:
        logger.warning("op failed", exc_info=True)
        return None


def reraise(op):
    try:
        return op()
    except Exception:
        raise


def classify(op, problems):
    try:
        return op()
    except Exception as e:
        problems.append(f"failed: {e!r}")
        return None


def capture(op, errors):
    try:
        return op()
    except BaseException:
        errors.append(traceback.format_exc())
        return None


def narrow(op):
    # Narrow catches are out of scope for the rule.
    try:
        return op()
    except FileNotFoundError:
        return None
