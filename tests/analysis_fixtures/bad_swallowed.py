"""Fixture: broad handlers that discard failures (SNAP003)."""


def retry(op):
    try:
        return op()
    except Exception:
        return None


def cleanup(paths, remove):
    for p in paths:
        try:
            remove(p)
        except:  # noqa: E722
            pass


def tolerant(op):
    try:
        op()
    except BaseException:
        return False
    return True
