"""SNAP007 positive fixtures: blocking work on the event loop."""
import subprocess
import time


class ReadHandler:
    def _read_sync(self, req):
        return open(req).read()

    async def handle_read(self, req):
        # Sync storage helper called directly on the loop: every
        # in-flight request stalls behind this read.
        return self._read_sync(req)

    async def handle_lock(self, req):
        self._cache_lock.acquire()
        try:
            return self._cache[req]
        finally:
            self._cache_lock.release()

    async def handle_probe(self, cmd):
        return subprocess.check_output(cmd)


def _backoff_helper(seconds):
    time.sleep(seconds)


async def drain_step(item):
    # Transitively blocking: the helper runs on the loop.
    _backoff_helper(0.5)
    return item
