"""Fixture: a file-wide suppression silences one rule everywhere."""
# snapcheck: disable-file=swallowed-exception
import time


def swallow_one(op):
    try:
        return op()
    except Exception:
        return None


def swallow_two(op):
    try:
        return op()
    except Exception:
        return None


async def still_flagged():
    time.sleep(0.01)
