"""SNAP006 positive fixtures: dropped/double/discarded obligations."""
from torchsnapshot_tpu import tracing


def leaked_lease_on_exception_edge(pool, nbytes, consume):
    lease = pool.acquire(nbytes)
    consume(lease.buffer)  # may raise -> release never runs
    lease.release()


def double_release(pool, nbytes, consume, degraded):
    lease = pool.acquire(nbytes)
    try:
        if degraded:
            lease.release()
    finally:
        lease.release()


def discarded_acquire(pool, nbytes):
    pool.acquire(nbytes)


def write_through_dropped_on_failure(rt, root, path, write_durable):
    rt.begin_write_through(root, path)
    write_durable(path)  # raising skips BOTH note and abort
    rt.note_write_through(root, path)


def bare_span_never_enters(path):
    tracing.span("write", path=path)


def release_skipped_on_early_return(pool, nbytes, cond):
    lease = pool.acquire(nbytes)
    if cond:
        return None
    lease.release()
    return True
