"""Fixture: sync helpers may block; async code defers to executors."""
import asyncio
import time
import numpy as np


def _stage_sync(x):
    # Blocking is fine here: sync helpers run inside a thread executor.
    time.sleep(0.001)
    return np.asarray(x)


async def stage(x, executor):
    loop = asyncio.get_running_loop()
    await asyncio.sleep(0)
    return await loop.run_in_executor(executor, _stage_sync, x)
