"""GCS / S3 plugin logic tests against in-memory fake clients.

The reference tests cloud plugins only against real buckets, gated by env
vars and skipped in CI (tests/test_s3_storage_plugin.py:25,
tests/test_gcs_storage_plugin.py:25). Here the plugins accept an injected
client, so their request-shaping logic — key layout, ranged-read header
semantics (both services use *inclusive* end offsets), BytesIO vs bytes
write paths, delete — is exercised hermetically. Real-bucket smoke tests
remain possible by omitting the injection.
"""

import asyncio
import io

import pytest

from torchsnapshot_tpu.io_types import IOReq, io_payload
from torchsnapshot_tpu.storage_plugins.gcs import GCSStoragePlugin
from torchsnapshot_tpu.storage_plugins.s3 import S3StoragePlugin


# ------------------------------------------------------------------ fakes


class _FakeNotFound(Exception):
    """Shaped like google.api_core.exceptions.NotFound (code=404 plus an
    ``errors`` attribute — the classifier requires HTTP-library shape,
    not a bare overloaded ``code``). The real client never raises
    KeyError — fakes must speak the same dialect the structural
    not-found classifier understands."""

    code = 404
    errors = ()


class _FakeBlob:
    def __init__(self, store, key, hooks=None):
        self._store = store
        self._key = key
        self._hooks = hooks or {}
        self.size = None

    def upload_from_file(self, fileobj):
        on_upload = self._hooks.get("on_upload")
        if on_upload is not None:
            on_upload(self._key)
        self._store[self._key] = fileobj.read()

    def download_as_bytes(self, start=None, end=None):
        if self._key not in self._store:
            raise _FakeNotFound(f"404 GET {self._key}")
        data = self._store[self._key]
        if start is None:
            return data
        # google-cloud-storage: `end` is INCLUSIVE.
        return data[start : end + 1]

    def compose(self, sources):
        # Server-side concatenation, as google.cloud.storage.Blob.compose.
        assert len(sources) <= 32, "GCS compose caps at 32 components"
        on_compose = self._hooks.get("on_compose")
        if on_compose is not None:
            on_compose(self._key)
        self._store[self._key] = b"".join(
            self._store[s._key] for s in sources
        )

    def reload(self):
        self.size = len(self._store[self._key])

    def delete(self):
        if self._key not in self._store:
            raise _FakeNotFound(f"404 DELETE {self._key}")
        del self._store[self._key]


class _FakeGCSBucket:
    def __init__(self, store, hooks=None):
        self._store = store
        self._hooks = hooks

    def blob(self, key):
        return _FakeBlob(self._store, key, self._hooks)


class _FakeGCSClient:
    def __init__(self):
        self.store = {}
        # Test-injected failure hooks: callables invoked with the object
        # key before the corresponding fake operation runs.
        self.hooks = {}

    def bucket(self, name):
        return _FakeGCSBucket(self.store, self.hooks)

    def list_blobs(self, bucket_name, prefix=""):
        from types import SimpleNamespace

        return [
            SimpleNamespace(name=k)
            for k in sorted(self.store)
            if k.startswith(prefix)
        ]


class _FakeS3Client:
    def __init__(self):
        self.store = {}

    def put_object(self, Bucket, Key, Body):
        self.store[(Bucket, Key)] = bytes(Body)

    def get_object(self, Bucket, Key, Range=None):
        data = self.store[(Bucket, Key)]
        if Range is not None:
            # "bytes=<start>-<end>"; HTTP range ends are INCLUSIVE.
            spec = Range.split("=", 1)[1]
            start_s, end_s = spec.split("-")
            data = data[int(start_s) : int(end_s) + 1]
        return {"Body": io.BytesIO(data)}

    def delete_object(self, Bucket, Key):
        del self.store[(Bucket, Key)]

    def get_paginator(self, op):
        assert op == "list_objects_v2"
        store = self.store

        class _Paginator:
            def paginate(self, Bucket, Prefix=""):
                contents = [
                    {"Key": k}
                    for (b, k) in sorted(store)
                    if b == Bucket and k.startswith(Prefix)
                ]
                yield {"Contents": contents}

        return _Paginator()


# ------------------------------------------------------------------ tests


def _write(plugin, path, payload=None, buf=None):
    io_req = IOReq(path=path, data=payload)
    if buf is not None:
        io_req = IOReq(path=path, buf=buf)
    asyncio.run(plugin.write(io_req))


def _read(plugin, path, byte_range=None):
    io_req = IOReq(path=path, byte_range=byte_range)
    asyncio.run(plugin.read(io_req))
    return bytes(io_payload(io_req))


def test_gcs_roundtrip_and_key_layout():
    client = _FakeGCSClient()
    plugin = GCSStoragePlugin(root="bucket/run/step-5", client=client)
    payload = bytes(range(256))
    _write(plugin, "0/model/w", payload)
    assert client.store["run/step-5/0/model/w"] == payload
    assert _read(plugin, "0/model/w") == payload
    plugin.close()


def test_gcs_ranged_read_end_exclusive_to_inclusive():
    plugin = GCSStoragePlugin(root="b/p", client=_FakeGCSClient())
    payload = bytes(range(100))
    _write(plugin, "obj", payload)
    # IOReq byte_range is [start, end) — must translate to inclusive end.
    assert _read(plugin, "obj", byte_range=(10, 20)) == payload[10:20]
    assert _read(plugin, "obj", byte_range=(0, 1)) == payload[0:1]
    plugin.close()


def test_gcs_bytesio_write_and_delete():
    client = _FakeGCSClient()
    plugin = GCSStoragePlugin(root="b/p", client=client)
    _write(plugin, "x", buf=io.BytesIO(b"hello"))
    assert _read(plugin, "x") == b"hello"
    asyncio.run(plugin.delete("x"))
    assert client.store == {}
    plugin.close()


def test_gcs_root_validation():
    with pytest.raises(ValueError, match="bucket/path"):
        GCSStoragePlugin(root="nobucketpath", client=_FakeGCSClient())


def test_s3_roundtrip_and_key_layout():
    client = _FakeS3Client()
    plugin = S3StoragePlugin(root="bucket/run/step-5", client=client)
    payload = bytes(range(256))
    _write(plugin, "0/model/w", payload)
    assert client.store[("bucket", "run/step-5/0/model/w")] == payload
    assert _read(plugin, "0/model/w") == payload
    plugin.close()


def test_s3_ranged_read_header_semantics():
    plugin = S3StoragePlugin(root="b/p", client=_FakeS3Client())
    payload = bytes(range(100))
    _write(plugin, "obj", payload)
    assert _read(plugin, "obj", byte_range=(10, 20)) == payload[10:20]
    plugin.close()


def test_s3_delete_and_root_validation():
    client = _FakeS3Client()
    plugin = S3StoragePlugin(root="b/p", client=client)
    _write(plugin, "x", b"1")
    asyncio.run(plugin.delete("x"))
    assert client.store == {}
    plugin.close()
    with pytest.raises(ValueError, match="bucket/path"):
        S3StoragePlugin(root="nobucket", client=_FakeS3Client())


def test_snapshot_end_to_end_on_fake_gcs(monkeypatch):
    """Full Snapshot take/restore flowing through the GCS plugin."""
    import numpy as np
    import jax.numpy as jnp

    import torchsnapshot_tpu.storage_plugin as sp

    client = _FakeGCSClient()
    monkeypatch.setattr(
        sp,
        "url_to_storage_plugin",
        lambda url: GCSStoragePlugin(root="bucket/snap", client=client),
    )
    monkeypatch.setattr(
        "torchsnapshot_tpu.snapshot.url_to_storage_plugin",
        sp.url_to_storage_plugin,
    )

    from torchsnapshot_tpu import Snapshot

    class _Holder:
        def __init__(self, sd):
            self.sd = sd

        def state_dict(self):
            return self.sd

        def load_state_dict(self, sd):
            self.sd = sd

    w = np.arange(4096, dtype=np.float32)
    Snapshot.take("gs://bucket/snap", {"m": _Holder({"w": jnp.asarray(w)})})
    target = _Holder({"w": jnp.zeros((4096,), dtype=jnp.float32)})
    Snapshot("gs://bucket/snap").restore({"m": target})
    np.testing.assert_array_equal(np.asarray(target.sd["w"]), w)


def test_gcs_parallel_composite_upload(monkeypatch):
    """Large objects upload as concurrent nonce-named parts + one
    server-side compose; parts are cleaned up; payload is byte-exact."""
    monkeypatch.setenv("TPUSNAPSHOT_GCS_PARALLEL_UPLOAD_BYTES", str(1 << 10))
    client = _FakeGCSClient()
    plugin = GCSStoragePlugin("bucket/prefix", client=client)
    payload = bytes(range(256)) * 64  # 16 KiB -> 16 parts at 1 KiB
    io_req = IOReq(path="sharded/big_chunk", data=payload)
    asyncio.run(plugin.write(io_req))
    assert client.store["prefix/sharded/big_chunk"] == payload
    # No part objects remain.
    assert [k for k in client.store if ".part" in k] == []
    # Round-trips through the normal read path (incl. a ranged read).
    out = IOReq(path="sharded/big_chunk")
    asyncio.run(plugin.read(out))
    assert io_payload(out) == payload
    ranged = IOReq(path="sharded/big_chunk", byte_range=(100, 300))
    asyncio.run(plugin.read(ranged))
    assert io_payload(ranged) == payload[100:300]
    plugin.close()


def test_gcs_small_write_stays_single_object(monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_GCS_PARALLEL_UPLOAD_BYTES", str(1 << 20))
    client = _FakeGCSClient()
    plugin = GCSStoragePlugin("bucket/prefix", client=client)
    asyncio.run(plugin.write(IOReq(path="small", data=b"abc")))
    assert client.store["prefix/small"] == b"abc"
    plugin.close()


def test_gcs_compose_respects_32_component_cap(monkeypatch):
    """A payload many times the threshold still composes in one call:
    parts grow instead of exceeding GCS's 32-component limit."""
    monkeypatch.setenv("TPUSNAPSHOT_GCS_PARALLEL_UPLOAD_BYTES", str(64))
    client = _FakeGCSClient()
    plugin = GCSStoragePlugin("bucket/p", client=client)
    payload = b"z" * (64 * 100)  # 100x threshold
    asyncio.run(plugin.write(IOReq(path="huge", data=payload)))
    assert client.store["p/huge"] == payload
    plugin.close()


def test_gcs_list_prefix(monkeypatch):
    client = _FakeGCSClient()
    plugin = GCSStoragePlugin("bucket/prefix", client=client)
    for key in ("a/b", "a/c", "d"):
        asyncio.run(plugin.write(IOReq(path=key, data=b"x")))
    got = asyncio.run(plugin.list_prefix("a/"))
    assert sorted(got) == ["a/b", "a/c"]
    assert sorted(asyncio.run(plugin.list_prefix(""))) == ["a/b", "a/c", "d"]
    plugin.close()


def test_s3_list_prefix():
    client = _FakeS3Client()
    plugin = S3StoragePlugin("bucket/prefix", client=client)
    for key in ("a/b", "a/c", "d"):
        asyncio.run(plugin.write(IOReq(path=key, data=b"x")))
    got = asyncio.run(plugin.list_prefix("a/"))
    assert sorted(got) == ["a/b", "a/c"]
    plugin.close()


# --------------------------------------------- composite-upload faults


def test_gcs_part_upload_failure_cleans_parts_and_surfaces(monkeypatch):
    """One part failing mid-composite: the error must surface (not a
    silently truncated object), every already-uploaded part must be
    cleaned, and nothing must land at the destination key."""
    monkeypatch.setenv("TPUSNAPSHOT_GCS_PARALLEL_UPLOAD_BYTES", str(1 << 10))
    client = _FakeGCSClient()

    def fail_part3(key):
        if ".part3." in key:
            raise RuntimeError("injected: part 3 upload failed")

    client.hooks["on_upload"] = fail_part3
    plugin = GCSStoragePlugin("bucket/p", client=client)
    payload = bytes(range(256)) * 32  # 8 KiB -> 8 parts
    with pytest.raises(RuntimeError, match="part 3"):
        asyncio.run(plugin.write(IOReq(path="big", data=payload)))
    assert [k for k in client.store if ".part" in k] == []
    assert "p/big" not in client.store
    plugin.close()


def test_gcs_compose_failure_cleans_parts_and_surfaces(monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_GCS_PARALLEL_UPLOAD_BYTES", str(1 << 10))
    client = _FakeGCSClient()

    def fail_compose(key):
        raise RuntimeError("injected: compose failed")

    client.hooks["on_compose"] = fail_compose
    plugin = GCSStoragePlugin("bucket/p", client=client)
    payload = b"x" * (4 << 10)
    with pytest.raises(RuntimeError, match="compose failed"):
        asyncio.run(plugin.write(IOReq(path="big", data=payload)))
    assert [k for k in client.store if ".part" in k] == []
    assert "p/big" not in client.store
    plugin.close()


def test_gcs_transient_part_failure_retried_whole_object(monkeypatch):
    """The retry layer re-runs the WHOLE composite write after a
    transient part failure; the second attempt succeeds byte-exact and
    leaves no part objects."""
    from torchsnapshot_tpu.io_types import RetryingStoragePlugin

    monkeypatch.setenv("TPUSNAPSHOT_GCS_PARALLEL_UPLOAD_BYTES", str(1 << 10))
    monkeypatch.setenv("TPUSNAPSHOT_STORAGE_RETRIES", "2")
    client = _FakeGCSClient()
    fails = {"n": 0}

    def fail_once(key):
        if ".part1." in key and fails["n"] == 0:
            fails["n"] += 1
            raise ConnectionError("injected: transient 503")

    client.hooks["on_upload"] = fail_once
    plugin = RetryingStoragePlugin(
        GCSStoragePlugin("bucket/p", client=client)
    )
    payload = bytes(range(256)) * 16  # 4 KiB -> 4 parts
    asyncio.run(plugin.write(IOReq(path="big", data=payload)))
    assert client.store["p/big"] == payload
    assert [k for k in client.store if ".part" in k] == []
    assert fails["n"] == 1
    plugin.close()


def test_gcs_composed_size_mismatch_detected(monkeypatch):
    """A composed object whose size disagrees with the payload (lost
    part, interfering concurrent upload) is detected by the post-compose
    size cross-check instead of surfacing at restore time."""
    monkeypatch.setenv("TPUSNAPSHOT_GCS_PARALLEL_UPLOAD_BYTES", str(1 << 10))
    client = _FakeGCSClient()
    store = client.store

    def corrupt_compose(key):
        # Simulate an interfering writer truncating one part between its
        # upload and the compose call.
        for k in list(store):
            if ".part2." in k:
                store[k] = store[k][:-7]

    client.hooks["on_compose"] = corrupt_compose
    plugin = GCSStoragePlugin("bucket/p", client=client)
    payload = b"y" * (4 << 10)
    with pytest.raises(RuntimeError, match="composed object is"):
        asyncio.run(plugin.write(IOReq(path="big", data=payload)))
    assert [k for k in client.store if ".part" in k] == []
    plugin.close()


def test_gcs_crashed_upload_parts_removed_by_sweep(monkeypatch):
    """Parts orphaned by a crashed process (no finally cleanup ran) are
    provably removed by Snapshot.delete(sweep=True)."""
    import jax.numpy as jnp

    import torchsnapshot_tpu.storage_plugin as sp

    client = _FakeGCSClient()
    monkeypatch.setattr(
        sp,
        "url_to_storage_plugin",
        lambda url: GCSStoragePlugin(root="bucket/snap", client=client),
    )
    monkeypatch.setattr(
        "torchsnapshot_tpu.snapshot.url_to_storage_plugin",
        sp.url_to_storage_plugin,
    )
    from torchsnapshot_tpu import Snapshot

    class _Holder:
        def __init__(self, sd):
            self.sd = sd

        def state_dict(self):
            return self.sd

        def load_state_dict(self, sd):
            self.sd = sd

    Snapshot.take(
        "gs://bucket/snap", {"m": _Holder({"w": jnp.arange(64.0)})}
    )
    # A concurrent take to the same path crashed mid-composite: nonce-
    # named part objects remain under the prefix.
    client.store["snap/sharded/chunk.part0.deadbeef0123"] = b"orphan"
    client.store["snap/sharded/chunk.part1.deadbeef0123"] = b"orphan"
    snap = Snapshot("gs://bucket/snap")
    snap.delete(sweep=True)
    assert client.store == {}
