"""Chunked parallel device→host staging (transport-level optimization).

Large arrays stage via parallel device-slice transfers
(io_preparer._parallel_device_get) instead of one serial stream. The
on-disk payload must be byte-identical to the unchunked path — these
tests force the chunked path on the CPU backend and check round trips
and payload equality, including non-divisible chunk boundaries and
ml_dtypes payloads (bfloat16).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torchsnapshot_tpu import Snapshot
from torchsnapshot_tpu.io_preparer import (
    _parallel_device_get,
    _should_chunk_transfer,
)
from torchsnapshot_tpu.utils.train_state import PytreeStateful


@pytest.fixture
def force_chunked(monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_FORCE_CHUNKED_TRANSFER", "1")
    monkeypatch.setenv("TPUSNAPSHOT_TRANSFER_CHUNK_BYTES", str(1 << 10))


@pytest.mark.parametrize(
    "shape,dtype",
    [
        ((1024, 7), jnp.float32),  # axis-0 largest, non-divisible
        ((3, 2048), jnp.bfloat16),  # axis-1 largest, ml_dtypes payload
        ((17, 33, 11), jnp.int32),  # 3-D, odd sizes
        ((5000,), jnp.float16),  # 1-D
    ],
)
def test_parallel_device_get_bit_exact(force_chunked, shape, dtype):
    key = jax.random.key(0)
    if jnp.issubdtype(dtype, jnp.integer):
        arr = jax.random.randint(key, shape, -1000, 1000, dtype=dtype)
    else:
        arr = jax.random.normal(key, shape).astype(dtype)
    assert _should_chunk_transfer(arr)
    got = _parallel_device_get(arr)
    want = np.asarray(arr)
    assert got.dtype == want.dtype
    np.testing.assert_array_equal(
        got.view(np.uint8), want.view(np.uint8)
    )


def test_should_chunk_transfer_small_and_nonjax(force_chunked):
    assert not _should_chunk_transfer(np.zeros((1024, 1024)))  # numpy
    assert not _should_chunk_transfer(jnp.zeros(4))  # below threshold
    assert not _should_chunk_transfer(jnp.float32(3.0))  # scalar


def test_snapshot_round_trip_chunked(force_chunked, tmp_path):
    state = {
        "w": jax.random.normal(jax.random.key(1), (512, 9)),
        "b": jax.random.normal(jax.random.key(2), (2000,)).astype(jnp.bfloat16),
    }
    app = {"model": PytreeStateful(state)}
    Snapshot.take(str(tmp_path / "snap"), app)

    target_state = {
        "w": jnp.zeros((512, 9)),
        "b": jnp.zeros((2000,), dtype=jnp.bfloat16),
    }
    target = {"model": PytreeStateful(target_state)}
    Snapshot(str(tmp_path / "snap")).restore(target)
    restored = target["model"].tree
    np.testing.assert_array_equal(
        np.asarray(restored["w"]), np.asarray(state["w"])
    )
    np.testing.assert_array_equal(
        np.asarray(restored["b"]).view(np.uint8),
        np.asarray(state["b"]).view(np.uint8),
    )


def test_chunked_payload_matches_unchunked(tmp_path, monkeypatch):
    """The stored bytes are identical whether or not staging chunks."""
    arr = jax.random.normal(jax.random.key(3), (777, 13))
    app = lambda: {"m": PytreeStateful({"x": arr})}  # noqa: E731

    Snapshot.take(str(tmp_path / "plain"), app())
    monkeypatch.setenv("TPUSNAPSHOT_FORCE_CHUNKED_TRANSFER", "1")
    monkeypatch.setenv("TPUSNAPSHOT_TRANSFER_CHUNK_BYTES", str(1 << 10))
    Snapshot.take(str(tmp_path / "chunked"), app())

    a = (tmp_path / "plain" / "0" / "m" / "x").read_bytes()
    b = (tmp_path / "chunked" / "0" / "m" / "x").read_bytes()
    assert a == b
