"""Chunked parallel device→host staging (transport-level optimization).

Large arrays stage via parallel device-slice transfers
(io_preparer._parallel_device_get) instead of one serial stream. The
on-disk payload must be byte-identical to the unchunked path — these
tests force the chunked path on the CPU backend and check round trips
and payload equality, including non-divisible chunk boundaries and
ml_dtypes payloads (bfloat16).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torchsnapshot_tpu import Snapshot
from torchsnapshot_tpu.io_preparer import (
    _parallel_device_get,
    _should_chunk_transfer,
)
from torchsnapshot_tpu.utils.train_state import PytreeStateful


@pytest.fixture
def force_chunked(monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_FORCE_CHUNKED_TRANSFER", "1")
    monkeypatch.setenv("TPUSNAPSHOT_TRANSFER_CHUNK_BYTES", str(1 << 10))


@pytest.mark.parametrize(
    "shape,dtype",
    [
        ((1024, 7), jnp.float32),  # axis-0 largest, non-divisible
        ((3, 2048), jnp.bfloat16),  # axis-1 largest, ml_dtypes payload
        ((17, 33, 11), jnp.int32),  # 3-D, odd sizes
        ((5000,), jnp.float16),  # 1-D
    ],
)
def test_parallel_device_get_bit_exact(force_chunked, shape, dtype):
    key = jax.random.key(0)
    if jnp.issubdtype(dtype, jnp.integer):
        arr = jax.random.randint(key, shape, -1000, 1000, dtype=dtype)
    else:
        arr = jax.random.normal(key, shape).astype(dtype)
    assert _should_chunk_transfer(arr)
    got = _parallel_device_get(arr)
    want = np.asarray(arr)
    assert got.dtype == want.dtype
    np.testing.assert_array_equal(
        got.view(np.uint8), want.view(np.uint8)
    )


def test_should_chunk_transfer_small_and_nonjax(force_chunked):
    assert not _should_chunk_transfer(np.zeros((1024, 1024)))  # numpy
    assert not _should_chunk_transfer(jnp.zeros(4))  # below threshold
    assert not _should_chunk_transfer(jnp.float32(3.0))  # scalar


def test_snapshot_round_trip_chunked(force_chunked, tmp_path):
    state = {
        "w": jax.random.normal(jax.random.key(1), (512, 9)),
        "b": jax.random.normal(jax.random.key(2), (2000,)).astype(jnp.bfloat16),
    }
    app = {"model": PytreeStateful(state)}
    Snapshot.take(str(tmp_path / "snap"), app)

    target_state = {
        "w": jnp.zeros((512, 9)),
        "b": jnp.zeros((2000,), dtype=jnp.bfloat16),
    }
    target = {"model": PytreeStateful(target_state)}
    Snapshot(str(tmp_path / "snap")).restore(target)
    restored = target["model"].tree
    np.testing.assert_array_equal(
        np.asarray(restored["w"]), np.asarray(state["w"])
    )
    np.testing.assert_array_equal(
        np.asarray(restored["b"]).view(np.uint8),
        np.asarray(state["b"]).view(np.uint8),
    )


def test_chunked_payload_matches_unchunked(tmp_path, monkeypatch):
    """The stored bytes are identical whether or not staging chunks."""
    arr = jax.random.normal(jax.random.key(3), (777, 13))
    app = lambda: {"m": PytreeStateful({"x": arr})}  # noqa: E731

    Snapshot.take(str(tmp_path / "plain"), app())
    monkeypatch.setenv("TPUSNAPSHOT_FORCE_CHUNKED_TRANSFER", "1")
    monkeypatch.setenv("TPUSNAPSHOT_TRANSFER_CHUNK_BYTES", str(1 << 10))
    Snapshot.take(str(tmp_path / "chunked"), app())

    a = (tmp_path / "plain" / "0" / "m" / "x").read_bytes()
    b = (tmp_path / "chunked" / "0" / "m" / "x").read_bytes()
    assert a == b


def test_chunked_device_put_round_trip(monkeypatch):
    """Restore's chunked H2D path: split → batched put → on-device
    concat+reshape must be bit-exact, including non-divisible tails and
    ml_dtypes payloads."""
    from torchsnapshot_tpu.ops.transfer import (
        chunked_device_put,
        should_chunk_h2d,
    )

    monkeypatch.setenv("TPUSNAPSHOT_FORCE_CHUNKED_TRANSFER", "1")
    monkeypatch.setenv("TPUSNAPSHOT_H2D_CHUNK_BYTES", str(1 << 10))
    dev = jax.devices()[0]
    rng = np.random.default_rng(0)
    for arr in (
        rng.standard_normal((1000, 3)).astype(np.float32),  # tail chunk
        np.asarray(
            jax.random.normal(jax.random.key(0), (7, 600)).astype(jnp.bfloat16)
        ),
        rng.integers(-5, 5, size=(2048,)).astype(np.int8),
    ):
        assert should_chunk_h2d(arr, dev)
        out = chunked_device_put(arr, dev)
        assert out.shape == arr.shape
        np.testing.assert_array_equal(
            np.asarray(out).view(np.uint8), arr.view(np.uint8)
        )


def test_restore_uses_chunked_h2d(monkeypatch, tmp_path):
    """End-to-end: a restore whose target buffers exceed the chunk
    threshold routes through chunked_device_put and round-trips."""
    import torchsnapshot_tpu.io_preparer as iop

    state = {"w": jax.random.normal(jax.random.key(5), (4096, 8))}
    app = {"m": PytreeStateful(dict(state))}
    Snapshot.take(str(tmp_path / "snap"), app)

    monkeypatch.setenv("TPUSNAPSHOT_FORCE_CHUNKED_TRANSFER", "1")
    monkeypatch.setenv("TPUSNAPSHOT_H2D_CHUNK_BYTES", str(1 << 12))
    calls = []
    real = iop.chunked_device_put

    def spy(arr, dev):
        calls.append(arr.nbytes)
        return real(arr, dev)

    # The chunked put may run at plan finalize (iop's symbol) or on the
    # H2D overlap engine (the transfer module's symbol) when the region
    # early-dispatches — spy on both.
    import torchsnapshot_tpu.ops.transfer as transfer_mod

    monkeypatch.setattr(iop, "chunked_device_put", spy)
    monkeypatch.setattr(transfer_mod, "chunked_device_put", spy)
    target = {"m": PytreeStateful({"w": jnp.zeros((4096, 8))})}
    Snapshot(str(tmp_path / "snap")).restore(target)
    assert calls  # the big buffer actually took the chunked path
    np.testing.assert_array_equal(
        np.asarray(target["m"].tree["w"]), np.asarray(state["w"])
    )


def test_resharded_restore_through_chunked_h2d(monkeypatch):
    """Elastic restore (different sharding than saved) with the chunked
    H2D path forced: per-region buffers assembled from ranged reads must
    survive the split->put->concat->reshape round trip bit-exactly."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    monkeypatch.setenv("TPUSNAPSHOT_FORCE_CHUNKED_TRANSFER", "1")
    monkeypatch.setenv("TPUSNAPSHOT_H2D_CHUNK_BYTES", str(1 << 12))

    import tempfile

    devices = np.array(jax.devices())
    mesh8 = Mesh(devices, ("x",))
    mesh2 = Mesh(devices[:2], ("x",))

    arr = jax.random.normal(jax.random.key(11), (64, 128), jnp.float32)
    sharded8 = jax.device_put(arr, NamedSharding(mesh8, P("x", None)))

    with tempfile.TemporaryDirectory() as tmp:
        Snapshot.take(f"{tmp}/snap", {"m": PytreeStateful({"w": sharded8})})
        # Restore onto a 2-way mesh sharded along the OTHER axis: every
        # target shard overlaps 8 saved chunks partially.
        template = jax.device_put(
            jnp.zeros((64, 128), jnp.float32),
            NamedSharding(mesh2, P(None, "x")),
        )
        target = {"m": PytreeStateful({"w": template})}
        Snapshot(f"{tmp}/snap").restore(target)
        got = target["m"].tree["w"]
        assert got.sharding.spec == P(None, "x")
        np.testing.assert_array_equal(np.asarray(got), np.asarray(arr))
