"""Regression tests for code-review findings on the initial implementation."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.coord import DictStore, StoreCoordinator


class _Holder:
    def __init__(self, sd):
        self.sd = sd

    def state_dict(self):
        return self.sd

    def load_state_dict(self, sd):
        self.sd = sd


def _run_world(world, fn, store=None):
    store = store or DictStore()
    errors = []
    results = [None] * world

    def worker(rank):
        try:
            coord = StoreCoordinator(store, rank, world, timeout_s=60)
            results[rank] = fn(coord, rank)
        except BaseException:  # pragma: no cover
            import traceback

            errors.append((rank, traceback.format_exc()))

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    if errors:
        raise AssertionError(f"rank {errors[0][0]} failed:\n{errors[0][1]}")
    return results


def test_numpy_scalar_leaves(tmp_path):
    """np.float64 subclasses float; must route to the array path, not
    PrimitiveEntry (which would raise)."""
    app = {
        "s": _Holder(
            {
                "best": np.float64(0.93),
                "count": np.int64(7),
                "flag": np.bool_(True),
                "f32": np.float32(1.25),
            }
        )
    }
    Snapshot.take(str(tmp_path / "snap"), app)
    target = _Holder(
        {
            "best": np.float64(0),
            "count": np.int64(0),
            "flag": np.bool_(False),
            "f32": np.float32(0),
        }
    )
    Snapshot(str(tmp_path / "snap")).restore({"s": target})
    assert float(target.sd["best"]) == 0.93
    assert int(target.sd["count"]) == 7
    assert bool(target.sd["flag"]) is True
    assert float(target.sd["f32"]) == 1.25


def test_same_coordinator_two_takes(tmp_path):
    """Key generations must not collide across successive operations on
    one coordinator (persistent-store key reuse)."""

    def worker(coord, rank):
        Snapshot.take(str(tmp_path / "s1"), {"a": StateDict(x=rank)}, coord=coord)
        Snapshot.take(str(tmp_path / "s2"), {"a": StateDict(x=rank + 10)}, coord=coord)
        app = {"a": StateDict(x=-1)}
        Snapshot(str(tmp_path / "s2")).restore(app, coord=coord)
        assert app["a"]["x"] == rank + 10

    _run_world(2, worker)


def test_replicated_striping_with_divergent_keys(tmp_path):
    """Round-robin ownership must be computed over the (rank-identical)
    replicated path set, not each rank's full flattened list — otherwise a
    replicated object can end up written by nobody."""
    path = str(tmp_path / "snap")

    def worker(coord, rank):
        sd = {"shared": np.arange(4, dtype=np.float32)}
        if rank == 1:
            # Extra per-rank keys sorting *before* "shared" shift rank 1's
            # flattened index of the replicated path.
            sd["aaa_extra0"] = np.zeros(1, dtype=np.float32)
            sd["aab_extra1"] = np.zeros(1, dtype=np.float32)
        Snapshot.take(path, {"st": _Holder(sd)}, coord=coord, replicated=["st/shared"])

    _run_world(2, worker)
    # The replicated object must exist and be restorable by a fresh process.
    assert (tmp_path / "snap" / "replicated" / "st" / "shared").exists()
    target = _Holder({"shared": np.zeros(4, dtype=np.float32)})
    Snapshot(path).restore({"st": target})
    np.testing.assert_array_equal(target.sd["shared"], np.arange(4, dtype=np.float32))


def test_per_rank_divergent_container_keys(tmp_path):
    """Each rank's dict key set may differ; get_available_entries must
    resolve containers per-rank so inflation matches the local structure."""
    path = str(tmp_path / "snap")

    def take_worker(coord, rank):
        Snapshot.take(
            path,
            {"st": _Holder({"cursor": {f"worker{rank}": rank * 11}})},
            coord=coord,
        )

    _run_world(2, take_worker)

    def restore_worker(coord, rank):
        target = _Holder({"cursor": {f"worker{rank}": -1}})
        Snapshot(path).restore({"st": target}, coord=coord)
        assert target.sd["cursor"] == {f"worker{rank}": rank * 11}

    _run_world(2, restore_worker)


def test_sharded_prng_key_array(tmp_path):
    """Partitioned typed PRNG key arrays must take the sharded path and
    round-trip exactly."""
    mesh = Mesh(np.array(jax.devices()), ("x",))
    keys = jax.random.split(jax.random.key(0), 8)
    sharded_keys = jax.device_put(keys, NamedSharding(mesh, P("x")))
    assert not sharded_keys.is_fully_replicated

    holder = _Holder({"keys": sharded_keys})
    Snapshot.take(str(tmp_path / "snap"), {"st": holder})

    manifest = Snapshot(str(tmp_path / "snap")).get_manifest()
    from torchsnapshot_tpu.manifest import ShardedArrayEntry

    entry = manifest["0/st/keys"]
    assert isinstance(entry, ShardedArrayEntry)
    assert entry.prng_impl is not None

    template = jax.device_put(jax.random.split(jax.random.key(9), 8),
                              NamedSharding(mesh, P("x")))
    target = _Holder({"keys": template})
    Snapshot(str(tmp_path / "snap")).restore({"st": target})
    restored = target.sd["keys"]
    assert jax.dtypes.issubdtype(restored.dtype, jax.dtypes.prng_key)
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(restored)),
        np.asarray(jax.random.key_data(keys)),
    )
    # Streams must be identical.
    np.testing.assert_array_equal(
        np.asarray(jax.random.normal(restored[3], (4,))),
        np.asarray(jax.random.normal(keys[3], (4,))),
    )


def test_async_retake_same_path(tmp_path):
    """A second async_take to the same path must not be confused by the
    first take's completion markers."""
    path = str(tmp_path / "snap")
    p1 = Snapshot.async_take(path, {"s": _Holder({"w": np.arange(4.0)})})
    p1.wait()
    p2 = Snapshot.async_take(path, {"s": _Holder({"w": np.arange(4.0) * 2})})
    p2.wait()
    target = _Holder({"w": np.zeros(4)})
    Snapshot(path).restore({"s": target})
    np.testing.assert_array_equal(target.sd["w"], np.arange(4.0) * 2)


def test_async_budget_respected(tmp_path, monkeypatch):
    """Async writes go through the budgeted pipeline (no unbounded
    simultaneous staging)."""
    monkeypatch.setenv("TPUSNAPSHOT_PER_RANK_MEMORY_BUDGET_BYTES", "4096")
    arrays = {f"w{i}": jnp.arange(256, dtype=jnp.float32) for i in range(20)}
    pending = Snapshot.async_take(str(tmp_path / "snap"), {"s": _Holder(arrays)})
    snap = pending.wait()
    target = _Holder({k: jnp.zeros(256, dtype=jnp.float32) for k in arrays})
    snap.restore({"s": target})
    for k, v in arrays.items():
        np.testing.assert_array_equal(np.asarray(target.sd[k]), np.asarray(v))
