"""Live GCS bucket integration tests (env-gated, skipped in CI).

Parity with the reference's real-bucket suite
(reference tests/test_gcs_storage_plugin.py:25): a ~100 MB payload
round-trips through both the raw plugin and the Snapshot API against a
real bucket. Gated exactly like the reference — set

    TPUSNAPSHOT_ENABLE_GCP_TEST=1 TPUSNAPSHOT_GCP_TEST_BUCKET=<bucket>

with ambient GCP credentials (e.g. a TPU VM service account). The suite
skips cleanly when the gate is absent, so the hermetic CI run is
unaffected; it exists so the real network/auth/retry path of the
north-star storage target (gs://) runs the moment a bucket is available.
"""

import asyncio
import os
import uuid

import numpy as np
import pytest

import jax.numpy as jnp

_GATE = os.environ.get("TPUSNAPSHOT_ENABLE_GCP_TEST") == "1"
_BUCKET = os.environ.get("TPUSNAPSHOT_GCP_TEST_BUCKET")

pytestmark = pytest.mark.skipif(
    not (_GATE and _BUCKET),
    reason=(
        "live GCS test gated: set TPUSNAPSHOT_ENABLE_GCP_TEST=1 and "
        "TPUSNAPSHOT_GCP_TEST_BUCKET"
    ),
)

_PAYLOAD_BYTES = 100 * 1024 * 1024


@pytest.fixture
def gcs_prefix():
    prefix = f"tpusnapshot-test/{uuid.uuid4().hex}"
    yield f"{_BUCKET}/{prefix}"
    # Best-effort cleanup of everything the test wrote.
    try:
        from torchsnapshot_tpu.storage_plugins.gcs import GCSStoragePlugin

        plugin = GCSStoragePlugin(f"{_BUCKET}/{prefix}")
        leftovers = asyncio.run(plugin.list_prefix("")) or []
        for path in leftovers:
            asyncio.run(plugin.delete(path))
        plugin.close()
    except Exception:
        pass


def test_raw_plugin_large_object_round_trip(gcs_prefix):
    from torchsnapshot_tpu.io_types import IOReq, io_payload
    from torchsnapshot_tpu.storage_plugins.gcs import GCSStoragePlugin

    plugin = GCSStoragePlugin(gcs_prefix)
    payload = np.random.default_rng(0).bytes(_PAYLOAD_BYTES)
    asyncio.run(plugin.write(IOReq(path="blob", data=payload)))

    out = IOReq(path="blob")
    asyncio.run(plugin.read(out))
    assert bytes(io_payload(out)) == payload

    ranged = IOReq(path="blob", byte_range=(12345, 123456))
    asyncio.run(plugin.read(ranged))
    assert bytes(io_payload(ranged)) == payload[12345:123456]

    asyncio.run(plugin.delete("blob"))
    plugin.close()


def test_snapshot_api_round_trip(gcs_prefix):
    from torchsnapshot_tpu import Snapshot, StateDict

    w = jnp.arange(_PAYLOAD_BYTES // 4, dtype=jnp.float32)
    url = f"gs://{gcs_prefix}/snap"
    Snapshot.take(url, {"s": StateDict(w=w)})

    target = StateDict(w=jnp.zeros_like(w))
    Snapshot(url).restore({"s": target})
    np.testing.assert_array_equal(np.asarray(target["w"]), np.asarray(w))
    Snapshot(url).delete(sweep=True)


def test_parallel_composite_upload_live(gcs_prefix):
    """The ≥64 MB composite-upload path against the real service."""
    from torchsnapshot_tpu.io_types import IOReq, io_payload
    from torchsnapshot_tpu.storage_plugins.gcs import GCSStoragePlugin

    plugin = GCSStoragePlugin(gcs_prefix)
    payload = np.random.default_rng(1).bytes(_PAYLOAD_BYTES)
    asyncio.run(plugin.write(IOReq(path="composite", data=payload)))
    out = IOReq(path="composite")
    asyncio.run(plugin.read(out))
    assert bytes(io_payload(out)) == payload
    leftovers = asyncio.run(plugin.list_prefix(""))
    assert leftovers == ["composite"]  # no stray part objects
    asyncio.run(plugin.delete("composite"))
    plugin.close()
