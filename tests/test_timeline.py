"""timeline sentinel + goodput accountant + checkpoint-budget doctor
rule (ISSUE 5: the readers layered on the telemetry ledger)."""

import json
import time

import pytest

from torchsnapshot_tpu import telemetry
from torchsnapshot_tpu.telemetry import doctor, goodput, ledger, timeline


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    goodput.reset()
    yield
    telemetry.reset()
    goodput.reset()


# ------------------------------------------------------------- sentinel


def _series(values):
    return [(f"step {i}", v) for i, v in enumerate(values)]


def test_sentinel_flags_spike_with_first_bad_step():
    hit = timeline.detect_regressions(
        _series([1.0, 1.1, 0.9, 1.0, 1.05, 5.0, 6.0]), "high"
    )
    assert hit is not None
    assert hit["label"] == "step 5"  # FIRST bad point, not the worst
    assert hit["value"] == 5.0
    assert hit["baseline_median"] == pytest.approx(1.0, abs=0.11)


def test_sentinel_low_direction():
    hit = timeline.detect_regressions(
        _series([2.0, 2.1, 1.9, 2.0, 0.4]), "low"
    )
    assert hit is not None and hit["label"] == "step 4"
    assert (
        timeline.detect_regressions(_series([2.0, 2.1, 1.9, 2.0, 2.2]), "low")
        is None
    )


def test_sentinel_needs_history():
    # Two points of history are not enough to judge the third.
    assert (
        timeline.detect_regressions(_series([1.0, 1.0, 99.0]), "high")
        is None
    )


def test_sentinel_skips_missing_values():
    # None = missing data (a skipped bench section), never zero: it
    # neither flags nor pollutes the baseline.
    hit = timeline.detect_regressions(
        _series([1.0, None, 1.1, 0.9, None, 1.0, 4.0]), "high"
    )
    assert hit is not None and hit["label"] == "step 6"
    assert (
        timeline.detect_regressions(
            _series([1.0, 1.1, 0.9, None, None, None]), "high"
        )
        is None
    )


def test_sentinel_robust_to_one_earlier_outlier():
    # Median/MAD: one early spike must not inflate the baseline into
    # hiding a later sustained drift, nor flag the healthy tail.
    values = [1.0, 1.1, 0.9, 8.0, 1.0, 0.95, 1.05, 1.0]
    hit = timeline.detect_regressions(_series(values), "high")
    assert hit is not None and hit["label"] == "step 3"
    # The outlier inside the window does not poison the median: the
    # tail (baselines that include the 8.0) stays healthy.
    tail_hit = timeline.detect_regressions(_series(values[4:]), "high")
    assert tail_hit is None


def test_sentinel_min_dev_floor():
    # Tiny absolute wiggles below min_dev never flag, whatever the MAD.
    assert (
        timeline.detect_regressions(
            _series([0.010, 0.010, 0.010, 0.012]), "high", min_dev=0.05
        )
        is None
    )


# ------------------------------------------------------------ ledger CLI


def _take_record(step, wall_s=0.1, gbps=1.0, **over):
    record = {
        "format_version": 1,
        "kind": "take",
        "ts_epoch_s": 1700000000.0 + step,
        "path": f"/run/step-{step}",
        "step": step,
        "take_id": f"t{step}",
        "world_size": 2,
        "wall_s": wall_s,
        "bytes": int(gbps * (1 << 30) * wall_s),
        "gbps": gbps,
        "stall_s": 0.0,
        "stall_pct": 0.0,
        "retries": 0,
        "faults": 0,
        "phases": {"capture_s": wall_s / 2, "write_s": wall_s / 2},
        "goodput": {"goodput_fraction": 0.97, "window_fraction": 0.97},
        "churn": {"efficiency": 0.8, "basis": "incremental"},
        "doctor": [],
    }
    record.update(over)
    return record


def _write_ledger(path, records):
    path.write_text(
        "".join(ledger.encode_line(r) + "\n" for r in records)
    )
    return str(path)


def test_timeline_healthy_ledger_exits_zero(tmp_path, capsys):
    f = _write_ledger(
        tmp_path / "ledger.jsonl",
        [_take_record(i) for i in range(20)],
    )
    assert timeline.main([f]) == 0
    out = capsys.readouterr().out
    assert "no regression" in out


def test_timeline_throughput_regression_exits_one(tmp_path, capsys):
    records = [_take_record(i) for i in range(19)]
    records.append(_take_record(19, gbps=0.2))
    f = _write_ledger(tmp_path / "ledger.jsonl", records)
    assert timeline.main([f]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION take GB/s" in out
    assert "step 19" in out


def test_timeline_goodput_drift_and_doctor_history(tmp_path, capsys):
    records = [_take_record(i) for i in range(8)]
    records += [
        _take_record(
            8 + i,
            goodput={"goodput_fraction": 0.60, "window_fraction": 0.60},
            doctor=["checkpoint-overhead-above-budget"],
        )
        for i in range(2)
    ]
    f = _write_ledger(tmp_path / "ledger.jsonl", records)
    assert timeline.main([f]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION goodput fraction" in out
    assert "checkpoint-overhead-above-budget: fired 2x" in out


def test_timeline_json_output(tmp_path, capsys):
    records = [_take_record(i) for i in range(6)]
    records.append(_take_record(6, wall_s=2.0))
    f = _write_ledger(tmp_path / "ledger.jsonl", records)
    assert timeline.main([f, "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["n_takes"] == 7
    (finding,) = [
        r for r in doc["regressions"] if r["field"] == "wall_s"
    ]
    assert finding["label"] == "step 6"
    assert len(doc["records"]) == 7


def test_timeline_no_data_exits_two(tmp_path, capsys):
    empty = tmp_path / "ledger.jsonl"
    empty.write_text("")
    assert timeline.main([str(empty)]) == 2
    assert timeline.main([str(tmp_path / "nothing-here")]) == 2
    capsys.readouterr()


def test_timeline_skips_torn_lines(tmp_path, capsys):
    records = [_take_record(i) for i in range(5)]
    raw = "".join(ledger.encode_line(r) + "\n" for r in records)
    f = tmp_path / "ledger.jsonl"
    f.write_text(raw + '{"torn": ')
    assert timeline.main([str(f)]) == 0
    err = capsys.readouterr().err
    assert "torn/corrupt ledger line(s) skipped" in err


# ------------------------------------------------------------ bench mode


def _bench_doc(value, restore=2.0, gaps=None, wrapper=False):
    doc = {
        "metric": "snapshot_take_GBps",
        "value": value,
        "restore_GBps": restore,
        "take_vs_ceiling": 0.9,
        "restore_vs_ceiling": 0.8,
        "gaps": gaps or [],
    }
    if wrapper:
        return {"rc": 0, "tail": "noise\n" + json.dumps(doc) + "\n"}
    return doc


def test_timeline_bench_dir_mode(tmp_path, capsys):
    for i, value in enumerate([1.0, 1.05, 0.95, 1.0]):
        (tmp_path / f"BENCH_r{i:02d}.json").write_text(
            json.dumps(_bench_doc(value, wrapper=(i == 1)))
        )
    assert timeline.main([str(tmp_path)]) == 0
    capsys.readouterr()
    # A collapsed final round trips the sentinel; its skipped section
    # shows as a gap, not a zero.
    (tmp_path / "BENCH_r04.json").write_text(
        json.dumps(_bench_doc(0.2, restore=None, gaps=["step_stall"]))
    )
    assert timeline.main([str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION take GB/s" in out
    assert "BENCH_r04" in out
    assert "step_stall" in out


# --------------------------------------------------------------- goodput


def test_goodput_attribution():
    acct = goodput.GoodputAccountant()
    acct.step()
    time.sleep(0.03)
    with acct.blocked("sync_take"):
        time.sleep(0.05)
    acct.step()
    snap = acct.snapshot()
    assert snap["steps"] == 2
    assert snap["train_s"] == pytest.approx(0.03, abs=0.02)
    assert snap["by_mode"]["sync_take"] == pytest.approx(0.05, abs=0.02)
    assert 0 < snap["goodput_fraction"] < 1
    assert snap["checkpoint_overhead_pct"] == pytest.approx(
        100 - 100 * snap["goodput_fraction"], abs=0.01
    )


def test_goodput_nested_blocked_counts_once():
    acct = goodput.GoodputAccountant()
    with acct.blocked("sync_take"):
        with acct.blocked("restore"):
            time.sleep(0.03)
    snap = acct.snapshot()
    assert "restore" not in snap["by_mode"]
    assert snap["by_mode"]["sync_take"] == pytest.approx(0.03, abs=0.02)


def test_goodput_snapshot_includes_open_interval():
    acct = goodput.GoodputAccountant()
    with acct.blocked("sync_take"):
        time.sleep(0.03)
        snap = acct.snapshot()  # a flight summary built mid-take
        assert snap["by_mode"]["sync_take"] >= 0.02
    assert acct.snapshot()["by_mode"]["sync_take"] >= 0.02


def test_goodput_exports_metrics():
    goodput.step()
    time.sleep(0.02)
    with goodput.blocked("drain_wait"):
        time.sleep(0.01)
    goodput.step()
    snap = telemetry.snapshot()
    assert snap["tpusnapshot_goodput_train_seconds_total"] > 0
    assert (
        snap['tpusnapshot_goodput_checkpoint_seconds_total{mode="drain_wait"}']
        > 0
    )
    assert 0 < snap["tpusnapshot_goodput_fraction"] < 1


# ------------------------------------------------- doctor budget rule


def _goodput_report(overhead_pct, window_s=100.0):
    ckpt = window_s * overhead_pct / 100.0
    return {
        "kind": "take",
        "world_size": 1,
        "ranks": [
            {
                "rank": 0,
                "wall_s": 1.0,
                "goodput": {
                    "train_s": window_s - ckpt,
                    "checkpoint_s": ckpt,
                    "by_mode": {"sync_take": ckpt},
                    "checkpoint_overhead_pct": overhead_pct,
                    "goodput_fraction": 1 - overhead_pct / 100.0,
                },
            }
        ],
        "totals": {},
    }


def test_doctor_checkpoint_overhead_rule(monkeypatch):
    findings = doctor.diagnose_report(_goodput_report(8.0))
    rules = {f.rule for f in findings}
    assert "checkpoint-overhead-above-budget" in rules
    (finding,) = [
        f for f in findings if f.rule == "checkpoint-overhead-above-budget"
    ]
    assert finding.severity == "warn"
    assert finding.evidence["budget_pct"] == 5.0
    # 2x the budget escalates to critical.
    (critical,) = [
        f
        for f in doctor.diagnose_report(_goodput_report(12.0))
        if f.rule == "checkpoint-overhead-above-budget"
    ]
    assert critical.severity == "critical"
    # Within budget, or too little evidence: silent.
    assert not [
        f
        for f in doctor.diagnose_report(_goodput_report(3.0))
        if f.rule == "checkpoint-overhead-above-budget"
    ]
    assert not [
        f
        for f in doctor.diagnose_report(_goodput_report(8.0, window_s=1.0))
        if f.rule == "checkpoint-overhead-above-budget"
    ]
    # The env budget moves the line.
    monkeypatch.setenv("TPUSNAPSHOT_CKPT_BUDGET_PCT", "20")
    assert not [
        f
        for f in doctor.diagnose_report(_goodput_report(8.0))
        if f.rule == "checkpoint-overhead-above-budget"
    ]


def test_ledger_digest_carries_doctor_rules():
    record = ledger.digest_from_report(_goodput_report(15.0))
    assert "checkpoint-overhead-above-budget" in record["doctor"]
    assert record["goodput"]["checkpoint_overhead_pct"] == 15.0
