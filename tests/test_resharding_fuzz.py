"""Randomized save-sharding × restore-sharding round-trips.

SURVEY §7 ranks resharding correctness across arbitrary mesh/sharding
changes as hard-part #1 (reference edge-case model:
tests/gpu_tests/test_torchrec.py:165-169, non-divisible shard boundaries).
This fuzz deterministically sweeps random global shapes (including
non-divisible and size-1 dims), random source/target meshes and partition
specs (including replicated-within-sharded 2-D layouts), and a small
forced max-chunk size so chunk subdivision and ranged reads trigger.
"""

import itertools
import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import torchsnapshot_tpu.io_preparer as iop
from torchsnapshot_tpu import Snapshot


class _Holder:
    def __init__(self, sd):
        self.sd = sd

    def state_dict(self):
        return self.sd

    def load_state_dict(self, sd):
        self.sd = sd


def _random_mesh_and_spec(rng, shape):
    """A random mesh over a subset of devices and a random PartitionSpec.

    Mesh axes are only assigned to array dims they divide evenly (JAX
    rejects uneven NamedSharding placements); unassigned axes replicate.
    """
    ndim = len(shape)
    devs = jax.devices()
    n = rng.choice([d for d in (1, 2, 4, 8) if d <= len(devs)])
    mesh_shapes = {
        1: [(1,)],
        2: [(2,)],
        4: [(4,), (2, 2)],
        8: [(8,), (4, 2), (2, 2, 2)],
    }[n]
    mesh_shape = rng.choice(mesh_shapes)
    axes = tuple(f"ax{i}" for i in range(len(mesh_shape)))
    mesh = Mesh(np.array(devs[:n]).reshape(mesh_shape), axes)
    spec = [None] * ndim
    for ax, ax_size in zip(axes, mesh_shape):
        dim = rng.randrange(ndim + 1)  # == ndim -> replicated axis
        if dim < ndim and spec[dim] is None and shape[dim] % ax_size == 0:
            spec[dim] = ax
    return mesh, P(*spec)


CASES = list(range(20))


@pytest.mark.parametrize("case", CASES)
def test_random_reshard_roundtrip(tmp_path, case, monkeypatch):
    import ml_dtypes

    rng = random.Random(1234 + case)
    ndim = rng.choice([1, 2, 3])
    shape = tuple(rng.choice([1, 3, 4, 8, 12, 16]) for _ in range(ndim))
    # 4-, 2-, and 1-byte dtypes: chunk/overlap math works in bytes, so
    # itemsize interacts with every boundary computation; bfloat16 also
    # exercises the ml_dtypes (no buffer protocol) payload path.
    dtype = rng.choice(
        [np.float32, np.int32, np.float16, ml_dtypes.bfloat16, np.int8]
    )
    data = (
        np.arange(int(np.prod(shape))).astype(dtype).reshape(shape)
    )

    # Force chunk subdivision on moderately-sized arrays; 100 is not a
    # multiple of any itemsize*row so chunk boundaries land mid-row.
    monkeypatch.setattr(iop, "MAX_CHUNK_SIZE_BYTES", 100)

    src_mesh, src_spec = _random_mesh_and_spec(rng, shape)
    dst_mesh, dst_spec = _random_mesh_and_spec(rng, shape)

    arr = jax.device_put(data, NamedSharding(src_mesh, src_spec))
    snap_path = str(tmp_path / f"snap{case}")
    Snapshot.take(snap_path, {"m": _Holder({"w": arr})})

    template = jax.device_put(
        jnp.zeros(shape, dtype=dtype), NamedSharding(dst_mesh, dst_spec)
    )
    target = _Holder({"w": template})
    Snapshot(snap_path).restore({"m": target})

    restored = target.sd["w"]
    assert restored.sharding == template.sharding
    np.testing.assert_array_equal(np.asarray(restored), data)
    for shard in restored.addressable_shards:
        np.testing.assert_array_equal(np.asarray(shard.data), data[shard.index])


def test_all_1d_spec_pairs_roundtrip(tmp_path, monkeypatch):
    """Exhaustive 1-D sweep: every (src, dst) pairing of canonical layouts.
    Chunk size 12 bytes = 3 float32s does not divide the 3-element shards
    of the 8-way layout or the 6-element shards of the 4-way layout, so
    chunk boundaries fall mid-shard both ways."""
    monkeypatch.setattr(iop, "MAX_CHUNK_SIZE_BYTES", 12)
    data = np.arange(24, dtype=np.float32)
    devs = jax.devices()
    layouts = [
        (Mesh(np.array(devs[:1]), ("x",)), P()),
        (Mesh(np.array(devs[:8]), ("x",)), P("x")),
        (Mesh(np.array(devs[:4]), ("x",)), P("x")),
        (Mesh(np.array(devs[:8]), ("x",)), P()),  # fully replicated over 8
    ]
    for i, ((sm, sp), (dm, dp)) in enumerate(
        itertools.product(layouts, repeat=2)
    ):
        arr = jax.device_put(data, NamedSharding(sm, sp))
        snap_path = str(tmp_path / f"s{i}")
        Snapshot.take(snap_path, {"m": _Holder({"w": arr})})
        template = jax.device_put(
            jnp.zeros((24,), dtype=jnp.float32), NamedSharding(dm, dp)
        )
        target = _Holder({"w": template})
        Snapshot(snap_path).restore({"m": target})
        np.testing.assert_array_equal(np.asarray(target.sd["w"]), data)
