"""Elasticity: restore with a different world size / mesh shape
(reference analog: tests/test_manifest.py:102-189 + snapshot.py:79-113)."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.coord import DictStore, StoreCoordinator


def _run_world(world, fn):
    store = DictStore()
    errors = []

    def worker(rank):
        try:
            coord = StoreCoordinator(store, rank, world, timeout_s=60)
            fn(coord, rank)
        except BaseException as e:  # pragma: no cover
            import traceback

            errors.append((rank, traceback.format_exc()))

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    if errors:
        raise AssertionError(f"rank {errors[0][0]} failed:\n{errors[0][1]}")


class _Holder:
    def __init__(self, sd):
        self.sd = sd

    def state_dict(self):
        return self.sd

    def load_state_dict(self, sd):
        self.sd = sd


def test_replicated_elastic_shrink(tmp_path):
    """Save world=4 replicated, restore world=1."""
    path = str(tmp_path / "snap")
    value = np.arange(32, dtype=np.float32)

    def worker(coord, rank):
        Snapshot.take(
            path, {"st": _Holder({"w": value})}, coord=coord, replicated=["**"]
        )

    _run_world(4, worker)
    target = _Holder({"w": np.zeros(32, dtype=np.float32)})
    Snapshot(path).restore({"st": target})
    np.testing.assert_array_equal(target.sd["w"], value)


def test_replicated_elastic_grow(tmp_path):
    """Save world=2 replicated, restore world=3."""
    path = str(tmp_path / "snap")
    value = np.arange(8, dtype=np.float32)

    def take_worker(coord, rank):
        Snapshot.take(
            path, {"st": _Holder({"w": value})}, coord=coord, replicated=["**"]
        )

    _run_world(2, take_worker)

    def restore_worker(coord, rank):
        target = _Holder({"w": np.zeros(8, dtype=np.float32)})
        Snapshot(path).restore({"st": target}, coord=coord)
        np.testing.assert_array_equal(target.sd["w"], value)

    _run_world(3, restore_worker)


class _StubCoordinator:
    """Pretends to be one rank of a larger world; collectives are identity.

    Useful for exercising rank-dependent error paths without real peers
    (a raising rank would strand peers at a barrier — which is exactly the
    production behavior, so the error itself is tested single-process).
    """

    def __init__(self, rank, world):
        self._rank, self._world = rank, world

    def get_rank(self):
        return self._rank

    def get_world_size(self):
        return self._world

    def barrier(self, timeout_s=None):
        pass

    def all_gather_object(self, obj):
        return [obj] * self._world

    def broadcast_object(self, obj, src=0):
        return obj


def test_per_rank_world_change_raises(tmp_path):
    path = str(tmp_path / "snap")

    def take_worker(coord, rank):
        Snapshot.take(path, {"st": StateDict(x=rank)}, coord=coord)

    _run_world(2, take_worker)

    # Rank 2 of a hypothetical world=3 has no per-rank entry -> the
    # actionable elasticity error (reference snapshot.py:388-406).
    with pytest.raises(RuntimeError, match="only elastic"):
        Snapshot(path).restore(
            {"st": StateDict(x=-1)}, coord=_StubCoordinator(rank=2, world=3)
        )
    # Ranks that do have entries restore fine.
    app = {"st": StateDict(x=-1)}
    Snapshot(path).restore(app, coord=_StubCoordinator(rank=1, world=3))
    assert app["st"]["x"] == 1


def test_sharded_elastic_mesh_reshape(tmp_path):
    """Save on an 8-device mesh, restore onto 2- and 4-device meshes with
    different partition specs — the v5e-64 → v5e-32 elastic-restore analog
    (BASELINE.json configs)."""
    path = str(tmp_path / "snap")
    data = jnp.arange(32 * 16, dtype=jnp.float32).reshape(32, 16)
    mesh8 = Mesh(np.array(jax.devices()).reshape(8), ("x",))
    src = jax.device_put(data, NamedSharding(mesh8, P("x", None)))
    Snapshot.take(path, {"m": _Holder({"w": src})})

    for n, spec in [(2, P("x", None)), (4, P(None, "x")), (8, P("x", None))]:
        mesh = Mesh(np.array(jax.devices()[:n]), ("x",))
        template = jax.device_put(
            jnp.zeros_like(data), NamedSharding(mesh, spec)
        )
        target = _Holder({"w": template})
        Snapshot(path).restore({"m": target})
        np.testing.assert_array_equal(np.asarray(target.sd["w"]), np.asarray(data))
        assert target.sd["w"].sharding.is_equivalent_to(template.sharding, 2)


def test_sharded_save_shrink_then_grow(tmp_path):
    """2-device save -> 8-device restore with a 2D mesh."""
    path = str(tmp_path / "snap")
    data = jnp.arange(16 * 8, dtype=jnp.float32).reshape(16, 8)
    mesh2 = Mesh(np.array(jax.devices()[:2]), ("x",))
    src = jax.device_put(data, NamedSharding(mesh2, P("x", None)))
    Snapshot.take(path, {"m": _Holder({"w": src})})

    mesh8 = Mesh(np.array(jax.devices()).reshape(4, 2), ("a", "b"))
    template = jax.device_put(
        jnp.zeros_like(data), NamedSharding(mesh8, P("a", "b"))
    )
    target = _Holder({"w": template})
    Snapshot(path).restore({"m": target})
    np.testing.assert_array_equal(np.asarray(target.sd["w"]), np.asarray(data))
