"""Randomized incremental-chain scenarios.

Seeded fuzz over chains of incremental takes: random leaf sets (dense /
numpy / chunked-dense / sharded when the mesh allows), random change
subsets per step, restores at random points in the chain, verify() on
every snapshot, and child-first deletion at the end. Complements the
targeted cases in test_incremental.py the way test_roundtrip_fuzz.py
complements test_snapshot.py.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torchsnapshot_tpu import Snapshot, StateDict


def _random_state(rng: np.random.Generator, spec):
    out = {}
    for name, (kind, shape) in spec.items():
        data = rng.standard_normal(shape).astype(np.float32)
        if kind == "np":
            out[name] = data
        elif kind == "jax":
            out[name] = jnp.asarray(data)
        elif kind == "sharded":
            devices = jax.devices()[:4]
            mesh = jax.sharding.Mesh(np.array(devices).reshape(4), ("dp",))
            out[name] = jax.device_put(
                data,
                jax.sharding.NamedSharding(
                    mesh, jax.sharding.PartitionSpec("dp")
                ),
            )
        else:
            raise AssertionError(kind)
    return out


def _mutate(rng: np.random.Generator, state, names):
    for name in names:
        v = state[name]
        host = np.asarray(v).copy()
        idx = tuple(rng.integers(0, s) for s in host.shape)
        host[idx] += 1.0
        if isinstance(v, np.ndarray):
            state[name] = host
        elif hasattr(v, "sharding") and hasattr(v.sharding, "mesh"):
            state[name] = jax.device_put(host, v.sharding)
        else:
            state[name] = jnp.asarray(host)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_incremental_chain_fuzz(tmp_path, seed, monkeypatch):
    import torchsnapshot_tpu.io_preparer as iop

    rng = np.random.default_rng(seed)
    if rng.random() < 0.5:
        # Exercise format-level chunking half the time.
        monkeypatch.setattr(iop, "MAX_CHUNK_SIZE_BYTES", 1 << 11)
    can_shard = len(jax.devices()) >= 4
    kinds = ["np", "jax"] + (["sharded"] if can_shard else [])
    spec = {
        f"leaf{i}": (
            rng.choice(kinds),
            tuple(int(s) for s in rng.integers(1, 9, rng.integers(1, 3)))
            if rng.random() < 0.5
            else (int(rng.integers(4, 40)) * (4 if can_shard else 1),),
        )
        for i in range(int(rng.integers(3, 7)))
    }
    # sharded leaves need a leading dim divisible by 4
    spec = {
        n: (k, ((4 * max(1, s[0] // 4),) + s[1:]) if k == "sharded" else s)
        for n, (k, s) in spec.items()
    }

    state = _random_state(rng, spec)
    snapshots = []
    histories = []  # deep host copies per step for later comparison
    prev = None
    unchanged_into_step: set = set()
    total_refs = 0
    expected_ref_steps = 0
    n_steps = int(rng.integers(3, 6))
    for step in range(n_steps):
        path = str(tmp_path / f"step{step}")
        app = {"model": StateDict(**state)}
        snap = Snapshot.take(
            path,
            app,
            base=prev,
            fingerprint=True,
            compression="zlib" if rng.random() < 0.3 else None,
        )
        snapshots.append(snap)
        histories.append({n: np.asarray(v).copy() for n, v in state.items()})
        assert snap.verify() == {}, f"step {step} verify failed"
        manifest = snap.get_manifest()
        step_refs = sum(
            1
            for e in manifest.values()
            for a in (
                [s.array for s in e.shards] if hasattr(e, "shards") else [e]
            )
            if getattr(a, "base", None) is not None
        )
        if step > 0 and unchanged_into_step:
            # every unchanged leaf must have deduplicated something
            assert step_refs >= len(unchanged_into_step), (
                step,
                unchanged_into_step,
            )
            expected_ref_steps += 1
        total_refs += step_refs
        prev = snap
        # mutate a random subset (possibly empty) for the next step
        names = [n for n in spec if rng.random() < 0.5]
        _mutate(rng, state, names)
        unchanged_into_step = set(spec) - set(names)
    if expected_ref_steps:
        assert total_refs > 0

    # restore a few random steps, bit-exact, with device verification
    for step in rng.choice(n_steps, size=min(3, n_steps), replace=False):
        template = {
            "model": StateDict(
                **{
                    n: (
                        np.zeros_like(histories[step][n])
                        if isinstance(state[n], np.ndarray)
                        else jnp.zeros(
                            histories[step][n].shape, jnp.float32
                        )
                    )
                    for n in spec
                }
            )
        }
        snapshots[step].restore(template, verify_device=True)
        for n in spec:
            np.testing.assert_array_equal(
                np.asarray(template["model"][n]),
                histories[step][n],
                err_msg=f"step {step} leaf {n}",
            )

    # child-first deletion leaves nothing behind
    for step in reversed(range(n_steps)):
        snapshots[step].delete()
    for root, _, files in os.walk(tmp_path):
        assert not files, (root, files)
