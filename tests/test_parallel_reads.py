"""Parallel ranged reads for large objects on restore (VERDICT r3 #2).

A dense ArrayEntry is one storage object of unbounded size; a
single-stream download caps restore far below the link ceiling on
object stores. Whole-object reads above a threshold are split into
concurrent ranged sub-reads reassembled on host — the read-side mirror
of the GCS composite upload (reference analog: 100 MB download chunks,
reference torchsnapshot/storage_plugins/gcs.py:55).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin


class _Holder:
    def __init__(self, sd):
        self.sd = sd

    def state_dict(self):
        return self.sd

    def load_state_dict(self, sd):
        self.sd = sd


class _RecordingFS(FSStoragePlugin):
    """Records every read's (path, byte_range)."""

    reads = []  # class-level so monkeypatched constructor calls share it

    async def read(self, io_req):
        _RecordingFS.reads.append((io_req.path, io_req.byte_range))
        await super().read(io_req)


@pytest.fixture
def recording_fs(monkeypatch):
    import torchsnapshot_tpu.snapshot as snap_mod

    _RecordingFS.reads = []
    monkeypatch.setattr(
        snap_mod, "url_to_storage_plugin", lambda path: _RecordingFS(path)
    )
    return _RecordingFS


def _round_trip(tmp_path, arr, monkeypatch, threshold, strict=False):
    monkeypatch.setenv("TPUSNAPSHOT_PARALLEL_READ_THRESHOLD", str(threshold))
    if strict:
        monkeypatch.setenv("TPUSNAPSHOT_STRICT_INTEGRITY", "1")
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": _Holder({"w": arr})})
    target = {"m": _Holder({"w": jnp.zeros_like(arr)})}
    Snapshot(path).restore(target)
    return np.asarray(target["m"].sd["w"])


def test_large_dense_read_is_split_and_bit_exact(
    tmp_path, monkeypatch, recording_fs
):
    rng = np.random.default_rng(0)
    arr = jnp.asarray(rng.standard_normal((64, 64)), dtype=jnp.float32)
    nbytes = 64 * 64 * 4  # 16 KiB
    threshold = 4096
    out = _round_trip(tmp_path, arr, monkeypatch, threshold)
    np.testing.assert_array_equal(out, np.asarray(arr))
    ranged = [
        (p, r) for p, r in recording_fs.reads if r is not None and "/w" in p
    ]
    assert len(ranged) == nbytes // threshold  # 4 concurrent sub-reads
    # Sub-ranges tile the object exactly.
    spans = sorted(r for _, r in ranged)
    assert spans[0][0] == 0 and spans[-1][1] == nbytes
    for (_, a_end), (b_start, _) in zip(spans, spans[1:]):
        assert a_end == b_start


def test_split_read_verifies_checksum_over_assembled_object(
    tmp_path, monkeypatch, recording_fs
):
    """Splitting must stay integrity-preserving: the checksum is checked
    over the reassembled payload, so mid-object corruption is caught
    even though each sub-read alone cannot verify anything."""
    arr = jnp.arange(8192, dtype=jnp.float32)  # 32 KiB
    monkeypatch.setenv("TPUSNAPSHOT_PARALLEL_READ_THRESHOLD", "4096")
    monkeypatch.setenv("TPUSNAPSHOT_STRICT_INTEGRITY", "1")
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": _Holder({"w": arr})})
    # Corrupt bytes in the MIDDLE of the object (inside sub-read 3).
    obj = tmp_path / "snap" / "0" / "m" / "w"
    raw = bytearray(obj.read_bytes())
    raw[10000:10004] = b"\xde\xad\xbe\xef"
    obj.write_bytes(bytes(raw))
    target = {"m": _Holder({"w": jnp.zeros_like(arr)})}
    with pytest.raises(RuntimeError, match="[Cc]hecksum"):
        Snapshot(path).restore(target)


def test_split_read_strict_integrity_round_trip(
    tmp_path, monkeypatch, recording_fs
):
    arr = jnp.arange(4096, dtype=jnp.float32)
    out = _round_trip(tmp_path, arr, monkeypatch, 1024, strict=True)
    np.testing.assert_array_equal(out, np.arange(4096, dtype=np.float32))


def test_compressed_objects_are_not_split(tmp_path, monkeypatch, recording_fs):
    """Compressed stored size is not derivable from the manifest shape,
    so compressed objects read whole regardless of size."""
    monkeypatch.setenv("TPUSNAPSHOT_PARALLEL_READ_THRESHOLD", "1024")
    path = str(tmp_path / "snap")
    arr = jnp.zeros((4096,), dtype=jnp.float32)  # compresses well
    Snapshot.take(
        path, {"m": _Holder({"w": arr})}, compression="zlib"
    )
    target = {"m": _Holder({"w": jnp.ones_like(arr)})}
    Snapshot(path).restore(target)
    np.testing.assert_array_equal(np.asarray(target["m"].sd["w"]), 0.0)
    assert all(
        r is None for p, r in _RecordingFS.reads if p.endswith("/w")
    )


def test_truncated_object_fails_loudly_through_split_path(
    tmp_path, monkeypatch, recording_fs
):
    monkeypatch.setenv("TPUSNAPSHOT_PARALLEL_READ_THRESHOLD", "1024")
    path = str(tmp_path / "snap")
    arr = jnp.arange(2048, dtype=jnp.float32)  # 8 KiB
    Snapshot.take(path, {"m": _Holder({"w": arr})})
    obj = tmp_path / "snap" / "0" / "m" / "w"
    obj.write_bytes(obj.read_bytes()[:5000])  # truncate mid-object
    target = {"m": _Holder({"w": jnp.zeros_like(arr)})}
    with pytest.raises(Exception):
        Snapshot(path).restore(target)


def test_malformed_threshold_falls_back(monkeypatch):
    from torchsnapshot_tpu.io_preparer import (
        _DEFAULT_PARALLEL_READ_THRESHOLD,
        _parallel_read_threshold,
    )

    monkeypatch.setenv("TPUSNAPSHOT_PARALLEL_READ_THRESHOLD", "not-a-number")
    assert _parallel_read_threshold() == _DEFAULT_PARALLEL_READ_THRESHOLD


def test_sharded_contiguous_subrange_split(tmp_path, monkeypatch):
    """A large contiguous ranged read (resharded restore fetching a
    byte run of a saved chunk) is split the same way, with sub-ranges
    offset into the stored object."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip("needs >= 2 devices")
    mesh = Mesh(np.array(devices[:2]), ("x",))
    arr = jnp.asarray(
        np.random.default_rng(1).standard_normal((256, 16)), jnp.float32
    )
    sharded = jax.device_put(arr, NamedSharding(mesh, P("x", None)))
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": _Holder({"w": sharded})})

    monkeypatch.setenv("TPUSNAPSHOT_PARALLEL_READ_THRESHOLD", "1024")
    # Restore onto a single device: one region overlapping each saved
    # chunk wholly — contiguous ranges of each chunk.
    target = {"m": _Holder({"w": jnp.zeros_like(arr)})}
    Snapshot(path).restore(target)
    np.testing.assert_array_equal(np.asarray(target["m"].sd["w"]), arr)


def test_split_read_on_fake_gcs(monkeypatch):
    """The split path over the north-star gs:// backend: ranged
    sub-reads hit the fake GCS client's download_as_bytes(start, end)
    surface and reassemble bit-exactly."""
    import sys

    sys.path.insert(0, "tests")
    from test_cloud_plugins import _FakeGCSClient

    from torchsnapshot_tpu.storage_plugins.gcs import GCSStoragePlugin
    from torchsnapshot_tpu.io_types import RetryingStoragePlugin

    client = _FakeGCSClient()

    def to_plugin(url):
        root = url[len("gs://"):]
        return RetryingStoragePlugin(
            GCSStoragePlugin(root=root, client=client)
        )

    monkeypatch.setattr(
        "torchsnapshot_tpu.snapshot.url_to_storage_plugin", to_plugin
    )
    monkeypatch.setenv("TPUSNAPSHOT_PARALLEL_READ_THRESHOLD", "4096")
    rng = np.random.default_rng(7)
    arr = jnp.asarray(rng.standard_normal((64, 64)), dtype=jnp.float32)
    Snapshot.take("gs://bucket/snap", {"m": _Holder({"w": arr})})
    target = {"m": _Holder({"w": jnp.zeros_like(arr)})}
    Snapshot("gs://bucket/snap").restore(target)
    np.testing.assert_array_equal(np.asarray(target["m"].sd["w"]), arr)


def test_streaming_split_puts_subranges_eagerly(tmp_path, monkeypatch):
    """A large dense entry restored into a jax template must STREAM:
    one overlap-engine submission per completed sub-range (overlapping
    reads with H2D on the engine's transfer threads) rather than one
    put after full host reassembly."""
    from torchsnapshot_tpu.ops.transfer import H2DPipeline

    rng = np.random.default_rng(3)
    arr = jnp.asarray(rng.standard_normal((64, 64)), dtype=jnp.float32)
    monkeypatch.setenv("TPUSNAPSHOT_PARALLEL_READ_THRESHOLD", "4096")
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": _Holder({"w": arr})})

    calls = []
    orig_submit = H2DPipeline.submit

    def spy(self, host, device, profile=None):
        calls.append(int(getattr(host, "nbytes", len(host))))
        return orig_submit(self, host, device, profile=profile)

    monkeypatch.setattr(H2DPipeline, "submit", spy)
    target = {"m": _Holder({"w": jnp.zeros_like(arr)})}
    Snapshot(path).restore(target)
    np.testing.assert_array_equal(np.asarray(target["m"].sd["w"]), arr)
    # 16 KiB object / 4 KiB threshold = 4 streamed sub-range puts.
    assert len(calls) == 4
    assert all(c == 4096 for c in calls)


def test_streaming_split_strict_integrity_catches_corruption(
    tmp_path, monkeypatch
):
    """Streaming must not weaken integrity: with a jax template and
    strict mode, mid-object corruption is caught before the restored
    array is exposed."""
    monkeypatch.setenv("TPUSNAPSHOT_PARALLEL_READ_THRESHOLD", "4096")
    monkeypatch.setenv("TPUSNAPSHOT_STRICT_INTEGRITY", "1")
    arr = jnp.arange(8192, dtype=jnp.float32)  # 32 KiB
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": _Holder({"w": arr})})
    obj = tmp_path / "snap" / "0" / "m" / "w"
    raw = bytearray(obj.read_bytes())
    raw[20000:20004] = b"\xba\xad\xf0\x0d"
    obj.write_bytes(bytes(raw))
    target = {"m": _Holder({"w": jnp.zeros_like(arr)})}
    with pytest.raises(RuntimeError, match="[Cc]hecksum"):
        Snapshot(path).restore(target)


def test_numpy_template_split_falls_back_to_host_reassembly(
    tmp_path, monkeypatch
):
    """Host (numpy) restores have no device to stream to — the split
    path reassembles on host and stays bit-exact."""
    monkeypatch.setenv("TPUSNAPSHOT_PARALLEL_READ_THRESHOLD", "1024")
    rng = np.random.default_rng(5)
    host = rng.standard_normal((32, 32)).astype(np.float32)
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": _Holder({"w": host})})
    target = {"m": _Holder({"w": np.zeros_like(host)})}
    Snapshot(path).restore(target)
    np.testing.assert_array_equal(target["m"].sd["w"], host)
