"""Random-access read API + inspect CLI tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchsnapshot_tpu import Snapshot, StateDict


class _Holder:
    def __init__(self, sd):
        self.sd = sd

    def state_dict(self):
        return self.sd

    def load_state_dict(self, sd):
        self.sd = sd


@pytest.fixture
def snap(tmp_path):
    mesh = Mesh(np.array(jax.devices()), ("x",))
    sd = {
        "w": jnp.arange(24.0).reshape(4, 6),
        "sharded": jax.device_put(
            jnp.arange(64.0).reshape(16, 4), NamedSharding(mesh, P("x", None))
        ),
        "obj": {1, 2, 3},  # sets are not flattenable -> ObjectEntry leaf
        "count": 5,
    }
    return Snapshot.take(str(tmp_path / "snap"), {"m": _Holder(sd), "p": StateDict(e=1)})


def test_read_dense_array(snap):
    out = snap.read_object("m/w")
    np.testing.assert_array_equal(out, np.arange(24.0).reshape(4, 6))
    assert isinstance(out, np.ndarray)


def test_read_sharded_array_to_host(snap):
    out = snap.read_object("m/sharded")
    np.testing.assert_array_equal(out, np.arange(64.0).reshape(16, 4))


def test_read_with_template_resharding(snap):
    mesh = Mesh(np.array(jax.devices()[:2]), ("x",))
    template = jax.device_put(
        jnp.zeros((16, 4)), NamedSharding(mesh, P(None, "x"))
    )
    out = snap.read_object("m/sharded", template=template)
    np.testing.assert_array_equal(np.asarray(out), np.arange(64.0).reshape(16, 4))
    assert out.sharding.is_equivalent_to(template.sharding, 2)


def test_read_object_and_primitive(snap):
    assert snap.read_object("m/obj") == {1, 2, 3}
    assert snap.read_object("m/count") == 5
    assert snap.read_object("p/e") == 1


def test_read_missing_raises_with_suggestions(snap):
    with pytest.raises(KeyError, match="Available leaves include"):
        snap.read_object("m/nope")


def test_read_container_assembles_subtree(snap):
    out = snap.read_object("m")
    assert set(out.keys()) == {"w", "sharded", "obj", "count"}
    np.testing.assert_array_equal(out["w"], np.arange(24.0).reshape(4, 6))
    np.testing.assert_array_equal(out["sharded"], np.arange(64.0).reshape(16, 4))
    assert out["obj"] == {1, 2, 3}
    assert out["count"] == 5


def test_inspect_cli(snap, capsys):
    from torchsnapshot_tpu.inspect import main

    assert main([snap.path]) == 0
    out = capsys.readouterr().out
    assert "m/w" in out
    assert "ShardedArray" in out
    assert "entries" in out
    assert main([snap.path, "--raw"]) == 0
    raw = capsys.readouterr().out
    assert "0/m/w" in raw
