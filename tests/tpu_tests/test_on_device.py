"""Real-accelerator integration tier (reference analog:
tests/gpu_tests/test_torchrec.py — skipped without the accelerator).

Run on a TPU VM with:

    TPUSNAPSHOT_TPU_TESTS=1 python -m pytest tests/tpu_tests -q

Under the default hermetic suite (``pytest tests/``) the platform is
forced to cpu and every test here self-skips.
"""

import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.utils.train_state import PytreeStateful

pytestmark = pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="real-accelerator tier; run with TPUSNAPSHOT_TPU_TESTS=1 on a TPU VM",
)


def test_device_array_round_trip_bitexact(tmp_path):
    """HBM → storage → HBM round-trip of a ~64 MB array, chunked-transfer
    path included, compared byte-for-byte."""
    key = jax.random.key(0)
    arr = jax.random.normal(key, (16, 1024, 1024), jnp.float32)
    arr.block_until_ready()
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"s": StateDict(w=arr)})
    target = StateDict(w=jnp.zeros_like(arr))
    Snapshot(path).restore({"s": target})
    np.testing.assert_array_equal(np.asarray(target["w"]), np.asarray(arr))
    assert next(iter(target["w"].devices())).platform != "cpu"


def test_bf16_on_device_bitexact(tmp_path):
    arr = jax.random.normal(jax.random.key(1), (333, 517), jnp.bfloat16)
    arr.block_until_ready()
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"s": StateDict(w=arr)})
    target = StateDict(w=jnp.zeros_like(arr))
    Snapshot(path).restore({"s": target})
    np.testing.assert_array_equal(
        np.asarray(target["w"]).view(np.uint16),
        np.asarray(arr).view(np.uint16),
    )


def test_async_take_device_stage(tmp_path):
    """Device-staged consistent cut on real HBM: mutate (rebind) the
    source immediately after async_take returns; the snapshot must hold
    the pre-mutation values."""
    state = {"w": jnp.ones((8, 1024, 1024), jnp.float32)}
    holder = PytreeStateful(state)
    pending = Snapshot.async_take(
        str(tmp_path / "snap"), {"m": holder}, stage="device"
    )
    holder.tree = {"w": state["w"] * -1}
    snap = pending.wait()
    target = PytreeStateful({"w": jnp.zeros((8, 1024, 1024), jnp.float32)})
    snap.restore({"m": target})
    assert float(np.asarray(target.tree["w"]).min()) == 1.0


def test_flash_attention_kernel_on_device():
    """The fused attention Pallas kernel compiles via Mosaic and matches
    the einsum reference on real hardware (bf16 inputs)."""
    from torchsnapshot_tpu.ops.attention import (
        _reference_attention,
        flash_attention,
    )

    kq, kk, kv = jax.random.split(jax.random.key(3), 3)
    shape = (2, 4, 512, 64)
    q = jax.random.normal(kq, shape, jnp.bfloat16)
    k = jax.random.normal(kk, shape, jnp.bfloat16)
    v = jax.random.normal(kv, shape, jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True)
    expected = _reference_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32), True
    )
    err = float(jnp.abs(out.astype(jnp.float32) - expected).max())
    assert err < 2e-2, err


def test_flash_long_context_on_device():
    """32k-token causal attention on one chip: the fused kernel's O(S·D)
    memory is what makes this run at all — the dense path's score matrix
    would need B·H·S² f32 = 34 GB of HBM."""
    from torchsnapshot_tpu.ops.attention import flash_attention

    S = 32768
    kq, kk, kv = jax.random.split(jax.random.key(5), 3)
    q = jax.random.normal(kq, (1, 8, S, 64), jnp.bfloat16)
    k = jax.random.normal(kk, (1, 8, S, 64), jnp.bfloat16)
    v = jax.random.normal(kv, (1, 8, S, 64), jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True)
    out.block_until_ready()
    assert out.shape == (1, 8, S, 64)
    assert bool(jnp.isfinite(out.astype(jnp.float32)).all())


def test_flash_long_context_gradients_on_device():
    """Training-path long context: grads at 16k tokens on one chip. The
    tiled Pallas backward reconstructs p per tile from the saved
    log-sum-exp — a dense backward would materialize B·H·S² probability
    + score tensors (~17 GB here)."""
    from torchsnapshot_tpu.ops.attention import flash_attention

    S = 16384
    kq, kk, kv = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(kq, (1, 8, S, 64), jnp.bfloat16)
    k = jax.random.normal(kk, (1, 8, S, 64), jnp.bfloat16)
    v = jax.random.normal(kv, (1, 8, S, 64), jnp.bfloat16)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True).astype(jnp.float32) ** 2)

    grads = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    jax.block_until_ready(grads)
    for g in grads:
        assert g.shape == (1, 8, S, 64)
        assert bool(jnp.isfinite(g.astype(jnp.float32)).all())


def test_flash_chunk_vjp_on_device():
    """The ring's flash chunk (out, lse) custom VJP compiles under Mosaic
    and matches the einsum reference's gradients on real TPU — the
    long-context-training hot path (delta' = delta − dlse backward)."""
    from torchsnapshot_tpu.ops.attention import flash_chunk_attention

    kq, kk, kv = jax.random.split(jax.random.key(9), 3)
    shape = (1, 4, 1024, 64)
    q = jax.random.normal(kq, shape, jnp.bfloat16)
    k = jax.random.normal(kk, shape, jnp.bfloat16)
    v = jax.random.normal(kv, shape, jnp.bfloat16)

    def loss_flash(q, k, v):
        out, lse = flash_chunk_attention(q, k, v, True, 128, 128, False)
        return jnp.sum(out.astype(jnp.float32) ** 2) + jnp.sum(jnp.sin(lse))

    def ref_pair(q, k, v):
        d = q.shape[-1]
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
        ) / (d**0.5)
        length = q.shape[2]
        mask = jnp.tril(jnp.ones((length, length), bool))
        s = jnp.where(mask[None, None], s, -1e30)
        lse = jax.nn.logsumexp(s, axis=-1, keepdims=True)
        out = jnp.einsum(
            "bhqk,bhkd->bhqd", jnp.exp(s - lse), v.astype(jnp.float32)
        )
        return out, lse

    def loss_ref(q, k, v):
        out, lse = ref_pair(q, k, v)
        return jnp.sum(out**2) + jnp.sum(jnp.sin(lse))

    gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a, dtype=np.float32),
            np.asarray(b, dtype=np.float32),
            atol=0.15,  # bf16 inputs; kernel accumulates f32
            rtol=0.05,
        )


def test_flash_gqa_on_device():
    """GQA index maps lower under Mosaic: 8 q heads sharing 2 kv heads,
    forward + gradients on real TPU vs the repeat-kv einsum reference."""
    from torchsnapshot_tpu.ops.attention import (
        _reference_attention,
        flash_attention,
    )

    b, hq, hkv, s, d = 1, 8, 2, 512, 64
    kq, kk, kv = jax.random.split(jax.random.key(11), 3)
    q = jax.random.normal(kq, (b, hq, s, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, hkv, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, hkv, s, d), jnp.bfloat16)

    out = flash_attention(q, k, v, causal=True)
    g = hq // hkv
    expected = _reference_attention(
        q.astype(jnp.float32),
        jnp.repeat(k, g, axis=1).astype(jnp.float32),
        jnp.repeat(v, g, axis=1).astype(jnp.float32),
        True,
    )
    err = float(jnp.abs(out.astype(jnp.float32) - expected).max())
    assert err < 2e-2, err

    def loss(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True).astype(jnp.float32) ** 2
        )

    grads = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    jax.block_until_ready(grads)
    assert grads[1].shape == (b, hkv, s, d)
    for gr in grads:
        assert bool(jnp.isfinite(gr.astype(jnp.float32)).all())


def test_streaming_restore_device_budget_on_device(tmp_path, monkeypatch):
    """HBM admission control on the real chip (SURVEY §7 hard-part 5):
    two arrays whose combined streamed chunks exceed a forced device
    budget restore bit-exactly — regions admitted one at a time against
    the budget, with the resident halves staying charged. Payload is
    tunnel-sized (~128 MiB); the budget forces the same contention a
    near-HBM-capacity restore hits at full scale."""
    import torchsnapshot_tpu.io_preparer as iop

    monkeypatch.setattr(iop, "MAX_CHUNK_SIZE_BYTES", 16 << 20)
    monkeypatch.setenv(
        "TPUSNAPSHOT_PARALLEL_READ_THRESHOLD", str(4 << 20)
    )
    # Each 64 MiB region charges 2x its size; 160 MiB admits one region
    # (128 MiB charge) but never both at once.
    monkeypatch.setenv(
        "TPUSNAPSHOT_DEVICE_BUDGET_BYTES", str(160 << 20)
    )
    a = jax.random.normal(jax.random.key(11), (16 << 20,), jnp.float32)
    b = jax.random.normal(jax.random.key(12), (16 << 20,), jnp.float32)
    jax.block_until_ready((a, b))
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"s": StateDict(a=a, b=b)})
    target = StateDict(a=jnp.zeros_like(a), b=jnp.zeros_like(b))
    Snapshot(path).restore({"s": target})
    eq = jax.jit(lambda x, y: jnp.all(x == y))
    assert bool(eq(target["a"], a)) and bool(eq(target["b"], b))
    assert next(iter(target["a"].devices())).platform != "cpu"


def test_incremental_take_on_device(tmp_path):
    """Incremental dedup on the real chip: device fingerprints, skipped
    D2H for frozen leaves, device-verified restore (round 5)."""
    frozen = jax.random.normal(jax.random.key(7), (4, 1024, 1024), jnp.float32)
    head = jax.random.normal(jax.random.key(8), (1024,), jnp.float32)
    jax.block_until_ready((frozen, head))
    s1 = Snapshot.take(
        str(tmp_path / "s1"),
        {"m": StateDict(frozen=frozen, head=head)},
        fingerprint=True,
    )
    s2 = Snapshot.take(
        str(tmp_path / "s2"),
        {"m": StateDict(frozen=frozen, head=head + 1.0)},
        base=s1,
    )
    m = s2.get_manifest()
    frozen_entry = m["0/m/frozen"]
    refs = (
        [s.array for s in frozen_entry.shards]
        if hasattr(frozen_entry, "shards")
        else [frozen_entry]
    )
    assert all(a.base is not None for a in refs)
    assert m["0/m/head"].base is None
    target = StateDict(
        frozen=jnp.zeros_like(frozen), head=jnp.zeros_like(head)
    )
    s2.restore({"m": target}, verify_device=True)
    np.testing.assert_array_equal(np.asarray(target["frozen"]), np.asarray(frozen))
    np.testing.assert_array_equal(
        np.asarray(target["head"]), np.asarray(head) + 1.0
    )
    assert next(iter(target["frozen"].devices())).platform != "cpu"
    assert s2.verify() == {}
