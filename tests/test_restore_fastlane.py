"""Streaming zero-copy restore fast path (fastlane).

Covers the staging-buffer pool (reuse, capacity waits, the once-only
scheduler budget re-credit), the H2D overlap engine (transfers off the
consume wall, error surfacing before publication), chunk-granular
early region dispatch, concurrent restores sharing the pool without
profile cross-attribution, and the faultline crash-mid-stream
guarantee: a crash after some chunks are on device but before finalize
never publishes a torn leaf, and the retry is bit-exact.
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torchsnapshot_tpu import Snapshot, faultline as fl, staging_pool
from torchsnapshot_tpu.ops.transfer import H2DPipeline
from torchsnapshot_tpu.telemetry import consume_profile as _cprof


class _Holder:
    def __init__(self, sd):
        self.sd = sd

    def state_dict(self):
        return self.sd

    def load_state_dict(self, sd):
        self.sd = sd


@pytest.fixture(autouse=True)
def _fresh_pool(monkeypatch):
    staging_pool.reset_staging_pool()
    yield
    staging_pool.reset_staging_pool()


def _arr(nbytes, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(nbytes // 4), jnp.float32)


def _restore_report(root):
    import json
    import os

    with open(os.path.join(root, ".report.restore.json")) as f:
        return json.load(f)


# ------------------------------------------------------------ staging pool


def test_pool_reuses_exact_size_buffers():
    pool = staging_pool.StagingPool(capacity_bytes=1 << 20)
    a = pool.acquire(4096)
    backing = a.buffer
    a.release()
    b = pool.acquire(4096)
    assert b.buffer is backing  # exact-size reuse, zero allocation
    assert pool.stats()["in_use_bytes"] == 4096
    b.release()
    assert pool.stats()["in_use_bytes"] == 0
    assert pool.stats()["free_bytes"] == 4096


def test_pool_budget_recredit_fires_exactly_once():
    """The fastlane accounting fix: however many sub-reads shared a
    pooled buffer (and however many paths race to release it), the
    scheduler's host budget is re-credited once."""
    pool = staging_pool.StagingPool(capacity_bytes=1 << 20)
    credits = []
    lease = pool.acquire(8192)
    lease.set_budget_release(credits.append, 8192)
    lease.release()
    lease.release()  # double release: idempotent
    assert credits == [8192]
    # Releaser attached AFTER release (scheduler dispatch racing the
    # pipeline): fires immediately, still exactly once.
    lease2 = pool.acquire(8192)
    lease2.release()
    late = []
    lease2.set_budget_release(late.append, 8192)
    assert late == [8192]


def test_pool_disabled_by_env(monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_RESTORE_STAGING_POOL_BYTES", "0")
    staging_pool.reset_staging_pool()
    assert staging_pool.get_staging_pool() is None


def test_pool_capacity_wait_notes_pool_wait_and_never_deadlocks():
    pool = staging_pool.StagingPool(capacity_bytes=4096, max_wait_s=0.2)
    profile = _cprof.ConsumeProfile()
    first = pool.acquire(4096)
    # Release from another thread while the second acquire waits. Pool
    # acquisitions happen inside consumer executor bodies, i.e. inside
    # a consume section — pool_wait is an in-consume sub-step.
    t = threading.Timer(0.05, first.release)
    t.start()
    with _cprof.consume_section():
        second = pool.acquire(4096, profile)
    t.join()
    assert second.buffer is first.buffer
    waited = profile.summary().get("pool_wait")
    assert waited and waited["seconds"] > 0
    # At capacity with NO release coming: the bounded wait expires and
    # the pool allocates past the cap rather than deadlocking.
    third = pool.acquire(4096, profile)
    assert third.buffer is not second.buffer
    second.release()
    third.release()


def test_pool_retains_free_bytes_only_up_to_capacity():
    pool = staging_pool.StagingPool(capacity_bytes=8192, max_wait_s=0.05)
    leases = [pool.acquire(4096) for _ in range(3)]  # 3rd overflows cap
    for lease in leases:
        lease.release()
    assert pool.stats()["free_bytes"] <= 8192


def test_split_state_budget_recredit_once_through_pool(monkeypatch):
    """_SplitObjectReadState over a pooled assembly buffer: N sub-reads
    share one buffer; the deferred-cost releaser fires once, at pool
    return — not per sub-read (the pre-fastlane single-use
    assumption)."""
    import asyncio

    from torchsnapshot_tpu.io_preparer import _SplitObjectReadState
    from torchsnapshot_tpu.io_types import BufferConsumer

    monkeypatch.setenv(
        "TPUSNAPSHOT_RESTORE_STAGING_POOL_BYTES", str(1 << 20)
    )
    staging_pool.reset_staging_pool()
    assert staging_pool.get_staging_pool() is not None

    sink = {}

    class _Consumer(BufferConsumer):
        async def consume_buffer(self, buf, executor=None):
            sink["payload"] = bytes(buf)

        def get_consuming_cost_bytes(self):
            return 10

    state = _SplitObjectReadState(10, _Consumer())
    reqs = state.add_sub_reads("p", 4)
    consumers = [r.buffer_consumer for r in reqs]
    credits = []
    consumers[0].set_cost_releaser(credits.append)

    async def _run():
        await consumers[0].consume_buffer(b"aaaa")
        await consumers[1].consume_buffer(b"bbbb")
        assert credits == []  # buffer still leased: reservation held
        await consumers[2].consume_buffer(b"cc")

    asyncio.run(_run())
    assert credits == [10]  # exactly once, at pool return
    assert sink["payload"] == b"aaaabbbbcc"
    # The buffer actually went back to the pool for reuse.
    assert staging_pool.get_staging_pool().stats()["free_bytes"] >= 10


# ------------------------------------------------- streaming + overlap engine


def test_pooled_state_stores_lease_before_anything_else(monkeypatch):
    """Regression (snapcheck SNAP006): ``_ensure_buf`` must make the
    lease reachable from the state BEFORE any other work — an exception
    between acquire and store orphaned the pooled buffer (and its
    exactly-once budget re-credit) with no owner left to release it."""
    from torchsnapshot_tpu import io_preparer as iop

    class _BoomLease:
        def __init__(self):
            self.released = False
            self._budget_cb = None
            self._budget_nbytes = 0
            self.credits = []

        @property
        def buffer(self):
            raise RuntimeError("boom between acquire and store")

        def release(self):
            self.released = True
            cb, self._budget_cb = self._budget_cb, None
            if cb is not None:
                cb(self._budget_nbytes)

        def set_budget_release(self, cb, nbytes):
            if self.released:
                cb(nbytes)
            else:
                self._budget_cb = cb
                self._budget_nbytes = nbytes

    class _FakePool:
        def __init__(self):
            self.lease = _BoomLease()

        def acquire(self, nbytes, profile=None):
            return self.lease

    pool = _FakePool()
    monkeypatch.setattr(staging_pool, "get_staging_pool", lambda: pool)
    state = iop._SplitObjectReadState.__new__(iop._SplitObjectReadState)
    iop._PooledAssemblyState.__init__(state, nbytes=64)
    credits = []
    state.set_cost_releaser(credits.append)
    with pytest.raises(RuntimeError, match="boom"):
        state._ensure_buf()
    # The lease is reachable, so the state's release path returns it —
    # AND the scheduler re-credit the lease never got attached to
    # still fires, exactly once.
    assert state._lease is pool.lease
    state._release_assembly_buffer()
    assert pool.lease.released
    assert credits == [64]
    state._release_assembly_buffer()  # idempotent: no double credit
    assert credits == [64]


def test_streaming_report_moves_h2d_off_the_consume_wall(
    tmp_path, monkeypatch
):
    """On the streaming path the H2D runs on the overlap engine: the
    flight report shows h2d_overlap carrying the payload bytes, no
    device_put inside consume, and the in-consume sub-steps still
    reconcile exactly against the consume wall."""
    monkeypatch.setenv("TPUSNAPSHOT_PARALLEL_READ_THRESHOLD", str(64 << 10))
    arr = _arr(1 << 20, seed=7)
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": _Holder({"w": arr})})
    target = {"m": _Holder({"w": jnp.zeros_like(arr)})}
    Snapshot(path).restore(target)
    np.testing.assert_array_equal(
        np.asarray(target["m"].sd["w"]), np.asarray(arr)
    )
    report = _restore_report(path)
    profile = next(
        s["consume_profile"]
        for s in report["ranks"]
        if s and s.get("consume_profile")
    )
    substeps = profile["substeps"]
    overlap = substeps.get("h2d_overlap")
    assert overlap and overlap["bytes"] == arr.nbytes
    assert substeps.get("device_put", {}).get("bytes", 0) == 0
    in_consume = sum(
        e["seconds"]
        for n, e in substeps.items()
        if n not in ("read_wait", "h2d_overlap", "overlap_other")
    )
    assert in_consume == pytest.approx(profile["consume_s"], abs=1e-3)
    assert profile.get("h2d_overlap_gbps", 0) > 0


def test_early_region_dispatch_for_compressed_leaf(tmp_path, monkeypatch):
    """A compressed leaf cannot stream raw ranges, but its region's H2D
    still dispatches on the overlap engine the moment its last copy
    lands (chunk-granular overlap), not at plan finalize."""
    monkeypatch.setenv("TPUSNAPSHOT_H2D_CHUNK_BYTES", "4096")
    arr = _arr(64 << 10, seed=3)
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": _Holder({"w": arr})}, compression="zlib")

    submits = []
    orig_submit = H2DPipeline.submit

    def spy(self, host, device, profile=None):
        submits.append(int(getattr(host, "nbytes", len(host))))
        return orig_submit(self, host, device, profile=profile)

    monkeypatch.setattr(H2DPipeline, "submit", spy)
    target = {"m": _Holder({"w": jnp.zeros_like(arr)})}
    Snapshot(path).restore(target)
    np.testing.assert_array_equal(
        np.asarray(target["m"].sd["w"]), np.asarray(arr)
    )
    assert submits == [arr.nbytes]
    report = _restore_report(path)
    profile = next(
        s["consume_profile"]
        for s in report["ranks"]
        if s and s.get("consume_profile")
    )
    assert profile["substeps"]["h2d_overlap"]["bytes"] == arr.nbytes


def test_engine_transfer_failure_surfaces_and_never_publishes(
    tmp_path, monkeypatch
):
    """A failed overlap-engine transfer must fail the restore (surfaced
    by the plan's finalize) with the template untouched — and a retry
    without the fault restores bit-exact."""
    from concurrent.futures import Future

    monkeypatch.setenv("TPUSNAPSHOT_PARALLEL_READ_THRESHOLD", str(64 << 10))
    arr = _arr(512 << 10, seed=11)
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": _Holder({"w": arr})})

    orig_submit = H2DPipeline.submit
    calls = [0]

    def failing(self, host, device, profile=None):
        calls[0] += 1
        if calls[0] == 3:
            fut = Future()
            fut.set_exception(RuntimeError("injected transfer failure"))
            return fut
        return orig_submit(self, host, device, profile=profile)

    monkeypatch.setattr(H2DPipeline, "submit", failing)
    target = {"m": _Holder({"w": jnp.zeros_like(arr)})}
    with pytest.raises(RuntimeError, match="injected transfer failure"):
        Snapshot(path).restore(target)
    # Torn-leaf guard: the template was never overwritten.
    np.testing.assert_array_equal(
        np.asarray(target["m"].sd["w"]), np.zeros(arr.shape, np.float32)
    )
    monkeypatch.setattr(H2DPipeline, "submit", orig_submit)
    target = {"m": _Holder({"w": jnp.zeros_like(arr)})}
    Snapshot(path).restore(target)
    np.testing.assert_array_equal(
        np.asarray(target["m"].sd["w"]), np.asarray(arr)
    )


# ----------------------------------------------------- concurrency + faults


def test_concurrent_restores_share_pool_without_cross_attribution(
    tmp_path, monkeypatch
):
    """Two simultaneous restores draw from the ONE process pool; each
    flight report still reconciles exactly (sub-steps sum to its own
    consume wall — pooled buffers carry no cross-restore attribution)."""
    monkeypatch.setenv("TPUSNAPSHOT_PARALLEL_READ_THRESHOLD", str(64 << 10))
    monkeypatch.setenv(
        "TPUSNAPSHOT_RESTORE_STAGING_POOL_BYTES", str(8 << 20)
    )
    staging_pool.reset_staging_pool()
    roots, states = [], []
    for i in range(2):
        root = str(tmp_path / f"snap{i}")
        state = {"m": _Holder({"w": _arr(768 << 10, seed=20 + i)})}
        Snapshot.take(root, state)
        roots.append(root)
        states.append(state)
    errors = []

    def _restore(root, state):
        try:
            target = {
                "m": _Holder(
                    {"w": jnp.zeros_like(state["m"].sd["w"])}
                )
            }
            Snapshot(root).restore(target)
            np.testing.assert_array_equal(
                np.asarray(target["m"].sd["w"]),
                np.asarray(state["m"].sd["w"]),
            )
        except Exception as e:  # pragma: no cover
            errors.append(repr(e))

    threads = [
        threading.Thread(target=_restore, args=(r, s))
        for r, s in zip(roots, states)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    for root in roots:
        report = _restore_report(root)
        profile = next(
            s["consume_profile"]
            for s in report["ranks"]
            if s and s.get("consume_profile")
        )
        in_consume = sum(
            e["seconds"]
            for n, e in profile["substeps"].items()
            if n not in ("read_wait", "h2d_overlap", "overlap_other")
        )
        assert in_consume == pytest.approx(
            profile["consume_s"], abs=1e-3
        )
    pool = staging_pool.get_staging_pool()
    assert pool is not None
    # Every lease was donated back: nothing left in use.
    deadline = time.monotonic() + 10
    while (
        pool.stats()["in_use_bytes"] and time.monotonic() < deadline
    ):
        time.sleep(0.01)
    assert pool.stats()["in_use_bytes"] == 0


@pytest.mark.faultline
def test_crash_mid_stream_never_publishes_torn_leaf(tmp_path, monkeypatch):
    """A SimulatedCrash after some chunks are already device_put (but
    before finalize) fails the restore with the template untouched;
    the retry is bit-exact."""
    monkeypatch.setenv("TPUSNAPSHOT_PARALLEL_READ_THRESHOLD", str(64 << 10))
    arr = _arr(1 << 20, seed=5)  # 16 streamed sub-reads
    path = str(tmp_path / "snap")
    Snapshot.take(path, {"m": _Holder({"w": arr})})

    target = {"m": _Holder({"w": jnp.zeros_like(arr)})}
    sched = fl.FaultSchedule().crash_on(op="read", path="0/m/w", nth=10)
    with fl.inject(sched):
        with pytest.raises(fl.SimulatedCrash):
            Snapshot(path).restore(target)
    # No torn leaf: the template still holds its zeros — nothing was
    # published from the partially-transferred stream.
    np.testing.assert_array_equal(
        np.asarray(target["m"].sd["w"]), np.zeros(arr.shape, np.float32)
    )
    # Retry without the fault: bit-exact.
    target = {"m": _Holder({"w": jnp.zeros_like(arr)})}
    Snapshot(path).restore(target)
    np.testing.assert_array_equal(
        np.asarray(target["m"].sd["w"]), np.asarray(arr)
    )


def test_chunkstore_restore_pools_and_reconciles(tmp_path, monkeypatch):
    """Content-chunked (chunkstore) restores assemble through pooled
    buffers with decode+verify fused in the consume executors; the
    report still reconciles and the restore is bit-exact."""
    monkeypatch.setenv(
        "TPUSNAPSHOT_RESTORE_STAGING_POOL_BYTES", str(8 << 20)
    )
    staging_pool.reset_staging_pool()
    arr = _arr(256 << 10, seed=9)
    path = str(tmp_path / "snap")
    Snapshot.take(
        path, {"m": _Holder({"w": arr})}, chunks=True, codec="zlib"
    )
    target = {"m": _Holder({"w": jnp.zeros_like(arr)})}
    Snapshot(path).restore(target)
    np.testing.assert_array_equal(
        np.asarray(target["m"].sd["w"]), np.asarray(arr)
    )
    report = _restore_report(path)
    profile = next(
        s["consume_profile"]
        for s in report["ranks"]
        if s and s.get("consume_profile")
    )
    assert profile["substeps"]["decode"]["seconds"] > 0
    assert profile["substeps"]["verify"]["seconds"] > 0
    in_consume = sum(
        e["seconds"]
        for n, e in profile["substeps"].items()
        if n not in ("read_wait", "h2d_overlap", "overlap_other")
    )
    assert in_consume == pytest.approx(profile["consume_s"], abs=1e-3)


def test_depth_one_engine_never_deadlocks_finalize(tmp_path, monkeypatch):
    """TPUSNAPSHOT_H2D_DEPTH=1: an eager finalize fired from the
    engine's only worker must not block that worker on futures queued
    behind itself (finalize hops to its own pool). Two streamed leaves
    force queued transfers across plans."""
    from torchsnapshot_tpu.ops import transfer as transfer_mod

    monkeypatch.setenv("TPUSNAPSHOT_H2D_DEPTH", "1")
    monkeypatch.setenv("TPUSNAPSHOT_PARALLEL_READ_THRESHOLD", str(64 << 10))
    transfer_mod._reset_h2d_pipeline_for_tests()
    try:
        state = {
            "m": _Holder(
                {
                    "a": _arr(512 << 10, seed=31),
                    "b": _arr(512 << 10, seed=32),
                }
            )
        }
        path = str(tmp_path / "snap")
        Snapshot.take(path, state)
        target = {
            "m": _Holder(
                {
                    "a": jnp.zeros_like(state["m"].sd["a"]),
                    "b": jnp.zeros_like(state["m"].sd["b"]),
                }
            )
        }
        done = []

        def _run():
            Snapshot(path).restore(target)
            done.append(1)

        t = threading.Thread(target=_run)
        t.start()
        t.join(timeout=120)
        assert done == [1], "restore deadlocked at H2D depth 1"
        for k in ("a", "b"):
            np.testing.assert_array_equal(
                np.asarray(target["m"].sd[k]),
                np.asarray(state["m"].sd[k]),
            )
    finally:
        transfer_mod._reset_h2d_pipeline_for_tests()


def test_identity_chunk_decode_writes_straight_into_assembly(monkeypatch):
    """decode_and_verify_chunk's zero-copy hand-off: an identity chunk
    verifies on the stored view and lands in ``out`` with one copy;
    corruption still raises before anything is written back."""
    from torchsnapshot_tpu.chunkstore import decode_and_verify_chunk
    from torchsnapshot_tpu.fingerprint import fingerprint_host

    payload = np.arange(256, dtype=np.uint8).tobytes()
    key = f"{fingerprint_host(payload)}-{len(payload)}-raw"
    rec = {"k": key, "n": len(payload), "c": None}
    out = bytearray(len(payload))
    ret = decode_and_verify_chunk(
        rec, "uint8", payload, out=memoryview(out)
    )
    assert ret is None  # wrote in place
    assert bytes(out) == payload
    # Without out: the legacy contract returns the bytes.
    assert decode_and_verify_chunk(rec, "uint8", payload) == payload
    corrupt = bytearray(payload)
    corrupt[7] ^= 0xFF
    with pytest.raises(RuntimeError, match="fingerprint"):
        decode_and_verify_chunk(
            rec, "uint8", bytes(corrupt), out=memoryview(out)
        )
