"""Crash-matrix coverage for chunk-store GC (chunkstore.py +
faultline): a crash at ANY storage-op boundary across
``delete`` → ref-doc removal (the refcount decrement) → chunk-free →
``reconcile()`` must never free a chunk a committed manifest
references, and a follow-up ``reconcile()`` must reclaim every
unreferenced chunk leak-free.

Fast tier-1 subset: every Nth crash point on both backends. Full
per-op enumeration is ``-m slow``."""

import glob
import os
import uuid

import numpy as np
import pytest

import jax.numpy as jnp

from torchsnapshot_tpu import Snapshot, chunkstore
from torchsnapshot_tpu.faultline import (
    FaultSchedule,
    SimulatedCrash,
    count_storage_ops,
    inject,
)
from torchsnapshot_tpu.state_dict import StateDict
from torchsnapshot_tpu.storage_plugin import url_to_storage_plugin

pytestmark = pytest.mark.faultline

_STRIDE = 4  # fast-tier subsample of the crash points


@pytest.fixture(autouse=True)
def _gc_env(monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    monkeypatch.setenv("TPUSNAPSHOT_REFS_MIN_AGE_S", "0")
    monkeypatch.setenv("TPUSNAPSHOT_CHUNK_BYTES", "4096")
    monkeypatch.setenv("TPUSNAPSHOT_CHUNK_MIN_BYTES", "0")


def _expected_states():
    """Three takes: step-2 shares most chunks with step-1 (one dirty
    chunk), step-3 shares with step-2 — the sharing pattern that makes
    premature freeing visible."""
    rng = np.random.RandomState(7)
    base = rng.randn(256, 32).astype(np.float32)
    states = {}
    cur = base
    for step in (1, 2, 3):
        states[step] = cur.copy()
        nxt = cur.copy()
        nxt[(step * 32) : (step * 32) + 32] += 1.0
        cur = nxt
    return states


def _build_run(root: str) -> dict:
    states = _expected_states()
    for step, arr in states.items():
        Snapshot.take(
            f"{root}/step-{step}",
            {"m": StateDict(emb=jnp.asarray(arr))},
            chunks=True,
        )
    return states


def _assert_invariant(root: str, states: dict, deleted_step: int) -> None:
    """Restore-or-detect over the chunk plane: every still-committed
    step verifies clean (chunk objects present + content-verified) and
    restores bit-exact — whatever the crash interrupted."""
    for step, arr in states.items():
        if step == deleted_step:
            continue
        snap = Snapshot(f"{root}/step-{step}")
        try:
            snap.get_manifest()
        except Exception:
            continue  # never committed (not possible here) / deleted
        problems = snap.verify()
        assert not problems, (
            f"crash freed chunk(s) a committed manifest references "
            f"(step {step}): {problems}"
        )
        t = {"m": StateDict(emb=jnp.zeros(arr.shape, jnp.float32))}
        snap.restore(t)
        assert np.array_equal(np.asarray(t["m"]["emb"]), arr), step


def _assert_leak_free(root: str) -> None:
    """After reconcile, the store holds exactly the chunks live
    committed manifests reference (plus their ref docs)."""
    live_keys = set()
    live_refs = set()
    for md_glob in range(1, 4):
        url = f"{root}/step-{md_glob}"
        try:
            manifest = Snapshot(url).get_manifest()
        except Exception:
            continue
        keys = chunkstore.chunk_keys_of(manifest)
        if keys:
            live_keys |= keys
            live_refs.add(chunkstore.ref_doc_name(url))
    import asyncio

    storage = url_to_storage_plugin(f"{root}/.chunkstore")
    try:
        objs = asyncio.run(storage.list_prefix("")) or []
    finally:
        storage.close()
    on_disk_keys = {
        o.rsplit("/", 1)[-1]
        for o in objs
        if o.startswith(chunkstore.OBJECTS_PREFIX)
    }
    on_disk_refs = {
        o.rsplit("/", 1)[-1]
        for o in objs
        if o.startswith(chunkstore.REFS_PREFIX)
    }
    intents = [
        o for o in objs if o.startswith(chunkstore.INTENTS_PREFIX)
    ]
    assert on_disk_keys == live_keys, (
        f"leaked={sorted(on_disk_keys - live_keys)} "
        f"missing={sorted(live_keys - on_disk_keys)}"
    )
    assert on_disk_refs == live_refs
    assert not intents


def _scenario(root: str) -> None:
    Snapshot(f"{root}/step-1").delete()
    chunkstore.reconcile_store(root)


def _run_matrix(make_root, points=None):
    root = make_root()
    states = _build_run(root)
    total = count_storage_ops(lambda: _scenario(root))
    assert total > 0
    if points is None:
        points = range(1, total + 1)
    for k in points:
        root = make_root()
        states = _build_run(root)
        with inject(FaultSchedule().crash_at(k)):
            try:
                _scenario(root)
            except SimulatedCrash:
                pass
        _assert_invariant(root, states, deleted_step=1)
        # Recovery: finish the interrupted delete's intent (the
        # snapshot may be half-deleted — re-drive it), then reconcile
        # reclaims every leak.
        try:
            Snapshot(f"{root}/step-1").delete(sweep=True, force=True)
        except Exception:
            pass  # already fully deleted / uncommitted
        chunkstore.reconcile_store(root)
        _assert_invariant(root, states, deleted_step=1)
        _assert_leak_free(root)
    return total


def _fs_root_factory(tmp_path):
    counter = [0]

    def _make():
        counter[0] += 1
        d = tmp_path / f"run{counter[0]}"
        d.mkdir()
        return str(d)

    return _make


def _memory_root_factory():
    def _make():
        return f"memory://gcmx-{uuid.uuid4().hex[:10]}/run"

    return _make


class TestGCCrashMatrixFast:
    def test_fs_stride(self, tmp_path):
        make = _fs_root_factory(tmp_path)
        root = make()
        _build_run(root)
        total = count_storage_ops(lambda: _scenario(root))
        _run_matrix(make, points=range(1, total + 1, _STRIDE))

    def test_memory_stride(self):
        make = _memory_root_factory()
        root = make()
        _build_run(root)
        total = count_storage_ops(lambda: _scenario(root))
        _run_matrix(make, points=range(1, total + 1, _STRIDE))


@pytest.mark.slow
class TestGCCrashMatrixFull:
    def test_fs_full_enumeration(self, tmp_path):
        _run_matrix(_fs_root_factory(tmp_path))

    def test_memory_full_enumeration(self):
        _run_matrix(_memory_root_factory())


class TestGCWithoutFaults:
    def test_delete_all_steps_empties_store(self, tmp_path):
        root = str(tmp_path)
        _build_run(root)
        for step in (1, 2, 3):
            Snapshot(f"{root}/step-{step}").delete()
        assert not glob.glob(f"{root}/.chunkstore/objects/*/*")
        assert not glob.glob(f"{root}/.chunkstore/refs/*")

    def test_interrupted_delete_redriven_by_reconcile(self, tmp_path):
        # Simulate the worst half-done delete: metadata + ref doc gone,
        # chunks still present. reconcile must reclaim exactly the
        # now-unreferenced chunks.
        root = str(tmp_path)
        states = _build_run(root)
        url = f"{root}/step-1"
        keys1 = chunkstore.chunk_keys_of(Snapshot(url).get_manifest())
        os.remove(f"{root}/step-1/.snapshot_metadata")
        ref = (
            f"{root}/.chunkstore/refs/{chunkstore.ref_doc_name(url)}"
        )
        os.remove(ref)
        chunkstore.reconcile_store(root)
        _assert_invariant(root, states, deleted_step=1)
        remaining = {
            p.rsplit("/", 1)[-1]
            for p in glob.glob(f"{root}/.chunkstore/objects/*/*")
        }
        live = chunkstore.chunk_keys_of(
            Snapshot(f"{root}/step-2").get_manifest()
        ) | chunkstore.chunk_keys_of(
            Snapshot(f"{root}/step-3").get_manifest()
        )
        assert remaining == live
        assert not (keys1 - live) & remaining
