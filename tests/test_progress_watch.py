"""snapwatch: live progress records + watch straggler detection,
cross-rank trace merge + critical path, and the anomaly doctor
(ISSUE 4 acceptance criteria)."""

import asyncio
import json
import os
import subprocess
import sys
import threading
import time
import uuid

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, telemetry, tracing
from torchsnapshot_tpu.storage_plugin import (
    _MEMORY_STORES,
    set_plugin_wrap_hook,
    url_to_storage_plugin,
)
from torchsnapshot_tpu.telemetry import doctor, merge
from torchsnapshot_tpu.telemetry import progress as liveprog
from torchsnapshot_tpu.telemetry import summarize, watch
from torchsnapshot_tpu.utils.test_utils import run_thread_ranks


@pytest.fixture(autouse=True)
def _fresh_registry():
    telemetry.reset()
    yield
    telemetry.reset()


class _Model:
    def __init__(self, params):
        self.params = params

    def state_dict(self):
        return self.params

    def load_state_dict(self, sd):
        self.params = sd


def _rank_state(rank: int, n: int = 8192):
    rng = np.random.RandomState(rank + 1)
    return {"w": rng.randn(n).astype(np.float32)}


# ------------------------------------------------------------ publisher unit


def test_publisher_statusfile_roundtrip(tmp_path):
    pub = liveprog.ProgressPublisher(
        kind="take",
        path="memory://x/y",
        rank=2,
        world_size=4,
        statusfile_dir=str(tmp_path),
        interval_s=0.0,
    )
    pub.set_phase("write")
    pub.add_bytes_total(100)
    pub.pipeline_update("write", 40)
    rec = json.load(open(tmp_path / "rank2.progress.json"))
    assert rec["format_version"] == liveprog.PROGRESS_FORMAT_VERSION
    assert rec["phase"] == "write"
    assert rec["rank"] == 2
    assert rec["world_size"] == 4
    assert rec["bytes_done"] == 40
    assert rec["bytes_total"] == 100
    assert rec["ops"] == {"write": 1}
    assert rec["heartbeat_at"] >= rec["started_at"]
    pub.finish()
    rec = json.load(open(tmp_path / "rank2.progress.json"))
    assert rec["phase"] == liveprog.DONE_PHASE


def test_sync_take_and_restore_publish_statusfiles(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUSNAPSHOT_PROGRESS_DIR", str(tmp_path / "prog"))
    monkeypatch.setenv("TPUSNAPSHOT_PROGRESS_INTERVAL_S", "0")
    model = _Model(_rank_state(0))
    snap = Snapshot.take(str(tmp_path / "snap"), {"m": model})
    rec = json.load(open(tmp_path / "prog" / "rank0.progress.json"))
    assert rec["phase"] == "done"
    assert rec["kind"] == "take"
    assert rec["bytes_done"] == 8192 * 4
    assert rec["bytes_total"] == 8192 * 4
    snap.restore({"m": _Model(_rank_state(0))})
    rec = json.load(open(tmp_path / "prog" / "rank0.progress.json"))
    assert rec["kind"] == "restore"
    assert rec["phase"] == "done"
    assert rec["bytes_done"] == 8192 * 4
    # watch's directory mode renders the statusfiles
    grouped = watch.collect(str(tmp_path / "prog"))
    (records,) = grouped.values()
    out = watch.render_progress(records, stale_after_s=3600)
    assert "restore" in out and "done" in out
    # a finished operation's lingering statusfile renders but does NOT
    # count as in-flight: the scripting contract (exit 1 = idle) holds
    assert watch.main([str(tmp_path / "prog")]) == 1


# -------------------------------------------- acceptance: in-flight 4 ranks


class _GatedWrites:
    """Wrap hook plugin: writes whose path starts with ``prefix`` block
    until the gate opens — a deterministic 'paused in write phase'."""

    def __init__(self, inner, gate: threading.Event, prefix: str) -> None:
        self._inner = inner
        self._gate = gate
        self._prefix = prefix

    async def write(self, io_req):
        if io_req.path.startswith(self._prefix):
            while not self._gate.is_set():
                await asyncio.sleep(0.01)
        await self._inner.write(io_req)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def test_watch_four_rank_inflight_take_flags_straggler(
    monkeypatch, capsys
):
    """Acceptance: an in-flight (paused-in-phase) 4-rank async take —
    watch reports per-rank phase + bytes, and flags the gated rank's
    stale heartbeat as a straggler within the staleness window."""
    monkeypatch.setenv("TPUSNAPSHOT_PROGRESS_INTERVAL_S", "0")
    bucket = f"watchacc-{uuid.uuid4().hex[:10]}"
    url = f"memory://{bucket}/snap"
    gate = threading.Event()
    # Rank 3's payload objects live under "3/…": only they block.
    prev = set_plugin_wrap_hook(
        lambda plugin, u: _GatedWrites(plugin, gate, "3/")
    )
    try:
        def fn(coord, rank):
            return Snapshot.async_take(
                url, {"m": _Model(_rank_state(rank))}, coord=coord
            )

        pendings = run_thread_ranks(4, fn)

        # The drains run in background threads; wait until the expected
        # in-flight picture is observable: rank 3 paused mid-write,
        # ranks 1-2 done (terminal record pre-marker), rank 0 polling
        # markers in its commit phase.
        deadline = time.monotonic() + 30
        records = {}
        while time.monotonic() < deadline:
            ops = watch.collect(url)
            if ops:
                (records,) = ops.values()
                phases = {
                    r: rec.get("phase") for r, rec in records.items()
                }
                if (
                    len(records) == 4
                    and phases.get(3) == "write"
                    and phases.get(1) == "done"
                    and phases.get(2) == "done"
                    and phases.get(0) == "commit"
                ):
                    break
            time.sleep(0.05)
        assert len(records) == 4, f"records: {records.keys()}"

        # Let rank 3's heartbeat age past the staleness window.
        time.sleep(0.5)
        records = next(iter(watch.collect(url).values()))
        out = watch.render_progress(records, stale_after_s=0.3)
        lines = {
            int(line.split()[0]): line
            for line in out.splitlines()
            if line.strip() and line.split()[0].isdigit()
        }
        # Per-rank phase + bytes.
        assert "write" in lines[3] and "STALE" in lines[3]
        assert "done" in lines[1] and "STALE" not in lines[1]
        assert "done" in lines[2]
        assert "commit" in lines[0]
        nbytes = 8192 * 4
        for r in (1, 2):
            assert records[r]["bytes_done"] == nbytes
            assert records[r]["bytes_total"] == nbytes
        assert records[3]["bytes_done"] < nbytes
        assert records[3]["bytes_total"] == nbytes
        # The straggler summary names rank 3 (rank 0 legitimately also
        # reads stale: it is stuck waiting on rank 3's marker).
        straggler = [l for l in out.splitlines() if "STRAGGLER" in l]
        assert straggler and "3" in straggler[0]

        # The CLI renders the same picture and exits 0.
        assert watch.main([url, "--stale-after", "0.3"]) == 0
        cli_out = capsys.readouterr().out
        assert "STALE" in cli_out and "async_take in flight" in cli_out

        # Unblock the straggler: the take commits and every progress
        # object is cleaned at commit.
        gate.set()
        for pending in pendings:
            pending.wait(timeout_s=60)
    finally:
        gate.set()
        set_plugin_wrap_hook(prev)
    store = _MEMORY_STORES[bucket]
    assert "snap/.snapshot_metadata" in store
    assert [k for k in store if ".progress" in k] == []
    # Nothing in flight anymore: watch reports so and exits 1.
    assert watch.main([url]) == 1


# ----------------------------------------------------------- trace metadata


def test_trace_metadata_roundtrip(tmp_path):
    """Satellite: every flushed trace is self-describing — wall-clock
    epoch, rank, hostname — even single-rank ones."""
    import socket

    before = time.time()
    tracing.set_identity(rank=5)
    tracing.enable(str(tmp_path / "t.json"))
    with tracing.span("write", bytes=4):
        pass
    tracing.disable()
    doc = json.load(open(tmp_path / "t.json"))
    meta = doc["metadata"]
    assert before <= meta["clock_epoch_s"] <= time.time()
    assert meta["rank"] == 5
    assert meta["host"] == socket.gethostname()
    assert meta["pid"] == os.getpid()
    # merge's loader reads the same fields back
    loaded = merge.trace_meta(merge.load_trace(str(tmp_path / "t.json")), 0)
    assert loaded["rank"] == 5
    assert loaded["clock_epoch_s"] == meta["clock_epoch_s"]
    tracing.set_identity(rank=0)  # don't leak rank into other tests


def test_store_coordinator_emits_barrier_instants(tmp_path):
    """Barrier exits land in the trace as the merge's skew anchors."""
    from torchsnapshot_tpu.coord import DictStore, StoreCoordinator

    tracing.enable(str(tmp_path / "b.json"))
    try:
        coord = StoreCoordinator(DictStore(), 0, 1, timeout_s=5)
        coord.barrier()
        coord.barrier()
    finally:
        tracing.disable()
    doc = json.load(open(tmp_path / "b.json"))
    gens = [
        e["args"]["gen"]
        for e in doc["traceEvents"]
        if e.get("name") == "barrier_exit"
    ]
    assert len(gens) == 2 and gens[0] != gens[1]


# ------------------------------------------------------------- trace merge


def _synthetic_rank_trace(rank, epoch, write_end_us, skew_s=0.0):
    """One rank's trace: a shared barrier at ts=1ms, then a write span.
    ``skew_s`` shifts the recorded wall clock (a wrong host clock)."""
    events = [
        {
            "name": "barrier_exit",
            "ph": "i",
            "s": "p",
            "ts": 1000.0,
            "pid": 1,
            "tid": 1,
            "args": {"gen": 1},
        },
        {
            "name": "write",
            "ph": "b",
            "id": 1,
            "ts": 2000.0,
            "pid": 1,
            "tid": 1,
            "args": {"bytes": 1 << 20},
        },
        {
            "name": "write",
            "ph": "e",
            "id": 1,
            "ts": float(write_end_us),
            "pid": 1,
            "tid": 1,
        },
    ]
    if rank == 0:
        events.append(
            {
                "name": "metadata_committed",
                "ph": "i",
                "s": "p",
                "ts": float(write_end_us + 500_000),
                "pid": 1,
                "tid": 1,
            }
        )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "clock_epoch_s": epoch + skew_s,
            "rank": rank,
            "host": f"host{rank}",
            "pid": 100 + rank,
        },
    }


def test_merge_four_ranks_monotonic_clock_and_gating_rank(tmp_path, capsys):
    """Acceptance: merge over 4 per-rank traces yields one
    monotonic-clock trace whose critical path names the gating rank;
    the injected clock skew is detected and corrected."""
    epoch = 1_700_000_000.0
    # Rank 2 works 0.9s — the gater; rank 1's host clock is 0.25s fast.
    docs = {
        0: _synthetic_rank_trace(0, epoch, 950_000),
        1: _synthetic_rank_trace(1, epoch, 60_000, skew_s=0.25),
        2: _synthetic_rank_trace(2, epoch, 900_000),
        3: _synthetic_rank_trace(3, epoch, 55_000),
    }
    paths = []
    for rank, doc in docs.items():
        p = tmp_path / f"rank{rank}.json"
        p.write_text(json.dumps(doc))
        paths.append(str(p))
    merged_path = str(tmp_path / "merged.json")
    assert (
        merge.main(paths + ["-o", merged_path, "--json"]) == 0
    )
    info = json.loads(capsys.readouterr().out)
    assert info["skew_s"]["1"] == pytest.approx(0.25, abs=0.01)
    for r in ("0", "2", "3"):
        assert info["skew_s"][r] == pytest.approx(0.0, abs=0.01)
    cp = info["critical_path"]
    # Rank 0's write ends at 0.95s — the gating rank; rank 2 is close
    # behind; skew-corrected rank 1 lands with the short ranks.
    assert cp["gating_rank"] == 0
    assert cp["gating_phase"] == "write"
    slack = {row["rank"]: row["slack_s"] for row in cp["per_rank"]}
    assert slack[0] == 0.0
    assert slack[2] == pytest.approx(0.05, abs=0.01)
    assert slack[1] == pytest.approx(0.89, abs=0.02)

    merged = json.load(open(merged_path))
    assert merged["metadata"]["merged"] is True
    ts = [e["ts"] for e in merged["traceEvents"] if "ts" in e]
    assert all(t >= 0 for t in ts)
    assert ts == sorted(ts)  # one monotonic clock
    # per-rank process naming for Perfetto
    names = {
        e["pid"]: e["args"]["name"]
        for e in merged["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    assert names[2] == "rank 2 (host2)"
    # span ids are namespaced per rank (no cross-rank begin/end pairing)
    ids = {
        e["id"]
        for e in merged["traceEvents"]
        if e.get("ph") in ("b", "e")
    }
    assert ids == {f"r{r}:1" for r in range(4)}

    # summarize recognizes the merged trace and names the gating rank
    assert summarize.main([merged_path]) == 0
    out = capsys.readouterr().out
    assert "critical path: rank 0 gated the commit" in out
    assert summarize.main([merged_path, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["cross_rank"]["critical_path"]["gating_rank"] == 0


def test_merge_rejects_duplicate_ranks(tmp_path):
    doc = _synthetic_rank_trace(1, 1000.0, 5000)
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps(doc))
    b.write_text(json.dumps(doc))
    assert merge.main([str(a), str(b), "-o", str(tmp_path / "m.json")]) == 2


def test_merge_real_traces_from_two_takes(tmp_path, capsys):
    """End-to-end: two real flushed traces (distinct ranks stamped)
    merge into a loadable, summarizable timeline."""
    for rank in (0, 1):
        tracing.enable(str(tmp_path / f"r{rank}.json"))
        model = _Model(_rank_state(rank, 1024))
        Snapshot.take(str(tmp_path / f"snap{rank}"), {"m": model})
        # Both takes ran as (single-process) rank 0; restamp the second
        # before its flush to simulate a peer rank's trace.
        tracing.set_identity(rank=rank)
        tracing.disable()
    tracing.set_identity(rank=0)
    merged = str(tmp_path / "m.json")
    assert (
        merge.main(
            [str(tmp_path / "r0.json"), str(tmp_path / "r1.json"), "-o", merged]
        )
        == 0
    )
    capsys.readouterr()
    assert summarize.main([merged]) == 0
    out = capsys.readouterr().out
    assert "critical path: rank" in out


# ------------------------------------------------------------------ doctor


def _restore_report(read_s, consume_s, assemble_s=0.0, wall_s=None):
    wall = wall_s if wall_s is not None else read_s + consume_s + assemble_s
    return {
        "format_version": 1,
        "kind": "restore",
        "path": "memory://x/snap",
        "take_id": None,
        "world_size": 1,
        "ranks": [
            {
                "rank": 0,
                "wall_s": wall,
                "phases": {
                    "read_s": read_s,
                    "consume_s": consume_s,
                    "assemble_s": assemble_s,
                },
                "bytes": 209715200,
                "budget": {"high_water_bytes": 0, "stall_s": 0.0},
                "retries": {"total": 0},
            }
        ],
        "totals": {
            "bytes": 209715200,
            "wall_s": wall,
            "retries": 0,
            "faults": 0,
            "stall_s": 0.0,
        },
    }


def test_doctor_flags_bench_r05_consume_dominated_restore():
    """Acceptance: a BENCH_r05-shaped restore report (consume 176.3s vs
    read 0.76s) emits the consume-dominated finding with evidence and a
    remediation hint."""
    report = _restore_report(read_s=0.76, consume_s=176.3, assemble_s=1.21)
    findings = doctor.diagnose_report(report)
    rules = [f.rule for f in findings]
    assert "consume-dominated-restore" in rules
    f = next(x for x in findings if x.rule == "consume-dominated-restore")
    assert f.severity == "critical"
    assert f.evidence["consume_s"] == pytest.approx(176.3)
    assert f.evidence["read_s"] == pytest.approx(0.76)
    assert "deserialization" in f.remediation
    assert "storage is innocent" in f.remediation


def test_doctor_healthy_report_is_silent():
    report = _restore_report(read_s=1.0, consume_s=1.5)
    assert doctor.diagnose_report(report) == []


def test_doctor_read_dominated_restore():
    findings = doctor.diagnose_report(
        _restore_report(read_s=30.0, consume_s=1.0)
    )
    assert [f.rule for f in findings] == ["read-dominated-restore"]


def _take_report(rank_summaries, retries=0):
    return {
        "format_version": 1,
        "kind": "take",
        "path": "memory://x/snap",
        "take_id": "abc",
        "world_size": len(rank_summaries),
        "ranks": rank_summaries,
        "totals": {
            "bytes": sum((s or {}).get("bytes", 0) for s in rank_summaries),
            "wall_s": max(
                ((s or {}).get("wall_s", 0) for s in rank_summaries),
                default=0,
            ),
            "retries": retries,
            "faults": 0,
            "stall_s": sum(
                (s or {}).get("budget", {}).get("stall_s", 0)
                for s in rank_summaries
                if s
            ),
        },
    }


def _rank_summary(rank, wall_s=10.0, nbytes=1 << 26, stall_s=0.0, retries=0):
    return {
        "rank": rank,
        "wall_s": wall_s,
        "phases": {"capture_s": 0.1, "write_s": wall_s - 0.1},
        "bytes": nbytes,
        "budget": {"high_water_bytes": nbytes, "stall_s": stall_s},
        "scheduler_ops": {
            "stage": {"count": 4, "seconds": 0.5, "bytes": nbytes},
            "write": {"count": 4, "seconds": wall_s - 1, "bytes": nbytes},
        },
        "retries": {"total": retries, "backoff_s": 0.0, "by_op": {}},
        "faults": {},
    }


def test_doctor_straggler_and_stripe_and_storm_and_stall():
    report = _take_report(
        [
            _rank_summary(0, wall_s=30.0, nbytes=1 << 28, retries=12),
            _rank_summary(1, wall_s=4.0),
            _rank_summary(2, wall_s=4.2, stall_s=2.0),
            _rank_summary(3, wall_s=4.1),
        ],
        retries=12,
    )
    rules = {f.rule for f in doctor.diagnose_report(report)}
    assert "straggler-rank" in rules
    assert "imbalanced-stripe" in rules
    assert "retry-storm" in rules
    assert "budget-stall-dominated" in rules
    # critical findings sort first
    findings = doctor.diagnose_report(report)
    assert findings[0].severity == "critical"


def test_doctor_missing_rank_summary():
    report = _take_report(
        [_rank_summary(0, wall_s=3.0), None, _rank_summary(2, wall_s=3.0)]
    )
    rules = [f.rule for f in doctor.diagnose_report(report)]
    assert "missing-rank-summary" in rules


def test_doctor_cli_and_inspect(tmp_path, capsys):
    # report-file mode: findings -> exit 1, rendered with remediation
    rp = tmp_path / "report.json"
    rp.write_text(json.dumps(_restore_report(0.76, 176.3)))
    assert doctor.main([str(rp)]) == 1
    out = capsys.readouterr().out
    assert "consume-dominated-restore" in out and "remediation" in out
    assert doctor.main([str(rp), "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc[0]["rule"] == "consume-dominated-restore"

    # snapshot mode via inspect --doctor: a healthy real snapshot
    from torchsnapshot_tpu.inspect import main as inspect_main

    model = _Model(_rank_state(0, 2048))
    snap = Snapshot.take(str(tmp_path / "snap"), {"m": model})
    snap.restore({"m": _Model(_rank_state(0, 2048))})
    assert inspect_main([str(tmp_path / "snap"), "--doctor"]) == 0
    assert "no findings" in capsys.readouterr().out
    # no report at all -> exit 2
    assert doctor.main([str(tmp_path / "nothing-here")]) == 2


def test_doctor_trace_verdict_bridges_into_findings(tmp_path):
    summary = {
        "verdict": {
            "pipeline": "restore",
            "dominant_phase": "consume",
            "busy_s": 176.3,
            "sibling": "read",
            "sibling_busy_s": 0.76,
            "dominated": True,
        }
    }
    findings = doctor.diagnose([], trace_summary=summary)
    assert [f.rule for f in findings] == ["consume-dominated-restore"]


# ------------------------------------------------------- progress lifecycle


@pytest.mark.faultline
def test_progress_objects_never_survive_commit_or_detected_crash(
    tmp_path, monkeypatch
):
    """Satellite acceptance: .progress/<take_id>/<rank> objects are
    cleaned at commit, and reconcile reclaims the debris of a take that
    crashed mid-drain (the detected-crash arm)."""
    from torchsnapshot_tpu import CheckpointManager
    from torchsnapshot_tpu import faultline as fl

    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    monkeypatch.setenv("TPUSNAPSHOT_PROGRESS_INTERVAL_S", "0")
    base = str(tmp_path / "run")
    mgr = CheckpointManager(base, max_to_keep=3)

    # Commit arm: a clean async save leaves no progress object.
    handle = mgr.async_save(0, {"m": _Model(_rank_state(0, 1024))})
    handle.wait()
    leftovers = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(base)
        for f in fs
        if ".progress" in os.path.join(dp, f)
    ]
    assert leftovers == []

    # Crash arm: the drain dies mid-payload-write; the published
    # progress record is debris only until reconcile runs.
    sched = fl.FaultSchedule().crash_on(op="write", path="0/m/*")
    with fl.inject(sched):
        handle = mgr.async_save(1, {"m": _Model(_rank_state(1, 1024))})
        with pytest.raises(fl.SimulatedCrash):
            handle.wait()
    debris = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(base)
        for f in fs
        if ".progress" in os.path.join(dp, f)
    ]
    assert debris, "the crashed drain published a progress record"
    CheckpointManager(base).reconcile(adopt=True)
    debris = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(base)
        for f in fs
        if ".progress" in os.path.join(dp, f)
    ]
    assert debris == []
    # The committed step survived untouched.
    assert CheckpointManager(base).all_steps() == [0]


@pytest.mark.faultline
def test_reconcile_reclaims_progress_debris_under_committed_step(
    tmp_path, monkeypatch
):
    """A crash between commit and the rank-0 sweep leaves progress
    records under a COMMITTED step — exactly what
    _clean_progress_debris exists for (no sweep revisits a committed
    prefix)."""
    from torchsnapshot_tpu import CheckpointManager

    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "0")
    base = str(tmp_path / "run")
    mgr = CheckpointManager(base, max_to_keep=3)
    mgr.save(0, {"m": _Model(_rank_state(0, 1024))})
    debris_dir = os.path.join(base, "step-0", ".progress", "deadbeef")
    os.makedirs(debris_dir)
    with open(os.path.join(debris_dir, "1"), "w") as f:
        json.dump({"rank": 1, "phase": "commit"}, f)
    CheckpointManager(base).reconcile(adopt=True)
    assert not os.path.exists(os.path.join(debris_dir, "1"))
    assert CheckpointManager(base).all_steps() == [0]


@pytest.mark.faultline
def test_reconcile_age_guard_spares_young_progress_records(
    tmp_path, monkeypatch
):
    """An in-flight take's live records must survive reconcile."""
    from torchsnapshot_tpu import CheckpointManager

    monkeypatch.setenv("TPUSNAPSHOT_SWEEP_MIN_AGE_S", "3600")
    base = str(tmp_path / "run")
    mgr = CheckpointManager(base, max_to_keep=3)
    mgr.save(0, {"m": _Model(_rank_state(0, 1024))})
    debris = os.path.join(base, "step-0", ".progress", "live", "0")
    os.makedirs(os.path.dirname(debris))
    with open(debris, "w") as f:
        json.dump({"rank": 0, "phase": "write"}, f)
    CheckpointManager(base).reconcile(adopt=True)
    assert os.path.exists(debris)


def test_delete_removes_progress_debris(tmp_path):
    model = _Model(_rank_state(0, 1024))
    snap = Snapshot.take(str(tmp_path / "snap"), {"m": model})
    debris = tmp_path / "snap" / ".progress" / "dead" / "0"
    debris.parent.mkdir(parents=True)
    debris.write_text("{}")
    snap.delete()
    assert not debris.exists()


# ------------------------------------------------------------ bench_compare


_BENCH_COMPARE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools",
    "bench_compare.py",
)


def _run_compare(*args):
    return subprocess.run(
        [sys.executable, _BENCH_COMPARE, *args],
        capture_output=True,
        text=True,
    )


def test_bench_compare_self_test():
    proc = _run_compare("--self-test")
    assert proc.returncode == 0, proc.stderr
    assert "self-test OK" in proc.stdout


def test_bench_compare_regression_gate(tmp_path):
    old = {"metric": "snapshot_take_GBps", "value": 1.0, "restore_GBps": 2.0}
    good = {"metric": "snapshot_take_GBps", "value": 0.95, "restore_GBps": 2.1}
    bad = {"metric": "snapshot_take_GBps", "value": 0.5, "restore_GBps": 2.0}
    for name, doc in [("old", old), ("good", good), ("bad", bad)]:
        (tmp_path / f"{name}.json").write_text(json.dumps(doc))
    ok = _run_compare(str(tmp_path / "old.json"), str(tmp_path / "good.json"))
    assert ok.returncode == 0, ok.stdout + ok.stderr
    fail = _run_compare(str(tmp_path / "old.json"), str(tmp_path / "bad.json"))
    assert fail.returncode == 1
    assert "REGRESSION" in fail.stdout


def test_bench_compare_unwraps_repo_bench_files():
    repo = os.path.dirname(_BENCH_COMPARE)
    r03 = os.path.join(os.path.dirname(repo), "BENCH_r03.json")
    r05 = os.path.join(os.path.dirname(repo), "BENCH_r05.json")
    proc = _run_compare(r03, r05)
    # r05 improved restore/ceiling vs r03 — no regression either way on
    # the metrics both runs measured.
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "restore/ceiling" in proc.stdout
