"""Sharded/subdivided write-path coverage bench (VERDICT r3 #3).

The headline bench runs on ONE real TPU chip, where every parameter is a
dense per-rank array — the ShardedArrayEntry write path, the 512 MiB
subdivision (io_preparer.MAX_CHUNK_SIZE_BYTES), and multi-chunk
resharded restore never appear inside it. This script runs those paths
at scale on an 8-virtual-device CPU mesh (the same mechanism the
multi-chip dryrun uses) so the certified artifact includes a timed
save/restore whose write set contains subdivided chunks.

Invoked by bench.py as a subprocess with JAX_PLATFORMS=cpu; prints ONE
JSON line on stdout. These numbers measure host memory bandwidth + disk,
not the TPU link — they are path-coverage evidence, not the headline.
"""

import json
import math
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu import Snapshot
    from torchsnapshot_tpu.io_preparer import MAX_CHUNK_SIZE_BYTES
    from torchsnapshot_tpu.manifest import ShardedArrayEntry

    total_bytes = int(
        os.environ.get("TPUSNAPSHOT_SHARDED_BENCH_BYTES", 3 * (512 * 1024**2))
    )
    devices = jax.devices()
    assert len(devices) >= 8, f"expected 8 virtual devices, got {len(devices)}"

    # 2-way sharding of `total_bytes` makes each shard exceed the 512 MiB
    # subdivision cap (3 x 512 MiB total -> 768 MiB shards -> 512+256
    # chunks), so the write set contains subdivided chunks by
    # construction — asserted below, not assumed.
    n_rows = total_bytes // (4 * 1024)
    mesh2 = Mesh(np.array(devices[:2]), ("x",))
    arr = jax.device_put(
        jnp.ones((n_rows, 1024), jnp.float32),
        NamedSharding(mesh2, P("x", None)),
    )
    jax.block_until_ready(arr)

    class _Holder:
        def __init__(self, sd):
            self.sd = sd

        def state_dict(self):
            return self.sd

        def load_state_dict(self, sd):
            self.sd = sd

    bench_dir = tempfile.mkdtemp(prefix="tpusnapshot-sharded-bench-")
    try:
        path = f"{bench_dir}/snap"
        begin = time.monotonic()
        Snapshot.take(path, {"m": _Holder({"w": arr})})
        take_s = time.monotonic() - begin

        entry = Snapshot(path).get_manifest()["0/m/w"]
        assert isinstance(entry, ShardedArrayEntry)
        n_chunks = len(entry.shards)
        expected = 2 * math.ceil(
            (total_bytes / 2) / MAX_CHUNK_SIZE_BYTES
        )
        assert n_chunks == expected and n_chunks > 2, (
            f"write set not subdivided: {n_chunks} chunks "
            f"(expected {expected})"
        )

        # Multi-chunk resharded restore: 8-way sharding never seen at
        # save time; every target shard assembles from ranged reads of
        # the subdivided chunks.
        mesh8 = Mesh(np.array(devices), ("x",))
        template = jax.device_put(
            jnp.zeros((n_rows, 1024), jnp.float32),
            NamedSharding(mesh8, P("x", None)),
        )
        jax.block_until_ready(template)
        target = _Holder({"w": template})
        begin = time.monotonic()
        Snapshot(path).restore({"m": target})
        restored = target.sd["w"]
        # Force materialization before stopping the clock.
        float(jax.jit(jnp.sum)(restored))
        restore_s = time.monotonic() - begin
        ok = bool(float(jnp.sum(restored)) == float(n_rows * 1024))

        gib = total_bytes / 1024**3
        print(
            json.dumps(
                {
                    "ok": ok,
                    "bytes": total_bytes,
                    "subdivided_chunks": n_chunks,
                    "take_GBps": round(gib / take_s, 3),
                    "restore_GBps": round(gib / restore_s, 3),
                    "take_s": round(take_s, 2),
                    "restore_s": round(restore_s, 2),
                }
            )
        )
    finally:
        shutil.rmtree(bench_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
