"""In-situ async-snapshot stall: step-time inflation inside a real
jitted training loop (VERDICT r2 ask #7).

``bench.py`` measures the async stall against an idle device; the number
a training team quotes is different — how much does taking a snapshot
every K steps inflate the p50/p95 *step time* of a loop that is actually
using the chip? This script runs a jitted transformer SGD loop on the
real device, times every step (blocking on the loss), fires
``Snapshot.async_take`` every K steps mid-loop, and compares the
distribution against a no-snapshot baseline of the same length.

Prints one JSON line:
  {"baseline_p50_s": ..., "baseline_p95_s": ..., "snap_p50_s": ...,
   "snap_p95_s": ..., "p50_inflation_pct": ..., "p95_inflation_pct": ...,
   "take_step_overhead_s": ..., "n_steps": ..., "snap_every": ...,
   "param_bytes": ...}

Env knobs: TPUSNAPSHOT_STALL_STEPS (default 60),
TPUSNAPSHOT_STALL_EVERY (default 20), TPUSNAPSHOT_STALL_DMODEL (512),
TPUSNAPSHOT_STALL_LAYERS (4), TPUSNAPSHOT_STALL_SEQ (512),
TPUSNAPSHOT_STALL_BATCH (8), TPUSNAPSHOT_STALL_DIR (fresh tmpdir).
"""

import json
import os
import shutil
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from torchsnapshot_tpu import Snapshot  # noqa: E402
from torchsnapshot_tpu.models.transformer import (  # noqa: E402
    TransformerConfig,
    init_params,
    sgd_train_step,
)


class _ParamState:
    """Stateful over the training loop's live params pytree."""

    def __init__(self, params):
        self.params = params

    def state_dict(self):
        return {"params": self.params}

    def load_state_dict(self, sd):
        self.params = sd["params"]


def main() -> None:
    n_steps = int(os.environ.get("TPUSNAPSHOT_STALL_STEPS", 60))
    snap_every = int(os.environ.get("TPUSNAPSHOT_STALL_EVERY", 20))
    config = TransformerConfig(
        vocab_size=1024,
        d_model=int(os.environ.get("TPUSNAPSHOT_STALL_DMODEL", 512)),
        n_heads=8,
        n_layers=int(os.environ.get("TPUSNAPSHOT_STALL_LAYERS", 4)),
        d_ff=2048,
        max_seq_len=int(os.environ.get("TPUSNAPSHOT_STALL_SEQ", 512)),
    )
    batch = int(os.environ.get("TPUSNAPSHOT_STALL_BATCH", 8))
    seq = config.max_seq_len

    params = init_params(config, jax.random.key(0))
    param_bytes = sum(
        leaf.nbytes for leaf in jax.tree.leaves(params)
    )
    tokens = jax.random.randint(
        jax.random.key(1), (batch, seq), 0, config.vocab_size
    )
    step = jax.jit(
        lambda p, t: sgd_train_step(p, t, config), donate_argnums=(0,)
    )

    bench_dir = os.environ.get("TPUSNAPSHOT_STALL_DIR")
    own_dir = bench_dir is None
    if own_dir:
        bench_dir = tempfile.mkdtemp(prefix="tpusnapshot-stall-")

    def run_loop(with_snapshots: bool):
        nonlocal params
        times = []
        take_overheads = []
        pendings = []
        state = _ParamState(params)
        for i in range(n_steps):
            begin = time.monotonic()
            if with_snapshots and i > 0 and i % snap_every == 0:
                t0 = time.monotonic()
                state.params = params
                pendings.append(
                    Snapshot.async_take(
                        f"{bench_dir}/step-{i}", {"model": state}
                    )
                )
                take_overheads.append(time.monotonic() - t0)
            params, loss = step(params, tokens)
            # float() forces the scalar to host: on this platform
            # block_until_ready returns before work completes, so an
            # un-fetched loop just queues dispatches and every "step"
            # times at ~0.1 ms. Real training loops fetch the loss too.
            float(loss)
            times.append(time.monotonic() - begin)
        for p in pendings:
            p.wait()
        return times, take_overheads

    try:
        # Warm-up: compile + let the device settle.
        for _ in range(5):
            params, loss = step(params, tokens)
        jax.block_until_ready(loss)

        base_times, _ = run_loop(with_snapshots=False)
        snap_times, take_overheads = run_loop(with_snapshots=True)

        def p(q, xs):
            xs = sorted(xs)
            return xs[min(len(xs) - 1, int(q * len(xs)))]

        base_p50, base_p95 = p(0.50, base_times), p(0.95, base_times)
        snap_p50, snap_p95 = p(0.50, snap_times), p(0.95, snap_times)
        # Amortized cost over the whole loop — the number a training team
        # multiplies into their step budget (p95 on this platform mostly
        # measures the shared tunnel carrying drain bytes AND dispatch
        # round-trips at once).
        mean_inflation = 100 * (
            sum(snap_times) / max(sum(base_times), 1e-9) - 1
        )
        result = {
            "mean_inflation_pct": round(mean_inflation, 2),
            "baseline_p50_s": round(base_p50, 4),
            "baseline_p95_s": round(base_p95, 4),
            "snap_p50_s": round(snap_p50, 4),
            "snap_p95_s": round(snap_p95, 4),
            "p50_inflation_pct": round(100 * (snap_p50 / base_p50 - 1), 2),
            "p95_inflation_pct": round(100 * (snap_p95 / base_p95 - 1), 2),
            "take_step_overhead_s": round(
                statistics.median(take_overheads), 4
            )
            if take_overheads
            else None,
            "n_steps": n_steps,
            "snap_every": snap_every,
            "param_bytes": param_bytes,
        }
        print(
            f"[stall] baseline p50/p95 {base_p50:.3f}/{base_p95:.3f}s; "
            f"with async_take every {snap_every}: "
            f"{snap_p50:.3f}/{snap_p95:.3f}s; take-call overhead "
            f"{result['take_step_overhead_s']}s; params "
            f"{param_bytes / 1024**2:.1f} MiB",
            file=sys.stderr,
        )
        print(json.dumps(result))
    finally:
        if own_dir:
            shutil.rmtree(bench_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
