"""Summarize a snapshot Chrome trace into a phase breakdown.

Reads the JSON written by ``TPUSNAPSHOT_TRACE=/path/trace.json`` (see
torchsnapshot_tpu/tracing.py) and prints, per span name: count, total
span-seconds, and — the number that matters for a pipelined schedule —
the *busy wall-clock* (union of intervals), so "stage 18 s total but
9 s busy" reads as 2x overlap. Use it to answer VERDICT-style "where
does the take time go" questions from a file instead of a guess:

    TPUSNAPSHOT_TRACE=/tmp/t.json python bench.py
    python benchmarks/trace_report.py /tmp/t.json
"""

import json
import sys
from collections import defaultdict


def union_seconds(intervals):
    total = 0.0
    end = None
    for b, e in sorted(intervals):
        if end is None or b > end:
            total += e - b
            end = e
        elif e > end:
            total += e - end
            end = e
    return total


def main() -> int:
    if len(sys.argv) != 2:
        print(__doc__, file=sys.stderr)
        return 2
    with open(sys.argv[1]) as f:
        events = json.load(f)["traceEvents"]

    begins = {}
    spans = defaultdict(list)  # name -> [(begin_us, end_us)]
    bytes_by_name = defaultdict(int)
    for ev in events:
        if ev["ph"] == "b":
            begins[ev["id"]] = ev
        elif ev["ph"] == "e":
            b = begins.pop(ev["id"], None)
            if b is None:
                continue
            spans[b["name"]].append((b["ts"], ev["ts"]))
            args = b.get("args") or {}
            if isinstance(args.get("bytes"), int):
                bytes_by_name[b["name"]] += args["bytes"]

    if not spans:
        print("no spans found")
        return 1
    t0 = min(b for ivs in spans.values() for b, _ in ivs)
    t1 = max(e for ivs in spans.values() for _, e in ivs)
    print(f"trace wall-clock: {(t1 - t0) / 1e6:.2f}s")
    print(f"{'span':20s} {'count':>6s} {'total_s':>9s} {'busy_s':>8s} "
          f"{'overlap':>7s} {'GB':>7s} {'GB/s(busy)':>10s}")
    for name, ivs in sorted(
        spans.items(), key=lambda kv: -sum(e - b for b, e in kv[1])
    ):
        total = sum(e - b for b, e in ivs) / 1e6
        busy = union_seconds(ivs) / 1e6
        gb = bytes_by_name[name] / 1024**3
        rate = f"{gb / busy:10.3f}" if gb and busy else " " * 10
        print(
            f"{name:20s} {len(ivs):6d} {total:9.2f} {busy:8.2f} "
            f"{total / busy if busy else 0:6.1f}x {gb:7.2f} {rate}"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
