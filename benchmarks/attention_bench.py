"""On-device attention kernel benchmark: fused (flash) Pallas vs dense
einsum, forward and forward+backward, across sequence lengths.

Unlike the snapshot benchmark (bounded by the shared host↔device
tunnel), this measures ON-DEVICE compute: the timed region is a jitted
`lax.fori_loop` of attention steps, so dispatch/transfer overhead is
amortized and the number reflects kernel quality (MXU utilization, HBM
traffic) regardless of co-tenant traffic.

Run on a TPU VM:
    python benchmarks/attention_bench.py

Prints a table of per-step latency and achieved attention TFLOP/s
(4·B·H·S²·D FLOPs per forward — two matmuls, halved again when causal
— and 2.5× that for forward+backward).
"""

import sys
import time

import jax
import jax.numpy as jnp

import os  # noqa: E402

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from torchsnapshot_tpu.ops.attention import (  # noqa: E402
    _reference_attention,
    flash_attention,
    resolve_flash_block,
)

ITERS = 300


def _bench(fn, *args) -> float:
    """Median per-call seconds of a jitted loop of ITERS calls.

    The output feeds the next iteration's first argument (same shape),
    so the body has a true loop-carried dependency — XLA can neither
    hoist the attention out of the loop nor dead-code it (a
    multiply-by-zero feedback gets constant-folded and the 'benchmark'
    then measures one call amortized over ITERS)."""

    @jax.jit
    def loop(args):
        def body(_, carry):
            q = carry[0]
            out = fn(*carry)
            return (out.astype(q.dtype),) + carry[1:]

        return jnp.sum(
            jax.lax.fori_loop(0, ITERS, body, args)[0].astype(jnp.float32)
        )

    float(loop(args))  # compile
    times = []
    for _ in range(3):
        begin = time.monotonic()
        # float() fetches the scalar VALUE — the only reliable compute
        # fence on this platform (block_until_ready can return before
        # the device finishes behind the tunnel, same as the restore
        # path's forced-sync lesson in bench.py).
        float(loop(args))
        times.append((time.monotonic() - begin) / ITERS)
    return sorted(times)[1]


def main() -> None:
    b, h, d = 2, 16, 128
    print(f"B={b} H={h} D={d}, bf16, causal; {ITERS}-step jitted loop (latency amortized)")
    print(
        f"{'S':>6} {'flash fwd':>11} {'einsum fwd':>11} {'speedup':>8} "
        f"{'flash TFLOP/s':>13}  {'fwd+bwd flash':>13}"
    )
    for s in (1024, 2048, 4096, 8192):
        kq, kk, kv = jax.random.split(jax.random.key(s), 3)
        q = jax.random.normal(kq, (b, h, s, d), jnp.bfloat16)
        k = jax.random.normal(kk, (b, h, s, d), jnp.bfloat16)
        v = jax.random.normal(kv, (b, h, s, d), jnp.bfloat16)
        block = resolve_flash_block(s)

        def flash_fn(q, k, v):
            return flash_attention(
                q, k, v, causal=True, block_q=block, block_k=block,
                interpret=False,
            )

        def einsum_fn(q, k, v):
            return _reference_attention(q, k, v, True)

        t_flash = _bench(flash_fn, q, k, v)
        t_einsum = _bench(einsum_fn, q, k, v) if s <= 4096 else float("nan")

        def flash_grad(q, k, v):
            # argnums MUST cover k and v: with argnums=0 the dk/dv
            # Pallas kernel is dead code under jit and XLA DCEs it —
            # the "fwd+bwd" number would then time only fwd + dq
            # (~half the backward FLOPs missing).
            return jax.grad(
                lambda q, k, v: jnp.sum(
                    flash_fn(q, k, v).astype(jnp.float32) ** 2
                ),
                argnums=(0, 1, 2),
            )(q, k, v)

        @jax.jit
        def bwd_loop(q, k, v):
            def body(_, carry):
                dq, dk, dv = flash_grad(*carry)
                # All three grads feed the next iteration, so none of
                # the backward kernels can be dead-code-eliminated.
                return (
                    dq.astype(q.dtype),
                    dk.astype(k.dtype),
                    dv.astype(v.dtype),
                )

            out = jax.lax.fori_loop(0, ITERS, body, (q, k, v))
            return sum(jnp.sum(x.astype(jnp.float32)) for x in out)

        float(bwd_loop(q, k, v))  # compile
        bwd_times = []
        for _ in range(3):
            begin = time.monotonic()
            float(bwd_loop(q, k, v))
            bwd_times.append((time.monotonic() - begin) / ITERS)
        t_bwd = sorted(bwd_times)[1]

        causal_flops = 4 * b * h * s * s * d / 2
        tflops = causal_flops / t_flash / 1e12
        print(
            f"{s:>6} {t_flash * 1e3:>9.2f}ms {t_einsum * 1e3:>9.2f}ms "
            f"{t_einsum / t_flash:>7.2f}x {tflops:>13.2f} "
            f"{t_bwd * 1e3:>11.2f}ms"
        )


if __name__ == "__main__":
    main()
